"""ServingEngine: continuous-batching generation over a paged KV cache.

The device side of :mod:`apex_tpu.serving` — TWO compiled programs
(plus a third, ``spec_step``, when a drafter is attached), each with
one set of avals for the lifetime of the engine:

* ``prefill_chunk(params, pool, table_row, tokens, start, live, key)``
  — one fixed-size chunk of ONE slot's prompt through the stack: the
  chunk's k/v land in the slot's pool blocks (a scatter at traced block
  ids — blocks fully past the live tokens are redirected to the dead
  block so ragged final chunks never touch foreign memory), attention
  runs chunk-queries × the slot's gathered padded cache under the
  prefix-causal mask ``key_pos <= start + i``, and the LAST chunk's
  final-row logits sample the request's first token. ``start``/``live``
  are traced scalars, so every chunk of every prompt length is the same
  executable.
* ``decode_step(params, pool, tables, tokens, lengths, key)`` — one
  token for EVERY slot at once: per-slot cache writes resolve
  ``(block, row)`` through the table (dead slots' writes land in the
  dead block), attention is the paged
  :func:`apex_tpu.ops.decode_attention` (``lengths == 0`` rows are dead
  by the kernel's convention), and the fused sampling tail
  (:func:`apex_tpu.ops.fused_sample`) turns logits into tokens in one
  dispatch.

* ``spec_step(params, pool, tables, tokens, lengths, drafted, key)`` —
  the speculative round (``serve(draft=...)``): every decoding slot
  scores its pending token plus k drafts in one k+1-wide dispatch
  (the prefill-chunk attention shape batched over the slot array) and
  the fused verify tail (:func:`apex_tpu.ops.fused_verify`) emits
  per-slot ``(accept_len, next_token)``; the scheduler rewinds tables/
  lengths to the accepted frontier afterwards — contents-only, one
  executable per static k.

All donate the pool: XLA updates the cache in place, so a step's HBM
traffic is the live cache read plus one token's writes — never a pool
copy. Under a quantized ``kv_dtype`` (``"int8"`` or ``"fp8_e4m3"``)
the pool stores 1-byte k/v cells with per-block-row fp32 scales
alongside (quantize on write at every write site; dequantize in-VMEM
inside the paged decode kernel), halving the bytes the HBM-bound
decode stream pays — the float pool stays the parity oracle, and the
two quantized formats differ only in (qmax, storage dtype). Everything dynamic about traffic stays in
:class:`~apex_tpu.serving.scheduler.Scheduler` on the host; churn
reaches the device only as operand *contents*, which is why
``decode_step._cache_size()`` stays 1 across arbitrary admit/evict
(asserted by ``tests/test_serving.py`` and by ``bench.py --serve``).

The chunk-attention gather materializes one ``(h_kv, max_s, d)`` view
per layer per chunk — prefill is compute-bound and infrequent relative
to decode, so this buys simplicity where it is cheap; fusing the
chunk path into the flash family is future work (the decode hot path,
where the HBM bound lives, is already fused end to end).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.gpt import GPTModel, shard_params_for_tp
from apex_tpu.monitor import registry as monitor_registry
from apex_tpu.monitor import spans as monitor_spans
from apex_tpu.monitor import trace as monitor_trace
from apex_tpu.ops import (fused_layer_norm, fused_sample, fused_verify,
                          fused_verify_tree)
from apex_tpu.ops.decode_attention import decode_attention
from apex_tpu.ops.pallas.attention import NEG_INF
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.serving import tp as tp_serving
from apex_tpu.serving.kv_blocks import (DEAD_BLOCK, BlockAllocator,
                                        PrefixCache)
from apex_tpu.serving.scheduler import Request, Scheduler, SLOPolicy
from apex_tpu.serving.telemetry import ServeTelemetry


#: legal kv_dtype values and their (qmax, storage dtype): int8 rounds
#: into [-127, 127]; fp8_e4m3 keeps a mantissa and scales amax onto the
#: format's finite ceiling (448) — same per-block-row fp32 scale planes,
#: same 1 byte/cell, so the two pools share every write/gather site
KV_QUANT_SPECS = {
    "int8": (127.0, jnp.int8),
    "fp8_e4m3": (448.0, jnp.float8_e4m3fn),
}


def _quant_rows(x, axes, *, qmax=127.0, qdtype=jnp.int8):
    """Symmetric per-row quantization: one fp32 scale per row (``axes``
    reduced away — kv heads and head_dim share it, because the write
    sites land one token row at a time). Integer targets round into
    [-qmax, qmax] (int8's [-127, 127]); float targets (fp8) keep their
    own mantissa and just clip at the format's amax. The tiny floor
    keeps an all-zero row's scale finite (dead-block writes, padding) —
    it dequantizes back to exact zeros."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-20) / qmax
    y = xf / scale
    if jnp.issubdtype(jnp.dtype(qdtype), jnp.integer):
        q = jnp.clip(jnp.round(y), -qmax, qmax).astype(qdtype)
    else:
        q = jnp.clip(y, -qmax, qmax).astype(qdtype)
    return q, jnp.squeeze(scale, axis=axes)


@dataclass
class ServeStats:
    """Host-side accounting of one :meth:`ServingEngine.serve` call."""

    decode_steps: int = 0
    prefill_chunks: int = 0
    blocks_high_water: int = 0
    swaps: int = 0
    # speculative rounds (serve(draft=...)): a spec round is one
    # decode-width dispatch that can emit up to k+1 tokens per slot
    spec_rounds: int = 0
    spec_drafted: int = 0
    spec_accepted: int = 0
    # tree rounds (serve(draft=<tree drafter>)): spec_drafted counts
    # DEPTH rows (the chain-equivalent denominator — acceptance rates
    # stay comparable across tree and chain), spec_nodes the verify
    # rows actually scored (branching x depth per slot per round), and
    # spec_degraded the rounds the tree→chain→plain headroom ladder
    # stepped down instead of stalling
    tree_rounds: int = 0
    spec_nodes: int = 0
    spec_degraded: int = 0
    # per-SLOT spec rounds (spec_rounds counts dispatches; each live
    # slot in a dispatch is one slot-round — the efficiency denominator)
    spec_slot_rounds: int = 0
    occupancy_samples: List[int] = field(default_factory=list)

    def occupancy_pct(self, num_slots: int) -> Optional[float]:
        if not self.occupancy_samples:
            return None
        return (100.0 * sum(self.occupancy_samples)
                / (len(self.occupancy_samples) * num_slots))

    @property
    def spec_acceptance_rate(self) -> float:
        """Accepted drafts / drafted tokens (0.0 before any round)."""
        return (self.spec_accepted / self.spec_drafted
                if self.spec_drafted else 0.0)

    @property
    def spec_efficiency(self) -> float:
        """Emitted tokens per verify-row scored — the tree/chain
        cost-normalized yield (each per-slot round scores ``nodes + 1``
        rows and emits ``accepted + 1`` tokens); 0.0 before any round.
        The adaptive-vs-fixed bench comparison ranks on THIS: a wider
        tree that lifts acceptance but wastes more rows must win here,
        not just on raw acceptance."""
        rows = self.spec_nodes + self.spec_slot_rounds
        return ((self.spec_accepted + self.spec_slot_rounds) / rows
                if rows else 0.0)


class ServingEngine:
    """Continuous-batching serving over a :class:`GPTModel`.

    ``engine = ServingEngine(model, num_slots=8, block_size=128)``;
    ``results = engine.serve(params, requests)`` — each
    :class:`~apex_tpu.serving.scheduler.Request` comes back with its
    generated tokens and latency stamps.

    Knobs (all static — they shape the two compiled programs):

    * ``num_slots`` — concurrent streams; the decode step's batch width.
    * ``block_size`` — cache page granularity; 128 on TPU (the paged
      kernel's lane-tiling constraint), smaller off-TPU if desired.
    * ``max_seq_len`` — per-slot logical cap (prompt + generated - 1
      rows); must be a ``block_size`` multiple. Defaults to the model's
      position table rounded DOWN to the block grid.
    * ``num_blocks`` — pool capacity + 1 dead block. Defaults to full
      capacity (``num_slots * max_seq_len/block_size + 1``); size it
      DOWN to what live traffic needs — that is the point of paging —
      and the scheduler turns the shortfall into prefix-cache
      reclamation, then preemption (evict-and-recompute), instead of
      failure or an admission stall.
    * ``prefill_chunk`` — prompt tokens per prefill step (a
      ``block_size`` multiple); smaller chunks interleave tighter with
      decode (less per-step jitter), larger chunks reach the first
      token sooner.
    * ``temperature`` / ``top_k`` / ``top_p`` — the fused sampling
      tail's static program (greedy when ``temperature == 0``).
    * ``plan`` — a :class:`~apex_tpu.plan.parallel_plan.ParallelPlan`
      with ``tp >= 2`` serves the model tensor-parallel: the paged
      pool shards contiguous kv-head slices per chip (ONE logical free
      list — allocator/tables stay host-side and identical across
      shards), the projections ride the ring-overlapped collective
      matmuls, and the fused sampling tail psum-composes so greedy
      output stays token-identical to tp=1 (see
      :mod:`apex_tpu.serving.tp`). Validated eagerly HERE.
    """

    def __init__(self, model: GPTModel, *, num_slots: int,
                 block_size: int = 128, num_blocks: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 cache_dtype: Any = None, kv_dtype: Optional[str] = None,
                 temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0, plan=None):
        model.check_decode_supported()
        self.model = model
        c = self.config = model.config
        # quantized KV pools (ROADMAP item 3b + fp8 sibling): 1 byte per
        # cell instead of the cache dtype's 2, halving the bytes the
        # decode kernel streams and doubling live-token capacity; the
        # float pool (kv_dtype=None, dtype = cache_dtype) stays as the
        # parity oracle. int8 and fp8_e4m3 share the per-block-row fp32
        # scale layout and every write/gather site; only (qmax, storage
        # dtype) differ (see KV_QUANT_SPECS). Validated HERE — an
        # unsupported value or model composition must name the knob,
        # never surface as a deep XLA dtype/shape error mid-serve.
        if kv_dtype not in (None, *KV_QUANT_SPECS):
            legal = ", ".join(repr(k) for k in KV_QUANT_SPECS)
            raise ValueError(
                f"kv_dtype must be None (float pool in cache_dtype) or "
                f"one of {legal} (per-block-row scales, dequantized "
                f"in-kernel); got {kv_dtype!r}")
        if kv_dtype is not None \
                and getattr(model, "decode_rel_bias", None) is not None:
            raise ValueError(
                f"kv_dtype={kv_dtype!r} cannot serve a model with a "
                "decode relative-position bias (the quantized paged "
                "kernel path does not carry the bucketed bias) — serve "
                "this model with the float pool (kv_dtype=None)")
        if kv_dtype == "fp8_e4m3" and plan is not None \
                and int(getattr(plan, "tp", 1)) > 1:
            raise ValueError(
                "kv_dtype='fp8_e4m3' is tp=1 only for now (the "
                "tensor-parallel quantize path is int8-specific) — "
                "serve fp8 pools single-chip or use kv_dtype='int8'")
        self.kv_dtype = kv_dtype
        self.quantized = kv_dtype is not None
        self._qmax, self._qdtype = KV_QUANT_SPECS.get(
            kv_dtype, (127.0, jnp.int8))
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.block_size = int(block_size)
        max_s = int(max_seq_len if max_seq_len is not None
                    else c.max_seq_len - c.max_seq_len % self.block_size)
        if max_s < self.block_size or max_s % self.block_size:
            raise ValueError(
                f"max_seq_len ({max_s}) must be a positive multiple of "
                f"block_size ({self.block_size}) — round up: "
                f"max_seq_len={-(-max_s // self.block_size) * self.block_size}")
        if max_s > c.max_seq_len:
            raise ValueError(
                f"max_seq_len ({max_s}) exceeds the model's position "
                f"table ({c.max_seq_len})")
        self.max_s = max_s
        self.max_blocks_per_slot = max_s // self.block_size
        self.num_slots = int(num_slots)
        if self.num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        full = self.num_slots * self.max_blocks_per_slot + 1
        self.num_blocks = int(num_blocks if num_blocks is not None else full)
        self.prefill_chunk_size = int(
            prefill_chunk if prefill_chunk is not None else self.block_size)
        if (self.prefill_chunk_size < self.block_size
                or self.prefill_chunk_size % self.block_size):
            raise ValueError(
                f"prefill_chunk ({self.prefill_chunk_size}) must be a "
                f"positive multiple of block_size ({self.block_size})")
        self.cache_dtype = cache_dtype or c.dtype
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        # tensor-parallel serving (ROADMAP tier 2c): plan.tp >= 2 shards
        # the pool/projections/sampling tail across chips; tp == 1 (or
        # plan=None) leaves every path byte-identical to the seed
        self.plan = plan
        self.tp = int(plan.tp) if plan is not None else 1
        self._mesh = None
        self._swap_ref = None
        if self.tp > 1:
            tp_serving.validate_tp(
                plan, c, engine="ServingEngine",
                num_slots=self.num_slots,
                prefill_chunk=self.prefill_chunk_size,
                num_blocks=self.num_blocks,
                max_blocks_per_slot=self.max_blocks_per_slot,
                temperature=self.temperature, top_k=self.top_k,
                top_p=self.top_p,
                has_rel_bias=getattr(model, "decode_rel_bias",
                                     None) is not None)
            self._mesh = tp_serving.tp_mesh(self.tp)
            P = jax.sharding.PartitionSpec
            kv, rep = P(None, None, "tp"), P()
            pool_spec = ({"k": kv, "v": kv, "k_scale": rep,
                          "v_scale": rep} if self.quantized
                         else {"k": kv, "v": kv})
            self._pool_spec = pool_spec
            # the shard_mapped step bodies: params arrive P('tp') on the
            # leading per-rank axis, pool k/v shard the kv-head axis,
            # scales/tables/tokens/lengths/key replicate; sampled tokens
            # come back replicated (the psum-composed tail computes the
            # same ints on every shard) and logits reassemble the full
            # vocab row from the shards — output assembly, never an
            # all_gather inside the program (the jaxpr gate's witness)
            self._tp_prefill = mesh_lib.shard_map(
                self._prefill_chunk_body_tp, mesh=self._mesh,
                in_specs=(P("tp"), pool_spec, rep, rep, rep, rep, rep),
                out_specs=(pool_spec, rep, P("tp")))
            self._tp_decode = mesh_lib.shard_map(
                self._decode_step_body_tp, mesh=self._mesh,
                in_specs=(P("tp"), pool_spec, rep, rep, rep, rep),
                out_specs=(pool_spec, rep, P(None, "tp")))
            self._tp_spec = mesh_lib.shard_map(
                self._spec_step_body_tp, mesh=self._mesh,
                in_specs=(P("tp"), pool_spec, rep, rep, rep, rep, rep),
                out_specs=(pool_spec, rep, rep))
        self.last_stats: Optional[ServeStats] = None
        # the last serve run's final pool (set by _serve_loop): the
        # disaggregated prefill role exports its warm blocks from here
        self.last_pool = None
        # pending weight hot-swap: (at_step, new_params, label) —
        # applied by the serve loop BETWEEN dispatch steps (see
        # request_swap)
        self._pending_swap = None
        # one jitted executable each; both donate the pool (argnums:
        # params=0, pool=1, ... — the cache updates in place)
        self.prefill_chunk = jax.jit(self._prefill_chunk,
                                     donate_argnums=(1,))
        self.decode_step = jax.jit(self._decode_step, donate_argnums=(1,))
        # the speculative round (serve(draft=...)): every decoding slot
        # verifies k drafted tokens in ONE dispatch; avals depend only
        # on the static draft length, so across rounds and churn it
        # compiles exactly once like the other two
        self.spec_step = jax.jit(self._spec_step, donate_argnums=(1,))
        # the TREE speculative round (serve(draft=<tree drafter>)):
        # avals depend only on the (num_nodes+1, depth+1) topology, so
        # there is one pinned executable per (depth, branching) in use
        # — the adaptive controller's whole choice set compiles once
        self.spec_tree_step = jax.jit(self._tree_step, donate_argnums=(1,))

    # --- pool ----------------------------------------------------------------

    def init_pool(self) -> Dict[str, jax.Array]:
        """The zeroed block pool:
        ``{"k"/"v": (layers, num_blocks, kv_heads, block_size, head_dim)}``
        — block 0 is the dead block (see kv_blocks). Under a quantized
        ``kv_dtype`` (``"int8"`` / ``"fp8_e4m3"``) the k/v arrays hold
        1-byte cells and per-block-row fp32 scales ride alongside as
        ``k_scale``/``v_scale`` ``(layers, num_blocks, block_size)`` —
        one pool tree either way, its avals fixed for the engine's
        lifetime."""
        c = self.config
        shape = (c.num_layers, self.num_blocks, c.local_kv_heads,
                 self.block_size, c.head_dim)
        if self.quantized:
            sshape = (c.num_layers, self.num_blocks, self.block_size)
            pool = {"k": jnp.zeros(shape, self._qdtype),
                    "v": jnp.zeros(shape, self._qdtype),
                    "k_scale": jnp.zeros(sshape, jnp.float32),
                    "v_scale": jnp.zeros(sshape, jnp.float32)}
        else:
            pool = {"k": jnp.zeros(shape, self.cache_dtype),
                    "v": jnp.zeros(shape, self.cache_dtype)}
        if self.tp > 1:
            # commit the pool to its mesh sharding up front (k/v split
            # on kv heads, scale planes replicated): the first dispatch
            # then sees the same committed shardings as every later one
            # — an uncommitted->committed transition would be a second
            # jit cache entry, breaking the _cache_size() == 1 contract
            pool = {
                name: jax.device_put(a, jax.sharding.NamedSharding(
                    self._mesh, self._pool_spec[name]))
                for name, a in pool.items()}
        return pool

    def _prepare_params(self, params):
        """tp == 1: passthrough. Under tp: split the replicated params
        tree into per-rank shards (:func:`~apex_tpu.models.gpt.
        shard_params_for_tp` — every leaf gains a leading ``(tp,)``
        axis) and commit each leaf to the mesh under ``P('tp')``."""
        if self.tp == 1:
            return params
        sharded = shard_params_for_tp(params, self.tp, self.config)
        sh = jax.sharding.NamedSharding(self._mesh,
                                        jax.sharding.PartitionSpec("tp"))
        return jax.tree.map(lambda a: jax.device_put(a, sh), sharded)

    def pool_bytes(self) -> int:
        """HBM footprint of the whole pool (both k and v, plus the
        scale planes under int8)."""
        c = self.config
        cells = (c.num_layers * self.num_blocks * c.local_kv_heads
                 * self.block_size * c.head_dim)
        if self.quantized:
            scales = c.num_layers * self.num_blocks * self.block_size
            return 2 * cells + 2 * scales * 4
        return 2 * cells * jnp.dtype(self.cache_dtype).itemsize

    def _pool_out(self, ck, cv, ks, vs) -> Dict[str, jax.Array]:
        if self.quantized:
            return {"k": ck, "v": cv, "k_scale": ks, "v_scale": vs}
        return {"k": ck, "v": cv}

    # --- weight hot-swap -----------------------------------------------------

    @staticmethod
    def _validate_swap_avals(old, new) -> None:
        """The hot-swap contract: the new tree must be a contents-only
        mutation — same structure, same shape/dtype per leaf — so both
        jitted programs keep their compiled executables (stable avals;
        the jit caches stay pinned at 1 through a swap). Every mismatch
        names its leaf path eagerly; a silent aval drift would instead
        surface as a RECOMPILE mid-serve, exactly the failure mode the
        zero-recompile contract exists to prevent."""
        old_paths = jax.tree_util.tree_flatten_with_path(old)
        new_paths = jax.tree_util.tree_flatten_with_path(new)
        if jax.tree.structure(old) != jax.tree.structure(new):
            ok = {jax.tree_util.keystr(p) for p, _ in old_paths[0]}
            nk = {jax.tree_util.keystr(p) for p, _ in new_paths[0]}
            extra, missing = sorted(nk - ok), sorted(ok - nk)
            raise ValueError(
                f"hot-swap params tree mismatch: new tree "
                f"{'adds ' + str(extra) if extra else ''}"
                f"{' and ' if extra and missing else ''}"
                f"{'drops ' + str(missing) if missing else ''}"
                f"{'' if extra or missing else 'has a different structure'}"
                f" — a swap is contents-only (same model, new weights)")
        for (path, a), (_, b) in zip(old_paths[0], new_paths[0]):
            if jnp.shape(a) != jnp.shape(b) or \
                    jnp.asarray(a).dtype != jnp.asarray(b).dtype:
                raise ValueError(
                    f"hot-swap aval mismatch at {jax.tree_util.keystr(path)}: "
                    f"serving {jnp.shape(a)}/{jnp.asarray(a).dtype}, new "
                    f"checkpoint {jnp.shape(b)}/{jnp.asarray(b).dtype} — "
                    f"a swap must keep every aval (restore_params(..., "
                    f"like=current_params) produces a matching tree)")

    def request_swap(self, new_params, *, at_step: Optional[int] = None,
                     source: Optional[str] = None) -> None:
        """Queue a weight hot-swap for the live serve loop: the NEXT
        loop iteration whose dispatch counter has reached ``at_step``
        (immediately when ``None``) replaces the params reference
        BETWEEN dispatch steps — in-flight requests keep their KV cache
        and finish against the new weights without dropping. Avals are
        validated against the live params at apply time (an eager,
        leaf-naming error — never a mid-serve recompile); ``source``
        labels the ``swap`` lifecycle event (e.g. the checkpoint step).

        One swap is pending at a time (a newer request replaces an
        unapplied one), and an unapplied swap does NOT outlive the
        serve call — if ``at_step`` is never reached the swap is
        dropped when ``serve`` returns (``last_stats.swaps == 0`` is
        the tell), never silently applied to a later run.

        Typical use with the sharded checkpoint subsystem::

            new = apex_tpu.ckpt.restore_params(ckpt_dir, like=params)
            engine.request_swap(new, source="step_00000042")
        """
        self._pending_swap = (at_step, new_params, source)

    def _maybe_swap(self, params, nstep: int, tel, stats, now: float):
        if self._pending_swap is None:
            return params
        at_step, new_params, source = self._pending_swap
        if at_step is not None and nstep < at_step:
            return params
        self._pending_swap = None
        t0 = time.perf_counter()
        # under tp the live params are the SHARDED tree; the contract is
        # stated (and validated) against the replicated tree the caller
        # handed serve() — the swap error names the caller's leaves
        self._validate_swap_avals(
            self._swap_ref if self.tp > 1 else params, new_params)
        stats.swaps += 1
        if self.tp > 1:
            self._swap_ref = new_params
            new_params = self._prepare_params(new_params)
        if tel is not None:
            # the measured validate+rebind pause: attribution carves it
            # out of the decode time of every mid-decode request
            tel.on_swap(nstep, now, source=source,
                        dur_ms=(time.perf_counter() - t0) * 1e3)
        return new_params

    # --- sampling tail -------------------------------------------------------

    def _sample(self, logits, key):
        return fused_sample(logits, key, temperature=self.temperature,
                            top_k=self.top_k, top_p=self.top_p)

    # --- prefill chunk -------------------------------------------------------

    def _prefill_chunk(self, params, pool, table_row, tokens, start, live,
                       key):
        # trace-time step-anatomy span (PR 6): every HLO of the chunk
        # program carries the serve_prefill scope in device traces — the
        # join key request lifecycle records correlate on; no-op when
        # monitoring is off, and never touches the stable avals
        with monitor_spans.span("serve_prefill"):
            if self.tp > 1:
                return self._tp_prefill(params, pool, table_row, tokens,
                                        start, live, key)
            return self._prefill_chunk_body(params, pool, table_row,
                                            tokens, start, live, key)

    def _prefill_chunk_body(self, params, pool, table_row, tokens, start,
                            live, key):
        """One chunk of ONE slot's prompt: ``tokens`` (C,) are prompt
        positions [start, start+C) with the first ``live`` valid (the
        final chunk is ragged; pad rows are written but land either
        behind the live frontier — overwritten by decode later — or in
        the dead block). Returns ``(pool, first_token, last_logits)``;
        the token/logits are meaningful on the LAST chunk only (row
        ``live - 1`` is then the prompt's final token). ``start`` and
        ``live`` are traced: one executable for every chunk of every
        prompt."""
        model, c = self.model, self.config
        C, B = self.prefill_chunk_size, self.block_size
        nb, max_s = self.max_blocks_per_slot, self.max_s
        h_kv, group = c.local_kv_heads, c.local_heads // c.local_kv_heads
        d = c.head_dim
        start = jnp.asarray(start, jnp.int32)
        live = jnp.asarray(live, jnp.int32)

        x = model.embedding(params["embedding"], tokens[None])  # (1, C, H)
        pos = start + jnp.arange(C, dtype=jnp.int32)
        ptab = params["pos_embedding"]
        x = x + jnp.take(ptab, jnp.minimum(pos, ptab.shape[0] - 1),
                         axis=0)[None]

        # the chunk's target blocks: C/B table entries from start/B on
        # (chunks are block-aligned: start is always a B-multiple — the
        # scheduler resumes at the shared-prefix frontier, a whole
        # number of blocks — and C is a B-multiple); blocks with no
        # live token redirect to the dead block so the ragged tail
        # cannot touch another slot's memory. Earlier table entries
        # (a shared prefix) are READ via the gather below, never
        # written: the copy-on-write discipline in one index bound
        nblk = C // B
        ids = jax.lax.dynamic_slice(table_row.astype(jnp.int32),
                                    (start // B,), (nblk,))
        blk_live = (jnp.arange(nblk, dtype=jnp.int32) * B) < live
        ids = jnp.where(blk_live, ids, DEAD_BLOCK)

        scale = 1.0 / d ** 0.5
        js = jnp.arange(max_s, dtype=jnp.int32)
        mask = js[None, None, None, :] <= pos[None, None, :, None]
        ck, cv = pool["k"], pool["v"]
        ks, vs = pool.get("k_scale"), pool.get("v_scale")
        for i in range(c.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            h_in = fused_layer_norm(x, layer["ln1_w"], layer["ln1_b"])
            q, k, v = model._proj_qkv_bshd(layer, h_in)
            # chunk k/v → (C/B, h_kv, B, d) block scatter at traced ids
            kb = k[0].reshape(nblk, B, h_kv, d).transpose(0, 2, 1, 3)
            vb = v[0].reshape(nblk, B, h_kv, d).transpose(0, 2, 1, 3)
            if self.quantized:
                # quantize on write: per (block, row) scales over
                # (h_kv, d) — the same ids, so the dead-block redirect
                # covers the scale planes too
                kq, ksc = _quant_rows(kb, (1, 3), qmax=self._qmax,
                                      qdtype=self._qdtype)
                vq, vsc = _quant_rows(vb, (1, 3), qmax=self._qmax,
                                      qdtype=self._qdtype)
                ck = ck.at[i, ids].set(kq)
                cv = cv.at[i, ids].set(vq)
                ks = ks.at[i, ids].set(ksc)
                vs = vs.at[i, ids].set(vsc)
            else:
                ck = ck.at[i, ids].set(kb.astype(ck.dtype))
                cv = cv.at[i, ids].set(vb.astype(cv.dtype))
            # prefix attention: chunk queries × the slot's gathered
            # padded cache (chunk rows included — causal within the
            # chunk falls out of the same mask); int8 pools dequantize
            # in the gather (prefill is compute-bound — simplicity is
            # cheap here; the HBM-bound decode path dequantizes
            # in-kernel instead)
            if self.quantized:
                k_all = (ck[i][table_row].astype(jnp.float32)
                         * ks[i][table_row][:, None, :, None]) \
                    .transpose(1, 0, 2, 3).reshape(h_kv, max_s, d)
                v_all = (cv[i][table_row].astype(jnp.float32)
                         * vs[i][table_row][:, None, :, None]) \
                    .transpose(1, 0, 2, 3).reshape(h_kv, max_s, d)
            else:
                k_all = ck[i][table_row].transpose(1, 0, 2, 3) \
                    .reshape(h_kv, max_s, d)
                v_all = cv[i][table_row].transpose(1, 0, 2, 3) \
                    .reshape(h_kv, max_s, d)
            qg = q[0].reshape(C, h_kv, group, d).transpose(1, 2, 0, 3)
            s = jnp.einsum("hgcd,hsd->hgcs", qg,
                           k_all.astype(qg.dtype),
                           preferred_element_type=jnp.float32) * scale
            s = jnp.where(mask, s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("hgcs,hsd->hgcd", p.astype(v_all.dtype), v_all)
            ctx = ctx.transpose(2, 0, 1, 3).reshape(1, C, c.local_heads, d)
            x = x + model._proj_attn_out(layer, ctx)
            x = x + model._mlp(layer, fused_layer_norm(
                x, layer["ln2_w"], layer["ln2_b"]))
        x = fused_layer_norm(x, params["lnf_w"], params["lnf_b"])
        last = jax.lax.dynamic_slice(
            x, (jnp.int32(0), live - 1, jnp.int32(0)),
            (1, 1, c.hidden_size))
        logits = model.unembed(params, last)[:, 0]  # (1, V)
        return (self._pool_out(ck, cv, ks, vs),
                self._sample(logits, key)[0], logits[0])

    # --- decode step ---------------------------------------------------------

    def _decode_step(self, params, pool, tables, tokens, lengths, key):
        # same trace-time scope as above: one span per TRACE (not per
        # token), prefixing the whole decode step's HLOs in device traces
        with monitor_spans.span("serve_decode"):
            if self.tp > 1:
                return self._tp_decode(params, pool, tables, tokens,
                                       lengths, key)
            return self._decode_step_body(params, pool, tables, tokens,
                                          lengths, key)

    def _decode_step_body(self, params, pool, tables, tokens, lengths, key):
        """One token for EVERY slot: ``tokens`` (S,) are each slot's
        incoming sampled tokens, ``lengths`` (S,) the live rows INCLUDING
        them (0 = dead slot: write lands in the dead block, attention
        output zeros, sampled value ignored by the host). Returns
        ``(pool, next_tokens, logits)``. Avals are churn-independent:
        compiled exactly once."""
        model, c = self.model, self.config
        B = self.block_size
        lengths = lengths.astype(jnp.int32)
        pos = jnp.maximum(lengths - 1, 0)  # the incoming token's position
        x = model.embedding(params["embedding"], tokens[:, None])
        ptab = params["pos_embedding"]
        x = x + jnp.take(ptab, jnp.minimum(pos, ptab.shape[0] - 1),
                         axis=0)[:, None]
        tables = tables.astype(jnp.int32)
        bid = jnp.take_along_axis(tables, (pos // B)[:, None], axis=1)[:, 0]
        # dead slots (lengths == 0) write to the dead block NO MATTER what
        # their table row says: a slot mid-prefill is dead for decode but
        # its table already names real blocks — an unredirected write
        # would corrupt its own freshly prefilled cache
        bid = jnp.where(lengths > 0, bid, DEAD_BLOCK)
        row = pos % B
        rel_hook = getattr(model, "decode_rel_bias", None)
        rel_bias = None if rel_hook is None else rel_hook(params)
        ck, cv = pool["k"], pool["v"]
        ks, vs = pool.get("k_scale"), pool.get("v_scale")
        for i in range(c.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            q, k_row, v_row = model.decode_qkv(layer, x)
            # per-slot (block, row) scatter into the DONATED pool; dead
            # slots carry table rows of DEAD_BLOCK, so their writes are
            # absorbed harmlessly
            if self.quantized:
                kq, ksc = _quant_rows(k_row[:, :, 0], (1, 2),  # (S,)
                                      qmax=self._qmax, qdtype=self._qdtype)
                vq, vsc = _quant_rows(v_row[:, :, 0], (1, 2),
                                      qmax=self._qmax, qdtype=self._qdtype)
                ck = ck.at[i, bid, :, row].set(kq)
                cv = cv.at[i, bid, :, row].set(vq)
                ks = ks.at[i, bid, row].set(ksc)
                vs = vs.at[i, bid, row].set(vsc)
                scales = (ks[i], vs[i])
            else:
                ck = ck.at[i, bid, :, row].set(
                    k_row[:, :, 0].astype(ck.dtype))
                cv = cv.at[i, bid, :, row].set(
                    v_row[:, :, 0].astype(cv.dtype))
                scales = None
            x = model.decode_block(layer, x, q, ck[i], cv[i], lengths,
                                   rel_bias=rel_bias, block_tables=tables,
                                   kv_scales=scales)
        x = fused_layer_norm(x, params["lnf_w"], params["lnf_b"])
        logits = model.unembed(params, x)[:, 0]  # (S, V)
        return self._pool_out(ck, cv, ks, vs), self._sample(logits, key), \
            logits

    # --- speculative round ---------------------------------------------------

    def _spec_step(self, params, pool, tables, tokens, lengths, drafted,
                   key):
        # trace-time step-anatomy span, like serve_prefill/serve_decode
        with monitor_spans.span("serve_spec"):
            if self.tp > 1:
                return self._tp_spec(params, pool, tables, tokens,
                                     lengths, drafted, key)
            return self._spec_step_body(params, pool, tables, tokens,
                                        lengths, drafted, key)

    def _spec_step_body(self, params, pool, tables, tokens, lengths,
                        drafted, key):
        """One speculative round for EVERY slot at once: ``tokens``
        (S, k+1) are each slot's pending sampled token followed by its k
        drafted continuations, ``lengths`` (S,) the live rows INCLUDING
        the pending token (0 = dead slot: writes land in the dead block,
        outputs ignored by the host), ``drafted`` (S, k) the draft ids.
        All k+1 positions are scored in one multi-token step (the
        chunked-prefill attention shape at chunk = k+1, riding the same
        gathered-cache formulation), their k/v land in the slots' pool
        blocks past the live frontier (the scheduler pre-allocated
        them), and the fused verify tail emits per-slot ``(accept_len,
        next_token)``. Rows past each slot's accepted frontier hold
        rejected-draft k/v — the scheduler rewinds tables/lengths to the
        frontier (contents-only mutation; this program never retraces).
        Returns ``(pool, accept_lens (S,), next_tokens (S,))``."""
        model, c = self.model, self.config
        B = self.block_size
        S, K1 = tokens.shape
        h_kv, group = c.local_kv_heads, c.local_heads // c.local_kv_heads
        d = c.head_dim
        max_s = self.max_s
        lengths = lengths.astype(jnp.int32)
        base = jnp.maximum(lengths - 1, 0)
        pos = base[:, None] + jnp.arange(K1, dtype=jnp.int32)[None, :]
        x = model.embedding(params["embedding"], tokens)  # (S, K1, H)
        ptab = params["pos_embedding"]
        x = x + jnp.take(ptab, jnp.minimum(pos, ptab.shape[0] - 1),
                         axis=0)
        tables = tables.astype(jnp.int32)
        bid = jnp.take_along_axis(tables, pos // B, axis=1)  # (S, K1)
        # dead slots write to the dead block NO MATTER what their table
        # row says (same redirect as the decode step)
        bid = jnp.where(lengths[:, None] > 0, bid, DEAD_BLOCK)
        row = pos % B
        scale = 1.0 / d ** 0.5
        js = jnp.arange(max_s, dtype=jnp.int32)
        # prefix-causal per drafted row: row j of slot i sees keys
        # [0, base_i + j] — broadcastable over (S, h_kv, group, K1, max_s)
        mask = js[None, None, None, None, :] <= pos[:, None, None, :, None]
        ck, cv = pool["k"], pool["v"]
        ks, vs = pool.get("k_scale"), pool.get("v_scale")
        for i in range(c.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            h_in = fused_layer_norm(x, layer["ln1_w"], layer["ln1_b"])
            q, k, v = model._proj_qkv_bshd(layer, h_in)
            # (S, K1) rows scattered at traced (block, row) coordinates
            if self.quantized:
                kq, ksc = _quant_rows(k, (2, 3),  # scales (S, K1)
                                      qmax=self._qmax, qdtype=self._qdtype)
                vq, vsc = _quant_rows(v, (2, 3),
                                      qmax=self._qmax, qdtype=self._qdtype)
                ck = ck.at[i, bid, :, row].set(kq)
                cv = cv.at[i, bid, :, row].set(vq)
                ks = ks.at[i, bid, row].set(ksc)
                vs = vs.at[i, bid, row].set(vsc)
            else:
                ck = ck.at[i, bid, :, row].set(k.astype(ck.dtype))
                cv = cv.at[i, bid, :, row].set(v.astype(cv.dtype))
            # K1 queries per slot × the slot's gathered padded cache —
            # the prefill-chunk attention at chunk = k+1, batched over
            # the slot array (int8 pools dequantize in the gather)
            if self.quantized:
                k_all = (ck[i][tables].astype(jnp.float32)
                         * ks[i][tables][:, :, None, :, None])
                v_all = (cv[i][tables].astype(jnp.float32)
                         * vs[i][tables][:, :, None, :, None])
            else:
                k_all, v_all = ck[i][tables], cv[i][tables]
            k_all = k_all.transpose(0, 2, 1, 3, 4) \
                .reshape(S, h_kv, max_s, d)
            v_all = v_all.transpose(0, 2, 1, 3, 4) \
                .reshape(S, h_kv, max_s, d)
            qg = q.reshape(S, K1, h_kv, group, d).transpose(0, 2, 3, 1, 4)
            s = jnp.einsum("bhgcd,bhsd->bhgcs", qg,
                           k_all.astype(qg.dtype),
                           preferred_element_type=jnp.float32) * scale
            s = jnp.where(mask, s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("bhgcs,bhsd->bhgcd", p.astype(v_all.dtype),
                             v_all)
            ctx = ctx.transpose(0, 3, 1, 2, 4).reshape(S, K1,
                                                       c.local_heads, d)
            x = x + model._proj_attn_out(layer, ctx)
            x = x + model._mlp(layer, fused_layer_norm(
                x, layer["ln2_w"], layer["ln2_b"]))
        x = fused_layer_norm(x, params["lnf_w"], params["lnf_b"])
        logits = model.unembed(params, x)  # (S, K1, V)
        a, nxt = fused_verify(logits, drafted, key,
                              temperature=self.temperature,
                              top_k=self.top_k, top_p=self.top_p)
        return self._pool_out(ck, cv, ks, vs), a, nxt

    # --- tree speculative round ----------------------------------------------

    def _tree_step(self, params, pool, tables, tokens, lengths, parents,
                   anc, levels, key):
        # trace-time step-anatomy span, like serve_spec
        with monitor_spans.span("serve_spec_tree"):
            return self._tree_step_body(params, pool, tables, tokens,
                                        lengths, parents, anc, levels, key)

    def _tree_step_body(self, params, pool, tables, tokens, lengths,
                        parents, anc, levels, key):
        """One TREE speculative round for EVERY slot at once: ``tokens``
        (S, N+1) are each slot's pending sampled token (the root, column
        0) plus its N drafted tree-node tokens, ``parents``/``anc`` the
        :class:`~apex_tpu.spec.tree.DraftTree` operands tiled over the
        slot array, ``levels`` a ``(depth+1,)`` iota whose SHAPE carries
        the static depth. Unlike the chain round nothing is scattered
        into the pool before the verdict — sibling nodes SHARE positions,
        so a pre-write would collide; each node instead attends the
        committed cache rows (``js < base``) plus its own root path via
        the ``anc`` tree-attention mask under ONE softmax, the fused
        tree-verify tail picks the deepest accepted path, and only the
        WINNING path's k/v land in the slots' pool blocks (level ``l`` at
        row ``base + l``; levels past ``accept_len`` — and dead slots —
        redirect to the dead block). The scheduler then just commits the
        emitted tokens: no rejected rows ever touched the pool, so the
        rewind is pure host bookkeeping. Returns ``(pool, accept_lens
        (S,), j_star (S,), next_tokens (S,))`` — one executable per
        static ``(N+1, depth+1)``."""
        model, c = self.model, self.config
        B = self.block_size
        S, N1 = tokens.shape
        h_kv, group = c.local_kv_heads, c.local_heads // c.local_kv_heads
        d = c.head_dim
        max_s = self.max_s
        lengths = lengths.astype(jnp.int32)
        base = jnp.maximum(lengths - 1, 0)
        depth_vec = jnp.sum(anc.astype(jnp.int32), axis=-1) - 1  # (S, N1)
        positions = base[:, None] + depth_vec  # siblings SHARE positions
        x = model.embedding(params["embedding"], tokens)  # (S, N1, H)
        ptab = params["pos_embedding"]
        x = x + jnp.take(ptab, jnp.minimum(positions, ptab.shape[0] - 1),
                         axis=0)
        tables = tables.astype(jnp.int32)
        scale = 1.0 / d ** 0.5
        js = jnp.arange(max_s, dtype=jnp.int32)
        # committed rows only — the root's own k/v rides the TREE part
        # (node 0), not the cache, until the verdict commits it
        cache_mask = js[None, None, None, None, :] \
            < base[:, None, None, None, None]
        tree_mask = (anc != 0)[:, None, None]  # (S, 1, 1, N1, N1)
        ck, cv = pool["k"], pool["v"]
        ks, vs = pool.get("k_scale"), pool.get("v_scale")
        tks, tvs = [], []
        for i in range(c.num_layers):
            layer = jax.tree.map(lambda a_, i=i: a_[i], params["layers"])
            h_in = fused_layer_norm(x, layer["ln1_w"], layer["ln1_b"])
            q, k, v = model._proj_qkv_bshd(layer, h_in)  # (S, N1, h, d)
            tks.append(k)
            tvs.append(v)
            # N1 queries per slot × the slot's gathered padded cache —
            # the chain round's gather, minus the pre-verdict scatter
            if self.quantized:
                k_all = (ck[i][tables].astype(jnp.float32)
                         * ks[i][tables][:, :, None, :, None])
                v_all = (cv[i][tables].astype(jnp.float32)
                         * vs[i][tables][:, :, None, :, None])
            else:
                k_all, v_all = ck[i][tables], cv[i][tables]
            k_all = k_all.transpose(0, 2, 1, 3, 4) \
                .reshape(S, h_kv, max_s, d)
            v_all = v_all.transpose(0, 2, 1, 3, 4) \
                .reshape(S, h_kv, max_s, d)
            qg = q.reshape(S, N1, h_kv, group, d).transpose(0, 2, 3, 1, 4)
            s_c = jnp.einsum("bhgcd,bhsd->bhgcs", qg,
                             k_all.astype(qg.dtype),
                             preferred_element_type=jnp.float32) * scale
            s_c = jnp.where(cache_mask, s_c, NEG_INF)
            kt = k.transpose(0, 2, 1, 3)  # (S, h_kv, N1, d)
            vt = v.transpose(0, 2, 1, 3)
            s_t = jnp.einsum("bhgcd,bhnd->bhgcn", qg, kt.astype(qg.dtype),
                             preferred_element_type=jnp.float32) * scale
            s_t = jnp.where(tree_mask, s_t, NEG_INF)
            # ONE softmax across cache + tree keys — exactly the
            # distribution the committed-path decode would compute
            p = jax.nn.softmax(jnp.concatenate([s_c, s_t], axis=-1),
                               axis=-1)
            p_c, p_t = p[..., :max_s], p[..., max_s:]
            ctx = jnp.einsum("bhgcs,bhsd->bhgcd", p_c.astype(v_all.dtype),
                             v_all) \
                + jnp.einsum("bhgcn,bhnd->bhgcd", p_t.astype(vt.dtype), vt)
            ctx = ctx.transpose(0, 3, 1, 2, 4).reshape(S, N1,
                                                       c.local_heads, d)
            x = x + model._proj_attn_out(layer, ctx)
            x = x + model._mlp(layer, fused_layer_norm(
                x, layer["ln2_w"], layer["ln2_b"]))
        x = fused_layer_norm(x, params["lnf_w"], params["lnf_b"])
        logits = model.unembed(params, x)  # (S, N1, V)
        a, j_star, nxt = fused_verify_tree(
            logits, tokens, parents, anc, key,
            temperature=self.temperature, top_k=self.top_k,
            top_p=self.top_p)
        # commit the winning path: level l of j_star's root path (root =
        # level 0 = the pending token) lands at pool row base + l; levels
        # past accept_len — and dead slots — redirect to the dead block
        ii = jnp.arange(N1, dtype=jnp.int32)
        onpath = jnp.einsum(
            "si,sin->sn",
            (ii[None] == j_star[:, None]).astype(jnp.float32),
            anc.astype(jnp.float32))  # (S, N1)
        lvl = onpath[:, None, :] * (
            depth_vec[:, None, :] == levels[None, :, None]
        ).astype(jnp.float32)  # (S, depth+1, N1)
        wpos = base[:, None] + levels[None, :]  # (S, depth+1)
        valid = (levels[None, :] <= a[:, None]) & (lengths[:, None] > 0)
        bid = jnp.take_along_axis(tables, wpos // B, axis=1)
        bid = jnp.where(valid, bid, DEAD_BLOCK)
        row = wpos % B
        for i in range(c.num_layers):
            sel_k = jnp.einsum("bln,bnhd->blhd",
                               lvl.astype(tks[i].dtype), tks[i])
            sel_v = jnp.einsum("bln,bnhd->blhd",
                               lvl.astype(tvs[i].dtype), tvs[i])
            if self.quantized:
                kq, ksc = _quant_rows(sel_k, (2, 3),  # scales (S, depth+1)
                                      qmax=self._qmax, qdtype=self._qdtype)
                vq, vsc = _quant_rows(sel_v, (2, 3),
                                      qmax=self._qmax, qdtype=self._qdtype)
                ck = ck.at[i, bid, :, row].set(kq)
                cv = cv.at[i, bid, :, row].set(vq)
                ks = ks.at[i, bid, row].set(ksc)
                vs = vs.at[i, bid, row].set(vsc)
            else:
                ck = ck.at[i, bid, :, row].set(sel_k.astype(ck.dtype))
                cv = cv.at[i, bid, :, row].set(sel_v.astype(cv.dtype))
        return self._pool_out(ck, cv, ks, vs), a, j_star, nxt

    # --- tensor-parallel step bodies (plan.tp >= 2) --------------------------
    #
    # Per-shard twins of the bodies above, run INSIDE shard_map: params
    # arrive as shard_params_for_tp slices, the pool's kv-head axis is
    # this shard's contiguous slice (block ids/tables/free list are
    # GLOBAL — one logical pool), projections ride the ring-overlapped
    # collective matmuls (apex_tpu.serving.tp helpers over
    # ops/collective_matmul), attention math is unchanged at local head
    # counts (GQA group size is tp-invariant since kv_heads % tp), the
    # int8 scales pmax-compose to the tp=1 values, and the sampling/
    # verify tails psum-compose so every shard emits the same tokens.

    def _prefill_chunk_body_tp(self, params, pool, table_row, tokens,
                               start, live, key):
        c = self.config
        axis, tp = tp_serving.TENSOR_AXIS, self.tp
        C, B = self.prefill_chunk_size, self.block_size
        max_s = self.max_s
        h_loc, hkv_loc = c.num_heads // tp, c.kv_heads // tp
        group, d = h_loc // hkv_loc, c.head_dim
        params = tp_serving.take_shard(params)
        start = jnp.asarray(start, jnp.int32)
        live = jnp.asarray(live, jnp.int32)

        emb = params["embedding"]["weight"]  # (V/tp, H)
        x = tp_serving.vocab_embed(emb, tokens[None], axis=axis)
        pos = start + jnp.arange(C, dtype=jnp.int32)
        ptab = params["pos_embedding"]
        x = x + jnp.take(ptab, jnp.minimum(pos, ptab.shape[0] - 1),
                         axis=0)[None]

        nblk = C // B
        ids = jax.lax.dynamic_slice(table_row.astype(jnp.int32),
                                    (start // B,), (nblk,))
        blk_live = (jnp.arange(nblk, dtype=jnp.int32) * B) < live
        ids = jnp.where(blk_live, ids, DEAD_BLOCK)

        scale = 1.0 / d ** 0.5
        js = jnp.arange(max_s, dtype=jnp.int32)
        mask = js[None, None, None, :] <= pos[None, None, :, None]
        ck, cv = pool["k"], pool["v"]
        ks, vs = pool.get("k_scale"), pool.get("v_scale")
        for i in range(c.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            h_in = fused_layer_norm(x, layer["ln1_w"], layer["ln1_b"])
            y = tp_serving.column_parallel(
                h_in[0], layer["qkv"]["weight"],
                layer["qkv"].get("bias"), axis=axis, seq_dim=0)
            q = y[:, :h_loc * d].reshape(C, h_loc, d)
            k = y[:, h_loc * d:(h_loc + hkv_loc) * d] \
                .reshape(C, hkv_loc, d)
            v = y[:, (h_loc + hkv_loc) * d:].reshape(C, hkv_loc, d)
            kb = k.reshape(nblk, B, hkv_loc, d).transpose(0, 2, 1, 3)
            vb = v.reshape(nblk, B, hkv_loc, d).transpose(0, 2, 1, 3)
            if self.quantized:
                kq, ksc = tp_serving.quant_rows_tp(kb, (1, 3), axis)
                vq, vsc = tp_serving.quant_rows_tp(vb, (1, 3), axis)
                ck = ck.at[i, ids].set(kq)
                cv = cv.at[i, ids].set(vq)
                ks = ks.at[i, ids].set(ksc)
                vs = vs.at[i, ids].set(vsc)
                k_all = (ck[i][table_row].astype(jnp.float32)
                         * ks[i][table_row][:, None, :, None]) \
                    .transpose(1, 0, 2, 3).reshape(hkv_loc, max_s, d)
                v_all = (cv[i][table_row].astype(jnp.float32)
                         * vs[i][table_row][:, None, :, None]) \
                    .transpose(1, 0, 2, 3).reshape(hkv_loc, max_s, d)
            else:
                ck = ck.at[i, ids].set(kb.astype(ck.dtype))
                cv = cv.at[i, ids].set(vb.astype(cv.dtype))
                k_all = ck[i][table_row].transpose(1, 0, 2, 3) \
                    .reshape(hkv_loc, max_s, d)
                v_all = cv[i][table_row].transpose(1, 0, 2, 3) \
                    .reshape(hkv_loc, max_s, d)
            qg = q.reshape(C, hkv_loc, group, d).transpose(1, 2, 0, 3)
            s = jnp.einsum("hgcd,hsd->hgcs", qg,
                           k_all.astype(qg.dtype),
                           preferred_element_type=jnp.float32) * scale
            s = jnp.where(mask[0], s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("hgcs,hsd->hgcd", p.astype(v_all.dtype),
                             v_all)
            ctx = ctx.transpose(2, 0, 1, 3).reshape(C, h_loc * d)
            out = tp_serving.row_parallel(
                ctx, layer["attn_out"]["weight"],
                layer["attn_out"].get("bias"), axis=axis, seq_dim=0)
            x = x + out[None]
            h2 = fused_layer_norm(x, layer["ln2_w"], layer["ln2_b"])
            h = tp_serving.column_parallel(
                h2[0], layer["mlp_up"]["weight"],
                layer["mlp_up"].get("bias"), axis=axis, seq_dim=0)
            h = jax.nn.gelu(h, approximate=True)
            m = tp_serving.row_parallel(
                h, layer["mlp_down"]["weight"],
                layer["mlp_down"].get("bias"), axis=axis, seq_dim=0)
            x = x + m[None]
        x = fused_layer_norm(x, params["lnf_w"], params["lnf_b"])
        last = jax.lax.dynamic_slice(
            x, (jnp.int32(0), live - 1, jnp.int32(0)),
            (1, 1, c.hidden_size))
        logits = jnp.dot(last[0], emb.T)  # (1, V/tp)
        tok = tp_serving.sample_tp(logits, key,
                                   temperature=self.temperature,
                                   axis=axis)[0]
        return self._pool_out(ck, cv, ks, vs), tok, logits[0]

    def _decode_step_body_tp(self, params, pool, tables, tokens, lengths,
                             key):
        c = self.config
        axis, tp = tp_serving.TENSOR_AXIS, self.tp
        B = self.block_size
        h_loc, hkv_loc = c.num_heads // tp, c.kv_heads // tp
        d = c.head_dim
        params = tp_serving.take_shard(params)
        lengths = lengths.astype(jnp.int32)
        pos = jnp.maximum(lengths - 1, 0)
        emb = params["embedding"]["weight"]
        x = tp_serving.vocab_embed(emb, tokens[:, None], axis=axis)
        ptab = params["pos_embedding"]
        x = x + jnp.take(ptab, jnp.minimum(pos, ptab.shape[0] - 1),
                         axis=0)[:, None]
        tables = tables.astype(jnp.int32)
        bid = jnp.take_along_axis(tables, (pos // B)[:, None],
                                  axis=1)[:, 0]
        bid = jnp.where(lengths > 0, bid, DEAD_BLOCK)
        row = pos % B
        ck, cv = pool["k"], pool["v"]
        ks, vs = pool.get("k_scale"), pool.get("v_scale")
        for i in range(c.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            h_in = fused_layer_norm(x, layer["ln1_w"], layer["ln1_b"])
            y = tp_serving.column_parallel(
                h_in[:, 0], layer["qkv"]["weight"],
                layer["qkv"].get("bias"), axis=axis, seq_dim=0)
            q = y[:, :h_loc * d].reshape(-1, h_loc, d)
            k_row = y[:, h_loc * d:(h_loc + hkv_loc) * d] \
                .reshape(-1, hkv_loc, d)
            v_row = y[:, (h_loc + hkv_loc) * d:].reshape(-1, hkv_loc, d)
            if self.quantized:
                kq, ksc = tp_serving.quant_rows_tp(k_row, (1, 2), axis)
                vq, vsc = tp_serving.quant_rows_tp(v_row, (1, 2), axis)
                ck = ck.at[i, bid, :, row].set(kq)
                cv = cv.at[i, bid, :, row].set(vq)
                ks = ks.at[i, bid, row].set(ksc)
                vs = vs.at[i, bid, row].set(vsc)
                k_scale, v_scale = ks[i], vs[i]
            else:
                ck = ck.at[i, bid, :, row].set(k_row.astype(ck.dtype))
                cv = cv.at[i, bid, :, row].set(v_row.astype(cv.dtype))
                k_scale = v_scale = None
            # the paged decode-attention kernel, untouched: this shard
            # owns a contiguous kv-head slice, so block tables, length
            # masking, and the int8 scale indirection read identically
            ctx = decode_attention(q, ck[i], cv[i], lengths,
                                   block_tables=tables,
                                   k_scale=k_scale, v_scale=v_scale)
            out = tp_serving.row_parallel(
                ctx.reshape(-1, h_loc * d), layer["attn_out"]["weight"],
                layer["attn_out"].get("bias"), axis=axis, seq_dim=0)
            x = x + out[:, None]
            h2 = fused_layer_norm(x, layer["ln2_w"], layer["ln2_b"])
            h = tp_serving.column_parallel(
                h2[:, 0], layer["mlp_up"]["weight"],
                layer["mlp_up"].get("bias"), axis=axis, seq_dim=0)
            h = jax.nn.gelu(h, approximate=True)
            m = tp_serving.row_parallel(
                h, layer["mlp_down"]["weight"],
                layer["mlp_down"].get("bias"), axis=axis, seq_dim=0)
            x = x + m[:, None]
        x = fused_layer_norm(x, params["lnf_w"], params["lnf_b"])
        logits = jnp.dot(x[:, 0], emb.T)  # (S, V/tp)
        toks = tp_serving.sample_tp(logits, key,
                                    temperature=self.temperature,
                                    axis=axis)
        return self._pool_out(ck, cv, ks, vs), toks, logits

    def _spec_step_body_tp(self, params, pool, tables, tokens, lengths,
                           drafted, key):
        c = self.config
        axis, tp = tp_serving.TENSOR_AXIS, self.tp
        B = self.block_size
        S, K1 = tokens.shape
        h_loc, hkv_loc = c.num_heads // tp, c.kv_heads // tp
        group, d = h_loc // hkv_loc, c.head_dim
        max_s = self.max_s
        params = tp_serving.take_shard(params)
        lengths = lengths.astype(jnp.int32)
        base = jnp.maximum(lengths - 1, 0)
        pos = base[:, None] + jnp.arange(K1, dtype=jnp.int32)[None, :]
        emb = params["embedding"]["weight"]
        x = tp_serving.vocab_embed(emb, tokens, axis=axis)  # (S, K1, H)
        ptab = params["pos_embedding"]
        x = x + jnp.take(ptab, jnp.minimum(pos, ptab.shape[0] - 1),
                         axis=0)
        tables = tables.astype(jnp.int32)
        bid = jnp.take_along_axis(tables, pos // B, axis=1)
        bid = jnp.where(lengths[:, None] > 0, bid, DEAD_BLOCK)
        row = pos % B
        scale = 1.0 / d ** 0.5
        js = jnp.arange(max_s, dtype=jnp.int32)
        mask = js[None, None, None, None, :] \
            <= pos[:, None, None, :, None]
        ck, cv = pool["k"], pool["v"]
        ks, vs = pool.get("k_scale"), pool.get("v_scale")
        for i in range(c.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            h_in = fused_layer_norm(x, layer["ln1_w"], layer["ln1_b"])
            y = tp_serving.column_parallel(
                h_in, layer["qkv"]["weight"], layer["qkv"].get("bias"),
                axis=axis, seq_dim=0)  # (S, K1, F/tp)
            q = y[..., :h_loc * d]
            k = y[..., h_loc * d:(h_loc + hkv_loc) * d] \
                .reshape(S, K1, hkv_loc, d)
            v = y[..., (h_loc + hkv_loc) * d:].reshape(S, K1, hkv_loc, d)
            if self.quantized:
                kq, ksc = tp_serving.quant_rows_tp(k, (2, 3), axis)
                vq, vsc = tp_serving.quant_rows_tp(v, (2, 3), axis)
                ck = ck.at[i, bid, :, row].set(kq)
                cv = cv.at[i, bid, :, row].set(vq)
                ks = ks.at[i, bid, row].set(ksc)
                vs = vs.at[i, bid, row].set(vsc)
                k_all = (ck[i][tables].astype(jnp.float32)
                         * ks[i][tables][:, :, None, :, None])
                v_all = (cv[i][tables].astype(jnp.float32)
                         * vs[i][tables][:, :, None, :, None])
            else:
                ck = ck.at[i, bid, :, row].set(k.astype(ck.dtype))
                cv = cv.at[i, bid, :, row].set(v.astype(cv.dtype))
                k_all, v_all = ck[i][tables], cv[i][tables]
            k_all = k_all.transpose(0, 2, 1, 3, 4) \
                .reshape(S, hkv_loc, max_s, d)
            v_all = v_all.transpose(0, 2, 1, 3, 4) \
                .reshape(S, hkv_loc, max_s, d)
            qg = q.reshape(S, K1, hkv_loc, group, d) \
                .transpose(0, 2, 3, 1, 4)
            s = jnp.einsum("bhgcd,bhsd->bhgcs", qg,
                           k_all.astype(qg.dtype),
                           preferred_element_type=jnp.float32) * scale
            s = jnp.where(mask, s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("bhgcs,bhsd->bhgcd", p.astype(v_all.dtype),
                             v_all)
            ctx = ctx.transpose(0, 3, 1, 2, 4).reshape(S, K1,
                                                       h_loc * d)
            out = tp_serving.row_parallel(
                ctx, layer["attn_out"]["weight"],
                layer["attn_out"].get("bias"), axis=axis, seq_dim=0)
            x = x + out
            h2 = fused_layer_norm(x, layer["ln2_w"], layer["ln2_b"])
            h = tp_serving.column_parallel(
                h2, layer["mlp_up"]["weight"],
                layer["mlp_up"].get("bias"), axis=axis, seq_dim=0)
            h = jax.nn.gelu(h, approximate=True)
            m = tp_serving.row_parallel(
                h, layer["mlp_down"]["weight"],
                layer["mlp_down"].get("bias"), axis=axis, seq_dim=0)
            x = x + m
        x = fused_layer_norm(x, params["lnf_w"], params["lnf_b"])
        logits = jnp.dot(x, emb.T)  # (S, K1, V/tp)
        a, nxt = tp_serving.verify_greedy_tp(logits, drafted, axis=axis)
        return self._pool_out(ck, cv, ks, vs), a, nxt

    # --- the serving loop ----------------------------------------------------

    def make_scheduler(self, *, prefix_cache: bool = True,
                       prefix_capacity_blocks: Optional[int] = None,
                       policy: Optional[SLOPolicy] = None) -> Scheduler:
        """A fresh scheduler + allocator matching this engine's pool.

        ``prefix_cache=True`` (the default) attaches a
        :class:`~apex_tpu.serving.kv_blocks.PrefixCache` over the same
        allocator — full prompt blocks are shared copy-on-write across
        requests and survive them as reclaimable warm capacity.
        ``policy`` injects an :class:`~apex_tpu.serving.scheduler.
        SLOPolicy` (one is created by default) for SLO-aware dispatch
        when telemetry is attached."""
        alloc = BlockAllocator(self.num_blocks)
        cache = (PrefixCache(alloc, self.block_size,
                             capacity_blocks=prefix_capacity_blocks)
                 if prefix_cache else None)
        return Scheduler(
            num_slots=self.num_slots, block_size=self.block_size,
            max_blocks_per_slot=self.max_blocks_per_slot,
            allocator=alloc, prefill_chunk=self.prefill_chunk_size,
            prefix_cache=cache,
            policy=policy if policy is not None else SLOPolicy())

    def serve(self, params, requests: List[Request], *,
              key: Optional[jax.Array] = None,
              clock: Optional[Callable[[], float]] = None,
              scheduler: Optional[Scheduler] = None,
              telemetry=None, draft=None, adaptive=None,
              pool=None) -> List[Request]:
        """Run ``requests`` to completion; returns them in completion
        order with tokens and latency stamps filled in.

        Each loop iteration runs at most ONE prefill chunk and ONE
        decode step over the whole slot array — admission and prefill
        interleave with decode instead of stalling it. ``clock`` (a
        monotonically advancing ``() -> seconds`` callable, default
        ``time.perf_counter``) drives arrival replay and the latency
        stamps; requests whose ``arrival_s`` is in the future are held
        until the clock passes it. ``scheduler`` injects a pre-built
        scheduler (tests script churn through it).

        ``telemetry`` attaches a :class:`~apex_tpu.serving.telemetry.
        ServeTelemetry` — request lifecycle events, streaming latency
        histograms, periodic ``serve_window`` records, and the anomaly
        layer, all host-side and outside the jitted steps (the
        zero-recompile contract holds with telemetry on). When the
        monitor registry is enabled and no tracker is passed, a default
        one is attached so an instrumented process gets request traces
        for free; pass ``telemetry=False`` to suppress even that (timed
        baseline runs must not pay emit costs a comparison leg does
        not); with monitoring off and no tracker, every hook site is a
        single ``is None`` test.

        ``draft`` attaches a :class:`~apex_tpu.spec.drafter.Drafter`
        for speculative serving: spec rounds replace plain decode steps
        whenever every decoding slot has k+1 rows of headroom (near the
        row cap the loop falls back to the plain step — a host-side
        choice, never a retrace), interleaving with chunked prefill
        exactly as decode does. Greedy output stays token-identical to
        ``draft=None`` across arbitrary churn; acceptance is accounted
        in ``last_stats`` and per-round ``spec`` lifecycle events.

        A TREE drafter (``is_tree_drafter(draft)``: ``propose_tree``
        plus static ``depth``/``branching``) upgrades the round to the
        tree-verify step; per round the loop degrades tree → chain →
        plain on row or drafter-pool headroom (every rung is a
        pre-compiled program — the ladder never stalls and never
        retraces). A :class:`~apex_tpu.spec.tree.PagedModelDrafter` is
        bound to the scheduler's allocator here, so its KV blocks live
        in THIS pool's accounting. ``adaptive`` (an
        :class:`~apex_tpu.spec.tree.AdaptiveSpecController`) re-picks
        the round's (depth, branching) from its static choice set.

        ``pool`` injects a pre-populated block pool (the disaggregated
        decode role: :func:`~apex_tpu.serving.disagg.ingest_handoff`
        streamed prefilled KV blocks into it); it must have been
        created by THIS engine's :meth:`init_pool` and be paired with
        the ``scheduler`` whose allocator/prefix cache own its live
        blocks. Default: a fresh zeroed pool."""
        if self.temperature > 0 and key is None:
            raise ValueError("temperature > 0 serving requires a key")
        if draft is not None:
            if getattr(self.model, "decode_rel_bias", None) is not None:
                # the spec round's k+1-row scoring does not thread the
                # bucketed relative bias the plain decode step applies;
                # verifying against unbiased spec logits would silently
                # break the token-identical contract (same composition
                # guard as kv_dtype='int8')
                raise ValueError(
                    "serve(draft=...) cannot speculate for a model "
                    "with a decode relative-position bias (the spec "
                    "verify step does not carry the bucketed bias) — "
                    "serve this model with draft=None")
            if self.tp > 1 and self.temperature > 0:
                raise ValueError(
                    "serve(draft=...) with temperature="
                    f"{self.temperature} is unsupported under plan.tp="
                    f"{self.tp}: the sharded verify tail composes the "
                    "greedy argmax across shards but does not carry "
                    "the rejection-sampling draw — serve greedy "
                    "(temperature=0.0) or with plan.tp=1")
            from apex_tpu.spec.drafter import validate_drafter
            from apex_tpu.spec.tree import is_tree_drafter
            if is_tree_drafter(draft) and self.tp > 1:
                raise ValueError(
                    f"serve(draft=<tree drafter>) is unsupported under "
                    f"plan.tp={self.tp}: the tree-verify step has no "
                    f"sharded twin — serve tree drafts at tp=1, or use "
                    f"a chain drafter (which verifies through the tp "
                    f"spec step)")
            # eager, knob-naming validation: vocab/block_size/k/cache
            # bounds fail HERE, not as an XLA error three rounds in.
            # max_s rows suffice for the drafter: spec rounds only run
            # with k+1 rows of slot headroom (the loop falls back to
            # plain decode near the cap), so a drafter context never
            # exceeds max_s - k tokens
            validate_drafter(draft, self.config, needed_rows=self.max_s,
                             block_size=self.block_size)
        if adaptive is not None:
            from apex_tpu.spec.tree import is_tree_drafter
            if draft is None:
                raise ValueError(
                    "serve(adaptive=...) needs a drafter: the controller "
                    "picks the DRAFT shape per round — pass draft= a "
                    "tree drafter alongside it")
            if not is_tree_drafter(draft):
                raise ValueError(
                    "serve(adaptive=...) needs a TREE drafter (one with "
                    "propose_tree + static depth/branching): the "
                    "controller's choices are (depth, branching) tree "
                    "shapes — NGramTreeDrafter / PagedModelDrafter")
            for dd, _ in adaptive.choices:
                if dd > draft.depth:
                    raise ValueError(
                        f"adaptive choice set reaches depth {dd} but the "
                        f"drafter's static depth is {draft.depth} — the "
                        f"drafter cannot draft deeper than it was built "
                        f"for; shrink the choice set or deepen the "
                        f"drafter")
        if key is None:  # greedy: the key operand is ignored but keeps
            # the step signature (and avals) fixed
            key = jax.random.PRNGKey(0)  # apexlint: disable=APX502
        wall = clock is None
        clock = time.perf_counter if clock is None else clock
        t0 = clock()
        now = lambda: clock() - t0  # noqa: E731
        sched = scheduler if scheduler is not None else self.make_scheduler()
        if draft is not None and hasattr(draft, "bind"):
            # a paged drafter joins THIS scheduler's block economy: its
            # KV blocks come from the same allocator/refcount ledger the
            # target streams use (check_accounting() covers them), and
            # bind wires scheduler.draft_owner so preemption/finish
            # evict drafter blocks through the same path. Re-validate
            # after: bind sets cache_rows (the drafter-geometry cap),
            # which the pre-bind pass could not see
            from apex_tpu.spec.drafter import validate_drafter
            draft.bind(sched, block_size=self.block_size)
            validate_drafter(draft, self.config, needed_rows=self.max_s,
                             block_size=self.block_size)
        tel = telemetry
        if tel is False:  # explicit opt-out beats auto-attachment AND
            # any tracker a reused scheduler still carries — a timed
            # baseline must not fire scheduler-side hooks either
            tel = None
            sched.telemetry = None
        elif tel is None and sched.telemetry is not None:
            # a tracker attached at Scheduler construction is the
            # caller's choice: adopt it fully (engine-side hooks +
            # windows too) instead of shadowing it with an auto one
            tel = sched.telemetry
        elif tel is None and monitor_registry.enabled():
            # an instrumented process gets request traces for free; the
            # auto-attached tracker claims OK only on real hardware
            # (same convention as every bench record)
            backend = jax.default_backend()
            tel = (ServeTelemetry(slots=self.num_slots)
                   if backend == "tpu" else ServeTelemetry(
                       slots=self.num_slots, status="SKIP",
                       reason=f"auto-attached serve telemetry on "
                              f"{backend}: serving windows are TPU "
                              f"measurements"))
        if tel is not None:
            sched.telemetry = tel
            # stamp the pool-quantization knob so the serve record
            # names the pool it measured (absent on float pools)
            tel.kv_dtype = self.kv_dtype
        for r in requests:
            if tel is not None:
                r.submit_s = now()
                tel.on_submit(r, r.submit_s)
            sched.submit(r)
        if self.tp > 1:
            # keep the caller's replicated tree as the hot-swap aval
            # reference; the steps consume the sharded (tp,)-leading
            # copy placed once here (same jit cache across serve calls)
            self._swap_ref = params
            params = self._prepare_params(params)
        # a caller-provided pool must ride with ITS scheduler (the
        # disaggregated decode role: blocks ingested from a prefill
        # engine live in the pool AND in the scheduler's prefix cache /
        # allocator — one without the other would serve garbage rows)
        if pool is None:
            pool = self.init_pool()
        stats = ServeStats()
        # per-transition lifecycle records skip the per-line sink flush
        # inside the loop (one flush at the end) — the dominant cost of
        # an emit at token rates; see ServeTelemetry's overhead budget
        reg = monitor_registry.get_registry()
        flush_scope = (reg.buffered() if reg is not None and tel is not None
                       else contextlib.nullcontext())
        if tel is not None:
            # prime the first window's clock BEFORE any work: the first
            # iteration's tokens must not be divided by a window that
            # started after they were produced
            tel.maybe_window(now(), sched)
        try:
            # the serve-CALL trace context: engine-level records with no
            # per-request id (spans, serve_windows, rid -1 straggler /
            # swap events, the final serve record) share one ambient
            # serve-scoped id; per-request events carry their own
            # explicit ids, which win over the ambient one
            with flush_scope, \
                    monitor_trace.trace_context(
                        monitor_trace.new_trace_id("serve")):
                self._serve_loop(params, key, sched, tel, stats, now,
                                 wall, pool, draft, adaptive)
        finally:
            # a deferred swap this run never applied does NOT survive
            # into a later serve() call — clean return OR mid-run
            # exception — silently hot-swapping a stale checkpoint into
            # an unrelated run (or raising its aval error there) would
            # be worse than dropping it; stats.swaps==0 is the tell
            self._pending_swap = None
        self.last_stats = stats
        return sched.completed

    def _serve_loop(self, params, key, sched, tel, stats, now, wall, pool,
                    draft=None, adaptive=None):
        nstep = 0
        policy = sched.policy
        K = draft.k if draft is not None else 0
        if draft is not None:
            from apex_tpu.spec.tree import draft_tree, is_tree_drafter
            tree_capable = is_tree_drafter(draft)
        else:
            tree_capable = False
        ncompleted = len(sched.completed)
        while not sched.idle():
            # weight hot-swap lands HERE, between dispatch steps: a
            # contents-only params replacement (avals validated), so
            # neither jitted program retraces and in-flight requests
            # continue on their existing cache
            params = self._maybe_swap(params, nstep, tel, stats, now())
            sched.admit(now())
            did_work = False
            # the SLO policy widens the prefill share under queue
            # buildup: up to `prefill_share` chunks this iteration —
            # the SAME compiled program run more often, never a new one
            share = policy.prefill_share if policy is not None else 1
            for _ in range(share):
                work = sched.next_prefill(now())
                if work is None:
                    break
                sched.note_step(nstep)
                t_dispatch = now()
                pool, tok, _ = self.prefill_chunk(
                    params, pool,
                    jnp.asarray(sched.tables.row(work.slot)),
                    jnp.asarray(work.tokens),
                    jnp.int32(work.start), jnp.int32(work.live),
                    jax.random.fold_in(key, nstep))
                tok = int(tok)  # blocks until the chunk really ran
                if tel is not None:
                    tel.on_prefill_chunk(
                        work.rid, work.slot, now() - t_dispatch,
                        sched.blocks_held(work.slot), nstep, now())
                nstep += 1
                stats.prefill_chunks += 1
                sched.note_prefill(work, tok, now())
                did_work = True
            # the speculative mode ladder, re-picked per round: tree →
            # chain → plain, stepping DOWN on row headroom (every rung
            # is a pre-compiled program — a host-side choice, never a
            # retrace, never a stall). The tree rung needs depth+1 rows
            # of slot headroom, the chain rung k+1
            mode, shape = "plain", None
            if draft is not None:
                dec = sched.decoding_slots()
                if dec and tree_capable:
                    shape = (adaptive.round_shape(
                        [sched.slot_rid(i) for i in dec])
                        if adaptive is not None
                        else (draft.depth, draft.branching))
                    if all(sched.slot_length(i) + shape[0] + 1
                           <= self.max_s for i in dec):
                        mode = "tree"
                if mode == "plain" and dec and all(
                        sched.slot_length(i) + K + 1 <= self.max_s
                        for i in dec):
                    mode = "chain"
                    if tree_capable:
                        stats.spec_degraded += 1
            lookahead = (shape[0] if mode == "tree"
                         else K if mode == "chain" else 0)
            batch = sched.decode_batch(now(), lookahead=lookahead)
            # drafter-pool headroom comes AFTER decode_batch — it can
            # preempt (changing both the live set and the free count).
            # A short pool degrades the round down the same ladder:
            # blocks already reserved for the wider lookahead stay
            # assigned to their slots (reused as the stream grows —
            # never leaked), and the drafter allocates nothing
            if batch is not None and mode != "plain" \
                    and hasattr(draft, "round_blocks_needed"):
                while mode != "plain":
                    d_rows = shape[0] if mode == "tree" else K
                    need = sum(
                        draft.round_blocks_needed(
                            sched.slot_rid(i),
                            len(sched.slot_context(i)), depth=d_rows)
                        for i in sched.decoding_slots())
                    if need <= sched.allocator.num_free:
                        break
                    mode = "chain" if mode == "tree" else "plain"
                    stats.spec_degraded += 1
            if batch is not None and mode == "tree":
                toks, lens = batch
                depth, branching = shape
                tree = draft_tree(branching, depth)
                live = [i for i in range(self.num_slots) if lens[i] > 0]
                node_toks = np.zeros((self.num_slots, tree.num_nodes),
                                     np.int32)
                rids = {}
                for i in live:
                    rids[i] = sched.slot_rid(i)
                    node_toks[i] = draft.propose_tree(
                        rids[i], sched.slot_context(i),
                        shape=(depth, branching))
                tok_mat = np.zeros((self.num_slots, tree.n1), np.int32)
                tok_mat[:, 0] = toks
                tok_mat[:, 1:] = node_toks
                # topology operands ship as CONTENTS (uniform over the
                # slot array, dead rows ignored by the host): the
                # executable is pinned per (num_nodes+1, depth+1)
                parents, anc = tree.operands(self.num_slots)
                levels = np.arange(depth + 1, dtype=np.int32)
                sched.note_step(nstep)
                t_dispatch = now()
                pool, acc, jst, nxt = self.spec_tree_step(
                    params, pool, jnp.asarray(sched.tables.asarray()),
                    jnp.asarray(tok_mat), jnp.asarray(lens),
                    jnp.asarray(parents), jnp.asarray(anc),
                    jnp.asarray(levels), jax.random.fold_in(key, nstep))
                acc = np.asarray(acc)  # blocks: the round really ran
                jst = np.asarray(jst)
                nxt = np.asarray(nxt)
                round_dur = now() - t_dispatch
                if tel is not None:
                    tel.on_decode_step(round_dur, len(live), nstep, now())
                nstep += 1
                stats.decode_steps += 1
                stats.spec_rounds += 1
                stats.tree_rounds += 1
                stats.occupancy_samples.append(len(live))
                emitted = {}
                for i in live:
                    a = int(acc[i])
                    emitted[i] = tree.path_tokens(node_toks[i], a,
                                                  int(jst[i]), int(nxt[i]))
                    stats.spec_drafted += depth
                    stats.spec_accepted += a
                    stats.spec_nodes += tree.num_nodes
                    stats.spec_slot_rounds += 1
                    if tel is not None:
                        tel.on_spec_round(rids[i], i, a, depth, nstep - 1,
                                          now(), dur_ms=round_dur * 1e3,
                                          nodes=tree.num_nodes,
                                          branching=branching)
                    if adaptive is not None:
                        adaptive.note_round(rids[i], a, depth)
                sched.note_spec_tokens(emitted, now())
                did_work = True
            elif batch is not None and mode == "chain":
                toks, lens = batch
                live = [i for i in range(self.num_slots) if lens[i] > 0]
                # drafts come from the host drafter per stream; the
                # verify operands stay fixed-shape (static k)
                drafted = np.zeros((self.num_slots, K), np.int32)
                rids = {}
                for i in live:
                    rids[i] = sched.slot_rid(i)
                    drafted[i] = draft.propose(rids[i],
                                               sched.slot_context(i))
                tok_mat = np.zeros((self.num_slots, K + 1), np.int32)
                tok_mat[:, 0] = toks
                tok_mat[:, 1:] = drafted
                sched.note_step(nstep)
                t_dispatch = now()
                pool, acc, nxt = self.spec_step(
                    params, pool, jnp.asarray(sched.tables.asarray()),
                    jnp.asarray(tok_mat), jnp.asarray(lens),
                    jnp.asarray(drafted), jax.random.fold_in(key, nstep))
                acc = np.asarray(acc)  # blocks: the round really ran
                nxt = np.asarray(nxt)
                round_dur = now() - t_dispatch
                if tel is not None:
                    tel.on_decode_step(round_dur, len(live),
                                       nstep, now())
                nstep += 1
                stats.decode_steps += 1
                stats.spec_rounds += 1
                stats.occupancy_samples.append(len(live))
                for i in live:
                    a = int(acc[i])
                    stats.spec_drafted += K
                    stats.spec_accepted += a
                    stats.spec_nodes += K
                    stats.spec_slot_rounds += 1
                    if adaptive is not None:
                        # a degraded (chain) round still teaches the
                        # controller — acceptance over k chain rows
                        adaptive.note_round(rids[i], a, K)
                    if tel is not None:
                        # the round's full wall time for EVERY live slot
                        # (concurrent wall time — what a per-request e2e
                        # partition must bill)
                        tel.on_spec_round(rids[i], i, a, K, nstep - 1,
                                          now(), dur_ms=round_dur * 1e3)
                sched.note_spec(drafted, acc, nxt, now())
                did_work = True
            elif batch is not None:
                toks, lens = batch
                ndec = len(sched.decoding_slots())
                sched.note_step(nstep)
                t_dispatch = now()
                pool, sampled, _ = self.decode_step(
                    params, pool, jnp.asarray(sched.tables.asarray()),
                    jnp.asarray(toks), jnp.asarray(lens),
                    jax.random.fold_in(key, nstep))
                sampled = np.asarray(sampled)  # blocks: step really ran
                if tel is not None:
                    tel.on_decode_step(now() - t_dispatch, ndec, nstep,
                                       now())
                nstep += 1
                stats.decode_steps += 1
                stats.occupancy_samples.append(ndec)
                sched.note_decode(sampled, now())
                did_work = True
            if draft is not None and len(sched.completed) > ncompleted:
                # free finished streams' drafter state (caches bounded
                # by CONCURRENT streams, not request history)
                for r in sched.completed[ncompleted:]:
                    draft.release(r.rid)
                    if adaptive is not None:
                        adaptive.release(r.rid)
                ncompleted = len(sched.completed)
            stats.blocks_high_water = max(stats.blocks_high_water,
                                          sched.allocator.num_live)
            if tel is not None:
                if tel.maybe_window(now(), sched) is not None \
                        and policy is not None:
                    # window edge: fold the fresh SLO/anomaly signals
                    # into the dispatch knobs (SLO-aware scheduling)
                    policy.update(tel)
                    pop = getattr(policy, "pop_replan", None)
                    staged = pop() if pop is not None else None
                    if staged is not None:
                        # an online re-plan landed: the aval-stable
                        # knobs (share bound, admission order, SLO
                        # thresholds) are already applied in update();
                        # a spec-shape diff caps the adaptive ladder on
                        # its PRE-COMPILED choice set; aval-changing
                        # knobs ride the event as deferred_knobs —
                        # reported, never applied mid-serve
                        shape = staged.pop("spec_shape", None)
                        if shape is not None and adaptive is not None:
                            adaptive.set_cap(shape)
                        tel.on_replan(nstep, now(), **staged)
            if not did_work and wall:
                # nothing runnable: only future arrivals remain
                time.sleep(1e-4)
        # the final pool outlives the loop for the disaggregated
        # prefill role: export_handoff lifts warm prefix blocks out of
        # it (paired with the scheduler whose cache indexes them)
        self.last_pool = pool
