"""Tensor-parallel serving math: the shard-level helpers behind
``ServingEngine(plan=...)`` and ``DecodeEngine(plan=...)`` (ROADMAP
serving tier 2c — serve a model bigger than one chip).

The engines stay the owners of pools, schedulers, and the serve loop;
this module holds only what changes under ``tp >= 2``:

* **Eager validation** (:func:`validate_tp`): every illegal knob
  combination — kv heads that don't shard, a vocab the embedding can't
  split, a slot/chunk axis the rings can't chunk — raises a
  :class:`~apex_tpu.plan.parallel_plan.PlanError`-style named-knob
  message at ENGINE CONSTRUCTION, never as a shard_map shape error
  three dispatches in.
* **Vocab-parallel embedding** (:func:`vocab_embed`): masked local
  take + psum — bitwise identical to the full-table lookup (out-of-
  shard rows contribute exact zeros).
* **Ring-overlapped projections** (:func:`column_parallel` /
  :func:`row_parallel`): the PR-5 latency-hiding collective matmuls
  (``ops/collective_matmul.py``) applied to the decode/prefill GEMMs —
  each boundary collective rides the ring behind its GEMM
  (``overlap=True``), or degrades to the replicated-activation
  dot/psum form (``overlap=False``, the DecodeEngine path where batch
  axes aren't tp-divisible in general).
* **The psum-composed sampling tail** (:func:`row_argmax_tp` /
  :func:`sample_tp` / :func:`verify_greedy_tp`): each shard owns a
  contiguous vocab slice; the argmax composes exactly (global max via
  ``pmax``, first-max-lowest-index via ``pmin`` over offset local
  argmaxes — ``jnp.argmax``'s tie convention, so greedy under tp
  matches the tp=1 fused tail's decision function), and the Gumbel
  draw happens ONCE on the full vocab row (every shard draws the same
  ``(b, V)`` uniforms from the replicated key and slices its columns —
  the fused-sampling-tail fusion argument of arXiv:2502.17728 carried
  across the shard boundary).
* **Cross-shard int8 scales** (:func:`quant_rows_tp`): local amax,
  ``pmax`` over tp, THEN the scale floor — scales come out bitwise
  identical to the tp=1 pool's (max composes through the floor), so
  the scale planes stay replicated and the paged kernel's int8 scale
  indirection is untouched.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import collective_matmul as cm
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.plan.parallel_plan import ParallelPlan, PlanError

TENSOR_AXIS = mesh_lib.TENSOR_AXIS


# --- eager validation ---------------------------------------------------------

def validate_tp(plan: ParallelPlan, config, *, engine: str,
                num_slots: Optional[int] = None,
                prefill_chunk: Optional[int] = None,
                num_blocks: Optional[int] = None,
                max_blocks_per_slot: Optional[int] = None,
                temperature: float = 0.0, top_k: int = 0,
                top_p: float = 1.0, has_rel_bias: bool = False,
                devices=None) -> int:
    """Validate a serving :class:`ParallelPlan` against the model and
    engine knobs; returns ``plan.tp``. Every failure names its knob in
    the :meth:`ParallelPlan.validate` message style — the tp serving
    contract is enforced HERE, eagerly, never as a deep shard_map
    shape error."""
    tp = plan.tp
    if tp < 2:
        return 1
    for name in ("dp", "pp", "cp", "ep"):
        v = getattr(plan, name)
        if v != 1:
            raise PlanError(
                f"{name}={v} with tp={tp}: {engine} shards the serving "
                f"programs over the tensor axis only; legal values are "
                f"{name}=1")
    ndev = len(jax.devices() if devices is None else devices)
    if ndev < tp:
        raise PlanError(
            f"tp={tp}: tensor-parallel serving needs one device per "
            f"shard and this process exposes {ndev}; legal values are "
            f"tp <= {ndev}")
    if config.kv_heads % tp:
        raise PlanError(
            f"tp={tp} with kv_heads={config.kv_heads}: each shard owns "
            f"a contiguous slice of kv heads (the paged pool shards on "
            f"the kv-head axis, keeping the decode kernel body "
            f"untouched), so kv_heads % tp == 0; legal values are "
            f"divisors of kv_heads")
    if config.num_heads % tp:
        raise PlanError(
            f"tp={tp} with num_heads={config.num_heads}: the qkv "
            f"projection column-shards by query head, so "
            f"num_heads % tp == 0; legal values are divisors of "
            f"num_heads")
    if config.vocab_size % tp:
        raise PlanError(
            f"tp={tp} with vocab_size={config.vocab_size}: the tied "
            f"embedding/unembedding shard the vocab row, so "
            f"vocab_size % tp == 0; legal values are divisors of "
            f"vocab_size (pad the vocab to a tp multiple)")
    if num_slots is not None and num_slots % tp:
        raise PlanError(
            f"num_slots={num_slots} with tp={tp}: the decode step's "
            f"overlapped projections chunk the slot axis around the "
            f"ring, so num_slots % tp == 0; legal values are multiples "
            f"of tp")
    if prefill_chunk is not None and prefill_chunk % tp:
        raise PlanError(
            f"prefill_chunk={prefill_chunk} with tp={tp}: the prefill "
            f"chunk's overlapped projections chunk the token axis "
            f"around the ring, so prefill_chunk % tp == 0; legal "
            f"values are multiples of tp")
    if num_blocks is not None and max_blocks_per_slot is not None \
            and num_blocks - 1 < max_blocks_per_slot:
        raise PlanError(
            f"num_blocks={num_blocks} with tp={tp}: the sharded pool "
            f"keeps ONE logical free list — num_blocks is a GLOBAL "
            f"count (each shard holds kv_heads/tp of every block), so "
            f"it is NOT multiplied by tp; {num_blocks - 1} usable "
            f"blocks cannot hold one full slot "
            f"(max_blocks_per_slot={max_blocks_per_slot}); legal "
            f"values are num_blocks >= {max_blocks_per_slot + 1}")
    if temperature > 0 and (top_k > 0 or top_p < 1.0):
        raise PlanError(
            f"top_k={top_k}/top_p={top_p} with tp={tp}: the tp "
            f"sampling tail composes the full-vocab-row Gumbel argmax "
            f"across shards and does not thread the top-k/top-p "
            f"filters; legal values are top_k=0 and top_p=1.0 (or "
            f"temperature=0 for greedy)")
    if has_rel_bias:
        raise PlanError(
            f"tp={tp} cannot serve a model with a decode relative-"
            f"position bias (the sharded decode path does not carry "
            f"the bucketed bias table); legal values are tp=1 for "
            f"this model")
    return tp


def tp_mesh(tp: int):
    """A dp=1 mesh over the first ``tp`` devices — the serving engines'
    mesh (``(1, 1, 1, tp)``; serving never widens dp)."""
    return mesh_lib.make_mesh(tensor_model_parallel_size=tp,
                              devices=jax.devices()[:tp])


def take_shard(params):
    """Drop the leading per-rank axis ``shard_params_for_tp`` added:
    inside ``shard_map`` under ``P('tp', ...)`` every leaf arrives as
    ``(1, ...)`` — this rank's slice at index 0."""
    return jax.tree.map(lambda a: a[0], params)


# --- vocab-parallel embedding -------------------------------------------------

def vocab_embed(weight_local, ids, *, axis=TENSOR_AXIS):
    """Vocab-parallel lookup: ``weight_local`` (V/tp, H) is this rank's
    contiguous vocab slice; out-of-shard ids contribute exact zeros and
    the psum reassembles the full-table lookup bitwise (0 + x == x)."""
    v_loc = weight_local.shape[0]
    r = jax.lax.axis_index(axis)
    local = ids - r * v_loc
    in_shard = (local >= 0) & (local < v_loc)
    x = jnp.take(weight_local, jnp.where(in_shard, local, 0), axis=0)
    x = jnp.where(in_shard[..., None], x, jnp.zeros((), x.dtype))
    return jax.lax.psum(x, axis)


# --- ring-overlapped projections ----------------------------------------------

def column_parallel(x, w_local, b_local=None, *, axis=TENSOR_AXIS,
                    seq_dim=0, overlap=True):
    """Column-parallel projection of REPLICATED activations ``x``
    (..., in) against this rank's output slice ``w_local`` (out/tp, in).
    ``overlap=True`` rides the bidirectional all-gather ring: each rank
    slices its own ``seq_dim`` chunk (the replicated operand IS every
    rank's shard) and :func:`~apex_tpu.ops.collective_matmul.
    all_gather_matmul` rebuilds the full extent behind the GEMM — no
    full-width all_gather in the program. Returns (..., out/tp)."""
    if overlap:
        tp = jax.lax.axis_size(axis)
        r = jax.lax.axis_index(axis)
        shard = x.shape[seq_dim] // tp
        xc = jax.lax.dynamic_slice_in_dim(x, r * shard, shard,
                                          axis=seq_dim)
        y = cm.all_gather_matmul(xc, w_local, axis_name=axis,
                                 seq_dim=seq_dim)
    else:
        y = jnp.dot(x, w_local.T)
    if b_local is not None:
        y = y + b_local
    return y


def row_parallel(y, w_local, b=None, *, axis=TENSOR_AXIS, seq_dim=0,
                 overlap=True):
    """Row-parallel projection of partial-feature activations ``y``
    (..., in/tp) against ``w_local`` (out, in/tp); the cross-shard sum
    rides the ring-psum of :func:`~apex_tpu.ops.collective_matmul.
    matmul_all_reduce` (``overlap=True``; bitwise-identical result on
    every rank) or a plain dot + psum. The REPLICATED bias ``b`` is
    added AFTER the reduction (adding it per-shard would count it tp
    times). Returns replicated (..., out)."""
    if overlap:
        out = cm.matmul_all_reduce(y, w_local, axis_name=axis,
                                   seq_dim=seq_dim)
    else:
        out = jax.lax.psum(jnp.dot(y, w_local.T), axis)
    if b is not None:
        out = out + b
    return out


# --- cross-shard int8 scales --------------------------------------------------

def quant_rows_tp(x, axes, axis_name=TENSOR_AXIS):
    """The tp form of the engines' ``_quant_rows``: the amax composes
    across shards BEFORE the floor/divide, so every shard quantizes its
    local kv heads against the GLOBAL row scale and the scale planes
    come out bitwise identical to the tp=1 pool's (``pmax`` commutes
    with the monotonic ``max(amax, tiny)/127``) — replicated, exactly
    the layout the paged kernel's scale indirection reads."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axes, keepdims=True)
    amax = jax.lax.pmax(amax, axis_name)
    scale = jnp.maximum(amax, 1e-20) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, jnp.squeeze(scale, axis=axes)


# --- the psum-composed sampling tail ------------------------------------------

def row_argmax_tp(s_local, *, axis=TENSOR_AXIS):
    """Full-vocab-row argmax from per-shard slices ``s_local``
    (..., V/tp), ties to the LOWEST global index — ``jnp.argmax``'s
    convention, composed exactly: the global max via ``pmax`` (float
    max is exact), then the smallest offset local-argmax among shards
    achieving it via ``pmin``. Two scalar-lane collectives; no O(V)
    gather."""
    v_loc = s_local.shape[-1]
    tp = jax.lax.axis_size(axis)
    r = jax.lax.axis_index(axis)
    lmax = jnp.max(s_local, axis=-1)
    gmax = jax.lax.pmax(lmax, axis)
    lidx = jnp.argmax(s_local, axis=-1).astype(jnp.int32)
    cand = jnp.where(lmax == gmax, lidx + r * v_loc,
                     jnp.int32(tp * v_loc))
    return jax.lax.pmin(cand, axis)


def gumbel_sample_tp(logits_local, key, *, temperature,
                     axis=TENSOR_AXIS):
    """Temperature sampling with the Gumbel draw made ONCE on the full
    vocab row: every shard draws the same ``(b, V)`` uniforms from the
    replicated key (identical bits — the draw count stays one per row,
    not one per shard), slices its own columns, and the perturbed
    argmax composes like :func:`row_argmax_tp`. The same
    uniform→Gumbel→argmax formulation as the fused tp=1 tail
    (``ops/pallas/sampling.py``), unfiltered (top-k/top-p are rejected
    eagerly under tp)."""
    v_loc = logits_local.shape[-1]
    tp = jax.lax.axis_size(axis)
    r = jax.lax.axis_index(axis)
    b = logits_local.shape[0]
    tiny = jnp.finfo(jnp.float32).tiny
    u = jax.random.uniform(key, (b, v_loc * tp), jnp.float32,
                           minval=tiny, maxval=1.0)
    u_loc = jax.lax.dynamic_slice_in_dim(u, r * v_loc, v_loc, axis=1)
    s = logits_local.astype(jnp.float32) * (1.0 / temperature)
    x = s + -jnp.log(-jnp.log(u_loc))
    return row_argmax_tp(x, axis=axis)


def sample_tp(logits_local, key, *, temperature, axis=TENSOR_AXIS):
    """The fused sampling tail's decision function over sharded logits:
    greedy argmax at ``temperature == 0``, single-full-row Gumbel
    otherwise. ``logits_local`` (b, V/tp) → (b,) int32."""
    if temperature == 0.0:
        return row_argmax_tp(logits_local, axis=axis)
    return gumbel_sample_tp(logits_local, key, temperature=temperature,
                            axis=axis)


def verify_greedy_tp(logits_local, drafted, *, axis=TENSOR_AXIS):
    """The spec round's greedy verify tail over sharded logits:
    ``logits_local`` (S, k+1, V/tp), ``drafted`` (S, k) int32 →
    ``(accept_len (S,), next_token (S,))``. The candidate rows compose
    via :func:`row_argmax_tp` (f32 cast first — ``verify_greedy``'s
    exact decision function) and the acceptance-prefix / corrected-
    token math is the kernel's own helpers, verbatim."""
    from apex_tpu.ops.pallas.verify import (NO_DRAFT, accepted_prefix_len,
                                            select_row)
    s = logits_local.shape[0]
    cand = row_argmax_tp(logits_local.astype(jnp.float32), axis=axis)
    drafted_pad = jnp.concatenate(
        [drafted.astype(jnp.int32),
         jnp.full((s, 1), NO_DRAFT, jnp.int32)], axis=1)
    a = accepted_prefix_len(cand == drafted_pad)
    return a, select_row(cand, a)
