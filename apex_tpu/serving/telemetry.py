"""Request-level serving telemetry: lifecycle traces, streaming latency
histograms, live SLO windows, and anomaly detection.

The PR-7 engine was observable only post-hoc: ``bench.py --serve``
stored every per-token latency in host lists and computed p50/p99 once
at the end, with no visibility into *why* a tail request was slow
(queue wait vs chunked-prefill interleave vs a straggler decode step)
and no signal while a run degrades. :class:`ServeTelemetry` is the
missing layer, riding the PR-1/6 monitor stack:

* **Lifecycle event stream** — one rank-tagged ``serve_event`` JSONL
  record per request transition (``submit → admit → prefill_chunk*k →
  first_token → decode → finish``, with ``evict`` → re-``admit`` →
  resumed ``decode`` when preemption strikes) carrying queue wait,
  chunk count,
  blocks held, per-phase durations, and the engine step index of the
  dispatch that produced it. Device correlation is the PR-6
  scope-prefix join: the engine's jitted bodies trace under the
  ``serve_prefill`` / ``serve_decode`` named scopes, so every HLO of
  step *n* carries that prefix in a device trace and the lifecycle
  record's ``step`` names which dispatch it was.
* **Streaming histograms** — per-token (inter-token) latency and TTFT
  land in bounded-memory :class:`~apex_tpu.monitor.histogram.
  StreamingHistogram` pairs (cumulative for the final bench record,
  per-window for the live records) instead of unbounded host lists.
* **Live SLO windows** — a periodic ``serve_window`` record (sliding
  window tokens/s, TTFT / per-token quantiles, queue depth, slot
  occupancy, pool high-water, admission-blocked-by {slots|blocks}
  counts) with a ``serve_anomaly`` section.
* **Anomaly layer** — straggler decode steps against a rolling median,
  queue-buildup and SLO-burn flags (sustained TTFT over threshold),
  and free-list leak / fragmentation accounting from
  :class:`~apex_tpu.serving.kv_blocks.BlockAllocator`.

Everything here is host-side bookkeeping driven from OUTSIDE the jit'd
steps — the zero-recompile contract is untouched (asserted by tests and
the bench with telemetry enabled) and the cost is O(1) dict/histogram
work per token plus one JSONL write per transition/window, measured and
reported as ``telemetry_overhead_pct`` in the ``serve`` record (<1% of
a serve step; the hooks are a single ``is None`` test when no telemetry
is attached). Records only reach a file while the process-wide monitor
registry is enabled; the histograms and anomaly counters accumulate
regardless, so the bench reads its quantiles without a sink.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, Optional

from apex_tpu.monitor import registry as _reg
from apex_tpu.monitor import trace as _trace
from apex_tpu.monitor.histogram import StreamingHistogram
# the unified clock (== time.perf_counter_ns): overhead accounting and
# every monitor stream measure on the same CLOCK_MONOTONIC base
from apex_tpu.monitor.trace import monotonic_ns as _mono

__all__ = ["ServeTelemetry"]

# lifecycle phases, in order (evict fires on preemption: the request
# releases its blocks and re-queues for evict-and-recompute; swap is an
# ENGINE-level transition, rid -1 — a weight hot-swap landed between
# dispatch steps)
PHASES = ("submit", "admit", "prefill_chunk", "first_token", "decode",
          "finish", "evict", "swap", "spec", "handoff", "replan")


class _InFlight:
    """Per-request scratch while the request is live (freed at finish —
    the tracker's memory is bounded by concurrent requests, never by
    request history)."""

    __slots__ = ("queued_at", "admit_at", "chunks", "prefill_s",
                 "first_token_at", "requeued_at", "trace_id")

    def __init__(self, queued_at: float, trace_id: Optional[str] = None):
        self.queued_at = queued_at
        self.admit_at: Optional[float] = None
        self.chunks = 0
        self.prefill_s = 0.0
        self.first_token_at: Optional[float] = None
        # set on evict: re-admission measures queue wait from HERE, not
        # from the original submit (the prior in-slot service time is
        # not queueing)
        self.requeued_at: Optional[float] = None
        # the request-scoped trace id (minted at submit, mirrored on
        # the Request itself): rides every lifecycle record of this
        # request — across evict → re-admit → resume, because both this
        # tracker entry and the Request object survive the eviction
        self.trace_id = trace_id


class ServeTelemetry:
    """Request-level telemetry for one :meth:`ServingEngine.serve` call.

    Construct one per serve run and pass it as
    ``engine.serve(..., telemetry=tel)`` (the engine also auto-attaches
    one when the monitor registry is enabled). Knobs:

    * ``slots`` — the engine's slot count (occupancy denominator).
    * ``window_s`` — ``serve_window`` emission period on the serve
      clock (0 disables periodic records; stats still accumulate).
    * ``slo_ttft_ms`` — the TTFT service-level objective; ``None``
      disables SLO-burn detection.
    * ``slo_burn_count`` — consecutive over-SLO first tokens that flip
      the ``slo_burn`` flag (sustained breach, not a single outlier).
    * ``straggler_ratio`` / ``straggler_window`` — a decode step slower
      than ``ratio`` x the rolling median of the last ``window`` steps
      counts as a straggler (after the window has filled once).
    * ``status`` / ``reason`` — the claim the emitted ``serve_window``
      records carry ("OK" engages the no-nan honesty rule; off-TPU
      callers pass ``("SKIP", reason)`` semantics just like the bench
      record itself).
    """

    def __init__(self, *, slots: int, window_s: float = 0.5,
                 slo_ttft_ms: Optional[float] = None,
                 slo_burn_count: int = 3,
                 straggler_ratio: float = 3.0,
                 straggler_window: int = 32,
                 status: str = "OK", reason: Optional[str] = None,
                 collect_events: bool = False):
        if status not in ("OK", "SKIP"):
            raise ValueError(f"status must be OK|SKIP, got {status!r}")
        if status == "SKIP" and not reason:
            raise ValueError("SKIP serve_window records need a reason")
        self.slots = int(slots)
        self.window_s = float(window_s)
        self.slo_ttft_ms = slo_ttft_ms
        self.slo_burn_count = int(slo_burn_count)
        self.straggler_ratio = float(straggler_ratio)
        self.straggler_window = int(straggler_window)
        self.status = status
        self.reason = reason
        # collect_events=True keeps an in-memory ledger of every emitted
        # event's fields (same dict shape as the JSONL records), so
        # trace.serve_attribution() can run without any sink — how the
        # bench emits serve_attribution when no stream was requested
        self.collect_events = bool(collect_events)
        self.events: list = []

        # cumulative histograms back the final bench record; the window
        # pair resets at every serve_window emission (sliding view).
        # TTFT additionally splits by prefix-cache outcome: the
        # hit-vs-miss p50 gap IS the prefix cache's headline claim
        self.itl_ms = StreamingHistogram()
        self.ttft_ms = StreamingHistogram()
        self.ttft_hit_ms = StreamingHistogram()
        self.ttft_miss_ms = StreamingHistogram()
        self._win_itl = StreamingHistogram()
        self._win_ttft = StreamingHistogram()

        self._inflight: Dict[int, _InFlight] = {}
        self._recent_steps = deque(maxlen=self.straggler_window)
        self._queue_depths = deque(maxlen=4)  # at window emissions

        # counters surfaced on windows and the final record
        self.tokens = 0
        self.decode_steps = 0
        self.prefill_chunks = 0
        self.finished = 0
        self.admission_blocked_slots = 0
        self.admission_blocked_blocks = 0
        self.queue_peak = 0
        self.straggler_steps = 0
        self.straggler_last_ratio = 0.0
        self._ttft_over_slo_run = 0
        self.ttft_over_slo = 0
        self.slo_burn = False
        self.queue_buildup = False
        self.leaked_blocks = 0
        self.windows_emitted = 0
        # serving-tier-2 counters: preemption + prefix-cache outcomes
        self.preemptions = 0
        self.resumes = 0
        self.prefix_hit_requests = 0
        self.prefix_miss_requests = 0
        # weight hot-swaps applied between dispatch steps (ISSUE 14)
        self.swaps = 0
        # online re-plans: ReplanPolicy ladder switches at window edges
        self.replans = 0
        # disaggregated prefill→decode handoff legs this engine played
        # (either role): block/byte totals feed the tp_serve record
        self.handoffs = 0
        self.handoff_blocks = 0
        self.handoff_bytes = 0
        self.handoff_transfer_ms = 0.0
        # speculative-decoding rounds (ISSUE 15): per SLOT-round
        # accepted lengths accumulate into the serve record's
        # acceptance rate (spec_slot_rounds counts slot×dispatch —
        # distinct from ServeStats.spec_rounds, which counts dispatches)
        self.spec_slot_rounds = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.draft_k = 0
        # tree speculative rounds (ISSUE 19): verify rows actually
        # scored (chain rounds count k) and how many rounds were trees
        self.spec_nodes = 0
        self.spec_tree_rounds = 0
        # the engine stamps its pool-quantization knob here at serve
        # start so the record names the pool it measured
        self.kv_dtype: Optional[str] = None

        self._win_t0: Optional[float] = None
        self._win_tokens = 0
        self._win_steps = 0
        self._win_chunks = 0
        self.overhead_ns = 0  # real host ns spent inside the hooks

    # --- internals -----------------------------------------------------------

    @property
    def overhead_s(self) -> float:
        return self.overhead_ns * 1e-9

    def _emit(self, kind: str, **fields) -> None:
        if self.collect_events:
            self.events.append({"kind": kind, **fields})
        r = _reg.get_registry()
        if r is None:
            return
        if kind == "serve_window":
            r.emit_serve_window(self.status, **fields)
        else:
            r.emit(kind, **fields)

    @staticmethod
    def _tid(fl: Optional[_InFlight]) -> Dict[str, str]:
        """The trace-id field of a lifecycle record ({} when the
        tracker never saw a submit — explicit ids beat the ambient
        serve-level id the registry would otherwise stamp)."""
        if fl is not None and fl.trace_id:
            return {"trace_id": fl.trace_id}
        return {}

    @staticmethod
    def _skip_or(value, why: str):
        return value if value is not None else ("skipped", why)

    # --- lifecycle hooks (called by Scheduler / ServingEngine) ---------------

    def on_submit(self, req, now: float) -> None:
        t = _mono()
        # mint the request's trace id HERE (unless the caller already
        # stamped one) and mirror it on the Request object: the object
        # survives evict → re-admit → resume, so continuity is free
        tid = getattr(req, "trace_id", None)
        if not tid:
            tid = _trace.new_trace_id("req")
            try:
                req.trace_id = tid
            except AttributeError:
                pass  # slotted/frozen stand-ins: the tracker still has it
        self._inflight[req.rid] = _InFlight(
            queued_at=max(now, float(req.arrival_s)), trace_id=tid)
        self._emit("serve_event", rid=req.rid, phase="submit", at_s=now,
                   trace_id=tid,
                   prompt_len=int(len(req.prompt)),
                   max_new_tokens=int(req.max_new_tokens))
        self.overhead_ns += _mono() - t

    def on_admit(self, req, slot: int, now: float,
                 prefix_hit_blocks: int = 0, resumed: bool = False
                 ) -> None:
        t = _mono()
        fl = self._inflight.get(req.rid)
        if fl is None:  # submitted before the tracker attached
            fl = self._inflight[req.rid] = _InFlight(
                float(req.arrival_s), getattr(req, "trace_id", None))
        fl.admit_at = now
        # a re-admission waited since its EVICTION, not since submit —
        # billing the prior in-slot service time as queueing would
        # inflate exactly the rows preemption analysis looks at
        since = fl.requeued_at if fl.requeued_at is not None \
            else fl.queued_at
        fl.requeued_at = None
        queue_wait_ms = max(now - since, 0.0) * 1e3
        fields = dict(rid=req.rid, phase="admit", at_s=now,
                      slot=int(slot),
                      queue_wait_ms=round(queue_wait_ms, 3),
                      **self._tid(fl))
        if prefix_hit_blocks:
            fields["prefix_hit_blocks"] = int(prefix_hit_blocks)
        if resumed:  # re-admission after an evict
            fields["resumed"] = True
        self._emit("serve_event", **fields)
        self.overhead_ns += _mono() - t

    def on_evict(self, req, slot: int, blocks_released: int, reason: str,
                 requeue_pos: int, step: int, now: float) -> None:
        """The reserved preemption transition: slot ``slot``'s request
        released ``blocks_released`` block references and re-queued at
        ``requeue_pos`` for evict-and-recompute."""
        t = _mono()
        self.preemptions += 1
        fl = self._inflight.get(req.rid)
        if fl is not None:
            fl.requeued_at = now
        self._emit("serve_event", rid=req.rid, phase="evict", at_s=now,
                   slot=int(slot), step=int(step),
                   evict_reason=str(reason),
                   blocks_released=int(blocks_released),
                   requeue_pos=int(requeue_pos),
                   generated=len(req.tokens), **self._tid(fl))
        self.overhead_ns += _mono() - t

    def on_resume(self, req, slot: int, blocks_held: int, step: int,
                  now: float) -> None:
        """An evicted request finished its re-prefill and re-entered
        steady decode (the recompute's sampled token was discarded —
        the stream continues exactly where it left off)."""
        t = _mono()
        self.resumes += 1
        self._emit("serve_event", rid=req.rid, phase="decode", at_s=now,
                   slot=int(slot), blocks_held=int(blocks_held),
                   step=int(step), resumed=True,
                   **self._tid(self._inflight.get(req.rid)))
        self.overhead_ns += _mono() - t

    def on_swap(self, step: int, now: float,
                source: Optional[str] = None,
                dur_ms: Optional[float] = None) -> None:
        """A weight hot-swap landed between dispatch steps (rid -1:
        engine-level, like straggler events). ``source`` names where
        the weights came from (e.g. the checkpoint step directory);
        ``dur_ms`` is the measured validate+rebind pause — attribution
        carves it out of the decode time of every request that was
        mid-decode when the swap landed."""
        t = _mono()
        self.swaps += 1
        fields = dict(rid=-1, phase="swap", at_s=now, step=int(step))
        if source:
            fields["swap_source"] = str(source)
        if dur_ms is not None:
            fields["dur_ms"] = round(float(dur_ms), 3)
        self._emit("serve_event", **fields)
        self.overhead_ns += _mono() - t

    def on_replan(self, step: int, now: float, *, plan_from: str,
                  plan_to: str, trigger: str,
                  live_knobs: Optional[list] = None,
                  deferred_knobs: Optional[list] = None,
                  dur_ms: Optional[float] = None) -> None:
        """An online re-plan landed at a window edge (rid -1,
        engine-level, like ``swap``): the :class:`~apex_tpu.serving
        .scheduler.ReplanPolicy` switched the active ServePlan.
        ``plan_from``/``plan_to`` are plan content digests and
        ``trigger`` names the load signal (``queue_buildup`` /
        ``slo_burn`` / ``calm``); ``live_knobs`` lists the aval-stable
        diffs applied in place, ``deferred_knobs`` the aval-changing
        diffs REPORTED but not applied (they wait for a
        ``request_swap``-style engine rebuild)."""
        t = _mono()
        self.replans += 1
        fields = dict(rid=-1, phase="replan", at_s=now, step=int(step),
                      plan_from=str(plan_from), plan_to=str(plan_to),
                      replan_trigger=str(trigger))
        if live_knobs:
            fields["live_knobs"] = [str(k) for k in live_knobs]
        if deferred_knobs:
            fields["deferred_knobs"] = [str(k) for k in deferred_knobs]
        if dur_ms is not None:
            fields["dur_ms"] = round(float(dur_ms), 3)
        self._emit("serve_event", **fields)
        self.overhead_ns += _mono() - t

    def on_spec_round(self, rid: int, slot: int, accepted: int, k: int,
                      step: int, now: float,
                      dur_ms: Optional[float] = None,
                      nodes: Optional[int] = None,
                      branching: Optional[int] = None) -> None:
        """One slot's speculative round: ``accepted`` of ``k`` drafted
        tokens survived verification (the round emitted
        ``accepted + 1`` tokens up to the request's budget). Feeds the
        acceptance-rate accounting and one ``spec``-phase lifecycle
        record. ``dur_ms`` is the round's dispatch wall time (the same
        value for every live slot of the round — concurrent wall time,
        which is what a per-request e2e partition must bill); an
        all-rejected round (``accepted == 0``) is attributed to
        ``spec_rewind_ms``, the others to ``spec_ms``. A TREE round
        additionally passes ``nodes`` (verify rows scored, branching x
        depth) and ``branching`` — ``k`` is then the tree DEPTH, so the
        acceptance accounting stays chain-comparable while the record
        still prices the wider verify."""
        t = _mono()
        self.spec_slot_rounds += 1
        self.spec_drafted += k
        self.spec_accepted += accepted
        self.draft_k = k
        if nodes is not None:
            self.spec_tree_rounds += 1
            self.spec_nodes += nodes
        else:
            self.spec_nodes += k
        fields = dict(rid=rid, phase="spec", at_s=now,
                      slot=int(slot), step=int(step),
                      accepted_len=int(accepted), draft_k=int(k),
                      **self._tid(self._inflight.get(rid)))
        if nodes is not None:
            fields["tree_nodes"] = int(nodes)
        if branching is not None:
            fields["tree_branching"] = int(branching)
        if dur_ms is not None:
            fields["dur_ms"] = round(float(dur_ms), 3)
        self._emit("serve_event", **fields)
        self.overhead_ns += _mono() - t

    def on_handoff(self, rid: int, role: str, blocks: int, nbytes: int,
                   now: float, dur_ms: Optional[float] = None,
                   trace_id: Optional[str] = None) -> None:
        """One request's KV-block handoff leg (disaggregated serving):
        ``role`` names which side this engine played (``"export"`` on
        the prefill engine, ``"ingest"`` on the decode engine). The
        SAME ``trace_id`` rides both roles' records — the caller
        carries it across the process boundary inside the handoff
        payload, so a merged timeline joins the export and ingest legs
        of one request on one id."""
        t = _mono()
        if role not in ("export", "ingest"):
            raise ValueError(
                f"handoff role must be export|ingest, got {role!r}")
        self.handoffs += 1
        self.handoff_blocks += int(blocks)
        self.handoff_bytes += int(nbytes)
        fl = self._inflight.get(rid)
        tid = trace_id or (fl.trace_id if fl is not None else None)
        fields = dict(rid=int(rid), phase="handoff", at_s=now,
                      handoff_role=role, blocks=int(blocks),
                      transfer_bytes=int(nbytes),
                      **({"trace_id": tid} if tid else {}))
        if dur_ms is not None:
            self.handoff_transfer_ms += float(dur_ms)
            fields["dur_ms"] = round(float(dur_ms), 3)
        self._emit("serve_event", **fields)
        self.overhead_ns += _mono() - t

    def on_blocked(self, why: str, n: int = 1) -> None:
        if why == "slots":
            self.admission_blocked_slots += n
        elif why == "blocks":
            self.admission_blocked_blocks += n
        else:
            raise ValueError(f"unknown admission block reason {why!r}")

    def on_prefill_chunk(self, rid: int, slot: int, dur_s: float,
                         blocks_held: int, step: int, now: float) -> None:
        t = _mono()
        self.prefill_chunks += 1
        self._win_chunks += 1
        fl = self._inflight.get(rid)
        chunk = 0
        if fl is not None:
            chunk = fl.chunks
            fl.chunks += 1
            fl.prefill_s += dur_s
        self._emit("serve_event", rid=rid, phase="prefill_chunk", at_s=now,
                   slot=int(slot), chunk=chunk,
                   dur_ms=round(dur_s * 1e3, 3),
                   blocks_held=int(blocks_held), step=int(step),
                   **self._tid(fl))
        self.overhead_ns += _mono() - t

    def on_first_token(self, req, slot: int, blocks_held: int, step: int,
                       now: float) -> None:
        t = _mono()
        was_burning = self.slo_burn
        fl = self._inflight.get(req.rid)
        if fl is None:
            fl = self._inflight[req.rid] = _InFlight(
                float(req.arrival_s), getattr(req, "trace_id", None))
        fl.first_token_at = now
        ttft_ms = max(now - fl.queued_at, 0.0) * 1e3
        self.ttft_ms.add(ttft_ms)
        self._win_ttft.add(ttft_ms)
        # the prefix-cache witness: TTFT split by whether the request's
        # first admission mapped shared blocks out of the cache
        if getattr(req, "prefix_hit_blocks", 0) > 0:
            self.prefix_hit_requests += 1
            self.ttft_hit_ms.add(ttft_ms)
        else:
            self.prefix_miss_requests += 1
            self.ttft_miss_ms.add(ttft_ms)
        self.tokens += 1
        self._win_tokens += 1
        if self.slo_ttft_ms is not None:
            if ttft_ms > self.slo_ttft_ms:
                self.ttft_over_slo += 1
                self._ttft_over_slo_run += 1
                if self._ttft_over_slo_run >= self.slo_burn_count:
                    self.slo_burn = True
            else:
                self._ttft_over_slo_run = 0
        self._emit("serve_event", rid=req.rid, phase="first_token",
                   at_s=now, slot=int(slot),
                   ttft_ms=round(ttft_ms, 3), chunks=fl.chunks,
                   prefill_ms=round(fl.prefill_s * 1e3, 3),
                   blocks_held=int(blocks_held), step=int(step),
                   **self._tid(fl))
        if req.max_new_tokens > 1:  # the request enters steady decode
            self._emit("serve_event", rid=req.rid, phase="decode",
                       at_s=now, slot=int(slot),
                       blocks_held=int(blocks_held), step=int(step),
                       **self._tid(fl))
        self.overhead_ns += _mono() - t
        if self.slo_burn and not was_burning:
            # first flip of the anomaly flag: preserve the last-N raw
            # events for post-hoc debugging (no-op without a recorder;
            # once=True keeps repeats from re-dumping)
            _trace.flight_dump("serve_anomaly:slo_burn")

    def observe_itl(self, itl_s: float) -> None:
        """One inter-token gap (decode token ``i`` → ``i+1`` of one
        request) into the latency histograms."""
        t = _mono()
        ms = itl_s * 1e3
        self.itl_ms.add(ms)
        self._win_itl.add(ms)
        self.tokens += 1
        self._win_tokens += 1
        self.overhead_ns += _mono() - t

    def on_decode_step(self, dur_s: float, live_slots: int, step: int,
                       now: float) -> None:
        """One full-width decode step's wall time: feeds the straggler
        detector (vs the rolling median of recent steps)."""
        t = _mono()
        self.decode_steps += 1
        self._win_steps += 1
        straggled = False
        recent = self._recent_steps
        if len(recent) == recent.maxlen:
            med = sorted(recent)[len(recent) // 2]
            if med > 0 and dur_s > self.straggler_ratio * med:
                straggled = True
                self.straggler_steps += 1
                self.straggler_last_ratio = round(dur_s / med, 2)
                self._emit("serve_event", rid=-1, phase="decode",
                           at_s=now, step=int(step), straggler=True,
                           dur_ms=round(dur_s * 1e3, 3),
                           ratio_to_median=self.straggler_last_ratio,
                           slots=int(live_slots))
        recent.append(dur_s)
        self.overhead_ns += _mono() - t
        if straggled:
            _trace.flight_dump("serve_anomaly:straggler")

    def on_finish(self, req, slot: int, blocks_held: int, step: int,
                  now: float) -> None:
        t = _mono()
        self.finished += 1
        fl = self._inflight.pop(req.rid, None)
        decode_ms = None
        if fl is not None and fl.first_token_at is not None:
            decode_ms = round(max(now - fl.first_token_at, 0.0) * 1e3, 3)
        fields = dict(rid=req.rid, phase="finish", at_s=now,
                      slot=int(slot), tokens=len(req.tokens),
                      blocks_held=int(blocks_held), step=int(step),
                      total_ms=round(
                          max(now - float(req.arrival_s), 0.0) * 1e3, 3),
                      **self._tid(fl))
        if decode_ms is not None:
            fields["decode_ms"] = decode_ms
        if fl is not None:
            fields["chunks"] = fl.chunks
        self._emit("serve_event", **fields)
        self.overhead_ns += _mono() - t

    # --- windows + anomalies -------------------------------------------------

    @property
    def slo_burning(self) -> bool:
        """The LIVE burn signal: the current run of consecutive
        over-SLO first tokens has reached the burn count. Unlike the
        sticky :attr:`slo_burn` record flag, this clears when a first
        token lands back under the SLO — it is what the
        :class:`~apex_tpu.serving.scheduler.SLOPolicy` keys its
        deprioritize-long-prompts knob on (a policy must be able to
        stand down)."""
        return (self.slo_ttft_ms is not None
                and self._ttft_over_slo_run >= self.slo_burn_count)

    def anomaly_section(self, allocator=None) -> Dict[str, Any]:
        """The ``serve_anomaly`` object riding ``serve_window`` records
        and the final ``serve`` record. With an ``allocator``, folds in
        the free-list leak / fragmentation accounting."""
        if allocator is not None and allocator.leaked:
            # counter drift is a leak whenever it shows; the idle-pool
            # flavor (live blocks with no active requests) is detected
            # at window time and sticks in self.leaked_blocks
            self.leaked_blocks = allocator.leaked
        if self.leaked_blocks:
            _trace.flight_dump("serve_anomaly:leak")
        out: Dict[str, Any] = {
            "straggler_steps": self.straggler_steps,
            "straggler_last_ratio": self.straggler_last_ratio,
            "queue_buildup": self.queue_buildup,
            "slo_burn": self.slo_burn,
            "ttft_over_slo": self.ttft_over_slo,
            "leaked_blocks": self.leaked_blocks,
        }
        if allocator is not None:
            out["free_list_frag_pct"] = round(
                allocator.fragmentation_pct(), 2)
        return out

    def maybe_window(self, now: float, sched) -> Optional[Dict[str, Any]]:
        """Emit a ``serve_window`` record when ``window_s`` has elapsed
        on the serve clock; returns the fields dict when one was
        emitted. ``sched`` is the live :class:`Scheduler` (queue depth,
        occupancy, allocator state are read from it). Queue depth
        counts requests that have ARRIVED and are waiting
        (:meth:`Scheduler.num_queued` — not the unarrived replay tail,
        which would saturate the peak at the trace length) and is
        sampled on EVERY call (the peak must not depend on window
        cadence); the record only on the window edge. The engine calls
        this once BEFORE its loop so the first window's clock starts
        before the first work, not after it."""
        queued = sched.num_queued(now)
        if queued > self.queue_peak:
            self.queue_peak = queued
        if self.window_s <= 0:
            return None
        if self._win_t0 is None:
            self._win_t0 = now
            return None
        if now - self._win_t0 < self.window_s:
            return None
        t = _mono()
        fields = self._window_fields(now, sched)
        self._emit("serve_window", **fields)
        self.windows_emitted += 1
        self._win_t0 = now
        self._win_tokens = 0
        self._win_steps = 0
        self._win_chunks = 0
        self._win_itl.reset()
        self._win_ttft.reset()
        self.overhead_ns += _mono() - t
        return fields

    def _window_fields(self, now: float, sched) -> Dict[str, Any]:
        window = max(now - (self._win_t0 if self._win_t0 is not None
                            else now), 0.0)
        queue = sched.num_queued(now)
        self.queue_peak = max(self.queue_peak, queue)
        self._queue_depths.append(queue)
        qd = list(self._queue_depths)
        self.queue_buildup = (
            len(qd) >= 3 and qd[-1] > 0
            and all(b > a for a, b in zip(qd[-3:], qd[-2:])))
        active = sched.num_active
        alloc = sched.allocator
        # a pool leak only means something when nothing SHOULD hold
        # blocks: counter drift is a leak at any time, and live blocks
        # with zero active requests are one too — MINUS the blocks the
        # prefix cache keeps resident (refcounted warm capacity is the
        # cache doing its job, not a leak; num_resident counts exactly
        # the cache-pinned live blocks)
        if alloc.leaked:
            self.leaked_blocks = alloc.leaked
        elif (active == 0 and queue == 0
                and alloc.num_live > getattr(alloc, "num_resident", 0)):
            self.leaked_blocks = (alloc.num_live
                                  - getattr(alloc, "num_resident", 0))
        cache = getattr(sched, "prefix_cache", None)
        hit_rate = cache.hit_rate() if cache is not None else None
        itl = self._win_itl
        ttft = self._win_ttft
        no_itl = "no inter-token samples in window"
        no_ttft = "no first tokens in window"
        return dict(
            at_s=round(now, 6),  # serve clock: joins the request rows
            window_s=round(window, 6),
            steps=self._win_steps,
            prefill_chunks=self._win_chunks,
            tokens=self._win_tokens,
            tokens_per_s=round(self._win_tokens / window, 1) if window > 0
            else ("skipped", "zero-length window"),
            latency_p50_ms=self._skip_or(
                _r3(itl.quantile(0.5)), no_itl),
            latency_p99_ms=self._skip_or(
                _r3(itl.quantile(0.99)), no_itl),
            ttft_p50_ms=self._skip_or(_r3(ttft.quantile(0.5)), no_ttft),
            ttft_p99_ms=self._skip_or(_r3(ttft.quantile(0.99)), no_ttft),
            queue_depth=queue,
            active_slots=active,
            slots=self.slots,
            occupancy_pct=round(100.0 * active / self.slots, 2),
            blocks_live=alloc.num_live,
            blocks_high_water=alloc.high_water,
            blocks_resident=getattr(alloc, "num_resident", 0),
            admission_blocked_slots=self.admission_blocked_slots,
            admission_blocked_blocks=self.admission_blocked_blocks,
            # serving tier 2: prefix-cache effectiveness + preemption
            # pressure, live per window
            prefix_hit_rate=self._skip_or(
                None if hit_rate is None else round(hit_rate, 4),
                "no prefix cache attached or nothing queried yet"),
            preemptions=getattr(sched, "preemptions", self.preemptions),
            recompute_tokens=getattr(sched, "recompute_tokens", 0),
            serve_anomaly=self.anomaly_section(alloc),
            **({"reason": self.reason} if self.reason else {}),
        )

    # --- the final bench-record fields ---------------------------------------

    def final_fields(self, allocator=None,
                     scheduler=None) -> Dict[str, Any]:
        """The telemetry-derived fields of the final ``serve`` record:
        cumulative streaming-histogram quantiles (replacing the
        sample-list percentile math), the hit-vs-miss TTFT split,
        preemption/recompute pressure, anomaly section, admission
        pressure counts, and the measured hook overhead.

        Call AFTER the serve run completed: every request has finished,
        so any block still live on the allocator BEYOND the prefix
        cache's residents IS a leak (the finish-path-stopped-freeing
        regression this flag exists for — the in-loop idle check can
        only fire on a window edge, which the last iteration rarely
        lands on; a warm prefix cache holding refcounted residents is
        NOT a leak)."""
        resident = getattr(allocator, "num_resident", 0) \
            if allocator is not None else 0
        if allocator is not None and allocator.num_live > resident:
            self.leaked_blocks = max(self.leaked_blocks,
                                     allocator.num_live - resident)
        cache = getattr(scheduler, "prefix_cache", None)
        hit_rate = cache.hit_rate() if cache is not None else None
        no_itl = "no inter-token samples (single-token outputs)"
        no_ttft = "no requests reached a first token"
        no_hit = "no prefix-hit requests reached a first token"
        no_miss = "no prefix-miss requests reached a first token"
        return dict(
            latency_p50_ms=self._skip_or(
                _r3(self.itl_ms.quantile(0.5)), no_itl),
            latency_p99_ms=self._skip_or(
                _r3(self.itl_ms.quantile(0.99)), no_itl),
            ttft_p50_ms=self._skip_or(
                _r3(self.ttft_ms.quantile(0.5)), no_ttft),
            ttft_p99_ms=self._skip_or(
                _r3(self.ttft_ms.quantile(0.99)), no_ttft),
            prefix_hit_ttft_p50_ms=self._skip_or(
                _r3(self.ttft_hit_ms.quantile(0.5)), no_hit),
            prefix_hit_ttft_p99_ms=self._skip_or(
                _r3(self.ttft_hit_ms.quantile(0.99)), no_hit),
            prefix_miss_ttft_p50_ms=self._skip_or(
                _r3(self.ttft_miss_ms.quantile(0.5)), no_miss),
            prefix_miss_ttft_p99_ms=self._skip_or(
                _r3(self.ttft_miss_ms.quantile(0.99)), no_miss),
            prefix_hit_rate=self._skip_or(
                None if hit_rate is None else round(hit_rate, 4),
                "no prefix cache attached or nothing queried yet"),
            prefix_hit_requests=self.prefix_hit_requests,
            prefix_miss_requests=self.prefix_miss_requests,
            preemptions=getattr(scheduler, "preemptions",
                                self.preemptions),
            recompute_tokens=getattr(scheduler, "recompute_tokens", 0),
            swaps=self.swaps,
            replans=self.replans,
            blocks_resident=resident,
            # speculative serving: acceptance accounting (only when spec
            # rounds actually ran — a plain serve record stays unchanged)
            **({"spec_slot_rounds": self.spec_slot_rounds,
                "spec_drafted": self.spec_drafted,
                "spec_accepted": self.spec_accepted,
                "spec_acceptance_rate": round(
                    self.spec_accepted / self.spec_drafted, 4),
                "draft_k": self.draft_k}
               if self.spec_drafted else {}),
            # the pool-quantization knob the run served with (stamped
            # by the engine; absent on float pools)
            **({"kv_dtype": self.kv_dtype} if self.kv_dtype else {}),
            serve_anomaly=self.anomaly_section(allocator),
            admission_blocked_slots=self.admission_blocked_slots,
            admission_blocked_blocks=self.admission_blocked_blocks,
            queue_peak=self.queue_peak,
            serve_windows=self.windows_emitted,
        )


def _r3(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 3)
