"""Continuous-batching serving: paged KV cache + request scheduler +
chunked prefill on top of the decode stack.

``apex_tpu.inference.DecodeEngine`` decodes ONE fixed batch end to end —
every sequence prefills together, decodes in lockstep, and finishes
together. Real traffic is nothing like that: requests of mixed prompt
and output lengths arrive continuously, and a production engine must
admit and retire them *between* decode steps without ever recompiling or
stalling the in-flight streams. This package is that engine:

* :mod:`~apex_tpu.serving.kv_blocks` — the **paged KV cache**: one
  pre-allocated, donated pool of fixed-size blocks shared by every
  request, a host-side REFCOUNTED free-list :class:`~apex_tpu.serving.
  kv_blocks.BlockAllocator`, per-slot block tables, and the
  :class:`~apex_tpu.serving.kv_blocks.PrefixCache` — a chained
  full-token-key LRU index that lets N requests with a common system
  prompt share one physical prefix copy-on-write and skip its prefill
  entirely. Cache memory is bound by LIVE tokens, not
  ``batch × max_s``.
* :mod:`~apex_tpu.serving.scheduler` — the **continuous-batching
  scheduler**: a fixed-width slot array with admit/evict between steps
  by mutating cache contents, tables, and lengths only (stable avals —
  the jit cache stays at ONE executable across arbitrary churn),
  OPTIMISTIC FCFS admission against live-token demand with
  evict-and-recompute **preemption** under pool pressure (the reserved
  ``evict`` lifecycle event; the resumed token stream is identical to
  the unpreempted baseline), an :class:`~apex_tpu.serving.scheduler.
  SLOPolicy` that folds the live telemetry signals back into dispatch
  (TTFT burn → deprioritize long prompts; queue buildup → widen the
  prefill share), and **chunked prefill** so a long prompt never
  stalls the streams already decoding.
* :mod:`~apex_tpu.serving.engine` — :class:`~apex_tpu.serving.engine.
  ServingEngine`: the jitted ``prefill_chunk`` / ``decode_step`` /
  ``spec_step`` triple (each compiles once), the paged decode
  attention (:func:`apex_tpu.ops.decode_attention` with
  ``block_tables=`` — int8 pools dequantize in-kernel under the
  ``kv_dtype`` knob, per-block-row scales riding the same
  indirection), the fused sampling tail
  (:func:`apex_tpu.ops.fused_sample`), and — with ``serve(draft=...)``
  — speculative rounds: every decoding slot verifies k drafted tokens
  per dispatch through the fused verify tail
  (:func:`apex_tpu.ops.fused_verify`), block tables/lengths rewound to
  the accepted frontier, greedy output token-identical to plain
  decode (see :mod:`apex_tpu.spec` for the drafters).

* :mod:`~apex_tpu.serving.tp` — **tensor-parallel serving** (ISSUE 17):
  the eager :func:`~apex_tpu.serving.tp.validate_tp` door (every
  divisibility and knob check fails at construction with the knob
  named), plus the shard-level building blocks the TP step bodies are
  written in — column/row-parallel projections riding the ring-overlap
  collective matmuls, psum-composed vocab embed / argmax / Gumbel
  sampling tails (one draw over the full vocab row, so greedy AND
  sampled output is token-identical to ``tp=1``), and the pmax-amax
  int8 quantization whose scales are bitwise those of the unsharded
  pool.
* :mod:`~apex_tpu.serving.disagg` — **disaggregated prefill → decode**
  (ISSUE 17): the prefill role serves ``max_new_tokens=1`` clones
  (:func:`~apex_tpu.serving.disagg.prefill_requests`), exports each
  request's full-block KV chain out of the paged pool content-addressed
  by the :class:`~apex_tpu.serving.kv_blocks.PrefixCache` keys
  (:func:`~apex_tpu.serving.disagg.export_handoff`), frames it on disk
  as a digest-carrying manifest + raw block payloads
  (:func:`~apex_tpu.serving.disagg.write_handoff` /
  :func:`~apex_tpu.serving.disagg.read_handoff`, the PR-14 checkpoint
  manifest idiom), and the decode role ingests the streamed blocks
  into its own pool + prefix cache
  (:func:`~apex_tpu.serving.disagg.ingest_handoff`) so admission hits
  the warm chain and prefill collapses to the final private block —
  output token-identical to the monolithic engine.

* :mod:`~apex_tpu.serving.telemetry` — **request-level telemetry**
  (ISSUE 10): per-request lifecycle ``serve_event`` records
  (``submit → admit → prefill_chunk*k → first_token → decode →
  finish``), bounded-memory streaming latency histograms, periodic
  ``serve_window`` SLO records, and the anomaly layer (straggler decode
  steps, queue buildup, SLO burn, free-list leak/fragmentation), all
  host-side and outside the jitted steps.

Serving throughput/latency under churn is measured by ``python bench.py
--serve`` (one schema-validated ``serve`` monitor record plus the
``serve_event``/``serve_window`` stream when monitoring is enabled);
the greedy no-churn output is token-identical to ``DecodeEngine`` (the
parity the bench asserts). See ``docs/api/inference.md`` for block math
and the scheduler contract, ``docs/OBSERVABILITY.md`` for the telemetry
walkthrough.
"""

from apex_tpu.serving.disagg import (  # noqa: F401
    Handoff,
    export_handoff,
    ingest_handoff,
    prefill_requests,
    read_handoff,
    write_handoff,
)
from apex_tpu.serving.engine import ServingEngine  # noqa: F401
from apex_tpu.serving.kv_blocks import (  # noqa: F401
    DEAD_BLOCK,
    BlockAllocator,
    PrefixCache,
    blocks_needed,
)
from apex_tpu.serving.scheduler import (  # noqa: F401
    ReplanPolicy,
    Request,
    Scheduler,
    SLOPolicy,
)
from apex_tpu.serving.telemetry import ServeTelemetry  # noqa: F401
