"""Continuous-batching request scheduler: fixed slots, paged blocks,
chunked prefill — all host-side bookkeeping, zero retraces.

The device side of serving is two compiled programs with FIXED avals
(``ServingEngine.prefill_chunk`` / ``decode_step``); everything dynamic
about traffic — arrivals, mixed lengths, completions — lives HERE, in
plain Python, and is expressed to the device only as *contents* of
fixed-shape operands (tokens, lengths, block tables). That split is the
whole trick: admit/evict between steps mutates a table row and a length,
never an aval, so the jit cache stays at one executable across arbitrary
churn (asserted by ``tests/test_serving.py``).

Policies (deliberately simple, each replaceable without touching the
device programs):

* **Optimistic FCFS admission against live-token demand.** A request is
  admitted when a slot is free AND the pool (free blocks plus whatever
  the prefix cache could reclaim) covers its FIRST prefill chunk beyond
  any shared prefix — not its worst case. Blocks are allocated lazily
  as tokens actually land (memory ~ live tokens); mid-flight shortfall
  is handled by preemption, not prevented by reservation, so a pool
  sized for the common case admits far deeper under the same memory.
* **Prefix sharing (copy-on-write).** At admission the prompt's full
  blocks are looked up in the :class:`~apex_tpu.serving.kv_blocks.
  PrefixCache`; hits are retained (refcount + 1) and mapped straight
  into the slot's table row, and prefill RESUMES at the first uncached
  block — N requests with a common system prompt share one physical
  prefix and skip those chunks entirely. At least the block holding
  the prompt's last token is always recomputed privately (its
  final-row logits seed the first sampled token): that recompute IS
  the copy-on-write — shared blocks are immutable and never written.
* **Preemption = evict-and-recompute.** When an in-flight allocation
  cannot be satisfied, the scheduler reclaims prefix-cache residents
  first, then evicts the LOWEST-priority (most recently admitted)
  request: its blocks are released, the reserved ``evict`` lifecycle
  event fires, and the request re-queues at the FRONT with its
  generated tokens intact. On re-admission the generated tokens are
  teacher-forced through prefill (usually riding its own still-warm
  prefix blocks), the re-prefill's sampled token is DISCARDED, and
  decode resumes from exactly the pre-eviction state — the token
  stream is identical to the unpreempted baseline. The OLDEST request
  is never preempted for a younger one's benefit, so the head of the
  line always progresses: exhaustion degrades p99, never livelocks.
* **SLO-aware dispatch.** :class:`SLOPolicy` consumes the live
  telemetry signals (PR 9's window/anomaly layer): sustained TTFT burn
  flips admission to shortest-arrived-first (long prompts
  deprioritized until the burn clears), queue buildup widens the
  prefill-chunk share of each engine iteration (draining admission
  backlog at the cost of decode jitter).
* **Chunked prefill.** Prompts enter the cache ``prefill_chunk`` tokens
  at a time, interleaved with decode steps — a long prompt never stalls
  streams that are already decoding (the chunk size is the knob trading
  time-to-first-token against decode-step jitter).
* **Eviction = free + clear.** A finished request's references go back
  to the allocator (shared blocks just drop a count) and its table row
  resets to the dead block; the slot is immediately admissible. No
  device work at all.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from apex_tpu.serving.kv_blocks import (
    DEAD_BLOCK,
    ROOT_EID,
    BlockAllocator,
    BlockTables,
    PrefixCache,
    blocks_needed,
)


@dataclasses.dataclass
class Request:
    """One generation request plus its serving-side result fields.

    ``arrival_s`` is on the caller's clock (the engine only admits
    requests whose arrival is in the past — the bench uses it to replay
    a Poisson trace). The scheduler stamps ``admit_s`` /
    ``first_token_s`` / ``finish_s`` on the same clock and appends every
    sampled token to ``tokens`` (so per-token latency is
    ``np.diff(token_s)``).
    """

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    arrival_s: float = 0.0
    # the request-scoped trace id: minted by the telemetry at submit
    # (or stamped by the caller beforehand) and carried on this OBJECT,
    # so one id survives evict → re-admit → resume and joins every
    # span / serve_event / spec record of the request
    trace_id: Optional[str] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    token_s: List[float] = dataclasses.field(default_factory=list)
    submit_s: Optional[float] = None
    admit_s: Optional[float] = None
    first_token_s: Optional[float] = None
    finish_s: Optional[float] = None
    # serving-tier-2 result fields: how many times the request was
    # preempted, and how many full prompt blocks its FIRST admission
    # pulled straight from the prefix cache (>0 = a prefix hit — the
    # TTFT histograms split on it)
    evictions: int = 0
    prefix_hit_blocks: int = 0
    # cache rows live at the moment of the last eviction (internal:
    # sizes the recompute_tokens accounting at re-admission)
    _progress_at_evict: int = 0


@dataclasses.dataclass
class SLOPolicy:
    """SLO-aware dispatch knobs, driven by the live telemetry signals
    (:class:`~apex_tpu.serving.telemetry.ServeTelemetry`'s own
    window/anomaly layer — the engine calls :meth:`update` at every
    window edge):

    * **TTFT burn** (sustained first tokens over the SLO) →
      ``prefer_short_prompts``: admission picks the shortest ARRIVED
      prompt instead of the FCFS head — long prompts are deprioritized
      (never dropped) until the burn clears.
    * **Queue buildup** (monotone growth across windows) →
      ``prefill_share`` widens (up to ``max_prefill_share`` chunks per
      engine iteration, backing off one step per clean window): the
      backlog drains faster at the cost of decode-step jitter.

    Both knobs change only host-side dispatch ORDER and REPETITION of
    the same two compiled programs — avals never move.
    """

    max_prefill_share: int = 4
    prefill_share: int = 1
    prefer_short_prompts: bool = False
    adjustments: int = 0  # how many window edges changed a knob

    def update(self, tel) -> None:
        # key off the LIVE signal only: the sticky record flag
        # (`slo_burn`) never clears, and a policy keyed on it could
        # never stand down after TTFT recovers
        burning = bool(getattr(tel, "slo_burning", False))
        buildup = bool(getattr(tel, "queue_buildup", False))
        before = (self.prefer_short_prompts, self.prefill_share)
        self.prefer_short_prompts = burning
        if buildup:
            self.prefill_share = min(self.max_prefill_share,
                                     self.prefill_share + 1)
        else:
            # narrow on ANY window without queue buildup — NOT only on
            # fully-clean ones: a persistent benign anomaly (e.g. one
            # straggler flag per window) must never pin the share at
            # max forever (regression-tested)
            self.prefill_share = max(1, self.prefill_share - 1)
        if (self.prefer_short_prompts, self.prefill_share) != before:
            self.adjustments += 1


@dataclasses.dataclass
class ReplanPolicy(SLOPolicy):
    """Online re-planning: :class:`SLOPolicy` generalized from one
    adapted knob to a LADDER of priced ServePlan configurations
    (:mod:`apex_tpu.plan.serve`), swapped at telemetry window edges
    under load shifts — the AMP discipline (a configuration is a priced
    choice) applied online, with the veScale constraint (semantics
    equal to the baseline) enforced by construction:

    * ``plans`` is ordered calm → loaded (e.g. the top two of a
      ``search_serve_plans`` ranking). Queue buildup or a TTFT burn
      steps UP the ladder; ``calm_windows`` consecutive windows with
      neither signal step back DOWN.
    * On a switch only the AVAL-STABLE knob diffs apply live
      (:func:`~apex_tpu.plan.serve.split_knob_changes`): prefill
      share, admission order, SLO thresholds, and — between adaptive
      tree plans — the spec-shape ceiling on the controller's
      pre-compiled ladder. They change host-side dispatch ORDER and
      REPETITION only, so both jit caches stay at one executable and
      greedy output is token-identical across the switch (pinned by
      ``tests/test_serve_plan.py``).
    * Aval-CHANGING diffs (block/pool/slot/chunk sizing, drafter
      identity, kv_dtype) are DEFERRED: counted, named on the
      ``replan`` lifecycle event, and left for a ``request_swap``-style
      engine rebuild — never applied mid-serve.

    The base-class dynamics keep running WITHIN the active plan (the
    share still widens/narrows per window, bounded by the active
    plan's ``max_prefill_share``).
    """

    plans: tuple = ()
    active: int = 0
    calm_windows: int = 2        # clean windows before stepping down
    replans: int = 0             # ladder switches taken
    deferred_total: int = 0      # aval-changing knob diffs reported
    _clean_streak: int = dataclasses.field(default=0, repr=False)
    _staged: Optional[dict] = dataclasses.field(default=None, repr=False)

    def __post_init__(self):
        if not self.plans:
            raise ValueError(
                "ReplanPolicy needs at least one priced ServePlan, "
                "ordered calm -> loaded (e.g. search_serve_plans(...)"
                ".ranked[:2] plans)")
        self.plans = tuple(self.plans)
        if not 0 <= self.active < len(self.plans):
            raise ValueError(
                f"active={self.active} is not a plan index; legal "
                f"values are 0..{len(self.plans) - 1}")
        self._apply_live(self.plans[self.active], None)

    @property
    def plan(self):
        """The active :class:`~apex_tpu.plan.serve.ServePlan`."""
        return self.plans[self.active]

    def _apply_live(self, plan, tel) -> None:
        """Apply ``plan``'s aval-stable knobs: the share bound (+clamp),
        the admission order, and — when a telemetry is attached — the
        SLO thresholds its burn detector keys on."""
        self.max_prefill_share = int(plan.max_prefill_share)
        self.prefill_share = min(self.prefill_share,
                                 self.max_prefill_share)
        if plan.admission == "short_first":
            self.prefer_short_prompts = True
        if tel is not None:
            tel.slo_ttft_ms = plan.slo_ttft_ms
            tel.slo_burn_count = int(plan.slo_burn_count)

    def update(self, tel) -> None:
        burning = bool(getattr(tel, "slo_burning", False))
        buildup = bool(getattr(tel, "queue_buildup", False))
        super().update(tel)
        if self.plan.admission == "short_first":
            # the plan pins shortest-first regardless of burn state
            # (super().update keys it off the live burn signal)
            self.prefer_short_prompts = True
        if buildup or burning:
            self._clean_streak = 0
            if self.active + 1 < len(self.plans):
                self._switch(self.active + 1,
                             "queue_buildup" if buildup else "slo_burn",
                             tel)
        else:
            self._clean_streak += 1
            if self._clean_streak >= self.calm_windows and self.active:
                self._clean_streak = 0
                self._switch(self.active - 1, "calm", tel)

    def _switch(self, idx: int, trigger: str, tel) -> None:
        from apex_tpu.plan.serve import split_knob_changes

        old, new = self.plans[self.active], self.plans[idx]
        live, deferred = split_knob_changes(old, new)
        self.active = idx
        self.replans += 1
        self.adjustments += 1
        self.deferred_total += len(deferred)
        self._apply_live(new, tel)
        spec_shape = None
        if "spec_depth" in live or "spec_branching" in live:
            spec_shape = (new.spec_depth, new.spec_branching)
        self._staged = dict(
            plan_from=old.digest(), plan_to=new.digest(),
            trigger=trigger, live_knobs=sorted(live),
            deferred_knobs=sorted(deferred), spec_shape=spec_shape)

    def pop_replan(self) -> Optional[dict]:
        """The staged switch of the update that just ran (or None).
        The engine pops it at the window edge to cap the adaptive spec
        ladder and fire the ``replan`` lifecycle event — at most one
        switch is staged per window."""
        staged, self._staged = self._staged, None
        return staged


@dataclasses.dataclass
class _Slot:
    """Host state of one batch slot (None request = free)."""

    request: Optional[Request] = None
    prefilled: int = 0   # effective-prompt tokens already in the cache
    length: int = 0      # total cache rows live (prompt + generated-1)
    n_blocks: int = 0    # blocks mapped to this slot (incl. shared)
    block_ids: List[int] = dataclasses.field(default_factory=list)
    last_token: int = 0  # the sampled token the next decode step consumes
    generated: int = 0   # tokens sampled so far
    # the token rows prefill actually runs: the original prompt, plus —
    # after a preemption — the already-generated tokens teacher-forced
    # back in (all but the last, which the resumed decode re-consumes)
    eprompt: Optional[np.ndarray] = None
    shared_blocks: int = 0     # leading table entries retained from cache
    registered_blocks: int = 0  # full blocks already offered to the cache
    parent_eid: int = ROOT_EID  # cache-chain parent for the next insert
    resumed: bool = False      # re-admitted mid-generation: discard the
    #                            re-prefill's sampled token

    @property
    def free(self) -> bool:
        return self.request is None

    @property
    def prefill_done(self) -> bool:
        return (self.request is not None and self.eprompt is not None
                and self.prefilled >= len(self.eprompt))


@dataclasses.dataclass
class PrefillWork:
    """One chunk of one slot's prompt: run ``tokens`` (padded to the
    chunk size) at cache positions ``[start, start + live)``."""

    slot: int
    tokens: np.ndarray  # (prefill_chunk,) int32, zero-padded past live
    start: int
    live: int
    is_last: bool
    rid: int = -1  # the request the chunk belongs to (telemetry join)


class Scheduler:
    """See the module docstring for the policy; this class is the
    mechanism. Drive it as the engine does::

        sched.admit(now)
        work = sched.next_prefill(now)     # -> PrefillWork | None
        ... run the chunk ...; sched.note_prefill(work, token, now)
        batch = sched.decode_batch(now)    # -> (tokens, lengths) | None
        ... run the step ...; sched.note_decode(sampled, now)
    """

    def __init__(self, *, num_slots: int, block_size: int,
                 max_blocks_per_slot: int, allocator: BlockAllocator,
                 prefill_chunk: int, telemetry=None,
                 prefix_cache: Optional[PrefixCache] = None,
                 policy: Optional[SLOPolicy] = None):
        if prefill_chunk < block_size or prefill_chunk % block_size:
            raise ValueError(
                f"prefill_chunk ({prefill_chunk}) must be a positive "
                f"multiple of block_size ({block_size}) — chunks write "
                f"whole blocks")
        if (prefix_cache is not None
                and prefix_cache.allocator is not allocator):
            raise ValueError(
                "prefix_cache must index the scheduler's own allocator "
                "(its retains/releases and the pool's refcounts are one "
                "accounting)")
        self.num_slots = int(num_slots)
        self.block_size = int(block_size)
        self.max_blocks_per_slot = int(max_blocks_per_slot)
        self.prefill_chunk = int(prefill_chunk)
        self.allocator = allocator
        self.prefix_cache = prefix_cache
        self.policy = policy
        # optional apex_tpu.serving.telemetry.ServeTelemetry: lifecycle
        # hooks fire from the host bookkeeping here (admit/evict/finish
        # and admission-pressure accounting); None costs one is-None test
        self.telemetry = telemetry
        self.tables = BlockTables(num_slots, max_blocks_per_slot)
        self._slots = [_Slot() for _ in range(self.num_slots)]
        self._waiting: Deque[Request] = deque()
        # admission order of live slots: prefill picks the oldest first,
        # preemption the YOUNGEST (the tail) — FCFS priority both ways
        self._admit_order: List[int] = []
        self.completed: List[Request] = []
        # serving-tier-2 counters (surfaced on windows + the record)
        self.preemptions = 0
        self.recompute_tokens = 0
        # a paged drafter sharing this scheduler's allocator (set by
        # PagedModelDrafter.bind): its per-stream blocks free through
        # the SAME preempt/finish paths as the stream's target blocks,
        # so a preempted stream's drafter state rewinds with it
        self.draft_owner = None
        # the engine step index of the dispatch currently noted; the
        # telemetry stamps it on lifecycle records so they join to the
        # serve_prefill/serve_decode device-trace scopes by step
        self._step = 0

    # --- capacity accounting -------------------------------------------------

    def _worst_blocks(self, req: Request) -> int:
        # generation leaves the LAST sampled token out of the cache (it
        # is returned, never decoded from), hence the -1
        rows = len(req.prompt) + max(req.max_new_tokens - 1, 0)
        return blocks_needed(rows, self.block_size)

    def _effective_prompt(self, req: Request) -> np.ndarray:
        """The rows prefill must run: the prompt, plus — after a
        preemption mid-generation — every generated token but the last
        teacher-forced back in (the resumed decode step consumes the
        last one exactly as the unpreempted baseline did)."""
        if req.tokens:
            return np.concatenate(
                [np.asarray(req.prompt, np.int32),
                 np.asarray(req.tokens[:-1], np.int32)])
        return np.asarray(req.prompt, np.int32)

    def _make_room(self, need: int, requester: int, now: float) -> bool:
        """Free pool blocks until ``need`` fit: reclaim LRU prefix-cache
        residents first, then preempt the YOUNGEST in-flight request
        (never the oldest for someone else's benefit — the head of the
        line always progresses, so pressure degrades p99 instead of
        livelocking). Returns False when the requester itself was the
        youngest and got preempted (the caller skips it this round)."""
        alloc = self.allocator
        while alloc.num_free < need:
            if (self.prefix_cache is not None
                    and self.prefix_cache.reclaim(
                        need - alloc.num_free) > 0):
                continue
            victim = self._admit_order[-1] if self._admit_order else None
            if victim is None or (victim == requester
                                  and len(self._admit_order) == 1):
                raise RuntimeError(
                    f"cannot make room for {need} block(s): nothing to "
                    f"reclaim or preempt with {alloc.num_free} free of "
                    f"{alloc.num_blocks - 1} — the pool is too small "
                    f"for a single in-flight request (submit() should "
                    f"have refused it)")
            self._preempt(victim, now)
            if victim == requester:
                return False
        return True

    # --- request intake ------------------------------------------------------

    def submit(self, req: Request) -> None:
        cap = self.max_blocks_per_slot * self.block_size
        rows = len(req.prompt) + max(req.max_new_tokens - 1, 0)
        if len(req.prompt) < 1 or req.max_new_tokens < 1:
            raise ValueError(
                f"request {req.rid}: prompt and max_new_tokens must be "
                f">= 1 (the final prefill chunk samples the first token)")
        if rows > cap:
            raise ValueError(
                f"request {req.rid}: prompt ({len(req.prompt)}) + "
                f"max_new_tokens ({req.max_new_tokens}) needs {rows} "
                f"cache rows; a slot holds {cap} "
                f"(max_blocks_per_slot={self.max_blocks_per_slot} x "
                f"block_size={self.block_size})")
        # a request whose worst case exceeds the WHOLE pool could never
        # pass the admission gate — refusing it here turns a permanent
        # queue stall (serve() would spin forever) into an eager error
        # naming the knob AND the rounding recipe that sizes it
        pool_cap = self.allocator.num_blocks - 1
        need = self._worst_blocks(req)
        if need > pool_cap:
            raise ValueError(
                f"request {req.rid}: worst case needs {need} blocks — "
                f"ceil((prompt {len(req.prompt)} + max_new_tokens "
                f"{req.max_new_tokens} - 1) / block_size "
                f"{self.block_size}) — but the pool only has {pool_cap} "
                f"allocatable (num_blocks={self.allocator.num_blocks} "
                f"minus 1 dead block); it could never be admitted. "
                f"Raise num_blocks to >= {need + 1} (worst-case blocks "
                f"+ the dead block) or shorten the request")
        self._waiting.append(req)

    def admit(self, now: float) -> List[int]:
        """Move arrived waiting requests into free slots while the
        OPTIMISTIC gate holds: the pool (free + prefix-cache
        reclaimable) must cover the request's FIRST prefill chunk
        beyond its shared prefix — live-token demand, not the worst
        case. Order is FCFS; under a TTFT burn the :class:`SLOPolicy`
        flips it to shortest-arrived-prompt-first. Returns the slots
        admitted this call. The telemetry (when attached) gets one
        ``admit`` lifecycle event per admission and an
        admission-blocked-by {slots|blocks} count when an arrived
        request is held back."""
        tel = self.telemetry
        B, C = self.block_size, self.prefill_chunk
        admitted = []
        while self._waiting:
            free_slots = [i for i, s in enumerate(self._slots) if s.free]
            if not free_slots:
                break
            arrived = [k for k, r in enumerate(self._waiting)
                       if r.arrival_s <= now]
            if not arrived:
                break
            k = arrived[0]
            if self.policy is not None and self.policy.prefer_short_prompts:
                # TTFT burn: deprioritize long prompts (the effective
                # prompt — a preempted request's recompute rides along)
                k = min(arrived, key=lambda j: len(
                    self._waiting[j].prompt) + len(self._waiting[j].tokens))
            req = self._waiting[k]
            ep = self._effective_prompt(req)
            chain = (self.prefix_cache.match(ep, count=False)
                     if self.prefix_cache is not None else [])
            shared = min(len(chain), (len(ep) - 1) // B)
            first_rows = min(shared * B + C, len(ep))
            need = blocks_needed(first_rows, B) - shared
            # reclaimable headroom must EXCLUDE the chain blocks this
            # very admission would retain: they stop being reclaimable
            # the moment the request maps them, so counting them would
            # admit into guaranteed self-preemption (admit→evict thrash
            # inflating the preemption stats until the pool drains)
            self_pinned = sum(
                1 for e in chain[:shared]
                if self.allocator.refcount(e.block_id) == 1)
            headroom = self.allocator.num_free + (
                self.prefix_cache.reclaimable() - self_pinned
                if self.prefix_cache is not None else 0)
            if need > headroom:
                if tel is not None:
                    tel.on_blocked("blocks")
                break  # pool pressure: hold order, retry next step
            del self._waiting[k]
            admitted.append(self._admit_one(free_slots[0], req, ep, now,
                                            chain))
        if (tel is not None and self._waiting
                and not any(s.free for s in self._slots)
                and any(r.arrival_s <= now for r in self._waiting)):
            tel.on_blocked("slots")
        return admitted

    def _admit_one(self, i: int, req: Request, ep: np.ndarray,
                   now: float, chain) -> int:
        """Bind ``req`` to slot ``i``: retain its cached prefix chain
        (``chain`` — the gate's side-effect-free match, now committed:
        stamped MRU + counted) into the table row, set prefill to
        resume at the first uncached block, and — on a re-admission
        after preemption — restore the decode state (generated count +
        last sampled token) so the resumed stream is the unpreempted
        stream."""
        B = self.block_size
        if self.prefix_cache is not None:
            self.prefix_cache.commit_match(ep, chain)
        # never use a hit on the block holding the prompt's LAST token:
        # its final-row logits seed the first sample, so that block is
        # recomputed into a private copy (the COW discipline — shared
        # blocks are immutable, writes only ever land past them)
        shared = min(len(chain), (len(ep) - 1) // B)
        slot = _Slot(request=req, eprompt=ep)
        for idx in range(shared):
            bid = chain[idx].block_id
            self.allocator.retain([bid])
            self.tables.assign(i, idx, bid)
            slot.block_ids.append(bid)
        slot.n_blocks = shared
        slot.shared_blocks = shared
        slot.registered_blocks = shared
        slot.parent_eid = chain[shared - 1].eid if shared else ROOT_EID
        slot.prefilled = shared * B
        slot.length = slot.prefilled
        first_admission = req.admit_s is None
        if first_admission:
            req.prefix_hit_blocks = shared
        else:
            # evict-and-recompute: rows that were live at eviction and
            # must be prefilled AGAIN beyond what the cache handed back
            self.recompute_tokens += max(
                0, int(req._progress_at_evict) - shared * B)
        if req.tokens:
            slot.resumed = True
            slot.generated = len(req.tokens)
            slot.last_token = int(req.tokens[-1])
        self._slots[i] = slot
        self._admit_order.append(i)
        req.admit_s = now
        if self.telemetry is not None:
            self.telemetry.on_admit(req, i, now, prefix_hit_blocks=shared,
                                    resumed=slot.resumed)
        return i

    # --- chunked prefill -----------------------------------------------------

    def next_prefill(self, now: float = 0.0) -> Optional[PrefillWork]:
        """The oldest admitted slot still prefilling → its next chunk
        (allocating the blocks the chunk's LIVE tokens land in; under
        pool pressure :meth:`_make_room` reclaims cache residents or
        preempts the youngest request first). Chunks run over the slot's
        EFFECTIVE prompt and resume at the shared-prefix frontier, so a
        prefix hit never re-runs the cached chunks."""
        for i in list(self._admit_order):
            slot = self._slots[i]
            if slot.request is None or slot.prefill_done:
                continue
            req = slot.request
            ep = slot.eprompt
            start = slot.prefilled
            live = min(self.prefill_chunk, len(ep) - start)
            need = blocks_needed(start + live, self.block_size) - slot.n_blocks
            if need > 0:
                if not self._make_room(need, i, now):
                    continue  # the slot preempted ITSELF: next candidate
                for bid in self.allocator.allocate(need):
                    self.tables.assign(i, slot.n_blocks, bid)
                    slot.block_ids.append(bid)
                    slot.n_blocks += 1
            tokens = np.zeros((self.prefill_chunk,), np.int32)
            tokens[:live] = ep[start:start + live]
            return PrefillWork(
                slot=i, tokens=tokens, start=start, live=live,
                is_last=(start + live >= len(ep)), rid=req.rid)
        return None

    def note_prefill(self, work: PrefillWork, sampled_token: int,
                     now: float) -> List[Request]:
        """Record a finished chunk; on the LAST chunk, ``sampled_token``
        is the request's first generated token (time-to-first-token
        stamps here) — UNLESS the slot is resuming after a preemption:
        the resumed decode state was restored at admission and the
        re-prefill's sample is discarded, so the next decode step
        re-samples from exactly the baseline program and operands.
        Freshly completed full prompt blocks are offered to the prefix
        cache. Returns requests finished by this call (max_new_tokens
        == 1 completes at prefill)."""
        slot = self._slots[work.slot]
        slot.prefilled += work.live
        slot.length = slot.prefilled
        self._register_prefix_blocks(work.slot)
        if not work.is_last:
            return []
        req = slot.request
        tel = self.telemetry
        if slot.resumed:
            slot.resumed = False  # back in steady decode
            if tel is not None:
                tel.on_resume(req, work.slot, slot.n_blocks, self._step,
                              now)
            return []
        slot.last_token = int(sampled_token)
        slot.generated = 1
        req.tokens.append(int(sampled_token))
        req.token_s.append(now)
        req.first_token_s = now
        if tel is not None:
            tel.on_first_token(req, work.slot, slot.n_blocks, self._step,
                               now)
        if slot.generated >= req.max_new_tokens:
            return [self._finish(work.slot, now)]
        return []

    def _register_prefix_blocks(self, i: int) -> None:
        """Offer every freshly completed FULL effective-prompt block to
        the prefix cache, chained on the slot's verified parent. Only
        prompt rows are ever indexed (generated rows beyond the
        effective prompt belong to decode and keep mutating); once a
        full block's chunk completes, its content is immutable — decode
        writes land strictly past the prompt frontier."""
        if self.prefix_cache is None:
            return
        slot = self._slots[i]
        B = self.block_size
        full = min(slot.prefilled // B, len(slot.eprompt) // B)
        for idx in range(slot.registered_blocks, full):
            slot.parent_eid = self.prefix_cache.insert(
                slot.parent_eid, slot.eprompt[idx * B:(idx + 1) * B],
                slot.block_ids[idx],
                trace_id=slot.request.trace_id)
            slot.registered_blocks = idx + 1

    # --- decode --------------------------------------------------------------

    def decoding_slots(self) -> List[int]:
        return [i for i, s in enumerate(self._slots)
                if s.request is not None and s.prefill_done]

    def decode_batch(self, now: float = 0.0, lookahead: int = 0
                     ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """The next decode step's host operands: ``(tokens, lengths)``
        over the full slot array — ``lengths[i]`` counts live rows
        INCLUDING slot i's incoming token (0 marks a dead slot: its row
        is masked on device and its write lands in the dead block).
        Allocates the new block when a slot's next position crosses a
        block boundary, visiting slots OLDEST-FIRST so that under pool
        pressure the youngest yields (reclaim, then preemption — a
        preempted victim is always at-or-after the current slot in
        admit order, so rows already placed in the batch never go
        stale). None when nothing is decoding.

        ``lookahead`` reserves blocks for that many EXTRA rows past the
        incoming token — the speculative round's k drafted rows
        (:meth:`note_spec` rewinds the reservation to the accepted
        frontier afterwards)."""
        tokens = np.zeros((self.num_slots,), np.int32)
        lengths = np.zeros((self.num_slots,), np.int32)
        any_live = False
        for i in list(self._admit_order):
            slot = self._slots[i]
            if slot.request is None or not slot.prefill_done:
                continue
            need = blocks_needed(slot.length + 1 + lookahead,
                                 self.block_size) - slot.n_blocks
            if need > 0:
                if not self._make_room(need, i, now):
                    continue  # the slot preempted ITSELF this round
                for bid in self.allocator.allocate(need):
                    self.tables.assign(i, slot.n_blocks, bid)
                    slot.block_ids.append(bid)
                    slot.n_blocks += 1
            tokens[i] = slot.last_token
            lengths[i] = slot.length + 1
            any_live = True
        if not any_live:
            return None
        return tokens, lengths

    def note_spec(self, drafted: np.ndarray, accepted: np.ndarray,
                  next_tokens: np.ndarray, now: float) -> List[Request]:
        """Record one CHAIN speculative round: per decoding slot, the
        accepted draft prefix plus the corrected token. The commit and
        rewind live in :meth:`note_spec_tokens` — this wrapper only
        turns the chain verdict (a per-slot accept LENGTH) into the
        emitted token lists; the tree path turns its accepted-path mask
        into the same shape and shares the rest verbatim."""
        emitted = {}
        for i in self.decoding_slots():
            a = int(accepted[i])
            emitted[i] = [int(t) for t in drafted[i][:a]] \
                + [int(next_tokens[i])]
        return self.note_spec_tokens(emitted, now)

    def note_spec_tokens(self, emitted_by_slot: Dict[int, List[int]],
                         now: float) -> List[Request]:
        """Commit one speculative round's emissions (any acceptance
        pattern — a chain prefix or a tree path, already resolved to
        per-slot token lists) capped at each request's remaining
        budget, and REWIND the block tables to the accepted frontier —
        blocks the round reserved past ``blocks_needed(new length)``
        free in reverse-allocation order (the LIFO free list is
        restored exactly; the worst case, an all-rejected round, leaves
        tables/lengths/free-list as a plain decode step would have) and
        their table entries reset to the dead block. Contents-only
        mutation throughout: the device programs never see an aval
        change. Inter-token latency is amortized over the round's
        emissions (a round's tokens arrive in one dispatch). Returns
        requests finished by the round."""
        tel = self.telemetry
        finished = []
        B = self.block_size
        for i, emitted in emitted_by_slot.items():
            slot = self._slots[i]
            req = slot.request
            emitted = emitted[:req.max_new_tokens - slot.generated]
            m = len(emitted)
            if tel is not None and req.token_s:
                gap = max(now - req.token_s[-1], 0.0) / m
                for _ in range(m):
                    tel.observe_itl(gap)
            req.tokens.extend(emitted)
            req.token_s.extend([now] * m)
            slot.generated += m
            slot.length += m
            slot.last_token = emitted[-1]
            # the rewind: drop the reservation past the accepted
            # frontier (pop order reverses allocation order, so the
            # allocator's LIFO free list is restored exactly)
            keep = blocks_needed(slot.length, B)
            while slot.n_blocks > keep:
                bid = slot.block_ids.pop()
                slot.n_blocks -= 1
                self.tables.assign(i, slot.n_blocks, DEAD_BLOCK)
                self.allocator.free([bid])
            if slot.generated >= req.max_new_tokens:
                finished.append(self._finish(i, now))
        return finished

    def note_decode(self, sampled: np.ndarray, now: float) -> List[Request]:
        """Record one decode step's samples; returns requests finished
        (and evicted) by it."""
        tel = self.telemetry
        finished = []
        for i in self.decoding_slots():
            slot = self._slots[i]
            slot.length += 1
            slot.last_token = int(sampled[i])
            slot.generated += 1
            req = slot.request
            if tel is not None and req.token_s:
                tel.observe_itl(now - req.token_s[-1])
            req.tokens.append(int(sampled[i]))
            req.token_s.append(now)
            if slot.generated >= req.max_new_tokens:
                finished.append(self._finish(i, now))
        return finished

    # --- eviction ------------------------------------------------------------

    def _finish(self, i: int, now: float) -> Request:
        slot = self._slots[i]
        req = slot.request
        req.finish_s = now
        tel = self.telemetry
        if tel is not None:  # blocks_held captured BEFORE they free
            tel.on_finish(req, i, slot.n_blocks, self._step, now)
        self.allocator.free(slot.block_ids)
        if self.draft_owner is not None:
            # the stream's drafter blocks free through the same path —
            # one eviction economy for target and drafter state
            self.draft_owner.evict_stream(req.rid)
        self.tables.clear(i)
        self._slots[i] = _Slot()
        self._admit_order.remove(i)
        self.completed.append(req)
        return req

    def _preempt(self, i: int, now: float,
                 reason: str = "pool_pressure") -> Request:
        """Evict-and-recompute: release slot ``i``'s block references
        (shared prefix blocks just drop a count — the cache keeps them
        warm, so the victim's own re-admission usually hits them), emit
        the reserved ``evict`` lifecycle event, and re-queue the request
        at the FRONT of the waiting line with its generated tokens
        intact. Victims are always the youngest in-flight request
        (:meth:`_make_room`), so FCFS order survives preemption."""
        slot = self._slots[i]
        req = slot.request
        req.evictions += 1
        req._progress_at_evict = (slot.length if slot.prefill_done
                                  else slot.prefilled)
        self.preemptions += 1
        tel = self.telemetry
        if tel is not None:  # blocks captured BEFORE they release
            tel.on_evict(req, i, slot.n_blocks, reason, 0, self._step,
                         now)
        self.allocator.free(slot.block_ids)
        if self.draft_owner is not None:
            # preemption rewinds the stream's drafter state through the
            # identical path: its shared-pool blocks free here and the
            # drafter's frontier rebuilds by replay on re-admission
            self.draft_owner.evict_stream(req.rid)
        self.tables.clear(i)
        self._slots[i] = _Slot()
        self._admit_order.remove(i)
        self._waiting.appendleft(req)
        return req

    def blocks_held(self, i: int) -> int:
        """Pool blocks currently allocated to slot ``i``."""
        return self._slots[i].n_blocks

    def slot_length(self, i: int) -> int:
        """Live cache rows of slot ``i`` (the spec round's headroom
        check reads this before reserving draft rows)."""
        return self._slots[i].length

    def slot_rid(self, i: int) -> int:
        """Request id bound to slot ``i`` (the drafter's stream key)."""
        return self._slots[i].request.rid

    def slot_context(self, i: int) -> List[int]:
        """Slot ``i``'s TRUE token stream — prompt plus every generated
        token — the context the drafter proposes continuations of
        (deliberately not the effective prompt: a resumed request's
        stream is the unpreempted stream, so the drafter's incremental
        frontier survives eviction)."""
        req = self._slots[i].request
        return [int(t) for t in req.prompt] + list(req.tokens)

    def note_step(self, step: int) -> None:
        """Record the engine's dispatch counter so lifecycle events can
        name the prefill/decode step that produced them (the join key
        onto the serve_prefill/serve_decode device-trace scopes)."""
        self._step = int(step)

    # --- state queries -------------------------------------------------------

    @property
    def num_active(self) -> int:
        return sum(1 for s in self._slots if s.request is not None)

    @property
    def num_waiting(self) -> int:
        return len(self._waiting)

    def num_queued(self, now: float) -> int:
        """Waiting requests that have actually ARRIVED by ``now`` — the
        honest queue depth. Arrival-replay serving submits the whole
        trace upfront with future ``arrival_s``; counting those as
        queued would saturate queue telemetry at the trace length
        before any request ever waited for capacity."""
        return sum(1 for r in self._waiting if r.arrival_s <= now)

    def next_arrival(self) -> Optional[float]:
        return self._waiting[0].arrival_s if self._waiting else None

    def idle(self) -> bool:
        """No request anywhere: waiting empty and every slot free."""
        return not self._waiting and self.num_active == 0
