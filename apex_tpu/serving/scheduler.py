"""Continuous-batching request scheduler: fixed slots, paged blocks,
chunked prefill — all host-side bookkeeping, zero retraces.

The device side of serving is two compiled programs with FIXED avals
(``ServingEngine.prefill_chunk`` / ``decode_step``); everything dynamic
about traffic — arrivals, mixed lengths, completions — lives HERE, in
plain Python, and is expressed to the device only as *contents* of
fixed-shape operands (tokens, lengths, block tables). That split is the
whole trick: admit/evict between steps mutates a table row and a length,
never an aval, so the jit cache stays at one executable across arbitrary
churn (asserted by ``tests/test_serving.py``).

Policies (deliberately simple, each replaceable without touching the
device programs):

* **FCFS admission behind a worst-case reservation gate.** A request is
  admitted when a slot is free AND the pool can still cover EVERY
  in-flight request's worst case (``prompt + max_new_tokens`` rounded up
  to blocks) plus this one's. Blocks are *allocated* lazily as tokens
  actually land (memory ~ live tokens) but *reserved* pessimistically,
  so in-flight streams can never deadlock on the pool — no preemption
  machinery needed.
* **Chunked prefill.** Prompts enter the cache ``prefill_chunk`` tokens
  at a time, one chunk per scheduler iteration, interleaved with decode
  steps — a long prompt never stalls streams that are already decoding
  (the chunk size is the knob trading time-to-first-token against
  decode-step jitter).
* **Eviction = free + clear.** A finished request's blocks go back to
  the free list and its table row resets to the dead block; the slot is
  immediately admissible. No device work at all.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, List, Optional, Tuple

import numpy as np

from apex_tpu.serving.kv_blocks import (
    DEAD_BLOCK,
    BlockAllocator,
    BlockTables,
    blocks_needed,
)


@dataclasses.dataclass
class Request:
    """One generation request plus its serving-side result fields.

    ``arrival_s`` is on the caller's clock (the engine only admits
    requests whose arrival is in the past — the bench uses it to replay
    a Poisson trace). The scheduler stamps ``admit_s`` /
    ``first_token_s`` / ``finish_s`` on the same clock and appends every
    sampled token to ``tokens`` (so per-token latency is
    ``np.diff(token_s)``).
    """

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    arrival_s: float = 0.0
    tokens: List[int] = dataclasses.field(default_factory=list)
    token_s: List[float] = dataclasses.field(default_factory=list)
    submit_s: Optional[float] = None
    admit_s: Optional[float] = None
    first_token_s: Optional[float] = None
    finish_s: Optional[float] = None


@dataclasses.dataclass
class _Slot:
    """Host state of one batch slot (None request = free)."""

    request: Optional[Request] = None
    prefilled: int = 0   # prompt tokens already in the cache
    length: int = 0      # total cache rows live (prompt + generated-1)
    n_blocks: int = 0    # blocks allocated to this slot
    block_ids: List[int] = dataclasses.field(default_factory=list)
    last_token: int = 0  # the sampled token the next decode step consumes
    generated: int = 0   # tokens sampled so far

    @property
    def free(self) -> bool:
        return self.request is None

    @property
    def prefill_done(self) -> bool:
        return (self.request is not None
                and self.prefilled >= len(self.request.prompt))


@dataclasses.dataclass
class PrefillWork:
    """One chunk of one slot's prompt: run ``tokens`` (padded to the
    chunk size) at cache positions ``[start, start + live)``."""

    slot: int
    tokens: np.ndarray  # (prefill_chunk,) int32, zero-padded past live
    start: int
    live: int
    is_last: bool
    rid: int = -1  # the request the chunk belongs to (telemetry join)


class Scheduler:
    """See the module docstring for the policy; this class is the
    mechanism. Drive it as the engine does::

        sched.admit(now)
        work = sched.next_prefill()        # -> PrefillWork | None
        ... run the chunk ...; sched.note_prefill(work, token, now)
        batch = sched.decode_batch()       # -> (tokens, lengths) | None
        ... run the step ...; sched.note_decode(sampled, now)
    """

    def __init__(self, *, num_slots: int, block_size: int,
                 max_blocks_per_slot: int, allocator: BlockAllocator,
                 prefill_chunk: int, telemetry=None):
        if prefill_chunk < block_size or prefill_chunk % block_size:
            raise ValueError(
                f"prefill_chunk ({prefill_chunk}) must be a positive "
                f"multiple of block_size ({block_size}) — chunks write "
                f"whole blocks")
        self.num_slots = int(num_slots)
        self.block_size = int(block_size)
        self.max_blocks_per_slot = int(max_blocks_per_slot)
        self.prefill_chunk = int(prefill_chunk)
        self.allocator = allocator
        # optional apex_tpu.serving.telemetry.ServeTelemetry: lifecycle
        # hooks fire from the host bookkeeping here (admit/finish and
        # admission-pressure accounting); None costs one is-None test
        self.telemetry = telemetry
        self.tables = BlockTables(num_slots, max_blocks_per_slot)
        self._slots = [_Slot() for _ in range(self.num_slots)]
        self._waiting: Deque[Request] = deque()
        # admission order of live slots: prefill picks the oldest first
        self._admit_order: List[int] = []
        self.completed: List[Request] = []
        # the engine step index of the dispatch currently noted; the
        # telemetry stamps it on lifecycle records so they join to the
        # serve_prefill/serve_decode device-trace scopes by step
        self._step = 0

    # --- capacity accounting -------------------------------------------------

    def _worst_blocks(self, req: Request) -> int:
        # generation leaves the LAST sampled token out of the cache (it
        # is returned, never decoded from), hence the -1
        rows = len(req.prompt) + max(req.max_new_tokens - 1, 0)
        return blocks_needed(rows, self.block_size)

    def _outstanding_reservation(self) -> int:
        """Blocks the in-flight requests may still demand (worst case
        minus what they already hold)."""
        out = 0
        for slot in self._slots:
            if slot.request is not None:
                out += self._worst_blocks(slot.request) - slot.n_blocks
        return out

    # --- request intake ------------------------------------------------------

    def submit(self, req: Request) -> None:
        cap = self.max_blocks_per_slot * self.block_size
        rows = len(req.prompt) + max(req.max_new_tokens - 1, 0)
        if len(req.prompt) < 1 or req.max_new_tokens < 1:
            raise ValueError(
                f"request {req.rid}: prompt and max_new_tokens must be "
                f">= 1 (the final prefill chunk samples the first token)")
        if rows > cap:
            raise ValueError(
                f"request {req.rid}: prompt ({len(req.prompt)}) + "
                f"max_new_tokens ({req.max_new_tokens}) needs {rows} "
                f"cache rows; a slot holds {cap} "
                f"(max_blocks_per_slot={self.max_blocks_per_slot} x "
                f"block_size={self.block_size})")
        # a request whose worst case exceeds the WHOLE pool could never
        # pass the admission gate — refusing it here turns a permanent
        # queue stall (serve() would spin forever) into an eager error
        # naming the knob AND the rounding recipe that sizes it
        pool_cap = self.allocator.num_blocks - 1
        need = self._worst_blocks(req)
        if need > pool_cap:
            raise ValueError(
                f"request {req.rid}: worst case needs {need} blocks — "
                f"ceil((prompt {len(req.prompt)} + max_new_tokens "
                f"{req.max_new_tokens} - 1) / block_size "
                f"{self.block_size}) — but the pool only has {pool_cap} "
                f"allocatable (num_blocks={self.allocator.num_blocks} "
                f"minus 1 dead block); it could never be admitted. "
                f"Raise num_blocks to >= {need + 1} (worst-case blocks "
                f"+ the dead block) or shorten the request")
        self._waiting.append(req)

    def admit(self, now: float) -> List[int]:
        """Move arrived waiting requests into free slots, FCFS, while the
        reservation gate holds. Returns the slots admitted this call.
        The telemetry (when attached) gets one ``admit`` lifecycle event
        per admission and an admission-blocked-by {slots|blocks} count
        when an arrived request is held back."""
        tel = self.telemetry
        admitted = []
        free_slots = [i for i, s in enumerate(self._slots) if s.free]
        while (self._waiting and free_slots
               and self._waiting[0].arrival_s <= now):
            req = self._waiting[0]
            if (self._worst_blocks(req) + self._outstanding_reservation()
                    > self.allocator.num_free):
                if tel is not None:
                    tel.on_blocked("blocks")
                break  # pool pressure: hold FCFS order, retry next step
            self._waiting.popleft()
            i = free_slots.pop(0)
            self._slots[i] = _Slot(request=req)
            self._admit_order.append(i)
            req.admit_s = now
            admitted.append(i)
            if tel is not None:
                tel.on_admit(req, i, now)
        if (tel is not None and not free_slots and self._waiting
                and self._waiting[0].arrival_s <= now):
            tel.on_blocked("slots")
        return admitted

    # --- chunked prefill -----------------------------------------------------

    def next_prefill(self) -> Optional[PrefillWork]:
        """The oldest admitted slot still prefilling → its next chunk
        (allocating the blocks the chunk's LIVE tokens land in)."""
        for i in self._admit_order:
            slot = self._slots[i]
            if slot.request is None or slot.prefill_done:
                continue
            req = slot.request
            start = slot.prefilled
            live = min(self.prefill_chunk, len(req.prompt) - start)
            need = blocks_needed(start + live, self.block_size) - slot.n_blocks
            if need > 0:
                for bid in self.allocator.allocate(need):
                    self.tables.assign(i, slot.n_blocks, bid)
                    slot.block_ids.append(bid)
                    slot.n_blocks += 1
            tokens = np.zeros((self.prefill_chunk,), np.int32)
            tokens[:live] = req.prompt[start:start + live]
            return PrefillWork(
                slot=i, tokens=tokens, start=start, live=live,
                is_last=(start + live >= len(req.prompt)), rid=req.rid)
        return None

    def note_prefill(self, work: PrefillWork, sampled_token: int,
                     now: float) -> List[Request]:
        """Record a finished chunk; on the LAST chunk, ``sampled_token``
        is the request's first generated token (time-to-first-token
        stamps here). Returns requests finished by this call
        (max_new_tokens == 1 completes at prefill)."""
        slot = self._slots[work.slot]
        slot.prefilled += work.live
        slot.length = slot.prefilled
        if not work.is_last:
            return []
        req = slot.request
        slot.last_token = int(sampled_token)
        slot.generated = 1
        req.tokens.append(int(sampled_token))
        req.token_s.append(now)
        req.first_token_s = now
        tel = self.telemetry
        if tel is not None:
            tel.on_first_token(req, work.slot, slot.n_blocks, self._step,
                               now)
        if slot.generated >= req.max_new_tokens:
            return [self._finish(work.slot, now)]
        return []

    # --- decode --------------------------------------------------------------

    def decoding_slots(self) -> List[int]:
        return [i for i, s in enumerate(self._slots)
                if s.request is not None and s.prefill_done]

    def decode_batch(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """The next decode step's host operands: ``(tokens, lengths)``
        over the full slot array — ``lengths[i]`` counts live rows
        INCLUDING slot i's incoming token (0 marks a dead slot: its row
        is masked on device and its write lands in the dead block).
        Allocates the new block when a slot's next position crosses a
        block boundary. None when nothing is decoding."""
        dec = self.decoding_slots()
        if not dec:
            return None
        tokens = np.zeros((self.num_slots,), np.int32)
        lengths = np.zeros((self.num_slots,), np.int32)
        for i in dec:
            slot = self._slots[i]
            need = blocks_needed(slot.length + 1, self.block_size) \
                - slot.n_blocks
            if need > 0:  # reservation gate guarantees this succeeds
                for bid in self.allocator.allocate(need):
                    self.tables.assign(i, slot.n_blocks, bid)
                    slot.block_ids.append(bid)
                    slot.n_blocks += 1
            tokens[i] = slot.last_token
            lengths[i] = slot.length + 1
        return tokens, lengths

    def note_decode(self, sampled: np.ndarray, now: float) -> List[Request]:
        """Record one decode step's samples; returns requests finished
        (and evicted) by it."""
        tel = self.telemetry
        finished = []
        for i in self.decoding_slots():
            slot = self._slots[i]
            slot.length += 1
            slot.last_token = int(sampled[i])
            slot.generated += 1
            req = slot.request
            if tel is not None and req.token_s:
                tel.observe_itl(now - req.token_s[-1])
            req.tokens.append(int(sampled[i]))
            req.token_s.append(now)
            if slot.generated >= req.max_new_tokens:
                finished.append(self._finish(i, now))
        return finished

    # --- eviction ------------------------------------------------------------

    def _finish(self, i: int, now: float) -> Request:
        slot = self._slots[i]
        req = slot.request
        req.finish_s = now
        tel = self.telemetry
        if tel is not None:  # blocks_held captured BEFORE they free
            tel.on_finish(req, i, slot.n_blocks, self._step, now)
        self.allocator.free(slot.block_ids)
        self.tables.clear(i)
        self._slots[i] = _Slot()
        self._admit_order.remove(i)
        self.completed.append(req)
        return req

    def blocks_held(self, i: int) -> int:
        """Pool blocks currently allocated to slot ``i``."""
        return self._slots[i].n_blocks

    def note_step(self, step: int) -> None:
        """Record the engine's dispatch counter so lifecycle events can
        name the prefill/decode step that produced them (the join key
        onto the serve_prefill/serve_decode device-trace scopes)."""
        self._step = int(step)

    # --- state queries -------------------------------------------------------

    @property
    def num_active(self) -> int:
        return sum(1 for s in self._slots if s.request is not None)

    @property
    def num_waiting(self) -> int:
        return len(self._waiting)

    def num_queued(self, now: float) -> int:
        """Waiting requests that have actually ARRIVED by ``now`` — the
        honest queue depth. Arrival-replay serving submits the whole
        trace upfront with future ``arrival_s``; counting those as
        queued would saturate queue telemetry at the trace length
        before any request ever waited for capacity."""
        return sum(1 for r in self._waiting if r.arrival_s <= now)

    def next_arrival(self) -> Optional[float]:
        return self._waiting[0].arrival_s if self._waiting else None

    def idle(self) -> bool:
        """No request anywhere: waiting empty and every slot free."""
        return not self._waiting and self.num_active == 0
