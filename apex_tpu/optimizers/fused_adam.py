"""Fused Adam / AdamW.

Re-design of ``apex.optimizers.FusedAdam`` (``apex/optimizers/fused_adam.py:4``;
kernel ``csrc/multi_tensor_adam.cu:25-140``). Semantics preserved:

* ``adam_w_mode=True`` (default): decoupled weight decay
  (``ADAM_MODE_1``) — ``p -= lr * (m_hat/(sqrt(v_hat)+eps) + wd*p)``
* ``adam_w_mode=False``: L2 mode (``ADAM_MODE_0``) — ``g += wd*p`` before the
  moment updates
* ``bias_correction`` on by default
* all math fp32; one fused pass over the whole parameter set

The whole update is one XLA loop over the chunked mega-buffer — the TPU
equivalent of the single ``multi_tensor_adam`` launch.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax

from apex_tpu.optimizers import multi_tensor as mt
from apex_tpu.optimizers._fused import (
    make_fused_transform, make_per_tensor_transform, resolve_layout,
    schedule_value)


def fused_adam(
    learning_rate=1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    adam_w_mode: bool = True,
    bias_correction: bool = True,
    chunk_size: int = None,  # explicit value implies layout='chunked'
    layout: str = "auto",
) -> optax.GradientTransformation:
    def adam_math(g, p, m, v, count):
        step = count.astype(jnp.float32)
        if not adam_w_mode and weight_decay:
            g = g + weight_decay * p
        m = b1 * m + (1.0 - b1) * g
        v = b2 * v + (1.0 - b2) * g * g
        if bias_correction:
            m_hat = m / (1.0 - b1 ** step)
            v_hat = v / (1.0 - b2 ** step)
        else:
            m_hat, v_hat = m, v
        update = m_hat / (jnp.sqrt(v_hat) + eps)
        if adam_w_mode and weight_decay:
            update = update + weight_decay * p
        lr = schedule_value(learning_rate, count)
        return p - lr * update, m, v

    if resolve_layout(layout, chunk_size) == "per_tensor":
        def leaf_kernel(g, p, bufs, scal, count, stats):
            new_p, m, v = adam_math(g, p, bufs["m"], bufs["v"], count)
            return new_p, {"m": m, "v": v}, scal

        return make_per_tensor_transform(
            state_buffers=("m", "v"), leaf_kernel=leaf_kernel)

    def kernel(g, p, buffers, scalars, count, layout_):
        new_p, m, v = adam_math(g, p, buffers["m"], buffers["v"], count)
        return new_p, {"m": m, "v": v}, scalars

    return make_fused_transform(
        state_buffers=("m", "v"), kernel=kernel, chunk_size=chunk_size or mt.DEFAULT_CHUNK
    )


# Apex-style alias
FusedAdam = fused_adam
