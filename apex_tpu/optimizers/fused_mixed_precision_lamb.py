"""Fused mixed-precision LAMB.

Re-design of ``apex.optimizers.FusedMixedPrecisionLamb``
(``apex/optimizers/fused_mixed_precision_lamb.py``): LAMB that holds fp32
master params + fp32 moments in *optimizer state* while the model trains in
bf16/fp16. The reference keeps ``model_params`` and ``master_params`` lists
and runs the kernel on the masters (``lamb_mp`` kernel,
``csrc/multi_tensor_lamb_mp.cu``); the returned update here is
``cast(new_master) - model_param``, so ``optax.apply_updates`` lands the model
exactly on the re-cast master — no drift between the two copies.

Also supports the reference's tensor-valued hyperparameters (lr/step as
device scalars) simply because every hyperparameter is traced.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers import multi_tensor as mt
from apex_tpu.optimizers.fused_lamb import lamb_chunked_update

PyTree = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MixedPrecisionLambState:
    count: jax.Array
    layout: mt.ChunkLayout
    master: jax.Array              # fp32 master params, chunked
    m: jax.Array
    v: jax.Array


def fused_mixed_precision_lamb(
    learning_rate=1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    bias_correction: bool = True,
    grad_averaging: bool = True,
    max_grad_norm: float = 1.0,
    use_nvlamb: bool = False,
    chunk_size: int = mt.DEFAULT_CHUNK,
) -> optax.GradientTransformation:
    def init_fn(params):
        layout = mt.make_layout(params, chunk_size)
        master, _ = mt.flatten_to_chunks(params, layout)  # fp32 copy
        zeros = jnp.zeros_like(master)
        return MixedPrecisionLambState(
            count=jnp.zeros((), jnp.int32), layout=layout,
            master=master, m=zeros, v=jnp.zeros_like(master),
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_mixed_precision_lamb requires params")
        layout = state.layout
        g, _ = mt.flatten_to_chunks(grads, layout)
        count = state.count + 1
        # identical math to fused_lamb, run on the fp32 masters
        new_master, m, v = lamb_chunked_update(
            g, state.master, state.m, state.v, count, layout,
            learning_rate=learning_rate, b1=b1, b2=b2, eps=eps,
            weight_decay=weight_decay, bias_correction=bias_correction,
            grad_averaging=grad_averaging, max_grad_norm=max_grad_norm,
            use_nvlamb=use_nvlamb,
        )

        # updates land the half-precision model exactly on cast(master)
        new_model = mt.unflatten_from_chunks(new_master, layout, like=params)
        updates = jax.tree.map(lambda n, o: (n - o).astype(o.dtype), new_model, params)
        return updates, MixedPrecisionLambState(
            count=count, layout=layout, master=new_master, m=m, v=v
        )

    return optax.GradientTransformation(init_fn, update_fn)


FusedMixedPrecisionLamb = fused_mixed_precision_lamb
