"""Fused optimizers over the chunked multi-tensor layout.

TPU-native equivalent of ``apex.optimizers``
(``apex/optimizers/__init__.py:1-6``): FusedAdam, FusedLAMB, FusedSGD,
FusedNovoGrad, FusedAdagrad, FusedMixedPrecisionLamb — each an
optax-compatible ``GradientTransformation`` whose update is a single fused
pass over a chunked flat parameter buffer (see
:mod:`apex_tpu.optimizers.multi_tensor`).
"""

from apex_tpu.optimizers.multi_tensor import (  # noqa: F401
    ChunkLayout,
    make_layout,
    flatten_to_chunks,
    unflatten_from_chunks,
    per_tensor_sqnorm,
    per_tensor_maxnorm,
    broadcast_per_tensor,
    global_norm,
    multi_tensor_scale,
    multi_tensor_axpby,
    multi_tensor_l2norm,
)
from apex_tpu.optimizers.fused_adam import fused_adam, FusedAdam  # noqa: F401
from apex_tpu.optimizers.fused_sgd import fused_sgd, FusedSGD  # noqa: F401
from apex_tpu.optimizers.fused_lamb import fused_lamb, FusedLAMB  # noqa: F401
from apex_tpu.optimizers.fused_novograd import fused_novograd, FusedNovoGrad  # noqa: F401
from apex_tpu.optimizers.fused_adagrad import fused_adagrad, FusedAdagrad  # noqa: F401
from apex_tpu.optimizers.fused_mixed_precision_lamb import (  # noqa: F401
    fused_mixed_precision_lamb,
    FusedMixedPrecisionLamb,
)
