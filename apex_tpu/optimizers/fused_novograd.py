"""Fused NovoGrad.

Re-design of ``apex.optimizers.FusedNovoGrad``
(``apex/optimizers/fused_novograd.py``; kernel
``csrc/multi_tensor_novograd.cu:100-140``). The second moment is a *per-tensor
scalar* norm of the gradient, not an elementwise buffer:

* the state stores the *norm itself*, not its square ("we store norm here
  (not ^2) so we can unify calculation for norm types",
  ``fused_novograd.py:160-162``): ``v_t = b2*v + (1-b2)*||g||`` and
  ``denom = v_t / sqrt(1-b2^t) + eps`` (``novograd.cu:151,99``)
* ``norm_type=2``: L2 norm; ``norm_type=0``: infinity norm via segment-max
* ``init_zero=False`` (default): first step initializes ``v`` to the first
  norm instead of averaging from zero (``fused_novograd.py:55-58``)
* ``reg_inside_moment`` selects where weight decay / normalization enter
  (moment_mode 0 vs 1, ``novograd.cu:100-112``)
* ``grad_averaging``: ``beta3 = 1-b1`` applied to the (normalized) grad

Per-tensor norms come from the chunked layout's segment reduction; the scalar
``v`` vector lives in ``state.scalars`` — tiny, exactly like the reference's
per-tensor ``grad_norms`` tensor.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax

from apex_tpu.optimizers import multi_tensor as mt
from apex_tpu.optimizers._fused import (
    make_fused_transform, make_per_tensor_transform, resolve_layout,
    schedule_value)


def fused_novograd(
    learning_rate=1e-3,
    b1: float = 0.95,
    b2: float = 0.98,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    grad_averaging: bool = True,
    reg_inside_moment: bool = False,
    norm_type: int = 2,
    init_zero: bool = False,
    bias_correction: bool = False,
    chunk_size: int = None,  # explicit value implies layout='chunked'
    layout: str = "auto",
) -> optax.GradientTransformation:
    if norm_type not in (0, 2):
        raise ValueError("norm_type must be 2 (L2) or 0 (inf)")

    def _common(g, p, m, v, gnorm, count, broadcast):
        step = count.astype(jnp.float32)
        beta3 = 1.0 - b1 if grad_averaging else 1.0
        first = count == 1
        if init_zero:
            v_new = b2 * v + (1.0 - b2) * gnorm
        else:
            v_new = jnp.where(first, gnorm, b2 * v + (1.0 - b2) * gnorm)
        if bias_correction:
            v_unbiased = v_new / jnp.sqrt(1.0 - b2 ** step)
            b1_corr = 1.0 - b1 ** step
        else:
            v_unbiased = v_new
            b1_corr = 1.0
        denom = broadcast(v_unbiased + eps)
        if reg_inside_moment:  # moment_mode 0 (novograd.cu:100-105)
            g_term = g / denom + weight_decay * p
            m = b1 * m + beta3 * g_term
            update = m / b1_corr
        else:  # moment_mode 1 (novograd.cu:107-112)
            m = b1 * m + beta3 * g
            update = (m / b1_corr) / denom + weight_decay * p
        lr = schedule_value(learning_rate, count)
        return p - lr * update, m, v_new

    if resolve_layout(layout, chunk_size) == "per_tensor":
        def leaf_kernel(g, p, bufs, scal, count, stats):
            gnorm = (jnp.sqrt(jnp.sum(g * g)) if norm_type == 2
                     else jnp.max(jnp.abs(g)))
            new_p, m, v_new = _common(
                g, p, bufs["m"], scal["v"], gnorm, count, lambda s: s)
            return new_p, {"m": m}, {"v": v_new}

        return make_per_tensor_transform(
            state_buffers=("m",), state_scalars=("v",),
            leaf_kernel=leaf_kernel)

    def kernel(g, p, buffers, scalars, count, layout):
        # the NORM is blended, not its square (reference
        # fused_novograd.py:160-177); beta2_correction = sqrt(1-b2^t)
        # (novograd.cu:151)
        if norm_type == 2:
            gnorm = jnp.sqrt(mt.per_tensor_sqnorm(g, layout))
        else:
            gnorm = mt.per_tensor_maxnorm(g, layout)
        new_p, m, v_new = _common(
            g, p, buffers["m"], scalars["v"], gnorm, count,
            lambda s: mt.broadcast_per_tensor(s, layout))
        return new_p, {"m": m}, {"v": v_new}

    return make_fused_transform(
        state_buffers=("m",), state_scalars=("v",), kernel=kernel, chunk_size=chunk_size or mt.DEFAULT_CHUNK
    )


FusedNovoGrad = fused_novograd
