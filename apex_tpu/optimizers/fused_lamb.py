"""Fused LAMB.

Re-design of ``apex.optimizers.FusedLAMB`` (``apex/optimizers/fused_lamb.py:4``;
kernels ``csrc/multi_tensor_lamb.cu``). Two-phase algorithm preserved:

1. global grad norm over ALL params (the reference blends fp16+fp32 lists,
   ``fused_lamb.py:120-141``); grads divided by
   ``clipped = max(global_norm / max_grad_norm, 1)`` (``multi_tensor_lamb.cu:66``)
2. Adam-style moments on the clipped grad; update term
   ``m_hat/(sqrt(v_hat)+eps) + wd*p``; per-tensor trust ratio
   ``ratio = lr * ||p|| / ||update||`` applied when ``use_nvlamb`` or
   ``wd != 0`` and both norms are nonzero (``multi_tensor_lamb.cu:255-262``)

Phase 1's per-tensor norms ride the chunked layout's segment reduction — the
whole optimizer is two fused passes + two tiny segment ops, matching the
reference's two multi-tensor launches.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax

import jax

from apex_tpu.optimizers import multi_tensor as mt
from apex_tpu.optimizers._fused import (
    make_fused_transform, make_per_tensor_transform, resolve_layout,
    schedule_value)


def lamb_update_math(
    g, p, m, v, count, clipped, *, sqnorm, broadcast,
    learning_rate, b1, b2, eps, weight_decay, bias_correction,
    grad_averaging, use_nvlamb,
):
    """Phase-2 LAMB math, layout-injected: ``sqnorm(t)`` returns per-tensor
    squared norms and ``broadcast(r)`` expands per-tensor scalars back to
    ``t``'s shape — identity/scalar for the per-tensor layout, segment ops
    for the chunked buffer. One copy of the formula serves both layouts and
    ``fused_mixed_precision_lamb``. Returns ``(new_p, new_m, new_v)``."""
    step = count.astype(jnp.float32)
    beta3 = 1.0 - b1 if grad_averaging else 1.0
    g = g / clipped

    m = b1 * m + beta3 * g
    v = b2 * v + (1.0 - b2) * g * g
    if bias_correction:
        m_hat = m / (1.0 - b1 ** step)
        v_hat = v / (1.0 - b2 ** step)
    else:
        m_hat, v_hat = m, v
    update = m_hat / (jnp.sqrt(v_hat) + eps)
    if weight_decay:
        update = update + weight_decay * p

    # per-tensor trust ratios (lamb.cu:244-262)
    p_norm = jnp.sqrt(sqnorm(p))
    u_norm = jnp.sqrt(sqnorm(update))
    lr = schedule_value(learning_rate, count)
    if use_nvlamb or weight_decay != 0.0:
        ratio = jnp.where((p_norm > 0.0) & (u_norm > 0.0),
                          lr * p_norm / u_norm,
                          jnp.full_like(p_norm, lr))
    else:
        ratio = jnp.full_like(p_norm, lr)
    return p - broadcast(ratio) * update, m, v


def clip_by_global_norm(gnorm, max_grad_norm):
    """phase 1's divisor (fused_lamb.py:120-141, lamb.cu:66)."""
    return jnp.where(gnorm > max_grad_norm, gnorm / max_grad_norm, 1.0)


def lamb_chunked_update(
    g, p, m, v, count, layout, *,
    learning_rate, b1, b2, eps, weight_decay, bias_correction,
    grad_averaging, max_grad_norm, use_nvlamb,
):
    """The two-phase LAMB math over chunked buffers; shared by
    :func:`fused_lamb` and ``fused_mixed_precision_lamb``.

    Returns ``(new_p, new_m, new_v)``.
    """
    clipped = clip_by_global_norm(mt.global_norm(g), max_grad_norm)
    return lamb_update_math(
        g, p, m, v, count, clipped,
        sqnorm=lambda t: mt.per_tensor_sqnorm(t, layout),
        broadcast=lambda r: mt.broadcast_per_tensor(r, layout),
        learning_rate=learning_rate, b1=b1, b2=b2, eps=eps,
        weight_decay=weight_decay, bias_correction=bias_correction,
        grad_averaging=grad_averaging, use_nvlamb=use_nvlamb,
    )


def fused_lamb(
    learning_rate=1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    bias_correction: bool = True,
    grad_averaging: bool = True,
    adam_w_mode: bool = True,
    max_grad_norm: float = 1.0,
    use_nvlamb: bool = False,
    chunk_size: int = None,  # explicit value implies layout='chunked'
    layout: str = "auto",
) -> optax.GradientTransformation:
    if resolve_layout(layout, chunk_size) == "per_tensor":
        def global_stats(g32, count):
            # phase 1: global norm over ALL params (fused_lamb.py:120-141)
            gnorm = jnp.sqrt(sum(jnp.sum(x * x) for x in jax.tree.leaves(g32)))
            return clip_by_global_norm(gnorm, max_grad_norm)

        def leaf_kernel(g, p, bufs, scal, count, clipped):
            new_p, m, v = lamb_update_math(
                g, p, bufs["m"], bufs["v"], count, clipped,
                sqnorm=lambda t: jnp.sum(t * t),
                broadcast=lambda r: r,
                learning_rate=learning_rate, b1=b1, b2=b2, eps=eps,
                weight_decay=weight_decay, bias_correction=bias_correction,
                grad_averaging=grad_averaging, use_nvlamb=use_nvlamb,
            )
            return new_p, {"m": m, "v": v}, scal

        return make_per_tensor_transform(
            state_buffers=("m", "v"), leaf_kernel=leaf_kernel,
            global_stats=global_stats)

    def kernel(g, p, buffers, scalars, count, layout_):
        new_p, m, v = lamb_chunked_update(
            g, p, buffers["m"], buffers["v"], count, layout_,
            learning_rate=learning_rate, b1=b1, b2=b2, eps=eps,
            weight_decay=weight_decay, bias_correction=bias_correction,
            grad_averaging=grad_averaging, max_grad_norm=max_grad_norm,
            use_nvlamb=use_nvlamb,
        )
        return new_p, {"m": m, "v": v}, scalars

    return make_fused_transform(
        state_buffers=("m", "v"), kernel=kernel, chunk_size=chunk_size or mt.DEFAULT_CHUNK
    )


FusedLAMB = fused_lamb
