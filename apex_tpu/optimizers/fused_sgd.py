"""Fused SGD with momentum.

Re-design of ``apex.optimizers.FusedSGD`` (``apex/optimizers/fused_sgd.py:6``;
kernel ``csrc/multi_tensor_sgd_kernel.cu``): classic torch-SGD semantics —
L2 weight decay into the gradient, momentum buffer
``buf = momentum*buf + (1-dampening)*g``, optional Nesterov
(``g + momentum*buf``), ``first_run`` initializing the buffer to the gradient.

The reference's special amp integration (unscale folded into the step so fp16
master grads never materialize, ``fused_sgd.py:79,95,175``) is expressed here
by the optional ``grad_scale`` argument of the kernel: pass the loss-scale
reciprocal and the unscale fuses into the same pass.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax

from apex_tpu.optimizers import multi_tensor as mt
from apex_tpu.optimizers._fused import (
    make_fused_transform, make_per_tensor_transform, resolve_layout,
    schedule_value)


def fused_sgd(
    learning_rate=1e-3,
    momentum: float = 0.0,
    dampening: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
    grad_scale: float = 1.0,
    chunk_size: int = None,  # explicit value implies layout='chunked'
    layout: str = "auto",
) -> optax.GradientTransformation:
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError("nesterov requires momentum > 0 and zero dampening")

    def kernel(g, p, buffers, scalars, count, layout):
        if grad_scale != 1.0:
            g = g * (1.0 / grad_scale)  # fused unscale (fused_sgd.py:212)
        if weight_decay:
            g = g + weight_decay * p
        if momentum:
            buf = buffers["momentum"]
            first = count == 1
            buf = jnp.where(first, g, momentum * buf + (1.0 - dampening) * g)
            d_p = g + momentum * buf if nesterov else buf
            new_buffers = {"momentum": buf}
        else:
            d_p = g
            new_buffers = buffers
        lr = schedule_value(learning_rate, count)
        return p - lr * d_p, new_buffers, scalars

    if resolve_layout(layout, chunk_size) == "per_tensor":
        # the kernel is purely elementwise — reuse it per leaf
        return make_per_tensor_transform(
            state_buffers=("momentum",) if momentum else (),
            leaf_kernel=lambda g, p, b, sc, c, stats: kernel(g, p, b, sc, c, None),
        )

    return make_fused_transform(
        state_buffers=("momentum",) if momentum else (),
        kernel=kernel,
        chunk_size=chunk_size or mt.DEFAULT_CHUNK,
    )


FusedSGD = fused_sgd
