"""The multi-tensor engine: chunked flat parameter layout.

TPU-native re-design of ``apex.multi_tensor_apply`` + ``amp_C``
(``apex/multi_tensor_apply/multi_tensor_apply.py:27-34``,
``csrc/multi_tensor_apply.cuh:41-133``). The reference batches up to 110
tensor pointers per kernel launch so one CUDA kernel updates every parameter.
On TPU the equivalent is a *layout*, not a launcher: all tensors of one dtype
are packed into a single 2-D buffer of shape ``(n_chunks, chunk_size)``,
where every tensor owns an integer number of chunks (zero-padded tail). Then:

* elementwise ops (scale/axpby/adam/sgd) are single fused XLA loops over one
  contiguous buffer — no per-tensor dispatch at all;
* per-tensor reductions (LAMB trust ratios, NovoGrad norms) become a chunk
  reduction (axis 1) followed by a tiny ``segment_sum`` over the
  chunk→tensor map — the same two-level reduction the CUDA kernels do with
  per-block partials;
* per-tensor scalars broadcast back via one gather over the chunk map.

``chunk_size`` defaults to 1024 (lane-dim multiple of 128; the reference uses
2048*32 elements per chunk, ``apex/multi_tensor_apply/__init__.py:3``).

This layout is also the substrate for ZeRO-style sharding: the flat buffer
partitions evenly over the ``dp`` axis (cf. ``DistributedFusedLAMB``'s
block/chunk/shard scheme, ``apex/contrib/optimizers/distributed_fused_lamb.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any

DEFAULT_CHUNK = 1024


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ChunkLayout:
    """Static description of how a pytree packs into the chunked buffer."""

    chunk_to_tensor: jax.Array  # i32[n_chunks] — which tensor owns each chunk
    treedef: Any = dataclasses.field(metadata=dict(static=True), default=None)
    shapes: Tuple[Tuple[int, ...], ...] = dataclasses.field(
        metadata=dict(static=True), default=()
    )
    chunk_size: int = dataclasses.field(metadata=dict(static=True), default=DEFAULT_CHUNK)

    @property
    def n_tensors(self) -> int:
        return len(self.shapes)

    @property
    def sizes(self) -> Tuple[int, ...]:
        return tuple(int(np.prod(s)) for s in self.shapes)


def make_layout(tree: PyTree, chunk_size: int = DEFAULT_CHUNK) -> ChunkLayout:
    from apex_tpu import native

    leaves, treedef = jax.tree.flatten(tree)
    shapes = tuple(tuple(x.shape) for x in leaves)
    sizes = [int(np.prod(s)) for s in shapes]
    # vectorized host-side planner (the apex_C/multi_tensor_apply host
    # loop analog; numpy repeat/cumsum, no native tier needed)
    chunk_to_tensor, _ = native.plan_layout(sizes, chunk_size)
    return ChunkLayout(
        chunk_to_tensor=jnp.asarray(chunk_to_tensor),
        treedef=treedef,
        shapes=shapes,
        chunk_size=chunk_size,
    )


def flatten_to_chunks(
    tree: PyTree, layout: ChunkLayout | None = None, *, dtype=jnp.float32
) -> Tuple[jax.Array, ChunkLayout]:
    """Pack a pytree into the ``(n_chunks, chunk_size)`` buffer (math dtype
    fp32 by default, matching the kernels' ``MATH_T = float``,
    ``csrc/multi_tensor_lamb.cu:38``)."""
    if layout is None:
        layout = make_layout(tree)
    leaves = jax.tree.leaves(tree)
    c = layout.chunk_size
    parts = []
    for x in leaves:
        flat = jnp.reshape(jnp.asarray(x, dtype), (-1,))
        pad = (-flat.size) % c if flat.size else c
        if pad:
            flat = jnp.pad(flat, (0, pad))
        parts.append(flat)
    buf = jnp.concatenate(parts).reshape(-1, c)
    return buf, layout


def unflatten_from_chunks(buf: jax.Array, layout: ChunkLayout, like: PyTree = None) -> PyTree:
    """Unpack back to the original pytree structure; if ``like`` is given,
    each leaf is cast to the corresponding leaf's dtype."""
    flat = buf.reshape(-1)
    c = layout.chunk_size
    out = []
    offset = 0
    for shape, size in zip(layout.shapes, layout.sizes):
        out.append(jnp.reshape(flat[offset : offset + size], shape))
        offset += max(1, -(-size // c)) * c
    tree = jax.tree.unflatten(layout.treedef, out)
    if like is not None:
        tree = jax.tree.map(lambda o, l: o.astype(l.dtype), tree, like)
    return tree


# --- per-tensor reductions over the chunked buffer ---------------------------

def per_tensor_sqnorm(buf: jax.Array, layout: ChunkLayout) -> jax.Array:
    """Squared L2 norm of every tensor in one pass: chunk partials + segment
    combine (cf. two-stage reduction in ``multi_tensor_l2norm_kernel.cu``)."""
    chunk_sq = jnp.sum(buf * buf, axis=1)
    return jax.ops.segment_sum(
        chunk_sq, layout.chunk_to_tensor, num_segments=layout.n_tensors
    )


def per_tensor_maxnorm(buf: jax.Array, layout: ChunkLayout) -> jax.Array:
    """Per-tensor infinity norm (NovoGrad ``norm_type=0``)."""
    chunk_max = jnp.max(jnp.abs(buf), axis=1)
    return jax.ops.segment_max(
        chunk_max, layout.chunk_to_tensor, num_segments=layout.n_tensors
    )


def broadcast_per_tensor(vals: jax.Array, layout: ChunkLayout) -> jax.Array:
    """Expand per-tensor scalars to ``(n_chunks, 1)`` for elementwise use."""
    return vals[layout.chunk_to_tensor][:, None]


def global_norm(buf: jax.Array) -> jax.Array:
    return jnp.sqrt(jnp.sum(buf * buf))


# --- pytree-level multi-tensor ops (API parity with amp_C) -------------------

def multi_tensor_scale(tree: PyTree, scale: jax.Array | float) -> Tuple[PyTree, jax.Array]:
    """Scaled copy + fused non-finite detection — ``amp_C.multi_tensor_scale``
    (``csrc/multi_tensor_scale_kernel.cu``). Returns (scaled, all_finite)."""
    from apex_tpu.utils.pytree import tree_all_finite

    scaled = jax.tree.map(lambda x: jnp.asarray(x, jnp.float32) * scale, tree)
    return scaled, tree_all_finite(scaled)


def multi_tensor_axpby(
    x_tree: PyTree, y_tree: PyTree, a: float | jax.Array = 1.0, b: float | jax.Array = 1.0
) -> Tuple[PyTree, jax.Array]:
    """``out = a*x + b*y`` with non-finite detection —
    ``amp_C.multi_tensor_axpby`` (``csrc/multi_tensor_axpby_kernel.cu``)."""
    from apex_tpu.utils.pytree import tree_all_finite

    out = jax.tree.map(
        lambda x, y: a * jnp.asarray(x, jnp.float32) + b * jnp.asarray(y, jnp.float32),
        x_tree,
        y_tree,
    )
    return out, tree_all_finite(out)


def multi_tensor_l2norm(tree: PyTree, *, per_tensor: bool = False):
    """Global (and optionally per-tensor) L2 norm —
    ``amp_C.multi_tensor_l2norm`` (``csrc/multi_tensor_l2norm_kernel.cu``)."""
    buf, layout = flatten_to_chunks(tree)
    sq = per_tensor_sqnorm(buf, layout)
    total = jnp.sqrt(jnp.sum(sq))
    if per_tensor:
        return total, jnp.sqrt(sq)
    return total
