"""Fused Adagrad.

Re-design of ``apex.optimizers.FusedAdagrad``
(``apex/optimizers/fused_adagrad.py``; kernel
``csrc/multi_tensor_adagrad.cu``): ``h += g^2``,
``p -= lr * g / (sqrt(h) + eps)``, with "adagrad_w"-style decoupled weight
decay when ``adagrad_w_mode`` (the reference's ``adagrad_w_mode`` adds
``wd*p`` to the update; plain mode folds L2 into the gradient).
"""

from __future__ import annotations

import jax.numpy as jnp
import optax

from apex_tpu.optimizers import multi_tensor as mt
from apex_tpu.optimizers._fused import (
    make_fused_transform, make_per_tensor_transform, resolve_layout,
    schedule_value)


def fused_adagrad(
    learning_rate=1e-2,
    eps: float = 1e-10,
    weight_decay: float = 0.0,
    adagrad_w_mode: bool = False,
    chunk_size: int = None,  # explicit value implies layout='chunked'
    layout: str = "auto",
) -> optax.GradientTransformation:
    def kernel(g, p, buffers, scalars, count, layout):
        h = buffers["h"]
        if not adagrad_w_mode and weight_decay:
            g = g + weight_decay * p
        h = h + g * g
        update = g / (jnp.sqrt(h) + eps)
        if adagrad_w_mode and weight_decay:
            update = update + weight_decay * p
        lr = schedule_value(learning_rate, count)
        return p - lr * update, {"h": h}, scalars

    if resolve_layout(layout, chunk_size) == "per_tensor":
        return make_per_tensor_transform(
            state_buffers=("h",),
            leaf_kernel=lambda g, p, b, sc, c, stats: kernel(g, p, b, sc, c, None),
        )

    return make_fused_transform(state_buffers=("h",), kernel=kernel, chunk_size=chunk_size or mt.DEFAULT_CHUNK)


FusedAdagrad = fused_adagrad
