"""Shared scaffolding for the fused optimizers.

Every fused optimizer follows the reference's shape
(``apex/optimizers/fused_adam.py:98-171``): collect all params into flat
lists, run ONE fused update over them, write results back. Here the flat list
is the chunked buffer of :mod:`apex_tpu.optimizers.multi_tensor`, the fused
update is a pure function ``(g2d, p2d, state2d..., count) -> (new_p2d,
new_state2d...)`` that XLA compiles to a single fused loop, and the write-back
is the unflatten. Each optimizer exposes an optax-compatible
``GradientTransformation`` so it chains with schedules/clipping like any other.

Math is fp32 regardless of param dtype (``MATH_T = float`` in every reference
kernel, e.g. ``csrc/multi_tensor_adam.cu``); updates are cast back to each
param's dtype at unflatten.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers import multi_tensor as mt

PyTree = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class FusedState:
    """Optimizer state held in the chunked layout."""

    count: jax.Array                 # i32 step counter
    layout: mt.ChunkLayout
    buffers: Dict[str, jax.Array]    # name -> (n_chunks, chunk) f32 buffers
    scalars: Dict[str, jax.Array]    # name -> per-tensor f32 vectors (novograd)


def schedule_value(lr, count):
    """Evaluate a schedule at the optax convention (0-based step): ``count``
    here is the post-increment 1-based counter kernels use for bias
    correction, so schedules see ``count - 1``."""
    return lr(count - 1) if callable(lr) else jnp.asarray(lr, jnp.float32)


def make_fused_transform(
    *,
    state_buffers: tuple,
    kernel: Callable[..., tuple],
    state_scalars: tuple = (),
    chunk_size: int = mt.DEFAULT_CHUNK,
) -> optax.GradientTransformation:
    """Build a GradientTransformation from a chunked update ``kernel``.

    ``kernel(g2d, p2d, buffers, scalars, count, layout) -> (new_p2d,
    new_buffers, new_scalars)``. The transformation's ``update`` returns
    optax-style additive updates (``new_p - p``) in each param's dtype.
    """

    def init_fn(params):
        layout = mt.make_layout(params, chunk_size)
        n_chunks = int(layout.chunk_to_tensor.shape[0])
        buffers = {
            name: jnp.zeros((n_chunks, layout.chunk_size), jnp.float32)
            for name in state_buffers
        }
        scalars = {
            name: jnp.zeros((layout.n_tensors,), jnp.float32) for name in state_scalars
        }
        return FusedState(
            count=jnp.zeros((), jnp.int32), layout=layout, buffers=buffers, scalars=scalars
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused optimizers require params")
        layout = state.layout
        g2d, _ = mt.flatten_to_chunks(grads, layout)
        p2d, _ = mt.flatten_to_chunks(params, layout)
        count = state.count + 1
        new_p2d, new_buffers, new_scalars = kernel(
            g2d, p2d, state.buffers, state.scalars, count, layout
        )
        updates = mt.unflatten_from_chunks(new_p2d - p2d, layout, like=params)
        new_state = FusedState(
            count=count, layout=layout, buffers=new_buffers, scalars=new_scalars
        )
        return updates, new_state

    return optax.GradientTransformation(init_fn, update_fn)
