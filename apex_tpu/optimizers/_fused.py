"""Shared scaffolding for the fused optimizers.

Every fused optimizer follows the reference's shape
(``apex/optimizers/fused_adam.py:98-171``): collect all params, run ONE
fused update over them, write results back. Math is fp32 regardless of
param dtype (``MATH_T = float`` in every reference kernel, e.g.
``csrc/multi_tensor_adam.cu``); updates are cast back to each param's
dtype. Each optimizer exposes an optax-compatible
``GradientTransformation`` so it chains with schedules/clipping like any
other.

Two layouts implement that contract:

* ``per_tensor`` (default): the update formula maps over the param pytree;
  XLA fuses the whole per-leaf elementwise forest into a handful of loops.
  The reference's multi-tensor *launcher* exists to amortize CUDA kernel
  dispatch over thousands of tensors — on TPU there is no per-tensor
  dispatch to amortize, and honest carry-loop timing (tools/microbench.py)
  showed the chunked path's flatten/unflatten costing two full HBM passes:
  18.4 vs 4.0 ms per step against per-tensor optax on a 186M-param GPT
  pytree, ~19 ms/step on the flagship bench.
* ``chunked``: the :mod:`apex_tpu.optimizers.multi_tensor` mega-buffer —
  the reference's semantic twin and the substrate the ZeRO-style
  distributed optimizers shard over (there the flat buffer pays for itself
  as the reduce-scatter/all-gather layout).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers import multi_tensor as mt

PyTree = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class FusedState:
    """Optimizer state held in the chunked layout."""

    count: jax.Array                 # i32 step counter
    layout: mt.ChunkLayout
    buffers: Dict[str, jax.Array]    # name -> (n_chunks, chunk) f32 buffers
    scalars: Dict[str, jax.Array]    # name -> per-tensor f32 vectors (novograd)


def schedule_value(lr, count):
    """Evaluate a schedule at the optax convention (0-based step): ``count``
    here is the post-increment 1-based counter kernels use for bias
    correction, so schedules see ``count - 1``."""
    return lr(count - 1) if callable(lr) else jnp.asarray(lr, jnp.float32)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PerTensorState:
    """Optimizer state as fp32 pytrees mirroring the params."""

    count: jax.Array                 # i32 step counter
    buffers: Dict[str, PyTree]       # name -> pytree of f32 leaves
    scalars: Dict[str, PyTree]       # name -> pytree of f32 scalars


def resolve_layout(layout: str, chunk_size=None) -> str:
    """``auto`` → per_tensor (measured: see module docstring) — unless the
    caller explicitly tuned ``chunk_size``, which only the chunked engine
    honors; silently ignoring it would be worse than taking the hint."""
    if layout == "auto":
        return "chunked" if chunk_size is not None else "per_tensor"
    if layout not in ("per_tensor", "chunked"):
        raise ValueError(
            f"layout must be auto|per_tensor|chunked, got {layout!r}")
    return layout


def make_per_tensor_transform(
    *,
    state_buffers: tuple,
    leaf_kernel: Callable[..., tuple],
    global_stats: Optional[Callable] = None,
    state_scalars: tuple = (),
) -> optax.GradientTransformation:
    """Build a GradientTransformation from a per-leaf fp32 update.

    ``leaf_kernel(g32, p32, bufs: dict, scal: dict, count, stats) ->
    (new_p32, new_bufs, new_scal)`` runs on each leaf; ``global_stats``
    (optional) maps the full fp32 grad pytree to a value passed to every
    leaf (e.g. LAMB's global grad norm).
    """

    def init_fn(params):
        buffers = {
            name: jax.tree.map(
                lambda x: jnp.zeros(x.shape, jnp.float32), params)
            for name in state_buffers
        }
        scalars = {
            name: jax.tree.map(lambda x: jnp.zeros((), jnp.float32), params)
            for name in state_scalars
        }
        return PerTensorState(
            count=jnp.zeros((), jnp.int32), buffers=buffers, scalars=scalars)

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused optimizers require params")
        count = state.count + 1
        g32 = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        stats = global_stats(g32, count) if global_stats else None

        leaves_g, treedef = jax.tree.flatten(g32)
        leaves_p = jax.tree.leaves(params)
        bufs = {n: jax.tree.leaves(state.buffers[n]) for n in state_buffers}
        scal = {n: jax.tree.leaves(state.scalars[n]) for n in state_scalars}
        upd, new_bufs, new_scal = [], {n: [] for n in state_buffers}, \
            {n: [] for n in state_scalars}
        for i, (g, p) in enumerate(zip(leaves_g, leaves_p)):
            p32 = p.astype(jnp.float32)
            nb = {n: bufs[n][i] for n in state_buffers}
            ns = {n: scal[n][i] for n in state_scalars}
            new_p, nb, ns = leaf_kernel(g, p32, nb, ns, count, stats)
            upd.append((new_p - p32).astype(p.dtype))
            for n in state_buffers:
                new_bufs[n].append(nb[n])
            for n in state_scalars:
                new_scal[n].append(ns[n])

        new_state = PerTensorState(
            count=count,
            buffers={n: jax.tree.unflatten(treedef, new_bufs[n])
                     for n in state_buffers},
            scalars={n: jax.tree.unflatten(treedef, new_scal[n])
                     for n in state_scalars},
        )
        return jax.tree.unflatten(treedef, upd), new_state

    return optax.GradientTransformation(init_fn, update_fn)


def make_fused_transform(
    *,
    state_buffers: tuple,
    kernel: Callable[..., tuple],
    state_scalars: tuple = (),
    chunk_size: int = mt.DEFAULT_CHUNK,
) -> optax.GradientTransformation:
    """Build a GradientTransformation from a chunked update ``kernel``.

    ``kernel(g2d, p2d, buffers, scalars, count, layout) -> (new_p2d,
    new_buffers, new_scalars)``. The transformation's ``update`` returns
    optax-style additive updates (``new_p - p``) in each param's dtype.
    """

    def init_fn(params):
        layout = mt.make_layout(params, chunk_size)
        n_chunks = int(layout.chunk_to_tensor.shape[0])
        buffers = {
            name: jnp.zeros((n_chunks, layout.chunk_size), jnp.float32)
            for name in state_buffers
        }
        scalars = {
            name: jnp.zeros((layout.n_tensors,), jnp.float32) for name in state_scalars
        }
        return FusedState(
            count=jnp.zeros((), jnp.int32), layout=layout, buffers=buffers, scalars=scalars
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused optimizers require params")
        layout = state.layout
        g2d, _ = mt.flatten_to_chunks(grads, layout)
        p2d, _ = mt.flatten_to_chunks(params, layout)
        count = state.count + 1
        new_p2d, new_buffers, new_scalars = kernel(
            g2d, p2d, state.buffers, state.scalars, count, layout
        )
        updates = mt.unflatten_from_chunks(new_p2d - p2d, layout, like=params)
        new_state = FusedState(
            count=count, layout=layout, buffers=new_buffers, scalars=new_scalars
        )
        return updates, new_state

    return optax.GradientTransformation(init_fn, update_fn)
