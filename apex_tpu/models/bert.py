"""BERT: bidirectional encoder with MLM head.

Re-design of ``apex/transformer/testing/standalone_bert.py``: same TP block
structure as GPT but padding-masked (bidirectional) attention via the fused
``scaled_masked_softmax`` and an MLM head over the tied vocab-parallel
embedding. Post-LN residuals (BERT convention), token-type embeddings, and a
pooler for the NSP/classification head.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import fused_layer_norm, scaled_masked_softmax
from apex_tpu.ops.attention import flash_attention
from apex_tpu.transformer import tensor_parallel as tp_lib
from apex_tpu.transformer.tensor_parallel.utils import divide


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30592
    max_seq_len: int = 512
    hidden_size: int = 768
    ffn_hidden_size: Optional[int] = None
    num_layers: int = 12
    num_heads: int = 12
    num_token_types: int = 2
    tp_size: int = 1
    tp_axis: Optional[str] = "tp"
    remat: bool = True
    dtype: Any = jnp.float32
    # "softmax": materialized scores through the fused scaled-masked-softmax
    # kernel, arbitrary pad masks (the Megatron standalone_bert path).
    # "flash": blockwise flash attention with the pad mask converted to
    # per-row kv lengths — O(s) memory, no sequence cap; requires the mask
    # to be SUFFIX padding (True only after each row's last valid token),
    # the layout every standard BERT batcher produces.
    attention_impl: str = "softmax"

    def __post_init__(self):
        if self.attention_impl not in ("softmax", "flash"):
            raise ValueError(
                f"attention_impl must be softmax|flash, got "
                f"{self.attention_impl!r}")

    @property
    def ffn(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return divide(self.hidden_size, self.num_heads)

    @property
    def local_heads(self) -> int:
        return divide(self.num_heads, self.tp_size)


class BertModel:
    def __init__(self, config: BertConfig):
        c = self.config = config
        axis = c.tp_axis if c.tp_size > 1 else None
        self.axis = axis
        self.embedding = tp_lib.VocabParallelEmbedding(
            c.vocab_size, c.hidden_size, tp_size=c.tp_size, axis_name=axis
        )
        self.qkv = tp_lib.ColumnParallelLinear(
            c.hidden_size, 3 * c.hidden_size, tp_size=c.tp_size, axis_name=axis
        )
        self.attn_out = tp_lib.RowParallelLinear(
            c.hidden_size, c.hidden_size, tp_size=c.tp_size, axis_name=axis
        )
        self.mlp_up = tp_lib.ColumnParallelLinear(
            c.hidden_size, c.ffn, tp_size=c.tp_size, axis_name=axis
        )
        self.mlp_down = tp_lib.RowParallelLinear(
            c.ffn, c.hidden_size, tp_size=c.tp_size, axis_name=axis
        )

    def init(self, key, rank: int = 0):
        c = self.config
        keys = jax.random.split(key, c.num_layers + 4)
        layers = []
        for i in range(c.num_layers):
            k = jax.random.split(keys[i], 4)
            layers.append({
                "qkv": self.qkv.init(k[0], rank, c.dtype),
                "attn_out": self.attn_out.init(k[1], rank, c.dtype),
                "ln1_w": jnp.ones((c.hidden_size,), c.dtype),
                "ln1_b": jnp.zeros((c.hidden_size,), c.dtype),
                "mlp_up": self.mlp_up.init(k[2], rank, c.dtype),
                "mlp_down": self.mlp_down.init(k[3], rank, c.dtype),
                "ln2_w": jnp.ones((c.hidden_size,), c.dtype),
                "ln2_b": jnp.zeros((c.hidden_size,), c.dtype),
            })
        return {
            "embedding": self.embedding.init(keys[-4], rank, c.dtype),
            "pos_embedding": jax.random.normal(
                keys[-3], (c.max_seq_len, c.hidden_size), c.dtype) * 0.01,
            "type_embedding": jax.random.normal(
                keys[-2], (c.num_token_types, c.hidden_size), c.dtype) * 0.01,
            "ln_emb_w": jnp.ones((c.hidden_size,), c.dtype),
            "ln_emb_b": jnp.zeros((c.hidden_size,), c.dtype),
            "layers": jax.tree.map(lambda *xs: jnp.stack(xs), *layers),
            "pooler_w": jax.random.normal(
                keys[-1], (c.hidden_size, c.hidden_size), c.dtype)
            * (1.0 / c.hidden_size ** 0.5),
            "pooler_b": jnp.zeros((c.hidden_size,), c.dtype),
        }

    def _attention(self, p, x, pad_mask):
        c = self.config
        b, s, _ = x.shape
        h, d = c.local_heads, c.head_dim
        if c.attention_impl == "flash":
            # pad mask -> per-row valid lengths: the row is truncated at the
            # FIRST masked position. For suffix padding (every standard BERT
            # batcher) this equals the valid length exactly; for an interior
            # mask it truncates early rather than ever attending a masked
            # token (sum(~mask) would) — still prefer the softmax impl for
            # arbitrary masks.
            lens = None
            if pad_mask is not None:
                lens = jnp.where(jnp.any(pad_mask, -1),
                                 jnp.argmax(pad_mask, -1), s).astype(jnp.int32)
            from apex_tpu.ops.attention import bshd_kernel_ok
            if bshd_kernel_ok(s, s, h, d, x.dtype):
                # the fast path: seq-major q/k/v straight from the GEMMs,
                # per-BATCH kv_lens consumed by the bshd kernels' head-
                # folded index maps — padded batches keep the zero-layout-
                # copy route (VERDICT r3 weak #5 cured)
                from apex_tpu.ops.attention import (
                    bshd_output_projection, bshd_qkv_projection)
                xg = self.qkv.gather_input(x)
                q, k, v = bshd_qkv_projection(
                    xg, p["qkv"]["weight"], p["qkv"].get("bias"), h, h, d)
                ctx = flash_attention(q, k, v, kv_lens=lens, layout="bshd")
                y = bshd_output_projection(ctx, p["attn_out"]["weight"],
                                           h, d)
                y = self.attn_out.reduce_output(y)
                if "bias" in p["attn_out"]:
                    y = y + p["attn_out"]["bias"]
                return y
            qkv = self.qkv.headwise(p["qkv"], x, 3 * h).reshape(
                b, 3, h, s, d)
            q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
            kv_lens = (None if lens is None
                       else jnp.broadcast_to(lens[:, None], (b, h)))
            ctx = flash_attention(q, k, v, kv_lens=kv_lens)
            return self.attn_out.headwise(p["attn_out"], ctx)
        # Head-batched projection, grouped (3, h, d) local packing — the
        # transpose-free layout of models/gpt.py:_attention
        qkv = self.qkv.headwise(p["qkv"], x, 3 * h).reshape(b, 3, h, s, d)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k)
        # mask: (b, 1, 1, s) True = masked out (padding)
        mask = None if pad_mask is None else pad_mask[:, None, None, :]
        probs = scaled_masked_softmax(scores, mask, 1.0 / float(d) ** 0.5)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        return self.attn_out.headwise(p["attn_out"], ctx)

    def _block(self, p, x, pad_mask):
        # post-LN (BERT): LN(x + sublayer(x))
        x = fused_layer_norm(x + self._attention(p, x, pad_mask), p["ln1_w"], p["ln1_b"])
        h = jax.nn.gelu(self.mlp_up(p["mlp_up"], x), approximate=True)
        m = self.mlp_down(p["mlp_down"], h)
        return fused_layer_norm(x + m, p["ln2_w"], p["ln2_b"])

    def hidden_states(self, params, tokens, token_types=None, pad_mask=None):
        c = self.config
        s = tokens.shape[1]
        x = self.embedding(params["embedding"], tokens)
        x = x + params["pos_embedding"][:s]
        if token_types is not None:
            x = x + jnp.take(params["type_embedding"], token_types, axis=0)
        x = fused_layer_norm(x, params["ln_emb_w"], params["ln_emb_b"])

        if (c.attention_impl == "flash" and pad_mask is not None
                and not isinstance(pad_mask, jax.core.Tracer)):
            # eager call (tests, interactive; checked HERE, before the
            # scan/remat turns the mask into a tracer): fail loudly on an
            # interior mask instead of silently truncating at the first
            # masked position (under jit the mask is traced and this check
            # can't run — the docstring constraint stands)
            # numpy, not jnp: a CONCRETE mask captured by a jit closure is
            # not a tracer, but jnp.any on it inside the trace yields one
            # — bool() would then fail on the very path this guard is
            # supposed to serve (found by the r4 varlen hardware drive)
            import numpy as np
            mb = np.asarray(pad_mask, bool)  # accept 0/1 float masks
            if bool(np.any(mb[..., :-1] & ~mb[..., 1:])):
                raise ValueError(
                    "attention_impl='flash' supports suffix padding only "
                    "(the pad mask must be monotone per row); use "
                    "attention_impl='softmax' for interior masks")

        block = self._block
        if c.remat:
            block = jax.checkpoint(block, static_argnums=())

        def body(x, layer):
            return block(layer, x, pad_mask), None

        x, _ = jax.lax.scan(body, x, params["layers"])
        return x

    def pooled(self, params, hidden):
        return jnp.tanh(hidden[:, 0] @ params["pooler_w"] + params["pooler_b"])

    def mlm_loss(self, params, tokens, targets, loss_mask, token_types=None, pad_mask=None):
        """Masked-LM loss over positions where loss_mask is 1."""
        x = self.hidden_states(params, tokens, token_types, pad_mask)
        logits = jnp.dot(x, params["embedding"]["weight"].T)
        losses = tp_lib.vocab_parallel_cross_entropy(logits, targets, axis_name=self.axis)
        denom = jnp.maximum(jnp.sum(loss_mask), 1.0)
        return jnp.sum(losses * loss_mask) / denom
