"""T5-class encoder-decoder transformer (seq2seq flagship for the
split-rank pipeline).

The reference ships standalone GPT/BERT test models
(``apex/transformer/testing/standalone_gpt.py``, ``standalone_bert.py``)
and carries encoder-decoder *plumbing* (``ModelType.encoder_and_decoder``,
the pipeline split rank, ``parallel_state.py:147-149``) but no
encoder-decoder model to drive it. This fills that hole TPU-first:

* pre-LN encoder blocks (bidirectional self-attention + MLP);
* pre-LN decoder blocks (causal self-attention → cross-attention over the
  encoder output → MLP);
* learned positions, tied embedding/unembedding shared by both sides,
  vocab-parallel cross entropy on the decoder output;
* attention through :func:`~apex_tpu.ops.attention.flash_attention`
  (``attention_impl='flash'``) or the fused-softmax composition;
* :class:`EncDecPipeline` partitions the stacks over a two-segment
  pipeline — stages ``[0, split)`` hold encoder layers, ``[split, pp)``
  decoder layers — driving
  :func:`~apex_tpu.transformer.pipeline_parallel.pipeline_spmd_forward_enc_dec`
  with the REAL model (the depth standard ``GPTPipeline`` set for GPT).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import fused_layer_norm, scaled_masked_softmax
from apex_tpu.ops.attention import BucketedBias, flash_attention
# the ONE bucketing closed form, shared with the Pallas kernels (public
# re-export: tests and user code keep importing it from here)
from apex_tpu.ops.pallas.attention import relative_position_bucket
from apex_tpu.transformer import tensor_parallel as tp_lib
from apex_tpu.transformer.tensor_parallel.utils import divide


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    max_seq_len: int = 512
    hidden_size: int = 512
    ffn_hidden_size: Optional[int] = None  # default 4*hidden
    num_encoder_layers: int = 6
    num_decoder_layers: int = 6
    num_heads: int = 8
    dtype: Any = jnp.float32
    attention_impl: str = "softmax"  # softmax | flash
    remat: bool = True
    # "blocks": per-block jax.checkpoint (minimum memory, the r3 default);
    # "encode_only": re-encode-in-backward — the WHOLE encoder is one
    # checkpoint, so during the decoder's forward+backward only enc_out
    # (b, s, H) stays live instead of every encoder-internal activation,
    # and the decoder itself runs un-rematted (the memory design that
    # makes the enc-dec model remat-off-capable on the decoder side;
    # VERDICT r3 weak #6).
    remat_policy: str = "blocks"
    # "learned": absolute learned positions (the r3 model). "relative":
    # T5's relative position bias — per-stack (num_buckets, heads) tables
    # added to the SELF-attention scores (encoder bidirectional buckets,
    # decoder causal buckets; cross-attention carries none, per T5), no
    # absolute positions. Composes with BOTH attention impls: 'flash'
    # hands the flash kernels the BUCKETED operand (r6, default — the
    # tiny table rides into VMEM and every score tile recomputes its
    # bias in-kernel: O(buckets·h) bias memory instead of the former
    # materialized O(h·s²) array, and the table gradient comes from the
    # in-kernel dtable kernel), and 'softmax' adds the materialized bias
    # to the scores.
    position_encoding: str = "learned"
    relative_num_buckets: int = 32
    relative_max_distance: int = 128
    # "bucketed": the in-kernel path above (flash only). "materialized":
    # the r5 behavior — build the (1, h, sq, sk) array host-side and feed
    # the kernels' array-bias operand. Kept as the FALLBACK/ORACLE the
    # parity tests compare against; O(h·s²) HBM, unusable at long seq.
    relative_bias_impl: str = "bucketed"
    # Mirror of GPTConfig.tp_overlap, validated here so the flag means
    # the same thing across both model configs: this stack's block
    # builders run their linears UNSHARDED (no tp axis — the enc-dec
    # model parallelizes over dp/pp only), so there is no boundary
    # collective to overlap and True is an eager config error rather
    # than a silent no-op.
    tp_overlap: bool = False
    # Unified parallelism object (ISSUE 12), mirror of GPTConfig.plan:
    # the enc-dec stack runs its linears unsharded (dp/pp only), so a
    # plan here must carry tp=1 / tp_overlap=False — anything else is
    # the same eager error the loose tp_overlap flag raises. A shim
    # plan is constructed when None so every config owns one.
    plan: Optional[Any] = None

    def __post_init__(self):
        from apex_tpu.plan.parallel_plan import ParallelPlan

        if self.plan is not None:
            p = self.plan
            if not isinstance(p, ParallelPlan):
                p = ParallelPlan.from_json(p)
                object.__setattr__(self, "plan", p)
            if p.tp > 1 or p.tp_overlap or p.sequence_parallel:
                raise ValueError(
                    f"plan {p.describe()} sets tensor-parallel knobs "
                    "(tp/sequence_parallel/tp_overlap), and the enc-dec "
                    "stack runs its linears unsharded (dp/pp only); "
                    "tensor parallelism belongs on GPTConfig, whose "
                    "Column/Row parallel linears carry it")
            if self.tp_overlap:
                # an explicit loose tp_overlap=True must keep its
                # historical eager error (below), never be silently
                # overwritten by the plan's False
                raise ValueError(
                    f"tp_overlap=True contradicts plan={p.describe()} "
                    "(which implies tp_overlap=False) — and tp_overlap "
                    "belongs on GPTConfig either way")
        else:
            # every config owns a plan (tp_overlap=True raises its own
            # GPTConfig-pointing error below either way)
            object.__setattr__(self, "plan",
                               ParallelPlan.from_model_kwargs(tp_size=1))
        if self.attention_impl not in ("softmax", "flash"):
            raise ValueError(
                f"attention_impl must be softmax|flash, got "
                f"{self.attention_impl!r}")
        if self.remat_policy not in ("blocks", "encode_only"):
            raise ValueError(
                f"remat_policy must be blocks|encode_only, got "
                f"{self.remat_policy!r}")
        if self.position_encoding not in ("learned", "relative"):
            raise ValueError(
                f"position_encoding must be learned|relative, got "
                f"{self.position_encoding!r}")
        if self.relative_bias_impl not in ("bucketed", "materialized"):
            raise ValueError(
                f"relative_bias_impl must be bucketed|materialized, got "
                f"{self.relative_bias_impl!r}")
        if self.tp_overlap:
            raise ValueError(
                "tp_overlap overlaps tensor-parallel boundary collectives "
                "with the linears' GEMMs, and the enc-dec stack runs its "
                "linears unsharded (dp/pp only — no tp axis, no boundary "
                "collective to hide); set tp_overlap on GPTConfig, whose "
                "Column/Row parallel linears carry the overlapped rings")

    @property
    def ffn(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return divide(self.hidden_size, self.num_heads)


def _dense(key, shape, dtype, scale=None):
    fan_in = shape[-1]
    s = scale if scale is not None else (1.0 / fan_in) ** 0.5
    return jax.random.normal(key, shape, dtype) * s


def relative_bias(table, sq, sk, *, bidirectional, num_buckets,
                  max_distance):
    """(1, heads, sq, sk) additive attention bias MATERIALIZED from a
    (num_buckets, heads) table — the oracle/softmax-impl form (the flash
    default computes the same bias in-kernel from the table; see
    ``T5Config.relative_bias_impl``). ``relative_position_bucket`` is
    re-exported here from ``ops.pallas.attention`` — the ONE closed-form
    definition the kernels evaluate per tile."""
    rel = (jnp.arange(sk, dtype=jnp.int32)[None, :]
           - jnp.arange(sq, dtype=jnp.int32)[:, None])
    buckets = relative_position_bucket(
        rel, bidirectional=bidirectional, num_buckets=num_buckets,
        max_distance=max_distance)
    return table[buckets].transpose(2, 0, 1)[None]  # (1, h, sq, sk)


class EncoderDecoderModel:
    """Functional T5-class model. ``init(key)`` → params;
    ``loss_fn(params, enc_tokens, dec_tokens, targets)`` → mean CE of the
    decoder output (teacher forcing: ``dec_tokens`` is the shifted-right
    target stream)."""

    def __init__(self, config: T5Config):
        self.config = config

    # --- params ---------------------------------------------------------------

    def init(self, key):
        c = self.config
        H, F = c.hidden_size, c.ffn

        def enc_layer(k):
            ks = jax.random.split(k, 4)
            return {
                "ln1_w": jnp.ones((H,), c.dtype),
                "ln1_b": jnp.zeros((H,), c.dtype),
                "qkv": _dense(ks[0], (3 * H, H), c.dtype),
                "attn_out": _dense(ks[1], (H, H), c.dtype),
                "ln2_w": jnp.ones((H,), c.dtype),
                "ln2_b": jnp.zeros((H,), c.dtype),
                "mlp_up": _dense(ks[2], (F, H), c.dtype),
                "mlp_down": _dense(ks[3], (H, F), c.dtype),
            }

        def dec_layer(k):
            ks = jax.random.split(k, 7)
            return {
                "ln1_w": jnp.ones((H,), c.dtype),
                "ln1_b": jnp.zeros((H,), c.dtype),
                "qkv": _dense(ks[0], (3 * H, H), c.dtype),
                "attn_out": _dense(ks[1], (H, H), c.dtype),
                "ln_x_w": jnp.ones((H,), c.dtype),
                "ln_x_b": jnp.zeros((H,), c.dtype),
                "xq": _dense(ks[2], (H, H), c.dtype),
                "xkv": _dense(ks[3], (2 * H, H), c.dtype),
                "x_out": _dense(ks[4], (H, H), c.dtype),
                "ln2_w": jnp.ones((H,), c.dtype),
                "ln2_b": jnp.zeros((H,), c.dtype),
                "mlp_up": _dense(ks[5], (F, H), c.dtype),
                "mlp_down": _dense(ks[6], (H, F), c.dtype),
            }

        keys = jax.random.split(key, c.num_encoder_layers
                                + c.num_decoder_layers + 3)
        enc = [enc_layer(keys[i]) for i in range(c.num_encoder_layers)]
        dec = [dec_layer(keys[c.num_encoder_layers + i])
               for i in range(c.num_decoder_layers)]
        params = {
            "embedding": _dense(keys[-2], (c.vocab_size, H), c.dtype,
                                scale=1.0),
            "encoder": jax.tree.map(lambda *x: jnp.stack(x), *enc),
            "decoder": jax.tree.map(lambda *x: jnp.stack(x), *dec),
            "ln_enc_w": jnp.ones((H,), c.dtype),
            "ln_enc_b": jnp.zeros((H,), c.dtype),
            "ln_dec_w": jnp.ones((H,), c.dtype),
            "ln_dec_b": jnp.zeros((H,), c.dtype),
        }
        if c.position_encoding == "relative":
            # per-stack tables SHARED across the stack's layers (T5's
            # convention); no absolute positions in relative mode
            kb = jax.random.split(keys[-3], 2)
            params["rel_bias_enc"] = jax.random.normal(
                kb[0], (c.relative_num_buckets, c.num_heads),
                c.dtype) * 0.1
            params["rel_bias_dec"] = jax.random.normal(
                kb[1], (c.relative_num_buckets, c.num_heads),
                c.dtype) * 0.1
        else:
            params["pos_embedding"] = jax.random.normal(
                keys[-1], (c.max_seq_len, H), c.dtype) * 0.01
        return params

    # --- attention pieces -----------------------------------------------------

    def _heads(self, x):
        b, s, _ = x.shape
        c = self.config
        return x.reshape(b, s, c.num_heads, c.head_dim).transpose(0, 2, 1, 3)

    def _merge(self, x):
        b, h, s, d = x.shape
        return x.transpose(0, 2, 1, 3).reshape(b, s, h * d)

    def _attn(self, q, k, v, causal, bias=None, kv_lens=None):
        """``kv_lens`` (b,) int32: per-batch valid KEY lengths (suffix
        padding) — positions >= the length are masked out. The padding
        path of the enc-dec stack (VERDICT r4 next #4; the reference's
        ``encdec_multihead_attn`` ``key_padding_mask``,
        ``contrib/multihead_attn/encdec_multihead_attn.py:106-119``):
        encoder self-attention takes the encoder pad lengths, decoder
        cross-attention takes the SAME lengths over the encoder memory."""
        c = self.config
        if c.attention_impl == "flash":
            # bucketed mode hands the kernels the BucketedBias operand
            # directly (in-kernel recompute; dtable kernel grads).
            # Materialized mode: bias (1, h, sq, sk) → the kernels'
            # (h, sq, sk) per-head form (row r of the b·h flatten reads
            # bias row r % h = its head); the flash custom-VJP returns
            # dbias, which autodiff carries back through relative_bias's
            # gather into the bucket table. kv_lens expands to q's (b, h)
            # leading dims (heads share a row's padding) — the flash path
            # stays fused under padding.
            lens = None
            if kv_lens is not None:
                lens = jnp.broadcast_to(kv_lens[:, None].astype(jnp.int32),
                                        q.shape[:2])
            if isinstance(bias, BucketedBias):
                fbias = bias
            else:
                fbias = None if bias is None else bias[0]
            return flash_attention(
                q, k, v, causal=causal, kv_lens=lens, bias=fbias)
        d = q.shape[-1]
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k)
        b, h, sq, sk = scores.shape
        pad = None
        if kv_lens is not None:  # True = masked (key position >= length)
            pad = (jnp.arange(sk)[None, :]
                   >= kv_lens[:, None])[:, None, None, :]
        if bias is not None:
            # relative position bias enters the SCALED scores (this model
            # keeps the 1/sqrt(d) scale T5 proper omits — the bias is
            # learned against whatever scale the scores carry)
            s = scores.astype(jnp.float32) / float(d) ** 0.5 + bias
            if causal:
                cmask = jnp.tril(jnp.ones((sq, sk), bool))
                s = jnp.where(cmask[None, None], s, -1e30)
            if pad is not None:
                s = jnp.where(pad, -1e30, s)
            probs = jax.nn.softmax(s, axis=-1).astype(v.dtype)
            return jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        if causal:
            mask = ~jnp.tril(jnp.ones((sq, sk), bool))[None, None]
            if pad is not None:
                mask = mask | pad
        else:
            mask = (jnp.broadcast_to(pad, (b, 1, sq, sk))
                    if pad is not None else None)
        probs = scaled_masked_softmax(scores, mask, 1.0 / float(d) ** 0.5)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, v)

    # --- blocks ---------------------------------------------------------------

    def encoder_block(self, p, x, bias=None, pad_lens=None):
        h = fused_layer_norm(x, p["ln1_w"], p["ln1_b"])
        q, k, v = jnp.split(h @ p["qkv"].T, 3, -1)
        a = self._merge(self._attn(self._heads(q), self._heads(k),
                                   self._heads(v), False, bias,
                                   kv_lens=pad_lens))
        x = x + a @ p["attn_out"].T
        h = fused_layer_norm(x, p["ln2_w"], p["ln2_b"])
        return x + jax.nn.gelu(h @ p["mlp_up"].T,
                               approximate=True) @ p["mlp_down"].T

    def decoder_block(self, p, x, enc_out, bias=None, enc_pad_lens=None):
        h = fused_layer_norm(x, p["ln1_w"], p["ln1_b"])
        q, k, v = jnp.split(h @ p["qkv"].T, 3, -1)
        a = self._merge(self._attn(self._heads(q), self._heads(k),
                                   self._heads(v), True, bias))
        x = x + a @ p["attn_out"].T
        h = fused_layer_norm(x, p["ln_x_w"], p["ln_x_b"])
        q = h @ p["xq"].T
        ck, cv = jnp.split(enc_out @ p["xkv"].T, 2, -1)
        # cross-attention masks the ENCODER's padded positions as keys —
        # padded enc_out rows (whatever garbage the padded tokens carry)
        # can never reach a decoder position
        a = self._merge(self._attn(self._heads(q), self._heads(ck),
                                   self._heads(cv), False,
                                   kv_lens=enc_pad_lens))
        x = x + a @ p["x_out"].T
        h = fused_layer_norm(x, p["ln2_w"], p["ln2_b"])
        return x + jax.nn.gelu(h @ p["mlp_up"].T,
                               approximate=True) @ p["mlp_down"].T

    def _wrapped(self, fn):
        c = self.config
        if c.remat and c.remat_policy == "blocks":
            return jax.checkpoint(fn)
        return fn

    def _stack_bias(self, params, name, sq, sk, bidirectional):
        c = self.config
        if c.position_encoding != "relative":
            return None
        if (c.attention_impl == "flash"
                and c.relative_bias_impl == "bucketed"):
            # the in-kernel path: hand the TINY table to the kernels —
            # nothing O(s²) is ever built, and the same operand rides
            # ring/ulysses under cp (global offsets per stripe piece)
            return BucketedBias(
                params[name], bidirectional=bidirectional,
                max_distance=c.relative_max_distance)
        return relative_bias(
            params[name].astype(jnp.float32), sq, sk,
            bidirectional=bidirectional,
            num_buckets=c.relative_num_buckets,
            max_distance=c.relative_max_distance)

    def enc_bias(self, params, sq, sk):
        '''Shared encoder self-attention bias — a BucketedBias on the
        flash bucketed path, the materialized (1, h, sq, sk) array on the
        softmax/materialized-oracle paths, or None (learned mode).'''
        return self._stack_bias(params, "rel_bias_enc", sq, sk, True)

    def dec_bias(self, params, sq, sk):
        return self._stack_bias(params, "rel_bias_dec", sq, sk, False)

    # --- forward --------------------------------------------------------------

    def embed(self, params, tokens):
        x = jnp.take(params["embedding"], tokens, axis=0)
        if self.config.position_encoding == "relative":
            return x  # positions live in the attention bias
        return x + params["pos_embedding"][:tokens.shape[1]]

    def encode(self, params, enc_tokens, enc_pad_lens=None):
        """``enc_pad_lens`` (b,) int32: per-batch valid encoder lengths
        (suffix padding) — self-attention masks padded KEY positions on
        the flash fast path via the kernels' ``kv_lens`` operand (padded
        QUERY rows still compute, but nothing downstream ever reads them:
        cross-attention masks them as keys and the loss never sees
        encoder positions)."""
        x = self.embed(params, enc_tokens)
        s = enc_tokens.shape[1]
        bias = self.enc_bias(params, s, s)
        block = self._wrapped(self.encoder_block)

        def body(x, layer):
            return block(layer, x, bias, enc_pad_lens), None

        x, _ = jax.lax.scan(body, x, params["encoder"])
        return fused_layer_norm(x, params["ln_enc_w"], params["ln_enc_b"])

    def decode(self, params, dec_tokens, enc_out, enc_pad_lens=None):
        x = self.embed(params, dec_tokens)
        s = dec_tokens.shape[1]
        bias = self.dec_bias(params, s, s)
        block = self._wrapped(self.decoder_block)

        def body(x, layer):
            return block(layer, x, enc_out, bias, enc_pad_lens), None

        x, _ = jax.lax.scan(body, x, params["decoder"])
        return fused_layer_norm(x, params["ln_dec_w"], params["ln_dec_b"])

    def logits(self, params, enc_tokens, dec_tokens, enc_pad_lens=None):
        c = self.config
        encode = self.encode
        if c.remat and c.remat_policy == "encode_only":
            # re-encode-in-backward: only enc_out stays live through the
            # decoder; the encoder re-forwards once during backward
            encode = jax.checkpoint(self.encode)
        enc_out = encode(params, enc_tokens, enc_pad_lens)
        x = self.decode(params, dec_tokens, enc_out, enc_pad_lens)
        return x @ params["embedding"].T  # tied unembedding

    def loss_fn(self, params, enc_tokens, dec_tokens, targets,
                loss_mask=None, enc_pad_lens=None):
        """``enc_pad_lens`` (b,) masks encoder padding through the stack
        (see :meth:`encode`); ``loss_mask`` masks decoder padding out of
        the mean — together they make padded seq2seq batches first-class
        on the fused path (VERDICT r4 next #4)."""
        logits = self.logits(params, enc_tokens, dec_tokens, enc_pad_lens)
        losses = tp_lib.vocab_parallel_cross_entropy(
            logits, targets, axis_name=None)
        return tp_lib.masked_mean(losses, loss_mask)


@dataclasses.dataclass
class EncDecPipeline:
    """Two-segment pipeline execution of :class:`EncoderDecoderModel`:
    stages ``[0, split)`` hold encoder-layer slices, ``[split, pp)``
    decoder-layer slices. Stage params carry the UNION structure (each
    stage stores both segments' leaves; the unused one is dead weight —
    program uniformity, cf. ``pipeline_parallel/encoder_decoder.py``).

    ``partition(params)`` → ``{embed, stages, head}`` with stage leaves
    leading ``(pp, ...)``; ``loss_and_grads`` runs inside shard_map with
    the pp axis bound and returns the same loss as ``loss_fn`` on the
    concatenated microbatches."""

    model: EncoderDecoderModel
    pp: int
    split: int

    def __post_init__(self):
        c = self.model.config
        if not (0 < self.split < self.pp):
            raise ValueError(
                f"split ({self.split}) must lie strictly inside the "
                f"{self.pp}-stage pipeline")
        if c.num_encoder_layers % self.split:
            raise ValueError(
                f"num_encoder_layers ({c.num_encoder_layers}) must divide "
                f"over {self.split} encoder stages")
        if c.num_decoder_layers % (self.pp - self.split):
            raise ValueError(
                f"num_decoder_layers ({c.num_decoder_layers}) must divide "
                f"over {self.pp - self.split} decoder stages")

    @property
    def enc_per_stage(self):
        return self.model.config.num_encoder_layers // self.split

    @property
    def dec_per_stage(self):
        return self.model.config.num_decoder_layers // (self.pp - self.split)

    def partition(self, params):
        ne, nd = self.enc_per_stage, self.dec_per_stage
        n_dec_stages = self.pp - self.split

        def split_enc(x):  # (L_e, ...) -> (pp, ne, ...): pad decoder
            y = x.reshape(self.split, ne, *x.shape[1:])
            pad = jnp.zeros((n_dec_stages, ne) + x.shape[1:], x.dtype)
            return jnp.concatenate([y, pad], 0)

        def split_dec(x):  # (L_d, ...) -> (pp, nd, ...): pad encoder
            y = x.reshape(n_dec_stages, nd, *x.shape[1:])
            pad = jnp.zeros((self.split, nd) + x.shape[1:], x.dtype)
            return jnp.concatenate([pad, y], 0)

        embed = {"embedding": params["embedding"],
                 "ln_enc_w": params["ln_enc_w"],
                 "ln_enc_b": params["ln_enc_b"]}
        # learned mode carries pos_embedding; relative mode the two
        # per-stack bias tables — replicate whichever exists
        for name in ("pos_embedding", "rel_bias_enc", "rel_bias_dec"):
            if name in params:
                embed[name] = params[name]
        return {
            "embed": embed,
            "stages": {
                "enc": jax.tree.map(split_enc, params["encoder"]),
                "dec": jax.tree.map(split_dec, params["decoder"]),
            },
            "head": {"ln_dec_w": params["ln_dec_w"],
                     "ln_dec_b": params["ln_dec_b"]},
        }

    def param_specs(self, pipe_params):
        from jax.sharding import PartitionSpec as P
        return {
            "embed": jax.tree.map(lambda _: P(), pipe_params["embed"]),
            "stages": jax.tree.map(lambda _: P("pp"),
                                   pipe_params["stages"]),
            "head": jax.tree.map(lambda _: P(), pipe_params["head"]),
        }

    def loss_and_grads(self, pipe_params, enc_tokens, dec_tokens, targets,
                       *, loss_mask=None, enc_pad_lens=None,
                       accum_dtype=jnp.float32, dp_axis=None):
        """(M, b, s) microbatched token triples → (loss, grads). Must run
        inside shard_map with the pp axis bound; stage leaves are this
        device's local (n_layers, ...) slices.

        ``enc_pad_lens`` (M, b) int32: per-microbatch encoder valid
        lengths — threaded to each stage via the schedule's microbatch
        index (``mb_index=True``), so encoder self-attention and decoder
        cross-attention mask the right rows on every tick."""
        from apex_tpu.transformer.pipeline_parallel import (
            encoder_decoder, schedules)

        model = self.model
        e_acc, e_down = schedules._main_grad_cast(
            pipe_params["embed"], accum_dtype)
        s_acc, s_down = schedules._main_grad_cast(
            pipe_params["stages"], accum_dtype)
        h_acc, h_down = schedules._main_grad_cast(
            pipe_params["head"], accum_dtype)

        M, b, s_dec = dec_tokens.shape

        def full_loss(p):
            ep = e_down(p["embed"])

            s_enc = enc_tokens.shape[2]
            enc_b = model.enc_bias(ep, s_enc, s_enc)
            dec_b = model.dec_bias(ep, s_dec, s_dec)

            def mb_lens(m):
                if enc_pad_lens is None:
                    return None
                return jax.lax.dynamic_index_in_dim(
                    jnp.asarray(enc_pad_lens, jnp.int32), m, 0,
                    keepdims=False)

            def enc_fn(sp_, h, m):
                lens = mb_lens(m)

                def run_stack(sp2, h2):
                    def body(hh, layer):
                        return self.model._wrapped(
                            model.encoder_block)(layer, hh, enc_b,
                                                 lens), None
                    h2, _ = jax.lax.scan(body, h2, sp2["enc"])
                    return h2

                c_ = model.config
                if c_.remat and c_.remat_policy == "encode_only":
                    # stage-local re-encode-in-backward: this stage's
                    # encoder slice is ONE checkpoint (the pipeline analog
                    # of logits()'s whole-encoder checkpoint; without this
                    # the policy would silently degenerate to remat-off —
                    # review r4)
                    return jax.checkpoint(run_stack)(sp_, h)
                return run_stack(sp_, h)

            def dec_fn(sp_, h, ctx, m):
                lens = mb_lens(m)
                # the encoder output enters the decoder segment through
                # the LATCHED context; the final-encoder LN applies at the
                # seam (each decoder stage normalizes its arriving raw
                # ctx — same value as the serial model's one-time LN)
                ctx = fused_layer_norm(ctx, ep["ln_enc_w"],
                                       ep["ln_enc_b"])

                def body(h, layer):
                    return self.model._wrapped(
                        lambda lp, hh: model.decoder_block(
                            lp, hh, ctx, dec_b, lens)
                    )(layer, h), None
                h, _ = jax.lax.scan(body, h, sp_["dec"])
                return h

            emb_p = {k: ep[k] for k in ("embedding", "pos_embedding")
                     if k in ep}
            enc_emb = jax.vmap(lambda t: model.embed(emb_p, t))(enc_tokens)
            dec_emb = jax.vmap(lambda t: model.embed(emb_p, t))(dec_tokens)
            outs = encoder_decoder.pipeline_spmd_forward_enc_dec(
                lambda pp_, h, m: enc_fn(s_down(pp_), h, m),
                lambda pp_, h, ctx_, m: dec_fn(s_down(pp_), h, ctx_, m),
                p["stages"], enc_emb, dec_emb,
                split_rank=self.split, remat=False,
                broadcast_outputs=False, mb_index=True,
            )
            hp = h_down(p["head"])
            x = outs.reshape(M * b, s_dec, -1)
            x = fused_layer_norm(x, hp["ln_dec_w"], hp["ln_dec_b"])
            logits = x @ ep["embedding"].T
            losses = tp_lib.vocab_parallel_cross_entropy(
                logits, targets.reshape(M * b, s_dec), axis_name=None)
            lm = (None if loss_mask is None
                  else loss_mask.reshape(M * b, s_dec))
            loss = tp_lib.masked_mean(losses, lm)
            return schedules._broadcast_from_first(loss, "pp")

        loss, g = jax.value_and_grad(full_loss)(
            {"embed": e_acc, "stages": s_acc, "head": h_acc})
        psum_pp = lambda t: jax.tree.map(  # noqa: E731
            lambda x: jax.lax.psum(x, "pp"), t)
        g["embed"], g["head"] = psum_pp(g["embed"]), psum_pp(g["head"])
        if dp_axis is not None:
            loss = jax.lax.pmean(loss, dp_axis)
            g = jax.tree.map(lambda x: jax.lax.pmean(x, dp_axis), g)
        return loss, g
