"""T5-class encoder-decoder transformer (seq2seq flagship for the
split-rank pipeline).

The reference ships standalone GPT/BERT test models
(``apex/transformer/testing/standalone_gpt.py``, ``standalone_bert.py``)
and carries encoder-decoder *plumbing* (``ModelType.encoder_and_decoder``,
the pipeline split rank, ``parallel_state.py:147-149``) but no
encoder-decoder model to drive it. This fills that hole TPU-first:

* pre-LN encoder blocks (bidirectional self-attention + MLP);
* pre-LN decoder blocks (causal self-attention → cross-attention over the
  encoder output → MLP);
* learned positions, tied embedding/unembedding shared by both sides,
  vocab-parallel cross entropy on the decoder output;
* attention through :func:`~apex_tpu.ops.attention.flash_attention`
  (``attention_impl='flash'``) or the fused-softmax composition;
* :class:`EncDecPipeline` partitions the stacks over a two-segment
  pipeline — stages ``[0, split)`` hold encoder layers, ``[split, pp)``
  decoder layers — driving
  :func:`~apex_tpu.transformer.pipeline_parallel.pipeline_spmd_forward_enc_dec`
  with the REAL model (the depth standard ``GPTPipeline`` set for GPT).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import fused_layer_norm, scaled_masked_softmax
from apex_tpu.ops.attention import flash_attention
from apex_tpu.transformer import tensor_parallel as tp_lib
from apex_tpu.transformer.tensor_parallel.utils import divide


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    max_seq_len: int = 512
    hidden_size: int = 512
    ffn_hidden_size: Optional[int] = None  # default 4*hidden
    num_encoder_layers: int = 6
    num_decoder_layers: int = 6
    num_heads: int = 8
    dtype: Any = jnp.float32
    attention_impl: str = "softmax"  # softmax | flash
    remat: bool = True

    def __post_init__(self):
        if self.attention_impl not in ("softmax", "flash"):
            raise ValueError(
                f"attention_impl must be softmax|flash, got "
                f"{self.attention_impl!r}")

    @property
    def ffn(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return divide(self.hidden_size, self.num_heads)


def _dense(key, shape, dtype, scale=None):
    fan_in = shape[-1]
    s = scale if scale is not None else (1.0 / fan_in) ** 0.5
    return jax.random.normal(key, shape, dtype) * s


class EncoderDecoderModel:
    """Functional T5-class model. ``init(key)`` → params;
    ``loss_fn(params, enc_tokens, dec_tokens, targets)`` → mean CE of the
    decoder output (teacher forcing: ``dec_tokens`` is the shifted-right
    target stream)."""

    def __init__(self, config: T5Config):
        self.config = config

    # --- params ---------------------------------------------------------------

    def init(self, key):
        c = self.config
        H, F = c.hidden_size, c.ffn

        def enc_layer(k):
            ks = jax.random.split(k, 4)
            return {
                "ln1_w": jnp.ones((H,), c.dtype),
                "ln1_b": jnp.zeros((H,), c.dtype),
                "qkv": _dense(ks[0], (3 * H, H), c.dtype),
                "attn_out": _dense(ks[1], (H, H), c.dtype),
                "ln2_w": jnp.ones((H,), c.dtype),
                "ln2_b": jnp.zeros((H,), c.dtype),
                "mlp_up": _dense(ks[2], (F, H), c.dtype),
                "mlp_down": _dense(ks[3], (H, F), c.dtype),
            }

        def dec_layer(k):
            ks = jax.random.split(k, 7)
            return {
                "ln1_w": jnp.ones((H,), c.dtype),
                "ln1_b": jnp.zeros((H,), c.dtype),
                "qkv": _dense(ks[0], (3 * H, H), c.dtype),
                "attn_out": _dense(ks[1], (H, H), c.dtype),
                "ln_x_w": jnp.ones((H,), c.dtype),
                "ln_x_b": jnp.zeros((H,), c.dtype),
                "xq": _dense(ks[2], (H, H), c.dtype),
                "xkv": _dense(ks[3], (2 * H, H), c.dtype),
                "x_out": _dense(ks[4], (H, H), c.dtype),
                "ln2_w": jnp.ones((H,), c.dtype),
                "ln2_b": jnp.zeros((H,), c.dtype),
                "mlp_up": _dense(ks[5], (F, H), c.dtype),
                "mlp_down": _dense(ks[6], (H, F), c.dtype),
            }

        keys = jax.random.split(key, c.num_encoder_layers
                                + c.num_decoder_layers + 2)
        enc = [enc_layer(keys[i]) for i in range(c.num_encoder_layers)]
        dec = [dec_layer(keys[c.num_encoder_layers + i])
               for i in range(c.num_decoder_layers)]
        return {
            "embedding": _dense(keys[-2], (c.vocab_size, H), c.dtype,
                                scale=1.0),
            "pos_embedding": jax.random.normal(
                keys[-1], (c.max_seq_len, H), c.dtype) * 0.01,
            "encoder": jax.tree.map(lambda *x: jnp.stack(x), *enc),
            "decoder": jax.tree.map(lambda *x: jnp.stack(x), *dec),
            "ln_enc_w": jnp.ones((H,), c.dtype),
            "ln_enc_b": jnp.zeros((H,), c.dtype),
            "ln_dec_w": jnp.ones((H,), c.dtype),
            "ln_dec_b": jnp.zeros((H,), c.dtype),
        }

    # --- attention pieces -----------------------------------------------------

    def _heads(self, x):
        b, s, _ = x.shape
        c = self.config
        return x.reshape(b, s, c.num_heads, c.head_dim).transpose(0, 2, 1, 3)

    def _merge(self, x):
        b, h, s, d = x.shape
        return x.transpose(0, 2, 1, 3).reshape(b, s, h * d)

    def _attn(self, q, k, v, causal):
        c = self.config
        if c.attention_impl == "flash":
            return flash_attention(q, k, v, causal=causal)
        d = q.shape[-1]
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k)
        b, h, sq, sk = scores.shape
        if causal:
            mask = ~jnp.tril(jnp.ones((sq, sk), bool))
            probs = scaled_masked_softmax(
                scores, mask[None, None], 1.0 / float(d) ** 0.5)
        else:
            probs = scaled_masked_softmax(scores, None, 1.0 / float(d) ** 0.5)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, v)

    # --- blocks ---------------------------------------------------------------

    def encoder_block(self, p, x):
        h = fused_layer_norm(x, p["ln1_w"], p["ln1_b"])
        q, k, v = jnp.split(h @ p["qkv"].T, 3, -1)
        a = self._merge(self._attn(self._heads(q), self._heads(k),
                                   self._heads(v), False))
        x = x + a @ p["attn_out"].T
        h = fused_layer_norm(x, p["ln2_w"], p["ln2_b"])
        return x + jax.nn.gelu(h @ p["mlp_up"].T,
                               approximate=True) @ p["mlp_down"].T

    def decoder_block(self, p, x, enc_out):
        h = fused_layer_norm(x, p["ln1_w"], p["ln1_b"])
        q, k, v = jnp.split(h @ p["qkv"].T, 3, -1)
        a = self._merge(self._attn(self._heads(q), self._heads(k),
                                   self._heads(v), True))
        x = x + a @ p["attn_out"].T
        h = fused_layer_norm(x, p["ln_x_w"], p["ln_x_b"])
        q = h @ p["xq"].T
        ck, cv = jnp.split(enc_out @ p["xkv"].T, 2, -1)
        a = self._merge(self._attn(self._heads(q), self._heads(ck),
                                   self._heads(cv), False))
        x = x + a @ p["x_out"].T
        h = fused_layer_norm(x, p["ln2_w"], p["ln2_b"])
        return x + jax.nn.gelu(h @ p["mlp_up"].T,
                               approximate=True) @ p["mlp_down"].T

    def _wrapped(self, fn):
        return jax.checkpoint(fn) if self.config.remat else fn

    # --- forward --------------------------------------------------------------

    def embed(self, params, tokens):
        x = jnp.take(params["embedding"], tokens, axis=0)
        return x + params["pos_embedding"][:tokens.shape[1]]

    def encode(self, params, enc_tokens):
        x = self.embed(params, enc_tokens)
        block = self._wrapped(self.encoder_block)

        def body(x, layer):
            return block(layer, x), None

        x, _ = jax.lax.scan(body, x, params["encoder"])
        return fused_layer_norm(x, params["ln_enc_w"], params["ln_enc_b"])

    def decode(self, params, dec_tokens, enc_out):
        x = self.embed(params, dec_tokens)
        block = self._wrapped(self.decoder_block)

        def body(x, layer):
            return block(layer, x, enc_out), None

        x, _ = jax.lax.scan(body, x, params["decoder"])
        return fused_layer_norm(x, params["ln_dec_w"], params["ln_dec_b"])

    def logits(self, params, enc_tokens, dec_tokens):
        enc_out = self.encode(params, enc_tokens)
        x = self.decode(params, dec_tokens, enc_out)
        return x @ params["embedding"].T  # tied unembedding

    def loss_fn(self, params, enc_tokens, dec_tokens, targets,
                loss_mask=None):
        logits = self.logits(params, enc_tokens, dec_tokens)
        losses = tp_lib.vocab_parallel_cross_entropy(
            logits, targets, axis_name=None)
        return tp_lib.masked_mean(losses, loss_mask)


@dataclasses.dataclass
class EncDecPipeline:
    """Two-segment pipeline execution of :class:`EncoderDecoderModel`:
    stages ``[0, split)`` hold encoder-layer slices, ``[split, pp)``
    decoder-layer slices. Stage params carry the UNION structure (each
    stage stores both segments' leaves; the unused one is dead weight —
    program uniformity, cf. ``pipeline_parallel/encoder_decoder.py``).

    ``partition(params)`` → ``{embed, stages, head}`` with stage leaves
    leading ``(pp, ...)``; ``loss_and_grads`` runs inside shard_map with
    the pp axis bound and returns the same loss as ``loss_fn`` on the
    concatenated microbatches."""

    model: EncoderDecoderModel
    pp: int
    split: int

    def __post_init__(self):
        c = self.model.config
        if not (0 < self.split < self.pp):
            raise ValueError(
                f"split ({self.split}) must lie strictly inside the "
                f"{self.pp}-stage pipeline")
        if c.num_encoder_layers % self.split:
            raise ValueError(
                f"num_encoder_layers ({c.num_encoder_layers}) must divide "
                f"over {self.split} encoder stages")
        if c.num_decoder_layers % (self.pp - self.split):
            raise ValueError(
                f"num_decoder_layers ({c.num_decoder_layers}) must divide "
                f"over {self.pp - self.split} decoder stages")

    @property
    def enc_per_stage(self):
        return self.model.config.num_encoder_layers // self.split

    @property
    def dec_per_stage(self):
        return self.model.config.num_decoder_layers // (self.pp - self.split)

    def partition(self, params):
        ne, nd = self.enc_per_stage, self.dec_per_stage
        n_dec_stages = self.pp - self.split

        def split_enc(x):  # (L_e, ...) -> (pp, ne, ...): pad decoder
            y = x.reshape(self.split, ne, *x.shape[1:])
            pad = jnp.zeros((n_dec_stages, ne) + x.shape[1:], x.dtype)
            return jnp.concatenate([y, pad], 0)

        def split_dec(x):  # (L_d, ...) -> (pp, nd, ...): pad encoder
            y = x.reshape(n_dec_stages, nd, *x.shape[1:])
            pad = jnp.zeros((self.split, nd) + x.shape[1:], x.dtype)
            return jnp.concatenate([pad, y], 0)

        return {
            "embed": {"embedding": params["embedding"],
                      "pos_embedding": params["pos_embedding"],
                      "ln_enc_w": params["ln_enc_w"],
                      "ln_enc_b": params["ln_enc_b"]},
            "stages": {
                "enc": jax.tree.map(split_enc, params["encoder"]),
                "dec": jax.tree.map(split_dec, params["decoder"]),
            },
            "head": {"ln_dec_w": params["ln_dec_w"],
                     "ln_dec_b": params["ln_dec_b"]},
        }

    def param_specs(self, pipe_params):
        from jax.sharding import PartitionSpec as P
        return {
            "embed": jax.tree.map(lambda _: P(), pipe_params["embed"]),
            "stages": jax.tree.map(lambda _: P("pp"),
                                   pipe_params["stages"]),
            "head": jax.tree.map(lambda _: P(), pipe_params["head"]),
        }

    def loss_and_grads(self, pipe_params, enc_tokens, dec_tokens, targets,
                       *, loss_mask=None, accum_dtype=jnp.float32,
                       dp_axis=None):
        """(M, b, s) microbatched token triples → (loss, grads). Must run
        inside shard_map with the pp axis bound; stage leaves are this
        device's local (n_layers, ...) slices."""
        from apex_tpu.transformer.pipeline_parallel import (
            encoder_decoder, schedules)

        model = self.model
        e_acc, e_down = schedules._main_grad_cast(
            pipe_params["embed"], accum_dtype)
        s_acc, s_down = schedules._main_grad_cast(
            pipe_params["stages"], accum_dtype)
        h_acc, h_down = schedules._main_grad_cast(
            pipe_params["head"], accum_dtype)

        M, b, s_dec = dec_tokens.shape

        def full_loss(p):
            ep = e_down(p["embed"])

            def enc_fn(sp_, h):
                def body(h, layer):
                    return self.model._wrapped(
                        model.encoder_block)(layer, h), None
                h, _ = jax.lax.scan(body, h, sp_["enc"])
                return h

            def dec_fn(sp_, h, ctx):
                # the encoder output enters the decoder segment through
                # the LATCHED context; the final-encoder LN applies at the
                # seam (each decoder stage normalizes its arriving raw
                # ctx — same value as the serial model's one-time LN)
                ctx = fused_layer_norm(ctx, ep["ln_enc_w"],
                                       ep["ln_enc_b"])

                def body(h, layer):
                    return self.model._wrapped(
                        lambda pl, hh: model.decoder_block(pl, hh, ctx)
                    )(layer, h), None
                h, _ = jax.lax.scan(body, h, sp_["dec"])
                return h

            emb_p = {"embedding": ep["embedding"],
                     "pos_embedding": ep["pos_embedding"]}
            enc_emb = jax.vmap(lambda t: model.embed(emb_p, t))(enc_tokens)
            dec_emb = jax.vmap(lambda t: model.embed(emb_p, t))(dec_tokens)
            outs = encoder_decoder.pipeline_spmd_forward_enc_dec(
                lambda pp_, h: enc_fn(s_down(pp_), h),
                lambda pp_, h, ctx_: dec_fn(s_down(pp_), h, ctx_),
                p["stages"], enc_emb, dec_emb,
                split_rank=self.split, remat=False,
                broadcast_outputs=False,
            )
            hp = h_down(p["head"])
            x = outs.reshape(M * b, s_dec, -1)
            x = fused_layer_norm(x, hp["ln_dec_w"], hp["ln_dec_b"])
            logits = x @ ep["embedding"].T
            losses = tp_lib.vocab_parallel_cross_entropy(
                logits, targets.reshape(M * b, s_dec), axis_name=None)
            lm = (None if loss_mask is None
                  else loss_mask.reshape(M * b, s_dec))
            loss = tp_lib.masked_mean(losses, lm)
            return schedules._broadcast_from_first(loss, "pp")

        loss, g = jax.value_and_grad(full_loss)(
            {"embed": e_acc, "stages": s_acc, "head": h_acc})
        psum_pp = lambda t: jax.tree.map(  # noqa: E731
            lambda x: jax.lax.psum(x, "pp"), t)
        g["embed"], g["head"] = psum_pp(g["embed"]), psum_pp(g["head"])
        if dp_axis is not None:
            loss = jax.lax.pmean(loss, dp_axis)
            g = jax.tree.map(lambda x: jax.lax.pmean(x, dp_axis), g)
        return loss, g
