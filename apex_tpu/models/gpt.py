"""GPT: the flagship model — a Megatron-style decoder-only transformer.

Re-design of ``apex/transformer/testing/standalone_gpt.py`` (``ParallelMLP``
:236, ``ParallelAttention`` :285, full GPT stack): vocab-parallel embedding,
N pre-LN blocks of (fused LN → TP attention → residual → fused LN → TP MLP →
residual), final LN, tied unembedding, vocab-parallel cross-entropy.

TPU-first choices:
* activations are (batch, seq, hidden) bf16-able; attention uses the fused
  causal softmax kernel (no 2048 seq cap);
* TP via Column/Row parallel linears (QKV column-sharded by head, output
  row-sharded), runnable at tp_size=1 with zero collectives;
* sequence parallelism optional on the linears (``sequence_parallel``);
* activation remat per block via ``jax.checkpoint`` (``remat=True``);
* dropout keys are explicit (``jax.random``), folded per (layer, op, tp rank).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import fused_layer_norm, scaled_upper_triang_masked_softmax
from apex_tpu.ops.attention import flash_attention, seed_from_key
from apex_tpu.transformer import tensor_parallel as tp_lib
from apex_tpu.transformer.tensor_parallel.utils import divide


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304
    max_seq_len: int = 2048
    hidden_size: int = 1024
    ffn_hidden_size: Optional[int] = None  # default 4*hidden
    num_layers: int = 12
    num_heads: int = 16
    # grouped-query attention: fewer kv heads than query heads (None =
    # num_heads, full MHA; 1 = MQA). Beyond the reference — its fmha
    # kernels require equal head counts. Must divide num_heads and be
    # divisible by tp_size.
    num_kv_heads: Optional[int] = None
    tp_size: int = 1
    tp_axis: Optional[str] = "tp"  # None → single-chip, no collectives
    sequence_parallel: bool = False
    # Ring-overlapped TP boundary collectives (ops.collective_matmul): the
    # Column/Row linears and the flash attention projections trade their
    # blocking all-gather/reduce-scatter/psum for compute-overlapped
    # ppermute rings (with SP: ag→matmul and matmul→reduce-scatter;
    # without: overlapped backward psum / matmul→all-reduce). Blocking
    # (False) stays the parity oracle. Requires tp_size >= 2 and the
    # flash attention path; composing with cp is future work.
    tp_overlap: bool = False
    # Pipeline schedule family, consumed by GPTPipeline (pp >= 2):
    # "1f1b" — scanned forward + autodiff backward (interleaved when the
    # pipeline runs virtual chunks); "zb" — zero-bubble split backward
    # (dX on the critical path, dW deferred into a real-items-only sweep;
    # schedules.py has the cost model). overlap_p2p restructures every
    # pipeline tick so the stage-boundary ppermute hop is issued before
    # the stage body it no longer feeds (the PR-5 collective-matmul trick
    # at the pp boundary; with virtual chunks the microbatch count must
    # then divide 2*pp).
    pp_schedule: str = "1f1b"
    overlap_p2p: bool = False
    dropout: float = 0.0
    remat: bool = True
    # "full": recompute the whole block in backward (Megatron
    # CheckpointFunction semantics, minimum memory); "save_attn"/
    # "save_attn_mlp": full-block remat that stores the attention output
    # (/+ mlp hidden) so the re-forward skips those matmuls — NOTE attention
    # *backward* still needs q/k/v, so the qkv projection and flash forward
    # are recomputed regardless and the win is small; "mlp_only": leave the
    # attention half un-rematted (its residuals stay live, ~+2G at
    # GPT-medium/seq1024/b16) and recompute only the MLP half — skips the
    # whole attention re-forward, the measured-fastest policy that still
    # bounds the big (4H) mlp activations.
    remat_policy: str = "full"
    # scan vs unrolled layer loop: scan compiles O(1) in depth (the
    # reference-style module list is inherently "unrolled"); unrolling
    # removes the scan carry's copy/dynamic-slice overhead at the price of
    # depth-proportional compile time — measured on the flagship bench
    # before choosing the default
    scan_layers: bool = True
    dtype: Any = jnp.float32  # param dtype; compute follows inputs/policy
    # "softmax": materialized scores + fused causal softmax (the Megatron
    # path, ``standalone_gpt.py``'s ParallelAttention); "flash": blockwise
    # flash attention — O(s) memory, no seq cap, preferred at long seq;
    # "naive": plain jnp softmax with autodiff-saved probabilities — the
    # stock-JAX reference point benchmarks compare against, never preferred.
    attention_impl: str = "softmax"
    # Mixture-of-experts in the MLP slot (None = dense). The expert FFN
    # width is ``ffn``; experts shard over ``ep_axis`` when run inside
    # shard_map (apex_tpu.parallel.mesh's dedicated ep axis). The router's
    # aux losses enter loss_fn with the coefficients below; aux stats
    # (incl. drop_fraction) surface via loss_fn(..., return_aux=True).
    moe_num_experts: Optional[int] = None
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coeff: float = 1e-2
    moe_z_coeff: float = 1e-3
    ep_axis: Optional[str] = None
    # Context parallelism: activations (and tokens) are sharded along the
    # SEQUENCE over this mesh axis; attention runs distributed — "ring"
    # (zigzag-sharded ring attention: shard `zigzag_shard(tokens, cp)`
    # over the axis; O(s_local) memory, kv rotates the ring) or "ulysses"
    # (contiguous sharding, two all_to_alls re-shard heads; needs
    # heads % cp == 0). Everything else in the block is position-wise, so
    # the model runs unchanged on the shard; position embeddings follow
    # the layout (zigzag stripes / contiguous) automatically. Composes
    # with tp (+SP), pp, and dp in one mesh.
    cp_axis: Optional[str] = None
    cp_impl: str = "ring"
    # The unified parallelism object (ISSUE 12): pass a ParallelPlan and
    # the loose knobs above (tp_size, sequence_parallel, tp_overlap,
    # pp_schedule, overlap_p2p, cp_axis/ep_axis) are DERIVED from it —
    # one validated source of truth shared with make_mesh and
    # build_schedule. Left None, the loose kwargs construct a shim plan
    # (the deprecated path — no caller breaks), and the parallel
    # cross-field validation below routes through ParallelPlan.validate
    # either way. Model-coupled constraints (flash attention for
    # tp_overlap/cp, head divisibility) stay here: the plan cannot know
    # them.
    plan: Optional[Any] = None

    def __post_init__(self):
        from apex_tpu.plan.parallel_plan import ParallelPlan

        if self.plan is not None:
            p = self.plan
            if not isinstance(p, ParallelPlan):
                p = ParallelPlan.from_json(p)
                object.__setattr__(self, "plan", p)
            # the plan is the single source of truth: a loose parallel
            # kwarg explicitly set to something the plan contradicts is
            # an eager named-knob error, never a silent override
            derived = {"tp_size": p.tp,
                       "sequence_parallel": p.sequence_parallel,
                       "tp_overlap": p.tp_overlap,
                       "pp_schedule": p.pp_schedule,
                       "overlap_p2p": p.overlap_p2p}
            defaults = {"tp_size": 1, "sequence_parallel": False,
                        "tp_overlap": False, "pp_schedule": "1f1b",
                        "overlap_p2p": False}
            for name, want in derived.items():
                got = getattr(self, name)
                if got != defaults[name] and got != want:
                    raise ValueError(
                        f"{name}={got!r} contradicts plan="
                        f"{p.describe()} (which implies {name}="
                        f"{want!r}); pass the knob through the plan, "
                        f"not alongside it")
                object.__setattr__(self, name, want)
            if p.cp > 1 and self.cp_axis is None:
                object.__setattr__(self, "cp_axis", "cp")
            if p.ep > 1 and self.ep_axis is None:
                object.__setattr__(self, "ep_axis", "ep")
        else:
            # the deprecated loose-kwarg shim: every construction owns a
            # plan, and the plan's validator is the one that rejects
            # illegal parallel combos (PlanError is a ValueError)
            object.__setattr__(self, "plan", ParallelPlan.from_model_kwargs(
                tp_size=self.tp_size,
                sequence_parallel=self.sequence_parallel,
                tp_overlap=self.tp_overlap,
                pp_schedule=self.pp_schedule,
                overlap_p2p=self.overlap_p2p))
        if self.moe_num_experts is not None:
            if self.moe_num_experts < 2:
                raise ValueError("moe_num_experts must be >= 2 (None = dense)")
            if self.ffn % self.tp_size:
                raise ValueError(
                    f"MoE with tensor parallelism shards each expert's ffn "
                    f"dim: ffn ({self.ffn}) must be divisible by tp_size "
                    f"({self.tp_size})")
        if self.attention_impl not in ("softmax", "flash", "naive"):
            raise ValueError(
                f"attention_impl must be softmax|flash|naive, got "
                f"{self.attention_impl!r}")
        # pp_schedule legality (and tp_overlap's tp_size >= 2) now live
        # in ParallelPlan.validate — routed through the plan above
        if self.remat_policy not in (
                "full", "save_attn", "save_attn_mlp", "mlp_only"):
            raise ValueError(
                f"remat_policy must be full|save_attn|save_attn_mlp|mlp_only, "
                f"got {self.remat_policy!r}")
        if self.cp_axis is not None:
            if self.cp_impl not in ("ring", "ulysses"):
                raise ValueError(
                    f"cp_impl must be ring|ulysses, got {self.cp_impl!r}")
            if self.attention_impl != "flash":
                raise ValueError(
                    "context parallelism distributes the flash kernel "
                    "family; set attention_impl='flash'")
        if self.tp_overlap:
            if self.tp_axis is None:
                raise ValueError(
                    "tp_overlap needs a bound tp axis; tp_axis=None runs "
                    "the linears without collectives, so the flag would "
                    "silently measure the blocking path — unset "
                    "tp_overlap or name the mesh axis")
            if self.attention_impl != "flash":
                raise ValueError(
                    "tp_overlap rides the flash attention path (the packed "
                    "QKV projection the ring feeds); set "
                    "attention_impl='flash'")
            if self.cp_axis is not None:
                raise ValueError(
                    "tp_overlap does not yet compose with context "
                    "parallelism (the cp attention branch re-shards the "
                    "sequence the rings chunk); run cp with the blocking "
                    "boundary collectives")
        if self.num_kv_heads is not None:
            if self.num_kv_heads < 1:
                raise ValueError(
                    f"num_kv_heads must be >= 1, got {self.num_kv_heads}")
            if self.num_heads % self.num_kv_heads:
                raise ValueError(
                    f"num_kv_heads ({self.num_kv_heads}) must divide "
                    f"num_heads ({self.num_heads})")
            if self.num_kv_heads % self.tp_size:
                raise ValueError(
                    f"num_kv_heads ({self.num_kv_heads}) must be divisible "
                    f"by tp_size ({self.tp_size})")

    @property
    def ffn(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return divide(self.hidden_size, self.num_heads)

    @property
    def local_heads(self) -> int:
        return divide(self.num_heads, self.tp_size)

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads if self.num_kv_heads is not None else self.num_heads

    @property
    def local_kv_heads(self) -> int:
        return divide(self.kv_heads, self.tp_size)

    @property
    def qkv_features(self) -> int:
        """Global QKV projection width: h_q + 2*h_kv head groups."""
        return (self.num_heads + 2 * self.kv_heads) * self.head_dim


class GPTModel:
    """Functional GPT. ``init(key)`` → params pytree (per-TP-shard when
    tp_size > 1 — build under ``shard_map`` or shard a replicated init);
    ``loss_fn(params, tokens, targets, key)`` → mean LM loss."""

    def __init__(self, config: GPTConfig):
        c = self.config = config
        axis = c.tp_axis if c.tp_size > 1 else None
        self.axis = axis
        sp = c.sequence_parallel and c.tp_size > 1
        self.sp = sp
        self.moe = c.moe_num_experts is not None
        if self.moe:
            from apex_tpu.transformer.moe import MoEMLP
            self.moe_bank = MoEMLP(c.moe_num_experts, c.hidden_size, c.ffn,
                                   tp_size=c.tp_size)
        self.embedding = tp_lib.VocabParallelEmbedding(
            c.vocab_size, c.hidden_size, tp_size=c.tp_size, axis_name=axis
        )
        # activations are (batch, seq, hidden) → seq_dim=1 for the SP
        # all-gather/reduce-scatter boundaries
        overlap = c.tp_overlap and axis is not None
        self.overlap = overlap
        self.qkv = tp_lib.ColumnParallelLinear(
            c.hidden_size, c.qkv_features, tp_size=c.tp_size, axis_name=axis,
            sequence_parallel=sp, seq_dim=1, overlap_comm=overlap,
        )
        self.attn_out = tp_lib.RowParallelLinear(
            c.hidden_size, c.hidden_size, tp_size=c.tp_size, axis_name=axis,
            sequence_parallel=sp, seq_dim=1, overlap_comm=overlap,
        )
        self.mlp_up = tp_lib.ColumnParallelLinear(
            c.hidden_size, c.ffn, tp_size=c.tp_size, axis_name=axis,
            sequence_parallel=sp, seq_dim=1, overlap_comm=overlap,
        )
        self.mlp_down = tp_lib.RowParallelLinear(
            c.ffn, c.hidden_size, tp_size=c.tp_size, axis_name=axis,
            sequence_parallel=sp, seq_dim=1, overlap_comm=overlap,
        )

    # --- params ---------------------------------------------------------------

    def init(self, key, rank: int = 0):
        c = self.config
        keys = jax.random.split(key, c.num_layers + 2)
        layers = []
        for i in range(c.num_layers):
            k = jax.random.split(keys[i], 4)
            layer = {
                "ln1_w": jnp.ones((c.hidden_size,), c.dtype),
                "ln1_b": jnp.zeros((c.hidden_size,), c.dtype),
                "qkv": self.qkv.init(k[0], rank, c.dtype),
                "attn_out": self.attn_out.init(k[1], rank, c.dtype),
                "ln2_w": jnp.ones((c.hidden_size,), c.dtype),
                "ln2_b": jnp.zeros((c.hidden_size,), c.dtype),
            }
            if self.moe:
                # the FULL expert bank (this tp rank's ffn shard under tp);
                # under expert parallelism shard the leading expert axis of
                # w1/b1/w2/b2 over ep (router replicated) — cf.
                # shard_params_for_tp's pattern
                layer["moe"] = self.moe_bank.init(k[2], rank, c.dtype)
            else:
                layer["mlp_up"] = self.mlp_up.init(k[2], rank, c.dtype)
                layer["mlp_down"] = self.mlp_down.init(k[3], rank, c.dtype)
            layers.append(layer)
        params = {
            "embedding": self.embedding.init(keys[-2], rank, c.dtype),
            "pos_embedding": jax.random.normal(
                keys[-1], (c.max_seq_len, c.hidden_size), c.dtype
            ) * 0.01,
            "layers": jax.tree.map(lambda *xs: jnp.stack(xs), *layers),
            "lnf_w": jnp.ones((c.hidden_size,), c.dtype),
            "lnf_b": jnp.zeros((c.hidden_size,), c.dtype),
        }
        return params

    # --- blocks ---------------------------------------------------------------

    def _cp_positions(self, s_loc):
        """Global position ids of this cp rank's sequence shard: the zigzag
        stripe pair for ring (device r holds stripes (r, 2cp−1−r) of 2·cp —
        `ops.attention.zigzag_indices`), contiguous for ulysses."""
        c = self.config
        cp = jax.lax.axis_size(c.cp_axis)
        if cp * s_loc > c.max_seq_len:
            # out-of-range ids would silently CLAMP in the pos_embedding
            # gather (JAX gather default) — half the sequence training on
            # repeated positions with no error; fail at trace time instead
            # (the dense path fails loudly via the [:s] shape mismatch)
            raise ValueError(
                f"global sequence cp*s_local = {cp}*{s_loc} exceeds "
                f"max_seq_len ({c.max_seq_len}); raise max_seq_len")
        rank = jax.lax.axis_index(c.cp_axis)
        if c.cp_impl == "ring":
            st = s_loc // 2
            return jnp.concatenate([
                rank * st + jnp.arange(st),
                (2 * cp - 1 - rank) * st + jnp.arange(st)])
        return rank * s_loc + jnp.arange(s_loc)

    def _attention(self, p, x, key):
        c = self.config
        h, d = c.local_heads, c.head_dim
        hkv = c.local_kv_heads
        use_flash = c.attention_impl == "flash"
        drop = c.dropout if (c.dropout > 0 and key is not None) else 0.0
        seed = None
        if drop > 0 and use_flash:
            # in-kernel probs dropout seed: per (layer, op-slot 0) from the
            # caller's folded key, plus the tp rank — each rank's heads
            # draw decorrelated masks (Megatron's model-parallel RNG
            # stream for attention dropout, tensor_parallel/random.py)
            k0 = jax.random.fold_in(key, 0)
            if self.axis is not None:
                k0 = jax.random.fold_in(k0, jax.lax.axis_index(self.axis))
            seed = seed_from_key(k0)
        if use_flash and self.overlap:
            return self._attention_tp_overlap(p, x, drop, seed)
        if use_flash:
            xg = self.qkv.gather_input(x)             # (b, s, H) full seq
            s_len = xg.shape[1]
            from apex_tpu.amp.lists import apply_op_rules
            from apex_tpu.ops import _backend
            from apex_tpu.ops.attention import (bshd_kernel_ok,
                                                flash_auto_crossover,
                                                fused_qkv_attention)
            # the O1 per-op cast applies before the kernel-eligibility
            # gate — an fp16-casting policy must land on the XLA path
            # (Mosaic has no f16), so the gate sees the POST-cast dtype
            xc, w_qkv, b_qkv, w_out = apply_op_rules(
                "attention", xg, p["qkv"]["weight"],
                p["qkv"].get("bias"), p["attn_out"]["weight"])
            fused_ok = (
                c.cp_axis is None  # cp: attention is distributed below
                and "bias" in p["qkv"]
                and bshd_kernel_ok(s_len, s_len, h, d, xc.dtype)
                and (s_len >= flash_auto_crossover(d)
                     or _backend.interpret_forced())
                and _backend.choose_impl("auto", True) == "pallas"
            )
            if fused_ok:
                # The zero-layout-copy path: packed QKV GEMM → flash
                # kernels reading head windows straight from the packed
                # buffer → output GEMM, all plain 2D contractions with a
                # hand-written VJP (see ops.attention.fused_qkv_attention
                # — kills the ~4.5 GB/step of XLA layout-conversion copies
                # the composed formulation paid, PERF.md r3).
                y = fused_qkv_attention(
                    xc, w_qkv, b_qkv, w_out, None, seed, None, h, hkv, d,
                    1.0 / float(d) ** 0.5, True, drop)
                y = self.attn_out.reduce_output(y)
                if "bias" in p["attn_out"]:
                    y = y + p["attn_out"]["bias"]
                return y
            if (c.cp_axis is None
                    and not bshd_kernel_ok(s_len, s_len, h, d, xc.dtype)
                    and d == 64 and s_len % 128 == 0
                    and xc.dtype != jnp.float16
                    and (s_len >= flash_auto_crossover(d)
                         or _backend.interpret_forced())
                    and _backend.choose_impl("auto", True) == "pallas"):
                # d=64 multi-head can't ride the folded bshd layout (its
                # 64-wide blocks break the 128-lane tile rule) but the
                # bh-flat kernel handles d=64 fine — keep the pre-r3
                # head-batched route so those configs don't silently lose
                # the kernel (the layout copies it pays are the r2 cost
                # model; head_dim 128 is the recommended config anyway)
                qkv4 = self.qkv.headwise(p["qkv"], x, h + 2 * hkv)
                q4 = qkv4[:, :h]
                k4 = qkv4[:, h:h + hkv]
                v4 = qkv4[:, h + hkv:]
                ctx4 = flash_attention(q4, k4, v4, causal=True,
                                       dropout_rate=drop,
                                       dropout_seed=seed)
                return self.attn_out.headwise(p["attn_out"], ctx4)
            # Below the kernel crossover (or bias-less layers): seq-major
            # (bshd) einsums + the flash entry's XLA/Pallas dispatch. The
            # (b, s, h, d) layout is the GEMM's natural output, so this
            # path too avoids the old head-batched formulation's copies.
            from apex_tpu.ops.attention import (bshd_output_projection,
                                                bshd_qkv_projection)
            q, k, v = bshd_qkv_projection(
                xg, p["qkv"]["weight"], p["qkv"].get("bias"), h, hkv, d)
            if c.cp_axis is not None:
                # context parallelism: q/k/v cover this device's sequence
                # shard; attention distributes over the cp axis — ring (kv
                # shards rotate, zigzag-balanced causal) or Ulysses (two
                # all_to_alls trade seq for head sharding). The op-rules
                # cast that flash_attention applies internally is applied
                # here instead (ring/ulysses take q/k/v directly).
                from apex_tpu.ops.attention import (ring_attention,
                                                    ulysses_attention)
                q, k, v = apply_op_rules("attention", q, k, v)
                if c.cp_impl == "ulysses":
                    ctx = ulysses_attention(q, k, v, axis_name=c.cp_axis,
                                            causal=True,
                                            dropout_rate=drop,
                                            dropout_seed=seed)
                elif bshd_kernel_ok(q.shape[1] // 2, q.shape[1] // 2, h,
                                    d, q.dtype):
                    # ring rides the seq-major kernels directly (r4 late):
                    # the stripe pieces read the projection GEMMs' layout
                    # with zero transposes per ring step
                    ctx = ring_attention(q, k, v, axis_name=c.cp_axis,
                                         causal=True, layout="bshd",
                                         dropout_rate=drop,
                                         dropout_seed=seed)
                else:
                    # bh-flat fallback (d=64-class shapes the folded bshd
                    # tiling can't express): transpose round trip per layer
                    b_sz, s_loc = q.shape[0], q.shape[1]
                    to_bh = lambda z: z.transpose(0, 2, 1, 3).reshape(  # noqa: E731
                        b_sz * z.shape[2], s_loc, d)
                    of = ring_attention(to_bh(q), to_bh(k), to_bh(v),
                                        axis_name=c.cp_axis, causal=True,
                                        dropout_rate=drop,
                                        dropout_seed=seed)
                    ctx = of.reshape(b_sz, h, s_loc, d).transpose(0, 2, 1, 3)
            else:
                ctx = flash_attention(q, k, v, causal=True, layout="bshd",
                                      dropout_rate=drop, dropout_seed=seed)
            y = bshd_output_projection(ctx, p["attn_out"]["weight"], h, d)
            y = self.attn_out.reduce_output(y)
            if "bias" in p["attn_out"]:
                y = y + p["attn_out"]["bias"]
            return y

        # Head-batched QKV projection (ColumnParallelLinear.headwise):
        # q/k/v come out (b, h, s, d) straight from the MXU (the
        # materialized-scores paths below want that layout anyway).
        # Local output features stay packed (q-heads | k-heads | v-heads) —
        # grouped, heads within each group (Megatron packs (h, 3d) because
        # its *global* qkv weight must shard per-head across tp ranks; here
        # params are built per-rank, so the grouped order is free). With
        # grouped-query attention (num_kv_heads < num_heads) the k/v groups
        # are simply narrower.
        qkv = self.qkv.headwise(p["qkv"], x, h + 2 * hkv)  # (b, h+2hkv, s, d)
        b, s = qkv.shape[0], qkv.shape[2]
        # (b, h, s, d) / (b, hkv, s, d)
        q = qkv[:, :h]
        k = qkv[:, h:h + hkv]
        v = qkv[:, h + hkv:]
        if hkv < h:
            # the materialized-scores paths below broadcast kv heads
            k = jnp.repeat(k, h // hkv, axis=1)
            v = jnp.repeat(v, h // hkv, axis=1)
        if c.attention_impl == "naive":
            # stock-JAX formulation: materialized scores, jnp softmax, probs
            # saved by autodiff for backward — no framework ops
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / float(d) ** 0.5
            mask = jnp.tril(jnp.ones((s, s), bool))
            scores = jnp.where(mask, scores.astype(jnp.float32), -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
            if c.dropout > 0 and key is not None:
                probs = _dropout(probs, c.dropout, jax.random.fold_in(key, 0))
            ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        else:
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k)
            probs = scaled_upper_triang_masked_softmax(
                scores.reshape(b * h, s, s), 1.0 / float(d) ** 0.5
            ).reshape(b, h, s, s)
            if c.dropout > 0 and key is not None:
                probs = _dropout(probs, c.dropout, jax.random.fold_in(key, 0))
            ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        # Output projection contracted directly over (heads, d) — no
        # transpose back to (b, s, h*d) (RowParallelLinear.headwise).
        return self.attn_out.headwise(p["attn_out"], ctx)

    def _attention_tp_overlap(self, p, x, drop, seed):
        """The flash attention block with the TP boundary collectives fused
        into ring collective matmuls (``ops.collective_matmul``): the
        packed QKV projection rides the ag→matmul ring (SP) or the plain
        local GEMM with an overlapped-psum backward (copy_matmul), and the
        output projection the matmul→reduce-scatter / matmul→all-reduce
        ring — no blocking all-gather of the activation anywhere in the
        block, forward or backward. The weight packing is the same
        (q-heads | k-heads | v-heads) feature order every other path uses,
        so ``shard_params_for_tp`` shards are shared with the blocking
        oracle."""
        c = self.config
        h, hkv, d = c.local_heads, c.local_kv_heads, c.head_dim
        from apex_tpu.amp.lists import apply_op_rules
        from apex_tpu.ops import collective_matmul as cm
        xc, w_qkv, b_qkv, w_out = apply_op_rules(
            "attention", x, p["qkv"]["weight"], p["qkv"].get("bias"),
            p["attn_out"]["weight"])
        proj = cm.all_gather_matmul if self.sp else cm.copy_matmul
        y = proj(xc, w_qkv, axis_name=self.axis, seq_dim=1)
        if b_qkv is not None:
            y = y + b_qkv
        b_sz, s_len = y.shape[0], y.shape[1]
        q = y[..., :h * d].reshape(b_sz, s_len, h, d)
        k = y[..., h * d:(h + hkv) * d].reshape(b_sz, s_len, hkv, d)
        v = y[..., (h + hkv) * d:].reshape(b_sz, s_len, hkv, d)
        ctx = flash_attention(q, k, v, causal=True, layout="bshd",
                              dropout_rate=drop, dropout_seed=seed)
        epi = cm.matmul_reduce_scatter if self.sp else cm.matmul_all_reduce
        out = epi(ctx.reshape(b_sz, s_len, h * d), w_out,
                  axis_name=self.axis, seq_dim=1)
        if "bias" in p["attn_out"]:
            out = out + p["attn_out"]["bias"]
        return out

    def _mlp(self, p, x):
        if self.moe:
            from apex_tpu.transformer.moe import moe_layer
            c = self.config
            if self.sp:
                # Megatron-SP boundary: the residual stream is seq-sharded
                # over tp; routing needs every rank to see identical full
                # sequences (the expert ffn shards split the SAME tokens'
                # GEMMs), so gather on entry and re-scatter on exit — the
                # same all-gather/reduce-scatter placement the dense MLP's
                # Col/Row linears use, hoisted around the whole MoE block.
                x = self._sp_gather(x)
            y, aux = moe_layer(
                p["moe"], x, k=c.moe_top_k,
                capacity_factor=c.moe_capacity_factor,
                axis_name=c.ep_axis, tp_axis=self.axis, priority="gate")
            if self.sp:
                y = self._sp_scatter(y)
            return y, aux
        h = self.mlp_up(p["mlp_up"], x)
        h = jax.nn.gelu(h, approximate=True)
        if self.config.remat and self.config.remat_policy == "save_attn_mlp":
            from jax.ad_checkpoint import checkpoint_name

            h = checkpoint_name(h, "mlp_h")
        return self.mlp_down(p["mlp_down"], h)

    def _sp_scatter(self, x):
        """Enter the SP region: this tp rank's seq slice. Backward
        all-gathers the cotangent so upstream (embedding, pos) parameters
        see every position's contribution (Megatron's
        ``_ScatterToSequenceParallelRegion``)."""
        return _sp_scatter_seq1(x, self.axis)

    def _sp_gather(self, x):
        """Leave the SP region: full sequence. Backward takes this rank's
        slice of the (replicated) cotangent — the plain all_gather transpose
        (psum_scatter) would multiply by tp_size."""
        return _sp_gather_seq1(x, self.axis)

    def sp_grad_sync(self, grads):
        """All-reduce over tp the gradients of parameters applied to
        seq-sharded activations (block LNs and row-linear biases) — each tp
        rank only saw its sequence slice's contribution. The analog of
        Megatron's sequence-parallel param-grad all-reduce hook. No-op when
        SP is off."""
        if not self.sp:
            return grads
        lay = dict(grads["layers"])
        for name in ("ln1_w", "ln1_b", "ln2_w", "ln2_b"):
            lay[name] = jax.lax.psum(lay[name], self.axis)
        # moe layers have no mlp_down; their expert-bank grads come from
        # FULL (gathered) sequences so need no tp sync (see _mlp)
        for mod in ("attn_out", "mlp_down"):
            if mod not in lay:
                continue
            m = dict(lay[mod])
            if "bias" in m:
                m["bias"] = jax.lax.psum(m["bias"], self.axis)
            lay[mod] = m
        out = dict(grads)
        out["layers"] = lay
        return out

    def _block(self, p, x, key):
        """Residual block. Dense: → new x. MoE: → (new x, router aux)."""
        c = self.config
        a = self._attention(p, fused_layer_norm(x, p["ln1_w"], p["ln1_b"]), key)
        if c.remat and c.remat_policy in ("save_attn", "save_attn_mlp"):
            from jax.ad_checkpoint import checkpoint_name

            a = checkpoint_name(a, "attn_out")
        if c.dropout > 0 and key is not None:
            a = _dropout(a, c.dropout, jax.random.fold_in(key, 1))
        x = x + a

        def mlp_half(p_, x_):
            return self._mlp(p_, fused_layer_norm(x_, p_["ln2_w"], p_["ln2_b"]))

        if c.remat and c.remat_policy == "mlp_only":
            mlp_half = jax.checkpoint(mlp_half)
        m = mlp_half(p, x)
        aux = None
        if self.moe:
            m, aux = m
        if c.dropout > 0 and key is not None:
            m = _dropout(m, c.dropout, jax.random.fold_in(key, 2))
        x = x + m
        return (x, aux) if self.moe else x

    def wrapped_block(self):
        """The transformer block with the config's remat policy applied —
        the unit both :meth:`hidden_states` and the pipeline stage
        partitioner (``pipeline_parallel/build_model.py``) iterate."""
        c = self.config
        block = self._block
        if c.remat:
            if c.remat_policy == "save_attn":
                block = jax.checkpoint(
                    block,
                    policy=jax.checkpoint_policies.save_only_these_names(
                        "attn_out"),
                )
            elif c.remat_policy == "save_attn_mlp":
                block = jax.checkpoint(
                    block,
                    policy=jax.checkpoint_policies.save_only_these_names(
                        "attn_out", "mlp_h"),
                )
            elif c.remat_policy == "mlp_only":
                pass  # _block already wraps its mlp half in jax.checkpoint
            else:
                block = jax.checkpoint(block)
        return block

    # --- KV-cached inference branch -------------------------------------------
    #
    # The decode-time twin of _attention/_block: prefill runs the training
    # forward once over the prompt and EXPOSES each layer's k/v in the
    # attention-native cache layout (b, h_kv, s, d); decode_block runs ONE
    # token through a block against the pre-allocated cache via the fused
    # decode-attention op. Cache allocation, the in-place
    # dynamic_update_slice writes, and sampling live in
    # apex_tpu.inference.DecodeEngine — this branch holds only model math,
    # so a weight-layout change cannot strand the inference path.
    # Inference-only: no dropout, single-chip (tp_size == 1), dense MLP.

    def check_decode_supported(self):
        c = self.config
        if c.tp_size > 1 or self.moe or c.cp_axis is not None:
            raise NotImplementedError(
                "the KV-cached decode path is single-chip dense-MLP only "
                "(tp_size == 1, no MoE, no context parallelism) — serve "
                "tp-sharded checkpoints by merging shards first")

    def _proj_qkv_bshd(self, p, x):
        """(b, s, H) → seq-major q (b, s, h, d), k/v (b, s, h_kv, d) via
        the packed projection — the SAME weight slicing every training
        path uses (``bshd_qkv_projection``), so cached k/v are the
        training forward's k/v activations by construction."""
        from apex_tpu.ops.attention import bshd_qkv_projection
        c = self.config
        return bshd_qkv_projection(
            x, p["qkv"]["weight"], p["qkv"].get("bias"),
            c.local_heads, c.local_kv_heads, c.head_dim)

    def _proj_attn_out(self, p, ctx):
        """(b, s, h, d) context → (b, s, H) through the output weight."""
        from apex_tpu.ops.attention import bshd_output_projection
        c = self.config
        y = bshd_output_projection(
            ctx, p["attn_out"]["weight"], c.local_heads, c.head_dim)
        if "bias" in p["attn_out"]:
            y = y + p["attn_out"]["bias"]
        return y

    def prefill_block(self, p, x):
        """One block of the PREFILL forward: the training block (pre-LN →
        causal attention → residual → pre-LN → MLP → residual, no dropout)
        that additionally returns this layer's (k, v) in the cache layout
        (b, h_kv, s, d) — what the engine writes into cache positions
        [0, s)."""
        h_in = fused_layer_norm(x, p["ln1_w"], p["ln1_b"])
        q, k, v = self._proj_qkv_bshd(p, h_in)
        from apex_tpu.ops.attention import flash_attention
        ctx = flash_attention(q, k, v, causal=True, layout="bshd")
        x = x + self._proj_attn_out(p, ctx)
        m = self._mlp(p, fused_layer_norm(x, p["ln2_w"], p["ln2_b"]))
        return x + m, (k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3))

    def decode_qkv(self, p, x):
        """ONE token's attention inputs: pre-LN + packed projection of the
        residual stream x (b, 1, H) → q (b, h, d) plus this token's cache
        rows k/v (b, h_kv, 1, d) — shaped for the engine's
        ``dynamic_update_slice`` write at the current position (the write
        happens BEFORE attention so the token attends to itself)."""
        h_in = fused_layer_norm(x, p["ln1_w"], p["ln1_b"])
        q, k, v = self._proj_qkv_bshd(p, h_in)
        return q[:, 0], k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3)

    def decode_block(self, p, x, q, k_lay, v_lay, lengths, rel_bias=None,
                     block_tables=None, kv_scales=None):
        """One token through one block against this layer's cache slices
        (ALREADY holding the token's own k/v row — the engine writes
        between :meth:`decode_qkv` and this call): x (b, 1, H) is the
        block's residual-stream input, ``q`` (b, h, d) the token's query
        heads, ``k_lay``/``v_lay`` (b, h_kv, max_s, d), ``lengths`` (b,)
        the live prefix length INCLUDING this token. ``rel_bias``: an
        optional causal BucketedBias the engine threads from the model's
        ``decode_rel_bias`` hook (T5-style relative bias at decode —
        recomputed in-kernel from the tiny table). ``block_tables``: the
        serving engine's paged-cache path — ``k_lay``/``v_lay`` are then
        the shared (num_blocks, h_kv, block_size, d) pool and the table
        maps each slot's logical kv blocks to pool blocks (see
        :func:`apex_tpu.ops.decode_attention`). ``kv_scales``: the int8
        paged pool's ``(k_scale, v_scale)`` per-row dequantization
        factors (the serving engine's ``kv_dtype="int8"`` knob).
        Returns the block output (b, 1, H)."""
        from apex_tpu.ops import decode_attention
        k_scale, v_scale = kv_scales if kv_scales is not None else (None,
                                                                    None)
        ctx = decode_attention(q, k_lay, v_lay, lengths, bias=rel_bias,
                               block_tables=block_tables,
                               k_scale=k_scale, v_scale=v_scale)
        x = x + self._proj_attn_out(p, ctx[:, None])
        m = self._mlp(p, fused_layer_norm(x, p["ln2_w"], p["ln2_b"]))
        return x + m

    # --- forward --------------------------------------------------------------

    def hidden_states(self, params, tokens, key=None):
        x, _ = self.hidden_states_with_aux(params, tokens, key)
        return x

    def hidden_states_with_aux(self, params, tokens, key=None):
        """(final hidden states, MoE router aux dict or None). The aux
        scalars (load_balance_loss, router_z_loss, drop_fraction) are
        per-layer means."""
        c = self.config
        s = tokens.shape[1]
        x = self.embedding(params["embedding"], tokens)
        if c.cp_axis is not None:
            # tokens are a sequence shard: gather the shard's GLOBAL
            # positions (zigzag stripes under ring)
            x = x + params["pos_embedding"][self._cp_positions(s)]
            if key is not None:
                # decorrelate the residual-dropout streams per cp rank:
                # each shard holds DIFFERENT global token positions, so an
                # unfolded key would hand them identical local-coordinate
                # keep masks (ADVICE r4). GPTPipeline folds its data-like
                # axes (incl. cp) before its stage fns — which bypass this
                # method — so the fold lives here for the direct path only.
                key = jax.random.fold_in(
                    key, jax.lax.axis_index(c.cp_axis))
        else:
            x = x + params["pos_embedding"][:s]
        if self.sp:
            x = self._sp_scatter(x)  # residual stream is seq-sharded

        block = self.wrapped_block()
        if self.moe:
            from apex_tpu.transformer.moe import router_aux_zeros
            aux0 = router_aux_zeros()
        else:
            aux0 = None

        if c.scan_layers:
            def body(carry, layer_and_key):
                x, aux = carry
                layer, i = layer_and_key
                k = None if key is None else jax.random.fold_in(key, i)
                out = block(layer, x, k)
                if self.moe:
                    x, a = out
                    aux = jax.tree.map(lambda t, u: t + u, aux, a)
                else:
                    x = out
                return (x, aux), None

            (x, aux), _ = jax.lax.scan(
                body, (x, aux0), (params["layers"], jnp.arange(c.num_layers))
            )
        else:
            # unrolled: larger program (compile time ~ num_layers) but no
            # while-loop carry copies / dynamic-slices; XLA schedules across
            # layer boundaries
            aux = aux0
            for i in range(c.num_layers):
                layer = jax.tree.map(lambda a, i=i: a[i], params["layers"])
                k = None if key is None else jax.random.fold_in(key, i)
                out = block(layer, x, k)
                if self.moe:
                    x, a = out
                    aux = jax.tree.map(lambda t, u: t + u, aux, a)
                else:
                    x = out
        if self.moe:
            aux = jax.tree.map(lambda t: t / c.num_layers, aux)
        if self.sp:
            x = self._sp_gather(x)  # full seq for the head
        return fused_layer_norm(x, params["lnf_w"], params["lnf_b"]), aux

    def logits(self, params, tokens, key=None):
        """Tied unembedding: local shard logits (b, s, V/tp)."""
        x = self.hidden_states(params, tokens, key)
        return self.unembed(params, x)

    def unembed(self, params, x):
        """Hidden states → local-shard logits. Under tp the input passes
        through copy-to-region (identity forward, psum backward) — the LM
        head is column-parallel over vocab, so each shard's matmul backward
        yields only its vocab slice's contribution to dx; without the psum
        transpose, per-rank gradients of everything upstream (final LN, the
        whole stack) would be partial sums (Megatron's
        ``parallel_lm_logits`` places the same ``copy_to`` for the same
        reason)."""
        if self.axis is not None:
            x = tp_lib.copy_to_tensor_model_parallel_region(x, self.axis)
        return jnp.dot(x, params["embedding"]["weight"].T)

    def loss_fn(self, params, tokens, targets, key=None, loss_mask=None,
                return_aux=False):
        """Mean LM loss via vocab-parallel CE (the reference's
        ``vocab_parallel_cross_entropy`` on the last stage). ``loss_mask``
        (tokens-shaped, 1 = count) weights the mean — the consumer of
        ``get_ltor_masks_and_position_ids``'s loss mask (reference
        ``pipeline_parallel/utils.py:303``: EOD and padding positions are
        excluded from the loss there the same way).

        With MoE, the router's load-balance and z losses enter with the
        config coefficients; ``return_aux=True`` additionally returns the
        aux dict (per-layer-mean load_balance_loss / router_z_loss /
        drop_fraction — the drop stat training loops should log)."""
        x, aux = self.hidden_states_with_aux(params, tokens, key)
        logits = self.unembed(params, x)
        losses = tp_lib.vocab_parallel_cross_entropy(
            logits, targets, axis_name=self.axis
        )
        loss = tp_lib.masked_mean(losses, loss_mask)
        if self.moe:
            c = self.config
            loss = (loss + c.moe_aux_coeff * aux["load_balance_loss"]
                    + c.moe_z_coeff * aux["router_z_loss"])
        return (loss, aux) if return_aux else loss


def _dropout(x, rate, key):
    """Counter-hash dropout — the same PRNG family as the in-kernel
    attention masks (``ops.pallas.attention.dropout_keep``): one scalar
    threefry draw for the seed, then ~10 integer ops per element vs the
    per-element threefry of ``jax.random.bernoulli`` (measured ~50 → ~3 ms
    of residual-dropout cost per flagship train step, PERF.md r4)."""
    from apex_tpu.ops.pallas.attention import dropout_keep
    seed = seed_from_key(key)
    # (rows, cols) coordinates rather than one flat arange: a flat int32
    # counter overflows at 2^31 elements (review r4) — splitting on the
    # last axis keeps both coordinates small at any realistic shape
    n = x.shape[-1]
    rows = jnp.arange(x.size // n, dtype=jnp.int32)[:, None]
    cols = jnp.arange(n, dtype=jnp.int32)[None, :]
    keep = dropout_keep(seed, jnp.int32(0), rows, cols, rate
                        ).reshape(x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def shard_params_for_tp(params, tp: int, config: GPTConfig):
    """Split a replicated (tp=1) :meth:`GPTModel.init` pytree into per-rank
    TP shards: every leaf gains a leading ``(tp,)`` axis holding rank r's
    slice at index r (replicated leaves are broadcast). Shard under
    ``P('tp', ...)`` specs and index ``[0]`` inside ``shard_map``.

    The layout mirrors the layers' own partitioning (reference
    ``tensor_parallel/layers.py``): qkv/mlp_up column-sharded by head /
    output features, attn_out/mlp_down row-sharded by input features,
    embedding vocab-sharded; LNs, positions and row-linear biases
    replicated. The qkv split respects the (q-heads | k-heads | v-heads)
    grouped feature packing of :meth:`GPTModel._attention`, including
    narrower k/v groups under grouped-query attention."""
    c = config
    hq, hkv = c.num_heads, c.kv_heads
    d = c.head_dim

    def split_qkv(x, feature_axis):
        # features packed (q: hq*d | k: hkv*d | v: hkv*d); each rank takes
        # its head range from every group
        q, k, v = jnp.split(
            x, [hq * d, (hq + hkv) * d], axis=feature_axis)

        def per_rank(y, heads):
            shape = y.shape
            hs = y.reshape(
                *shape[:feature_axis], heads, d, *shape[feature_axis + 1:])
            return [
                jnp.take(hs, jnp.arange(i * heads // tp, (i + 1) * heads // tp),
                         axis=feature_axis).reshape(
                             *shape[:feature_axis], heads // tp * d,
                             *shape[feature_axis + 1:])
                for i in range(tp)
            ]

        qs, ks, vs = per_rank(q, hq), per_rank(k, hkv), per_rank(v, hkv)
        return jnp.stack([
            jnp.concatenate([qs[i], ks[i], vs[i]], axis=feature_axis)
            for i in range(tp)
        ])

    def shard_layer_leaf(path, x):
        name = "/".join(str(p) for p in path)
        # leaves carry a leading (num_layers,) axis from the stacked init
        if "qkv" in name:  # weight (L, F, hid) and bias (L, F) split alike
            return split_qkv(x, 1)
        if "mlp_up" in name:  # weight (L, ffn, hid) or bias (L, ffn)
            return jnp.stack(jnp.split(x, tp, axis=1))
        if "attn_out" in name and "weight" in name:  # (L, hid, hid) row-shard
            return split_qkv_like_rows(x)
        if "mlp_down" in name and "weight" in name:  # (L, hid, ffn)
            return jnp.stack(jnp.split(x, tp, axis=2))
        if "moe" in name:
            # expert banks shard each expert's ffn dim (MoEMLP tp layout):
            # w1 (L, E, hid, ffn) col-, w2 (L, E, ffn, hid) row-, b1
            # (L, E, ffn) alike; router (L, hid, E) and b2 (L, E, hid)
            # replicate
            if "w1" in name:
                return jnp.stack(jnp.split(x, tp, axis=3))
            if "b1" in name or "w2" in name:
                return jnp.stack(jnp.split(x, tp, axis=2))
        return jnp.broadcast_to(x, (tp,) + x.shape)

    def split_qkv_like_rows(x):
        # attn_out input features are (heads, d) contiguous — row-shard by
        # head range
        L, out = x.shape[0], x.shape[1]
        y = x.reshape(L, out, hq, d)
        per = hq // tp
        return jnp.stack([
            y[:, :, i * per:(i + 1) * per].reshape(L, out, per * d)
            for i in range(tp)
        ])

    return {
        "embedding": {
            "weight": jnp.stack(
                jnp.split(params["embedding"]["weight"], tp, axis=0)),
        },
        "pos_embedding": jnp.broadcast_to(
            params["pos_embedding"], (tp,) + params["pos_embedding"].shape),
        "layers": jax.tree_util.tree_map_with_path(
            shard_layer_leaf, params["layers"]),
        "lnf_w": jnp.broadcast_to(params["lnf_w"], (tp,) + params["lnf_w"].shape),
        "lnf_b": jnp.broadcast_to(params["lnf_b"], (tp,) + params["lnf_b"].shape),
    }


# --- sequence-parallel boundary collectives (custom transposes) ---------------

import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _sp_scatter_seq1(x, axis_name):
    size = jax.lax.axis_size(axis_name)
    if x.shape[1] % size:
        # a flooring chunk would silently DROP the trailing tokens from
        # every rank's shard (and the backward gather would rebuild the
        # wrong length deep inside XLA) — fail at trace time, naming the
        # knob
        raise ValueError(
            f"GPTConfig(sequence_parallel=True): sequence length "
            f"{x.shape[1]} is not divisible by the {axis_name!r} axis "
            f"size {size} — the SP residual stream shards the sequence "
            f"per tp rank; pad the sequence to a multiple of {size}")
    rank = jax.lax.axis_index(axis_name)
    chunk = x.shape[1] // size
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=1)


def _sp_scatter_fwd(x, axis_name):
    return _sp_scatter_seq1(x, axis_name), None


def _sp_scatter_bwd(axis_name, _, g):
    return (jax.lax.all_gather(g, axis_name, axis=1, tiled=True),)


_sp_scatter_seq1.defvjp(_sp_scatter_fwd, _sp_scatter_bwd)


@_functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _sp_gather_seq1(x, axis_name):
    return jax.lax.all_gather(x, axis_name, axis=1, tiled=True)


def _sp_gather_fwd(x, axis_name):
    return _sp_gather_seq1(x, axis_name), None


def _sp_gather_bwd(axis_name, _, g):
    size = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    chunk = g.shape[1] // size
    return (jax.lax.dynamic_slice_in_dim(g, rank * chunk, chunk, axis=1),)


_sp_gather_seq1.defvjp(_sp_gather_fwd, _sp_gather_bwd)
