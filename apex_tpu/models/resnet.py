"""ResNet-50, NHWC, with SyncBatchNorm — the north-star benchmark model.

Analog of the reference's ``examples/imagenet/main_amp.py`` torchvision
ResNet-50 under amp O2 + apex DDP + SyncBN (the L1 convergence config and
the driver's ResNet-50 target). NHWC is the native TPU conv layout; batch
norm is :func:`apex_tpu.parallel.sync_batchnorm.sync_batch_norm` reducing
over the ``dp`` axis when ``bn_axis`` is set (= ``convert_syncbn_model``),
local otherwise. The fused add+ReLU epilogue of the reference's
``bottleneck``/``groupbn`` contrib kernels is the ``residual``/``fuse_relu``
path of sync_batch_norm, which XLA fuses.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import BatchNormState, sync_batch_norm

Layers50 = (3, 4, 6, 3)


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    width: int = 64
    layers: Tuple[int, ...] = Layers50
    bn_axis: Optional[str] = None  # 'dp' → SyncBatchNorm across data parallel
    bn_momentum: float = 0.1
    dtype: Any = jnp.float32


def _conv_init(key, shape, dtype):
    # kaiming-normal fan_out (torchvision's ResNet init)
    fan_out = shape[0] * shape[1] * shape[3]
    std = (2.0 / fan_out) ** 0.5
    return jax.random.normal(key, shape, dtype) * std


def _conv(x, w, stride=1, padding=None):
    """Symmetric explicit padding = (k-1)//2 per side, matching
    torchvision's Conv2d(padding=k//2): XLA's "SAME" pads asymmetrically
    ((0,1) for stride-2 3x3), which shifts every strided window by one."""
    if padding is None:
        kh, kw = w.shape[0], w.shape[1]
        padding = ((kh // 2, kh // 2), (kw // 2, kw // 2))
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


class ResNet50:
    """Functional ResNet-v1.5 (stride-2 in the 3x3, torchvision layout)."""

    def __init__(self, config: ResNetConfig = ResNetConfig()):
        self.config = config

    # --- init -----------------------------------------------------------------

    def _bn_init(self, ch):
        return (
            {"scale": jnp.ones((ch,), self.config.dtype),
             "bias": jnp.zeros((ch,), self.config.dtype)},
            BatchNormState.create(ch),
        )

    def init(self, key):
        c = self.config
        k = iter(jax.random.split(key, 200))
        params, state = {}, {}
        params["conv1"] = _conv_init(next(k), (7, 7, 3, c.width), c.dtype)
        params["bn1"], state["bn1"] = self._bn_init(c.width)

        in_ch = c.width
        for si, (blocks, ch) in enumerate(zip(c.layers, (64, 128, 256, 512))):
            for bi in range(blocks):
                name = f"s{si}b{bi}"
                stride = 2 if (bi == 0 and si > 0) else 1
                out_ch = ch * 4
                p = {
                    "conv_a": _conv_init(next(k), (1, 1, in_ch, ch), c.dtype),
                    "conv_b": _conv_init(next(k), (3, 3, ch, ch), c.dtype),
                    "conv_c": _conv_init(next(k), (1, 1, ch, out_ch), c.dtype),
                }
                st = {}
                p["bn_a"], st["bn_a"] = self._bn_init(ch)
                p["bn_b"], st["bn_b"] = self._bn_init(ch)
                p["bn_c"], st["bn_c"] = self._bn_init(out_ch)
                if bi == 0:
                    p["conv_proj"] = _conv_init(next(k), (1, 1, in_ch, out_ch), c.dtype)
                    p["bn_proj"], st["bn_proj"] = self._bn_init(out_ch)
                params[name], state[name] = p, st
                in_ch = out_ch

        params["fc_w"] = jax.random.normal(next(k), (in_ch, c.num_classes), c.dtype) * 0.01
        params["fc_b"] = jnp.zeros((c.num_classes,), c.dtype)
        return params, state

    # --- forward --------------------------------------------------------------

    def _bn(self, p, st, x, training, residual=None, relu=True):
        c = self.config
        return sync_batch_norm(
            x, p["scale"], p["bias"], st,
            training=training, momentum=c.bn_momentum,
            axis_name=c.bn_axis, fuse_relu=relu, residual=residual,
        )

    def _bottleneck(self, p, st, x, stride, training):
        """Bottleneck with the fused BN+add+ReLU epilogue
        (cf. ``apex/contrib/bottleneck/bottleneck.py:112``)."""
        new_st = {}
        identity = x
        h = _conv(x, p["conv_a"])
        h, new_st["bn_a"] = self._bn(p["bn_a"], st["bn_a"], h, training)
        h = _conv(h, p["conv_b"], stride)
        h, new_st["bn_b"] = self._bn(p["bn_b"], st["bn_b"], h, training)
        h = _conv(h, p["conv_c"])
        if "conv_proj" in p:
            identity = _conv(x, p["conv_proj"], stride)
            identity, new_st["bn_proj"] = self._bn(
                p["bn_proj"], st["bn_proj"], identity, training, relu=False
            )
        # fused: BN(h) + identity → ReLU
        h, new_st["bn_c"] = self._bn(p["bn_c"], st["bn_c"], h, training,
                                     residual=identity, relu=True)
        return h, new_st

    def apply(self, params, state, x, *, training: bool = True):
        """x: (N, H, W, 3) NHWC. Returns (logits, new_state)."""
        c = self.config
        new_state = {}
        h = _conv(x, params["conv1"], stride=2)
        h, new_state["bn1"] = self._bn(params["bn1"], state["bn1"], h, training)
        # MaxPool2d(3, stride=2, padding=1): symmetric, like the convs
        h = jax.lax.reduce_window(
            h, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
            ((0, 0), (1, 1), (1, 1), (0, 0)),
        )
        for si, blocks in enumerate(c.layers):
            for bi in range(blocks):
                name = f"s{si}b{bi}"
                stride = 2 if (bi == 0 and si > 0) else 1
                h, new_state[name] = self._bottleneck(
                    params[name], state[name], h, stride, training
                )
        h = jnp.mean(h, axis=(1, 2))
        logits = h @ params["fc_w"] + params["fc_b"]
        return logits, new_state
