"""Model zoo: runnable models exercising the framework end-to-end.

Analog of the reference's ``apex/transformer/testing/standalone_gpt.py`` /
``standalone_bert.py`` (single-file GPT/BERT driving the TP/PP stack) and
``examples/imagenet``'s torchvision ResNet-50.
"""

from apex_tpu.models.gpt import GPTConfig, GPTModel  # noqa: F401
from apex_tpu.models.bert import BertConfig, BertModel  # noqa: F401
from apex_tpu.models.resnet import ResNet50, ResNetConfig  # noqa: F401
from apex_tpu.models.t5 import (  # noqa: F401
    EncDecPipeline,
    EncoderDecoderModel,
    T5Config,
)
