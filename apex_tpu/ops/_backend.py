"""Kernel-dispatch policy: Pallas on TPU, interpret-mode Pallas in CPU tests,
jnp fallback otherwise.

The reference gates each CUDA kernel behind an import check and a shape
eligibility check (e.g. ``FusedScaleMaskSoftmax.is_kernel_available``,
``apex/transformer/functional/fused_softmax.py:159-179``). Here the same
decision is a function of (a) the active JAX backend, (b) per-op tiling
constraints, and (c) an override:

* ``impl='pallas'`` — always use the Pallas kernel (interpret mode off-TPU);
* ``impl='xla'``    — always use the jnp composition;
* ``impl='auto'``   — each op's *measured* default: flash attention picks
  the Pallas kernel from seq >= 1024 — or seq >= 512 at head_dim >= 128
  (``attention.flash_auto_crossover``) — (the one kernel family with a large
  honest win — it removes an O(s²) HBM tensor XLA cannot); layer norm,
  softmax, dense, and MLP resolve to their custom-VJP XLA compositions,
  which outran the kernels at every measured shape (PERF.md). Ops encode
  their default via :func:`resolve_auto`.

``APEX_TPU_PALLAS=0`` disables Pallas globally (escape hatch);
``APEX_TPU_PALLAS=interpret`` forces interpret-mode kernels everywhere, which
is how the CPU test suite exercises the real kernel code paths.
"""

from __future__ import annotations

import os

import jax

_ENV = "APEX_TPU_PALLAS"


def backend_platform() -> str:
    return jax.default_backend()


def interpret_mode() -> bool:
    """Pallas interpret=True — needed anywhere but real TPU hardware."""
    return backend_platform() != "tpu"


def pallas_enabled() -> bool:
    return os.environ.get(_ENV, "1") != "0"


def interpret_forced() -> bool:
    """True when the test suite forces interpret-mode kernels everywhere
    (``APEX_TPU_PALLAS=interpret``) — ops whose ``auto`` resolves to the XLA
    composition on measured grounds still take the kernel path then, so the
    kernel code stays covered off-TPU."""
    return os.environ.get(_ENV, "") == "interpret"


def resolve_auto(impl: str, default: str = "xla") -> str:
    """Resolve ``impl='auto'`` to an op's measured default — except under
    ``APEX_TPU_PALLAS=interpret``, where auto keeps taking the kernel path
    so CPU tests cover the kernel code regardless of the default."""
    if impl == "auto" and not interpret_forced():
        return default
    return impl


def choose_impl(impl: str, shapes_ok: bool) -> str:
    """Resolve an ``impl`` argument to 'pallas' or 'xla'."""
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(f"impl must be auto|pallas|xla, got {impl!r}")
    if impl == "xla" or not pallas_enabled():
        return "xla"
    if impl == "pallas":
        if not shapes_ok:
            raise ValueError("shapes do not satisfy the Pallas kernel's tiling constraints")
        return "pallas"
    # auto: kernels only pay off on real TPU; under interpret mode they are
    # pure overhead, so auto==xla on CPU unless tests force interpret.
    env = os.environ.get(_ENV, "")
    on_tpu = backend_platform() == "tpu"
    if shapes_ok and (on_tpu or env == "interpret"):
        return "pallas"
    return "xla"
