"""Fused ops: the TPU-native equivalent of the reference's ``csrc/`` tier.

Every CUDA extension in the reference (SURVEY.md §2.2) maps here to either a
Pallas TPU kernel (``apex_tpu/ops/pallas/``) or an XLA-fused composition, each
wrapped in ``jax.custom_vjp`` where the reference's autograd Function saves
non-trivial residuals:

* ``fused_layer_norm_cuda``  → :mod:`apex_tpu.ops.layer_norm`
* ``scaled_masked_softmax_cuda`` / ``scaled_upper_triang_masked_softmax_cuda``
  → :mod:`apex_tpu.ops.softmax`
* ``fused_dense_cuda`` / ``mlp_cuda`` → :mod:`apex_tpu.ops.fused_dense`,
  :mod:`apex_tpu.ops.mlp`
* ``xentropy_cuda`` → :mod:`apex_tpu.ops.xentropy`
* ``focal_loss_cuda`` → :mod:`apex_tpu.ops.focal_loss`
* ``fmhalib`` / ``fast_multihead_attn`` → :mod:`apex_tpu.ops.attention`
  (blockwise flash attention; removes the reference's seq≤512 / sk≤2048 caps)
* ``transducer_{joint,loss}_cuda`` → :mod:`apex_tpu.ops.transducer`

Kernel selection: ``impl='auto'`` resolves to each op's *measured* default
(see ``_backend`` and PERF.md): the flash-attention kernel from seq >= 1024
(512 at head_dim >= 128 — ``attention.flash_auto_crossover``);
the custom-VJP XLA compositions for layer norm, softmax, dense, and MLP,
which outran their kernels at every measured shape. ``impl='pallas'`` forces
a kernel (raising when shapes miss its tiling constraints — the analog of
the reference's eligibility check failing, ``fused_softmax.py:159-179``);
``impl='xla'`` forces the composition.
"""

from apex_tpu.ops.layer_norm import (  # noqa: F401
    fused_layer_norm,
    fused_rms_norm,
    FusedLayerNorm,
    FusedRMSNorm,
)
from apex_tpu.ops.softmax import (  # noqa: F401
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.ops.fused_dense import (  # noqa: F401
    fused_dense,
    fused_dense_gelu_dense,
    FusedDense,
    FusedDenseGeluDense,
)
from apex_tpu.ops.mlp import MLP, mlp  # noqa: F401
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss  # noqa: F401
from apex_tpu.ops.focal_loss import focal_loss  # noqa: F401
from apex_tpu.ops.attention import (BucketedBias, flash_attention,  # noqa: F401
                                    ring_attention, ulysses_attention)
from apex_tpu.ops.decode_attention import decode_attention  # noqa: F401
from apex_tpu.ops.sampling import fused_sample  # noqa: F401
from apex_tpu.ops.fused_verify import (fused_verify,  # noqa: F401
                                       fused_verify_tree)
from apex_tpu.ops.collective_matmul import (  # noqa: F401
    all_gather_matmul,
    copy_matmul,
    matmul_all_reduce,
    matmul_reduce_scatter,
)
