"""Fused softmax cross-entropy with label smoothing, logits-memory backward.

Re-design of ``apex.contrib.xentropy.SoftmaxCrossEntropyLoss``
(``apex/contrib/xentropy/softmax_xentropy.py:4-28``; kernel
``apex/contrib/csrc/xentropy/xentropy_kernel.cu``). The reference's memory
win: backward saves only (logits, max_log_sum_exp) — not the softmax — and
recomputes ``exp(logit - lse)`` in the gradient kernel. This ``custom_vjp``
keeps the identical residual set; XLA fuses the recompute into one pass, so a
separate Pallas kernel buys nothing extra here (the logits never materialize
a softmax-sized temporary either way).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.lists import apply_op_rules


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _xent_core(logits, labels, smoothing, half_to_float):
    loss, _ = _xent_fwd(logits, labels, smoothing, half_to_float)
    return loss


def softmax_cross_entropy_loss(
    logits: jax.Array,
    labels: jax.Array,
    smoothing: float = 0.0,
    half_to_float: bool = False,
) -> jax.Array:
    """Per-example loss over (..., V) logits and integer labels.

    ``smoothing``: label-smoothing factor ε — loss is
    ``(1-ε)·NLL(target) + ε·mean-NLL(all classes)`` (matching the kernel's
    smoothing formulation). ``half_to_float`` returns fp32 losses from half
    inputs (the reference's flag of the same name). Losses are FLOAT-class
    under O1 (``lists/functional_overrides.py:28-67``): half logits are cast
    up when the ambient policy has per-op rules.
    """
    logits, = apply_op_rules("cross_entropy", logits)
    return _xent_core(logits, labels, smoothing, half_to_float)


def binary_cross_entropy(
    probs: jax.Array, targets: jax.Array, *, eps: float = 1e-12
) -> jax.Array:
    """Elementwise BCE on probabilities — the reference's canonical *banned*
    op (``lists/functional_overrides.py:69-80``): under an O1 policy with
    half inputs this raises (use logits + :func:`softmax_cross_entropy_loss`
    or compute in fp32), matching ``wrap.err_if_any_half``
    (``apex/amp/wrap.py:114-130``). Legal in fp32 or outside O1.
    """
    probs, targets = apply_op_rules("binary_cross_entropy", probs, targets)
    p = jnp.clip(probs, eps, 1.0 - eps)
    return -(targets * jnp.log(p) + (1.0 - targets) * jnp.log1p(-p))


def _xent_fwd(logits, labels, smoothing, half_to_float):
    lf = logits.astype(jnp.float32)
    m = jnp.max(lf, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(lf - m), axis=-1, keepdims=True)) + m
    target_logit = jnp.take_along_axis(lf, labels[..., None], axis=-1)
    nll = (lse - target_logit)[..., 0]
    if smoothing:
        mean_nll = jnp.mean(lse[..., 0:1] - lf, axis=-1)
        loss = (1.0 - smoothing) * nll + smoothing * mean_nll
    else:
        loss = nll
    out_dtype = jnp.float32 if (half_to_float or logits.dtype == jnp.float32) else logits.dtype
    # residuals: logits + lse only (the reference's max_log_sum_exp save)
    return loss.astype(out_dtype), (logits, labels, lse)


def _xent_bwd(smoothing, half_to_float, res, dloss):
    logits, labels, lse = res
    lf = logits.astype(jnp.float32)
    probs = jnp.exp(lf - lse)  # recompute softmax from saved lse
    v = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, v, dtype=jnp.float32)
    grad = probs - (1.0 - smoothing) * onehot - smoothing / v
    grad = grad * dloss[..., None].astype(jnp.float32)
    return grad.astype(logits.dtype), None


_xent_core.defvjp(_xent_fwd, _xent_bwd)


class SoftmaxCrossEntropyLoss:
    """Class-style wrapper mirroring the reference's autograd.Function use."""

    @staticmethod
    def apply(logits, labels, smoothing=0.0, padding_idx=0, half_to_float=False):
        del padding_idx  # reference ignores it too (softmax_xentropy.py:14)
        return softmax_cross_entropy_loss(logits, labels, smoothing, half_to_float)
