"""Fused LayerNorm / RMSNorm with saved-statistics backward.

Re-design of ``apex.normalization.FusedLayerNorm`` / ``FusedRMSNorm``
(``apex/normalization/fused_layer_norm.py:33-125,204+``). The reference's
autograd Functions call ``fused_layer_norm_cuda`` and save (mean, rstd) for
backward; here the same contract is a ``jax.custom_vjp`` over the Pallas
kernels in :mod:`apex_tpu.ops.pallas.layer_norm`, with an XLA composition as
the fallback path (analog of the reference's ``F.layer_norm`` fallback when
the extension is missing, ``fused_layer_norm.py:16-30``).

Mixed-dtype behavior (the reference's ``MixedFusedLayerNorm`` /
``memory_efficient`` variants): statistics are always fp32; the output dtype
follows the input; weights may be fp32 with bf16 inputs.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.lists import apply_op_rules
from apex_tpu.ops import _backend
from apex_tpu.ops.pallas import layer_norm as _k


def _normalized_size(normalized_shape) -> int:
    if isinstance(normalized_shape, int):
        return normalized_shape
    size = 1
    for s in normalized_shape:
        size *= int(s)
    return size


def _shapes_ok(hidden: int) -> bool:
    return hidden % 128 == 0


# --- XLA reference path -------------------------------------------------------

def _xla_fwd(x2d, weight, bias, eps, rms):
    xf = x2d.astype(jnp.float32)
    if rms:
        mean = jnp.zeros((xf.shape[0], 1), jnp.float32)
        xc = xf
    else:
        mean = jnp.mean(xf, axis=1, keepdims=True)
        xc = xf - mean
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x2d.dtype), mean, rstd


def _xla_bwd(dy2d, x2d, mean, rstd, weight, rms):
    dy = dy2d.astype(jnp.float32)
    xf = x2d.astype(jnp.float32)
    xhat = (xf * rstd) if rms else ((xf - mean) * rstd)
    if weight is not None:
        dw = jnp.sum(dy * xhat, axis=0)
        db = jnp.sum(dy, axis=0)
        dyw = dy * weight.astype(jnp.float32)
    else:
        dw = db = None
        dyw = dy
    h = xf.shape[1]
    c2 = jnp.sum(dyw * xhat, axis=1, keepdims=True) / h
    if rms:
        dx = (dyw - xhat * c2) * rstd
    else:
        c1 = jnp.sum(dyw, axis=1, keepdims=True) / h
        dx = (dyw - c1 - xhat * c2) * rstd
    return dx.astype(x2d.dtype), dw, db


# --- custom_vjp core ----------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _norm_core(x2d, weight, bias, eps, rms, use_pallas):
    y, _ = _norm_fwd(x2d, weight, bias, eps, rms, use_pallas)
    return y


def _norm_fwd(x2d, weight, bias, eps, rms, use_pallas):
    if use_pallas:
        y, mean, rstd = _k.ln_fwd(
            x2d, weight, bias, eps=eps, rms=rms, interpret=_backend.interpret_mode()
        )
    else:
        y, mean, rstd = _xla_fwd(x2d, weight, bias, eps, rms)
    return y, (x2d, weight, bias, mean, rstd)


def _norm_bwd(eps, rms, use_pallas, res, dy):
    x2d, weight, bias, mean, rstd = res
    if use_pallas:
        dx, dw, db = _k.ln_bwd(
            dy, x2d, mean, rstd, weight, rms=rms, interpret=_backend.interpret_mode()
        )
    else:
        dx, dw, db = _xla_bwd(dy, x2d, mean, rstd, weight, rms)
    dw = None if weight is None else dw.astype(weight.dtype)
    db = None if bias is None else db.astype(bias.dtype)
    return dx, dw, db


_norm_core.defvjp(
    lambda x2d, weight, bias, eps, rms, use_pallas: _norm_fwd(
        x2d, weight, bias, eps, rms, use_pallas
    ),
    _norm_bwd,
)




def _ln_auto(impl: str) -> str:
    """auto == xla for the norms: XLA's two-pass LN composition beats the
    Pallas kernel at every measured shape (tools/microbench.py carry-loop
    timing on v5e with all of dx/dgamma/dbeta consumed, constant 16M
    elements: pallas/xla 1.63x at 16k rows x 1024, 1.57x at 1024x16384,
    1.99x at 256x65536) — the kernel fuses the stats pass but the XLA
    fusion pipelines the same HBM traffic better. The kernel stays
    reachable via ``impl='pallas'`` and carries the custom-VJP residual
    structure either way."""
    return _backend.resolve_auto(impl)


# --- public functional API ----------------------------------------------------

def fused_layer_norm(
    x: jax.Array,
    weight: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    normalized_shape: Optional[Sequence[int]] = None,
    *,
    eps: float = 1e-5,
    impl: str = "auto",
) -> jax.Array:
    """LayerNorm over the trailing ``normalized_shape`` dims (default: last).

    Equivalent of ``fused_layer_norm_affine`` / ``fused_layer_norm``
    (``apex/normalization/fused_layer_norm.py:33-76``). FLOAT-class under an
    O1 per-op-rules policy (norms stay fp32, ``lists/torch_overrides.py:29-60``).
    """
    x, weight, bias = apply_op_rules("layer_norm", x, weight, bias)
    if normalized_shape is None:
        normalized_shape = (x.shape[-1],) if weight is None else weight.shape
    hidden = _normalized_size(normalized_shape)
    x2d = x.reshape(-1, hidden)
    w = None if weight is None else weight.reshape(hidden)
    b = None if bias is None else bias.reshape(hidden)
    use_pallas = _backend.choose_impl(_ln_auto(impl), _shapes_ok(hidden)) == "pallas"
    y = _norm_core(x2d, w, b, eps, False, use_pallas)
    return y.reshape(x.shape)


def fused_rms_norm(
    x: jax.Array,
    weight: Optional[jax.Array] = None,
    normalized_shape: Optional[Sequence[int]] = None,
    *,
    eps: float = 1e-5,
    impl: str = "auto",
) -> jax.Array:
    """RMSNorm (``fused_rms_norm_affine``, ``fused_layer_norm.py:78-125``).
    FLOAT-class under an O1 per-op-rules policy."""
    x, weight = apply_op_rules("rms_norm", x, weight)
    if normalized_shape is None:
        normalized_shape = (x.shape[-1],) if weight is None else weight.shape
    hidden = _normalized_size(normalized_shape)
    x2d = x.reshape(-1, hidden)
    w = None if weight is None else weight.reshape(hidden)
    use_pallas = _backend.choose_impl(_ln_auto(impl), _shapes_ok(hidden)) == "pallas"
    y = _norm_core(x2d, w, None, eps, True, use_pallas)
    return y.reshape(x.shape)


# --- module wrappers (constructor parity with the reference modules) ----------

class FusedLayerNorm:
    """``apex.normalization.FusedLayerNorm`` (``fused_layer_norm.py:204``):
    holds (weight, bias) for ``normalized_shape``; functional call."""

    rms = False

    def __init__(self, normalized_shape, eps: float = 1e-5,
                 elementwise_affine: bool = True, impl: str = "auto"):
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.eps = eps
        self.elementwise_affine = elementwise_affine
        self.impl = impl

    def init(self, dtype=jnp.float32) -> dict:
        if not self.elementwise_affine:
            return {}
        params = {"weight": jnp.ones(self.normalized_shape, dtype)}
        if not self.rms:
            params["bias"] = jnp.zeros(self.normalized_shape, dtype)
        return params

    def __call__(self, params: dict, x: jax.Array) -> jax.Array:
        if self.rms:
            return fused_rms_norm(
                x, params.get("weight"), self.normalized_shape,
                eps=self.eps, impl=self.impl,
            )
        return fused_layer_norm(
            x, params.get("weight"), params.get("bias"), self.normalized_shape,
            eps=self.eps, impl=self.impl,
        )


class FusedRMSNorm(FusedLayerNorm):
    """``apex.normalization.FusedRMSNorm`` (``fused_layer_norm.py:300``)."""

    rms = True


# Mixed variants: in the reference these keep fp32 weights with fp16 inputs
# (``MixedFusedLayerNorm`` ``fused_layer_norm.py:398,420``); here *all* norms
# compute statistics in fp32 and respect param dtype, so these are aliases.
MixedFusedLayerNorm = FusedLayerNorm
MixedFusedRMSNorm = FusedRMSNorm
