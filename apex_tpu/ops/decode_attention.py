"""Fused decode attention: one query token per sequence against a KV cache.

The inference-side sibling of :func:`apex_tpu.ops.attention.flash_attention`:
forward-only (decode never differentiates), GQA-aware, masked to each row's
current length so a pre-allocated ``max_s`` cache costs compute proportional
to the live prefix. The Pallas kernel
(:mod:`apex_tpu.ops.pallas.decode_attention`) streams the cache through VMEM
once with the online-softmax recurrence in scratch — no ``logits-max``-style
staging writes; the XLA fallback is the same math as one fused
scores→softmax→weighted-sum composition (what ``JAX_PLATFORMS=cpu`` runs).

Dispatch follows the house rule (:mod:`apex_tpu.ops._backend`): Pallas on
TPU when the cache shape satisfies the tiling constraints, interpret-mode
Pallas under ``APEX_TPU_PALLAS=interpret``, XLA otherwise.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import _backend
from apex_tpu.ops.pallas.attention import NEG_INF
from apex_tpu.ops.pallas.decode_attention import decode_attn_fwd


def decode_kernel_ok(max_s: int, d: int, dtype) -> bool:
    """Mosaic eligibility for the decode kernel: the cache's seq dim must
    tile in 128-blocks and d must fill the lane dim (the same trailing-dim
    rules as the flash family; f16 has no Mosaic support). The inference
    engine allocates ``max_s`` as a 128-multiple precisely so this holds."""
    return (max_s % 128 == 0 and (d % 128 == 0 or d == 64)
            and dtype != jnp.float16)


def _xla_decode(q, k, v, lengths, scale, bias=None):
    """(b, h_kv, group, d) q against (b, h_kv, max_s, d) cache — a single
    einsum→softmax→einsum chain; XLA fuses the max/exp/sum on one pass of
    the scores, which never leave registers/cache at CPU test scale.
    ``bias``: a causal BucketedBias — the query sits at position
    ``lengths - 1``, keys at [0, max_s)."""
    s = jnp.einsum("bgqd,bgkd->bgqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        from apex_tpu.ops.pallas.attention import relative_position_bucket
        b_, h_kv, group, max_s = s.shape
        rel = (jnp.arange(max_s, dtype=jnp.int32)[None, :]
               - (lengths.astype(jnp.int32)[:, None] - 1))
        buckets = relative_position_bucket(
            rel, bidirectional=False, num_buckets=bias.num_buckets,
            max_distance=bias.max_distance)            # (b, max_s)
        vals = bias.table.astype(jnp.float32)[buckets]  # (b, max_s, h)
        s = s + vals.transpose(0, 2, 1).reshape(b_, h_kv, group, max_s)
    mask = jnp.arange(k.shape[2])[None, None, None, :] \
        < lengths[:, None, None, None]
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bgqk,bgkd->bgqd", p.astype(v.dtype), v)
    # length-0 rows: uniform-softmax garbage -> zeros (the kernel's
    # dead-row convention)
    dead = (lengths == 0)[:, None, None, None]
    return jnp.where(dead, 0.0, o).astype(q.dtype)


def decode_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, lengths: jax.Array,
    *, scale: Optional[float] = None, impl: str = "auto", bias=None,
) -> jax.Array:
    """Attention of ONE query token per sequence over a KV cache.

    ``q`` (b, h, d) — the current token's query heads; ``k``/``v``
    (b, h_kv, max_s, d) — the pre-allocated cache in the attention-native
    layout (``h_kv`` must divide ``h``; fewer kv heads = GQA, 1 = MQA);
    ``lengths`` (b,) int32 — the number of LIVE cache positions per row
    (the new token's k/v already written); positions >= the length are
    masked and, on the kernel path, whole KV blocks past it are skipped.
    Returns (b, h, d).

    No causal mask: at decode the query IS the last position, so "mask to
    the current length" is the entire causal structure. Forward-only —
    wrap in ``jax.lax.stop_gradient`` semantics by construction (there is
    no VJP; decode paths never differentiate).

    ``bias``: a CAUSAL :class:`~apex_tpu.ops.attention.BucketedBias`
    (``bidirectional=False``; table heads == h) — T5-style relative
    position bias at decode: the query is position ``lengths - 1``, so
    rel_pos = key − (len − 1) derives from the length operand the kernel
    already carries, and the bias recomputes in-kernel from the tiny
    table (offsets are cache positions; the container's q/k offsets are
    ignored here). The decode sibling of the flash kernels' in-kernel
    bucketed bias.
    """
    if q.ndim != 3 or k.ndim != 4 or k.shape != v.shape:
        raise ValueError(
            f"decode_attention takes q (b, h, d) and k/v (b, h_kv, max_s, "
            f"d); got q {q.shape}, k {k.shape}, v {v.shape}")
    b, h, d = q.shape
    h_kv, max_s = k.shape[1], k.shape[2]
    if k.shape[0] != b or k.shape[3] != d or h % h_kv:
        raise ValueError(
            f"cache (b, h_kv, max_s, d) must match q's batch/head_dim with "
            f"h_kv | h; got q {q.shape} vs cache {k.shape}")
    if lengths.shape != (b,):
        raise ValueError(f"lengths must be ({b},); got {lengths.shape}")
    lengths = lengths.astype(jnp.int32)
    group = h // h_kv
    scale = float(scale if scale is not None else 1.0 / d ** 0.5)
    qg = q.reshape(b, h_kv, group, d)
    rel_bias = None
    if bias is not None:
        from apex_tpu.ops.attention import BucketedBias, _validate_bucketed
        if not isinstance(bias, BucketedBias):
            raise ValueError(
                "decode_attention takes bias as a BucketedBias (decode "
                "recomputes the bias from the table and the live length; "
                "a materialized array has no decode form)")
        _validate_bucketed(bias)
        if bias.bidirectional:
            raise ValueError(
                "decode bias must use causal bucketing "
                "(bidirectional=False) — the query IS the last position")
        if bias.heads != h:
            raise ValueError(
                f"decode bias table heads ({bias.heads}) must equal q "
                f"heads ({h})")
        rel_bias = (bias.kernel_operands()[0],
                    (bias.num_buckets, bias.max_distance))

    # gate on BOTH operand dtypes: a mixed fp16 cache under fp32 q must
    # fall back too (Mosaic has no f16 in any operand position)
    ok = decode_kernel_ok(max_s, d, q.dtype) and k.dtype != jnp.float16
    # decode is HBM-bound: the kernel's one-pass cache read is the measured
    # default on TPU; off-TPU interpret-mode kernels are pure overhead
    use_pallas = _backend.choose_impl(impl, ok) == "pallas"
    if not use_pallas:
        return _xla_decode(qg, k, v, lengths, scale,
                           bias).reshape(b, h, d)
    o = decode_attn_fwd(
        qg.reshape(b * h_kv, group, d),
        k.reshape(b * h_kv, max_s, d),
        v.reshape(b * h_kv, max_s, d),
        jnp.repeat(lengths, h_kv),
        scale=scale, rel_bias=rel_bias,
        interpret=_backend.interpret_mode())
    return o.reshape(b, h, d)
