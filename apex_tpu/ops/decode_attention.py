"""Fused decode attention: one query token per sequence against a KV cache.

The inference-side sibling of :func:`apex_tpu.ops.attention.flash_attention`:
forward-only (decode never differentiates), GQA-aware, masked to each row's
current length so a pre-allocated ``max_s`` cache costs compute proportional
to the live prefix. The Pallas kernel
(:mod:`apex_tpu.ops.pallas.decode_attention`) streams the cache through VMEM
once with the online-softmax recurrence in scratch — no ``logits-max``-style
staging writes; the XLA fallback is the same math as one fused
scores→softmax→weighted-sum composition (what ``JAX_PLATFORMS=cpu`` runs).

Dispatch follows the house rule (:mod:`apex_tpu.ops._backend`): Pallas on
TPU when the cache shape satisfies the tiling constraints, interpret-mode
Pallas under ``APEX_TPU_PALLAS=interpret``, XLA otherwise.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import _backend
from apex_tpu.ops.pallas.attention import NEG_INF
from apex_tpu.ops.pallas.decode_attention import (decode_attn_fwd,
                                                  decode_attn_paged_fwd)

#: 1-byte pool storage dtypes the paged path dequantizes with per-row
#: scales (the serving engine's kv_dtype="int8"/"fp8_e4m3" pools); the
#: dequant is dtype-agnostic (astype(f32) * scale), so both share the
#: kernel and fallback verbatim
QUANT_POOL_DTYPES = (jnp.dtype(jnp.int8), jnp.dtype(jnp.float8_e4m3fn))


def decode_kernel_ok(max_s: int, d: int, dtype) -> bool:
    """Mosaic eligibility for the decode kernel: the cache's seq dim must
    tile in 128-blocks and d must fill the lane dim (the same trailing-dim
    rules as the flash family; f16 has no Mosaic support). The inference
    engine allocates ``max_s`` as a 128-multiple precisely so this holds."""
    return (max_s % 128 == 0 and (d % 128 == 0 or d == 64)
            and dtype != jnp.float16)


def paged_kernel_ok(block_size: int, d: int, dtype) -> bool:
    """Mosaic eligibility for the PAGED decode kernel: each cache block is
    one kernel kv-block, so the block size itself must be a 128-multiple
    (the serving engine defaults to 128 on TPU precisely so this holds);
    d/dtype rules are the contiguous kernel's."""
    return decode_kernel_ok(block_size, d, dtype)


def _gather_blocks(pool, tables, scale=None, out_dtype=None):
    """(num_blocks, h_kv, bs, d) pool + (b, nb) tables → the contiguous
    (b, h_kv, nb·bs, d) per-slot view — the XLA fallback materializes the
    indirection as one gather, then runs the EXACT contiguous math (so
    paged == contiguous is bitwise on this path, the parity tests'
    anchor). ``scale`` ((num_blocks, bs) fp32, the int8-pool path)
    dequantizes the gathered view: int8 rows × per-row scales →
    ``out_dtype``."""
    g = pool[tables]  # (b, nb, h_kv, bs, d)
    b, nb, h_kv, bs, d = g.shape
    if scale is not None:
        g = (g.astype(jnp.float32)
             * scale[tables][:, :, None, :, None]).astype(out_dtype)
    return g.transpose(0, 2, 1, 3, 4).reshape(b, h_kv, nb * bs, d)


def _xla_decode(q, k, v, lengths, scale, bias=None):
    """(b, h_kv, group, d) q against (b, h_kv, max_s, d) cache — a single
    einsum→softmax→einsum chain; XLA fuses the max/exp/sum on one pass of
    the scores, which never leave registers/cache at CPU test scale.
    ``bias``: a causal BucketedBias — the query sits at position
    ``lengths - 1``, keys at [0, max_s)."""
    s = jnp.einsum("bgqd,bgkd->bgqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        from apex_tpu.ops.pallas.attention import relative_position_bucket
        b_, h_kv, group, max_s = s.shape
        rel = (jnp.arange(max_s, dtype=jnp.int32)[None, :]
               - (lengths.astype(jnp.int32)[:, None] - 1))
        buckets = relative_position_bucket(
            rel, bidirectional=False, num_buckets=bias.num_buckets,
            max_distance=bias.max_distance)            # (b, max_s)
        vals = bias.table.astype(jnp.float32)[buckets]  # (b, max_s, h)
        s = s + vals.transpose(0, 2, 1).reshape(b_, h_kv, group, max_s)
    mask = jnp.arange(k.shape[2])[None, None, None, :] \
        < lengths[:, None, None, None]
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bgqk,bgkd->bgqd", p.astype(v.dtype), v)
    # length-0 rows: uniform-softmax garbage -> zeros (the kernel's
    # dead-row convention)
    dead = (lengths == 0)[:, None, None, None]
    return jnp.where(dead, 0.0, o).astype(q.dtype)


def decode_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, lengths: jax.Array,
    *, scale: Optional[float] = None, impl: str = "auto", bias=None,
    block_tables: Optional[jax.Array] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Attention of ONE query token per sequence over a KV cache.

    ``q`` (b, h, d) — the current token's query heads; ``k``/``v``
    (b, h_kv, max_s, d) — the pre-allocated cache in the attention-native
    layout (``h_kv`` must divide ``h``; fewer kv heads = GQA, 1 = MQA);
    ``lengths`` (b,) int32 — the number of LIVE cache positions per row
    (the new token's k/v already written); positions >= the length are
    masked and, on the kernel path, whole KV blocks past it are skipped.
    Returns (b, h, d).

    No causal mask: at decode the query IS the last position, so "mask to
    the current length" is the entire causal structure. Forward-only —
    wrap in ``jax.lax.stop_gradient`` semantics by construction (there is
    no VJP; decode paths never differentiate).

    ``bias``: a CAUSAL :class:`~apex_tpu.ops.attention.BucketedBias`
    (``bidirectional=False``; table heads == h) — T5-style relative
    position bias at decode: the query is position ``lengths - 1``, so
    rel_pos = key − (len − 1) derives from the length operand the kernel
    already carries, and the bias recomputes in-kernel from the tiny
    table (offsets are cache positions; the container's q/k offsets are
    ignored here). The decode sibling of the flash kernels' in-kernel
    bucketed bias.

    ``block_tables``: the PAGED cache path (the serving engine's
    block-pool layout, :mod:`apex_tpu.serving.kv_blocks`). ``k``/``v``
    are then the SHARED pool ``(num_blocks, h_kv, block_size, d)`` and
    ``block_tables`` is ``(b, nb_max)`` int32 — slot i's j-th logical kv
    block lives at pool index ``block_tables[i, j]``; logical length
    masking, block skip, and the bias are unchanged (columns stay
    logical positions). Every table entry must be a valid pool index —
    fill unused entries with the engine's reserved dead block 0 (their
    DMA runs but their columns are masked/skipped). The XLA fallback
    gathers the table into the contiguous view and runs the contiguous
    math, so paged == contiguous bitwise on that path.

    ``k_scale``/``v_scale``: the QUANTIZED paged pool (the serving
    engine's ``kv_dtype`` knob, ``"int8"`` or ``"fp8_e4m3"``) —
    ``k``/``v`` are then 1-byte pools and the scales are
    ``(num_blocks, block_size)`` fp32 per-row dequantization factors
    (shared across kv heads and head_dim: the write site quantizes one
    token row at a time). The Pallas kernel dequantizes each block IN
    VMEM after its (halved) HBM copy; the XLA fallback dequantizes the
    gathered view and runs the standard math — the dequant is the same
    ``astype(f32) * scale`` either way, so both storage dtypes share
    every path below. Scales are paged-path-only and required exactly
    when the pool is quantized.
    """
    if q.ndim != 3 or k.ndim != 4 or k.shape != v.shape:
        raise ValueError(
            f"decode_attention takes q (b, h, d) and k/v (b, h_kv, max_s, "
            f"d) — or (num_blocks, h_kv, block_size, d) pools with "
            f"block_tables; got q {q.shape}, k {k.shape}, v {v.shape}")
    b, h, d = q.shape
    if block_tables is None and (k_scale is not None
                                 or k.dtype in QUANT_POOL_DTYPES):
        raise ValueError(
            "quantized k/v pools (and their k_scale/v_scale) are the PAGED "
            "path only — pass block_tables (the serving engine's "
            "kv_dtype knob; the contiguous DecodeEngine cache keeps a "
            "float cache_dtype)")
    if block_tables is not None:
        return _paged_decode_attention(q, k, v, lengths, block_tables,
                                       scale=scale, impl=impl, bias=bias,
                                       k_scale=k_scale, v_scale=v_scale)
    h_kv, max_s = k.shape[1], k.shape[2]
    if k.shape[0] != b or k.shape[3] != d or h % h_kv:
        raise ValueError(
            f"cache (b, h_kv, max_s, d) must match q's batch/head_dim with "
            f"h_kv | h; got q {q.shape} vs cache {k.shape}")
    if lengths.shape != (b,):
        raise ValueError(f"lengths must be ({b},); got {lengths.shape}")
    lengths = lengths.astype(jnp.int32)
    group = h // h_kv
    scale = float(scale if scale is not None else 1.0 / d ** 0.5)
    qg = q.reshape(b, h_kv, group, d)
    rel_bias = _validate_decode_bias(bias, h)

    # gate on BOTH operand dtypes: a mixed fp16 cache under fp32 q must
    # fall back too (Mosaic has no f16 in any operand position)
    ok = decode_kernel_ok(max_s, d, q.dtype) and k.dtype != jnp.float16
    # decode is HBM-bound: the kernel's one-pass cache read is the measured
    # default on TPU; off-TPU interpret-mode kernels are pure overhead
    use_pallas = _backend.choose_impl(impl, ok) == "pallas"
    if not use_pallas:
        return _xla_decode(qg, k, v, lengths, scale,
                           bias).reshape(b, h, d)
    o = decode_attn_fwd(
        qg.reshape(b * h_kv, group, d),
        k.reshape(b * h_kv, max_s, d),
        v.reshape(b * h_kv, max_s, d),
        jnp.repeat(lengths, h_kv),
        scale=scale, rel_bias=rel_bias,
        interpret=_backend.interpret_mode())
    return o.reshape(b, h, d)


def _validate_decode_bias(bias, h):
    """Shared bias validation for the contiguous and paged paths →
    ``(table, (num_buckets, max_distance))`` kernel operands or None."""
    if bias is None:
        return None
    from apex_tpu.ops.attention import BucketedBias, _validate_bucketed
    if not isinstance(bias, BucketedBias):
        raise ValueError(
            "decode_attention takes bias as a BucketedBias (decode "
            "recomputes the bias from the table and the live length; "
            "a materialized array has no decode form)")
    _validate_bucketed(bias)
    if bias.bidirectional:
        raise ValueError(
            "decode bias must use causal bucketing "
            "(bidirectional=False) — the query IS the last position")
    if bias.heads != h:
        raise ValueError(
            f"decode bias table heads ({bias.heads}) must equal q "
            f"heads ({h})")
    return (bias.kernel_operands()[0],
            (bias.num_buckets, bias.max_distance))


def _paged_decode_attention(q, k, v, lengths, block_tables, *, scale,
                            impl, bias, k_scale=None, v_scale=None):
    """The block-table indirection path: the pool layout + table resolve
    to the same logical (b, h_kv, nb·bs, d) cache the contiguous path
    reads — by one gather on the XLA fallback, by scalar-prefetched
    index maps on the kernel path. An int8 pool rides the same
    indirection with its (num_blocks, bs) scales (dequantized in-VMEM
    in the kernel, post-gather on the fallback)."""
    b, h, d = q.shape
    num_blocks, h_kv, bs = k.shape[0], k.shape[1], k.shape[2]
    if k.shape[3] != d or h % h_kv:
        raise ValueError(
            f"paged cache pool (num_blocks, h_kv, block_size, d) must "
            f"match q's head_dim with h_kv | h; got q {q.shape} vs pool "
            f"{k.shape}")
    if block_tables.ndim != 2 or block_tables.shape[0] != b:
        raise ValueError(
            f"block_tables must be (b={b}, nb_max) int32; got "
            f"{block_tables.shape}")
    if not jnp.issubdtype(block_tables.dtype, jnp.integer):
        raise ValueError(
            f"block_tables must be integer block ids; got "
            f"{block_tables.dtype}")
    if lengths.shape != (b,):
        raise ValueError(f"lengths must be ({b},); got {lengths.shape}")
    quant = k.dtype in QUANT_POOL_DTYPES
    if quant != (k_scale is not None) or quant != (v_scale is not None):
        raise ValueError(
            "quantized pools require BOTH k_scale and v_scale (and float "
            "pools take neither): the per-row scales are half the "
            "quantized representation — got k dtype "
            f"{k.dtype}, k_scale {'set' if k_scale is not None else 'None'}, "
            f"v_scale {'set' if v_scale is not None else 'None'}")
    if quant:
        for name, sc in (("k_scale", k_scale), ("v_scale", v_scale)):
            if sc.shape != (num_blocks, bs):
                raise ValueError(
                    f"{name} must be (num_blocks={num_blocks}, "
                    f"block_size={bs}) per-row scales; got {sc.shape}")
        if bias is not None:
            raise ValueError(
                "quantized paged decode does not carry the bucketed "
                "relative bias (no quantized kernel path exists for the "
                "bias composition) — serve T5-style models with a float "
                "kv_dtype")
    lengths = lengths.astype(jnp.int32)
    group = h // h_kv
    scale = float(scale if scale is not None else 1.0 / d ** 0.5)
    qg = q.reshape(b, h_kv, group, d)
    rel_bias = _validate_decode_bias(bias, h)

    ok = paged_kernel_ok(bs, d, q.dtype) and k.dtype != jnp.float16
    use_pallas = _backend.choose_impl(impl, ok) == "pallas"
    if not use_pallas:
        out_dtype = qg.dtype if quant else None
        return _xla_decode(
            qg,
            _gather_blocks(k, block_tables, k_scale, out_dtype),
            _gather_blocks(v, block_tables, v_scale, out_dtype),
            lengths, scale, bias).reshape(b, h, d)
    o = decode_attn_paged_fwd(
        qg.reshape(b * h_kv, group, d),
        k.reshape(num_blocks * h_kv, bs, d),
        v.reshape(num_blocks * h_kv, bs, d),
        jnp.repeat(lengths, h_kv),
        block_tables,
        scale=scale, rel_bias=rel_bias,
        k_scale=k_scale, v_scale=v_scale,
        interpret=_backend.interpret_mode())
    return o.reshape(b, h, d)
