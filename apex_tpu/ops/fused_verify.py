"""Fused speculative-decoding verification: k+1 logit rows + k drafted
ids → (longest accepted prefix, corrected next token) in ONE tail.

The op-level wrapper over :mod:`apex_tpu.ops.pallas.verify` following
the house dispatch rule (:mod:`apex_tpu.ops._backend`): the Pallas
kernel on TPU when the vocab tiles the lane dim, interpret-mode Pallas
under ``APEX_TPU_PALLAS=interpret``, and an XLA composition otherwise.
The XLA fallback calls the SAME module-level acceptance helpers the
kernel body runs, so the two paths agree token-for-token on shared
noise — the parity anchor ``tests/test_spec.py`` pins, the same
discipline as :func:`apex_tpu.ops.fused_sample`.

This is the speculative engines' verification tail (one fused dispatch
per spec round, :class:`apex_tpu.inference.DecodeEngine` and
:class:`apex_tpu.serving.ServingEngine`); the acceptance math is
documented in :mod:`apex_tpu.ops.pallas.verify`.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.ops import _backend
from apex_tpu.ops.pallas.verify import (NO_DRAFT, VERIFY_LANES,
                                        fused_verify_fwd,
                                        fused_verify_tree_fwd,
                                        verify_greedy, verify_sampled,
                                        verify_tree_greedy,
                                        verify_tree_sampled)


def verify_kernel_ok(vocab: int, dtype) -> bool:
    """Mosaic eligibility: the vocab is the lane dim of every whole-row
    reduction (same rule as the fused sampling tail); f16 has no Mosaic
    support."""
    return vocab % 128 == 0 and dtype != jnp.float16


def _pad_lanes(x, fill):
    """Pad the trailing dim of a (b, k+1) operand to ``VERIFY_LANES``
    (one full lane tile — covers every k the drafters allow) for the
    kernel's tiling; contents beyond k+1 are ignored."""
    b, k1 = x.shape
    if k1 >= VERIFY_LANES:
        return x
    return jnp.pad(x, ((0, 0), (0, VERIFY_LANES - k1)),
                   constant_values=fill)


def fused_verify(logits: jax.Array, drafted: jax.Array,
                 key: Optional[jax.Array] = None, *,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, impl: str = "auto"
                 ) -> Tuple[jax.Array, jax.Array]:
    """Verify ``k`` drafted tokens against ``k+1`` target logit rows.

    ``logits`` (b, k+1, V): row i is the target's distribution for the
    token AFTER the prefix plus i accepted drafts (row k is the bonus
    position when every draft is accepted). ``drafted`` (b, k) int32.
    Returns ``(accept_len (b,), next_token (b,))`` int32: the longest
    accepted draft prefix per row, and the corrected token sampled from
    row ``accept_len`` — so one spec round emits
    ``drafted[:accept_len] + [next_token]``, between 1 and k+1 tokens.

    ``temperature == 0`` is exact greedy acceptance (the spec stream is
    token-identical to non-speculative greedy decoding — the parity the
    engines witness). ``temperature > 0`` is exact rejection-sampling
    acceptance for point-mass (greedy) drafts under the same
    temperature→top-k→top-p filtered distribution the fused sampling
    tail draws from. All knobs are STATIC — they select the compiled
    program, never retrace per round.

    The uniform noise is drawn inside the caller's jit by ``jax.random``
    and consumed by the kernel in the same program; kernel and XLA
    fallback share it, so ``impl`` never changes the verdict.
    """
    if logits.ndim != 3:
        raise ValueError(
            f"fused_verify takes (b, k+1, V) logits; got {logits.shape}")
    b, k1, V = logits.shape
    if drafted.ndim != 2 or drafted.shape != (b, k1 - 1):
        raise ValueError(
            f"drafted must be (b={b}, k={k1 - 1}) to match the (b, k+1, "
            f"V) logits; got {drafted.shape}")
    if k1 < 2:
        raise ValueError(
            f"fused_verify needs k >= 1 drafted tokens (k+1 = {k1} logit "
            f"rows); a 1-row verify is just sampling — use fused_sample")
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    sampled = temperature > 0.0
    if sampled and key is None:
        raise ValueError(
            "temperature > 0 verification requires a PRNG key")
    # bonus row rides as NO_DRAFT: its accept flag is structurally False
    drafted_pad = jnp.concatenate(
        [drafted.astype(jnp.int32),
         jnp.full((b, 1), NO_DRAFT, jnp.int32)], axis=1)
    top_k = min(int(top_k), V)
    u_acc = u_gum = None
    if sampled:
        ka, kg = jax.random.split(key)
        tiny = jnp.finfo(jnp.float32).tiny  # (0, 1]: log(u) stays finite
        u_acc = jax.random.uniform(ka, (b, k1), jnp.float32, minval=tiny,
                                   maxval=1.0)
        u_gum = jax.random.uniform(kg, (b, k1, V), jnp.float32,
                                   minval=tiny, maxval=1.0)
    ok = verify_kernel_ok(V, logits.dtype)
    if _backend.choose_impl(impl, ok) == "pallas":
        return fused_verify_fwd(
            logits,
            _pad_lanes(drafted_pad, NO_DRAFT),
            None if u_acc is None else _pad_lanes(u_acc, 1.0),
            u_gum, temperature=float(temperature), top_k=top_k,
            top_p=float(top_p), interpret=_backend.interpret_mode())
    if sampled:
        return verify_sampled(logits, drafted_pad, u_acc, u_gum,
                              temperature=float(temperature), top_k=top_k,
                              top_p=float(top_p))
    return verify_greedy(logits, drafted_pad)


def fused_verify_tree(logits: jax.Array, tokens: jax.Array,
                      parents: jax.Array, anc: jax.Array,
                      key: Optional[jax.Array] = None, *,
                      temperature: float = 0.0, top_k: int = 0,
                      top_p: float = 1.0, impl: str = "auto"
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Verify a DRAFT TREE of N tokens against N+1 target logit rows.

    ``logits`` (b, N+1, V): row j is the target's distribution for the
    token AFTER node j's token — row 0 after the committed pending
    token (the tree's root), rows 1..N after the drafted nodes.
    ``tokens`` (b, N+1) int32 node tokens (column 0 is the pending
    token and is ignored — it is pinned to ``NO_DRAFT`` internally);
    ``parents`` (b, N+1) int32 parent pointers into the same node
    index space (``parents[:, 0] == 0``, ``parents[:, j] < j`` — a
    topological order the drafters emit by construction); ``anc``
    (b, N+1, N+1) int32 ancestor-or-self closure (``anc[:, i, j] == 1``
    iff node j lies on node i's root path, node 0 and i included —
    :class:`apex_tpu.spec.tree.DraftTree` precomputes it once per
    static topology, so it ships as constant operand contents).

    Returns ``(accept_len (b,), j_star (b,), next_token (b,))`` int32:
    the deepest fully-accepted root path's length (accepted drafted
    tokens), its terminal node index, and the bonus/corrected token
    sampled from that node's row — one tree round emits the path's
    tokens plus ``next_token``, between 1 and depth+1 tokens. At
    branching 1 the semantics degenerate to :func:`fused_verify` (the
    chain is the one-branch tree). ``temperature == 0`` is exact
    greedy acceptance (the tree stream is token-identical to
    non-speculative greedy decoding); ``temperature > 0`` applies the
    point-mass rejection rule edge-wise along every root path, with
    each correction row filtering ALL of its drafted children (the
    chain's single-child residual, generalized). Noise is drawn inside
    the caller's jit and shared between kernel and XLA fallback, so
    ``impl`` never changes the verdict.
    """
    if logits.ndim != 3:
        raise ValueError(
            f"fused_verify_tree takes (b, N+1, V) logits; got "
            f"{logits.shape}")
    b, n1, V = logits.shape
    if tokens.shape != (b, n1) or parents.shape != (b, n1):
        raise ValueError(
            f"tokens/parents must be (b={b}, N+1={n1}) to match the "
            f"(b, N+1, V) logits; got {tokens.shape} / {parents.shape}")
    if anc.shape != (b, n1, n1):
        raise ValueError(
            f"anc must be the (b={b}, N+1={n1}, N+1={n1}) "
            f"ancestor-or-self closure; got {anc.shape}")
    if n1 < 2:
        raise ValueError(
            f"fused_verify_tree needs N >= 1 drafted nodes (N+1 = {n1} "
            f"logit rows); a 1-row verify is just sampling — use "
            f"fused_sample")
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    sampled = temperature > 0.0
    if sampled and key is None:
        raise ValueError(
            "temperature > 0 tree verification requires a PRNG key")
    # the root row carries no draft: its accept flag is structurally
    # irrelevant (tree_accepted_path forces node 0 accepted) but a
    # pinned NO_DRAFT keeps it out of the children filter
    tokens = tokens.astype(jnp.int32).at[:, 0].set(NO_DRAFT)
    parents = parents.astype(jnp.int32)
    anc = anc.astype(jnp.int32)
    top_k = min(int(top_k), V)
    u_acc = u_gum = None
    if sampled:
        ka, kg = jax.random.split(key)
        tiny = jnp.finfo(jnp.float32).tiny
        u_acc = jax.random.uniform(ka, (b, n1), jnp.float32, minval=tiny,
                                   maxval=1.0)
        u_gum = jax.random.uniform(kg, (b, n1, V), jnp.float32,
                                   minval=tiny, maxval=1.0)
    ok = verify_kernel_ok(V, logits.dtype) and n1 <= VERIFY_LANES
    if _backend.choose_impl(impl, ok) == "pallas":
        anc_pad = anc if n1 >= VERIFY_LANES else jnp.pad(
            anc, ((0, 0), (0, 0), (0, VERIFY_LANES - n1)))
        return fused_verify_tree_fwd(
            logits, _pad_lanes(tokens, NO_DRAFT),
            _pad_lanes(parents, 0), anc_pad,
            None if u_acc is None else _pad_lanes(u_acc, 1.0),
            u_gum, temperature=float(temperature), top_k=top_k,
            top_p=float(top_p), interpret=_backend.interpret_mode())
    if sampled:
        return verify_tree_sampled(logits, tokens, parents, anc, u_acc,
                                   u_gum, temperature=float(temperature),
                                   top_k=top_k, top_p=float(top_p))
    return verify_tree_greedy(logits, tokens, parents, anc)
