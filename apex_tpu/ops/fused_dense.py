"""Fused dense layers: GEMM+bias and GEMM+bias+GELU+GEMM.

Re-design of ``apex.fused_dense`` (``apex/fused_dense/fused_dense.py:7-86``;
kernels ``csrc/fused_dense_cuda.cu``). The reference leans on cuBLASLt
epilogues; on TPU the same fusion is either XLA's (which fuses bias+GELU into
the matmul consumer natively — the ``impl='xla'`` path) or the explicit Pallas
epilogue kernel (:func:`apex_tpu.ops.pallas.matmul.matmul_bias_act`).

Backward follows the reference's autograd Functions
(``fused_dense.py:7-52``): ``dX = dY Wᵀ``, ``dW = Xᵀ dY``, ``db = Σ dY``,
with the GELU derivative applied from the *saved pre-activation* in the
gelu-dense-dense case.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.lists import apply_op_rules
from apex_tpu.ops import _backend
from apex_tpu.ops.pallas.matmul import matmul_bias_act


def _mm(x, w, b=None, activation="none", use_pallas=False, out_dtype=None):
    if use_pallas:
        return matmul_bias_act(
            x, w, b, activation=activation, out_dtype=out_dtype,
            interpret=_backend.interpret_mode(),
        )
    # no preferred_element_type=f32: the MXU accumulates bf16 dots in fp32
    # regardless, and forcing an f32 *output* doubles HBM traffic on every
    # intermediate (measured 0.65x vs stock jnp on the DenseGeluDense
    # microbench before this change)
    r = jnp.dot(x, w)
    if b is not None:
        r = r + b
    if activation == "gelu":
        r = jax.nn.gelu(r, approximate=True)
    elif activation == "relu":
        r = jnp.maximum(r, 0.0)
    elif activation == "sigmoid":
        r = jax.nn.sigmoid(r)
    return r.astype(out_dtype or x.dtype)


def _gelu(x):
    return jax.nn.gelu(x, approximate=True)


def _dgelu(x):
    # derivative of tanh-approximate GELU, matching the fwd approximation
    c = jnp.sqrt(2.0 / jnp.pi)
    inner = c * (x + 0.044715 * x**3)
    t = jnp.tanh(inner)
    return 0.5 * (1.0 + t) + 0.5 * x * (1.0 - t * t) * c * (1.0 + 3 * 0.044715 * x * x)


# --- fused_dense: y = x @ w + b ----------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _dense_core(x, w, b, use_pallas):
    return _mm(x, w, b, "none", use_pallas)


def _dense_fwd(x, w, b, use_pallas):
    return _mm(x, w, b, "none", use_pallas), (x, w, b is not None)


def _dense_bwd(use_pallas, res, dy):
    x, w, has_bias = res
    dx = _mm(dy, w.T, use_pallas=use_pallas, out_dtype=x.dtype)
    dw = _mm(x.T, dy, use_pallas=use_pallas, out_dtype=w.dtype)
    db = jnp.sum(dy, axis=0).astype(w.dtype) if has_bias else None
    return dx, dw, db


_dense_core.defvjp(_dense_fwd, _dense_bwd)


def fused_dense(
    x: jax.Array, weight: jax.Array, bias: Optional[jax.Array] = None,
    *, impl: str = "auto",
) -> jax.Array:
    """``fused_dense_function`` (``apex/fused_dense/fused_dense.py:48``):
    ``x @ weightᵀ + bias`` (torch Linear weight layout (out, in))."""
    x, weight, bias = apply_op_rules("dense", x, weight, bias)
    use_pallas = _choose(impl, x, weight)
    lead = x.shape[:-1]
    x2d = x.reshape(-1, x.shape[-1])
    y = _dense_core(x2d, weight.T, bias, use_pallas)
    return y.reshape(*lead, weight.shape[0])


# --- fused_dense_gelu_dense ---------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _dgd_core(x, w1, b1, w2, b2, use_pallas):
    h_pre = _mm(x, w1, b1, "none", use_pallas)
    return _mm(_gelu(h_pre), w2, b2, "none", use_pallas)


def _dgd_fwd(x, w1, b1, w2, b2, use_pallas):
    # save the pre-GELU activation, like fused_dense_cuda's
    # linear_gelu_forward returns (output, gelu_in)
    h_pre = _mm(x, w1, b1, "none", use_pallas)
    h = _gelu(h_pre)
    y = _mm(h, w2, b2, "none", use_pallas)
    return y, (x, w1, w2, h_pre, h)


def _dgd_bwd(use_pallas, res, dy):
    x, w1, w2, h_pre, h = res
    dh = _mm(dy, w2.T, use_pallas=use_pallas, out_dtype=h.dtype)
    dw2 = _mm(h.T, dy, use_pallas=use_pallas, out_dtype=w2.dtype)
    db2 = jnp.sum(dy, axis=0).astype(w2.dtype)
    dh_pre = (dh * _dgelu(h_pre.astype(jnp.float32)).astype(dh.dtype))
    dx = _mm(dh_pre, w1.T, use_pallas=use_pallas, out_dtype=x.dtype)
    dw1 = _mm(x.T, dh_pre, use_pallas=use_pallas, out_dtype=w1.dtype)
    db1 = jnp.sum(dh_pre, axis=0).astype(w1.dtype)
    return dx, dw1, db1, dw2, db2


_dgd_core.defvjp(_dgd_fwd, _dgd_bwd)


def fused_dense_gelu_dense(
    x: jax.Array, weight1: jax.Array, bias1: jax.Array,
    weight2: jax.Array, bias2: jax.Array, *, impl: str = "auto",
) -> jax.Array:
    """``FusedDenseGeluDenseFunc`` (``fused_dense.py:27-46``): two Linears
    with a GELU between, saving the pre-GELU for backward."""
    x, weight1, bias1, weight2, bias2 = apply_op_rules(
        "dense", x, weight1, bias1, weight2, bias2
    )
    use_pallas = _choose(impl, x, weight1)
    lead = x.shape[:-1]
    x2d = x.reshape(-1, x.shape[-1])
    y = _dgd_core(x2d, weight1.T, bias1, weight2.T, bias2, use_pallas)
    return y.reshape(*lead, weight2.shape[0])


def _choose(impl: str, x, w) -> bool:
    # pallas path needs lane-aligned contraction/output dims
    ok = x.shape[-1] % 128 == 0 and w.shape[0] % 128 == 0
    # auto == xla here: XLA's native dot outruns the Pallas matmul on every
    # measured dense shape (tools/microbench.py, v5e: pallas 0.031 ms vs xla
    # 0.023 ms on 2k x 1024x4096 fwd+bwd) — the fused-dense win is the
    # custom_vjp epilogue/recompute structure, which both impls share. The
    # kernel stays reachable via impl='pallas' (and the env force) for
    # shapes XLA tiles badly.
    return _backend.choose_impl(_backend.resolve_auto(impl), ok) == "pallas"


# --- module wrappers ----------------------------------------------------------

class FusedDense:
    """``apex.fused_dense.FusedDense`` (``fused_dense.py:55``)."""

    def __init__(self, in_features: int, out_features: int, bias: bool = True,
                 impl: str = "auto"):
        self.in_features, self.out_features = in_features, out_features
        self.use_bias = bias
        self.impl = impl

    def init(self, key, dtype=jnp.float32) -> dict:
        bound = 1.0 / jnp.sqrt(self.in_features)
        w = jax.random.uniform(
            key, (self.out_features, self.in_features), dtype, -bound, bound
        )
        params = {"weight": w}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.out_features,), dtype)
        return params

    def __call__(self, params: dict, x: jax.Array) -> jax.Array:
        return fused_dense(x, params["weight"], params.get("bias"), impl=self.impl)


class FusedDenseGeluDense:
    """``apex.fused_dense.FusedDenseGeluDense`` (``fused_dense.py:72``)."""

    def __init__(self, in_features: int, intermediate_features: int,
                 out_features: int, impl: str = "auto"):
        self.in_features = in_features
        self.intermediate_features = intermediate_features
        self.out_features = out_features
        self.impl = impl

    def init(self, key, dtype=jnp.float32) -> dict:
        k1, k2 = jax.random.split(key)
        b1 = 1.0 / jnp.sqrt(self.in_features)
        b2 = 1.0 / jnp.sqrt(self.intermediate_features)
        return {
            "weight1": jax.random.uniform(
                k1, (self.intermediate_features, self.in_features), dtype, -b1, b1),
            "bias1": jnp.zeros((self.intermediate_features,), dtype),
            "weight2": jax.random.uniform(
                k2, (self.out_features, self.intermediate_features), dtype, -b2, b2),
            "bias2": jnp.zeros((self.out_features,), dtype),
        }

    def __call__(self, params: dict, x: jax.Array) -> jax.Array:
        return fused_dense_gelu_dense(
            x, params["weight1"], params["bias1"],
            params["weight2"], params["bias2"], impl=self.impl,
        )
