"""Flash attention (functional) + ring / Ulysses sequence parallelism.

``flash_attention`` supersedes the reference's ``apex.contrib.fmha``
(``apex/contrib/fmha/fmha.py:33-76``: fp16, seq≤512 only) and the fused MHA
cores of ``apex.contrib.multihead_attn``: one blockwise kernel, any length,
causal or full, bf16/fp32.

``ring_attention`` and ``ulysses_attention`` are the long-context
capabilities the reference lacks entirely (SURVEY.md §5 "Long-context: not
present"; §2.3 lists both CP and Ulysses as absent strategies). Ring: Q/K/V
sharded over the ``cp`` mesh axis along sequence; KV shards rotate via
``ppermute`` while each device folds incoming blocks into the
online-softmax state — O(s_local) memory, comm hidden behind per-step
compute. Ulysses: two ``all_to_all``s swap sequence sharding for head
sharding so each device runs *unmodified* flash attention over the full
sequence for its head subset — cheaper comm than ring when heads ≥ devices
(2 all-to-alls of the activations vs cp rotations of KV).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp.lists import apply_op_rules
from apex_tpu.ops import _backend
from apex_tpu.ops.pallas import attention as _k
from apex_tpu.parallel import mesh as mesh_lib


# --- single-device flash attention -------------------------------------------

def flash_auto_crossover(head_dim: int) -> int:
    """Minimum kv sequence length at which ``impl='auto'`` picks the Pallas
    kernel — measured end-to-end on v5e (see :func:`flash_attention`'s
    docstring table): 1024 at head_dim 64, 512 from head_dim 128 (full MXU
    lanes lower the kernel's break-even)."""
    return 512 if head_dim >= 128 else 1024

def masked_scores(q, k, scale, causal, kv_lens=None):
    """fp32 scaled scores over (..., seq, head_dim) with the bottom-right-
    aligned causal mask (last ``sq`` query rows of an ``sk``-long context)
    and optional per-row valid kv lengths (padding). ``kv_lens`` requires
    the flattened 3D layout (rows, seq, d) with one length per row."""
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32) * scale
    sq, sk = s.shape[-2], s.shape[-1]
    if causal:
        mask = jnp.arange(sk)[None, :] <= jnp.arange(sq)[:, None] + (sk - sq)
        s = jnp.where(mask, s, _k.NEG_INF)
    if kv_lens is not None:
        if s.ndim != 3:
            raise ValueError(
                "kv_lens masking requires 3D (rows, sq, sk) scores; flatten "
                "leading dims to rows first")
        s = jnp.where(jnp.arange(sk)[None, None, :] < kv_lens[:, None, None],
                      s, _k.NEG_INF)
    return s


def _xla_attention(q, k, v, scale, causal, kv_lens=None):
    s = masked_scores(q, k, scale, causal, kv_lens)
    lse = jax.nn.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    o = jnp.einsum("bqk,bkd->bqd", p.astype(q.dtype), v)
    if kv_lens is not None:
        # fully-masked rows: uniform-softmax garbage -> zeros, and pin lse
        # to 0 so backward's exp(NEG_INF - lse) underflows to 0 (the kernel
        # path's dead-row convention)
        dead = (kv_lens == 0)[:, None]
        o = jnp.where(dead[..., None], 0.0, o).astype(q.dtype)
        lse = jnp.where(dead, 0.0, lse)
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_core(q, k, v, kv_lens, scale, causal, use_pallas):
    o, _ = _flash_fwd_res(q, k, v, kv_lens, scale, causal, use_pallas)
    return o


def _flash_fwd_res(q, k, v, kv_lens, scale, causal, use_pallas):
    if use_pallas:
        o, lse = _k.flash_fwd(
            q, k, v, scale=scale, causal=causal, kv_lens=kv_lens,
            interpret=_backend.interpret_mode(),
        )
    else:
        group = q.shape[0] // k.shape[0]
        kf = jnp.repeat(k, group, 0) if group > 1 else k
        vf = jnp.repeat(v, group, 0) if group > 1 else v
        o, lse = _xla_attention(q, kf, vf, scale, causal, kv_lens)
    return o, (q, k, v, o, lse)


def _flash_fwd(q, k, v, kv_lens, scale, causal, use_pallas):
    o, res = _flash_fwd_res(q, k, v, kv_lens, scale, causal, use_pallas)
    return o, (res, kv_lens)


def _flash_bwd_impl(q, k, v, o, lse, do, kv_lens, scale, causal, use_pallas):
    """dq/dk/dv from saved (o, lse). With a *global* lse this is also the
    per-shard backward of distributed (ring) attention: p = exp(s − lse)
    and Δ = rowsum(do·o_final) are exact per shard, so each shard's ds —
    and hence its dq/dk/dv contribution — needs no cross-shard state."""
    if use_pallas:
        return _k.flash_bwd(
            q, k, v, o, lse, do, scale=scale, causal=causal, kv_lens=kv_lens,
            interpret=_backend.interpret_mode(),
        )
    group = q.shape[0] // k.shape[0]
    kf = jnp.repeat(k, group, 0) if group > 1 else k
    vf = jnp.repeat(v, group, 0) if group > 1 else v
    s = masked_scores(q, kf, scale, causal, kv_lens)
    p = jnp.exp(s - lse[..., None])
    dof = do.astype(jnp.float32)
    dv = jnp.einsum("bqk,bqd->bkd", p, dof)
    dp = jnp.einsum("bqd,bkd->bqk", dof, vf.astype(jnp.float32))
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1, keepdims=True)
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf.astype(jnp.float32)).astype(q.dtype)
    dk = jnp.einsum("bqk,bqd->bkd", ds, q.astype(jnp.float32))
    if group > 1:
        # per-q-head kv grads -> sum each kv group
        sk, d = k.shape[1], k.shape[2]
        dk = dk.reshape(-1, group, sk, d).sum(1)
        dv = dv.reshape(-1, group, sk, d).sum(1)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_bwd(scale, causal, use_pallas, res_and_lens, do):
    res, kv_lens = res_and_lens
    q, k, v, o, lse = res
    dq, dk, dv = _flash_bwd_impl(
        q, k, v, o, lse, do, kv_lens, scale, causal, use_pallas)
    if kv_lens is None:
        dlens = None
    else:
        import numpy as np
        dlens = np.zeros(kv_lens.shape, jax.dtypes.float0)
    return dq, dk, dv, dlens


_flash_core.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *, causal: bool = False, scale: Optional[float] = None,
    kv_lens: Optional[jax.Array] = None, impl: str = "auto",
) -> jax.Array:
    """Blockwise attention over (..., seq, head_dim) with any number of
    leading batch/head dims. No sequence-length cap (cf. fmha's 512).
    HALF-class under O1 (attention is matmul-shaped; the in-kernel softmax
    accumulates fp32 regardless).

    Grouped-query / multi-query attention: k/v may carry FEWER heads than q
    — flattened leading dims must divide q's (e.g. q (b, 8, s, d) with kv
    (b, 2, s, d) is a group of 4; kv (b, 1, s, d) is MQA). The kernel reads
    each kv row once per group via its BlockSpec index map — kv is never
    repeated in HBM. A capability the reference's fixed-shape fmha kernels
    (seq≤512, equal heads) cannot express.

    ``kv_lens``: per-row valid kv length over q's leading dims (padded
    batches) — positions >= the length are masked out; the compute of KV
    blocks entirely past it is skipped dynamically in-kernel (their
    HBM→VMEM copies still run — BlockSpec DMA is unconditional), so ragged
    batches save MXU time but not block DMA. Rows with length 0 return
    zeros. Composes with ``causal``. Passing ``kv_lens=None`` compiles
    kernels with no varlen operand or masking at all. (The reference's
    fused softmax takes a full (b,1,sq,sk) mask tensor; a length vector
    expresses the padded-batch case in O(rows) and keeps the flash memory
    profile.)

    ``impl='auto'`` picks the Pallas kernel from seq >= 1024, or from
    seq >= 512 when head_dim >= 128 (full MXU lanes lower the kernel's
    break-even): below the crossover the grid/launch overhead outweighs the
    saved score-tensor HBM traffic and XLA's batched-matmul composition of
    the same math (still recompute-in-backward via this function's
    custom_vjp — O(s) residuals) is faster on v5e-class chips. Measured
    end-to-end on GPT-medium train steps (v5e): d=64 S=1024 pallas 248.7
    vs xla 264.6 ms/step; d=128 S=512 163.4 vs 170.1 (kernel wins), S=256
    165.8 vs 158.7 (xla wins). Isolated-kernel timings through the remote
    tunnel had previously suggested a 4096 crossover — the full-step
    measurement (where the kernel competes with everything else for HBM)
    is the one that matters."""
    q, k, v = apply_op_rules("attention", q, k, v)
    d = q.shape[-1]
    scale = float(scale if scale is not None else 1.0 / d ** 0.5)
    lead = q.shape[:-2]
    q3 = q.reshape(-1, q.shape[-2], d)
    k3 = k.reshape(-1, k.shape[-2], d)
    v3 = v.reshape(-1, v.shape[-2], d)
    if k.shape[:-2] != v.shape[:-2]:
        raise ValueError(f"k/v leading dims differ: {k.shape} vs {v.shape}")
    if q.ndim >= 4:
        # batch dims must MATCH; only the head axis (last leading dim) may
        # be narrower on kv — a flattened-ratio check alone would accept a
        # mismatched batch dim and silently pair q rows with wrong batches
        if (q.shape[:-3] != k.shape[:-3]
                or q.shape[-3] % k.shape[-3]):
            raise ValueError(
                f"kv heads ({k.shape[-3]}) must divide q heads "
                f"({q.shape[-3]}) with equal batch dims "
                f"({q.shape[:-3]} vs {k.shape[:-3]}) for grouped-query "
                f"attention")
    elif q3.shape[0] % k3.shape[0]:
        raise ValueError(
            f"kv heads ({k3.shape[0]} flattened) must divide q heads "
            f"({q3.shape[0]} flattened) for grouped-query attention")
    ok = (
        q3.shape[-2] % 128 == 0 and k3.shape[-2] % 128 == 0
        and (d % 128 == 0 or d == 64)
    )
    if (impl == "auto" and k3.shape[-2] < flash_auto_crossover(d)
            and not _backend.interpret_forced()):
        impl = "xla"  # grid overhead beats saved score traffic below this
    use_pallas = _backend.choose_impl(impl, ok) == "pallas"
    if kv_lens is not None:
        if kv_lens.shape != lead:
            raise ValueError(
                f"kv_lens shape {kv_lens.shape} must equal q's leading dims "
                f"{lead}")
        # int32 before the custom_vjp: backward returns a float0 cotangent,
        # which JAX only accepts for integer primals
        kv_lens = kv_lens.reshape(-1).astype(jnp.int32)
    o = _flash_core(q3, k3, v3, kv_lens, scale, causal, use_pallas)
    return o.reshape(*lead, q.shape[-2], d)


# --- ring attention (context parallel) ---------------------------------------

def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *, axis_name: str = mesh_lib.CONTEXT_AXIS, causal: bool = False,
    scale: Optional[float] = None, impl: str = "auto",
) -> jax.Array:
    """Attention over a sequence sharded along ``axis_name``: q/k/v are this
    device's (bh, s_local, d) shard; the full sequence is cp·s_local.

    Must run inside shard_map with the axis bound. Per ring step the local
    KV shard rotates to the next device and the blockwise state (m, l, acc)
    folds the arriving shard in — identical math to flash attention's inner
    loop, with the block loop distributed over devices. Causal masking uses
    each shard's global offset, skipping fully-masked shards' compute is left
    to XLA (the mask zeroes them).

    Backward differentiates through the ``lax.scan`` of ring steps; each
    step's attention is rematerialized (``jax.checkpoint``) so live memory
    stays O(s_local) — the blockwise-parallel-transformer property.
    """
    cp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    d = q.shape[-1]
    scale = float(scale if scale is not None else 1.0 / d ** 0.5)
    s_local = q.shape[-2]
    perm = [(i, (i + 1) % cp) for i in range(cp)]
    if q.shape[0] % k.shape[0]:
        raise ValueError(
            f"kv rows ({k.shape[0]}) must divide q rows ({q.shape[0]}) "
            f"for grouped-query ring attention")
    group = q.shape[0] // k.shape[0]

    qf = q.astype(jnp.float32)

    @jax.checkpoint
    def partial_scores(kv, kv_rank):
        kk, vv = kv
        if group > 1:
            # grouped-query: the NARROW kv rotates the ring (that is the
            # GQA bandwidth win under context parallelism); broadcast to q
            # heads only here, at compute time
            kk = jnp.repeat(kk, group, 0)
            vv = jnp.repeat(vv, group, 0)
        s = jnp.einsum("bqd,bkd->bqk", qf, kk.astype(jnp.float32)) * scale
        if causal:
            q_pos = rank * s_local + jnp.arange(s_local)[:, None]
            k_pos = kv_rank * s_local + jnp.arange(s_local)[None, :]
            s = jnp.where(k_pos <= q_pos, s, _k.NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jnp.einsum("bqk,bkd->bqd", p, vv.astype(jnp.float32))
        return m, l, o

    def step(carry, _):
        m_acc, l_acc, o_acc, kv, kv_rank = carry
        m, l, o = partial_scores(kv, kv_rank)
        m_new = jnp.maximum(m_acc, m)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m - m_new)
        l_new = l_acc * alpha + l * beta
        o_new = o_acc * alpha + o * beta
        kv = jax.tree.map(lambda x: jax.lax.ppermute(x, axis_name, perm), kv)
        kv_rank = (kv_rank - 1) % cp
        return (m_new, l_new, o_new, kv, kv_rank), None

    bh = q.shape[0]
    init = (
        jnp.full((bh, s_local, 1), _k.NEG_INF, jnp.float32),
        jnp.zeros((bh, s_local, 1), jnp.float32),
        jnp.zeros((bh, s_local, d), jnp.float32),
        (k, v),
        rank,
    )
    (m_acc, l_acc, o_acc, _, _), _ = jax.lax.scan(step, init, None, length=cp)
    return (o_acc / jnp.maximum(l_acc, 1e-30)).astype(q.dtype)


# --- Ulysses attention (all-to-all sequence parallel) -------------------------

def ulysses_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *, axis_name: str = mesh_lib.CONTEXT_AXIS, causal: bool = False,
    scale: Optional[float] = None, impl: str = "auto",
) -> jax.Array:
    """DeepSpeed-Ulysses-style sequence parallelism: q/k/v are this device's
    (batch, s_local, heads, head_dim) sequence shard with ALL heads; an
    ``all_to_all`` re-shards heads over ``axis_name`` while gathering the
    full sequence, unmodified :func:`flash_attention` runs per local head
    group, and a reverse ``all_to_all`` restores sequence sharding.

    Must run inside shard_map with the axis bound; requires
    ``heads % axis_size == 0``. Complements :func:`ring_attention`: Ulysses
    moves activations twice (cheap when heads >= devices, and each device
    sees the full sequence so any attention variant drops in); ring never
    materializes the full sequence on one device (memory-optimal, arbitrary
    cp). Backward is the transposed all-to-alls around flash's custom VJP —
    no hand-written grad needed.
    """
    sp = jax.lax.axis_size(axis_name)
    b, s_local, h, d = q.shape
    h_kv = k.shape[2]
    if h % sp != 0 or h_kv % sp != 0:
        raise ValueError(
            f"ulysses_attention needs q heads ({h}) and kv heads ({h_kv}) "
            f"divisible by the {axis_name!r} axis size ({sp}); use "
            f"ring_attention otherwise")

    # (b, s/P, h, d) -> (b, s, h/P, d): scatter heads, gather sequence.
    # With grouped-query kv (h_kv < h) each tensor scatters its own head
    # count — the kv all_to_alls move group-times less data, and the
    # downstream flash kernel handles the grouping natively.
    def seq_to_head(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qg, kg, vg = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    s, h_loc = qg.shape[1], qg.shape[2]

    def to_bh(x):  # (b, s, x_heads, d) -> (b*x_heads, s, d)
        return x.transpose(0, 2, 1, 3).reshape(b * x.shape[2], s, d)

    o = flash_attention(to_bh(qg), to_bh(kg), to_bh(vg),
                        causal=causal, scale=scale, impl=impl)
    o = o.reshape(b, h_loc, s, d).transpose(0, 2, 1, 3)
    # (b, s, h/P, d) -> (b, s/P, h, d): gather heads, re-scatter sequence
    return jax.lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)
