"""Flash attention (functional) + ring / Ulysses sequence parallelism.

``flash_attention`` supersedes the reference's ``apex.contrib.fmha``
(``apex/contrib/fmha/fmha.py:33-76``: fp16, seq≤512 only) and the fused MHA
cores of ``apex.contrib.multihead_attn``: one blockwise kernel, any length,
causal or full, bf16/fp32.

``ring_attention`` and ``ulysses_attention`` are the long-context
capabilities the reference lacks entirely (SURVEY.md §5 "Long-context: not
present"; §2.3 lists both CP and Ulysses as absent strategies). Ring: Q/K/V
sharded over the ``cp`` mesh axis along sequence; KV shards rotate via
``ppermute`` while each device folds incoming *flash-kernel* (o, lse)
pieces into the online-softmax state — O(s_local·d) memory, comm hidden
behind per-step compute, causal load balanced by zigzag stripe sharding
(see :func:`ring_attention`). Ulysses: two ``all_to_all``s swap sequence
sharding for head sharding so each device runs *unmodified* flash attention
over the full sequence for its head subset — cheaper comm than ring when
heads ≥ devices (2 all-to-alls of the activations vs cp rotations of KV).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

import numpy as _np

from apex_tpu.amp.lists import apply_op_rules
from apex_tpu.ops import _backend
from apex_tpu.ops.pallas import attention as _k
from apex_tpu.ops.pallas.attention import relative_position_bucket  # noqa: F401 (public re-export)
from apex_tpu.parallel import mesh as mesh_lib


def _float0_like(x):
    """Zero cotangent for an integer primal (kv_lens, dropout seeds):
    custom-VJP backwards must return float0 for ints, None for absent."""
    return (None if x is None
            else _np.zeros(jnp.shape(x), jax.dtypes.float0))


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class BucketedBias:
    """T5 bucketed relative position bias as a first-class attention
    operand: the TINY ``(num_buckets, heads)`` table plus its bucketing
    config, recomputed per score tile INSIDE the flash kernels — the
    O(h·s²) HBM of a materialized ``(h, sq, sk)`` bias array collapses to
    O(num_buckets·h) (~1.6 GB → ~1 KB at s=8192, h=6), and because every
    tile derives its bias from GLOBAL coordinates (``q_offset`` /
    ``k_offset``: the global position of this shard's first query/key
    row), the same operand is computable per block under ANY sequence
    sharding — which is what lets ``ring_attention`` and
    ``ulysses_attention`` accept it (the materialized array cannot ride
    cp without replicating O(s²) per device).

    ``bidirectional=True`` is the T5 encoder bucketing (sign-split
    buckets), ``False`` the causal decoder form (future clamps to bucket
    0). Differentiable in ``table`` (the flash custom-VJPs return the
    bucket-table cotangent, computed in-kernel by the dtable kernel on
    the Pallas path); offsets are integer positions (float0 cotangents).

    Pass an instance as ``bias=`` to :func:`flash_attention` (both
    layouts), :func:`ring_attention`, :func:`ulysses_attention`, or
    ``decode_attention``. The packed ``fused_qkv_attention`` path takes
    materialized arrays only."""

    table: jax.Array                 # (num_buckets, heads)
    bidirectional: bool = False
    max_distance: int = 128
    q_offset: Any = 0                # global position of query row 0
    k_offset: Any = 0                # global position of key row 0

    def tree_flatten(self):
        return ((self.table, self.q_offset, self.k_offset),
                (self.bidirectional, self.max_distance))

    @classmethod
    def tree_unflatten(cls, aux, children):
        table, q_off, k_off = children
        return cls(table, aux[0], aux[1], q_off, k_off)

    @property
    def num_buckets(self) -> int:
        return self.table.shape[0]

    @property
    def heads(self) -> int:
        return self.table.shape[1]

    @property
    def static(self):
        """The kernels' static bucketing triple."""
        return (self.num_buckets, self.bidirectional, self.max_distance)

    def shifted(self, dq, dk) -> "BucketedBias":
        """Same table, offsets advanced by (dq, dk) — how the cp paths
        hand each stripe piece its global window."""
        return BucketedBias(self.table, self.bidirectional,
                            self.max_distance,
                            self.q_offset + dq, self.k_offset + dk)

    def kernel_operands(self):
        """(table (h, 128) fp32 head-major, offsets (2,) int32, static) —
        the Pallas kernels' ``rel_bias`` triple (one (1, 128) VMEM row per
        head; buckets pad the lane dim)."""
        nb, h = self.table.shape
        tab = jnp.zeros((h, _k._REL_LANES), jnp.float32)
        tab = tab.at[:, :nb].set(self.table.astype(jnp.float32).T)
        off = jnp.stack([
            jnp.asarray(self.q_offset, jnp.int32).reshape(()),
            jnp.asarray(self.k_offset, jnp.int32).reshape(())])
        return tab, off, self.static

    def materialize(self, sq, sk) -> jax.Array:
        """The (heads, sq, sk) fp32 array this operand abbreviates — the
        XLA-fallback/oracle form (O(h·sq·sk): only for fallbacks and
        tests; the kernels never build it)."""
        rel = ((jnp.asarray(self.k_offset, jnp.int32)
                + jnp.arange(sk, dtype=jnp.int32))[None, :]
               - (jnp.asarray(self.q_offset, jnp.int32)
                  + jnp.arange(sq, dtype=jnp.int32))[:, None])
        buckets = relative_position_bucket(
            rel, bidirectional=self.bidirectional,
            num_buckets=self.num_buckets, max_distance=self.max_distance)
        return self.table.astype(jnp.float32)[buckets].transpose(2, 0, 1)


def _bias_rows(bias) -> int:
    """Leading (row) extent of the bias operand — table heads for the
    bucketed form, hb for a materialized array — for the r % hb divide
    checks shared by both forms."""
    return bias.heads if isinstance(bias, BucketedBias) else bias.shape[0]


def _validate_bucketed(bias: BucketedBias) -> None:
    if bias.table.ndim != 2:
        raise ValueError(
            f"BucketedBias.table must be (num_buckets, heads); got "
            f"{bias.table.shape}")
    nb = bias.num_buckets
    if not 2 <= nb <= _k._REL_LANES:
        raise ValueError(
            f"num_buckets must be in [2, {_k._REL_LANES}] (the table pads "
            f"one 128-lane VMEM row); got {nb}")
    if bias.bidirectional and nb % 2:
        raise ValueError(
            f"bidirectional bucketing splits the range by sign and needs "
            f"an even num_buckets; got {nb}")


def _bucketed_table_grad(bias: BucketedBias, dbias_arr: jax.Array):
    """(num_buckets, heads) table cotangent from a materialized dbias
    (heads, sq, sk) — the gather's VJP (scatter-add by bucket), used by
    the XLA fallback backward (the Pallas path gets dtable straight from
    the in-kernel dtable kernel)."""
    sq, sk = dbias_arr.shape[1], dbias_arr.shape[2]
    _, vjp = jax.vjp(
        lambda t: dataclasses.replace(bias, table=t).materialize(sq, sk),
        bias.table)
    (dtable,) = vjp(dbias_arr)
    return dtable


# --- single-device flash attention -------------------------------------------

def flash_auto_crossover(head_dim: int) -> int:
    """Minimum kv sequence length at which ``impl='auto'`` picks the Pallas
    kernel — measured end-to-end on v5e (see :func:`flash_attention`'s
    docstring table): 1024 at head_dim 64, 512 from head_dim 128 (full MXU
    lanes lower the kernel's break-even)."""
    return 512 if head_dim >= 128 else 1024

def masked_scores(q, k, scale, causal, kv_lens=None, bias=None):
    """fp32 scaled scores over (..., seq, head_dim) with the bottom-right-
    aligned causal mask (last ``sq`` query rows of an ``sk``-long context)
    and optional per-row valid kv lengths (padding). ``kv_lens`` requires
    the flattened 3D layout (rows, seq, d) with one length per row.
    ``bias`` (hb, sq, sk): additive score bias, row ``r`` reading bias row
    ``r % hb`` (same contract as the Pallas kernels) — added to the scaled
    scores BEFORE the masks; requires the 3D layout."""
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32) * scale
    sq, sk = s.shape[-2], s.shape[-1]
    if bias is not None:
        if s.ndim != 3:
            raise ValueError(
                "bias requires 3D (rows, sq, sk) scores; flatten leading "
                "dims to rows first")
        hb = bias.shape[0]
        # rows r = b·hb + th share bias row th — the reshape groups them
        s = (s.reshape(-1, hb, sq, sk)
             + bias.astype(jnp.float32)).reshape(s.shape)
    if causal:
        mask = jnp.arange(sk)[None, :] <= jnp.arange(sq)[:, None] + (sk - sq)
        s = jnp.where(mask, s, _k.NEG_INF)
    if kv_lens is not None:
        if s.ndim != 3:
            raise ValueError(
                "kv_lens masking requires 3D (rows, sq, sk) scores; flatten "
                "leading dims to rows first")
        s = jnp.where(jnp.arange(sk)[None, None, :] < kv_lens[:, None, None],
                      s, _k.NEG_INF)
    return s


def _dropout_keep_dense(seed, bh, sq, sk, rate):
    """(bh, sq, sk) BOOL keep mask from the SAME counter-based hash the
    Pallas kernels evaluate blockwise (``pallas.attention.dropout_keep``)
    — kernel and XLA dispatch produce BIT-IDENTICAL masks, so the impl
    choice never changes a training run. Bool (not a pre-scaled fp32
    multiplier): the 1/(1-rate) rescale folds into each use site's
    ``where`` so XLA fuses the mask into its consumer instead of holding
    a persistent fp32 O(s²) tensor on the fallback path (ADVICE r4)."""
    t = jnp.arange(bh, dtype=jnp.int32)[:, None, None]
    rows = jnp.arange(sq, dtype=jnp.int32)[None, :, None]
    cols = jnp.arange(sk, dtype=jnp.int32)[None, None, :]
    return _k.dropout_keep(jnp.asarray(seed, jnp.int32), t, rows, cols,
                           rate)


def _dropout_apply_dense(x, keep, rate):
    """mask-and-rescale fused in one ``where`` (see above)."""
    return jnp.where(keep, x * jnp.float32(1.0 / (1.0 - rate)), 0.0)


def _xla_attention(q, k, v, scale, causal, kv_lens=None,
                   dropout_rate=0.0, dropout_seed=None, bias=None):
    s = masked_scores(q, k, scale, causal, kv_lens, bias)
    lse = jax.nn.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    if dropout_rate > 0.0:
        # probs dropout: the normalizer (lse) stays un-dropped, the
        # weighted sum takes the masked, rescaled probabilities
        p = _dropout_apply_dense(
            p, _dropout_keep_dense(dropout_seed, s.shape[0], s.shape[-2],
                                   s.shape[-1], dropout_rate),
            dropout_rate)
    o = jnp.einsum("bqk,bkd->bqd", p.astype(q.dtype), v)
    if kv_lens is not None:
        # fully-masked rows: uniform-softmax garbage -> zeros, and pin lse
        # to 0 so backward's exp(NEG_INF - lse) underflows to 0 (the kernel
        # path's dead-row convention)
        dead = (kv_lens == 0)[:, None]
        o = jnp.where(dead[..., None], 0.0, o).astype(q.dtype)
        lse = jnp.where(dead, 0.0, lse)
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9))
def _flash_core(q, k, v, bias, kv_lens, dropout_seed, scale, causal,
                use_pallas, dropout_rate):
    o, _ = _flash_fwd_res(q, k, v, bias, kv_lens, dropout_seed, scale,
                          causal, use_pallas, dropout_rate)
    return o


def _flash_fwd_res(q, k, v, bias, kv_lens, dropout_seed, scale, causal,
                   use_pallas, dropout_rate):
    bucketed = isinstance(bias, BucketedBias)
    if use_pallas:
        # full_lse: the residual keeps the (bh, sq, LANES) carrier so the
        # backward kernel reads it as-is (no slice/re-broadcast round trip)
        o, lse = _k.flash_fwd(
            q, k, v, scale=scale, causal=causal, kv_lens=kv_lens,
            bias=None if bucketed else bias,
            rel_bias=bias.kernel_operands() if bucketed else None,
            full_lse=True, interpret=_backend.interpret_mode(),
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        )
    else:
        group = q.shape[0] // k.shape[0]
        kf = jnp.repeat(k, group, 0) if group > 1 else k
        vf = jnp.repeat(v, group, 0) if group > 1 else v
        # XLA fallback: the bucketed operand materializes (the O(s²) array
        # exists ONLY on this path — small-seq / non-kernel shapes)
        bias_arr = (bias.materialize(q.shape[1], k.shape[1]) if bucketed
                    else bias)
        o, lse = _xla_attention(q, kf, vf, scale, causal, kv_lens,
                                dropout_rate, dropout_seed, bias_arr)
    return o, (q, k, v, o, lse)


def _flash_fwd(q, k, v, bias, kv_lens, dropout_seed, scale, causal,
               use_pallas, dropout_rate):
    o, res = _flash_fwd_res(q, k, v, bias, kv_lens, dropout_seed, scale,
                            causal, use_pallas, dropout_rate)
    return o, (res, bias, kv_lens, dropout_seed)


def _flash_bwd_impl(q, k, v, o, lse, do, kv_lens, scale, causal, use_pallas,
                    dropout_rate=0.0, dropout_seed=None, bias=None):
    """(dq, dk, dv, dbias) from saved (o, lse) — dbias is None when no bias
    rode the forward. With a *global* lse this is also the per-shard
    backward of distributed (ring) attention: p = exp(s − lse) and
    Δ = rowsum(do·o_final) are exact per shard, so each shard's ds —
    and hence its dq/dk/dv contribution — needs no cross-shard state.

    Dropout chain (S → P=softmax → Pd=mask∘P/(1-r) → O=Pd·V): the mask
    regenerates from the same counter hash as forward; dV = Pdᵀ·dO and
    dS = P ∘ (mask/(1-r) ∘ (dO·Vᵀ) − Δ) — Δ = rowsum(dO∘O) already equals
    rowsum(Pd ∘ dPd), so only the dPd term re-masks.

    Bias: dbias = Σ over the rows sharing each bias row of the UNSCALED
    dS (bias enters S additively after the 1/√d scale). With a
    :class:`BucketedBias` the fourth output is the (num_buckets, heads)
    TABLE cotangent instead (in-kernel dtable on the Pallas path; gather
    VJP on the materialized fallback)."""
    bucketed = isinstance(bias, BucketedBias)
    if use_pallas:
        out = _k.flash_bwd(
            q, k, v, o, lse, do, scale=scale, causal=causal, kv_lens=kv_lens,
            bias=None if bucketed else bias,
            rel_bias=bias.kernel_operands() if bucketed else None,
            interpret=_backend.interpret_mode(),
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        )
        if bias is None:
            return (*out, None)
        if bucketed:
            dq, dk, dv, dtab_hm = out
            return dq, dk, dv, dtab_hm[:, :bias.num_buckets].T
        return out
    group = q.shape[0] // k.shape[0]
    kf = jnp.repeat(k, group, 0) if group > 1 else k
    vf = jnp.repeat(v, group, 0) if group > 1 else v
    bias_arr = (bias.materialize(q.shape[1], k.shape[1]) if bucketed
                else bias)
    s = masked_scores(q, kf, scale, causal, kv_lens, bias_arr)
    p = jnp.exp(s - lse[..., None])
    dof = do.astype(jnp.float32)
    if dropout_rate > 0.0:
        keep = _dropout_keep_dense(
            dropout_seed, s.shape[0], s.shape[-2], s.shape[-1], dropout_rate)
        pd = _dropout_apply_dense(p, keep, dropout_rate)
    else:
        pd = p
    dv = jnp.einsum("bqk,bqd->bkd", pd, dof)
    dp = jnp.einsum("bqd,bkd->bqk", dof, vf.astype(jnp.float32))
    if dropout_rate > 0.0:
        dp = _dropout_apply_dense(dp, keep, dropout_rate)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1, keepdims=True)
    ds_pre = p * (dp - delta)  # the unscaled dS (the bias cotangent)
    dbias = None
    if bias is not None:
        hb, sq, sk_ = bias_arr.shape
        dbias = ds_pre.reshape(-1, hb, sq, sk_).sum(0)
        if bucketed:
            dbias = _bucketed_table_grad(bias, dbias)
    ds = ds_pre * scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf.astype(jnp.float32)).astype(q.dtype)
    dk = jnp.einsum("bqk,bqd->bkd", ds, q.astype(jnp.float32))
    if group > 1:
        # per-q-head kv grads -> sum each kv group
        sk, d = k.shape[1], k.shape[2]
        dk = dk.reshape(-1, group, sk, d).sum(1)
        dv = dv.reshape(-1, group, sk, d).sum(1)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype), dbias


def _bias_cotangent(bias, dbias):
    """Package the 4th backward output as the bias primal's cotangent:
    arrays get the array grad in their own dtype; a BucketedBias gets a
    BucketedBias whose table is the (num_buckets, heads) grad and whose
    integer offsets carry float0."""
    if bias is None:
        return None
    if isinstance(bias, BucketedBias):
        return BucketedBias(
            dbias.astype(bias.table.dtype), bias.bidirectional,
            bias.max_distance, _float0_like(bias.q_offset),
            _float0_like(bias.k_offset))
    return dbias.astype(bias.dtype)


def _flash_bwd(scale, causal, use_pallas, dropout_rate, res_pack, do):
    res, bias, kv_lens, dropout_seed = res_pack
    q, k, v, o, lse = res
    dq, dk, dv, dbias = _flash_bwd_impl(
        q, k, v, o, lse, do, kv_lens, scale, causal, use_pallas,
        dropout_rate, dropout_seed, bias)
    return (dq, dk, dv, _bias_cotangent(bias, dbias),
            _float0_like(kv_lens), _float0_like(dropout_seed))


_flash_core.defvjp(_flash_fwd, _flash_bwd)


# --- seq-major (bshd) core ----------------------------------------------------

def bshd_kernel_ok(sq: int, sk: int, h: int, d: int, dtype) -> bool:
    """Mosaic eligibility for the seq-major (folded) kernels — shared by
    ``flash_attention(layout='bshd')``, ``fused_qkv_attention`` callers,
    and the GPT fused-path gate so the rule lives in ONE place. The folded
    (b, s, h·d) views take d-wide column blocks, so d must tile the
    128-lane rule itself (d == 64 only passes when it IS the folded dim,
    i.e. a single head); f16 has no Mosaic support at all."""
    return (sq % 128 == 0 and sk % 128 == 0
            and (d % 128 == 0 or (h == 1 and d == 64))
            and dtype != jnp.float16)


def bshd_qkv_projection(x, weight, bias, h, h_kv, d):
    """(b, s, H) activations through a PACKED q|k|v weight ((h+2·h_kv)·d,
    H), features ordered q-heads|k-heads|v-heads — straight to the
    seq-major (b, s, heads, d) layout the bshd kernels read with no layout
    copy. The ONE place the packed-layout slicing lives (GPT and BERT both
    ride it; a layout change edits one function)."""
    H = weight.shape[-1]
    wq = weight[:h * d].reshape(h, d, H)
    wk = weight[h * d:(h + h_kv) * d].reshape(h_kv, d, H)
    wv = weight[(h + h_kv) * d:].reshape(h_kv, d, H)
    q = jnp.einsum("bsH,hdH->bshd", x, wq)
    k = jnp.einsum("bsH,hdH->bshd", x, wk)
    v = jnp.einsum("bsH,hdH->bshd", x, wv)
    if bias is not None:
        q = q + bias[:h * d].reshape(h, d)
        k = k + bias[h * d:(h + h_kv) * d].reshape(h_kv, d)
        v = v + bias[(h + h_kv) * d:].reshape(h_kv, d)
    return q, k, v


def bshd_output_projection(ctx, weight, h, d):
    """(b, s, h, d) attention context through the output weight (O, h·d),
    contracted directly over (heads, d) — no transpose back to flat."""
    return jnp.einsum("bshd,Hhd->bsH", ctx, weight.reshape(-1, h, d))


def _to_bh(x):  # (b, s, h, d) -> (b*h, s, d) for the XLA fallback
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _from_bh(x, b, h):  # (b*h, s, d) -> (b, s, h, d)
    s, d = x.shape[1], x.shape[2]
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9))
def _flash_core_bshd(q, k, v, bias, kv_lens, dropout_seed, scale, causal,
                     use_pallas, dropout_rate):
    o, _ = _flash_fwd_res_bshd(q, k, v, bias, kv_lens, dropout_seed, scale,
                               causal, use_pallas, dropout_rate)
    return o


def _expand_lens_bh(kv_lens, h):
    """(b,) per-batch lengths -> (b*h,) per-row for the flat XLA path
    (matches _to_bh's b-major row order)."""
    return None if kv_lens is None else jnp.repeat(kv_lens, h)


def _flash_fwd_res_bshd(q, k, v, bias, kv_lens, dropout_seed, scale, causal,
                        use_pallas, dropout_rate):
    bucketed = isinstance(bias, BucketedBias)
    if use_pallas:
        # carrier residual, same rationale as _flash_fwd_res
        o, lse = _k.flash_fwd_bshd(
            q, k, v, scale=scale, causal=causal, kv_lens=kv_lens,
            bias=None if bucketed else bias,
            rel_bias=bias.kernel_operands() if bucketed else None,
            full_lse=True, interpret=_backend.interpret_mode(),
            dropout_rate=dropout_rate, dropout_seed=dropout_seed)
    else:
        b, h = q.shape[0], q.shape[2]
        group = h // k.shape[2]
        # flat repeat matches the grouped row order (q row b·h + h_i reads
        # kv row (b·h + h_i)//group) — same expansion _flash_bwd_impl uses;
        # bias rows keep the r % hb contract under the b-major flatten
        kf = _to_bh(k)
        vf = _to_bh(v)
        if group > 1:
            kf = jnp.repeat(kf, group, 0)
            vf = jnp.repeat(vf, group, 0)
        bias_arr = (bias.materialize(q.shape[1], k.shape[1]) if bucketed
                    else bias)
        o3, lse3 = _xla_attention(_to_bh(q), kf, vf, scale, causal,
                                  _expand_lens_bh(kv_lens, h),
                                  dropout_rate, dropout_seed, bias_arr)
        o = _from_bh(o3, b, h)
        lse = lse3.reshape(b, h, -1)
    return o, (q, k, v, o, lse)


def _flash_fwd_bshd(q, k, v, bias, kv_lens, dropout_seed, scale, causal,
                    use_pallas, dropout_rate):
    o, res = _flash_fwd_res_bshd(q, k, v, bias, kv_lens, dropout_seed,
                                 scale, causal, use_pallas, dropout_rate)
    return o, (res, bias, kv_lens, dropout_seed)


def _flash_bwd_bshd_impl(q, k, v, o, lse, do, kv_lens, scale, causal,
                         use_pallas, dropout_rate=0.0, dropout_seed=None,
                         bias=None):
    """(dq, dk, dv, dbias) for the seq-major layout — the bshd twin of
    :func:`_flash_bwd_impl`, same raw-cotangent contract: dbias is the
    UNcast fp32 bucket-table grad (BucketedBias) / fp32 dbias array /
    None — so cross-piece accumulators (the ring) sum full-precision
    partials and only the final custom-vjp cotangent casts to the
    primal's dtype."""
    bucketed = isinstance(bias, BucketedBias)
    if use_pallas:
        out = _k.flash_bwd_bshd(
            q, k, v, o, lse, do, scale=scale, causal=causal,
            kv_lens=kv_lens, bias=None if bucketed else bias,
            rel_bias=bias.kernel_operands() if bucketed else None,
            interpret=_backend.interpret_mode(),
            dropout_rate=dropout_rate, dropout_seed=dropout_seed)
        dq, dk, dv = out[:3]
        dbias = None
        if bias is not None:
            dbias = (out[3][:, :bias.num_buckets].T if bucketed
                     else out[3])
        return dq, dk, dv, dbias
    b, h = q.shape[0], q.shape[2]
    h_kv = k.shape[2]
    dq3, dk3, dv3, dbias = _flash_bwd_impl(
        _to_bh(q), _to_bh(k), _to_bh(v), _to_bh(o),
        lse.reshape(b * h, -1), _to_bh(do), _expand_lens_bh(kv_lens, h),
        scale, causal, use_pallas=False, dropout_rate=dropout_rate,
        dropout_seed=dropout_seed, bias=bias)
    return (_from_bh(dq3, b, h), _from_bh(dk3, b, h_kv),
            _from_bh(dv3, b, h_kv), dbias)


def _flash_bwd_bshd(scale, causal, use_pallas, dropout_rate, res_pack, do):
    res, bias, kv_lens, dropout_seed = res_pack
    q, k, v, o, lse = res
    dq, dk, dv, dbias = _flash_bwd_bshd_impl(
        q, k, v, o, lse, do, kv_lens, scale, causal, use_pallas,
        dropout_rate, dropout_seed, bias)
    return (dq, dk, dv, _bias_cotangent(bias, dbias),
            _float0_like(kv_lens), _float0_like(dropout_seed))


_flash_core_bshd.defvjp(_flash_fwd_bshd, _flash_bwd_bshd)


# --- fused projection + attention block ---------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11, 12))
def fused_qkv_attention(x, w_qkv, b_qkv, w_out, bias, dropout_seed,
                        kv_lens, h, h_kv, d, scale, causal,
                        dropout_rate=0.0):
    """Packed-QKV projection → flash attention → output projection as ONE
    differentiable block in which every large contraction is a plain 2D
    GEMM over (tokens, features) folded views, and the flash kernels read
    q/k/v straight out of the packed projection buffer via window-offset
    index maps.

    Why it exists (PERF.md r3): composed from separate einsums, XLA's
    layout assignment inserts ~4.5 GB/step of conversion copies between
    the projection dots (whose multi-dim-contraction forward/transpose
    lowerings pick non-default layouts) and the Pallas kernels (which pin
    default layouts). Folding everything to 2D GEMMs leaves no layout
    freedom anywhere, and the hand-written VJP contracts dq/dk/dv against
    their weight windows separately — a packed dqkv is never materialized.

    ``x`` (b, s, H); ``w_qkv`` ((h + 2·h_kv)·d, H) packed q|k|v (heads
    contiguous per part); ``b_qkv`` ((h+2·h_kv)·d,); ``w_out`` (O, h·d).
    Returns (b, s, O) — the output-projection bias and (under tp) the
    partial-product reduce stay with the caller, matching
    ``RowParallelLinear``'s post-reduce bias order. Pallas-only (the
    caller gates on kernel eligibility). ``dropout_rate > 0`` applies
    in-kernel probs dropout (``dropout_seed`` required — pass None
    otherwise); masks regenerate in backward from the same counter hash
    (see ``pallas.attention.dropout_keep``). ``kv_lens`` (b,) int32 masks
    each batch row's kv positions >= its length (padded batches; pass
    None for full sequences). ``bias`` (hb, s, s) with hb | h: additive
    score bias read in-kernel (q-head row t reads bias row t % hb),
    differentiated (dbias = Σ_batch dS via the batch-innermost dbias
    kernel); pass None for unbiased attention."""
    y, _ = _fused_attn_fwd(x, w_qkv, b_qkv, w_out, bias, dropout_seed,
                           kv_lens, h, h_kv, d, scale, causal, dropout_rate)
    return y


def _fused_attn_fwd(x, w_qkv, b_qkv, w_out, bias, dropout_seed, kv_lens, h,
                    h_kv, d, scale, causal, dropout_rate=0.0):
    b, s, H = x.shape
    if isinstance(bias, BucketedBias):
        raise ValueError(
            "fused_qkv_attention takes a materialized (hb, s, s) bias; "
            "the bucketed form rides flash_attention(layout='bshd') (same "
            "kernels, separate projections)")
    if bias is not None:
        # same contract flash_attention enforces: a non-dividing hb would
        # pair heads with bias rows inconsistently across batches (the
        # kernels' t % hb map) and the dbias grid would silently drop rows
        if (bias.ndim != 3 or bias.shape[1:] != (s, s)
                or h % bias.shape[0]):
            raise ValueError(
                f"bias must be (hb, {s}, {s}) with hb dividing h ({h}); "
                f"got {bias.shape}")
    qkv = (jnp.dot(x.reshape(-1, H), w_qkv.T) + b_qkv).reshape(b, s, -1)
    # full_lse: keep the (b, h, s, LANES) lane carrier as the residual —
    # backward hands it straight back to the kernel (slicing lane 0 here
    # would force a re-broadcast there, one slice+broadcast pair per layer)
    o, lse = _k.flash_fwd_packed(
        qkv, h, h_kv, d, scale=scale, causal=causal, kv_lens=kv_lens,
        bias=bias, full_lse=True, interpret=_backend.interpret_mode(),
        dropout_rate=dropout_rate, dropout_seed=dropout_seed)
    # dead rows (kv_lens == 0): the kernel writes zero context rows and
    # zeros propagate through the projection — no extra masking needed
    y = jnp.dot(o.reshape(-1, h * d), w_out.T).reshape(b, s, -1)
    return y, (x, qkv, o, lse, w_qkv, w_out, bias, dropout_seed, kv_lens)


def _fused_attn_bwd(h, h_kv, d, scale, causal, dropout_rate, res, dy):
    x, qkv, o, lse, w_qkv, w_out, bias, dropout_seed, kv_lens = res
    b, s, H = x.shape
    T = b * s
    dy2 = dy.reshape(T, -1)
    o2 = o.reshape(T, h * d)
    dw_out = jnp.dot(dy2.T, o2)
    do = jnp.dot(dy2, w_out).reshape(b, s, h * d)
    out = _k.flash_bwd_packed(
        qkv, h, h_kv, d, o, lse, do, scale=scale, causal=causal,
        kv_lens=kv_lens, bias=bias, interpret=_backend.interpret_mode(),
        dropout_rate=dropout_rate, dropout_seed=dropout_seed)
    dq, dk, dv = out[:3]
    dbias = out[3].astype(bias.dtype) if bias is not None else None
    x2 = x.reshape(T, H)
    dq2 = dq.reshape(T, -1)
    dk2 = dk.reshape(T, -1)
    dv2 = dv.reshape(T, -1)
    wq = w_qkv[:h * d]
    wk = w_qkv[h * d:(h + h_kv) * d]
    wv = w_qkv[(h + h_kv) * d:]
    dx = (jnp.dot(dq2, wq) + jnp.dot(dk2, wk) + jnp.dot(dv2, wv)
          ).reshape(b, s, H)
    dw_qkv = jnp.concatenate(
        [jnp.dot(dq2.T, x2), jnp.dot(dk2.T, x2), jnp.dot(dv2.T, x2)], 0)
    db_qkv = jnp.concatenate(
        [jnp.sum(dq2, 0), jnp.sum(dk2, 0), jnp.sum(dv2, 0)])
    return dx, dw_qkv.astype(w_qkv.dtype), db_qkv.astype(w_qkv.dtype), \
        dw_out.astype(w_out.dtype), dbias, _float0_like(dropout_seed), \
        _float0_like(kv_lens)


fused_qkv_attention.defvjp(_fused_attn_fwd, _fused_attn_bwd)


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *, causal: bool = False, scale: Optional[float] = None,
    kv_lens: Optional[jax.Array] = None, bias: Optional[jax.Array] = None,
    impl: str = "auto", layout: str = "bhsd", dropout_rate: float = 0.0,
    dropout_seed: Optional[jax.Array] = None,
) -> jax.Array:
    """Blockwise attention over (..., seq, head_dim) with any number of
    leading batch/head dims. No sequence-length cap (cf. fmha's 512).
    HALF-class under O1 (attention is matmul-shaped; the in-kernel softmax
    accumulates fp32 regardless).

    Grouped-query / multi-query attention: k/v may carry FEWER heads than q
    — flattened leading dims must divide q's (e.g. q (b, 8, s, d) with kv
    (b, 2, s, d) is a group of 4; kv (b, 1, s, d) is MQA). The kernel reads
    each kv row once per group via its BlockSpec index map — kv is never
    repeated in HBM. A capability the reference's fixed-shape fmha kernels
    (seq≤512, equal heads) cannot express.

    ``kv_lens``: per-row valid kv length over q's leading dims (padded
    batches) — positions >= the length are masked out; the compute of KV
    blocks entirely past it is skipped dynamically in-kernel (their
    HBM→VMEM copies still run — BlockSpec DMA is unconditional), so ragged
    batches save MXU time but not block DMA. Rows with length 0 return
    zeros. Composes with ``causal``. Passing ``kv_lens=None`` compiles
    kernels with no varlen operand or masking at all. (The reference's
    fused softmax takes a full (b,1,sq,sk) mask tensor; a length vector
    expresses the padded-batch case in O(rows) and keeps the flash memory
    profile.)

    ``impl='auto'`` picks the Pallas kernel from seq >= 1024, or from
    seq >= 512 when head_dim >= 128 (full MXU lanes lower the kernel's
    break-even): below the crossover the grid/launch overhead outweighs the
    saved score-tensor HBM traffic and XLA's batched-matmul composition of
    the same math (still recompute-in-backward via this function's
    custom_vjp — O(s) residuals) is faster on v5e-class chips. Measured
    end-to-end on GPT-medium train steps (v5e): d=64 S=1024 pallas 248.7
    vs xla 264.6 ms/step; d=128 S=512 163.4 vs 170.1 (kernel wins), S=256
    165.8 vs 158.7 (xla wins). Isolated-kernel timings through the remote
    tunnel had previously suggested a 4096 crossover — the full-step
    measurement (where the kernel competes with everything else for HBM)
    is the one that matters.

    ``layout='bshd'``: operands are (batch, seq, heads, head_dim) — the
    seq-major layout the QKV projection GEMMs naturally emit. The Pallas
    kernels read it via head-strided index maps, so NO layout-conversion
    copies sit between the projections and the kernels (the bh-flat layout
    cost the flagship ~4.5 GB/step of pure copies — PERF.md r3). Prefer it
    whenever q/k/v come straight from a (tokens, features) GEMM. In this
    layout ``kv_lens`` is PER BATCH ((b,) int32 — heads share a row's
    padding), which is both the padded-batch reality and what the
    kernels' head-folded index maps consume with zero expansion.

    ``dropout_rate > 0`` applies IN-KERNEL probs dropout (the reference's
    fused-attention capability, ``apex/contrib/csrc/fmha/fmha_api.cpp:44``):
    masks come from a stateless counter hash of (seed, head, row, col) —
    O(block) memory, regenerated in backward, bit-identical between the
    Pallas and XLA dispatches, deterministic per ``dropout_seed`` (int32
    scalar, required). The softmax normalizer is computed pre-dropout
    (standard probs-dropout semantics: E[output] = no-dropout output).
    The realized drop probability is ``dropout_rate`` quantized to the
    nearest multiple of 2^-24 (the hash compares in a 24-bit integer
    domain) — sub-1e-7 rates round to off.

    ``bias`` (hb, sq, sk): an arbitrary ADDITIVE score bias applied
    IN-KERNEL — the reference's fused-mask capability
    (``csrc/megatron/scaled_masked_softmax.cpp:85-94`` applies a
    per-batch mask fused with scale+softmax; the additive ``attn_mask``
    variants of ``contrib/multihead_attn/self_multihead_attn.py:144-198``)
    generalized: T5 relative position bias, ALiBi slopes, additive
    attention masks all ride the same operand. Row ``r`` of the flattened
    (batch·heads) leading dims reads bias row ``r % hb`` — so (h, sq, sk)
    is a per-head bias shared over batch (the T5 case), (1, sq, sk) a
    broadcast bias, (b·h, sq, sk) fully per-row. Added to the scaled
    scores BEFORE causal/kv_lens masks; differentiable (dbias = Σ over
    the sharing rows of dS, computed by a third, batch-innermost backward
    kernel — ~2 extra GEMM passes, paid only when bias is given).
    Composes with causal, kv_lens, dropout, GQA, and both layouts (with
    ``layout='bshd'`` hb must divide h)."""
    q, k, v = apply_op_rules("attention", q, k, v)
    if layout not in ("bhsd", "bshd"):
        raise ValueError(f"layout must be bhsd|bshd, got {layout!r}")
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got "
                         f"{dropout_rate}")
    if dropout_rate > 0.0:
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires dropout_seed")
        dropout_seed = jnp.asarray(dropout_seed, jnp.int32)
    else:
        dropout_seed = None
    if isinstance(bias, BucketedBias):
        _validate_bucketed(bias)
    elif bias is not None:
        sq_, sk_ = q.shape[-2], k.shape[-2]
        if layout == "bshd":
            sq_, sk_ = q.shape[1], k.shape[1]
        if bias.ndim != 3 or bias.shape[1:] != (sq_, sk_):
            raise ValueError(
                f"bias must be (hb, sq, sk) = (hb, {sq_}, {sk_}); got "
                f"{bias.shape}")
    if layout == "bshd":
        if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
            raise ValueError(
                f"layout='bshd' takes (b, s, h, d) operands; got "
                f"{q.shape} / {k.shape}")
        if causal and q.shape[1] > k.shape[1]:
            raise ValueError(
                f"causal attention requires sq <= sk; got sq={q.shape[1]} "
                f"> sk={k.shape[1]}")
        if q.shape[2] % k.shape[2] or k.shape[:2] != v.shape[:2]:
            raise ValueError(
                f"kv heads ({k.shape[2]}) must divide q heads "
                f"({q.shape[2]}) with matching batch/seq dims")
        d = q.shape[-1]
        s_scale = float(scale if scale is not None else 1.0 / d ** 0.5)
        if kv_lens is not None:
            # per-BATCH lengths (heads share a row's padding) — the (b,)
            # form the kernels' t//h index maps consume directly
            if kv_lens.shape != (q.shape[0],):
                raise ValueError(
                    f"layout='bshd' takes per-batch kv_lens of shape "
                    f"({q.shape[0]},); got {kv_lens.shape}")
            kv_lens = kv_lens.astype(jnp.int32)
        if bias is not None and q.shape[2] % _bias_rows(bias):
            raise ValueError(
                f"layout='bshd' needs bias rows ({_bias_rows(bias)}) "
                f"dividing q heads ({q.shape[2]})")
        ok = bshd_kernel_ok(q.shape[1], k.shape[1], q.shape[2], d, q.dtype)
        impl_ = impl
        if (impl_ == "auto" and k.shape[1] < flash_auto_crossover(d)
                and not _backend.interpret_forced()):
            impl_ = "xla"
        use_pallas = _backend.choose_impl(impl_, ok) == "pallas"
        return _flash_core_bshd(q, k, v, bias, kv_lens, dropout_seed,
                                s_scale, causal, use_pallas, dropout_rate)
    d = q.shape[-1]
    if causal and q.shape[-2] > k.shape[-2]:
        # bottom-right-aligned causal with sq > sk gives the first
        # (sq - sk) q rows ZERO visible keys — their softmax is undefined
        # (the kernel would emit exp(0)-weighted garbage). No attention
        # semantics wants this; reject instead of returning garbage.
        raise ValueError(
            f"causal attention requires sq <= sk (bottom-right alignment); "
            f"got sq={q.shape[-2]} > sk={k.shape[-2]} — rows before the "
            f"context start would attend nothing")
    scale = float(scale if scale is not None else 1.0 / d ** 0.5)
    lead = q.shape[:-2]
    q3 = q.reshape(-1, q.shape[-2], d)
    k3 = k.reshape(-1, k.shape[-2], d)
    v3 = v.reshape(-1, v.shape[-2], d)
    if k.shape[:-2] != v.shape[:-2]:
        raise ValueError(f"k/v leading dims differ: {k.shape} vs {v.shape}")
    if q.ndim >= 4:
        # batch dims must MATCH; only the head axis (last leading dim) may
        # be narrower on kv — a flattened-ratio check alone would accept a
        # mismatched batch dim and silently pair q rows with wrong batches
        if (q.shape[:-3] != k.shape[:-3]
                or q.shape[-3] % k.shape[-3]):
            raise ValueError(
                f"kv heads ({k.shape[-3]}) must divide q heads "
                f"({q.shape[-3]}) with equal batch dims "
                f"({q.shape[:-3]} vs {k.shape[:-3]}) for grouped-query "
                f"attention")
    elif q3.shape[0] % k3.shape[0]:
        raise ValueError(
            f"kv heads ({k3.shape[0]} flattened) must divide q heads "
            f"({q3.shape[0]} flattened) for grouped-query attention")
    ok = (
        q3.shape[-2] % 128 == 0 and k3.shape[-2] % 128 == 0
        and (d % 128 == 0 or d == 64)
        # the Mosaic dialect has no f16: strict-fp16 runs (half_dtype=
        # float16) take the XLA composition — measured on hardware, see
        # PERF.md "fp16-strict" (bf16 is the TPU half type; fp16 pays
        # this kernel tax on top of its scaler requirement)
        and q.dtype != jnp.float16
    )
    if (impl == "auto" and k3.shape[-2] < flash_auto_crossover(d)
            and not _backend.interpret_forced()):
        impl = "xla"  # grid overhead beats saved score traffic below this
    use_pallas = _backend.choose_impl(impl, ok) == "pallas"
    if kv_lens is not None:
        if kv_lens.shape != lead:
            raise ValueError(
                f"kv_lens shape {kv_lens.shape} must equal q's leading dims "
                f"{lead}")
        # int32 before the custom_vjp: backward returns a float0 cotangent,
        # which JAX only accepts for integer primals
        kv_lens = kv_lens.reshape(-1).astype(jnp.int32)
    if bias is not None and q3.shape[0] % _bias_rows(bias):
        raise ValueError(
            f"bias rows ({_bias_rows(bias)}) must divide q's flattened "
            f"leading dims ({q3.shape[0]})")
    o = _flash_core(q3, k3, v3, bias, kv_lens, dropout_seed, scale, causal,
                    use_pallas, dropout_rate)
    return o.reshape(*lead, q.shape[-2], d)


# --- ring attention (context parallel) ---------------------------------------

def zigzag_indices(cp: int, s: int):
    """The zigzag (striped) sequence permutation for causal context
    parallelism: the sequence is cut into ``2·cp`` stripes and device ``r``
    holds stripes ``(r, 2cp−1−r)`` — pairing an early stripe (little causal
    work) with a late one (much) so every rank's total is equal. Returns
    ``order`` such that ``x[order]`` laid out contiguously and sharded over
    ``cp`` gives each device its stripe pair, plus the inverse."""
    import numpy as np
    if s % (2 * cp):
        raise ValueError(f"sequence ({s}) must divide into 2*cp ({2 * cp}) "
                         "stripes for zigzag sharding")
    stripe = s // (2 * cp)
    order = np.concatenate([
        np.r_[r * stripe:(r + 1) * stripe,
              (2 * cp - 1 - r) * stripe:(2 * cp - r) * stripe]
        for r in range(cp)
    ])
    inverse = np.argsort(order)
    return order, inverse


def zigzag_shard(x: jax.Array, cp: int, seq_axis: int = -2) -> jax.Array:
    """Permute ``seq_axis`` into zigzag order (host/global side; shard the
    result contiguously over the cp mesh axis)."""
    order, _ = zigzag_indices(cp, x.shape[seq_axis])
    return jnp.take(x, jnp.asarray(order), axis=seq_axis)


def zigzag_unshard(x: jax.Array, cp: int, seq_axis: int = -2) -> jax.Array:
    """Inverse of :func:`zigzag_shard`."""
    _, inverse = zigzag_indices(cp, x.shape[seq_axis])
    return jnp.take(x, jnp.asarray(inverse), axis=seq_axis)


def seed_from_key(key) -> jax.Array:
    """The int32 dropout seed for the counter-hash dropout family from a
    ``jax.random`` PRNG key. One place so every module (GPT blocks,
    contrib MHA, ...) derives seeds identically — the mapping is the
    cross-module determinism contract for the in-kernel masks."""
    return jax.lax.bitcast_convert_type(
        jax.random.bits(key, (), jnp.uint32), jnp.int32)


def fold_dropout_seed(seed, *ids):
    """Derive a decorrelated int32 dropout seed from ``seed`` and integer
    identifiers (cp rank, ring step, piece index, ...) via the same fmix32
    avalanche the mask hash uses. Deterministic, traced-friendly; the
    tool that lets distributed attention give every (shard, step, piece)
    its own mask stream while forward and backward re-derive identical
    seeds."""
    h = jnp.asarray(seed).astype(jnp.uint32)
    for i in ids:
        h = _k._fmix32(h ^ (jnp.asarray(i).astype(jnp.uint32)
                            * jnp.uint32(0x9E3779B9)))
    return jax.lax.bitcast_convert_type(h, jnp.int32)


def _piece_seed(dropout_seed, rank, t, piece):
    """The ring's per-(rank, step, piece) mask-stream fold — ONE
    definition so forward and the hand-written backward can never drift
    apart (bit-identical folds are the gradient-correctness contract)."""
    if dropout_seed is None:
        return None
    return fold_dropout_seed(dropout_seed, rank, t, piece)


def _piece_fwd(q, k, v, scale, causal, use_pallas, dropout_rate=0.0,
               dropout_seed=None, kv_lens=None, bias=None):
    """(o, lse) of one attention piece through the flash kernel (or the XLA
    composition below its crossover). ``kv_lens``/``bias`` are this
    PIECE's window-local operands (lengths clipped to the piece's kv
    window; a :class:`BucketedBias` with the piece's global offsets).
    Rows whose window is EMPTY come back with lse == NEG_INF — the
    single-kernel dead-row lse=0 is an *output* convention; inside the
    ring's online-softmax fold it would weight a dead piece e^0."""
    o, res = _flash_fwd_res(q, k, v, bias, kv_lens, dropout_seed, scale,
                            causal, use_pallas, dropout_rate)
    lse = res[4]
    if lse.ndim == 3:  # pallas (bh, s, LANES) carrier → (bh, s) rows
        lse = lse[..., 0]
    if kv_lens is not None:
        lse = jnp.where(kv_lens[:, None] > 0, lse, _k.NEG_INF)
    return o, lse


def _piece_fwd_bshd(q, k, v, scale, causal, use_pallas, dropout_rate=0.0,
                    dropout_seed=None, kv_lens=None, bias=None):
    """(o (b, s, h, d), lse (b, h, s)) of one seq-major piece — the
    bshd-layout twin of :func:`_piece_fwd` (kernels read the projection
    GEMMs' natural layout; no transpose round trip per ring step).
    ``kv_lens`` is the piece-window (b,) form; dead-piece rows get
    lse == NEG_INF (see :func:`_piece_fwd`)."""
    o, res = _flash_fwd_res_bshd(q, k, v, bias, kv_lens, dropout_seed,
                                 scale, causal, use_pallas, dropout_rate)
    lse = res[4]
    # the pallas path returns the (b, h, s, LANES) carrier; the ring's
    # fold arithmetic runs on the sliced (b, h, s) row form
    lse = lse[..., 0] if lse.ndim == 4 else lse
    if kv_lens is not None:
        lse = jnp.where(kv_lens[:, None, None] > 0, lse, _k.NEG_INF)
    return o, lse


def _fold(o1, l1, o2, l2, bshd=False):
    """Merge two normalized attention pieces over the same q rows:
    (o, lse) ⊕ (o, lse) → (o, lse), the online-softmax combine. With
    ``bshd``, o is (b, s, h, d) and lse (b, h, s) — the weights transpose
    to the seq-major broadcast."""
    m = jnp.maximum(l1, l2)
    e1 = jnp.exp(l1 - m)
    e2 = jnp.exp(l2 - m)
    tot = e1 + e2
    w1, w2 = e1 / tot, e2 / tot
    if bshd:
        w1 = w1.transpose(0, 2, 1)[..., None]
        w2 = w2.transpose(0, 2, 1)[..., None]
    else:
        w1, w2 = w1[..., None], w2[..., None]
    o = o1 * w1 + o2.astype(jnp.float32) * w2
    return o, m + jnp.log(tot)


def _piece_lens(kv_lens, k_off, extent):
    """This piece's kv window lengths: global valid lengths clipped to a
    kv window starting at global position ``k_off`` with ``extent``
    columns — how the per-row/per-batch ``kv_lens`` operand rides any
    sequence sharding (a position is valid iff its GLOBAL index is below
    the row's length)."""
    if kv_lens is None:
        return None
    return jnp.clip(kv_lens - k_off, 0, extent)


def _zigzag_pair_lens(kv_lens, a_off, b_off, ss):
    """Valid kv count of the CONCATENATED zigzag stripe pair [a; b]: the
    pair is position-monotonic (a < b), so the globally-valid positions
    form a local PREFIX and a single per-row length expresses them."""
    if kv_lens is None:
        return None
    return (jnp.clip(kv_lens - a_off, 0, ss)
            + jnp.clip(kv_lens - b_off, 0, ss))


def _ring_fwd_impl(q, k, v, axis_name, scale, causal, use_pallas,
                   dropout_rate=0.0, dropout_seed=None, bshd=False,
                   kv_lens=None, bias=None):
    """Layout-generic ring forward: ``bshd=False`` takes (bh, s, d)
    operands with lse (bh, s); ``bshd=True`` takes (b, s, h, d) with lse
    (b, h, s) — the seq axis is 1 either way, only the lse carrier and
    the piece/fold functions differ (the bshd kernels read the projection
    GEMMs' layout directly, removing the per-ring-step transpose round
    trip the flat layout paid).

    ``kv_lens`` (global per-row/per-batch valid lengths) and ``bias`` (a
    :class:`BucketedBias`) ride per piece: every piece knows its kv
    window's GLOBAL start, so lengths clip to the window
    (:func:`_piece_lens`) and the bias recomputes in-kernel from the
    window's offsets (:meth:`BucketedBias.shifted`). With bias under
    causal zigzag, step 0 decomposes into its three stripe pieces
    (lo·lo causal, hi·hi causal, hi·lo full) — the concatenated pair is
    position-monotonic but not position-CONTIGUOUS, which a mask
    tolerates and an offset-pair does not."""
    cp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % cp) for i in range(cp)]
    piece = _piece_fwd_bshd if bshd else _piece_fwd
    lse_ax = 2 if bshd else 1
    s_loc = q.shape[1]

    def pseed(t, piece_id):
        # each (q, k) pair is covered by exactly one piece, so the
        # per-piece streams stay i.i.d. Bernoulli globally
        return _piece_seed(dropout_seed, rank, t, piece_id)

    def pb(q_off, k_off):
        return None if bias is None else bias.shifted(q_off, k_off)

    def rotate(t):
        return jax.tree.map(
            lambda x: jax.lax.ppermute(x, axis_name, perm), t)

    def pin_dead(lse):
        # GLOBALLY-dead rows (kv_lens == 0): every piece folded in at
        # lse == NEG_INF, so the accumulated lse is ~NEG_INF — pin it to
        # 0 (the single-kernel dead-row convention) AFTER all folds, so
        # backward's p = exp(NEG_INF − 0) underflows to 0 on every piece
        # (with lse ≈ NEG_INF it would be exp(0) = 1: garbage dq/dk/dv
        # for padded-out rows)
        if kv_lens is None:
            return lse
        live = kv_lens > 0
        return jnp.where(live[:, None, None] if bshd else live[:, None],
                         lse, 0.0)

    if not causal:
        # contiguous sharding: shard r holds global rows [r·s_loc, ...)
        q_off = rank * s_loc
        o0, l0 = piece(q, k, v, scale, False, use_pallas,
                       dropout_rate, pseed(0, 0),
                       kv_lens=_piece_lens(kv_lens, q_off, s_loc),
                       bias=pb(q_off, q_off))

        def step(carry, t):
            o_acc, l_acc, kv = carry
            kv = rotate(kv)
            k_off = ((rank - t) % cp) * s_loc
            oi, li = piece(q, kv[0], kv[1], scale, False, use_pallas,
                           dropout_rate, pseed(t, 0),
                           kv_lens=_piece_lens(kv_lens, k_off, s_loc),
                           bias=pb(q_off, k_off))
            o_acc, l_acc = _fold(o_acc, l_acc, oi, li, bshd)
            return (o_acc, l_acc, kv), None

        (o_acc, l_acc, _), _ = jax.lax.scan(
            step, (o0.astype(jnp.float32), l0, (k, v)),
            jnp.arange(1, cp), length=cp - 1)
        return o_acc.astype(q.dtype), pin_dead(l_acc)

    ss = s_loc // 2
    # zigzag stripe pair: rank r holds stripes (r, 2cp−1−r) of 2·cp
    a_off = rank * ss
    b_off = (2 * cp - 1 - rank) * ss
    lhalf = lambda l: (jax.lax.slice_in_dim(l, 0, ss, axis=lse_ax),  # noqa: E731
                       jax.lax.slice_in_dim(l, ss, 2 * ss, axis=lse_ax))
    q_lo, q_hi = q[:, :ss], q[:, ss:]

    # step 0 — the local stripe pair. Without bias: ONE causal flash over
    # the position-monotonic pair (local causal == global causal; varlen
    # valid positions form a local prefix, _zigzag_pair_lens). With bias:
    # the three stripe pieces, each position-contiguous with its own
    # global offsets.
    if bias is None:
        o0, l0 = piece(q, k, v, scale, True, use_pallas,
                       dropout_rate, pseed(0, 0),
                       kv_lens=_zigzag_pair_lens(kv_lens, a_off, b_off, ss))
        l0_lo, l0_hi = lhalf(l0)
        o_lo0, l_lo0 = o0[:, :ss].astype(jnp.float32), l0_lo
        o_hi0, l_hi0 = o0[:, ss:].astype(jnp.float32), l0_hi
    else:
        k_lo0, k_hi0 = k[:, :ss], k[:, ss:]
        v_lo0, v_hi0 = v[:, :ss], v[:, ss:]
        o_ll, l_ll = piece(q_lo, k_lo0, v_lo0, scale, True, use_pallas,
                           dropout_rate, pseed(0, 0),
                           kv_lens=_piece_lens(kv_lens, a_off, ss),
                           bias=pb(a_off, a_off))
        o_hh, l_hh = piece(q_hi, k_hi0, v_hi0, scale, True, use_pallas,
                           dropout_rate, pseed(0, 1),
                           kv_lens=_piece_lens(kv_lens, b_off, ss),
                           bias=pb(b_off, b_off))
        o_hl, l_hl = piece(q_hi, k_lo0, v_lo0, scale, False, use_pallas,
                           dropout_rate, pseed(0, 2),
                           kv_lens=_piece_lens(kv_lens, a_off, ss),
                           bias=pb(b_off, a_off))
        o_lo0, l_lo0 = o_ll.astype(jnp.float32), l_ll
        o_hi0, l_hi0 = _fold(o_hh, l_hh, o_hl, l_hl, bshd)

    def step(carry, t):
        o_lo, l_lo, o_hi, l_hi, kv = carry
        kv = rotate(kv)
        kk, vv = kv
        k_lo, k_hi = kk[:, :ss], kk[:, ss:]
        v_lo, v_hi = vv[:, :ss], vv[:, ss:]
        j = (rank - t) % cp
        ja, jb = j * ss, (2 * cp - 1 - j) * ss
        # piece 1: this rank's HIGH stripe vs the arriving LOW stripe —
        # always a full (unmasked) attend (stripe j < cp <= 2cp−1−rank)
        o1, l1 = piece(q_hi, k_lo, v_lo, scale, False, use_pallas,
                       dropout_rate, pseed(t, 1),
                       kv_lens=_piece_lens(kv_lens, ja, ss),
                       bias=pb(b_off, ja))
        o_hi, l_hi = _fold(o_hi, l_hi, o1, l1, bshd)
        # piece 2: j < rank → our LOW stripe sees their LOW stripe;
        # j > rank → our HIGH stripe sees their HIGH stripe. Both full
        # attends — zigzag leaves no partially- or fully-masked work.
        lo_case = j < rank
        q2 = jnp.where(lo_case, q_lo, q_hi)
        k2 = jnp.where(lo_case, k_lo, k_hi)
        v2 = jnp.where(lo_case, v_lo, v_hi)
        qo2 = jnp.where(lo_case, a_off, b_off)
        ko2 = jnp.where(lo_case, ja, jb)
        o2, l2 = piece(q2, k2, v2, scale, False, use_pallas,
                       dropout_rate, pseed(t, 2),
                       kv_lens=_piece_lens(kv_lens, ko2, ss),
                       bias=pb(qo2, ko2))
        o_lo2, l_lo2 = _fold(o_lo, l_lo, o2, l2, bshd)
        o_hi2, l_hi2 = _fold(o_hi, l_hi, o2, l2, bshd)
        o_lo = jnp.where(lo_case, o_lo2, o_lo)
        l_lo = jnp.where(lo_case, l_lo2, l_lo)
        o_hi = jnp.where(lo_case, o_hi, o_hi2)
        l_hi = jnp.where(lo_case, l_hi, l_hi2)
        return (o_lo, l_lo, o_hi, l_hi, kv), None

    init = (o_lo0, l_lo0, o_hi0, l_hi0, (k, v))
    (o_lo, l_lo, o_hi, l_hi, _), _ = jax.lax.scan(
        step, init, jnp.arange(1, cp), length=cp - 1)
    o = jnp.concatenate([o_lo, o_hi], axis=1).astype(q.dtype)
    lse = jnp.concatenate([l_lo, l_hi], axis=lse_ax)
    return o, pin_dead(lse)


def _ring_bwd_impl(q, k, v, o, lse, do, axis_name, scale, causal,
                   use_pallas, dropout_rate=0.0, dropout_seed=None,
                   bshd=False, kv_lens=None, bias=None):
    """The distributed flash backward: per ring step call ``flash_bwd``
    with the GLOBAL (o, lse) — p and Δ are then exact per shard — while a
    dkv accumulator travels the ring with its kv shard and arrives home
    after a full cycle carrying every rank's contribution (the reference
    has no CP at all; this is the standard ring-attention backward).
    Dropout: each piece re-derives the SAME (rank, step, piece) seed fold
    as forward, so masks regenerate exactly. ``kv_lens``/``bias``: each
    piece re-derives the SAME window lens/offsets as forward; the
    bucket-table cotangent accumulates across pieces into a FOURTH return
    (fp32, this rank's partial — the caller psums it over the cp axis:
    the global dS decomposes disjointly over (rank, step, piece)).
    Returns (dq, dk, dv, dtable-or-None)."""
    cp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % cp) for i in range(cp)]
    lse_ax = 2 if bshd else 1
    s_loc = q.shape[1]

    def piece_bwd(qq, kk, vv, oo, ll, ddo, caus, sd, lens=None, pbias=None):
        # both layouts return the RAW fp32 bucket-table grad (no cast to
        # the table dtype between pieces — the cp·3 partials accumulate
        # full-precision, matching the single-chip cast-once-at-the-end)
        impl = _flash_bwd_bshd_impl if bshd else _flash_bwd_impl
        return impl(qq, kk, vv, oo, ll, ddo, lens, scale, caus,
                    use_pallas, dropout_rate, sd, pbias)

    def pseed(t, piece):
        return _piece_seed(dropout_seed, rank, t, piece)

    def pb(q_off, k_off):
        return None if bias is None else bias.shifted(q_off, k_off)

    def rotate_tree(t):
        return jax.tree.map(
            lambda x: jax.lax.ppermute(x, axis_name, perm), t)

    # dtable accumulator: the bias-less ring carries a scalar dummy so the
    # scan carry structure stays uniform (dead weight of one float)
    dt0 = (jnp.zeros(bias.table.shape, jnp.float32) if bias is not None
           else jnp.zeros((), jnp.float32))

    def dt_add(acc, dbi):
        return acc if dbi is None else acc + dbi.astype(jnp.float32)

    if not causal:
        q_off = rank * s_loc
        dq0, dk0, dv0, db0 = piece_bwd(
            q, k, v, o, lse, do, False, pseed(0, 0),
            lens=_piece_lens(kv_lens, q_off, s_loc), pbias=pb(q_off, q_off))
        dt0 = dt_add(dt0, db0)

        def step(carry, t):
            dq, kv, dk, dv, dt = carry
            kv, (dk, dv) = rotate_tree(kv), rotate_tree((dk, dv))
            k_off = ((rank - t) % cp) * s_loc
            dqi, dki, dvi, dbi = piece_bwd(
                q, kv[0], kv[1], o, lse, do, False, pseed(t, 0),
                lens=_piece_lens(kv_lens, k_off, s_loc),
                pbias=pb(q_off, k_off))
            return (dq + dqi, kv, dk + dki.astype(dk.dtype),
                    dv + dvi.astype(dv.dtype), dt_add(dt, dbi)), None

        init = (dq0.astype(jnp.float32), (k, v),
                dk0.astype(jnp.float32), dv0.astype(jnp.float32), dt0)
        (dq, _, dk, dv, dt), _ = jax.lax.scan(step, init, jnp.arange(1, cp),
                                              length=cp - 1)
        dk, dv = rotate_tree((dk, dv))  # final hop brings accumulators home
        return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
                dt if bias is not None else None)

    ss = s_loc // 2
    a_off = rank * ss
    b_off = (2 * cp - 1 - rank) * ss
    halves = lambda x: (x[:, :ss], x[:, ss:])
    lhalf = lambda l: (jax.lax.slice_in_dim(l, 0, ss, axis=lse_ax),  # noqa: E731
                       jax.lax.slice_in_dim(l, ss, 2 * ss, axis=lse_ax))
    q_lo, q_hi = halves(q)
    o_lo, o_hi = halves(o)
    l_lo, l_hi = lhalf(lse)
    do_lo, do_hi = halves(do)
    f32 = jnp.float32

    if bias is None:
        dq0, dk0, dv0, _ = piece_bwd(
            q, k, v, o, lse, do, True, pseed(0, 0),
            lens=_zigzag_pair_lens(kv_lens, a_off, b_off, ss))
        dq_lo0, dq_hi0 = dq0[:, :ss].astype(f32), dq0[:, ss:].astype(f32)
        dk_lo0, dk_hi0 = dk0[:, :ss].astype(f32), dk0[:, ss:].astype(f32)
        dv_lo0, dv_hi0 = dv0[:, :ss].astype(f32), dv0[:, ss:].astype(f32)
    else:
        # the forward's three stripe pieces, mirrored (same seeds/windows)
        k_lo0, k_hi0 = halves(k)
        v_lo0, v_hi0 = halves(v)
        dqll, dkll, dvll, dbll = piece_bwd(
            q_lo, k_lo0, v_lo0, o_lo, l_lo, do_lo, True, pseed(0, 0),
            lens=_piece_lens(kv_lens, a_off, ss), pbias=pb(a_off, a_off))
        dqhh, dkhh, dvhh, dbhh = piece_bwd(
            q_hi, k_hi0, v_hi0, o_hi, l_hi, do_hi, True, pseed(0, 1),
            lens=_piece_lens(kv_lens, b_off, ss), pbias=pb(b_off, b_off))
        dqhl, dkhl, dvhl, dbhl = piece_bwd(
            q_hi, k_lo0, v_lo0, o_hi, l_hi, do_hi, False, pseed(0, 2),
            lens=_piece_lens(kv_lens, a_off, ss), pbias=pb(b_off, a_off))
        dq_lo0 = dqll.astype(f32)
        dq_hi0 = dqhh.astype(f32) + dqhl.astype(f32)
        dk_lo0 = dkll.astype(f32) + dkhl.astype(f32)
        dk_hi0 = dkhh.astype(f32)
        dv_lo0 = dvll.astype(f32) + dvhl.astype(f32)
        dv_hi0 = dvhh.astype(f32)
        dt0 = dt_add(dt_add(dt_add(dt0, dbll), dbhh), dbhl)

    def step(carry, t):
        dq_lo, dq_hi, kv, dk_lo, dk_hi, dv_lo, dv_hi, dt = carry
        kv = rotate_tree(kv)
        dk_lo, dk_hi, dv_lo, dv_hi = rotate_tree(
            (dk_lo, dk_hi, dv_lo, dv_hi))
        kk, vv = kv
        k_lo, k_hi = halves(kk)
        v_lo, v_hi = halves(vv)
        j = (rank - t) % cp
        ja, jb = j * ss, (2 * cp - 1 - j) * ss
        # piece 1 (mirror of forward): q_hi vs arriving kv_lo, full attend
        dq1, dk1, dv1, db1 = piece_bwd(
            q_hi, k_lo, v_lo, o_hi, l_hi, do_hi, False, pseed(t, 1),
            lens=_piece_lens(kv_lens, ja, ss), pbias=pb(b_off, ja))
        dq_hi = dq_hi + dq1
        dk_lo = dk_lo + dk1
        dv_lo = dv_lo + dv1
        dt = dt_add(dt, db1)
        # piece 2: the selected stripe pair
        lo_case = j < rank
        q2 = jnp.where(lo_case, q_lo, q_hi)
        o2 = jnp.where(lo_case, o_lo, o_hi)
        l2 = jnp.where(lo_case, l_lo, l_hi)
        do2 = jnp.where(lo_case, do_lo, do_hi)
        k2 = jnp.where(lo_case, k_lo, k_hi)
        v2 = jnp.where(lo_case, v_lo, v_hi)
        qo2 = jnp.where(lo_case, a_off, b_off)
        ko2 = jnp.where(lo_case, ja, jb)
        dq2, dk2, dv2, db2 = piece_bwd(
            q2, k2, v2, o2, l2, do2, False, pseed(t, 2),
            lens=_piece_lens(kv_lens, ko2, ss), pbias=pb(qo2, ko2))
        dq_lo = dq_lo + jnp.where(lo_case, dq2, 0.0)
        dq_hi = dq_hi + jnp.where(lo_case, 0.0, dq2)
        dk_lo = dk_lo + jnp.where(lo_case, dk2, 0.0)
        dk_hi = dk_hi + jnp.where(lo_case, 0.0, dk2)
        dv_lo = dv_lo + jnp.where(lo_case, dv2, 0.0)
        dv_hi = dv_hi + jnp.where(lo_case, 0.0, dv2)
        dt = dt_add(dt, db2)
        return (dq_lo, dq_hi, kv, dk_lo, dk_hi, dv_lo, dv_hi, dt), None

    init = (dq_lo0, dq_hi0, (k, v), dk_lo0, dk_hi0, dv_lo0, dv_hi0, dt0)
    (dq_lo, dq_hi, _, dk_lo, dk_hi, dv_lo, dv_hi, dt), _ = jax.lax.scan(
        step, init, jnp.arange(1, cp), length=cp - 1)
    dk_lo, dk_hi, dv_lo, dv_hi = rotate_tree((dk_lo, dk_hi, dv_lo, dv_hi))
    dq = jnp.concatenate([dq_lo, dq_hi], axis=1).astype(q.dtype)
    dk = jnp.concatenate([dk_lo, dk_hi], axis=1).astype(k.dtype)
    dv = jnp.concatenate([dv_lo, dv_hi], axis=1).astype(v.dtype)
    return dq, dk, dv, (dt if bias is not None else None)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11))
def _ring_core(q, k, v, bias, kv_lens, dropout_seed, axis_name, scale,
               causal, use_pallas, dropout_rate, bshd):
    o, _ = _ring_fwd_impl(q, k, v, axis_name, scale, causal, use_pallas,
                          dropout_rate, dropout_seed, bshd, kv_lens, bias)
    return o


def _ring_fwd(q, k, v, bias, kv_lens, dropout_seed, axis_name, scale,
              causal, use_pallas, dropout_rate, bshd):
    o, lse = _ring_fwd_impl(q, k, v, axis_name, scale, causal, use_pallas,
                            dropout_rate, dropout_seed, bshd, kv_lens, bias)
    return o, (q, k, v, o, lse, bias, kv_lens, dropout_seed)


def _ring_bwd(axis_name, scale, causal, use_pallas, dropout_rate, bshd,
              res, do):
    q, k, v, o, lse, bias, kv_lens, dropout_seed = res
    dq, dk, dv, dtab = _ring_bwd_impl(
        q, k, v, o, lse, do, axis_name, scale, causal, use_pallas,
        dropout_rate, dropout_seed, bshd, kv_lens, bias)
    dbias = None
    if bias is not None:
        # this rank's partial — every (rank, step, piece) covers a
        # disjoint slice of the global score matrix, so the global table
        # grad is the plain cp-sum (each rank returns the full value: the
        # table is replicated, like the ring's traveling dkv convention)
        dtab = jax.lax.psum(dtab, axis_name)
        dbias = _bias_cotangent(bias, dtab)
    return (dq, dk, dv, dbias, _float0_like(kv_lens),
            _float0_like(dropout_seed))


_ring_core.defvjp(_ring_fwd, _ring_bwd)


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *, axis_name: str = mesh_lib.CONTEXT_AXIS, causal: bool = False,
    scale: Optional[float] = None, impl: str = "auto",
    layout: str = "bhsd", kv_lens: Optional[jax.Array] = None,
    bias: Optional[BucketedBias] = None,
    dropout_rate: float = 0.0, dropout_seed: Optional[jax.Array] = None,
) -> jax.Array:
    """Attention over a sequence sharded along ``axis_name``: q/k/v are this
    device's (bh, s_local, d) shard — or, with ``layout='bshd'``, the
    seq-major (b, s_local, h, d) shard the projection GEMMs emit, which
    the kernels read with NO transpose round trip per ring step (the same
    layout economics as ``flash_attention(layout='bshd')``; requires the
    bshd tiling rule — head_dim 128 class). The full sequence is
    cp·s_local. Must run inside shard_map with the axis bound.

    Built on the flash kernel family: per ring step the arriving KV shard
    goes through :func:`_piece_fwd` (the Pallas kernel above its measured
    crossover) and the normalized (o, lse) pieces merge by the
    online-softmax fold — per-step memory is O(s_local·d); no (s_local ×
    s_local) score tensor ever exists outside kernel VMEM. Backward is the
    distributed flash backward (:func:`_ring_bwd_impl`): kv re-rotates the
    ring while a dkv accumulator travels with each shard, so residuals are
    O(s_local·d) too.

    ``causal=True`` REQUIRES the zigzag stripe layout: shard
    ``zigzag_shard(x, cp)`` over the axis (and ``zigzag_unshard`` the
    output). Device r then holds stripes (r, 2cp−1−r) of 2·cp total, and
    every ring step on every rank is exactly two *unmasked* stripe-pair
    flash calls — total FLOPs equal the lower-triangle minimum (half of
    full), perfectly load-balanced, with no masked-and-discarded work and
    no conditionals. (Contiguous causal sharding would leave rank 0 idle
    (cp−1)/cp of the time and burn 2× the FLOPs in masked work.)

    Grouped-query kv: the NARROW kv rotates the ring — group-times less
    ICI traffic — and the kernels read it via their index maps.

    The reference has no context parallelism at all (SURVEY §2.3); this is
    the long-context extension built to the repo's own kernel bar.

    ``dropout_rate > 0`` (``dropout_seed`` required; pass the SAME seed
    on every cp rank — ranks decorrelate internally): in-kernel probs
    dropout with a distinct mask stream per (rank, ring step, piece),
    re-derived identically in the hand-written backward. Each (q, k)
    pair is covered by exactly one piece, so masks stay i.i.d.
    Bernoulli over the global score matrix.

    ``bias``: a :class:`BucketedBias` (pass the SAME replicated table on
    every cp rank) — relative position bias under context parallelism.
    Because the bucketed form recomputes per tile from GLOBAL offsets,
    every ring piece derives its own (q_offset, k_offset) window and the
    bias follows the zigzag/contiguous sharding exactly; the bucket-table
    gradient is psum'd over the cp axis in the hand-written backward. A
    materialized (hb, sq, sk) array is REJECTED here: it cannot ride cp
    without replicating O(s²) HBM per device — exactly what this operand
    exists to avoid.

    ``kv_lens``: GLOBAL per-row valid kv lengths ((bh,) int32 flat /
    (b,) with ``layout='bshd'``, replicated over cp) — padded batches
    under context parallelism. Each piece masks its kv window by the
    clipped global length; pieces whose window is empty fold in with
    zero weight. Rows with length 0 return zeros.
    """
    d = q.shape[-1]
    scale = float(scale if scale is not None else 1.0 / d ** 0.5)
    if layout not in ("bhsd", "bshd"):
        raise ValueError(f"layout must be bhsd|bshd, got {layout!r}")
    bshd = layout == "bshd"
    if bias is not None:
        if not isinstance(bias, BucketedBias):
            raise ValueError(
                "ring_attention takes bias as a BucketedBias (the bucketed "
                "table recomputes per block under any sharding); a "
                "materialized (hb, sq, sk) array cannot ride context "
                "parallelism without O(s²) replication")
        _validate_bucketed(bias)
        heads = q.shape[2] if bshd else None
        if bshd and heads % bias.heads:
            raise ValueError(
                f"bias table heads ({bias.heads}) must divide q heads "
                f"({heads})")
        if not bshd and q.shape[0] % bias.heads:
            raise ValueError(
                f"bias table heads ({bias.heads}) must divide q rows "
                f"({q.shape[0]})")
    if kv_lens is not None:
        want = (q.shape[0],)
        if kv_lens.shape != want:
            raise ValueError(
                f"kv_lens must be {want} ({'per-batch' if bshd else 'per-row'}"
                f" global lengths); got {kv_lens.shape}")
        kv_lens = kv_lens.astype(jnp.int32)
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got "
                         f"{dropout_rate}")
    if dropout_rate > 0.0:
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires dropout_seed")
        dropout_seed = jnp.asarray(dropout_seed, jnp.int32)
    else:
        dropout_seed = None
    if q.shape[1] != k.shape[1] or k.shape[1] != v.shape[1]:
        # ring requires IDENTICAL q/kv sequence sharding — a longer kv
        # would silently stripe-slice at the wrong boundaries
        raise ValueError(
            f"ring attention requires equal q/k/v local sequence lengths; "
            f"got {q.shape[1]} / {k.shape[1]} / {v.shape[1]}")
    if bshd:
        if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
            raise ValueError(
                f"layout='bshd' takes (b, s, h, d) operands; got "
                f"{q.shape} / {k.shape}")
        if (q.shape[2] % k.shape[2] or q.shape[0] != k.shape[0]
                or k.shape[:2] != v.shape[:2]):
            raise ValueError(
                f"kv heads ({k.shape[2]}) must divide q heads "
                f"({q.shape[2]}) with matching batch/seq dims "
                f"({q.shape} vs {k.shape})")
    elif q.shape[0] % k.shape[0]:
        raise ValueError(
            f"kv rows ({k.shape[0]}) must divide q rows ({q.shape[0]}) "
            f"for grouped-query ring attention")
    s_loc = q.shape[1]
    if causal and s_loc % 2:
        raise ValueError(
            f"causal ring attention needs an even local sequence "
            f"({s_loc}) — two zigzag stripes per device")
    ss = s_loc // 2 if causal else s_loc
    if bshd:
        ok = bshd_kernel_ok(ss, ss, q.shape[2], d, q.dtype)
    else:
        # fp16 exclusion mirrors flash_attention's gate (Mosaic has no f16)
        ok = (ss % 128 == 0 and (d % 128 == 0 or d == 64)
              and q.dtype != jnp.float16)
    if (impl == "auto" and ss < flash_auto_crossover(d)
            and not _backend.interpret_forced()):
        impl = "xla"
    use_pallas = _backend.choose_impl(impl, ok) == "pallas"
    return _ring_core(q, k, v, bias, kv_lens, dropout_seed, axis_name,
                      scale, causal, use_pallas, dropout_rate, bshd)


# --- Ulysses attention (all-to-all sequence parallel) -------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _table_head_slice(table, axis_name, h_loc, heads):
    """This rank's (num_buckets, h_loc) column slice of the REPLICATED
    bucket table — with a hand VJP that scatters the local grad back to
    full width and psums it over the axis, so the replicated table's
    cotangent is the global sum (each head group contributes its own
    columns disjointly)."""
    start = jax.lax.axis_index(axis_name) * h_loc
    return jax.lax.dynamic_slice_in_dim(table, start, h_loc, axis=1)


def _ths_fwd(table, axis_name, h_loc, heads):
    return _table_head_slice(table, axis_name, h_loc, heads), ()


def _ths_bwd(axis_name, h_loc, heads, _res, d_local):
    start = jax.lax.axis_index(axis_name) * h_loc
    full = jnp.zeros((d_local.shape[0], heads), d_local.dtype)
    full = jax.lax.dynamic_update_slice_in_dim(full, d_local, start, axis=1)
    return (jax.lax.psum(full, axis_name),)


_table_head_slice.defvjp(_ths_fwd, _ths_bwd)


def ulysses_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *, axis_name: str = mesh_lib.CONTEXT_AXIS, causal: bool = False,
    scale: Optional[float] = None, impl: str = "auto",
    kv_lens: Optional[jax.Array] = None,
    bias: Optional[BucketedBias] = None,
    dropout_rate: float = 0.0, dropout_seed: Optional[jax.Array] = None,
) -> jax.Array:
    """DeepSpeed-Ulysses-style sequence parallelism: q/k/v are this device's
    (batch, s_local, heads, head_dim) sequence shard with ALL heads; an
    ``all_to_all`` re-shards heads over ``axis_name`` while gathering the
    full sequence, unmodified :func:`flash_attention` runs per local head
    group, and a reverse ``all_to_all`` restores sequence sharding.

    Must run inside shard_map with the axis bound; requires
    ``heads % axis_size == 0``. Complements :func:`ring_attention`: Ulysses
    moves activations twice (cheap when heads >= devices, and each device
    sees the full sequence so any attention variant drops in); ring never
    materializes the full sequence on one device (memory-optimal, arbitrary
    cp). Backward is the transposed all-to-alls around flash's custom VJP —
    no hand-written grad needed.

    ``bias``: a :class:`BucketedBias` (same replicated table on every
    rank; table heads == q heads, or 1 for a broadcast bias). After the
    all-to-all each device holds the FULL sequence for a head subset, so
    the table simply slices to this rank's head columns
    (:func:`_table_head_slice` — its VJP scatters + psums the table grad)
    and rides unmodified :func:`flash_attention`; offsets stay 0 (global
    positions ARE local positions here). ``kv_lens``: (b,) per-batch
    GLOBAL valid kv lengths (replicated over the axis — the gathered
    sequence is the global one).
    """
    sp = jax.lax.axis_size(axis_name)
    b, s_local, h, d = q.shape
    if bias is not None:
        if not isinstance(bias, BucketedBias):
            raise ValueError(
                "ulysses_attention takes bias as a BucketedBias (a "
                "materialized array cannot ride context parallelism "
                "without O(s²) replication)")
        _validate_bucketed(bias)
        if bias.heads not in (1, h):
            raise ValueError(
                f"ulysses bias table heads ({bias.heads}) must be 1 "
                f"(broadcast) or equal q heads ({h}) — heads re-shard "
                f"over the axis, so per-head tables slice by rank")
    if kv_lens is not None:
        if kv_lens.shape != (b,):
            raise ValueError(
                f"ulysses kv_lens must be per-batch ({b},) global "
                f"lengths; got {kv_lens.shape}")
        kv_lens = kv_lens.astype(jnp.int32)
    if dropout_rate > 0.0:
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires dropout_seed")
        # each device attends a DIFFERENT head group over the full
        # sequence: fold the cp rank so head groups draw decorrelated
        # masks (pass the same base seed on every rank)
        dropout_seed = fold_dropout_seed(
            dropout_seed, jax.lax.axis_index(axis_name))
    h_kv = k.shape[2]
    if h % sp != 0 or h_kv % sp != 0:
        raise ValueError(
            f"ulysses_attention needs q heads ({h}) and kv heads ({h_kv}) "
            f"divisible by the {axis_name!r} axis size ({sp}); use "
            f"ring_attention otherwise")

    # (b, s/P, h, d) -> (b, s, h/P, d): scatter heads, gather sequence.
    # With grouped-query kv (h_kv < h) each tensor scatters its own head
    # count — the kv all_to_alls move group-times less data, and the
    # downstream flash kernel handles the grouping natively.
    def seq_to_head(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qg, kg, vg = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    s, h_loc = qg.shape[1], qg.shape[2]

    local_bias = bias
    if bias is not None and bias.heads == h:
        # per-head table: this rank attends heads [rank·h_loc, ...) — take
        # their columns (grad scatters + psums back through the hand VJP)
        local_bias = dataclasses.replace(
            bias, table=_table_head_slice(bias.table, axis_name, h_loc, h))

    if bshd_kernel_ok(s, s, h_loc, d, qg.dtype):
        # the all_to_all emits (b, s, h_loc, d) — exactly the kernels'
        # seq-major bshd layout, so attention runs on it directly; the
        # former unconditional bh-flat round trip (transpose+reshape on
        # every operand and the output, plus their autodiff transposes)
        # was pure layout traffic — the ~22% "head re-sharding" overhead
        # PERF.md measured was mostly these, not the collectives
        o = flash_attention(qg, kg, vg, causal=causal, scale=scale,
                            impl=impl, layout="bshd", kv_lens=kv_lens,
                            bias=local_bias,
                            dropout_rate=dropout_rate,
                            dropout_seed=dropout_seed)
    else:
        # bshd tiling ineligible (e.g. head_dim 64 with several local
        # heads) — keep the flat-kernel path rather than letting the bshd
        # XLA fallback materialize full (s, s) scores over the GATHERED
        # sequence at exactly the long-context scale Ulysses targets
        def to_bh(x):  # (b, s, x_heads, d) -> (b*x_heads, s, d)
            return x.transpose(0, 2, 1, 3).reshape(b * x.shape[2], s, d)

        o = flash_attention(to_bh(qg), to_bh(kg), to_bh(vg),
                            causal=causal, scale=scale, impl=impl,
                            kv_lens=(None if kv_lens is None
                                     else jnp.repeat(kv_lens, h_loc)),
                            bias=local_bias,
                            dropout_rate=dropout_rate,
                            dropout_seed=dropout_seed)
        o = o.reshape(b, h_loc, s, d).transpose(0, 2, 1, 3)
    # (b, s, h/P, d) -> (b, s/P, h, d): gather heads, re-scatter sequence
    return jax.lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)
