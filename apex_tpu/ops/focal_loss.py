"""Fused focal loss.

Re-design of ``apex.contrib.focal_loss``
(``apex/contrib/focal_loss/focal_loss.py:6-60``; kernel
``apex/contrib/csrc/focal_loss/focal_loss_cuda.cu``). The reference computes
the focal loss over classification logits for detection (anchors with a
label smoothing ε and per-example weighting) and stores a *partial gradient*
in forward to make backward a single in-place multiply; here the same
save-partial-grad trick is the ``custom_vjp`` residual.

Focal loss (Lin et al. 2017): ``FL(p_t) = -α_t (1 - p_t)^γ log(p_t)``, with
sigmoid logits over ``num_classes`` one-vs-all outputs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu.amp.lists import apply_op_rules


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _fl_core(logits, targets, num_classes, alpha, gamma, smoothing_factor):
    loss, _ = _fl_fwd(logits, targets, num_classes, alpha, gamma, smoothing_factor)
    return loss


def focal_loss(
    logits: jax.Array,
    targets: jax.Array,
    num_classes: int,
    alpha: float = 0.25,
    gamma: float = 2.0,
    smoothing_factor: float = 0.0,
) -> jax.Array:
    """Summed sigmoid focal loss; ``targets`` are integer class ids (0 =
    background, matching the reference's anchor labeling). Loss-class op:
    computed in fp32 under an O1 per-op-rules policy."""
    logits, = apply_op_rules("focal_loss", logits)
    return _fl_core(logits, targets, num_classes, alpha, gamma, smoothing_factor)


def _fl_sum(lf, targets, num_classes, alpha, gamma, smoothing):
    # one-vs-all targets: class c>0 maps to index c-1; background is all-zero
    onehot = jax.nn.one_hot(targets - 1, num_classes, dtype=jnp.float32)
    t = onehot * (1.0 - smoothing) + (1.0 - onehot) * smoothing
    p = jax.nn.sigmoid(lf)
    ce = jnp.logaddexp(0.0, lf) - t * lf  # BCE-with-logits against smoothed t
    p_t = p * onehot + (1.0 - p) * (1.0 - onehot)
    alpha_t = alpha * onehot + (1.0 - alpha) * (1.0 - onehot)
    loss_el = alpha_t * (1.0 - p_t) ** gamma * ce
    return jnp.sum(loss_el)


def _fl_fwd(logits, targets, num_classes, alpha, gamma, smoothing):
    # materialize the full partial gradient during forward (the reference's
    # saved partial-grad buffer) so backward is a single scale
    lf = logits.astype(jnp.float32)
    loss, pullback = jax.vjp(
        lambda l: _fl_sum(l, targets, num_classes, alpha, gamma, smoothing), lf
    )
    (dloss,) = pullback(jnp.ones((), jnp.float32))
    return loss, (dloss.astype(logits.dtype),)


def _fl_bwd(num_classes, alpha, gamma, smoothing, res, g):
    (dloss,) = res
    return ((g * dloss.astype(jnp.float32)).astype(dloss.dtype), None)


_fl_core.defvjp(_fl_fwd, _fl_bwd)
