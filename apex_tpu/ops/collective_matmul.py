"""Ring-decomposed, compute-overlapped collective matmuls for the TP/SP
boundary patterns.

Inside ``shard_map`` XLA does NOT overlap a boundary collective with the
GEMM it feeds (the latency-hiding scheduler only reorders collectives it
inserted itself, under pjit): ``ColumnParallelLinear``'s all-gather and
``RowParallelLinear``'s reduce-scatter/psum each stall the MXU for the full
boundary latency, twice per linear, forward and backward. This module
hand-decomposes those collectives into per-rank sequence chunks carried by
``lax.ppermute`` steps, matmuling the chunk already on hand while the next
chunk is in flight (Xu et al., arXiv:2004.13336; veScale does the same for
eager SPMD):

* :func:`all_gather_matmul` — ``all_gather(x) @ w.T`` as a bidirectional
  ring: ⌈(tp−1)/2⌉ ``ppermute`` steps, each delivering up to two remote
  chunks whose GEMMs run while the following chunks travel.
* :func:`matmul_reduce_scatter` — ``psum_scatter(x @ w.T)`` as the
  transpose ring: tp steps, each computing ONE destination shard's chunk
  GEMM and folding it into the partial sum arriving from the previous
  rank.
* :func:`matmul_all_reduce` — ``psum(x @ w.T)`` (the non-SP RowParallel
  epilogue) as the reduce-scatter ring above followed by a bidirectional
  chunk all-gather (pure rotation; nothing left to hide).
* :func:`copy_matmul` — the non-SP ColumnParallel pattern: forward is the
  plain local GEMM (``copy_to`` is the identity), backward overlaps the
  ``psum`` of ``g @ w`` the copy's transpose demands.

Custom VJPs pin the transpose pairs exactly as
``tensor_parallel.mappings`` pins the blocking collectives: the transpose
of ag-matmul is matmul-rs and vice versa; ``matmul_all_reduce`` carries the
``reduce_from`` pair (psum forward, identity backward) and ``copy_matmul``
the ``copy_to`` pair (identity forward, psum backward). Every reduction
visits contributions in a FIXED ring order (chunk ``j`` accumulates
``f_{j+1}, f_{j+2}, …, f_j``), so results are deterministic — two runs
produce the same bits — and each output shard is computed once, by one
rank's schedule, so replicated outputs are identical across tp ranks.

Weight-gradient partials accumulate in fp32 (``preferred_element_type``)
and cast to the weight dtype once at the end — the chunked sum otherwise
loses bits the blocking path's single fused GEMM keeps.

All functions take ``seq_dim ∈ {0, 1}`` (the layers' ``(s, b, h)`` /
``(b, s, h)`` layouts), require the axis to be bound (call inside
``shard_map``), and degrade to the plain GEMM at ``axis_name=None`` /
tp=1. The ``matmul_*`` family chunks a FULL-sequence operand and
validates divisibility eagerly with an error naming the knob, instead of
the bare XLA shape error the blocking ``psum_scatter`` dies with.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu.parallel import mesh as mesh_lib


def _count_ppermute(payload, count, axis_name):
    """Trace-time ppermute accounting (cf. pipeline ``_rotate``).
    Lazy-import shim only; the counting contract lives in
    ``monitor.hooks.count_traffic``."""
    from apex_tpu.monitor import hooks as monitor_hooks

    if count > 0:
        monitor_hooks.count_traffic("ppermute", payload, axis_name,
                                    count=count)


def _ring_span(op, payload, axis_name):
    """Trace-time step-anatomy span around one ring decomposition: the
    ring's HLOs (chunk GEMMs + ppermute hops) carry the
    ``<op>_ring_<axis>`` named scope into device traces, and the span
    record carries the per-hop chunk size (``bytes``) for CostDB
    calibration — the hop count rides the ``_count_ppermute`` counters.
    No-op while monitoring is disabled."""
    from apex_tpu.monitor import spans as monitor_spans

    return monitor_spans.collective_span(f"{op}_ring", payload, axis_name)


def _check_operands(x, w, seq_dim, op, *, features_from):
    """Eager shape validation with errors that name the operand and the
    layer knob (``overlap_comm``) instead of a deep-XLA shape mismatch."""
    if not 0 <= seq_dim < x.ndim - 1:
        raise ValueError(
            f"{op}: seq_dim={seq_dim} is not a leading axis of the "
            f"activation (shape {x.shape}; the last axis is features) — "
            f"the layers expose seq_dim=0 for (s, b, h) and 1 for "
            f"(b, s, h)")
    if w.ndim != 2 or w.shape[features_from] != x.shape[-1]:
        raise ValueError(
            f"{op}: weight {w.shape} does not contract with activation "
            f"features {x.shape[-1]} (torch-layout weight expected, "
            f"axis {features_from} = input features)")


def _check_divisible(x, seq_dim, tp, axis_name, op):
    if x.shape[seq_dim] % tp:
        raise ValueError(
            f"{op}: sequence extent {x.shape[seq_dim]} (axis {seq_dim} of "
            f"{x.shape}) is not divisible by the {axis_name!r} axis size "
            f"{tp} — the ring chunks the sequence per rank; pad the "
            f"sequence or turn off overlap_comm/sequence_parallel on "
            f"this linear")


# --- ring cores ---------------------------------------------------------------

def _ring_all_gather_apply(x, chunk_fn, axis_name, seq_dim,
                           acc_fn=None):
    """Bidirectional all-gather ring: deliver every rank's chunk of ``x``
    and write ``chunk_fn(chunk)`` at the chunk's global sequence offset.
    ⌈(tp−1)/2⌉ steps; each delivers two chunks (one per direction) except
    the final step of an even ring, where the directions meet. The GEMM of
    the chunk on hand overlaps the in-flight ``ppermute`` of the next.

    ``acc_fn(acc, chunk, j)`` optionally folds each delivered chunk into a
    side accumulator (the dW ride-along of ``matmul_reduce_scatter``'s
    backward); visit order is local chunk first, then alternating
    fwd/bwd — fixed, so the accumulation is deterministic.

    Returns ``(full-seq output, acc)``.
    """
    tp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    s_loc = x.shape[seq_dim]
    fwd_perm = [(i, (i + 1) % tp) for i in range(tp)]
    bwd_perm = [(i, (i - 1) % tp) for i in range(tp)]

    y_local = chunk_fn(x)
    out_shape = list(y_local.shape)
    out_shape[seq_dim] = tp * s_loc
    out = jnp.zeros(tuple(out_shape), y_local.dtype)
    out = jax.lax.dynamic_update_slice_in_dim(
        out, y_local, rank * s_loc, axis=seq_dim)
    acc = None if acc_fn is None else acc_fn(None, x, rank)

    steps = (tp - 1 + 1) // 2  # ⌈(tp−1)/2⌉
    n_bwd = steps - 1 if tp % 2 == 0 and tp > 1 else steps
    _count_ppermute(x, steps + n_bwd, axis_name)
    fwd = bwd = x
    for t in range(1, steps + 1):
        fwd = jax.lax.ppermute(fwd, axis_name, fwd_perm)
        jf = (rank - t) % tp
        out = jax.lax.dynamic_update_slice_in_dim(
            out, chunk_fn(fwd), jf * s_loc, axis=seq_dim)
        if acc_fn is not None:
            acc = acc_fn(acc, fwd, jf)
        if t == steps and tp % 2 == 0:
            break  # (rank − t) ≡ (rank + t) (mod tp): directions meet
        bwd = jax.lax.ppermute(bwd, axis_name, bwd_perm)
        jb = (rank + t) % tp
        out = jax.lax.dynamic_update_slice_in_dim(
            out, chunk_fn(bwd), jb * s_loc, axis=seq_dim)
        if acc_fn is not None:
            acc = acc_fn(acc, bwd, jb)
    return out, acc


def _ring_reduce_scatter(contrib_fn, axis_name, *, payload=None,
                         payload_fn=None):
    """Reduce-scatter ring: the accumulator destined for rank ``j`` starts
    at rank ``j+1`` and travels +1, each rank adding its own contribution
    ``contrib_fn(j)`` — the per-chunk GEMM, which depends only on local
    operands, so XLA overlaps it with the arriving partial sum's
    ``ppermute``. Per destination chunk the summation order is the fixed
    ring order ``f_{j+1} + f_{j+2} + … + f_j``.

    ``payload``/``payload_fn`` piggyback a second rotation in the same +1
    direction (the x-chunk ride-along of ``all_gather_matmul``'s backward):
    at step ``t`` the payload holds chunk ``(rank − t) % tp`` and
    ``payload_fn(extra, payload, j2)`` folds it.

    Returns ``(this rank's reduced chunk, extra)``.
    """
    tp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % tp) for i in range(tp)]
    acc = None
    extra = None
    for t in range(tp):
        j = (rank - 1 - t) % tp
        c = contrib_fn(j)
        if t == 0:
            acc = c
        else:
            acc = jax.lax.ppermute(acc, axis_name, perm) + c
        if payload_fn is not None:
            if t > 0:
                payload = jax.lax.ppermute(payload, axis_name, perm)
            extra = payload_fn(extra, payload, (rank - t) % tp)
    if acc is not None and tp > 1:
        _count_ppermute(acc, tp - 1, axis_name)
        if payload_fn is not None:
            _count_ppermute(payload, tp - 1, axis_name)
    return acc, extra


def _seq_chunk(x, seq_dim, j, s_loc):
    return jax.lax.dynamic_slice_in_dim(x, j * s_loc, s_loc, axis=seq_dim)


def _dw_fold(acc, g_chunk, x_chunk):
    """One chunk's weight-grad partial, accumulated in fp32 (the blocking
    path's single GEMM keeps fp32 accumulation inside the MXU; a chunked
    bf16 sum would not)."""
    part = jnp.einsum("...o,...i->oi", g_chunk, x_chunk,
                      preferred_element_type=jnp.float32)
    return part if acc is None else acc + part


# --- all-gather → matmul ------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _ag_matmul(x, w, axis_name, seq_dim):
    with _ring_span("ag_matmul", x, axis_name):
        y, _ = _ring_all_gather_apply(
            x, lambda c: jnp.dot(c, w.T), axis_name, seq_dim)
    return y


def _ag_matmul_fwd(x, w, axis_name, seq_dim):
    # residuals are the LOCAL shard + weight: the gathered activation is
    # never materialized, forward or backward (the blocking path saves the
    # full (s, …, h) gather as a matmul residual)
    return _ag_matmul(x, w, axis_name, seq_dim), (x, w)


def _ag_matmul_bwd(axis_name, seq_dim, res, g):
    x, w = res
    s_loc = x.shape[seq_dim]

    def contrib(j):  # dx chunk for rank j: local g slice, local w
        return jnp.dot(_seq_chunk(g, seq_dim, j, s_loc), w)

    def dw_ride(acc, x_chunk, j):  # x chunks rotate; g slices are local
        return _dw_fold(acc, _seq_chunk(g, seq_dim, j, s_loc), x_chunk)

    with _ring_span("ag_matmul_bwd", g, axis_name):
        dx, dw = _ring_reduce_scatter(
            contrib, axis_name, payload=x, payload_fn=dw_ride)
    return dx.astype(x.dtype), dw.astype(w.dtype)


_ag_matmul.defvjp(_ag_matmul_fwd, _ag_matmul_bwd)


def all_gather_matmul(x, w, *, axis_name=mesh_lib.TENSOR_AXIS, seq_dim=0):
    """``all_gather(x, seq_dim, tiled) @ w.T`` as a compute-overlapped
    bidirectional ring — the SP ``ColumnParallelLinear`` boundary. ``x`` is
    this rank's sequence shard, ``w`` the torch-layout ``(out_local, in)``
    column shard; returns the full-sequence ``(…, out_local)`` product.
    Backward is the matmul→reduce-scatter ring (dx) with the dW
    contraction riding the same rotation."""
    _check_operands(x, w, seq_dim, "all_gather_matmul", features_from=1)
    if axis_name is None:
        return jnp.dot(x, w.T)
    if jax.lax.axis_size(axis_name) == 1:
        return jnp.dot(x, w.T)
    return _ag_matmul(x, w, axis_name, seq_dim)


# --- matmul → reduce-scatter --------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _mm_rs(x, w, axis_name, seq_dim):
    tp = jax.lax.axis_size(axis_name)
    s_loc = x.shape[seq_dim] // tp

    def contrib(j):
        return jnp.dot(_seq_chunk(x, seq_dim, j, s_loc), w.T)

    with _ring_span("mm_rs", x, axis_name):
        y, _ = _ring_reduce_scatter(contrib, axis_name)
    return y


def _mm_rs_fwd(x, w, axis_name, seq_dim):
    return _mm_rs(x, w, axis_name, seq_dim), (x, w)


def _mm_rs_bwd(axis_name, seq_dim, res, g):
    x, w = res
    s_loc = g.shape[seq_dim]

    def dw_ride(acc, g_chunk, j):  # g chunks rotate; x slices are local
        return _dw_fold(acc, g_chunk, _seq_chunk(x, seq_dim, j, s_loc))

    with _ring_span("mm_rs_bwd", g, axis_name):
        dx, dw = _ring_all_gather_apply(
            g, lambda c: jnp.dot(c, w), axis_name, seq_dim, acc_fn=dw_ride)
    return dx.astype(x.dtype), dw.astype(w.dtype)


_mm_rs.defvjp(_mm_rs_fwd, _mm_rs_bwd)


def matmul_reduce_scatter(x, w, *, axis_name=mesh_lib.TENSOR_AXIS,
                          seq_dim=0):
    """``psum_scatter(x @ w.T, seq_dim, tiled)`` as the transpose ring —
    the SP ``RowParallelLinear`` epilogue. ``x`` is the full-sequence local
    activation ``(…, in_local)``, ``w`` the ``(out, in_local)`` row shard;
    returns this rank's sequence chunk of the summed product. Backward is
    the all-gather→matmul ring (dx) with dW riding the g rotation."""
    _check_operands(x, w, seq_dim, "matmul_reduce_scatter", features_from=1)
    if axis_name is None:
        return jnp.dot(x, w.T)
    tp = jax.lax.axis_size(axis_name)
    if tp == 1:
        return jnp.dot(x, w.T)
    _check_divisible(x, seq_dim, tp, axis_name, "matmul_reduce_scatter")
    return _mm_rs(x, w, axis_name, seq_dim)


# --- matmul → all-reduce (non-SP RowParallel) ---------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _mm_ar(x, w, axis_name, seq_dim):
    tp = jax.lax.axis_size(axis_name)
    s_loc = x.shape[seq_dim] // tp

    def contrib(j):
        return jnp.dot(_seq_chunk(x, seq_dim, j, s_loc), w.T)

    with _ring_span("mm_ar", x, axis_name):
        chunk, _ = _ring_reduce_scatter(contrib, axis_name)
        # all-gather phase: the reduced chunks rotate back out — pure
        # comm, but each destination chunk was summed once, in ring
        # order, so every rank receives bitwise-identical bytes (an XLA
        # psum makes no such ordering promise)
        y, _ = _ring_all_gather_apply(chunk, lambda c: c, axis_name,
                                      seq_dim)
    return y


def _mm_ar_fwd(x, w, axis_name, seq_dim):
    return _mm_ar(x, w, axis_name, seq_dim), (x, w)


def _mm_ar_bwd(axis_name, seq_dim, res, g):
    # the reduce_from pinned pair (psum forward, identity backward): the
    # cotangent of the reduced output is replicated, so dx and dW are
    # local GEMMs — no collective in this backward, same as blocking
    x, w = res
    dx = jnp.dot(g, w)
    dw = _dw_fold(None, g, x)
    return dx.astype(x.dtype), dw.astype(w.dtype)


_mm_ar.defvjp(_mm_ar_fwd, _mm_ar_bwd)


def matmul_all_reduce(x, w, *, axis_name=mesh_lib.TENSOR_AXIS, seq_dim=0):
    """``psum(x @ w.T)`` as reduce-scatter ring + chunk all-gather — the
    non-SP ``RowParallelLinear`` epilogue. The RS phase overlaps each
    destination chunk's GEMM with the partial sum's hop; the AG phase is
    rotation only. Backward is local (the ``reduce_from`` pinned pair)."""
    _check_operands(x, w, seq_dim, "matmul_all_reduce", features_from=1)
    if axis_name is None:
        return jnp.dot(x, w.T)
    tp = jax.lax.axis_size(axis_name)
    if tp == 1:
        return jnp.dot(x, w.T)
    _check_divisible(x, seq_dim, tp, axis_name, "matmul_all_reduce")
    return _mm_ar(x, w, axis_name, seq_dim)


# --- copy → matmul (non-SP ColumnParallel) ------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _copy_mm(x, w, axis_name, seq_dim):
    return jnp.dot(x, w.T)


def _copy_mm_fwd(x, w, axis_name, seq_dim):
    return jnp.dot(x, w.T), (x, w)


def _copy_mm_bwd(axis_name, seq_dim, res, g):
    # the copy_to pinned pair (identity forward, psum backward): dx must
    # be psum(g @ w) over tp — decomposed so each chunk's GEMM overlaps
    # the ring instead of one blocking GEMM feeding one blocking psum
    x, w = res
    tp = jax.lax.axis_size(axis_name)
    s_loc = g.shape[seq_dim] // tp

    def contrib(j):
        return jnp.dot(_seq_chunk(g, seq_dim, j, s_loc), w)

    with _ring_span("copy_mm_bwd", g, axis_name):
        chunk, _ = _ring_reduce_scatter(contrib, axis_name)
        dx, _ = _ring_all_gather_apply(chunk, lambda c: c, axis_name,
                                       seq_dim)
    dw = _dw_fold(None, g, x)
    return dx.astype(x.dtype), dw.astype(w.dtype)


_copy_mm.defvjp(_copy_mm_fwd, _copy_mm_bwd)


def copy_matmul(x, w, *, axis_name=mesh_lib.TENSOR_AXIS, seq_dim=0):
    """``copy_to(x) @ w.T`` — the non-SP ``ColumnParallelLinear`` pattern.
    Forward is the plain local GEMM (``copy_to`` is the identity);
    backward ring-overlaps the ``psum(g @ w)`` the copy's transpose
    demands. ``x`` must carry the full sequence (it is replicated over
    tp), divisible by the axis size for the backward chunking."""
    _check_operands(x, w, seq_dim, "copy_matmul", features_from=1)
    if axis_name is None:
        return jnp.dot(x, w.T)
    tp = jax.lax.axis_size(axis_name)
    if tp == 1:
        return jnp.dot(x, w.T)
    _check_divisible(x, seq_dim, tp, axis_name, "copy_matmul")
    return _copy_mm(x, w, axis_name, seq_dim)
