"""Fused sampling tail: logits → temperature/top-k/top-p → token.

The op-level wrapper over :mod:`apex_tpu.ops.pallas.sampling` following
the house dispatch rule (:mod:`apex_tpu.ops._backend`): the Pallas kernel
on TPU when the vocab tiles the lane dim, interpret-mode Pallas under
``APEX_TPU_PALLAS=interpret``, and an XLA composition otherwise. The XLA
fallback calls the SAME module-level filter/sample helpers the kernel
body runs, so the two paths agree token-for-token on shared noise — the
parity anchor ``tests/test_serving.py`` pins.

This is the serving engines' tail (one fused dispatch per decode step);
the standalone, sort/cumsum-formulated sampler for ad-hoc use stays in
:func:`apex_tpu.inference.sampling.sample_logits`.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import _backend
from apex_tpu.ops.pallas.sampling import (filtered_scaled, fused_sample_fwd,
                                          gumbel_argmax)


def sample_kernel_ok(vocab: int, dtype) -> bool:
    """Mosaic eligibility: the vocab is the lane dim of every whole-row
    reduction, so it must be a 128-multiple; f16 has no Mosaic support."""
    return vocab % 128 == 0 and dtype != jnp.float16


def fused_sample(logits: jax.Array, key: Optional[jax.Array] = None, *,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, impl: str = "auto") -> jax.Array:
    """(b, V) logits → (b,) int32 tokens through ONE fused tail.

    ``temperature == 0`` is greedy argmax (already a single reduction —
    no kernel needed, ``top_k``/``top_p`` are no-ops on an argmax).
    Otherwise: scale by ``1/temperature``, keep the ``top_k`` largest
    (0 = all), then the minimal top-``top_p`` probability mass (1.0 =
    all; ties at either threshold are kept), and draw via Gumbel-argmax
    on a uniform row folded from ``key``. All knobs are STATIC — they
    select the compiled program, never retrace per step.

    The uniform noise is drawn inside the caller's jit by ``jax.random``
    (one fused producer) and consumed by the kernel in the same program;
    kernel and XLA fallback share it, so ``impl`` never changes the
    sampled token.
    """
    if logits.ndim != 2:
        raise ValueError(f"fused_sample takes (b, V) logits; got "
                         f"{logits.shape}")
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if key is None:
        raise ValueError("temperature > 0 sampling requires a PRNG key")
    b, V = logits.shape
    top_k = min(int(top_k), V)
    # (0, 1]: tiny floor keeps log(u) finite (u=0 would pin a token's
    # Gumbel at -inf, silently excluding it)
    u = jax.random.uniform(key, (b, V), jnp.float32,
                           minval=jnp.finfo(jnp.float32).tiny, maxval=1.0)
    ok = sample_kernel_ok(V, logits.dtype)
    if _backend.choose_impl(impl, ok) == "pallas":
        return fused_sample_fwd(logits, u, temperature=float(temperature),
                                top_k=top_k, top_p=float(top_p),
                                interpret=_backend.interpret_mode())
    s = filtered_scaled(logits, temperature=float(temperature),
                        top_k=top_k, top_p=float(top_p))
    return gumbel_argmax(s, u).astype(jnp.int32)
