"""Fused MLP: a whole stack of Linear+bias+activation in one call chain.

Re-design of ``apex.mlp.MLP`` (``apex/mlp/mlp.py:8-80``; kernels
``csrc/mlp_cuda.cu:47-200``). The reference fuses N layers' GEMMs with custom
bias+relu/sigmoid epilogue kernels and hand-written backward; here each layer
is the fused GEMM+bias+act primitive (Pallas epilogue kernel or the
XLA-fused composition), and backward applies the activation derivative from
saved pre-activations — the same residuals mlp_cuda stashes.
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.lists import apply_op_rules
from apex_tpu.ops import _backend
from apex_tpu.ops.fused_dense import _mm


def _act(h, activation):
    if activation == "relu":
        return jnp.maximum(h, 0.0)
    if activation == "sigmoid":
        return jax.nn.sigmoid(h)
    if activation == "none":
        return h
    raise ValueError(f"mlp activation must be none|relu|sigmoid, got {activation!r}")


def _dact(h_pre, h_post, activation):
    if activation == "relu":
        return (h_pre > 0).astype(h_pre.dtype)
    if activation == "sigmoid":
        return h_post * (1.0 - h_post)
    return jnp.ones_like(h_pre)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _mlp_core(x, params, activation, use_pallas):
    h = x
    n = len(params) // 2
    for i in range(n):
        h = _mm(h, params[2 * i], params[2 * i + 1], activation, use_pallas)
    return h


def _mlp_fwd(x, params, activation, use_pallas):
    n = len(params) // 2
    h = x
    pres: List[jax.Array] = []
    posts: List[jax.Array] = [x]
    for i in range(n):
        pre = _mm(h, params[2 * i], params[2 * i + 1], "none", use_pallas)
        h = _act(pre, activation)
        pres.append(pre)
        posts.append(h)
    return h, (tuple(params), tuple(pres), tuple(posts))


def _mlp_bwd(activation, use_pallas, res, dy):
    params, pres, posts = res
    n = len(pres)
    dparams = [None] * (2 * n)
    g = dy
    for i in reversed(range(n)):
        g = g * _dact(pres[i], posts[i + 1], activation)
        w = params[2 * i]
        dparams[2 * i] = _mm(posts[i].T, g, use_pallas=use_pallas, out_dtype=w.dtype)
        dparams[2 * i + 1] = jnp.sum(g, axis=0).astype(w.dtype)
        g = _mm(g, w.T, use_pallas=use_pallas, out_dtype=posts[i].dtype)
    return g, tuple(dparams)


_mlp_core.defvjp(_mlp_fwd, _mlp_bwd)


def mlp(
    x: jax.Array,
    weights: Sequence[jax.Array],
    biases: Sequence[jax.Array],
    activation: str = "relu",
    *,
    impl: str = "auto",
) -> jax.Array:
    """Functional MLP; weights are torch-Linear layout (out, in), activation
    after every layer including the last (matching ``mlp_cuda``'s semantics
    where activation is applied uniformly, ``apex/mlp/mlp.py:13``). The
    reference registers MLP as a HALF op (``amp.half_function``,
    ``apex/mlp/mlp.py:24``) — under O1 the whole chain runs in compute dtype.
    """
    cast = apply_op_rules("mlp", x, *weights, *biases)
    x, weights, biases = (
        cast[0], cast[1:1 + len(weights)], cast[1 + len(weights):]
    )
    ok = all(w.shape[1] % 128 == 0 and w.shape[0] % 128 == 0 for w in weights)
    # auto == xla: measured on v5e (carry-loop timing, 3-layer
    # 512-1024-1024-512 bf16 fwd+bwd at 4096 rows: pallas 1.00 ms, xla
    # 0.83) — same verdict as fused_dense
    use_pallas = _backend.choose_impl(
        _backend.resolve_auto(impl), ok and x.shape[-1] % 128 == 0) == "pallas"
    lead = x.shape[:-1]
    h = x.reshape(-1, x.shape[-1])
    flat = []
    for w, b in zip(weights, biases):
        flat.extend([w.T, b])
    y = _mlp_core(h, tuple(flat), activation, use_pallas)
    return y.reshape(*lead, y.shape[-1])


class MLP:
    """``apex.mlp.MLP`` (``apex/mlp/mlp.py:26``): ``mlp_sizes`` is
    [in, h1, ..., out]; bias + relu/sigmoid/none activation."""

    def __init__(self, mlp_sizes: Sequence[int], bias: bool = True,
                 activation: str = "relu", impl: str = "auto"):
        if len(mlp_sizes) < 2:
            raise ValueError("mlp_sizes must have at least 2 entries")
        if not bias:
            raise NotImplementedError(
                "bias-less MLP: pass zero biases (kept for API parity; the "
                "reference also requires bias for the fused path, mlp.py:35)"
            )
        self.mlp_sizes = tuple(mlp_sizes)
        self.activation = activation
        self.impl = impl

    def init(self, key, dtype=jnp.float32) -> dict:
        params = {}
        keys = jax.random.split(key, len(self.mlp_sizes) - 1)
        for i, (din, dout) in enumerate(zip(self.mlp_sizes[:-1], self.mlp_sizes[1:])):
            # reference init: uniform(-1/sqrt(fan_in)) (mlp.py:43-49 resets
            # with kaiming-style bounds)
            bound = 1.0 / jnp.sqrt(din)
            params[f"weight_{i}"] = jax.random.uniform(
                keys[i], (dout, din), dtype, -bound, bound
            )
            params[f"bias_{i}"] = jnp.zeros((dout,), dtype)
        return params

    def __call__(self, params: dict, x: jax.Array) -> jax.Array:
        n = len(self.mlp_sizes) - 1
        ws = [params[f"weight_{i}"] for i in range(n)]
        bs = [params[f"bias_{i}"] for i in range(n)]
        return mlp(x, ws, bs, self.activation, impl=self.impl)
