"""Scaled masked softmax (fused) — functional API with probs-saving backward.

Re-design of ``apex.transformer.functional.fused_softmax``'s autograd wrappers
(``apex/transformer/functional/fused_softmax.py:21-98``): the reference saves
the softmax output and computes ``dx = scale * y * (dy - sum(dy*y))`` in its
backward kernel; we reproduce exactly that contract via ``jax.custom_vjp``
over the Pallas kernels in :mod:`apex_tpu.ops.pallas.softmax`.

Shapes follow the reference:
* ``scaled_masked_softmax(x, mask, scale)`` — x: (b, np, sq, sk),
  mask: (b or 1, 1, sq, sk) boolean (True ⇒ masked out);
* ``scaled_upper_triang_masked_softmax(x, scale)`` — x: (attn_batches, sq, sk)
  with the causal triangle applied in-kernel.

No ``16 < sk <= 2048`` cap (the CUDA kernels' limit,
``fused_softmax.py:166``): blocks stream over rows, sk only needs to be a
lane multiple for the Pallas path; anything else takes the XLA path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu.amp.lists import apply_op_rules
from apex_tpu.ops import _backend
from apex_tpu.ops.pallas import softmax as _k


def _xla_fwd(x2d, mask2d, scale, causal, sq):
    xf = x2d.astype(jnp.float32) * scale
    if causal:
        rows, sk = x2d.shape
        q = (jnp.arange(rows) % sq)[:, None]
        k = jnp.arange(sk)[None, :]
        xf = jnp.where(k <= q, xf, _k.MASK_FILL)
    elif mask2d is not None:
        xf = jnp.where(mask2d != 0, _k.MASK_FILL, xf)
    y = jax.nn.softmax(xf, axis=-1)
    return y.astype(x2d.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _softmax_core(x2d, mask2d, scale, causal, sq, use_pallas):
    y, _ = _softmax_fwd(x2d, mask2d, scale, causal, sq, use_pallas)
    return y


def _softmax_fwd(x2d, mask2d, scale, causal, sq, use_pallas):
    if use_pallas:
        y = _k.softmax_fwd(
            x2d, mask2d, scale=scale, causal=causal, sq=sq,
            interpret=_backend.interpret_mode(),
        )
    else:
        y = _xla_fwd(x2d, mask2d, scale, causal, sq)
    return y, y


def _softmax_bwd(scale, causal, sq, use_pallas, y, dy):
    if use_pallas:
        dx = _k.softmax_bwd(dy, y, scale=scale, interpret=_backend.interpret_mode())
    else:
        yf = y.astype(jnp.float32)
        dyf = dy.astype(jnp.float32)
        dx = (scale * yf * (dyf - jnp.sum(dyf * yf, axis=-1, keepdims=True))).astype(y.dtype)
    return dx, None


_softmax_core.defvjp(_softmax_fwd, _softmax_bwd)


def scaled_masked_softmax(
    x: jax.Array, mask: jax.Array | None, scale: float = 1.0, *, impl: str = "auto"
) -> jax.Array:
    """``ScaledMaskedSoftmax`` (``fused_softmax.py:57-98``). ``mask`` is
    boolean with True meaning *masked out*, broadcastable to ``x``.
    FLOAT-class under O1 (``lists/functional_overrides.py:28-67``)."""
    x, = apply_op_rules("softmax", x)
    sk = x.shape[-1]
    # auto == xla (measured, v5e: GPT-shaped causal (64,1024,1024) bf16
    # fwd+bwd — pallas 3.98 ms, this op's xla path 2.69, naive jnp 3.47;
    # the recompute-from-y backward is the win and both impls share it)
    use_pallas = _backend.choose_impl(
        _backend.resolve_auto(impl), sk % 128 == 0) == "pallas"
    x2d = x.reshape(-1, sk)
    mask2d = None
    if mask is not None:
        mask2d = jnp.broadcast_to(mask, x.shape).reshape(-1, sk).astype(jnp.int8)
    y = _softmax_core(x2d, mask2d, float(scale), False, x.shape[-2], use_pallas)
    return y.reshape(x.shape)


def scaled_upper_triang_masked_softmax(
    x: jax.Array, scale: float = 1.0, *, impl: str = "auto"
) -> jax.Array:
    """``ScaledUpperTriangMaskedSoftmax`` (``fused_softmax.py:21-54``):
    causal softmax over (..., sq, sk) with the triangle built in-kernel.
    FLOAT-class under O1."""
    x, = apply_op_rules("softmax", x)
    sq, sk = x.shape[-2], x.shape[-1]
    # auto == xla (measured, v5e: GPT-shaped causal (64,1024,1024) bf16
    # fwd+bwd — pallas 3.98 ms, this op's xla path 2.69, naive jnp 3.47;
    # the recompute-from-y backward is the win and both impls share it)
    use_pallas = _backend.choose_impl(
        _backend.resolve_auto(impl), sk % 128 == 0) == "pallas"
    x2d = x.reshape(-1, sk)
    y = _softmax_core(x2d, None, float(scale), True, sq, use_pallas)
    return y.reshape(x.shape)
