"""Fused verify-and-sample Pallas kernel: k+1 target logit rows + k
drafted tokens → (longest accepted prefix, corrected next token), one
kernel.

Speculative decoding's verification tail is, composed in XLA, a chain of
O(k·V) staging ops — scale, filter, per-row softmax/argmax, a prefix
scan over the accept flags, a gather of the corrected row — each
materializing an O(V) tensor between HBM round trips, exactly the
per-token-epilogue traffic arXiv:2502.17728 argues into one kernel (and
exactly what :mod:`apex_tpu.ops.pallas.sampling` already fused for the
single-row sampling tail). This kernel extends that fusion to the whole
accept/reject tail: the (k+1, V) logit block is read into VMEM once and
two int32 lanes come back — nothing O(V) returns to HBM.

Acceptance semantics (the drafters propose point-mass — greedy — drafts,
so both modes are EXACT: the emitted stream is distributed identically
to non-speculative decoding):

* **Greedy** (temperature == 0): row i's candidate is ``argmax`` of the
  target's i-th logit row; drafted token i is accepted iff it equals
  candidate i. The accepted prefix length ``a`` is the count of leading
  matches, and the corrected next token is candidate ``a`` — by
  construction the token the non-speculative greedy loop would have
  produced, so the spec stream is token-identical to the baseline.
* **Rejection sampling** (temperature > 0, top-k/top-p): the target
  distribution p is the same temperature→top-k→top-p filtered softmax
  the fused sampling tail draws from (the bisection helpers of
  :mod:`~apex_tpu.ops.pallas.sampling` are reused verbatim). A drafted
  token d_i — a point mass under the drafter — is accepted with
  probability p(d_i) (the ``min(1, p/q)`` rule with q = δ(d_i)); on the
  first rejection the corrected token is drawn from the residual
  ``p`` with d_i removed (the normalized ``max(p − q·min(p,q), 0)`` of
  a point-mass q), and if all k drafts are accepted the bonus token is
  drawn from the full filtered p. Both draws are Gumbel-argmax on
  pre-drawn uniform rows, shared with the XLA fallback.

The filtering/acceptance math lives in module-level helpers written for
arbitrary leading batch dims, shared VERBATIM with the XLA fallback in
:mod:`apex_tpu.ops.fused_verify` — kernel/fallback parity is by
construction on shared noise, the same discipline as ``fused_sample``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops.pallas.attention import _LSE_LANES
from apex_tpu.ops.pallas.sampling import FILTERED, filtered_scaled, gumbel_argmax

#: sentinel drafted id for the bonus row (row k has no draft to verify);
#: never equals a real candidate, so its accept flag is always False and
#: the accepted prefix length is capped at k
NO_DRAFT = -1

#: lane width of the drafted-id / acceptance-noise operands: one full
#: TPU lane tile, so every draft length the drafters allow (k+1 <=
#: MAX_DRAFT_K+1 = 33) fits one block — the 8-lane carrier the OUTPUT
#: scalars ride would truncate any k >= 8
VERIFY_LANES = 128


def row_argmax(s):
    """Row-wise argmax with ties to the LOWEST index (``jnp.argmax``'s
    convention, so greedy spec candidates match the engines' greedy
    tails bit for bit). ``s`` (..., V) → (...,) int32."""
    m = jnp.max(s, axis=-1, keepdims=True)
    V = s.shape[-1]
    idx = jax.lax.broadcasted_iota(jnp.int32, s.shape, s.ndim - 1)
    return jnp.min(jnp.where(s == m, idx, V), axis=-1)


def accepted_prefix_len(acc):
    """Length of the leading run of True accept flags: ``acc`` (..., k+1)
    bool → (...,) int32 in [0, k] (the bonus row's flag is always False
    — :data:`NO_DRAFT` never matches a candidate)."""
    return jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=-1), axis=-1)


def select_row(vals, a):
    """``vals[..., a]`` at a traced per-batch index ``a`` (...,) without
    a gather: one-hot sum over the row axis (VPU-only, kernel-safe)."""
    idx = jax.lax.broadcasted_iota(jnp.int32, vals.shape, vals.ndim - 1)
    return jnp.sum(jnp.where(idx == a[..., None], vals, 0), axis=-1)


def verify_greedy(logits, drafted_pad):
    """Exact greedy acceptance. ``logits`` (..., k+1, V); ``drafted_pad``
    (..., k+1) int32 with the bonus row pinned at :data:`NO_DRAFT`.
    Returns ``(accept_len (...,), next_token (...,))`` int32."""
    cand = row_argmax(logits.astype(jnp.float32))
    a = accepted_prefix_len(cand == drafted_pad)
    return a, select_row(cand, a)


def verify_sampled(logits, drafted_pad, u_acc, u_gum, *, temperature,
                   top_k, top_p):
    """Exact rejection-sampling acceptance for point-mass drafts under
    the temperature→top-k→top-p filtered target distribution.

    ``logits`` (..., k+1, V); ``drafted_pad`` (..., k+1) int32 (bonus row
    :data:`NO_DRAFT`); ``u_acc`` (..., k+1) uniform acceptance draws in
    (0, 1]; ``u_gum`` (..., k+1, V) uniform Gumbel noise in (0, 1].
    Row i accepts d_i iff ``u_acc_i < p(d_i)``; every row's correction
    candidate is drawn from p with its drafted token FILTERED (the exact
    point-mass residual; the bonus row draws from the full p), and the
    first rejected row's candidate is the emitted correction. A drafted
    token the top-k/top-p filter removed carries p == 0 and is always
    rejected — the filters bind identically to the non-speculative tail.
    """
    s = filtered_scaled(logits, temperature=temperature, top_k=top_k,
                        top_p=top_p)
    cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, s.ndim - 1)
    onehot = cols == drafted_pad[..., None]
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    p_d = (jnp.sum(jnp.where(onehot, e, 0.0), axis=-1)
           / jnp.sum(e, axis=-1))
    a = accepted_prefix_len(u_acc < p_d)
    cand = gumbel_argmax(jnp.where(onehot, FILTERED, s), u_gum)
    return a, select_row(cand, a)


def tree_depths(anc):
    """Per-node depth from the ancestor-or-self closure: ``anc``
    (..., N1, N1) int32 (``anc[i, j] == 1`` iff node j lies on node i's
    root path, including i itself and the root, node 0) → (..., N1)
    int32 depths (the root has depth 0)."""
    return jnp.sum(anc.astype(jnp.int32), axis=-1) - 1


def tree_accepted_path(acc, anc):
    """The deepest fully-accepted root path of a draft tree.

    ``acc`` (..., N1) per-node accept flags (node 0 — the committed
    pending token — is forced accepted here; padding nodes must arrive
    False); ``anc`` (..., N1, N1) the ancestor-or-self closure. A node
    is PATH-accepted iff every node on its root path is accepted, and
    the winner is the deepest path-accepted node (ties to the LOWEST
    node index — the drafters order siblings best-first, so the tie
    break is deterministic and drafter-meaningful). Returns
    ``(accept_len (...,), j_star (...,))`` int32: the winner's depth
    (== accepted drafted tokens) and its node index. Node 0 is always
    path-accepted, so ``accept_len >= 0`` and ``j_star`` is always a
    valid node."""
    n1 = anc.shape[-1]
    lanes = jax.lax.broadcasted_iota(jnp.int32, acc.shape, acc.ndim - 1)
    acc_i = jnp.maximum(acc.astype(jnp.int32),
                        (lanes == 0).astype(jnp.int32))
    bad = anc.astype(jnp.int32) * (1 - acc_i)[..., None, :]
    ok = jnp.sum(bad, axis=-1) == 0
    depth = tree_depths(anc)
    a = jnp.max(jnp.where(ok, depth, -1), axis=-1)
    idx = jax.lax.broadcasted_iota(jnp.int32, ok.shape, ok.ndim - 1)
    hit = ok & (depth == a[..., None])
    j_star = jnp.min(jnp.where(hit, idx, n1), axis=-1)
    return a, j_star


def _parent_onehot(parents, n1):
    """``po[..., c, r] = (parents[..., c] == r)`` — the one-hot parent
    gather both tree modes use (kernel-safe: iota + compare, no
    dynamic gather)."""
    cols = jax.lax.broadcasted_iota(jnp.int32, parents.shape + (n1,),
                                    parents.ndim)
    return cols == parents[..., None]


def verify_tree_greedy(logits, tokens, parents, anc):
    """Exact greedy tree acceptance. ``logits`` (..., N1, V): row j is
    the target's distribution AFTER node j's token (row 0 after the
    committed pending token); ``tokens`` (..., N1) int32 node tokens
    with row 0 pinned at :data:`NO_DRAFT`; ``parents`` (..., N1) int32
    parent pointers (``parents[0] == 0``, ``parents[j] < j`` —
    topological); ``anc`` (..., N1, N1) the ancestor-or-self closure.

    Node j is accepted iff its parent's argmax candidate equals
    ``tokens[j]`` — exactly the chain rule applied edge-wise, so at
    branching 1 this degenerates to :func:`verify_greedy` (with the
    chain's row i living at node i+1). The emitted path is the deepest
    fully-accepted one and the bonus/corrected token is the winner
    row's candidate; by the same maximality argument as the chain
    (a child carrying the winner's candidate would itself be accepted,
    contradicting maximality), the result is token-identical to
    non-speculative greedy decoding. Returns ``(accept_len, j_star,
    next_token)``, each (...,) int32."""
    cand = row_argmax(logits.astype(jnp.float32))        # (..., N1)
    n1 = cand.shape[-1]
    po = _parent_onehot(parents, n1)                     # (..., c, r)
    pc = jnp.sum(jnp.where(po, cand[..., None, :], 0), axis=-1)
    acc = (pc == tokens) & (tokens != NO_DRAFT)
    a, j_star = tree_accepted_path(acc, anc)
    return a, j_star, select_row(cand, j_star)


def verify_tree_sampled(logits, tokens, parents, anc, u_acc, u_gum, *,
                        temperature, top_k, top_p):
    """Rejection-sampling tree acceptance for point-mass drafts under
    the temperature→top-k→top-p filtered target distribution.

    Same operand contract as :func:`verify_tree_greedy` plus ``u_acc``
    (..., N1) uniform acceptance draws in (0, 1] (row 0 unused) and
    ``u_gum`` (..., N1, V) uniform Gumbel noise. Node j accepts iff
    ``u_acc[j] < p_parent(tokens[j])`` (the ``min(1, p/q)`` rule with
    a point-mass q, applied edge-wise along every root path); the
    correction candidate of each row is drawn from p with ALL of that
    node's drafted children FILTERED (the point-mass residual over the
    set of drafts rejected at that node — the chain's single-child
    filter, generalized), and the winner row's candidate is emitted.
    At branching 1 this degenerates to :func:`verify_sampled` edge for
    edge. A drafted token the filter removed carries p == 0 and is
    always rejected."""
    s = filtered_scaled(logits, temperature=temperature, top_k=top_k,
                        top_p=top_p)                     # (..., N1, V)
    n1 = s.shape[-2]
    real = tokens != NO_DRAFT
    cols_v = jax.lax.broadcasted_iota(jnp.int32, s.shape, s.ndim - 1)
    tok_oh = (cols_v == tokens[..., None]).astype(jnp.float32)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    z = jnp.sum(e, axis=-1)                              # (..., N1)
    # t[..., r, c] = e[..., r, tokens[c]] — every node's token mass
    # under every row, one MXU pass instead of an (N1, V) gather
    t = jnp.einsum("...rv,...cv->...rc", e, tok_oh,
                   preferred_element_type=jnp.float32)
    po = _parent_onehot(parents, n1)                     # (..., c, r)
    tt = jnp.swapaxes(t, -1, -2)                         # (..., c, r)
    p_num = jnp.sum(jnp.where(po, tt, 0.0), axis=-1)
    p_den = jnp.sum(jnp.where(po, z[..., None, :], 0.0), axis=-1)
    acc = (u_acc < p_num / p_den) & real
    a, j_star = tree_accepted_path(acc, anc)
    # child[..., r, c] = 1 iff c is a real drafted child of r; the
    # correction row r filters every child token it just rejected
    child = (jnp.swapaxes(po, -1, -2).astype(jnp.float32)
             * real.astype(jnp.float32)[..., None, :])
    child_oh = jnp.einsum("...rc,...cv->...rv", child, tok_oh,
                          preferred_element_type=jnp.float32) > 0.5
    cand = gumbel_argmax(jnp.where(child_oh, FILTERED, s), u_gum)
    return a, j_star, select_row(cand, j_star)


def _verify_kernel(logits_ref, drafted_ref, *refs, k1, temperature,
                   top_k, top_p, sampled):
    """One grid row: the whole (k+1, V) logit block is VMEM-resident;
    every reduction below runs on it in place — the only HBM traffic is
    the block reads and two 8-lane int32 writes."""
    if sampled:
        u_acc_ref, u_gum_ref, a_ref, tok_ref = refs
    else:
        a_ref, tok_ref = refs
    s = logits_ref[0]                       # (k+1, V)
    drafted = drafted_ref[0, :k1]           # (k+1,) — bonus lane NO_DRAFT
    if sampled:
        a, tok = verify_sampled(s, drafted, u_acc_ref[0, :k1],
                                u_gum_ref[0], temperature=temperature,
                                top_k=top_k, top_p=top_p)
    else:
        a, tok = verify_greedy(s, drafted)
    a_ref[:] = jnp.broadcast_to(a[None, None], (1, _LSE_LANES))
    tok_ref[:] = jnp.broadcast_to(tok[None, None], (1, _LSE_LANES))


def fused_verify_fwd(logits, drafted_pad, u_acc, u_gum, *, temperature,
                     top_k, top_p, interpret=False):
    """(b, k+1, V) logits + lane-padded drafts/noise → ``(accept_len
    (b,), next_token (b,))`` int32; one kernel invocation, grid over
    batch rows. ``drafted_pad``/``u_acc`` arrive padded to
    ``VERIFY_LANES`` lanes (contents beyond k+1 ignored); greedy mode
    (``temperature == 0``) takes ``u_acc``/``u_gum`` as None. V must be
    a 128-multiple (lane tiling); the op-level wrapper gates on that."""
    b, k1, V = logits.shape
    sampled = temperature > 0.0
    if k1 > VERIFY_LANES:  # unreachable through the drafters (k <= 32)
        raise ValueError(
            f"fused verify kernel carries drafted ids in one "
            f"{VERIFY_LANES}-lane block; got k+1 = {k1} rows — use the "
            f"XLA fallback (impl='xla') for drafts this long")
    in_specs = [
        pl.BlockSpec((1, k1, V), lambda i: (i, 0, 0)),
        pl.BlockSpec((1, VERIFY_LANES), lambda i: (i, 0)),
    ]
    args = [logits, drafted_pad]
    if sampled:
        in_specs.append(pl.BlockSpec((1, VERIFY_LANES), lambda i: (i, 0)))
        in_specs.append(pl.BlockSpec((1, k1, V), lambda i: (i, 0, 0)))
        args.extend([u_acc, u_gum])
    a, tok = pl.pallas_call(
        functools.partial(_verify_kernel, k1=k1, temperature=temperature,
                          top_k=top_k, top_p=top_p, sampled=sampled),
        grid=(b,),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, _LSE_LANES), lambda i: (i, 0)),
                   pl.BlockSpec((1, _LSE_LANES), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((b, _LSE_LANES), jnp.int32),
                   jax.ShapeDtypeStruct((b, _LSE_LANES), jnp.int32)],
        interpret=interpret,
    )(*args)
    return a[:, 0], tok[:, 0]


def _verify_tree_kernel(logits_ref, tokens_ref, parents_ref, anc_ref,
                        *refs, n1, temperature, top_k, top_p, sampled):
    """One grid row of the TREE verify: the whole (N1, V) logit block is
    VMEM-resident; the parent-pointer walk, per-edge acceptance, path
    max, and correction draw all run on it in place — three 128-lane
    int32 writes come back."""
    if sampled:
        u_acc_ref, u_gum_ref, a_ref, j_ref, tok_ref = refs
    else:
        a_ref, j_ref, tok_ref = refs
    s = logits_ref[0]                       # (N1, V)
    tokens = tokens_ref[0, :n1]
    parents = parents_ref[0, :n1]
    anc = anc_ref[0, :, :n1]                # (N1, N1)
    if sampled:
        a, j_star, tok = verify_tree_sampled(
            s, tokens, parents, anc, u_acc_ref[0, :n1], u_gum_ref[0],
            temperature=temperature, top_k=top_k, top_p=top_p)
    else:
        a, j_star, tok = verify_tree_greedy(s, tokens, parents, anc)
    a_ref[:] = jnp.broadcast_to(a[None, None], (1, _LSE_LANES))
    j_ref[:] = jnp.broadcast_to(j_star[None, None], (1, _LSE_LANES))
    tok_ref[:] = jnp.broadcast_to(tok[None, None], (1, _LSE_LANES))


def fused_verify_tree_fwd(logits, tokens_pad, parents_pad, anc_pad,
                          u_acc, u_gum, *, temperature, top_k, top_p,
                          interpret=False):
    """(b, N1, V) logits + lane-padded tree operands → ``(accept_len
    (b,), j_star (b,), next_token (b,))`` int32; one kernel invocation,
    grid over batch rows. ``tokens_pad``/``parents_pad``/``u_acc``
    arrive padded to ``VERIFY_LANES`` lanes and ``anc_pad`` to
    (b, N1, VERIFY_LANES) (contents beyond N1 ignored); greedy mode
    takes ``u_acc``/``u_gum`` as None. V must be a 128-multiple."""
    b, n1, V = logits.shape
    sampled = temperature > 0.0
    if n1 > VERIFY_LANES:  # unreachable through the drafters (N <= 32)
        raise ValueError(
            f"fused tree-verify kernel carries node ids in one "
            f"{VERIFY_LANES}-lane block; got N+1 = {n1} rows — use the "
            f"XLA fallback (impl='xla') for trees this wide")
    in_specs = [
        pl.BlockSpec((1, n1, V), lambda i: (i, 0, 0)),
        pl.BlockSpec((1, VERIFY_LANES), lambda i: (i, 0)),
        pl.BlockSpec((1, VERIFY_LANES), lambda i: (i, 0)),
        pl.BlockSpec((1, n1, VERIFY_LANES), lambda i: (i, 0, 0)),
    ]
    args = [logits, tokens_pad, parents_pad, anc_pad]
    if sampled:
        in_specs.append(pl.BlockSpec((1, VERIFY_LANES), lambda i: (i, 0)))
        in_specs.append(pl.BlockSpec((1, n1, V), lambda i: (i, 0, 0)))
        args.extend([u_acc, u_gum])
    a, j_star, tok = pl.pallas_call(
        functools.partial(_verify_tree_kernel, n1=n1,
                          temperature=temperature, top_k=top_k,
                          top_p=top_p, sampled=sampled),
        grid=(b,),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, _LSE_LANES), lambda i: (i, 0)),
                   pl.BlockSpec((1, _LSE_LANES), lambda i: (i, 0)),
                   pl.BlockSpec((1, _LSE_LANES), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((b, _LSE_LANES), jnp.int32),
                   jax.ShapeDtypeStruct((b, _LSE_LANES), jnp.int32),
                   jax.ShapeDtypeStruct((b, _LSE_LANES), jnp.int32)],
        interpret=interpret,
    )(*args)
    return a[:, 0], j_star[:, 0], tok[:, 0]
