"""Pallas blocked matmul with fused bias + activation epilogue.

TPU-native analog of ``fused_dense_cuda``'s cuBLASLt epilogue GEMMs
(``csrc/fused_dense_cuda.cu:10-60``) and ``mlp_cuda``'s chained GEMM+bias+act
(``csrc/mlp_cuda.cu:47-200``): one kernel computes ``act(x @ w + b)`` without
a round-trip to HBM for the intermediate. Classic MXU pattern: grid over
(M/bm, N/bn, K/bk), fp32 accumulator in VMEM scratch, epilogue applied on the
final K step.

Constraints: M, N, K multiples of the block sizes (the caller pads);
accumulation is always fp32 (``preferred_element_type``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _apply_act(r, activation):
    if activation == "none":
        return r
    if activation == "gelu":
        return jax.nn.gelu(r, approximate=True)
    if activation == "relu":
        return jnp.maximum(r, 0.0)
    if activation == "sigmoid":
        return jax.nn.sigmoid(r)
    raise ValueError(f"unknown activation {activation!r}")


def _matmul_kernel(x_ref, w_ref, b_ref, o_ref, acc_ref, *, activation, k_steps):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(
        x_ref[:], w_ref[:], preferred_element_type=jnp.float32
    )

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _epilogue():
        r = acc_ref[:]
        if b_ref is not None:
            r = r + b_ref[:].astype(jnp.float32)  # (1, bn) broadcasts over rows
        o_ref[:] = _apply_act(r, activation).astype(o_ref.dtype)


def _round_up(v, m):
    return -(-v // m) * m


def matmul_bias_act(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array | None = None,
    *,
    activation: str = "none",
    out_dtype=None,
    bm: int = 256,
    bn: int = 256,
    bk: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """``act(x @ w + b)``; x: (M, K), w: (K, N), b: (N,) or None."""
    M, K = x.shape
    K2, N = w.shape
    assert K == K2, (x.shape, w.shape)
    out_dtype = out_dtype or x.dtype
    bm, bn, bk = min(bm, _round_up(M, 8)), min(bn, _round_up(N, 128)), min(bk, _round_up(K, 128))
    Mp, Np, Kp = _round_up(M, bm), _round_up(N, bn), _round_up(K, bk)
    if (Mp, Kp) != (M, K):
        x = jnp.pad(x, ((0, Mp - M), (0, Kp - K)))
    if (Kp, Np) != (K, N):
        w = jnp.pad(w, ((0, Kp - K), (0, Np - N)))
    if b is not None and Np != N:
        b = jnp.pad(b, (0, Np - N))
    k_steps = Kp // bk

    base = functools.partial(_matmul_kernel, activation=activation, k_steps=k_steps)
    in_specs = [
        pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
        pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
    ]
    args = [x, w]
    if b is not None:
        # bias rides as (1, N): a flat 1D bf16 operand hits a Mosaic/XLA
        # layout mismatch ((1024)(128) vs (256)(128) sublane packing) on real
        # TPU; 2D row form tiles cleanly.
        in_specs.append(pl.BlockSpec((1, bn), lambda i, j, k: (0, j)))
        args.append(b.reshape(1, -1))
        kernel = base
    else:
        kernel = lambda xr, wr, orf, acc: base(xr, wr, None, orf, acc)  # noqa: E731

    out = pl.pallas_call(
        kernel,
        grid=(Mp // bm, Np // bn, k_steps),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((Mp, Np), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(*args)
    return out[:M, :N]
