"""Pallas TPU kernels — the native tier, analog of the reference's ``csrc/``.

Each module holds raw ``pallas_call`` kernels; the ``jax.custom_vjp`` wiring
and eligibility checks live one level up in ``apex_tpu/ops/*.py``.
"""

from jax.experimental.pallas import tpu as _pltpu

# jax renamed TPUCompilerParams -> CompilerParams across releases; the
# kernels use the new name, so on older jax alias it once here (every
# kernel module imports this package first).
if not hasattr(_pltpu, "CompilerParams"):  # pragma: no cover - jax-version dep
    _pltpu.CompilerParams = _pltpu.TPUCompilerParams


def exact_block(n: int, pref: int, quantum: int) -> int:
    """Largest ``quantum``-multiple divisor of ``n`` that is <= ``pref``, or
    0 when none exists. Blocks must tile the array exactly — Pallas pads
    partial edge blocks with *uninitialized* data, which would flow into
    softmax/sum accumulators. Shared by the attention and xentropy kernels.
    """
    b = min(pref, n)
    b -= b % quantum
    while b > quantum and n % b:
        b -= quantum
    return b if b >= quantum and n % b == 0 else 0
