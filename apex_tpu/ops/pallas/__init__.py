"""Pallas TPU kernels — the native tier, analog of the reference's ``csrc/``.

Each module holds raw ``pallas_call`` kernels; the ``jax.custom_vjp`` wiring
and eligibility checks live one level up in ``apex_tpu/ops/*.py``.
"""
