"""Pallas scaled-masked-softmax kernels (forward + backward-from-probs).

TPU-native equivalent of ``scaled_masked_softmax_cuda`` and
``scaled_upper_triang_masked_softmax_cuda``
(``csrc/megatron/scaled_masked_softmax.h``, ``scaled_upper_triang_masked_softmax.h``).
Contract matches the CUDA warp kernels: forward computes
``softmax(scale * x + mask)`` with the mask applied as a -10000 additive fill
(boolean mask) or a built-in causal triangle; backward consumes the *saved
probabilities*: ``dx = scale * y * (dy - sum(dy * y))``.

Layout: logits viewed as (rows, sk); one grid step owns (block_rows, sk) in
VMEM. The causal variant derives its row's global query index from the grid
position, so sq never has to fit in one block. Unlike the CUDA kernels there
is no ``16 < sk <= 2048`` cap — blocks just need sk % 128 == 0.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

MASK_FILL = -10000.0  # matches the CUDA kernels' masked fill value


def _pick_block_rows(sk: int, vmem_budget: int = 2 * 1024 * 1024) -> int:
    br = max(8, min(512, vmem_budget // (sk * 4)))
    p = 8
    while p * 2 <= br:
        p *= 2
    return p


def _pad_rows(a, br):
    pad = (-a.shape[0]) % br
    return jnp.pad(a, ((0, pad), (0, 0))) if pad else a


# --- forward ------------------------------------------------------------------

def _softmax_fwd_kernel(x_ref, mask_ref, y_ref, *, scale, causal, sq):
    x = x_ref[:].astype(jnp.float32) * scale
    rows, sk = x.shape
    if causal:
        # global query index of each row in this block; rows cycle through
        # sq within each (batch*head) slab, and blocks are row-contiguous.
        i = pl.program_id(0)
        row0 = i * rows
        q_idx = (row0 + jax.lax.broadcasted_iota(jnp.int32, (rows, sk), 0)) % sq
        k_idx = jax.lax.broadcasted_iota(jnp.int32, (rows, sk), 1)
        x = jnp.where(k_idx <= q_idx, x, MASK_FILL)
    elif mask_ref is not None:
        x = jnp.where(mask_ref[:] != 0, MASK_FILL, x)
    m = jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x - m)
    y = e / jnp.sum(e, axis=1, keepdims=True)
    y_ref[:] = y.astype(y_ref.dtype)


def softmax_fwd(x2d, mask2d, *, scale: float, causal: bool, sq: int, interpret: bool):
    """x2d: (rows, sk); mask2d: same shape (nonzero ⇒ masked) or None."""
    rows, sk = x2d.shape
    br = _pick_block_rows(sk)
    if causal:
        # keep block rows within one (batch, head) slab so q_idx math is exact
        while br > 8 and sq % br:
            br //= 2
        if sq % br:
            br = 8 if sq % 8 == 0 else 1
    x2d = _pad_rows(x2d, br)
    rows_p = x2d.shape[0]
    base = functools.partial(_softmax_fwd_kernel, scale=scale, causal=causal, sq=sq)
    in_specs = [pl.BlockSpec((br, sk), lambda i: (i, 0))]
    args = [x2d]
    if mask2d is not None and not causal:
        in_specs.append(pl.BlockSpec((br, sk), lambda i: (i, 0)))
        args.append(_pad_rows(mask2d, br))
        kernel = base
    else:
        kernel = lambda x, y: base(x, None, y)  # noqa: E731
    y = pl.pallas_call(
        kernel,
        grid=(rows_p // br,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((br, sk), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows_p, sk), x2d.dtype),
        interpret=interpret,
    )(*args)
    return y[:rows]


# --- backward -----------------------------------------------------------------

def _softmax_bwd_kernel(dy_ref, y_ref, dx_ref, *, scale):
    dy = dy_ref[:].astype(jnp.float32)
    y = y_ref[:].astype(jnp.float32)
    dot = jnp.sum(dy * y, axis=1, keepdims=True)
    dx_ref[:] = (scale * y * (dy - dot)).astype(dx_ref.dtype)


def softmax_bwd(dy2d, y2d, *, scale: float, interpret: bool):
    rows, sk = y2d.shape
    br = _pick_block_rows(sk)
    dy2d, y2d = _pad_rows(dy2d, br), _pad_rows(y2d, br)
    rows_p = y2d.shape[0]
    dx = pl.pallas_call(
        functools.partial(_softmax_bwd_kernel, scale=scale),
        grid=(rows_p // br,),
        in_specs=[
            pl.BlockSpec((br, sk), lambda i: (i, 0)),
            pl.BlockSpec((br, sk), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((br, sk), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows_p, sk), y2d.dtype),
        interpret=interpret,
    )(dy2d, y2d)
    return dx[:rows]
