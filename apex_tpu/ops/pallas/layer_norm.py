"""Pallas LayerNorm / RMSNorm forward+backward kernels.

TPU-native equivalent of ``fused_layer_norm_cuda``
(``csrc/layer_norm_cuda_kernel.cu``; exports ``csrc/layer_norm_cuda.cpp:429-441``).
Same contract as the CUDA kernels: forward emits (y, mean, rstd) so backward
never recomputes the reduction; backward emits dx plus fully reduced
(dgamma, dbeta) — accumulated in-kernel across the sequential row-block grid
into one revisited output block, replacing the CUDA version's two-stage
``cuComputePartGradGammaBeta``/``cuComputeGradGammaBeta`` reduction.

Layout: inputs are viewed as (rows, hidden); one grid step owns a
(block_rows, hidden) tile, reductions run on the VPU along the lane axis.
All statistics math is fp32 regardless of input dtype (the kernels'
``U = float`` accumulator type).

Constraints (checked by the caller): hidden % 128 == 0 and the whole
(block_rows, hidden) fp32 tile must fit VMEM; rows are padded by Pallas.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _pick_block_rows(rows: int, hidden: int, vmem_budget: int = 2 * 1024 * 1024) -> int:
    """Largest power-of-two row block whose fp32 tile fits the VMEM budget."""
    br = max(8, min(512, vmem_budget // (hidden * 4)))
    # round down to a power of two >= 8
    p = 8
    while p * 2 <= br:
        p *= 2
    return p


# --- forward ------------------------------------------------------------------

def _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps, rms):
    x = x_ref[:].astype(jnp.float32)
    if rms:
        mean = jnp.zeros((x.shape[0], 1), jnp.float32)
        xc = x
    else:
        mean = jnp.mean(x, axis=1, keepdims=True)
        xc = x - mean
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    y = xhat
    if w_ref is not None:
        y = y * w_ref[:].astype(jnp.float32)
    if b_ref is not None:
        y = y + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _pad_rows(a, br):
    """Zero-pad the row axis to a block multiple: Pallas pads partial input
    blocks with *undefined* data, which would poison the in-kernel
    reductions; explicit zeros are inert in every reduction below."""
    rows = a.shape[0]
    pad = (-rows) % br
    return jnp.pad(a, ((0, pad), (0, 0))) if pad else a


def ln_fwd(x2d, weight, bias, *, eps: float, rms: bool, interpret: bool):
    """x2d: (rows, hidden). Returns (y, mean(rows,1), rstd(rows,1)) fp32 stats."""
    rows, hidden = x2d.shape
    br = _pick_block_rows(rows, hidden)
    x2d = _pad_rows(x2d, br)
    rows_p = x2d.shape[0]
    grid = (rows_p // br,)
    base = functools.partial(_ln_fwd_kernel, eps=eps, rms=rms)
    if weight is None and bias is not None:
        raise ValueError("bias without weight is not supported")

    in_specs = [pl.BlockSpec((br, hidden), lambda i: (i, 0))]
    args = [x2d]
    # affine params ride as (1, hidden): flat 1D bf16 operands hit a
    # Mosaic/XLA sublane-packing layout mismatch on real TPU hardware
    if weight is not None:
        in_specs.append(pl.BlockSpec((1, hidden), lambda i: (0, 0)))
        args.append(weight.reshape(1, hidden))
    if bias is not None:
        in_specs.append(pl.BlockSpec((1, hidden), lambda i: (0, 0)))
        args.append(bias.reshape(1, hidden))
    # explicit positional signatures: Pallas passes inputs then outputs
    # positionally, so absent refs must vanish from the signature entirely
    if weight is not None and bias is not None:
        kernel = base
    elif weight is not None:
        kernel = lambda x, w, y, m, r: base(x, w, None, y, m, r)  # noqa: E731
    else:
        kernel = lambda x, y, m, r: base(x, None, None, y, m, r)  # noqa: E731

    y, mean, rstd = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows_p, hidden), x2d.dtype),
            jax.ShapeDtypeStruct((rows_p, 1), jnp.float32),
            jax.ShapeDtypeStruct((rows_p, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return y[:rows], mean[:rows], rstd[:rows]


# --- backward -----------------------------------------------------------------

def _ln_bwd_kernel(
    dy_ref, x_ref, mean_ref, rstd_ref, w_ref,
    dx_ref, dw_ref, db_ref, *, rms, has_affine,
):
    dy = dy_ref[:].astype(jnp.float32)
    x = x_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]
    if rms:
        xhat = x * rstd
    else:
        xhat = (x - mean_ref[:]) * rstd
    if has_affine:
        w = w_ref[:].astype(jnp.float32)
        dyw = dy * w
        # dgamma/dbeta accumulate across the sequential grid into one
        # revisited output block (the CUDA version's two-stage
        # cuComputePartGradGammaBeta/cuComputeGradGammaBeta reduction)
        @pl.when(pl.program_id(0) == 0)
        def _init():
            dw_ref[:] = jnp.zeros_like(dw_ref)
            db_ref[:] = jnp.zeros_like(db_ref)

        dw_ref[:] += jnp.sum(dy * xhat, axis=0, keepdims=True)
        db_ref[:] += jnp.sum(dy, axis=0, keepdims=True)
    else:
        dyw = dy
    h = x.shape[1]
    c2 = jnp.sum(dyw * xhat, axis=1, keepdims=True) / h
    if rms:
        dx = (dyw - xhat * c2) * rstd
    else:
        c1 = jnp.sum(dyw, axis=1, keepdims=True) / h
        dx = (dyw - c1 - xhat * c2) * rstd
    dx_ref[:] = dx.astype(dx_ref.dtype)


def ln_bwd(dy2d, x2d, mean, rstd, weight, *, rms: bool, interpret: bool):
    """Returns (dx, dweight|None, dbias|None); dweight/dbias fp32."""
    rows, hidden = x2d.shape
    br = _pick_block_rows(rows, hidden)
    dy2d, x2d = _pad_rows(dy2d, br), _pad_rows(x2d, br)
    mean, rstd = _pad_rows(mean, br), _pad_rows(rstd, br)
    rows_p = x2d.shape[0]
    nblocks = rows_p // br
    has_affine = weight is not None
    base = functools.partial(_ln_bwd_kernel, rms=rms, has_affine=has_affine)

    in_specs = [
        pl.BlockSpec((br, hidden), lambda i: (i, 0)),
        pl.BlockSpec((br, hidden), lambda i: (i, 0)),
        pl.BlockSpec((br, 1), lambda i: (i, 0)),
        pl.BlockSpec((br, 1), lambda i: (i, 0)),
    ]
    args = [dy2d, x2d, mean, rstd]
    if has_affine:
        in_specs.append(pl.BlockSpec((1, hidden), lambda i: (0, 0)))
        args.append(weight.reshape(1, hidden))
        kernel = base
    else:
        kernel = lambda dy, x, m, r, dx, dwp, dbp: base(  # noqa: E731
            dy, x, m, r, None, dx, dwp, dbp
        )

    dx, dw, db = pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows_p, hidden), x2d.dtype),
            jax.ShapeDtypeStruct((1, hidden), jnp.float32),
            jax.ShapeDtypeStruct((1, hidden), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)
        ),
        interpret=interpret,
    )(*args)
    if has_affine:
        return dx[:rows], dw[0], db[0]
    return dx[:rows], None, None
