"""Fused decode-attention Pallas kernel: one query token against a KV cache.

The decode hot path is HBM-bound — each generated token must stream the
whole KV cache through the chip once, and arithmetic intensity is O(1)
(one query row per cache row). What a kernel can win here is therefore
not FLOPs but *passes*: the composed XLA formulation materializes the
(heads, max_s) score tensor, writes it, reads it back for the row max,
writes the exp, reads it again for the sum — each a full staging pass
over an O(max_s) tensor ("LLM Inference Acceleration via Efficient
Operation Fusion", arXiv:2502.17728, makes exactly this staging-write
argument for softmax/layernorm on decode). This kernel runs the online-
softmax recurrence in VMEM scratch: the cache streams HBM→VMEM exactly
once and nothing O(max_s) is ever written back.

Layout contract (the attention-native cache layout the inference engine
allocates): q ``(b·h_kv, group, d)`` — the query heads of one kv group
folded into the sublane dim — and k/v ``(b·h_kv, max_s, d)``, a free
reshape of the engine's ``(b, h_kv, max_s, d)`` cache. ``lengths`` rides
the same (rows, 1, LANES) lane carrier as the flash kernels' kv_lens;
KV blocks entirely past a row's length are skipped dynamically (their
DMA still runs — BlockSpec copies are unconditional), so short contexts
in a long cache pay MXU time proportional to the *current* length.

GQA falls out of the layout: the group's q heads share the kv row as
rows of one (group, bk) score block — the head-grouping analog of the
head-batched projection layout (PERF.md). MQA is group == h.

PAGED variant (:func:`decode_attn_paged_fwd`): the serving engine's KV
cache is not one contiguous ``max_s`` strip per sequence but a set of
fixed-size BLOCKS scattered through one shared pool
(``apex_tpu.serving.kv_blocks``), named by a per-slot block table. The
kernel body is IDENTICAL — same online-softmax recurrence, same
dead-row/length masking, same block skip — only the *address* of each kv
block changes: the table rides as a scalar-prefetch operand
(``pltpu.PrefetchScalarGridSpec``) so the BlockSpec index map can read
``table[slot, j]`` on the scalar core while computing the j-th block's
DMA source. Logical column positions (``j*bs + iota``) are unchanged, so
length masking and the in-kernel relative bias work untouched.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops.pallas import exact_block
from apex_tpu.ops.pallas.attention import (_LSE_LANES, _REL_LANES, NEG_INF,
                                           _kvlen_rows,
                                           relative_position_bucket)


def _decode_kernel(*refs, scale, bk, nk, rel=None, quant=False):
    """Online-softmax decode step for one (batch, kv-head) row.

    Grid (b·h_kv, nk): the kv axis is the ONLY sequential dim; scratch
    carries (m, l, acc) across kv blocks and the output is written once
    at the last block — no (group, max_s) score tensor exists anywhere,
    in VMEM or HBM.

    ``rel = (num_buckets, max_distance)`` (static) adds the T5 CAUSAL
    bucketed relative bias recomputed in-kernel from a (group, 128)
    head-major table block: the query IS position ``kvlen - 1``, so
    rel_pos = col − (kvlen − 1) needs no extra operand beyond the table —
    the decode sibling of the flash kernels' ``rel_bias``.

    ``quant`` (static): the k/v refs hold int8 rows and two extra
    (1, bk) fp32 refs carry the per-row scales — the block dequantizes
    IN VMEM right after its (halved) HBM→VMEM copy, so the decode
    stream pays int8 bandwidth and fp32 math (the whole point of the
    quantized pool: the kernel is HBM-bound, the bytes are the cost).
    """
    refs = list(refs)
    q_ref, k_ref, v_ref, len_ref = refs[:4]
    n = 4
    ks_ref = vs_ref = None
    if quant:
        ks_ref, vs_ref = refs[n], refs[n + 1]
        n += 2
    if rel is not None:
        rtab_ref = refs[n]
        n += 1
    o_ref, m_scr, l_scr, acc_scr = refs[n:]
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    kvlen = len_ref[0, 0, 0]

    # skip KV blocks entirely past the current length — decode against a
    # pre-allocated max_s cache must cost MXU time ~ the LIVE prefix only
    @pl.when(j * bk < kvlen)
    def _step():
        q = q_ref[0]  # (group, d) — the kv group's query heads
        if quant:
            # in-VMEM dequantize: int8 block × per-row fp32 scale
            k = k_ref[0].astype(jnp.float32) * ks_ref[0][:, None]
            q = q.astype(jnp.float32)
        else:
            k = k_ref[0]  # (bk, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (group, bk)
        cols = j * bk + jax.lax.broadcasted_iota(
            jnp.int32, (q.shape[0], bk), 1)
        if rel is not None:
            nbk, maxd = rel
            buckets = relative_position_bucket(
                cols - (kvlen - 1), bidirectional=False, num_buckets=nbk,
                max_distance=maxd)  # (group, bk), rows identical
            bias = jnp.zeros(s.shape, jnp.float32)
            for b in range(nbk):
                bias = bias + jnp.where(buckets == b,
                                        rtab_ref[:, b:b + 1],
                                        jnp.float32(0.0))
            s = s + bias
        s = jnp.where(cols < kvlen, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        if quant:
            v = v_ref[0].astype(jnp.float32) * vs_ref[0][:, None]
            acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
                p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(j == nk - 1)
    def _finish():
        # length-0 rows never ran a step: l == 0 → zeros out (the flash
        # kernels' dead-row convention)
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


def decode_attn_fwd(q, k, v, lengths, *, scale, rel_bias=None, bk=512,
                    interpret=False):
    """q (rows, group, d); k/v (rows, max_s, d) with rows = b·h_kv;
    ``lengths`` (rows,) int32 — positions >= the length are masked and
    whole blocks past it are skipped. Returns (rows, group, d) context.
    Forward-only: decode never differentiates.

    ``rel_bias``: ``(table (h, 128) fp32 head-major, (num_buckets,
    max_distance))`` — causal T5 bucketed bias recomputed in-kernel;
    row r's table block covers its kv group's q heads
    ([(r % h_kv)·group, ...))."""
    rows, group, d = q.shape
    max_s = k.shape[1]
    bk = exact_block(max_s, bk, 128) or max_s
    nk = pl.cdiv(max_s, bk)
    rel, rel_static = (None, None) if rel_bias is None else (
        rel_bias[0], rel_bias[1])

    in_specs = [
        pl.BlockSpec((1, group, d), lambda b, j: (b, 0, 0)),
        pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
        pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
        pl.BlockSpec((1, 1, _LSE_LANES), lambda b, j: (b, 0, 0)),
    ]
    args = [q, k, v, _kvlen_rows(lengths, rows)]
    if rel is not None:
        # rows iterate (batch, kv head); table rows are q heads — row r's
        # group block sits at head offset (r % h_kv)·group
        h_kv = rel.shape[0] // group
        in_specs.append(pl.BlockSpec(
            (group, _REL_LANES),
            lambda b, j, hk=h_kv: (b % hk, 0)))
        args.append(rel)

    return pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, bk=bk, nk=nk,
                          rel=rel_static),
        grid=(rows, nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, group, d), lambda b, j: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, group, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(*args)


def _paged_kernel(tbl_ref, *refs, scale, bk, nk, rel=None, quant=False):
    """Scalar-prefetch wrapper: the block table is consumed entirely by
    the index maps (it addresses the DMAs); the body never touches it —
    logical positions, masking and bias are exactly the contiguous
    kernel's."""
    del tbl_ref
    _decode_kernel(*refs, scale=scale, bk=bk, nk=nk, rel=rel, quant=quant)


def decode_attn_paged_fwd(q, k_pool, v_pool, lengths, block_tables, *,
                          scale, rel_bias=None, k_scale=None,
                          v_scale=None, interpret=False):
    """Paged decode attention: q ``(rows, group, d)`` with
    ``rows = b·h_kv``; ``k_pool``/``v_pool`` ``(num_blocks·h_kv, bs, d)``
    — the free reshape of the serving pool's ``(num_blocks, h_kv, bs,
    d)`` layout; ``block_tables`` ``(b, nb_max)`` int32 mapping each
    slot's j-th LOGICAL kv block to a pool block id; ``lengths``
    ``(rows,)`` int32 live positions per row. Every table entry must be
    a valid pool index — the engine zero-fills unused entries with the
    reserved dead block 0, whose DMA is harmless (blocks past a row's
    length are compute-skipped, and in-block tails are masked by the
    length like the contiguous kernel). Returns (rows, group, d).

    ``rel_bias`` as in :func:`decode_attn_fwd` (cols are logical
    positions, so the causal bucketed bias is indirection-oblivious).

    ``k_scale``/``v_scale``: the int8-pool path — ``(num_blocks, bs)``
    fp32 per-row scales riding their own scalar-prefetched index maps
    (the SAME table lookup, minus the h_kv fold: scales are shared
    across kv heads and head_dim); the kernel dequantizes each block in
    VMEM, so the HBM stream is int8 (indirection-oblivious, like the
    bucketed bias).
    """
    rows, group, d = q.shape
    b, nb = block_tables.shape
    h_kv = rows // b
    bs = k_pool.shape[1]
    rel, rel_static = (None, None) if rel_bias is None else (
        rel_bias[0], rel_bias[1])
    quant = k_scale is not None

    # index maps receive the prefetched table LAST; k/v maps translate
    # (row, j) -> pool row table[row // h_kv, j] * h_kv + row % h_kv
    in_specs = [
        pl.BlockSpec((1, group, d), lambda r, j, tbl: (r, 0, 0)),
        pl.BlockSpec((1, bs, d),
                     lambda r, j, tbl, hk=h_kv: (tbl[r // hk, j] * hk
                                                 + r % hk, 0, 0)),
        pl.BlockSpec((1, bs, d),
                     lambda r, j, tbl, hk=h_kv: (tbl[r // hk, j] * hk
                                                 + r % hk, 0, 0)),
        pl.BlockSpec((1, 1, _LSE_LANES), lambda r, j, tbl: (r, 0, 0)),
    ]
    args = [q, k_pool, v_pool, _kvlen_rows(lengths, rows)]
    if quant:
        in_specs.append(pl.BlockSpec(
            (1, bs), lambda r, j, tbl, hk=h_kv: (tbl[r // hk, j], 0)))
        in_specs.append(pl.BlockSpec(
            (1, bs), lambda r, j, tbl, hk=h_kv: (tbl[r // hk, j], 0)))
        args.extend([k_scale, v_scale])
    if rel is not None:
        in_specs.append(pl.BlockSpec(
            (group, _REL_LANES),
            lambda r, j, tbl, hk=h_kv: (r % hk, 0)))
        args.append(rel)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(rows, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, group, d), lambda r, j, tbl: (r, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_kernel, scale=scale, bk=bs, nk=nb,
                          rel=rel_static, quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rows, group, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), *args)
