"""Blockwise (flash) attention Pallas kernels, forward + backward.

TPU-native replacement for the reference's ``fmhalib``
(``apex/contrib/csrc/fmha/`` — fp16, seq≤512, head_dim 64, SM80 only) and
``fast_multihead_attn`` (``apex/contrib/csrc/multihead_attn/``): one kernel
family with no sequence cap — the online-softmax recurrence streams KV blocks
through VMEM, so sequence length is bounded by HBM, not by a kernel table.

Shapes: q (bh, sq, d), k/v (bh, sk, d) with bh = batch*heads folded. Forward
returns (o, lse) — lse is the softmax log-normalizer row vector that backward
reuses (the same residual the CUTLASS fmha saves). Backward is the standard
two-kernel split: dq accumulates over KV blocks, dk/dv over Q blocks, with
D = rowsum(do·o) precomputed by the caller.

Block sizes default to 1024 (measured best on v5e at seq>=1024 — small
blocks leave the head_dim-64 MXU contraction starved and grid overhead
dominant); d must equal the full head dim
(trailing-dim tiling rule). Causal masking skips whole KV blocks above the
diagonal — the work saving that makes causal flash ~2x dense.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops.pallas import exact_block

NEG_INF = -1e30

# --- in-kernel attention dropout ---------------------------------------------
#
# The reference's fused attention kernels take a dropout probability inside
# the kernel (``apex/contrib/csrc/fmha/fmha_api.cpp:44,80-83``, Philox
# counters; ``apex/contrib/multihead_attn``'s fused softmax-dropout). The
# TPU formulation replaces the stateful Philox stream with a STATELESS
# counter-based hash of the global element coordinates: keep(t, row, col)
# is a pure function of (seed, q-head index, score position), so
# - forward and backward regenerate identical masks with zero saved state
#   (the O(s²) mask tensor never exists — only (bq, bk) blocks in VMEM);
# - the mask is independent of block sizes and of kernel vs XLA dispatch
#   (the XLA fallback evaluates the same function — bit-identical masks);
# - interpret-mode tests cover the real code path (pltpu.prng_random_bits
#   has no interpret lowering in this jax; plain vector ops do).
# Hash: murmur3's 32-bit finalizer (full avalanche) over a per-(seed, t)
# key xor a unique per-element counter — splitmix-style, plenty for
# Bernoulli masks.

_U32 = jnp.uint32


def _fmix32(h):
    """murmur3 fmix32: bijective avalanche mix on uint32."""
    h = h ^ (h >> _U32(16))
    h = h * _U32(0x85EBCA6B)
    h = h ^ (h >> _U32(13))
    h = h * _U32(0xC2B2AE35)
    h = h ^ (h >> _U32(16))
    return h


def dropout_keep(seed, t, rows, cols, rate):
    """Bernoulli(1-rate) keep mask for score elements (rows, cols) of
    q-head ``t``: uniform-in-[0,1) from the hash, compared in the integer
    domain (Mosaic has no uint32->f32 cast). ``seed``/``t`` scalar int32
    (traced ok); ``rows``/``cols`` int32 arrays of GLOBAL score
    coordinates (broadcastable, e.g. (bq, 1) x (1, bk)); ``rate`` static.

    Rows enter through their own fmix pass rather than a ``row·sk + col``
    linear counter: the counter form wraps uint32 when sq·sk > 2^32, which
    would hand row pairs 2^32/sk apart bit-identical masks exactly at the
    long-context scale the kernels advertise (review r4). Per-row key
    material costs one extra fmix32 on a (rows, 1) column — negligible.

    The realized keep probability is ``rate`` quantized to the nearest
    multiple of 2^-24 (the integer-domain compare uses a 24-bit
    threshold); rates below ~3e-8 round to dropout-off (ADVICE r4)."""
    key = _fmix32(seed.astype(_U32) ^ (jnp.asarray(t).astype(_U32)
                                       * _U32(0x9E3779B9)))
    row_key = _fmix32(key ^ rows.astype(_U32))
    thresh = _U32(min(1 << 24, int(round(rate * (1 << 24)))))
    return (_fmix32(row_key ^ cols.astype(_U32)) >> _U32(8)) >= thresh


def _mask_scale(seed, t, i, j, bq, bk, rate):
    """(bq, bk) fp32 dropout multiplier (1/(1-rate) kept, 0 dropped) for
    score block (i, j) — the shared fwd/bwd block recipe."""
    rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
    keep = dropout_keep(seed, t, rows, cols, rate)
    return jnp.where(keep, jnp.float32(1.0 / (1.0 - rate)), 0.0)


# --- in-kernel bucketed relative position bias --------------------------------
#
# The T5 relative bias is a LOOKUP: bias(q_pos, k_pos) = table[bucket(k_pos -
# q_pos), head] with bucket() a cheap closed form (exact small offsets, log-
# spaced large ones). Feeding it to the kernels as a materialized (h, sq, sk)
# operand costs O(h·s²) HBM (~1.6 GB fp32 at s=8192, h=6) — defeating the
# fused kernel's entire value proposition (never materializing O(s²)
# tensors; the reference fmha's core design, ``contrib/csrc/fmha``). Instead
# the kernels take the TINY (num_buckets, h) table itself (padded head-major
# to one (1, 128) VMEM row per head) plus a (2,) SMEM global-offset pair, and
# recompute each (bq, bk) bias tile from the grid coordinates: bucket indices
# from the closed form, then a num_buckets-step select-sum against the table
# row (VPU work ~num_buckets ops/element, overlapped with the MXU matmul;
# the arXiv:2502.17728 recompute-beats-streaming argument). The offsets make
# the SAME kernel correct under context parallelism: a shard whose q rows
# start at global position Q and kv block at K computes bucket((K + c) -
# (Q + r)) — bias follows the data onto any sharding for free.

_REL_LANES = 128  # table rows pad to one full lane row; num_buckets <= 128


def relative_position_bucket(rel_pos, *, bidirectional, num_buckets,
                             max_distance):
    """T5's relative-position bucketing (mesh-tf
    ``_relative_position_bucket``): ``rel_pos = key_pos - query_pos``.
    Half the buckets hold exact small offsets, the other half log-spaced
    larger ones up to ``max_distance``; bidirectional stacks split the
    range by sign, causal stacks clamp the future to bucket 0. Pure jnp on
    any-rank int32 arrays — the SAME function evaluates on (sq, sk) grids
    host-side (materialized oracle) and on (bq, bk) tiles inside the
    Pallas kernels (the sole definition, so kernel and oracle cannot
    drift)."""
    ret = jnp.zeros_like(rel_pos)
    n = -rel_pos
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_large = max_exact + (
        jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
        / jnp.log(max_distance / max_exact)
        * (num_buckets - max_exact)).astype(jnp.int32)
    val_large = jnp.minimum(val_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_large)


def _rel_bias_block(tab_ref, off_ref, i, j, bq, bk, rel):
    """(bq, bk) fp32 bias tile recomputed from grid coordinates: global
    positions from the (2,) SMEM offsets, buckets from the closed form,
    values by a ``num_buckets``-step select-sum over this head's (1, 128)
    table row. ``rel = (num_buckets, bidirectional, max_distance)``."""
    nb, bidir, maxd = rel
    rows = off_ref[0] + i * bq + jax.lax.broadcasted_iota(
        jnp.int32, (bq, 1), 0)
    cols = off_ref[1] + j * bk + jax.lax.broadcasted_iota(
        jnp.int32, (1, bk), 1)
    buckets = relative_position_bucket(
        cols - rows, bidirectional=bidir, num_buckets=nb, max_distance=maxd)
    bias = jnp.zeros((bq, bk), jnp.float32)
    for b in range(nb):
        bias = bias + jnp.where(buckets == b, tab_ref[0, b],
                                jnp.float32(0.0))
    return bias


def _blocks(n, b):
    return pl.cdiv(n, b)


def _fit_block(n, pref):
    """Largest 128-multiple divisor of ``n`` that is <= ``pref``, falling
    back to the whole sequence as one block when n has no 128-multiple
    divisor (the caller's shapes-ok gate rejects such shapes for the
    non-interpret path). Exact tiling matters: Pallas pads partial edge
    blocks with *uninitialized* data, which would flow into the softmax
    accumulators (fwd) and into dk/dv (bwd, padded rows pass the causal
    mask)."""
    return exact_block(n, pref, 128) or n


# --- forward ------------------------------------------------------------------

def _fwd_kernel(*refs, scale, causal, bq, bk, nk, off, varlen, bshd=False,
                rate=0.0, has_bias=False, rel=None):
    """``varlen`` is a STATIC specialization flag: without kv lengths the
    kernel carries no length operand, no per-block length select, and no
    dynamic predicate conjunct — the common (non-padded) call pays nothing.
    ``bshd``: the seq-major layout — q/k/v/o ride (b, s, h·d) folded views
    whose blocks are IDENTICAL to the bh-flat ones (a (bq, d) tile, the
    head picked by the block index along the folded feature dim), so only
    the lse carrier's rank differs ((b, h, sq, LANES) vs (bh, sq, LANES)).
    ``rate > 0`` (static) adds in-kernel probs dropout: the softmax
    normalizer ``l`` accumulates UN-dropped p (dropout applies to the
    normalized probabilities), the output accumulator takes the masked,
    1/(1-rate)-scaled p; masks come from :func:`dropout_keep` on global
    coordinates and a seed operand in SMEM.
    ``has_bias`` (static) adds an additive score-bias operand — a
    (1, bq, bk) block of the (hb, sq, sk) bias array, added to the scaled
    scores BEFORE the causal/varlen masks (the reference's in-kernel
    arbitrary mask, ``csrc/megatron/scaled_masked_softmax.cpp:85-94``,
    generalized to any additive bias — T5 relative position bias rides it).
    ``rel`` (static, exclusive with ``has_bias``) instead RECOMPUTES the T5
    bucketed relative bias per tile from a (1, 128) table row + (2,) SMEM
    global offsets (see :func:`_rel_bias_block`) — no O(s²) bias operand
    exists anywhere.
    """
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    n = 3
    if has_bias:
        bias_ref = refs[n]
        n += 1
    if rel is not None:
        rtab_ref, roff_ref = refs[n:n + 2]
        n += 2
    if varlen:
        kvlen_ref = refs[n]
        n += 1
    if rate > 0.0:
        seed_ref = refs[n]
        n += 1
    o_ref, lse_ref, m_scr, l_scr, acc_scr = refs[n:]
    t = pl.program_id(0)  # q-head row (dropout mask key)
    i = pl.program_id(1)  # q block
    j = pl.program_id(2)  # k block

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: process only blocks intersecting the (bottom-right aligned)
    # lower triangle — row r attends cols <= r + off, off = sk - sq.
    # varlen: additionally skip KV blocks entirely past this row's valid
    # length (a *dynamic* predicate — pl.when predicates the block; note the
    # block's DMA is issued regardless, only the compute is skipped).
    run = (not causal) or (j * bk <= (i + 1) * bq - 1 + off)
    if varlen:
        kvlen = kvlen_ref[0, 0, 0]
        run = jnp.logical_and(run, j * bk < kvlen)

    @pl.when(run)
    def _step():
        # MXU operands stay in the input dtype (bf16 in mixed precision —
        # an fp32 pre-cast would run the matmul at the ~8x-slower fp32 MXU
        # rate); preferred_element_type pins fp32 accumulation either way.
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (bq, bk)
        if has_bias:
            s = s + bias_ref[0].astype(jnp.float32)
        if rel is not None:
            s = s + _rel_bias_block(rtab_ref, roff_ref, i, j, bq, bk, rel)
        if causal or varlen:
            cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        if causal:
            rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            s = jnp.where(cols <= rows + off, s, NEG_INF)
        if varlen:
            s = jnp.where(cols < kvlen, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        if rate > 0.0:
            pd = p * _mask_scale(seed_ref[0], t, i, j, bq, bk, rate)
        else:
            pd = p
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            pd.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    @pl.when(j == nk - 1)
    def _finish():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_val = m_scr[:] + jnp.log(l)
        if varlen:
            # fully-masked rows (kvlen == 0): lse would be NEG_INF+log(eps),
            # and backward's exp(s - lse) with s == NEG_INF would overflow
            # to exp(+huge); pin dead rows' lse to 0 so p == exp(NEG_INF).
            lse_val = jnp.where(l_scr[:] > 0.0, lse_val, 0.0)
        # lse rides an (sq, 8) layout: TPU blocks must tile (8, 128) or match
        # the array dim, so a flat (1, bq) row block won't lower — broadcast
        # the column across 8 lanes and let the caller slice lane 0.
        lse_b = jnp.broadcast_to(lse_val, (l.shape[0], _LSE_LANES))
        if bshd:  # (b, h, sq, LANES) carrier
            lse_ref[0, 0] = lse_b
        else:
            lse_ref[0] = lse_b


_LSE_LANES = 8


def _expand_rows(x):
    """(bh, sq) -> (bh, sq, 8) broadcast, the tileable carrier layout."""
    return jnp.broadcast_to(x[..., None], (*x.shape, _LSE_LANES))


def _kvlen_rows(kv_lens, bh):
    """(bh,) int32 valid-lengths -> the (bh, 1, 8) lane-carrier the varlen
    kernels read (callers only build this when lengths are present — the
    no-length case compiles kernels with no length operand at all)."""
    return jnp.broadcast_to(kv_lens.astype(jnp.int32)[:, None, None],
                            (bh, 1, _LSE_LANES))



def _group_sum(x, h_kv, group, d, dtype):
    """Per-q-head fp32 dk/dv partials (b, s, h·d) → kv-head grads
    (b, s, h_kv·d): sum each kv group's q heads, THEN cast (fp32 before the
    cross-head sum — the ADVICE r2 precision rule; XLA fuses the reduction
    into the kernel's output write)."""
    b, s, _ = x.shape
    return x.reshape(b, s, h_kv, group, d).sum(3).astype(
        dtype).reshape(b, s, h_kv * d)


def _seed_operand(dropout_seed):
    """(1,) int32 SMEM operand from a scalar seed (traced or host)."""
    if dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    return jnp.asarray(dropout_seed, jnp.int32).reshape(1)


_SMEM_SPEC = pl.BlockSpec(memory_space=pltpu.SMEM)

# The 512-block cap applies ONLY to the MATERIALIZED (hb, sq, sk) bias
# operand (the oracle/fallback form, and contrib's additive attn_mask): its
# (bq, bk) fp32 blocks are bq·bk·4 bytes double-buffered — 4 MB at 1024²,
# too much VMEM next to the q/k/v/do blocks and accumulators; 1 MB at 512²
# fits. The BUCKETED path carries one (1, 128) table row + a (2,) scalar
# pair instead, so it tiles at the normal (uncapped) block sizes — the r6
# change that removed the cap from the production relative-bias path.
_BIAS_BLOCK_CAP = 512


def _bias_blocks(bias, bq, bk):
    """(bq, bk) clamped to the materialized-bias VMEM cap when a bias
    ARRAY operand is present; unchanged otherwise (incl. bucketed)."""
    if bias is not None:
        return min(bq, _BIAS_BLOCK_CAP), min(bk, _BIAS_BLOCK_CAP)
    return bq, bk


def _tail_operands(kv_lens, rows, dropout_rate, dropout_seed, lens_map,
                   bias=None, bias_map=None, bias_block=None,
                   rel=None, rel_map=None):
    """(specs, args) for the OPTIONAL trailing kernel operands, in the
    kernels' fixed unpack order: [score bias] then [rel table + offsets]
    then [kvlen carrier] then [dropout seed]. ``rows`` is the lens
    carrier's leading extent (bh for the flat layout, b for bshd/packed);
    ``lens_map`` the grid->carrier index map; ``bias`` the (hb, sq, sk)
    additive-score array with ``bias_map`` its grid->(row, qblk, kblk) map
    and ``bias_block`` the (1, bq, bk) block shape; ``rel`` the bucketed
    pair (table (hb, 128) fp32 head-major, offsets (2,) int32) with
    ``rel_map`` the grid->(head row, 0) map. One assembly point so a
    future operand cannot be appended in the wrong order at one of the
    call sites."""
    specs, args = [], []
    if bias is not None:
        specs.append(pl.BlockSpec(bias_block, bias_map))
        args.append(bias)
    if rel is not None:
        specs.append(pl.BlockSpec((1, _REL_LANES), rel_map))
        args.append(rel[0])
        specs.append(_SMEM_SPEC)
        args.append(rel[1])
    if kv_lens is not None:
        specs.append(pl.BlockSpec((1, 1, _LSE_LANES), lens_map))
        args.append(_kvlen_rows(kv_lens, rows))
    if dropout_rate > 0.0:
        specs.append(_SMEM_SPEC)
        args.append(_seed_operand(dropout_seed))
    return specs, args


def flash_fwd(q, k, v, *, scale, causal, kv_lens=None, bias=None,
              rel_bias=None, bq=1024, bk=1024, full_lse=False,
              interpret=False, dropout_rate=0.0, dropout_seed=None):
    """q (bh, sq, d); k/v (bh_kv, sk, d) where bh_kv divides bh — grouped-
    query attention falls out of the kv BlockSpec index maps (q row ``b``
    reads kv row ``b // group``), zero-copy: kv shards are never repeated
    in HBM. ``kv_lens`` (bh,) int32 masks each row's kv positions >= its
    length (padded batches); the MXU/VPU work of KV blocks entirely past
    the length is skipped dynamically (their DMA still runs — BlockSpec
    copies are unconditional). ``kv_lens=None`` compiles a kernel with no
    varlen operand or masking at all. ``full_lse`` returns the raw
    (bh, sq, LANES) lane carrier, which :func:`flash_bwd` accepts directly
    (saves the slice + re-broadcast pair when lse only rides residuals).

    ``bias`` (hb, sq, sk) with hb | bh: an additive score bias, row ``r``
    reading bias row ``r % hb`` — (h, sq, sk) shared over batch under the
    b-major row order, (1, sq, sk) fully broadcast, (bh, sq, sk) per-row.
    Added to the scaled scores before masks.

    ``rel_bias`` (exclusive with ``bias``): the BUCKETED relative-bias
    triple ``(table (hb, 128) fp32 head-major, offsets (2,) int32,
    (num_buckets, bidirectional, max_distance))`` — the bias is recomputed
    per tile inside the kernel from the tiny table (see
    :func:`_rel_bias_block`); no (hb, sq, sk) array exists anywhere. Row
    ``r`` reads table row ``r % hb`` (same contract as ``bias``)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    group = bh // k.shape[0]
    bq, bk = _bias_blocks(bias, bq, bk)
    bq, bk = _fit_block(sq, bq), _fit_block(sk, bk)
    nq, nk = _blocks(sq, bq), _blocks(sk, bk)
    varlen = kv_lens is not None
    hb = 0 if bias is None else bias.shape[0]
    rel, rel_static = (None, None) if rel_bias is None else (
        rel_bias[:2], rel_bias[2])
    rhb = 0 if rel is None else rel[0].shape[0]

    in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j, g=group: (b // g, j, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j, g=group: (b // g, j, 0)),
    ]
    args = [q, k, v]
    tail_specs, tail_args = _tail_operands(
        kv_lens, bh, dropout_rate, dropout_seed, lambda b, i, j: (b, 0, 0),
        bias, lambda b, i, j, hb=hb: (b % hb, i, j), (1, bq, bk),
        rel, lambda b, i, j, rhb=rhb: (b % rhb, 0))
    in_specs += tail_specs
    args += tail_args

    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk, off=sk - sq, varlen=varlen,
                          rate=dropout_rate, has_bias=bias is not None,
                          rel=rel_static),
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, _LSE_LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, _LSE_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(*args)
    return o, (lse if full_lse else lse[..., 0])


def flash_fwd_packed(qkv, h, h_kv, d, *, scale, causal, kv_lens=None,
                     bias=None, bq=1024, bk=1024, full_lse=False,
                     interpret=False, dropout_rate=0.0, dropout_seed=None):
    """Flash forward reading q/k/v directly out of the PACKED projection
    output: ``qkv`` (b, s, (h+2·h_kv)·d), features ordered q|k|v with heads
    contiguous inside each part. The same buffer rides in three times with
    window-offset index maps — the projection GEMM's output feeds the
    kernel with no slice, no copy, no layout change at all. Returns
    (o (b, s, h·d), lse (b, h, s)) — or, with ``full_lse``, the raw
    (b, h, s, LANES) lane carrier the kernel wrote, which
    :func:`flash_bwd_packed` accepts directly: round-tripping through the
    sliced form costs a slice + re-broadcast pair per layer for nothing.

    ``bias`` (hb, s, s) with hb | h: additive score bias, q-head row
    ``t = b·h + h_i`` reading bias row ``t % hb`` (i.e. per-head bias
    shared over batch at hb == h; broadcast at hb == 1)."""
    b, s, _ = qkv.shape
    group = h // h_kv
    bq, bk = _bias_blocks(bias, bq, bk)
    bq, bk = _fit_block(s, bq), _fit_block(s, bk)
    nq, nk = _blocks(s, bq), _blocks(s, bk)
    varlen = kv_lens is not None
    hb = 0 if bias is None else bias.shape[0]

    args = [qkv, qkv, qkv]
    in_specs = [
        pl.BlockSpec((1, bq, d),
                     lambda t, i, j, h=h: (t // h, i, t % h)),
        pl.BlockSpec((1, bk, d),
                     lambda t, i, j, h=h, g=group:
                     (t // h, j, h + (t % h) // g)),
        pl.BlockSpec((1, bk, d),
                     lambda t, i, j, h=h, hk=h_kv, g=group:
                     (t // h, j, h + hk + (t % h) // g)),
    ]
    tail_specs, tail_args = _tail_operands(
        kv_lens, b, dropout_rate, dropout_seed,
        lambda t, i, j, h=h: (t // h, 0, 0),
        bias, lambda t, i, j, hb=hb: (t % hb, i, j), (1, bq, bk))
    in_specs += tail_specs
    args += tail_args

    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk, off=0, varlen=varlen,
                          bshd=True, rate=dropout_rate,
                          has_bias=bias is not None),
        grid=(b * h, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d),
                         lambda t, i, j, h=h: (t // h, i, t % h)),
            pl.BlockSpec((1, 1, bq, _LSE_LANES),
                         lambda t, i, j, h=h: (t // h, t % h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, s, h * d), qkv.dtype),
            jax.ShapeDtypeStruct((b, h, s, _LSE_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(*args)
    return o, (lse if full_lse else lse[..., 0])


def _bwd_single_block_kernel(*refs, scale, causal, n, rate=0.0):
    """Single-block fused backward: when the whole (sq == sk == n) matrix
    fits one block, dq/dk/dv come out of ONE kernel that computes the
    score matrix once — the two-kernel split (which exists only because
    dq accumulates over kv blocks and dkv over q blocks) recomputes QKᵀ,
    the mask, and the exp twice. 5 GEMMs instead of 7; at the flagship
    shape that is ~4 ms/step of attention backward removed (PERF.md r3).

    D = rowsum(do·o) is computed HERE from the o block rather than taken
    as an operand: the XLA prologue that produced it materialized the
    fp32 do·o product (67 MB/layer), layout-copied it, reduced it, and
    broadcast the result into the lane carrier — ~0.4 ms/layer of pure
    HBM traffic for a VPU rowsum the kernel gets for free (PERF.md r3).
    """
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref = refs[:6]
    n_ = 6
    if rate > 0.0:
        seed_ref = refs[n_]
        n_ += 1
    dq_ref, dk_ref, dv_ref = refs[n_:]
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
        s = jnp.where(cols <= rows, s, NEG_INF)
    p = jnp.exp(s - lse_ref[0, 0][:, 0:1])
    if rate > 0.0:
        ms = _mask_scale(seed_ref[0], pl.program_id(0), 0, 0, n, n, rate)
        pd = p * ms
    else:
        pd = p
    delta = jnp.sum(do.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
                    axis=1, keepdims=True)
    dv_ref[0] = jax.lax.dot_general(
        pd.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    if rate > 0.0:
        dp = dp * ms
    ds = (p * (dp - delta) * scale).astype(q.dtype)
    dq_ref[0] = jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    dk_ref[0] = jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)


def flash_bwd_packed(qkv, h, h_kv, d, o, lse, do, *, scale, causal,
                     kv_lens=None, bias=None, bq=1024, bk=1024,
                     interpret=False, dropout_rate=0.0, dropout_seed=None):
    """Backward of :func:`flash_fwd_packed`: returns SEPARATE folded grads
    (dq (b, s, h·d), dk/dv (b, s, h_kv·d)) — the caller contracts each
    against its weight window (plain 2D GEMMs), never materializing a
    packed dqkv. When the sequence fits one block, a single fused kernel
    replaces the dq/dkv pair (see :func:`_bwd_single_block_kernel`).

    ``lse`` may be the sliced (b, h, s) form or the (b, h, s, LANES)
    carrier exactly as :func:`flash_fwd_packed` ``full_lse=True`` returned
    it — passing the carrier skips a per-layer re-broadcast.

    ``bias`` (hb, s, s), hb | h: adds a fourth output dbias (hb, s, s)
    fp32 (see :func:`flash_bwd`)."""
    b, s, _ = qkv.shape
    group = h // h_kv
    bq, bk = _bias_blocks(bias, bq, bk)
    bq, bk = _fit_block(s, bq), _fit_block(s, bk)
    nq, nk = _blocks(s, bq), _blocks(s, bk)
    lse4 = lse if lse.ndim == 4 else _expand_rows(lse)
    varlen = kv_lens is not None
    hb = 0 if bias is None else bias.shape[0]

    # varlen and bias ride the two-kernel split (the fused single-block
    # kernel carries no length operand, and it computes delta internally —
    # the dbias kernel needs delta as an operand; padded/biased batches pay
    # one extra QK^T recompute, the same cost every multi-block sequence
    # pays anyway)
    if nq == 1 and nk == 1 and not varlen and bias is None:
        qm = lambda t, h=h: (t // h, 0, t % h)  # noqa: E731
        km = lambda t, h=h, g=group: (t // h, 0, h + (t % h) // g)  # noqa: E731
        vm = lambda t, h=h, hk=h_kv, g=group: (  # noqa: E731
            t // h, 0, h + hk + (t % h) // g)
        rm = lambda t, h=h: (t // h, t % h, 0, 0)  # noqa: E731
        # grouped kv: each grid point is one q head, so dk/dv come out as
        # per-q-head fp32 partials (fp32 BEFORE the cross-head sum — the
        # ADVICE r2 precision rule) and the group reduction happens outside,
        # where XLA fuses it into the output write.
        dkv_dt = jnp.float32 if group > 1 else qkv.dtype
        sb_specs = [pl.BlockSpec((1, s, d), qm),
                    pl.BlockSpec((1, s, d), km),
                    pl.BlockSpec((1, s, d), vm),
                    pl.BlockSpec((1, s, d), qm),
                    pl.BlockSpec((1, s, d), qm),
                    pl.BlockSpec((1, 1, s, _LSE_LANES), rm)]
        sb_args = [qkv, qkv, qkv, do, o, lse4]
        if dropout_rate > 0.0:
            sb_specs.append(_SMEM_SPEC)
            sb_args.append(_seed_operand(dropout_seed))
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_single_block_kernel, scale=scale,
                              causal=causal, n=s, rate=dropout_rate),
            grid=(b * h,),
            in_specs=sb_specs,
            out_specs=[pl.BlockSpec((1, s, d), qm)] * 3,
            out_shape=[
                jax.ShapeDtypeStruct((b, s, h * d), qkv.dtype),
                jax.ShapeDtypeStruct((b, s, h * d), dkv_dt),
                jax.ShapeDtypeStruct((b, s, h * d), dkv_dt),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel",)),
            interpret=interpret,
        )(*sb_args)
        if group > 1:
            dk = _group_sum(dk, h_kv, group, d, qkv.dtype)
            dv = _group_sum(dv, h_kv, group, d, qkv.dtype)
        return dq, dk, dv
    delta = jnp.sum(
        do.astype(jnp.float32).reshape(b, s, h, d)
        * o.astype(jnp.float32).reshape(b, s, h, d), axis=-1)
    delta4 = _expand_rows(delta.transpose(0, 2, 1))
    qm = lambda t, i, j, h=h: (t // h, i, t % h)  # noqa: E731
    km = lambda t, i, j, h=h, g=group: (t // h, j, h + (t % h) // g)  # noqa: E731
    vm = lambda t, i, j, h=h, hk=h_kv, g=group: (  # noqa: E731
        t // h, j, h + hk + (t % h) // g)
    dom = lambda t, i, j, h=h: (t // h, i, t % h)  # noqa: E731
    rm = lambda t, i, j, h=h: (t // h, t % h, i, 0)  # noqa: E731
    extra_specs, extra_args = _tail_operands(
        kv_lens, b, dropout_rate, dropout_seed,
        lambda t, i, j, h=h: (t // h, 0, 0),
        bias, lambda t, i, j, hb=hb: (t % hb, i, j), (1, bq, bk))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk, off=0, varlen=varlen,
                          bshd=True, rate=dropout_rate,
                          has_bias=bias is not None),
        grid=(b * h, nq, nk),
        in_specs=[pl.BlockSpec((1, bq, d), qm),
                  pl.BlockSpec((1, bk, d), km),
                  pl.BlockSpec((1, bk, d), vm),
                  pl.BlockSpec((1, bq, d), dom),
                  pl.BlockSpec((1, 1, bq, _LSE_LANES), rm),
                  pl.BlockSpec((1, 1, bq, _LSE_LANES), rm)] + extra_specs,
        out_specs=pl.BlockSpec((1, bq, d), qm),
        out_shape=jax.ShapeDtypeStruct((b, s, h * d), qkv.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(qkv, qkv, qkv, do, lse4, delta4, *extra_args)

    qm2 = lambda t, j, i, h=h: (t // h, i, t % h)  # noqa: E731
    km2 = lambda t, j, i, h=h, g=group: (t // h, j, h + (t % h) // g)  # noqa: E731
    vm2 = lambda t, j, i, h=h, hk=h_kv, g=group: (  # noqa: E731
        t // h, j, h + hk + (t % h) // g)
    dom2 = lambda t, j, i, h=h: (t // h, i, t % h)  # noqa: E731
    rm2 = lambda t, j, i, h=h: (t // h, t % h, i, 0)  # noqa: E731
    dkm = lambda t, j, i, h=h: (t // h, j, t % h)  # noqa: E731
    dkv_dt = jnp.float32 if group > 1 else qkv.dtype
    extra_specs2, _ = _tail_operands(
        kv_lens, b, dropout_rate, dropout_seed,
        lambda t, j, i, h=h: (t // h, 0, 0),
        bias, lambda t, j, i, hb=hb: (t % hb, i, j), (1, bq, bk))

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, off=0, varlen=varlen,
                          bshd=True, rate=dropout_rate,
                          has_bias=bias is not None),
        grid=(b * h, nk, nq),
        in_specs=[pl.BlockSpec((1, bq, d), qm2),
                  pl.BlockSpec((1, bk, d), km2),
                  pl.BlockSpec((1, bk, d), vm2),
                  pl.BlockSpec((1, bq, d), dom2),
                  pl.BlockSpec((1, 1, bq, _LSE_LANES), rm2),
                  pl.BlockSpec((1, 1, bq, _LSE_LANES), rm2)] + extra_specs2,
        out_specs=[pl.BlockSpec((1, bk, d), dkm),
                   pl.BlockSpec((1, bk, d), dkm)],
        out_shape=[
            jax.ShapeDtypeStruct((b, s, h * d), dkv_dt),
            jax.ShapeDtypeStruct((b, s, h * d), dkv_dt),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(qkv, qkv, qkv, do, lse4, delta4, *extra_args)
    if group > 1:
        dk = _group_sum(dk, h_kv, group, d, qkv.dtype)
        dv = _group_sum(dv, h_kv, group, d, qkv.dtype)
    if bias is None:
        return dq, dk, dv
    # dbias over the packed buffer: q/k/v windows picked by feature-block
    # offsets (0 | h | h+h_kv), row r = bi·hb + th (see flash_bwd_bshd)
    nb = (b * h) // hb
    qmap = lambda th, i, j, bi, hb=hb, h=h: (  # noqa: E731
        (bi * hb + th) // h, i, (bi * hb + th) % h)
    kmap = lambda th, i, j, bi, hb=hb, h=h, g=group: (  # noqa: E731
        (bi * hb + th) // h, j, h + ((bi * hb + th) % h) // g)
    vmap = lambda th, i, j, bi, hb=hb, h=h, hk=h_kv, g=group: (  # noqa: E731
        (bi * hb + th) // h, j, h + hk + ((bi * hb + th) % h) // g)
    rmap = lambda th, i, j, bi, hb=hb, h=h: (  # noqa: E731
        (bi * hb + th) // h, (bi * hb + th) % h, i, 0)
    db_specs = [
        pl.BlockSpec((1, bq, d), qmap),
        pl.BlockSpec((1, bk, d), kmap),
        pl.BlockSpec((1, bk, d), vmap),
        pl.BlockSpec((1, bq, d), qmap),
        pl.BlockSpec((1, 1, bq, _LSE_LANES), rmap),
        pl.BlockSpec((1, 1, bq, _LSE_LANES), rmap),
        pl.BlockSpec((1, bq, bk), lambda th, i, j, bi: (th, i, j)),
    ]
    db_args = [qkv, qkv, qkv, do, lse4, delta4, bias]
    if varlen:
        db_specs.append(pl.BlockSpec(
            (1, 1, _LSE_LANES),
            lambda th, i, j, bi, hb=hb, h=h: ((bi * hb + th) // h, 0, 0)))
        db_args.append(_kvlen_rows(kv_lens, b))
    if dropout_rate > 0.0:
        db_specs.append(_SMEM_SPEC)
        db_args.append(_seed_operand(dropout_seed))
    dbias = _dbias_pallas(
        db_args, db_specs, hb=hb, sq=s, sk=s, nq=nq, nk=nk, nb=nb,
        bq=bq, bk=bk, scale=scale, causal=causal, off=0,
        varlen=varlen, bshd=True, rate=dropout_rate, interpret=interpret)
    return dq, dk, dv, dbias


def flash_fwd_bshd(q, k, v, *, scale, causal, kv_lens=None, bias=None,
                   rel_bias=None, bq=1024, bk=1024, full_lse=False,
                   interpret=False, dropout_rate=0.0, dropout_seed=None):
    """Seq-major flash forward: q (b, sq, h, d); k/v (b, sk, h_kv, d).

    The (s, h·d)-minor layout is exactly what the QKV projection GEMMs
    emit, so no layout conversion feeds the kernel (removes the
    ~4.5 GB/step of pre/post-kernel copies the bh-flat layout cost the
    flagship, PERF.md r3). Mechanics: the operands ride as (b, s, h·d)
    folded views (free bitcasts) and the head is selected by the block
    index along the folded feature dim — a d-wide column block, satisfying
    Mosaic's (8, 128) trailing-tile rule where a 4D singleton-head block
    cannot. Returns (o (b, sq, h, d), lse (b, h, sq)).

    ``kv_lens`` (b,) int32: per-BATCH valid kv lengths (heads share a
    row's length — the padded-batch case); same masking/skip semantics as
    :func:`flash_fwd`.

    ``bias`` (hb, sq, sk) with hb | h: additive score bias, q-head row
    ``t = b·h + h_i`` reading bias row ``t % hb``. ``rel_bias``: the
    bucketed triple (see :func:`flash_fwd`), table row ``t % hb``."""
    b, sq, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    group = h // h_kv
    bq, bk = _bias_blocks(bias, bq, bk)
    bq, bk = _fit_block(sq, bq), _fit_block(sk, bk)
    nq, nk = _blocks(sq, bq), _blocks(sk, bk)
    varlen = kv_lens is not None
    hb = 0 if bias is None else bias.shape[0]
    rel, rel_static = (None, None) if rel_bias is None else (
        rel_bias[:2], rel_bias[2])
    rhb = 0 if rel is None else rel[0].shape[0]

    args = [q.reshape(b, sq, h * d), k.reshape(b, sk, h_kv * d),
            v.reshape(b, sk, h_kv * d)]
    in_specs = [
        pl.BlockSpec((1, bq, d),
                     lambda t, i, j, h=h: (t // h, i, t % h)),
        pl.BlockSpec((1, bk, d),
                     lambda t, i, j, h=h, g=group:
                     (t // h, j, (t % h) // g)),
        pl.BlockSpec((1, bk, d),
                     lambda t, i, j, h=h, g=group:
                     (t // h, j, (t % h) // g)),
    ]
    tail_specs, tail_args = _tail_operands(
        kv_lens, b, dropout_rate, dropout_seed,
        lambda t, i, j, h=h: (t // h, 0, 0),
        bias, lambda t, i, j, hb=hb: (t % hb, i, j), (1, bq, bk),
        rel, lambda t, i, j, rhb=rhb: (t % rhb, 0))
    in_specs += tail_specs
    args += tail_args

    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk, off=sk - sq, varlen=varlen,
                          bshd=True, rate=dropout_rate,
                          has_bias=bias is not None, rel=rel_static),
        grid=(b * h, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d),
                         lambda t, i, j, h=h: (t // h, i, t % h)),
            pl.BlockSpec((1, 1, bq, _LSE_LANES),
                         lambda t, i, j, h=h: (t // h, t % h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, sq, h * d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, _LSE_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(*args)
    return o.reshape(b, sq, h, d), (lse if full_lse else lse[..., 0])


# --- backward -----------------------------------------------------------------

def _rd_row(ref, bshd):
    """lse/delta carrier block → (rows, LANES): the bshd carrier is the
    4D (b, h, sq, LANES) array, the flat one (bh, sq, LANES)."""
    return ref[0, 0] if bshd else ref[0]


def _bwd_dq_kernel(*refs, scale, causal, bq, bk, nk, off, varlen,
                   bshd=False, rate=0.0, has_bias=False, rel=None):
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    n = 6
    if has_bias:
        bias_ref = refs[n]
        n += 1
    if rel is not None:
        rtab_ref, roff_ref = refs[n:n + 2]
        n += 2
    if varlen:
        kvlen_ref = refs[n]
        n += 1
    if rate > 0.0:
        seed_ref = refs[n]
        n += 1
    dq_ref, acc_scr = refs[n:]
    t = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = (not causal) or (j * bk <= (i + 1) * bq - 1 + off)
    if varlen:
        kvlen = kvlen_ref[0, 0, 0]
        run = jnp.logical_and(run, j * bk < kvlen)

    @pl.when(run)
    def _step():
        # bf16 MXU operands, fp32 accumulation (see _fwd_kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if has_bias:
            s = s + bias_ref[0].astype(jnp.float32)
        if rel is not None:
            s = s + _rel_bias_block(rtab_ref, roff_ref, i, j, bq, bk, rel)
        if causal or varlen:
            cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        if causal:
            rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            s = jnp.where(cols <= rows + off, s, NEG_INF)
        if varlen:
            s = jnp.where(cols < kvlen, s, NEG_INF)
        p = jnp.exp(s - _rd_row(lse_ref, bshd)[:, 0:1])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if rate > 0.0:
            # dS = P ∘ (M/(1-r) ∘ dPd − Δ): the mask re-enters on the dPd
            # term only (Δ already equals rowsum(Pd ∘ dPd) — see the
            # softmax-dropout chain in flash_bwd's docstring)
            dp = dp * _mask_scale(seed_ref[0], t, i, j, bq, bk, rate)
        ds = (p * (dp - _rd_row(delta_ref, bshd)[:, 0:1]) * scale
              ).astype(k.dtype)
        acc_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, bq, bk, nq, off, varlen,
                    bshd=False, rate=0.0, has_bias=False, rel=None):
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    n = 6
    if has_bias:
        bias_ref = refs[n]
        n += 1
    if rel is not None:
        rtab_ref, roff_ref = refs[n:n + 2]
        n += 2
    if varlen:
        kvlen_ref = refs[n]
        n += 1
    if rate > 0.0:
        seed_ref = refs[n]
        n += 1
    dk_ref, dv_ref, dk_scr, dv_scr = refs[n:]
    t = pl.program_id(0)
    j = pl.program_id(1)  # k block (outer)
    i = pl.program_id(2)  # q block (inner, accumulated)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = (not causal) or ((i + 1) * bq - 1 + off >= j * bk)
    if varlen:
        kvlen = kvlen_ref[0, 0, 0]
        run = jnp.logical_and(run, j * bk < kvlen)

    @pl.when(run)
    def _step():
        # bf16 MXU operands, fp32 accumulation (see _fwd_kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if has_bias:
            s = s + bias_ref[0].astype(jnp.float32)
        if rel is not None:
            s = s + _rel_bias_block(rtab_ref, roff_ref, i, j, bq, bk, rel)
        if causal or varlen:
            cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        if causal:
            rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            s = jnp.where(cols <= rows + off, s, NEG_INF)
        if varlen:
            s = jnp.where(cols < kvlen, s, NEG_INF)
        p = jnp.exp(s - _rd_row(lse_ref, bshd)[:, 0:1])  # (bq, bk)
        if rate > 0.0:
            ms = _mask_scale(seed_ref[0], t, i, j, bq, bk, rate)
            pd = p * ms  # dropped+rescaled probs: dV = Pdᵀ dO
        else:
            pd = p
        dv_scr[:] += jax.lax.dot_general(
            pd.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if rate > 0.0:
            dp = dp * ms
        ds = (p * (dp - _rd_row(delta_ref, bshd)[:, 0:1]) * scale
              ).astype(q.dtype)
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dbias_kernel(*refs, scale, causal, bq, bk, nb, hb, off, varlen,
                      bshd=False, rate=0.0):
    """dbias[th] = Σ_b dS over the rows sharing bias row ``th`` (bias grad
    = sum of dS over batch — the custom-VJP contract for the additive
    score bias). dS = P ∘ (M/(1-r)∘dPd − Δ) recomputed blockwise, exactly
    the dq/dkv kernels' recipe, UNscaled (the 1/√d scale belongs to dq/dk,
    not to the bias which enters S additively).

    Grid (hb, nq, nk, nb) with the BATCH dim innermost: TPU Pallas only
    accumulates an output block over *consecutive* grid steps, and the
    cross-batch reduction is the one the dq/dkv grids (batch outermost)
    cannot host — hence a third kernel. Costs one extra QKᵀ + dO·Vᵀ pair
    (~2 of backward's 7 GEMMs), paid only when a bias is present.

    Row identity: global q-head row r = b·hb + th — the same ``t`` the
    forward grid used, so the dropout mask hash regenerates bit-exactly."""
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref = refs[:7]
    n = 7
    if varlen:
        kvlen_ref = refs[n]
        n += 1
    if rate > 0.0:
        seed_ref = refs[n]
        n += 1
    dbias_ref, acc_scr = refs[n:]
    th = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)
    b = pl.program_id(3)
    r = b * hb + th  # global q-head row (the forward grid's t)

    @pl.when(b == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = (not causal) or (j * bk <= (i + 1) * bq - 1 + off)
    if varlen:
        kvlen = kvlen_ref[0, 0, 0]
        run = jnp.logical_and(run, j * bk < kvlen)

    @pl.when(run)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale + bias_ref[0].astype(jnp.float32)
        if causal or varlen:
            cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        if causal:
            rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            s = jnp.where(cols <= rows + off, s, NEG_INF)
        if varlen:
            s = jnp.where(cols < kvlen, s, NEG_INF)
        p = jnp.exp(s - _rd_row(lse_ref, bshd)[:, 0:1])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if rate > 0.0:
            dp = dp * _mask_scale(seed_ref[0], r, i, j, bq, bk, rate)
        acc_scr[:] += p * (dp - _rd_row(delta_ref, bshd)[:, 0:1])

    @pl.when(b == nb - 1)
    def _finish():
        dbias_ref[0] = acc_scr[:]


def _dbias_pallas(args, in_specs, *, hb, sq, sk, nq, nk, nb, bq, bk, scale,
                  causal, off, varlen, bshd, rate, interpret):
    """Launch :func:`_bwd_dbias_kernel` — shared by the three layouts
    (only ``in_specs``/``args`` differ). Returns (hb, sq, sk) fp32."""
    return pl.pallas_call(
        functools.partial(_bwd_dbias_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nb=nb, hb=hb, off=off,
                          varlen=varlen, bshd=bshd, rate=rate),
        grid=(hb, nq, nk, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq, bk),
                               lambda th, i, j, b: (th, i, j)),
        out_shape=jax.ShapeDtypeStruct((hb, sq, sk), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bq, bk), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            # the b accumulation is order-dependent: innermost dim stays
            # sequential ("arbitrary"), the block-indexed dims parallel
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(*args)


def _bwd_dtable_kernel(*refs, scale, causal, bq, bk, nq, nk, nb, hb, off,
                       varlen, bshd=False, rate=0.0, rel=None):
    """Bucket-table gradient for the IN-KERNEL relative bias:
    dtable[bucket, th] = Σ over the rows sharing table column ``th`` and
    over all (r, c) with bucket(c − r) == bucket of the UNSCALED dS —
    the chain rule of the per-tile recompute, with the (sq, sk) → bucket
    contraction done inside the kernel (dS itself never leaves VMEM; the
    O(s²) dbias intermediate of the materialized path has no analog here).

    Grid (hb, nq, nk, nb), ALL inner dims accumulating into one (1, 128)
    output row per table column — unlike the dbias kernel (whose (hb, sq,
    sk) output blocks are indexed by (i, j), forcing batch-innermost),
    nothing here depends on (i, j), so the whole inner grid is one long
    consecutive revisit of the same block. Per-step cost: the dq/dkv
    kernels' dS recompute + ``num_buckets`` masked reductions of the
    (bq, bk) tile (VPU, overlapped with the step's two GEMMs).

    Row identity: global q-head row r = b·hb + th (the forward grid's
    ``t``), so the dropout mask hash regenerates bit-exactly."""
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    n = 6
    rtab_ref, roff_ref = refs[n:n + 2]
    n += 2
    if varlen:
        kvlen_ref = refs[n]
        n += 1
    if rate > 0.0:
        seed_ref = refs[n]
        n += 1
    dtab_ref, acc_scr = refs[n:]
    th = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)
    b = pl.program_id(3)
    r = b * hb + th  # global q-head row (the forward grid's t)
    nbk, bidir, maxd = rel

    @pl.when(jnp.logical_and(jnp.logical_and(i == 0, j == 0), b == 0))
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = (not causal) or (j * bk <= (i + 1) * bq - 1 + off)
    if varlen:
        kvlen = kvlen_ref[0, 0, 0]
        run = jnp.logical_and(run, j * bk < kvlen)

    @pl.when(run)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        s = s + _rel_bias_block(rtab_ref, roff_ref, i, j, bq, bk, rel)
        if causal or varlen:
            cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        if causal:
            rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            s = jnp.where(cols <= rows + off, s, NEG_INF)
        if varlen:
            s = jnp.where(cols < kvlen, s, NEG_INF)
        p = jnp.exp(s - _rd_row(lse_ref, bshd)[:, 0:1])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if rate > 0.0:
            dp = dp * _mask_scale(seed_ref[0], r, i, j, bq, bk, rate)
        ds = p * (dp - _rd_row(delta_ref, bshd)[:, 0:1])
        # bucket indices of this tile, recomputed exactly as forward
        grows = roff_ref[0] + i * bq + jax.lax.broadcasted_iota(
            jnp.int32, (bq, 1), 0)
        gcols = roff_ref[1] + j * bk + jax.lax.broadcasted_iota(
            jnp.int32, (1, bk), 1)
        buckets = relative_position_bucket(
            gcols - grows, bidirectional=bidir, num_buckets=nbk,
            max_distance=maxd)
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, _REL_LANES), 1)
        upd = jnp.zeros((1, _REL_LANES), jnp.float32)
        for bkt in range(nbk):
            sb = jnp.sum(jnp.where(buckets == bkt, ds, 0.0))
            upd = upd + jnp.where(lane == bkt, sb, jnp.float32(0.0))
        acc_scr[:] += upd

    @pl.when(jnp.logical_and(jnp.logical_and(i == nq - 1, j == nk - 1),
                             b == nb - 1))
    def _finish():
        dtab_ref[:] = acc_scr[:]


def _dtable_pallas(args, in_specs, *, hb, nq, nk, nb, bq, bk, scale,
                   causal, off, varlen, bshd, rate, rel, interpret):
    """Launch :func:`_bwd_dtable_kernel` — shared by the flat and bshd
    layouts (only ``in_specs``/``args`` differ). Returns (hb, 128) fp32
    head-major bucket-table grads (caller slices/transposes back to the
    (num_buckets, hb) table shape)."""
    return pl.pallas_call(
        functools.partial(_bwd_dtable_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, nk=nk, nb=nb, hb=hb,
                          off=off, varlen=varlen, bshd=bshd, rate=rate,
                          rel=rel),
        grid=(hb, nq, nk, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, _REL_LANES),
                               lambda th, i, j, b: (th, 0)),
        out_shape=jax.ShapeDtypeStruct((hb, _REL_LANES), jnp.float32),
        scratch_shapes=[pltpu.VMEM((1, _REL_LANES), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            # every inner dim accumulates into the one output row, so the
            # whole inner grid must stay sequential
            dimension_semantics=("parallel", "arbitrary", "arbitrary",
                                 "arbitrary")),
        interpret=interpret,
    )(*args)


def flash_bwd(q, k, v, o, lse, do, *, scale, causal, kv_lens=None,
              bias=None, rel_bias=None, bq=1024, bk=1024, interpret=False,
              dropout_rate=0.0, dropout_seed=None):
    """Gradients; with grouped kv (bh_kv < bh) dk/dv come back at kv shape —
    the dkv kernel runs per *q*-head (its scratch accumulates over q blocks
    within one grid row, so cross-head accumulation can't live in-kernel)
    and the per-head partials are summed over each kv group outside, where
    XLA fuses the reduction into the kernel's output write.

    ``lse`` is the sliced (bh, sq) form or the (bh, sq, LANES) carrier from
    ``flash_fwd(full_lse=True)``.

    ``bias`` (hb, sq, sk), hb | bh (row r reads bias row r % hb — see
    :func:`flash_fwd`): returns a FOURTH output, dbias (hb, sq, sk) fp32 =
    Σ over the rows sharing each bias row of the unscaled dS, produced by
    :func:`_bwd_dbias_kernel` (batch-innermost grid).

    ``rel_bias`` (the bucketed triple, see :func:`flash_fwd`): the dq/dkv
    kernels recompute the bias per tile, and the FOURTH output is the
    head-major bucket-table grad (hb, 128) fp32 from
    :func:`_bwd_dtable_kernel` — no O(s²) dbias intermediate exists."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    group = bh // k.shape[0]
    bq, bk = _bias_blocks(bias, bq, bk)
    bq, bk = _fit_block(sq, bq), _fit_block(sk, bk)
    nq, nk = _blocks(sq, bq), _blocks(sk, bk)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    lse3 = lse if lse.ndim == 3 else _expand_rows(lse)
    delta3 = _expand_rows(delta)
    varlen = kv_lens is not None
    hb = 0 if bias is None else bias.shape[0]
    rel, rel_static = (None, None) if rel_bias is None else (
        rel_bias[:2], rel_bias[2])
    rhb = 0 if rel is None else rel[0].shape[0]
    _, extra_args = _tail_operands(
        kv_lens, bh, dropout_rate, dropout_seed, None, bias, None, None,
        rel, None)

    def tail_specs(index_map, bias_map, rel_map):
        specs, _ = _tail_operands(
            kv_lens, bh, dropout_rate, dropout_seed, index_map,
            bias, bias_map, (1, bq, bk), rel, rel_map)
        return specs

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk, off=sk - sq, varlen=varlen,
                          rate=dropout_rate, has_bias=bias is not None,
                          rel=rel_static),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j, g=group: (b // g, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j, g=group: (b // g, j, 0)),
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, _LSE_LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, _LSE_LANES), lambda b, i, j: (b, i, 0)),
        ] + tail_specs(lambda b, i, j: (b, 0, 0),
                       lambda b, i, j, hb=hb: (b % hb, i, j),
                       lambda b, i, j, rhb=rhb: (b % rhb, 0)),
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q, k, v, do, lse3, delta3, *extra_args)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, off=sk - sq, varlen=varlen,
                          rate=dropout_rate, has_bias=bias is not None,
                          rel=rel_static),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i, g=group: (b // g, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i, g=group: (b // g, j, 0)),
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, _LSE_LANES), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, _LSE_LANES), lambda b, j, i: (b, i, 0)),
        ] + tail_specs(lambda b, j, i: (b, 0, 0),
                       lambda b, j, i, hb=hb: (b % hb, i, j),
                       lambda b, j, i, rhb=rhb: (b % rhb, 0)),
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            # grouped kv: per-q-head partials stay fp32 so the group-sum
            # below accumulates unrounded (a bf16 partial would round each
            # head's contribution before the sum); ungrouped writes go
            # straight out in the kv dtype
            jax.ShapeDtypeStruct(
                (bh, sk, d), jnp.float32 if group > 1 else k.dtype),
            jax.ShapeDtypeStruct(
                (bh, sk, d), jnp.float32 if group > 1 else v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q, k, v, do, lse3, delta3, *extra_args)
    if group > 1:
        dk = dk.reshape(-1, group, sk, d).sum(1).astype(k.dtype)
        dv = dv.reshape(-1, group, sk, d).sum(1).astype(v.dtype)
    if rel is not None:
        nb = bh // rhb
        qmap = lambda th, i, j, b, rhb=rhb: (b * rhb + th, i, 0)  # noqa: E731
        kmap = lambda th, i, j, b, rhb=rhb, g=group: (  # noqa: E731
            (b * rhb + th) // g, j, 0)
        dt_specs = [
            pl.BlockSpec((1, bq, d), qmap),
            pl.BlockSpec((1, bk, d), kmap),
            pl.BlockSpec((1, bk, d), kmap),
            pl.BlockSpec((1, bq, d), qmap),
            pl.BlockSpec((1, bq, _LSE_LANES), qmap),
            pl.BlockSpec((1, bq, _LSE_LANES), qmap),
            pl.BlockSpec((1, _REL_LANES), lambda th, i, j, b: (th, 0)),
            _SMEM_SPEC,
        ]
        dt_args = [q, k, v, do, lse3, delta3, rel[0], rel[1]]
        if varlen:
            dt_specs.append(pl.BlockSpec(
                (1, 1, _LSE_LANES),
                lambda th, i, j, b, rhb=rhb: (b * rhb + th, 0, 0)))
            dt_args.append(_kvlen_rows(kv_lens, bh))
        if dropout_rate > 0.0:
            dt_specs.append(_SMEM_SPEC)
            dt_args.append(_seed_operand(dropout_seed))
        dtable = _dtable_pallas(
            dt_args, dt_specs, hb=rhb, nq=nq, nk=nk, nb=nb, bq=bq, bk=bk,
            scale=scale, causal=causal, off=sk - sq, varlen=varlen,
            bshd=False, rate=dropout_rate, rel=rel_static,
            interpret=interpret)
        return dq, dk, dv, dtable
    if bias is None:
        return dq, dk, dv
    nb = bh // hb
    qmap = lambda th, i, j, b, hb=hb: (b * hb + th, i, 0)  # noqa: E731
    kmap = lambda th, i, j, b, hb=hb, g=group: (  # noqa: E731
        (b * hb + th) // g, j, 0)
    db_specs = [
        pl.BlockSpec((1, bq, d), qmap),
        pl.BlockSpec((1, bk, d), kmap),
        pl.BlockSpec((1, bk, d), kmap),
        pl.BlockSpec((1, bq, d), qmap),
        pl.BlockSpec((1, bq, _LSE_LANES), qmap),
        pl.BlockSpec((1, bq, _LSE_LANES), qmap),
        pl.BlockSpec((1, bq, bk), lambda th, i, j, b: (th, i, j)),
    ]
    db_args = [q, k, v, do, lse3, delta3, bias]
    if varlen:
        db_specs.append(pl.BlockSpec(
            (1, 1, _LSE_LANES),
            lambda th, i, j, b, hb=hb: (b * hb + th, 0, 0)))
        db_args.append(_kvlen_rows(kv_lens, bh))
    if dropout_rate > 0.0:
        db_specs.append(_SMEM_SPEC)
        db_args.append(_seed_operand(dropout_seed))
    dbias = _dbias_pallas(
        db_args, db_specs, hb=hb, sq=sq, sk=sk, nq=nq, nk=nk, nb=nb,
        bq=bq, bk=bk, scale=scale, causal=causal, off=sk - sq,
        varlen=varlen, bshd=False, rate=dropout_rate, interpret=interpret)
    return dq, dk, dv, dbias


def flash_bwd_bshd(q, k, v, o, lse, do, *, scale, causal, kv_lens=None,
                   bias=None, rel_bias=None, bq=1024, bk=1024,
                   interpret=False, dropout_rate=0.0, dropout_seed=None):
    """Seq-major backward (cf. :func:`flash_fwd_bshd`): q/o/do
    (b, sq, h, d), k/v (b, sk, h_kv, d), lse (b, h, sq) or the
    (b, h, sq, LANES) carrier from ``flash_fwd_bshd(full_lse=True)``.
    Returns (dq (b, sq, h, d), dk/dv (b, sk, h_kv, d)); with ``bias``
    (hb, sq, sk), hb | h, a fourth output dbias (hb, sq, sk) fp32 (see
    :func:`flash_bwd`); with ``rel_bias`` (the bucketed triple) a fourth
    output dtable (hb, 128) fp32 head-major (see :func:`flash_bwd`)."""
    b, sq, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    group = h // h_kv
    bq, bk = _bias_blocks(bias, bq, bk)
    bq, bk = _fit_block(sq, bq), _fit_block(sk, bk)
    nq, nk = _blocks(sq, bq), _blocks(sk, bk)
    hb = 0 if bias is None else bias.shape[0]
    rel, rel_static = (None, None) if rel_bias is None else (
        rel_bias[:2], rel_bias[2])
    rhb = 0 if rel is None else rel[0].shape[0]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    # (b, sq, h) -> the (b, h, sq, LANES) carrier the kernels read rowwise
    lse4 = lse if lse.ndim == 4 else _expand_rows(lse)
    delta4 = _expand_rows(delta.transpose(0, 2, 1))
    # folded (b, s, h·d) views — free bitcasts, head = block index (see
    # flash_fwd_bshd)
    q3 = q.reshape(b, sq, h * d)
    k3 = k.reshape(b, sk, h_kv * d)
    v3 = v.reshape(b, sk, h_kv * d)
    do3 = do.reshape(b, sq, h * d)

    def q_spec(index_map):
        return pl.BlockSpec((1, bq, d), index_map)

    def kv_spec(index_map):
        return pl.BlockSpec((1, bk, d), index_map)

    def row_spec(index_map):
        return pl.BlockSpec((1, 1, bq, _LSE_LANES), index_map)

    qm = lambda t, i, j, h=h: (t // h, i, t % h)  # noqa: E731
    km = lambda t, i, j, h=h, g=group: (t // h, j, (t % h) // g)  # noqa: E731
    rm = lambda t, i, j, h=h: (t // h, t % h, i, 0)  # noqa: E731
    varlen = kv_lens is not None
    extra_specs, extra_args = _tail_operands(
        kv_lens, b, dropout_rate, dropout_seed,
        lambda t, i, j, h=h: (t // h, 0, 0),
        bias, lambda t, i, j, hb=hb: (t % hb, i, j), (1, bq, bk),
        rel, lambda t, i, j, rhb=rhb: (t % rhb, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk, off=sk - sq, varlen=varlen,
                          bshd=True, rate=dropout_rate,
                          has_bias=bias is not None, rel=rel_static),
        grid=(b * h, nq, nk),
        in_specs=[q_spec(qm), kv_spec(km), kv_spec(km), q_spec(qm),
                  row_spec(rm), row_spec(rm)] + extra_specs,
        out_specs=q_spec(qm),
        out_shape=jax.ShapeDtypeStruct((b, sq, h * d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q3, k3, v3, do3, lse4, delta4, *extra_args)

    qm2 = lambda t, j, i, h=h: (t // h, i, t % h)  # noqa: E731
    km2 = lambda t, j, i, h=h, g=group: (t // h, j, (t % h) // g)  # noqa: E731
    rm2 = lambda t, j, i, h=h: (t // h, t % h, i, 0)  # noqa: E731
    # grouped kv: per-q-head fp32 partials at q-head positions, summed per
    # kv group outside (same rationale as flash_bwd)
    dkv_dtypes = (jnp.float32, jnp.float32) if group > 1 else (k.dtype,
                                                               v.dtype)
    dkm = lambda t, j, i, h=h: (t // h, j, t % h)  # noqa: E731
    extra_specs2, _ = _tail_operands(
        kv_lens, b, dropout_rate, dropout_seed,
        lambda t, j, i, h=h: (t // h, 0, 0),
        bias, lambda t, j, i, hb=hb: (t % hb, i, j), (1, bq, bk),
        rel, lambda t, j, i, rhb=rhb: (t % rhb, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, off=sk - sq, varlen=varlen,
                          bshd=True, rate=dropout_rate,
                          has_bias=bias is not None, rel=rel_static),
        grid=(b * h, nk, nq),
        in_specs=[q_spec(qm2), kv_spec(km2), kv_spec(km2), q_spec(qm2),
                  row_spec(rm2), row_spec(rm2)] + extra_specs2,
        out_specs=[kv_spec(dkm), kv_spec(dkm)],
        out_shape=[
            jax.ShapeDtypeStruct((b, sk, h * d), dkv_dtypes[0]),
            jax.ShapeDtypeStruct((b, sk, h * d), dkv_dtypes[1]),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q3, k3, v3, do3, lse4, delta4, *extra_args)
    dq = dq.reshape(b, sq, h, d)
    if group > 1:
        dk = _group_sum(dk, h_kv, group, d, k.dtype)
        dv = _group_sum(dv, h_kv, group, d, v.dtype)
    dk = dk.reshape(b, sk, h_kv, d)
    dv = dv.reshape(b, sk, h_kv, d)
    if rel is not None:
        # dtable: global q-head row r = b·rhb + th over the folded
        # (b, s, h·d) operands via (r // h, ·, r % h)
        nb = (b * h) // rhb
        qmap = lambda th, i, j, bi, rhb=rhb, h=h: (  # noqa: E731
            (bi * rhb + th) // h, i, (bi * rhb + th) % h)
        kmap = lambda th, i, j, bi, rhb=rhb, h=h, g=group: (  # noqa: E731
            (bi * rhb + th) // h, j, ((bi * rhb + th) % h) // g)
        rmap = lambda th, i, j, bi, rhb=rhb, h=h: (  # noqa: E731
            (bi * rhb + th) // h, (bi * rhb + th) % h, i, 0)
        dt_specs = [
            pl.BlockSpec((1, bq, d), qmap),
            pl.BlockSpec((1, bk, d), kmap),
            pl.BlockSpec((1, bk, d), kmap),
            pl.BlockSpec((1, bq, d), qmap),
            pl.BlockSpec((1, 1, bq, _LSE_LANES), rmap),
            pl.BlockSpec((1, 1, bq, _LSE_LANES), rmap),
            pl.BlockSpec((1, _REL_LANES), lambda th, i, j, bi: (th, 0)),
            _SMEM_SPEC,
        ]
        dt_args = [q3, k3, v3, do3, lse4, delta4, rel[0], rel[1]]
        if varlen:
            dt_specs.append(pl.BlockSpec(
                (1, 1, _LSE_LANES),
                lambda th, i, j, bi, rhb=rhb, h=h: (
                    (bi * rhb + th) // h, 0, 0)))
            dt_args.append(_kvlen_rows(kv_lens, b))
        if dropout_rate > 0.0:
            dt_specs.append(_SMEM_SPEC)
            dt_args.append(_seed_operand(dropout_seed))
        dtable = _dtable_pallas(
            dt_args, dt_specs, hb=rhb, nq=nq, nk=nk, nb=nb, bq=bq, bk=bk,
            scale=scale, causal=causal, off=sk - sq, varlen=varlen,
            bshd=True, rate=dropout_rate, rel=rel_static,
            interpret=interpret)
        return dq, dk, dv, dtable
    if bias is None:
        return dq, dk, dv
    # dbias: batch-innermost grid; global q-head row r = b·hb + th maps to
    # the folded (b, s, h·d) operands via (r // h, ·, r % h)
    nb = (b * h) // hb
    qmap = lambda th, i, j, bi, hb=hb, h=h: (  # noqa: E731
        (bi * hb + th) // h, i, (bi * hb + th) % h)
    kmap = lambda th, i, j, bi, hb=hb, h=h, g=group: (  # noqa: E731
        (bi * hb + th) // h, j, ((bi * hb + th) % h) // g)
    rmap = lambda th, i, j, bi, hb=hb, h=h: (  # noqa: E731
        (bi * hb + th) // h, (bi * hb + th) % h, i, 0)
    db_specs = [
        pl.BlockSpec((1, bq, d), qmap),
        pl.BlockSpec((1, bk, d), kmap),
        pl.BlockSpec((1, bk, d), kmap),
        pl.BlockSpec((1, bq, d), qmap),
        pl.BlockSpec((1, 1, bq, _LSE_LANES), rmap),
        pl.BlockSpec((1, 1, bq, _LSE_LANES), rmap),
        pl.BlockSpec((1, bq, bk), lambda th, i, j, bi: (th, i, j)),
    ]
    db_args = [q3, k3, v3, do3, lse4, delta4, bias]
    if varlen:
        db_specs.append(pl.BlockSpec(
            (1, 1, _LSE_LANES),
            lambda th, i, j, bi, hb=hb, h=h: ((bi * hb + th) // h, 0, 0)))
        db_args.append(_kvlen_rows(kv_lens, b))
    if dropout_rate > 0.0:
        db_specs.append(_SMEM_SPEC)
        db_args.append(_seed_operand(dropout_seed))
    dbias = _dbias_pallas(
        db_args, db_specs, hb=hb, sq=sq, sk=sk, nq=nq, nk=nk, nb=nb,
        bq=bq, bk=bk, scale=scale, causal=causal, off=sk - sq,
        varlen=varlen, bshd=True, rate=dropout_rate, interpret=interpret)
    return dq, dk, dv, dbias
