"""Fused sampling-tail Pallas kernel: logits → temperature/top-k/top-p →
sampled token, one kernel.

The decode tail the engines used to run is a chain of host-visible XLA
ops — scale, ``lax.top_k`` (sort!), filter, sort+cumsum for top-p,
categorical — each materializing an O(V) tensor between HBM round trips.
At decode rates that tail is pure staging traffic on a memory-bound path
("LLM Inference Acceleration via Efficient Operation Fusion",
arXiv:2502.17728 makes exactly this argument for fusing the per-token
epilogue). This kernel reads the logits row and a pre-drawn uniform row
ONCE into VMEM and emits a single int32 per row; no O(V) intermediate
ever returns to HBM.

Two ideas make full top-k *and* top-p fusible without an in-kernel sort:

* **Threshold by bisection, not by sorting.** The top-k filter only
  needs the k-th largest VALUE; ``count(s >= t) >= k`` is a monotone
  step function of ``t``, so ~48 VPU-cheap bisection steps over the
  whole-row VMEM resident pin the threshold to one float32 ulp — at
  which point the kept set {s >= t_lo} equals the sort-based
  {s >= kth} exactly (ties at the k-th value are all kept, the same
  convention as ``jnp.where(s < kth, ...)``). Top-p is the same
  bisection on the monotone unnormalized mass ``sum(exp(s - m) where
  s >= t)`` against ``p * Z``: the kept set is the minimal
  highest-probability set with mass >= p — the sorted-cumsum definition
  — without materializing a sort.
* **Gumbel-argmax instead of cumulative inverse-CDF.** With u ~ U(0,1),
  ``argmax(s + (-log(-log u)))`` IS a categorical draw over
  ``softmax(s)`` — one elementwise op + one reduction, no normalized
  probability vector, no scan.

The uniform row is drawn by ``jax.random`` in the caller's jit (interpret
mode has no TPU PRNG lowering, and a shared operand keeps the kernel and
the XLA fallback bit-comparable); it fuses into the same program, so the
"tail" stays one dispatch. The filtering math lives in module-level
helpers shared VERBATIM with the XLA fallback in
:mod:`apex_tpu.ops.sampling` — parity is by construction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops.pallas.attention import _LSE_LANES

# masked-out logit value (matches apex_tpu.inference.sampling._FILTERED):
# finite so a pathologically over-filtered row degrades to near-uniform
# over the kept set instead of NaN
FILTERED = -1e30

# bisection steps: each halves the threshold interval; ~30 reach one ulp
# of float32 values at logit magnitudes, 48 leaves margin (still ~100x
# cheaper than a V-length sort and all VMEM-resident)
_BISECT_ITERS = 48


def _bisect(s, keep_mass, target, lo=None, iters=_BISECT_ITERS):
    """Largest threshold t (per row) with ``mass(s >= t) >= target``,
    where ``mass`` counts elements (top-k) or sums ``keep_mass`` weights
    (top-p). ``s`` (rows, V) fp32; returns (rows, 1). The answer is an
    order statistic of ``s``, so once the interval collapses below one
    ulp the *kept set* {s >= lo} is exact. ``lo`` overrides the lower
    bound — it must still satisfy ``mass(s >= lo) >= target``: callers
    on already-FILTERED rows pass the min over LIVE entries, because a
    [-1e30, max] interval cannot collapse to a ulp in any finite number
    of halvings (the filtered sentinel would turn the search into a
    no-op)."""
    if lo is None:
        lo = jnp.min(s, axis=-1, keepdims=True)
    hi = jnp.max(s, axis=-1, keepdims=True)

    def body(_, lohi):
        lo, hi = lohi
        mid = 0.5 * (lo + hi)
        mass = jnp.sum(jnp.where(s >= mid, keep_mass, 0.0), axis=-1,
                       keepdims=True)
        ok = mass >= target
        return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid)

    lo, hi = jax.lax.fori_loop(0, iters, body, (lo, hi))
    return lo


def apply_top_k(s, top_k):
    """Keep each row's ``top_k`` largest entries (ties at the k-th value
    all kept); rest → FILTERED. ``s`` (rows, V) fp32, ``top_k`` static."""
    ones = jnp.ones(s.shape, jnp.float32)
    t = _bisect(s, ones, jnp.float32(top_k))
    return jnp.where(s >= t, s, FILTERED)


def apply_top_p(s, top_p):
    """Nucleus filter: keep the minimal highest-probability set whose
    softmax mass reaches ``top_p`` (the sorted-cumsum definition,
    crossing token included); rest → FILTERED. ``s`` (rows, V) fp32
    (post top-k: FILTERED entries carry exp()==0 mass), ``top_p``
    static."""
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    z = jnp.sum(e, axis=-1, keepdims=True)
    # bisect over the LIVE value range: after a top-k pass the row min is
    # the FILTERED sentinel, and [-1e30, max] never collapses in 48
    # halvings — the threshold would land below every real logit and
    # keep the whole top-k set (top-p silently off). Filtered entries
    # carry ~0 mass, so mass(>= live-min) is still >= top_p * z.
    lo = jnp.min(jnp.where(s > FILTERED * 0.5, s, m), axis=-1,
                 keepdims=True)
    t = _bisect(s, e, jnp.float32(top_p) * z, lo=lo)
    return jnp.where(s >= t, s, FILTERED)


def gumbel_argmax(s, u):
    """One categorical draw over softmax(s) per row via the Gumbel trick;
    ties broken to the lowest index (argmax convention). ``u`` uniform in
    (0, 1] — the caller clamps 0 away so log(u) is finite."""
    g = -jnp.log(-jnp.log(u))
    x = s + g
    m = jnp.max(x, axis=-1, keepdims=True)
    V = x.shape[-1]
    idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
    return jnp.min(jnp.where(x == m, idx, V), axis=-1)


def filtered_scaled(logits, *, temperature, top_k, top_p):
    """The shared tail: fp32 cast → 1/T scale → top-k → top-p. Static
    knobs select the program (no runtime branches — the serving engines'
    zero-recompile contract)."""
    s = logits.astype(jnp.float32) * (1.0 / temperature)
    if top_k > 0:
        s = apply_top_k(s, top_k)
    if top_p < 1.0:
        s = apply_top_p(s, top_p)
    return s


def _sample_kernel(logits_ref, u_ref, o_ref, *, temperature, top_k, top_p):
    """One grid row: the whole (1, V) logits row is VMEM-resident, every
    reduction below runs on it in place — the only HBM traffic is the two
    row reads and the 8-lane index write."""
    s = filtered_scaled(logits_ref[:], temperature=temperature,
                        top_k=top_k, top_p=top_p)
    idx = gumbel_argmax(s, u_ref[:])
    o_ref[:] = jnp.broadcast_to(idx[:, None], (1, _LSE_LANES))


def fused_sample_fwd(logits, u, *, temperature, top_k, top_p,
                     interpret=False):
    """(b, V) logits + (b, V) uniform noise → (b,) int32 tokens; one
    kernel invocation, grid over rows. V must be a 128-multiple (lane
    tiling); the op-level wrapper gates on that."""
    b, V = logits.shape
    out = pl.pallas_call(
        functools.partial(_sample_kernel, temperature=temperature,
                          top_k=top_k, top_p=top_p),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, V), lambda i: (i, 0)),
            pl.BlockSpec((1, V), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, _LSE_LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, _LSE_LANES), jnp.int32),
        interpret=interpret,
    )(logits, u)
    return out[:, 0]
