"""Pallas fused cross-entropy statistics kernel.

One blockwise pass over the (tokens, vocab-shard) logits computing, per row:
the max, the exp-sum relative to that max (online-softmax recurrence, same as
the attention kernels), the raw logit at the target column, and the raw row
sum (label smoothing). This is the TPU replacement for the fp32 staging pass
the XLA formulation materializes: with bf16 logits the jnp path writes a
full-size fp32 ``logits - max`` temporary (~2 GB on the flagship bench, ~5 ms
of pure HBM traffic per step) because the converted tensor has three
consumers; the kernel reads the bf16 logits once and writes only O(tokens)
statistics.

Role parity: ``apex/contrib/csrc/xentropy/xentropy_kernel.cu`` fuses the same
softmax statistics into its cross-entropy forward.

Out-of-range labels contribute 0 to the target stat — exactly the masked
gather the vocab-parallel algorithm needs (the owning shard is the only one
whose column range contains the label), so the caller psums the stat across
shards without any extra masking.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops.pallas import exact_block

NEG_INF = -1e30
_LANES = 8  # row-stat carrier lanes (cf. attention._LSE_LANES)


def shapes_ok(n: int, v: int) -> bool:
    return exact_block(n, 256, 8) > 0 and exact_block(v, 2048, 128) > 0


def _stats_kernel(x_ref, lab_ref, m_ref, l_ref, t_ref, s_ref,
                  m_scr, l_scr, t_scr, s_scr, *, bv, nv):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        t_scr[:] = jnp.zeros_like(t_scr)
        s_scr[:] = jnp.zeros_like(s_scr)

    x = x_ref[:].astype(jnp.float32)  # (bn, bv)
    bn = x.shape[0]
    m_new = jnp.maximum(m_scr[:], jnp.max(x, axis=1, keepdims=True))
    alpha = jnp.exp(m_scr[:] - m_new)
    l_scr[:] = l_scr[:] * alpha + jnp.sum(jnp.exp(x - m_new), axis=1,
                                          keepdims=True)
    m_scr[:] = m_new
    cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    hit = cols == lab_ref[:, 0:1]
    t_scr[:] += jnp.sum(jnp.where(hit, x, 0.0), axis=1, keepdims=True)
    s_scr[:] += jnp.sum(x, axis=1, keepdims=True)

    @pl.when(j == nv - 1)
    def _finish():
        shape = (bn, _LANES)
        m_ref[:] = jnp.broadcast_to(m_scr[:], shape)
        l_ref[:] = jnp.broadcast_to(l_scr[:], shape)
        t_ref[:] = jnp.broadcast_to(t_scr[:], shape)
        s_ref[:] = jnp.broadcast_to(s_scr[:], shape)


def xent_stats(logits2d, labels, *, interpret=False):
    """(N, V) logits + (N,) int labels -> per-row fp32 stats
    ``(max, sumexp_rel_max, target_logit_raw, row_sum_raw)``; labels outside
    ``[0, V)`` yield ``target_logit_raw == 0``."""
    n, v = logits2d.shape
    bn = exact_block(n, 256, 8)
    bv = exact_block(v, 2048, 128)
    if not bn or not bv:
        raise ValueError(f"untileable ({n}, {v}) for the xent stats kernel")
    nv = v // bv
    lab8 = jnp.broadcast_to(labels.astype(jnp.int32)[:, None], (n, _LANES))

    stat = jax.ShapeDtypeStruct((n, _LANES), jnp.float32)
    m, l, t, s = pl.pallas_call(
        functools.partial(_stats_kernel, bv=bv, nv=nv),
        grid=(n // bn, nv),
        in_specs=[
            pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
            pl.BlockSpec((bn, _LANES), lambda i, j: (i, 0)),
        ],
        out_specs=[pl.BlockSpec((bn, _LANES), lambda i, j: (i, 0))] * 4,
        out_shape=[stat] * 4,
        scratch_shapes=[pltpu.VMEM((bn, 1), jnp.float32)] * 4,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(logits2d, lab8)
    return m[:, 0], l[:, 0], t[:, 0], s[:, 0]
