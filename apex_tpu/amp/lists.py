"""Declarative per-op cast policy tables (opt-level O1 semantics).

The reference implements O1 by monkey-patching every listed function in
``torch``/``torch.Tensor``/``torch.nn.functional`` with cast wrappers
(``apex/amp/amp.py:68-177``, ``apex/amp/wrap.py``). The *policy* lives in
tables (``apex/amp/lists/functional_overrides.py``, ``torch_overrides.py``,
``tensor_overrides.py``). JAX has no mutable op namespace to patch — and XLA
already promotes correctly — so we keep only the tables, expressed over
abstract op families, and expose:

* :func:`op_cast_dtype` — the dtype a policy-aware layer should compute a
  given op family in. Layers in ``apex_tpu.ops`` consult this when the ambient
  policy has ``per_op_rules=True``.
* registries mirroring ``amp.register_half_function`` /
  ``register_float_function`` / ``register_promote_function``
  (``apex/amp/amp.py:30-64``) so user code can extend the tables.

Op families (not individual functions — JAX composes from primitives):

* HALF  (run in compute dtype): matmul-shaped ops — conv, dense, attention
  (cf. FP16 lists: ``lists/functional_overrides.py:17-26``,
  ``torch_overrides.py:7-27``).
* FLOAT (run in fp32): softmax, normalization, losses, transcendentals,
  reductions (cf. FP32 lists: ``functional_overrides.py:28-67``,
  ``torch_overrides.py:29-60``).
* PROMOTE (widest input dtype): multi-arg math, concat/stack
  (``torch_overrides.py:81-111``) — this is XLA's native promotion; listed for
  completeness and for the checker.
* BANNED: ops numerically unsafe in half precision regardless
  (``functional_overrides.py:69-80`` bans ``binary_cross_entropy``) —
  :func:`check_banned` raises with the same guidance.
"""

from __future__ import annotations

import jax.numpy as jnp

HALF_OPS = {
    # matmul/conv family → MXU, compute dtype
    "conv", "conv1d", "conv2d", "conv3d", "conv_transpose",
    "dense", "linear", "matmul", "bmm", "einsum", "attention", "mlp",
    # RNN cells are gate matmuls (cf. wrap.rnn_cast / rnn_compat,
    # apex/amp/wrap.py:157-265 — the reference casts weights+inputs half)
    "rnn", "lstm", "gru",
}

FLOAT_OPS = {
    # numerically sensitive → fp32
    "softmax", "log_softmax", "layer_norm", "rms_norm", "batch_norm",
    "group_norm", "cross_entropy", "nll_loss", "mse_loss", "l1_loss",
    "smooth_l1_loss", "kl_div", "cosine_similarity", "focal_loss",
    "exp", "log", "log1p", "pow", "erf", "erfinv", "softplus",
    "sum", "prod", "cumsum", "cumprod", "norm", "mean", "var", "std",
}

PROMOTE_OPS = {
    "add", "sub", "mul", "div", "addcmul", "addcdiv",
    "cat", "stack", "concatenate", "where", "equal", "dot",
}

BANNED_OPS = {
    # fp16-unsafe even with scaling; reference raises and points users at the
    # fused fp32 alternative (functional_overrides.py:69-80)
    "binary_cross_entropy": (
        "binary_cross_entropy on half inputs is numerically unsafe; compute "
        "the loss in fp32 (policy.cast_to_output) or use "
        "sigmoid_cross_entropy_with_logits"
    ),
}


def register_half_op(name: str) -> None:
    """cf. ``amp.register_half_function`` / ``@amp.half_function``
    (``apex/amp/amp.py:30-40``; used e.g. by ``apex/mlp/mlp.py:24``)."""
    FLOAT_OPS.discard(name)
    HALF_OPS.add(name)


def register_float_op(name: str) -> None:
    HALF_OPS.discard(name)
    FLOAT_OPS.add(name)


def register_promote_op(name: str) -> None:
    HALF_OPS.discard(name)
    FLOAT_OPS.discard(name)
    PROMOTE_OPS.add(name)


def _ref_spelling(register):
    """Reference-spelling wrappers: ``amp.register_half_function(module,
    'fn')`` (``apex/amp/__init__.py``) keys on a (module, name) pair because
    it must monkey-patch the module; the op-rule tables key on the op name
    alone, so the module argument is accepted and ignored."""

    def wrapper(module_or_name, function_name: str | None = None) -> None:
        register(function_name if function_name is not None else module_or_name)

    wrapper.__doc__ = _ref_spelling.__doc__
    return wrapper


register_half_function = _ref_spelling(register_half_op)
register_float_function = _ref_spelling(register_float_op)
register_promote_function = _ref_spelling(register_promote_op)


_HALF_DTYPES = (jnp.float16, jnp.bfloat16)


def check_banned(name: str, *input_dtypes) -> None:
    """Raise for fp16-unsafe ops — only when half inputs are actually
    present, matching ``wrap.err_if_any_half`` (``apex/amp/wrap.py:114-130``,
    which runs the original op untouched when no arg is half)."""
    if name in BANNED_OPS and (
        not input_dtypes or any(dt in _HALF_DTYPES for dt in input_dtypes)
    ):
        raise RuntimeError(f"amp: {BANNED_OPS[name]}")


def op_cast_dtype(op: str, policy, *input_dtypes):
    """Dtype an O1-style policy computes ``op`` in.

    HALF → ``policy.compute_dtype``; FLOAT → fp32; PROMOTE/unknown → widest
    input dtype (matching ``wrap.promote``'s ``maybe_float`` behavior,
    ``apex/amp/wrap.py:65-90``).
    """
    if not getattr(policy, "per_op_rules", False):
        return policy.compute_dtype
    check_banned(op, *input_dtypes)
    if op in HALF_OPS:
        return policy.compute_dtype
    if op in FLOAT_OPS:
        return jnp.float32
    if input_dtypes:
        return jnp.result_type(*input_dtypes)
    return policy.compute_dtype


def _is_float_array(a) -> bool:
    return (
        a is not None
        and hasattr(a, "dtype")
        and jnp.issubdtype(a.dtype, jnp.floating)
    )


def apply_op_rules(op: str, *arrays, policy=None):
    """Cast ``arrays`` to the dtype the ambient O1 policy assigns ``op``.

    This is the call-site half of the reference's cast wrappers
    (``make_cast_wrapper`` ``apex/amp/wrap.py:10-29`` for HALF/FLOAT ops,
    ``promote`` ``wrap.py:65-90``, ``err_if_any_half`` ``wrap.py:114-130``):
    every ``apex_tpu.ops`` entry point routes its floating inputs through
    here. Identity unless the ambient policy has ``per_op_rules`` (O1), so
    O0/O2/O3 pay nothing. Non-float leaves (int labels/tokens) and ``None``
    pass through untouched.

    The reference's fp16 weight cache (``utils.cached_cast``, invalidated
    per-iteration via ``_amp_state.handle._clear_cache``) has no analog here
    by design: under ``jit`` the cast is a traced op that XLA CSEs, so
    repeated casts of the same weight cost nothing at runtime.
    """
    if policy is None:
        from apex_tpu.amp.policy import current_policy

        policy = current_policy()
    if not getattr(policy, "per_op_rules", False):
        return arrays
    in_dtypes = [a.dtype for a in arrays if _is_float_array(a)]
    target = op_cast_dtype(op, policy, *in_dtypes)
    return tuple(
        a.astype(target) if _is_float_array(a) and a.dtype != target else a
        for a in arrays
    )
