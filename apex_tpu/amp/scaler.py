"""Static + dynamic loss scaling, as pure functions of explicit state.

Re-design of ``apex/amp/scaler.py`` (``LossScaler`` at ``:33``): dynamic
scaling starts at 2**16, doubles every 2000 overflow-free steps, halves on
overflow, clamped to [1, 2**24] (``scaler.py:38-56,197-217``). The reference
needs a fused CUDA kernel plus one D2H sync per step to learn whether grads
overflowed (``scaler.py:105-124,197-200``) and then monkey-patches
``optimizer.step`` into a one-shot skip (``apex/amp/handle.py:128-154``).

Here the whole protocol is on-device and branchless at the host level:
``all_finite`` is a fused reduction, the scale update is ``jnp.where``, and
the "skip step" is a ``jnp.where`` select between old and new params — zero
host syncs per step (better than the reference's one).

The model-parallel variant of torch's GradScaler
(``apex/transformer/amp/grad_scaler.py:38-49`` — all-reduce found_inf across
the model-parallel group) is unnecessary with global arrays: ``all_finite``
over a sharded pytree already reduces across every shard; XLA inserts the
cross-device reduction.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.utils.pytree import tree_all_finite

PyTree = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LossScalerState:
    """Carries the scale and the overflow-free step counter.

    ``dynamic`` is static metadata (it selects the traced program, like the
    reference choosing ``LossScaler("dynamic")`` vs a constant at
    ``apex/amp/_initialize.py:227-231``).
    """

    loss_scale: jax.Array          # f32 scalar
    growth_tracker: jax.Array      # i32 scalar: unskipped steps since last growth
    skipped_steps: jax.Array       # i32 scalar: lifetime overflow count (observability)
    dynamic: bool = dataclasses.field(metadata=dict(static=True), default=True)
    growth_interval: int = dataclasses.field(metadata=dict(static=True), default=2000)
    growth_factor: float = dataclasses.field(metadata=dict(static=True), default=2.0)
    backoff_factor: float = dataclasses.field(metadata=dict(static=True), default=0.5)
    max_loss_scale: float = dataclasses.field(metadata=dict(static=True), default=2.0 ** 24)
    min_loss_scale: float = dataclasses.field(metadata=dict(static=True), default=1.0)


def init_loss_scaler(
    loss_scale: str | float = "dynamic",
    *,
    init_scale: float = 2.0 ** 16,
    growth_interval: int = 2000,
    growth_factor: float = 2.0,
    backoff_factor: float = 0.5,
    max_loss_scale: float = 2.0 ** 24,
    min_loss_scale: float = 1.0,
) -> LossScalerState:
    """Create scaler state. ``loss_scale="dynamic"`` or a fixed float, exactly
    the surface of ``amp.initialize(loss_scale=...)`` (``frontend.py:195``)."""
    dynamic = loss_scale == "dynamic"
    scale = init_scale if dynamic else float(loss_scale)
    return LossScalerState(
        loss_scale=jnp.asarray(scale, jnp.float32),
        growth_tracker=jnp.zeros((), jnp.int32),
        skipped_steps=jnp.zeros((), jnp.int32),
        dynamic=dynamic,
        growth_interval=growth_interval,
        growth_factor=growth_factor,
        backoff_factor=backoff_factor,
        max_loss_scale=max_loss_scale,
        min_loss_scale=min_loss_scale,
    )


def scale_loss(state: LossScalerState, loss: jax.Array) -> jax.Array:
    """``loss.float() * loss_scale`` (cf. ``apex/amp/handle.py:113``)."""
    return jnp.asarray(loss, jnp.float32) * state.loss_scale


def unscale_grads(state: LossScalerState, grads: PyTree) -> PyTree:
    """Unscale grads to fp32 (the reference's ``scaler.unscale`` →
    ``amp_C.multi_tensor_scale``, ``scaler.py:94-189``; here XLA fuses the
    multiply into the producing op)."""
    inv = 1.0 / state.loss_scale
    return jax.tree.map(lambda g: jnp.asarray(g, jnp.float32) * inv, grads)


def all_finite(grads: PyTree) -> jax.Array:
    """Fused overflow check (cf. inf/nan detection inside
    ``multi_tensor_scale_kernel.cu``); result stays on device."""
    return tree_all_finite(grads)


def update_loss_scaler(state: LossScalerState, grads_finite: jax.Array) -> LossScalerState:
    """Post-step scale adjustment (``scaler.py:197-217``):

    overflow → scale *= backoff (clamped at min), tracker reset;
    otherwise → tracker += 1; at growth_interval → scale *= growth (clamped).
    """
    if not state.dynamic:
        # scale is fixed, but overflow bookkeeping still runs (the reference's
        # static LossScaler also skips steps on overflow, scaler.py:76-91)
        return dataclasses.replace(
            state, skipped_steps=state.skipped_steps + jnp.where(grads_finite, 0, 1)
        )
    tracker = jnp.where(grads_finite, state.growth_tracker + 1, 0)
    grow = tracker >= state.growth_interval
    scale = jnp.where(
        grads_finite,
        jnp.where(
            grow,
            jnp.minimum(state.loss_scale * state.growth_factor, state.max_loss_scale),
            state.loss_scale,
        ),
        jnp.maximum(state.loss_scale * state.backoff_factor, state.min_loss_scale),
    )
    tracker = jnp.where(grow, 0, tracker)
    return dataclasses.replace(
        state,
        loss_scale=scale,
        growth_tracker=tracker,
        skipped_steps=state.skipped_steps + jnp.where(grads_finite, 0, 1),
    )


def scaled_value_and_grad(
    fn: Callable[..., jax.Array],
    *,
    has_aux: bool = False,
) -> Callable[..., Tuple]:
    """``value_and_grad`` with loss scaling folded in.

    ``g = scaled_value_and_grad(loss_fn)`` then
    ``(loss, (grads, finite, new_scaler)) = g(scaler_state, params, ...)``:
    the loss is scaled before differentiation, grads are unscaled to fp32, the
    finite flag and updated scaler state come back with them. This is the
    whole ``with amp.scale_loss(...)`` protocol (``apex/amp/handle.py:16-154``)
    as one pure function.
    """

    def wrapped(scaler: LossScalerState, *args, **kwargs):
        def scaled_fn(*a, **k):
            out = fn(*a, **k)
            if has_aux:
                loss, aux = out
                return scale_loss(scaler, loss), aux
            return scale_loss(scaler, out)

        if has_aux:
            (scaled, aux), grads = jax.value_and_grad(scaled_fn, has_aux=True)(*args, **kwargs)
        else:
            scaled, grads = jax.value_and_grad(scaled_fn)(*args, **kwargs)
            aux = None
        grads = unscale_grads(scaler, grads)
        finite = all_finite(grads)
        new_scaler = update_loss_scaler(scaler, finite)
        loss = scaled / scaler.loss_scale
        if has_aux:
            return (loss, aux), (grads, finite, new_scaler)
        return loss, (grads, finite, new_scaler)

    return wrapped


def apply_if_finite(params: PyTree, new_params: PyTree, grads_finite: jax.Array) -> PyTree:
    """Select updated params only when grads were finite — the functional form
    of the reference's one-shot ``skip_step`` patch (``handle.py:128-154``)."""
    return jax.tree.map(lambda old, new: jnp.where(grads_finite, new, old), params, new_params)


def skip_step_if_nonfinite(opt):
    """Wrap an optax optimizer so an overflowed step is skipped *entirely* —
    zero updates AND untouched inner state (momenta, step count).

    The reference's skip patch replaces ``optimizer.step`` for the overflowed
    iteration (``handle.py:128-154``), which implicitly protects the
    optimizer's exp-avg buffers from inf/nan gradients. The functional
    translation must guard both halves: ``apply_if_finite`` alone keeps
    params clean, but running ``opt.update`` with inf grads still poisons
    m/v forever. Use this wrapper whenever grads can overflow (fp16 +
    loss scaling)::

        opt = amp.skip_step_if_nonfinite(fused_adam(1e-3))
        updates, opt_state = opt.update(grads, opt_state, params)  # safe
    """
    import optax

    def init(params):
        return opt.init(params)

    def update(grads, state, params=None):
        finite = all_finite(grads)
        # sanitize before the inner update: where() keeps the old state, but
        # inf * 0 inside the unselected branch would still produce nan that
        # XLA must not see in the selected lanes
        safe_grads = jax.tree.map(
            lambda g: jnp.where(jnp.isfinite(g), g, 0).astype(g.dtype), grads
        )
        updates, new_state = opt.update(safe_grads, state, params)
        updates = jax.tree.map(
            lambda u: jnp.where(finite, u, jnp.zeros_like(u)), updates
        )
        new_state = jax.tree.map(
            lambda old, new: jnp.where(finite, new, old), state, new_state
        )
        return updates, new_state

    return optax.GradientTransformation(init, update)


# -- observability -------------------------------------------------------------

def scaler_metrics(state: LossScalerState) -> dict:
    """Host-side observability numbers for a scaler state: the loss scale,
    the growth tracker and the lifetime overflow (skipped-step) count as
    Python scalars. This is the pull point ``apex_tpu.monitor`` reads
    (``monitor.observe_scaler``) — one device→host sync, only when called."""
    return {
        "loss_scale": float(state.loss_scale),
        "growth_tracker": int(state.growth_tracker),
        "skipped_steps": int(state.skipped_steps),
    }


# -- state-dict parity (apex/amp/frontend.py:361-400) -------------------------

def state_dict(state: LossScalerState) -> dict:
    """Serializable scaler state, mirroring ``amp.state_dict()``'s per-scaler
    ``{"loss_scale": ..., "unskipped": ...}`` payload."""
    return {
        "loss_scale": float(state.loss_scale),
        "unskipped": int(state.growth_tracker),
        "skipped": int(state.skipped_steps),
        "dynamic": state.dynamic,
    }


def load_state_dict(state: LossScalerState, payload: dict) -> LossScalerState:
    return dataclasses.replace(
        state,
        loss_scale=jnp.asarray(payload["loss_scale"], jnp.float32),
        growth_tracker=jnp.asarray(payload.get("unskipped", 0), jnp.int32),
        skipped_steps=jnp.asarray(payload.get("skipped", 0), jnp.int32),
        dynamic=payload.get("dynamic", state.dynamic),
    )
