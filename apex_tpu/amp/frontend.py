"""``amp.initialize`` and the decorator/context surface — frontend parity.

The reference's entry point (``apex/amp/frontend.py:195-358``) mutates the
model/optimizer in place and hides scaler state in a module global. The
functional mirror takes a param pytree and an optax-style optimizer and
returns everything explicitly as an :class:`AmpState`: cast (or
master-wrapped) params, the (overflow-guarded) optimizer, the loss-scaler
state, and the resolved policy. Nothing is patched; the training step
composes these values.

Also here: ``half_function`` / ``float_function`` / ``promote_function``
decorators (``apex/amp/amp.py:30-57`` — e.g. ``apex/mlp/mlp.py:24`` marks
MLP as half-class), ``disable_casts`` (``apex/amp/handle.py:163-167``), and
``master_params`` (``apex/amp/_amp_state.py``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional, Union

import jax
import jax.numpy as jnp

from apex_tpu.amp import lists as _lists
from apex_tpu.amp.master import MasterWeights
from apex_tpu.amp.policy import O0, Policy, get_policy, with_policy
from apex_tpu.amp.scaler import (LossScalerState, init_loss_scaler,
                                 skip_step_if_nonfinite)

# per-level loss-scale defaults, Properties tables (frontend.py:102-191):
# O1/O2 default "dynamic", O0/O3 default 1.0
_DEFAULT_LOSS_SCALE = {"O0": 1.0, "O1": "dynamic", "O2": "dynamic", "O3": 1.0}


@dataclasses.dataclass
class AmpState:
    """Everything ``amp.initialize`` configures, as explicit values.

    ``scaler`` is ``None`` when scaling is inactive (static scale 1.0 —
    O0/O3 defaults), one :class:`LossScalerState` normally, or a list of
    ``num_losses`` independent states when multiple losses were requested.
    """

    params: Any                     # cast pytree, or MasterWeights (O2)
    optimizer: Any                  # optax-style; overflow-guarded if scaled
    scaler: Union[None, LossScalerState, list]
    policy: Policy


def initialize(
    params,
    optimizer=None,
    opt_level: str = "O1",
    *,
    half_dtype=jnp.bfloat16,
    loss_scale=None,
    keep_batchnorm_fp32: Optional[bool] = None,
    master_weights: Optional[bool] = None,
    num_losses: int = 1,
    verbosity: int = 0,
) -> AmpState:
    """Functional ``amp.initialize`` (``apex/amp/frontend.py:195``).

    * ``opt_level`` / ``keep_batchnorm_fp32`` / ``master_weights`` /
      ``loss_scale`` keep the reference's names, defaults, and per-level
      validation (via :func:`apex_tpu.amp.policy.get_policy`);
    * params are cast to the policy's param dtype — O2 wraps them in
      :class:`MasterWeights` (fp32 masters + half model copy);
    * ``loss_scale=None`` takes the level's default ("dynamic" for O1/O2,
      1.0 for O0/O3 — with bf16 the dynamic scaler simply never fires);
    * the optimizer is wrapped with :func:`skip_step_if_nonfinite` whenever
      a scaler is active, the functional form of the reference's patched
      ``optimizer.step`` overflow skip;
    * ``num_losses > 1`` returns a LIST of independent scaler states
      (the reference's per-loss ``LossScaler`` array,
      ``apex/amp/_initialize.py:227-231`` + ``scale_loss(..., loss_id)``) —
      pass ``state.scaler[i]`` to :func:`scaled_value_and_grad` per loss.

    Run the model under ``with_policy(state.policy)`` (or pass the policy
    explicitly) so O1 per-op rules apply — the moral equivalent of the
    reference's namespace patching.
    """
    del verbosity  # rank-aware logging covers this (utils/logging.py)
    policy = get_policy(opt_level, half_dtype=half_dtype,
                        keep_norm_f32=keep_batchnorm_fp32,
                        master_weights=master_weights)

    if loss_scale is None:
        loss_scale = _DEFAULT_LOSS_SCALE[opt_level]
    scaler = init_loss_scaler(loss_scale)
    scaled = scaler.dynamic or float(scaler.loss_scale) != 1.0
    if scaled and num_losses > 1:
        scaler = [init_loss_scaler(loss_scale) for _ in range(num_losses)]

    if policy.master_weights:
        out_params = MasterWeights.create(params, policy)
    else:
        out_params = jax.tree.map(
            lambda a: a.astype(policy.param_dtype)
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
            else a,
            params,
        )

    if optimizer is not None and scaled:
        optimizer = skip_step_if_nonfinite(optimizer)

    return AmpState(params=out_params, optimizer=optimizer,
                    scaler=scaler if scaled else None, policy=policy)


def _op_decorator(register):
    def decorator(fn):
        name = fn.__name__
        if (name in _lists.HALF_OPS or name in _lists.FLOAT_OPS
                or name in _lists.PROMOTE_OPS):
            import warnings

            warnings.warn(
                f"amp: {name!r} is already a registered op family — "
                f"decorating a function with this name rewrites the O1 cast "
                f"rule for every op that consults it; rename the function "
                f"if that is not intended")
        register(name)

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            cast = _lists.apply_op_rules(name, *args)
            return fn(*cast, **kwargs)

        return wrapped

    return decorator


#: Decorators marking a function's cast class under O1 — float kwargs are
#: left untouched (positional arrays only), like the reference's wrappers
#: cast ``args`` (``apex/amp/wrap.py:19-25``).
half_function = _op_decorator(_lists.register_half_op)
float_function = _op_decorator(_lists.register_float_op)
promote_function = _op_decorator(_lists.register_promote_op)


def disable_casts():
    """Context manager suspending O1 per-op casting
    (``apex/amp/handle.py:163-167`` — the reference flips the handle
    inactive so wrapped ops run untouched; here the O0 policy is pushed, so
    ``apply_op_rules`` becomes identity)."""
    return with_policy(O0)


def master_params(state) -> list:
    """fp32 master leaves (``apex.amp.master_params(optimizer)``) — accepts
    an :class:`AmpState`, a :class:`MasterWeights`, or a bare pytree."""
    if isinstance(state, AmpState):
        state = state.params
    if isinstance(state, MasterWeights):
        state = state.master
    return jax.tree.leaves(state)
