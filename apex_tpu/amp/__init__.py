"""Automatic mixed precision — the policy engine.

TPU-native re-design of ``apex.amp`` (reference ``apex/amp/frontend.py``,
``_initialize.py``, ``scaler.py``, ``handle.py``, ``amp.py``/``wrap.py``).

The reference implements AMP by monkey-patching torch namespaces (O1), or by
casting modules in place and patching ``forward``/``step`` (O2/O3). In JAX,
parameters and activations are explicit pytrees and the program is traced
functionally, so the same four opt-levels become *data*:

======  ===========================  ==========================================
level   reference semantics          apex_tpu policy
======  ===========================  ==========================================
O0      fp32 everything              params fp32, compute fp32
O1      patched cast per-op          params fp32, compute bf16 with per-op
                                     dtype rules (see :mod:`apex_tpu.amp.lists`)
O2      fp16 model + fp32 masters    params bf16 at forward, fp32 master copy,
                                     norms fp32, fp32 optimizer update
O3      fp16 everything              params/compute bf16
======  ===========================  ==========================================

Loss scaling is optional (needed for fp16, usually unnecessary for bf16) and
is a pure function of a :class:`LossScalerState` — the reference's
"patch optimizer.step to skip" trick (``apex/amp/handle.py:128-154``) becomes
a ``lax.cond`` inside the update step, with zero host round-trips.
"""

from apex_tpu.amp.policy import (  # noqa: F401
    Policy,
    O0,
    O1,
    O2,
    O3,
    get_policy,
    with_policy,
    current_policy,
)
from apex_tpu.amp.scaler import (  # noqa: F401
    LossScalerState,
    init_loss_scaler,
    scale_loss,
    unscale_grads,
    update_loss_scaler,
    scaled_value_and_grad,
    all_finite,
    apply_if_finite,
    skip_step_if_nonfinite,
    scaler_metrics,
    state_dict,
    load_state_dict,
)
from apex_tpu.amp.master import MasterWeights, apply_updates_with_master  # noqa: F401
from apex_tpu.amp.lists import (  # noqa: F401
    apply_op_rules,
    check_banned,
    op_cast_dtype,
    register_float_op,
    register_half_op,
    register_promote_op,
    register_float_function,
    register_half_function,
    register_promote_function,
)
from apex_tpu.amp.frontend import (  # noqa: F401
    AmpState,
    initialize,
    half_function,
    float_function,
    promote_function,
    disable_casts,
    master_params,
)
