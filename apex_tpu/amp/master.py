"""fp32 master weights (opt-level O2).

Re-design of ``apex/amp/_process_optimizer.py``'s master-weight machinery:
the reference clones fp16 params into fp32 masters and swaps them into the
optimizer's ``param_groups`` (``_process_optimizer.py:28-90``), then patches
``step`` to copy master→model afterwards (``:354-364``).

Functionally: the fp32 master pytree is the single source of truth; the model
(compute-dtype) params are a *derived* cast, re-materialized once per step.
The master→model copy (``amp_C.multi_tensor_scale`` in the reference,
``_process_optimizer.py:14-25``) is one fused ``astype`` XLA folds into the
next forward pass.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.policy import Policy
from apex_tpu.amp.scaler import apply_if_finite
from apex_tpu.utils.pytree import tree_cast

PyTree = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MasterWeights:
    """fp32 masters + the derived compute-dtype model params."""

    master: PyTree                 # fp32, what the optimizer updates
    model: PyTree                  # param_dtype (bf16/fp16), what forward uses
    param_dtype: Any = dataclasses.field(metadata=dict(static=True), default=jnp.bfloat16)

    @classmethod
    def create(cls, params: PyTree, policy: Policy) -> "MasterWeights":
        """Initialize masters from (possibly half) params — the reference's
        ``lazy_init_with_master_weights`` (``_process_optimizer.py:28-90``)."""
        master = tree_cast(params, jnp.float32)
        return cls(master=master, model=tree_cast(master, policy.param_dtype),
                   param_dtype=policy.param_dtype)

    def resync(self) -> "MasterWeights":
        """Re-derive model params from masters (master→model copy,
        ``_process_optimizer.py:354-364``)."""
        return dataclasses.replace(self, model=tree_cast(self.master, self.param_dtype))


def apply_updates_with_master(
    weights: MasterWeights,
    updates: PyTree,
    *,
    grads_finite: Optional[jax.Array] = None,
) -> MasterWeights:
    """Apply optax-style additive ``updates`` to the fp32 masters, skip when
    grads overflowed, and re-derive the model params. The full O2 step
    epilogue as one pure function."""
    new_master = jax.tree.map(lambda p, u: p + jnp.asarray(u, p.dtype), weights.master, updates)
    if grads_finite is not None:
        new_master = apply_if_finite(weights.master, new_master, grads_finite)
    return dataclasses.replace(weights, master=new_master).resync()


def o2_state_dict_params(weights: MasterWeights) -> PyTree:
    """fp32 params for checkpointing regardless of cast — the reference's
    ``O2StateDictHook`` (``apex/amp/_initialize.py:133-143,207-210``)."""
    return weights.master
