"""Precision policies: the functional O0-O3 engine.

Replaces the reference's ``Properties`` / opt-level system
(``apex/amp/frontend.py:7-191``) and the cast machinery of
``apex/amp/_initialize.py`` (``convert_network`` at ``:176-182``, input/output
cast patching at ``:194-201``) with an explicit, composable policy object
applied to pytrees. ``keep_batchnorm_fp32`` (``frontend.py:134-144``)
generalizes to ``keep_norm_f32`` — normalization layers read
``current_policy().norm_dtype`` instead of being monkey-converted.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.utils.pytree import tree_cast

PyTree = Any


@dataclasses.dataclass(frozen=True)
class Policy:
    """A mixed-precision policy (jmp-style: param/compute/output dtypes).

    Attributes mirror the reference's opt-level ``Properties``
    (``apex/amp/frontend.py:37-97``):

    * ``cast_model_type``      → :attr:`compute_dtype`
    * ``master_weights``       → :attr:`master_weights`
    * ``keep_batchnorm_fp32``  → :attr:`keep_norm_f32`
    * ``patch_torch_functions``→ :attr:`per_op_rules` (declarative, not patched)
    * ``loss_scale``           → carried by the loss scaler, not the policy
    """

    name: str = "O0"
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    output_dtype: jnp.dtype = jnp.float32
    master_weights: bool = False
    keep_norm_f32: bool = True
    per_op_rules: bool = False  # O1: consult apex_tpu.amp.lists per op family

    # -- pytree casts ---------------------------------------------------------
    def cast_to_param(self, tree: PyTree) -> PyTree:
        return tree_cast(tree, self.param_dtype)

    def cast_to_compute(self, tree: PyTree) -> PyTree:
        """Cast params/inputs for the forward pass (the reference's patched
        ``model.forward`` input cast, ``_initialize.py:194-201``)."""
        return tree_cast(tree, self.compute_dtype)

    def cast_to_output(self, tree: PyTree) -> PyTree:
        """Cast network outputs (reference casts outputs back to fp32 so the
        loss is computed in fp32, ``_initialize.py:39-61``)."""
        return tree_cast(tree, self.output_dtype)

    @property
    def norm_dtype(self) -> jnp.dtype:
        """Compute dtype for normalization statistics (BN/LN/RMSNorm)."""
        return jnp.float32 if self.keep_norm_f32 else self.compute_dtype

    def run(self, fn, params: PyTree, *args, **kwargs):
        """Run ``fn(params, *args)`` under this policy: params+inputs cast to
        compute dtype, outputs cast to output dtype. One-call equivalent of
        ``amp.initialize`` + forward."""
        out = fn(
            self.cast_to_compute(params),
            *self.cast_to_compute(args),
            **self.cast_to_compute(kwargs),
        )
        return self.cast_to_output(out)


def _make(name, param, compute, output, master, keep_norm, per_op=False) -> Policy:
    return Policy(
        name=name,
        param_dtype=param,
        compute_dtype=compute,
        output_dtype=output,
        master_weights=master,
        keep_norm_f32=keep_norm,
        per_op_rules=per_op,
    )


# Opt-level presets (reference defaults: frontend.py:102-191). bf16 replaces
# fp16 as the TPU half dtype; pass half_dtype=jnp.float16 to get_policy for
# strict fp16 semantics (then pair with the dynamic loss scaler).
O0 = _make("O0", jnp.float32, jnp.float32, jnp.float32, master=False, keep_norm=True)
O1 = _make("O1", jnp.float32, jnp.bfloat16, jnp.float32, master=False, keep_norm=True, per_op=True)
O2 = _make("O2", jnp.bfloat16, jnp.bfloat16, jnp.float32, master=True, keep_norm=True)
O3 = _make("O3", jnp.bfloat16, jnp.bfloat16, jnp.bfloat16, master=False, keep_norm=False)

_LEVELS = {"O0": O0, "O1": O1, "O2": O2, "O3": O3}


def get_policy(
    opt_level: str = "O0",
    *,
    half_dtype: jnp.dtype = jnp.bfloat16,
    keep_norm_f32: Optional[bool] = None,
    master_weights: Optional[bool] = None,
) -> Policy:
    """Look up an opt-level preset with overrides.

    Mirrors ``amp.initialize(opt_level=..., keep_batchnorm_fp32=...,
    master_weights=...)`` (``apex/amp/frontend.py:195-358``) — overrides are
    validated against the level exactly as ``Properties.__setattr__`` does.
    """
    if opt_level not in _LEVELS:
        raise ValueError(f"unknown opt_level {opt_level!r}; expected one of {sorted(_LEVELS)}")
    p = _LEVELS[opt_level]
    sub = lambda d: half_dtype if d == jnp.bfloat16 else d  # noqa: E731
    p = dataclasses.replace(
        p,
        param_dtype=sub(p.param_dtype),
        compute_dtype=sub(p.compute_dtype),
        output_dtype=sub(p.output_dtype),
    )
    if keep_norm_f32 is not None:
        if opt_level == "O1" and not keep_norm_f32:
            raise ValueError("O1 keeps norms in fp32 (cf. frontend.py:125-131)")
        p = dataclasses.replace(p, keep_norm_f32=keep_norm_f32)
    if master_weights is not None:
        if opt_level == "O1" and master_weights:
            raise ValueError("O1 does not use master weights (cf. frontend.py:118)")
        p = dataclasses.replace(p, master_weights=master_weights)
    return p


# -- ambient policy context ---------------------------------------------------
# Layers (normalization, dense, attention) consult the ambient policy for
# their compute dtype, replacing the reference's module conversion walk.

_tls = threading.local()


class with_policy:
    """Context manager installing an ambient policy for layer construction.

    Also usable as a decorator. Equivalent role to ``amp.initialize`` making
    the whole program run under an opt level; unlike the reference it patches
    nothing — layers *read* the policy.
    """

    def __init__(self, policy: Policy):
        self.policy = policy

    def __enter__(self) -> Policy:
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self.policy)
        return self.policy

    def __exit__(self, *exc):
        _tls.stack.pop()
        return False

    def __call__(self, fn):
        def wrapped(*args, **kwargs):
            with self:
                return fn(*args, **kwargs)

        return wrapped


def current_policy() -> Policy:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else O0
