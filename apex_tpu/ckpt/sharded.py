"""Sharded ZeRO checkpoint format: per-rank chunk-row shards + manifest.

The persistence half of :mod:`apex_tpu.contrib.optimizers.distributed`
(ROADMAP item 4, lineage Xu et al. arXiv:2004.13336): the chunked
mega-buffer's flat ``(n_chunks, chunk_size)`` row space is
dp-independent — a rank's ZeRO shard is just a contiguous row slice of
it — so persisting each rank's ``(rows_per_rank, chunk)`` fp32 buffers
(m/v + masters) plus a self-describing :class:`~apex_tpu.ckpt.manifest.
Manifest` makes ELASTIC restore natural:

* **same dp**: each target rank reads exactly its source shard file —
  fp32 rows round-trip bitwise through npz, so resume is bitwise
  (masters + m/v + scaler identical; the acceptance witness);
* **dp′ ≠ dp**: the global row space is re-padded to dp′
  (``_pad_chunks`` padding rows are zeros at every width) and re-sliced
  into dp′ contiguous shards; a target rank's shard is assembled from
  the 1–2+ source shards its row range overlaps — no full-buffer
  materialization beyond the one target shard being built (plus one
  source shard in flight), which is what lets a small resumed fleet
  restore a big fleet's state.

Commit is ATOMIC: everything lands in a ``<dir>.tmp-*`` sibling first
and one ``os.rename`` publishes the finished checkpoint — a crash (or
the injected test fault) at ANY point mid-save leaves either no
directory or the complete one, never a torn checkpoint, and the
previous committed checkpoint untouched. Restore-side validation is
eager and knob-naming (missing manifest, digest mismatch, layout
mismatch), per repo style.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from apex_tpu.ckpt.manifest import (Manifest, pad_rows_for, read_manifest,
                                    shard_rows, write_manifest)
from apex_tpu.ckpt.pytree_io import array_digest, savez_atomic

PyTree = Any

SHARD_NAME = "shard_{:05d}.npz"

#: buffer name the replicated low-precision/fp32 params save under when
#: the state carries no sharded fp32 masters (fp32 training keeps the
#: params outside ZeroState; the checkpoint is self-contained either way)
PARAMS_BUFFER = "params"


#: absolute paths of tmp directories THIS process is actively writing —
#: cleanup_stale_tmp spares them (a second manager constructed over the
#: same root mid-save must not rmtree a live writer's work). Entries are
#: discarded when the write ends in ANY way (commit, error, or the
#: injected crash — after which no thread will touch the path again, so
#: the litter becomes sweepable exactly like a killed process's).
_ACTIVE_TMP: set = set()


class SimulatedCrash(BaseException):
    """Raised BY a test fault hook to emulate a SIGKILL mid-save: the
    writer stops where it stands — no cleanup, no commit — exactly the
    on-disk state a killed process leaves. BaseException so ordinary
    ``except Exception`` recovery paths cannot accidentally swallow it
    into a half-written commit."""


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(msg)


def snapshot_zero_state(state) -> Tuple[Dict[str, np.ndarray], int, int]:
    """Host copies of a gathered ZeroState's buffers: returns
    ``(buffers, count, n_chunks)`` with every buffer a numpy fp32 array.
    This is the device→host transfer the async saver runs BETWEEN steps
    — after it returns, the device state may keep training."""
    buffers = {k: np.asarray(v) for k, v in state.buffers.items()}
    count = int(np.asarray(state.count))
    n_chunks = int(np.shape(state.layout.chunk_to_tensor)[0])
    return buffers, count, n_chunks


def _params_rows(params, layout, padded_rows: int) -> np.ndarray:
    """Flatten a replicated param tree into fp32 chunk rows padded to
    the save width's row space (the live param image / the master-less
    ``params`` buffer). PURE numpy — the same packing rule as
    ``multi_tensor.flatten_to_chunks`` (fp32 upcast, per-tensor
    zero-padded tails, empty tensors own one chunk) but runnable on the
    async WRITER thread without dispatching device work mid-train."""
    import jax

    c = int(layout.chunk_size)
    parts = []
    for x in jax.tree.leaves(params):
        flat = np.asarray(x).astype(np.float32).reshape(-1)
        pad = (-flat.size) % c if flat.size else c
        if pad:
            flat = np.concatenate([flat, np.zeros(pad, np.float32)])
        parts.append(flat)
    buf = np.concatenate(parts).reshape(-1, c)
    tail = padded_rows - buf.shape[0]
    if tail:
        buf = np.concatenate([buf, np.zeros((tail, c), np.float32)])
    return buf


def save_zero_sharded(directory: str, state, *, dp: int,
                      params: Optional[PyTree] = None,
                      scaler_state: Any = None, step: int = 0,
                      fault=None, overwrite: bool = False) -> Manifest:
    """Write a sharded ZeRO checkpoint of ``state`` at width ``dp``.

    ``state`` is a :class:`~apex_tpu.contrib.optimizers.distributed.
    ZeroState` whose buffers are the GLOBAL gathered ``(padded_rows,
    chunk)`` arrays (``gather_zero_state`` exports the training-loop
    layout into this view; a multi-host deployment writes its
    addressable rows through :func:`write_shard` directly). ``params``
    must be passed when the state carries no ``master`` buffer (fp32
    training keeps params outside the state) so the checkpoint stays
    self-contained; ``scaler_state`` (a LossScalerState or its
    ``state_dict`` payload) rides in the manifest so fp16 recovery
    resumes mid-trajectory. ``fault`` is the crash-injection hook
    (called with ``"shard:<rank>"``/``"manifest"``/``"commit"``;
    raising :class:`SimulatedCrash` abandons the save exactly there).
    """
    buffers, count, n_chunks = snapshot_zero_state(state)
    _require(dp >= 1, f"dp must be >= 1, got {dp}")
    chunk = int(state.layout.chunk_size)
    padded, rows_per_rank = shard_rows(n_chunks, dp)
    for name, buf in buffers.items():
        _require(buf.ndim == 2 and buf.shape[1] == chunk,
                 f"buffer {name!r} has shape {buf.shape}; expected "
                 f"(rows, chunk_size={chunk})")
        _require(
            buf.shape[0] == padded,
            f"buffer {name!r} has {buf.shape[0]} rows but dp={dp} over "
            f"n_chunks={n_chunks} shards {padded} padded rows — save "
            f"takes the GLOBAL gathered state (out_specs P('dp') via "
            f"gather_zero_state), and dp must match the axis it was "
            f"gathered over")
    if params is not None:
        # the LIVE param image, always — even with fp32 masters in the
        # state: low-precision training params are p + (new - p) in the
        # param dtype, which is NOT bitwise the master's cast image, so
        # a bitwise mid-training resume needs the params themselves
        # (fp16/bf16 → fp32 rows is exact, as is the cast back)
        buffers[PARAMS_BUFFER] = _params_rows(params, state.layout,
                                              padded)
    elif "master" not in buffers:
        raise ValueError(
            "the state carries no 'master' buffer (fp32 training keeps "
            "params outside ZeroState) — pass params= so the "
            "checkpoint stays self-contained")

    scaler_payload = None
    if scaler_state is not None:
        if isinstance(scaler_state, dict):
            scaler_payload = dict(scaler_state)
        else:
            from apex_tpu.amp.scaler import state_dict as scaler_sd
            scaler_payload = scaler_sd(scaler_state)

    names = sorted(buffers)
    manifest = Manifest(
        dp=dp, chunk_size=chunk, n_chunks=n_chunks,
        pad_rows=pad_rows_for(n_chunks, dp), rows_per_rank=rows_per_rank,
        buffers=names,
        param_shapes=[list(s) for s in state.layout.shapes],
        step=int(step), count=count,
        digests={n: [array_digest(
            buffers[n][r * rows_per_rank:(r + 1) * rows_per_rank])
            for r in range(dp)] for n in names},
        scaler=scaler_payload,
        params_included=("master" in buffers
                         or PARAMS_BUFFER in buffers),
    )

    if os.path.exists(directory) and not overwrite:
        raise FileExistsError(
            f"checkpoint directory {directory!r} already exists — "
            f"pass overwrite=True or save to a fresh step directory")
    parent = os.path.dirname(os.path.abspath(directory))
    os.makedirs(parent, exist_ok=True)
    tmp = f"{directory}.tmp-{os.getpid()}"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    _ACTIVE_TMP.add(os.path.abspath(tmp))
    try:
        for r in range(dp):
            write_shard(tmp, r, {n: buffers[n][r * rows_per_rank:
                                               (r + 1) * rows_per_rank]
                                 for n in names})
            if fault is not None:
                fault(f"shard:{r}")
        write_manifest(tmp, manifest)
        if fault is not None:
            fault("manifest")
        if fault is not None:
            fault("commit")
        if overwrite and os.path.exists(directory):
            # only once the replacement is FULLY written: a crash
            # anywhere above leaves the old checkpoint untouched, and
            # the window between these two lines is the narrowest
            # possible
            shutil.rmtree(directory)
        os.rename(tmp, directory)  # the atomic commit
    finally:
        _ACTIVE_TMP.discard(os.path.abspath(tmp))
    return manifest


def write_shard(directory: str, rank: int,
                buffers: Dict[str, np.ndarray]) -> int:
    """The per-rank writer: one ``shard_<rank>.npz`` holding this
    rank's row slice of every buffer. Multi-host deployments call this
    with their addressable rows; the single-process saver loops it."""
    return savez_atomic(
        os.path.join(directory, SHARD_NAME.format(rank)),
        {k: np.ascontiguousarray(np.asarray(v, np.float32))
         for k, v in buffers.items()})


def _read_shard(directory: str, manifest: Manifest, rank: int,
                verify: bool,
                names: Optional[List[str]] = None
                ) -> Dict[str, np.ndarray]:
    """Read (and digest-verify) ``names`` buffers of one shard file —
    default all the manifest names. Callers that want a single buffer
    (the param loader) pass a subset so a multi-GB shard's m/v rows
    are neither decompressed nor hashed for nothing."""
    path = os.path.join(directory, SHARD_NAME.format(rank))
    if not os.path.isfile(path):
        raise FileNotFoundError(
            f"checkpoint {directory!r} is missing {SHARD_NAME.format(rank)} "
            f"(manifest says dp={manifest.dp} shards)")
    out = {}
    try:
        zf = np.load(path)
    except Exception as e:  # torn/overwritten archive: name the file,
        # never surface numpy's zip internals as the diagnosis
        raise ValueError(
            f"{path} is not a readable npz archive ({e}) — the shard "
            f"file is corrupt; restore from another checkpoint") from e
    with zf:
        for name in (manifest.buffers if names is None else names):
            if name not in zf.files:
                raise ValueError(
                    f"{path} is missing buffer {name!r} named by the "
                    f"manifest (holds: {sorted(zf.files)})")
            try:
                arr = zf[name]
            except Exception as e:  # bad CRC / truncated member
                raise ValueError(
                    f"{path} buffer {name!r} is unreadable ({e}) — the "
                    f"shard file is corrupt; restore from another "
                    f"checkpoint") from e
            want = (manifest.rows_per_rank, manifest.chunk_size)
            if tuple(arr.shape) != want:
                raise ValueError(
                    f"{path} buffer {name!r} has shape "
                    f"{tuple(arr.shape)}; manifest says {want}")
            if verify and name in manifest.digests:
                got = array_digest(arr)
                expect = manifest.digests[name][rank]
                if got != expect:
                    raise ValueError(
                        f"digest mismatch for buffer {name!r} in "
                        f"{SHARD_NAME.format(rank)}: manifest says "
                        f"{expect[:12]}..., file hashes {got[:12]}... — "
                        f"the checkpoint is corrupt (or was edited); "
                        f"pass verify=False only to forensically "
                        f"inspect it")
            out[name] = arr
    return out


def restore_zero_shard(directory: str, rank: int, dp: int, *,
                       manifest: Optional[Manifest] = None,
                       verify: bool = True,
                       buffers: Optional[List[str]] = None,
                       _cache: Optional[Dict[int, Dict[str, np.ndarray]]]
                       = None) -> Dict[str, np.ndarray]:
    """ONE target rank's buffers at width ``dp`` (elastic): reads only
    the source shards whose row ranges overlap the target's, assembling
    at most one target shard + one source shard at a time (pass a
    ``_cache`` dict to share source reads across target ranks in a
    single-process restore). ``buffers`` restricts which buffer names
    are read (default all)."""
    if manifest is None:
        manifest = read_manifest(directory)
    _require(dp >= 1, f"dp must be >= 1, got {dp}")
    n = manifest.n_chunks
    chunk = manifest.chunk_size
    padded_new, rpr_new = shard_rows(n, dp)
    _require(rank < dp, f"rank {rank} out of range for dp={dp}")
    src_rpr = manifest.rows_per_rank
    names = list(manifest.buffers) if buffers is None else list(buffers)

    def _src(r: int) -> Dict[str, np.ndarray]:
        if _cache is not None and r in _cache:
            return _cache[r]
        shard = _read_shard(directory, manifest, r, verify, names=names)
        if _cache is not None:
            _cache.clear()  # hold ONE source shard, the current run's
            _cache[r] = shard
        return shard

    start, stop = rank * rpr_new, (rank + 1) * rpr_new
    if dp == manifest.dp:
        # bitwise fast path: the target shard IS a source shard file
        return _src(rank)
    out = {name: np.zeros((rpr_new, chunk), np.float32)
           for name in names}
    live_stop = min(stop, n)  # rows >= n_chunks are padding: zeros
    g = start
    while g < live_stop:
        sr = g // src_rpr
        s_lo = g - sr * src_rpr
        s_hi = min(src_rpr, live_stop - sr * src_rpr)
        shard = _src(sr)
        for name in names:
            out[name][g - start:g - start + (s_hi - s_lo)] = \
                shard[name][s_lo:s_hi]
        g = sr * src_rpr + s_hi
    return out


@dataclasses.dataclass
class RestoredZero:
    """A restore's host-side result: GLOBAL buffers re-sliced to the
    target width (``(padded_rows(dp), chunk)`` each), the optimizer
    count, the save-time step, the scaler payload, and the manifest."""

    buffers: Dict[str, np.ndarray]
    count: int
    step: int
    scaler: Optional[Dict[str, Any]]
    manifest: Manifest
    dp: int


def restore_zero_sharded(directory: str, *, dp: int, verify: bool = True,
                         buffers: Optional[List[str]] = None
                         ) -> RestoredZero:
    """Assemble the full target-width state (every rank's shard,
    stacked rank-major — the single-process/test view; a real fleet
    calls :func:`restore_zero_shard` per rank instead). ``buffers``
    restricts which buffer names are read (default all)."""
    manifest = read_manifest(directory)
    _require(dp >= 1, f"dp must be >= 1, got {dp}")
    names = list(manifest.buffers) if buffers is None else list(buffers)
    cache: Dict[int, Dict[str, np.ndarray]] = {}
    parts: List[Dict[str, np.ndarray]] = [
        restore_zero_shard(directory, r, dp, manifest=manifest,
                           verify=verify, buffers=names, _cache=cache)
        for r in range(dp)]
    out = {name: np.concatenate([p[name] for p in parts])
           for name in names}
    return RestoredZero(buffers=out, count=manifest.count,
                        step=manifest.step, scaler=manifest.scaler,
                        manifest=manifest, dp=dp)


def _validate_layout(manifest: Manifest, layout,
                     chunk_size: Optional[int] = None) -> None:
    """The template's layout must reproduce the manifest's row space;
    each mismatch names its knob."""
    if chunk_size is not None and chunk_size != manifest.chunk_size:
        raise ValueError(
            f"chunk_size mismatch: checkpoint was saved with "
            f"chunk_size={manifest.chunk_size}, restore requested "
            f"{chunk_size} — the chunk-row space is only dp-elastic, "
            f"not chunk-elastic")
    shapes = [list(s) for s in layout.shapes]
    if shapes != manifest.param_shapes:
        for i, (a, b) in enumerate(zip(shapes, manifest.param_shapes)):
            if a != b:
                raise ValueError(
                    f"param tree mismatch at leaf {i}: template shape "
                    f"{a} vs checkpoint shape {b} — restore into the "
                    f"model the checkpoint was saved from")
        raise ValueError(
            f"param tree mismatch: template has {len(shapes)} leaves, "
            f"checkpoint has {len(manifest.param_shapes)}")
    n = int(np.shape(layout.chunk_to_tensor)[0])
    if n != manifest.n_chunks:
        raise ValueError(
            f"layout mismatch: template packs to {n} chunks, checkpoint "
            f"holds {manifest.n_chunks} (chunk_size="
            f"{manifest.chunk_size})")


def load_zero_state(directory: str, params_template: PyTree, *, dp: int,
                    verify: bool = True):
    """Restore into a ready-to-shard ZeroState at width ``dp``: the
    returned state's buffers are the GLOBAL re-sliced arrays — feed it
    through :func:`~apex_tpu.contrib.optimizers.distributed.
    scatter_zero_state` (in_specs ``P('dp')`` on the buffers) to get
    each rank its contiguous shard. Returns ``(state, restored)``."""
    import jax.numpy as jnp

    from apex_tpu.contrib.optimizers.distributed import ZeroState
    from apex_tpu.optimizers import multi_tensor as mt

    manifest = read_manifest(directory)
    layout = mt.make_layout(params_template, manifest.chunk_size)
    _validate_layout(manifest, layout)
    # the params buffer is not optimizer state — don't read (or hash)
    # its rows just to drop them; restore_params is its consumer
    state_names = [b for b in manifest.buffers if b != PARAMS_BUFFER]
    restored = restore_zero_sharded(directory, dp=dp, verify=verify,
                                    buffers=state_names)
    buffers = {k: jnp.asarray(v) for k, v in restored.buffers.items()}
    state = ZeroState(count=jnp.asarray(restored.count, jnp.int32),
                      layout=layout, buffers=buffers)
    return state, restored


def restore_params(directory: str, like: PyTree, *,
                   verify: bool = True) -> PyTree:
    """Rebuild the full (replicated) param tree from a sharded
    checkpoint: the fp32 ``master`` rows when the training was
    mixed-precision, else the ``params`` buffer — cast leaf-wise to
    ``like``'s dtypes. This is the serving hot-swap loader: the result
    has exactly ``like``'s avals, so swapping it into a live
    :class:`~apex_tpu.serving.engine.ServingEngine` is a contents-only
    mutation."""
    import jax.numpy as jnp

    from apex_tpu.optimizers import multi_tensor as mt

    manifest = read_manifest(directory)
    layout = mt.make_layout(like, manifest.chunk_size)
    _validate_layout(manifest, layout)
    # prefer the LIVE param image (bitwise mid-training resume); a
    # masters-only checkpoint rebuilds the master's low-precision cast
    # instead (identical maths going forward, one rounding ULP of
    # history short of bitwise — fine for eval/serving)
    source = PARAMS_BUFFER if PARAMS_BUFFER in manifest.buffers else (
        "master" if "master" in manifest.buffers else None)
    if source is None:
        raise ValueError(
            f"checkpoint {directory!r} holds neither 'master' nor "
            f"'params' buffers (buffers: {manifest.buffers}) — it was "
            f"saved without params= and cannot rebuild a param tree")
    n, chunk = manifest.n_chunks, manifest.chunk_size
    flat = np.zeros((n, chunk), np.float32)
    src_rpr = manifest.rows_per_rank
    for r in range(manifest.dp):
        lo = r * src_rpr
        if lo >= n:
            break
        # read+verify the ONE source buffer, not the whole shard —
        # the hot-swap loader must not hash a checkpoint's m/v rows
        shard = _read_shard(directory, manifest, r, verify,
                            names=[source])
        flat[lo:min(lo + src_rpr, n)] = shard[source][:n - lo]
    return mt.unflatten_from_chunks(jnp.asarray(flat), layout, like=like)
