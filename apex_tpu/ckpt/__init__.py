"""Elastic checkpointing subsystem (ISSUE 14 / ROADMAP item 4).

Grown out of the seed's single-file orbax wrapper
(``apex_tpu/checkpoint.py``, now a compatibility shim over this
package) into four pillars:

* :mod:`~apex_tpu.ckpt.state` — the legacy replicated ``TrainState``
  round-trip (orbax when importable, pure-numpy npz otherwise) plus
  :class:`AutoResume`, the preemption guard;
* :mod:`~apex_tpu.ckpt.sharded` — the dp-sharded ZeRO format: per-rank
  ``(rows_per_rank, chunk)`` fp32 shards + a self-describing manifest,
  bitwise at the same dp and ELASTIC across dp (restore at dp′ ≠ dp
  re-slices the chunk-row space, Xu et al. arXiv:2004.13336);
* :mod:`~apex_tpu.ckpt.async_save` / :mod:`~apex_tpu.ckpt.manager` —
  off-step saves (snapshot between steps, background write, atomic
  rename-commit) under :class:`ZeroCheckpointManager` rotation;
* the serving hot-swap loader (:func:`restore_params`) — rebuilds a
  param tree with exactly a template's avals, so a live
  :class:`~apex_tpu.serving.engine.ServingEngine` swaps weights as a
  contents-only mutation (``engine.request_swap``).

Save cost is observable: ``bench.py --ckpt`` emits the ``ckpt`` monitor
record (``save_overhead_pct`` gated lower-is-better by
``tools/bench_history.py``).
"""

from apex_tpu.ckpt.async_save import AsyncZeroSaver, cleanup_stale_tmp
from apex_tpu.ckpt.manager import ZeroCheckpointManager
from apex_tpu.ckpt.manifest import (Manifest, pad_rows_for, read_manifest,
                                    shard_rows, write_manifest)
from apex_tpu.ckpt.pytree_io import (array_digest, load_tree_npz,
                                     save_tree_npz)
from apex_tpu.ckpt.sharded import (RestoredZero, SimulatedCrash,
                                   load_zero_state, restore_params,
                                   restore_zero_shard,
                                   restore_zero_sharded,
                                   save_zero_sharded, snapshot_zero_state,
                                   write_shard)
from apex_tpu.ckpt.state import (AutoResume, CheckpointManager, TrainState,
                                 amp_load_state_dict, amp_state_dict,
                                 get_autoresume, restore_checkpoint,
                                 save_checkpoint)

__all__ = [
    "AsyncZeroSaver",
    "AutoResume",
    "CheckpointManager",
    "Manifest",
    "RestoredZero",
    "SimulatedCrash",
    "TrainState",
    "ZeroCheckpointManager",
    "amp_load_state_dict",
    "amp_state_dict",
    "array_digest",
    "cleanup_stale_tmp",
    "get_autoresume",
    "load_tree_npz",
    "load_zero_state",
    "pad_rows_for",
    "read_manifest",
    "restore_checkpoint",
    "restore_params",
    "restore_zero_shard",
    "restore_zero_sharded",
    "save_checkpoint",
    "save_tree_npz",
    "save_zero_sharded",
    "shard_rows",
    "snapshot_zero_state",
    "write_manifest",
    "write_shard",
]
