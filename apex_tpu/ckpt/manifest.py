"""The sharded-checkpoint manifest: a self-describing JSON header.

One ``manifest.json`` per checkpoint directory names everything restore
needs to re-slice the chunk-row space WITHOUT opening a shard file:
the chunk layout (per-tensor shapes + ``chunk_size`` — the
:class:`~apex_tpu.optimizers.multi_tensor.ChunkLayout` is re-derived
from these, never pickled), the dp width the shards were written at,
the ``_pad_chunks`` padding rows, the optimizer step count, the loss-
scaler payload, and a per-(buffer, rank) sha256 digest table.

Validation is EAGER and knob-naming (repo style): a mismatched
``chunk_size``, a padded row count its own ``dp`` cannot divide, or a
digest table missing a rank all raise here with the offending knob in
the message — never a deep reshape traceback three layers down.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Tuple

FORMAT = "apex_tpu.zero_sharded"
VERSION = 1

MANIFEST_NAME = "manifest.json"


def pad_rows_for(n_chunks: int, dp: int) -> int:
    """``_pad_chunks``'s padding row count at width ``dp``."""
    return (-n_chunks) % dp


def shard_rows(n_chunks: int, dp: int) -> Tuple[int, int]:
    """(padded_rows, rows_per_rank) of the global chunk-row space at
    width ``dp`` — the save/restore row math shared with
    :func:`apex_tpu.contrib.optimizers.distributed.shard_row_range`."""
    padded = n_chunks + pad_rows_for(n_chunks, dp)
    return padded, padded // dp


@dataclasses.dataclass
class Manifest:
    """Everything a restore needs, JSON-round-trippable."""

    dp: int
    chunk_size: int
    n_chunks: int
    pad_rows: int
    rows_per_rank: int
    buffers: List[str]
    param_shapes: List[List[int]]
    step: int = 0
    count: int = 0
    digests: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    scaler: Optional[Dict[str, Any]] = None
    params_included: bool = True
    digest_algo: str = "sha256"
    format: str = FORMAT
    version: int = VERSION

    @property
    def padded_rows(self) -> int:
        return self.n_chunks + self.pad_rows

    def validate(self) -> None:
        """Eager self-consistency check; every failure names the knob."""
        if self.format != FORMAT:
            raise ValueError(
                f"manifest format {self.format!r} is not {FORMAT!r} — "
                f"this directory does not hold a sharded ZeRO checkpoint")
        if self.version > VERSION:
            raise ValueError(
                f"manifest version {self.version} is newer than this "
                f"reader's {VERSION} — a future writer may have changed "
                f"digest or row-space semantics; upgrade before "
                f"restoring")
        if self.dp < 1:
            raise ValueError(f"manifest dp must be >= 1, got {self.dp}")
        if self.chunk_size < 1:
            raise ValueError(
                f"manifest chunk_size must be >= 1, got {self.chunk_size}")
        if self.pad_rows != pad_rows_for(self.n_chunks, self.dp):
            raise ValueError(
                f"manifest pad_rows ({self.pad_rows}) is not "
                f"(-n_chunks) % dp = {pad_rows_for(self.n_chunks, self.dp)} "
                f"for n_chunks={self.n_chunks}, dp={self.dp}")
        if self.padded_rows % self.dp:
            raise ValueError(
                f"manifest dp ({self.dp}) does not divide the padded row "
                f"count ({self.padded_rows} = n_chunks {self.n_chunks} + "
                f"pad_rows {self.pad_rows})")
        if self.rows_per_rank * self.dp != self.padded_rows:
            raise ValueError(
                f"manifest rows_per_rank ({self.rows_per_rank}) x dp "
                f"({self.dp}) != padded rows ({self.padded_rows})")
        if not self.buffers:
            raise ValueError("manifest names no buffers")
        for name, per_rank in self.digests.items():
            if name not in self.buffers:
                raise ValueError(
                    f"manifest digest table names unknown buffer {name!r} "
                    f"(buffers: {self.buffers})")
            if len(per_rank) != self.dp:
                raise ValueError(
                    f"manifest digest table for {name!r} has "
                    f"{len(per_rank)} entries for dp={self.dp}")

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, obj: Dict[str, Any]) -> "Manifest":
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(obj) - fields)
        if unknown:
            raise ValueError(
                f"manifest carries unknown keys {unknown} — not a "
                f"{FORMAT} manifest (or a newer format than version "
                f"{VERSION})")
        missing = sorted(
            {f.name for f in dataclasses.fields(cls)
             if f.default is dataclasses.MISSING
             and f.default_factory is dataclasses.MISSING} - set(obj))
        if missing:
            raise ValueError(f"manifest is missing required keys {missing}")
        m = cls(**obj)
        m.validate()
        return m

    def summary(self) -> Dict[str, Any]:
        """The closed ``manifest`` object riding the ``ckpt`` monitor
        record (CKPT_MANIFEST_SCHEMA: additionalProperties false — a
        junk key here fails validation)."""
        return {
            "format": self.format,
            "version": self.version,
            "step": self.step,
            "count": self.count,
            "dp": self.dp,
            "chunk_size": self.chunk_size,
            "n_chunks": self.n_chunks,
            "pad_rows": self.pad_rows,
            "rows_per_rank": self.rows_per_rank,
            "buffers": list(self.buffers),
            "digest_algo": self.digest_algo,
        }


def write_manifest(directory: str, manifest: Manifest) -> None:
    manifest.validate()
    path = os.path.join(directory, MANIFEST_NAME)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(manifest.to_json(), fh, indent=1)
    os.replace(tmp, path)


def read_manifest(directory: str) -> Manifest:
    """Read + eagerly validate ``manifest.json``; a missing manifest is
    a :class:`FileNotFoundError` naming the path (an uncommitted or
    foreign directory, not a corrupt checkpoint)."""
    path = os.path.join(directory, MANIFEST_NAME)
    if not os.path.isfile(path):
        raise FileNotFoundError(
            f"no {MANIFEST_NAME} under {directory!r} — not a committed "
            f"sharded checkpoint (an interrupted save never commits its "
            f"temp directory, so a missing manifest means this directory "
            f"never finished writing or is not a checkpoint at all)")
    with open(path) as fh:
        try:
            obj = json.load(fh)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path} is not valid JSON: {e}") from e
    if not isinstance(obj, dict):
        raise ValueError(f"{path} does not hold a JSON object")
    try:
        return Manifest.from_json(obj)
    except (TypeError, ValueError) as e:
        raise ValueError(f"{path}: {e}") from e
