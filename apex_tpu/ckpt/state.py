"""Replicated ``TrainState`` checkpointing + the preemption guard.

The legacy half of :mod:`apex_tpu.ckpt` (grown out of the seed's
``apex_tpu/checkpoint.py``): one ``TrainState`` pytree holds (master
params, optimizer state, loss scaler state, step) and round-trips
bitwise — through orbax when it is importable, else through the
pure-numpy ``.npz`` writer in :mod:`apex_tpu.ckpt.pytree_io` (the seed
raised ``RuntimeError("orbax is unavailable")`` instead, which made
every checkpoint test environment-dependent).

Re-design of the reference's checkpoint surface (SURVEY.md §5): the
reference persists amp's per-loss scaler state (``amp.state_dict()``
``frontend.py:361-400``), fp32 master weights regardless of cast
(``O2StateDictHook`` ``_initialize.py:133-143``), and
``FP16_Optimizer.state_dict`` (scaler + masters,
``fp16_optimizer.py:209-270``), documenting a bitwise-accurate resume
recipe (``README.md:60-100``).

The dp-SHARDED ZeRO state does not come through here — that is
:mod:`apex_tpu.ckpt.sharded` (elastic per-rank shards) driven by
:class:`apex_tpu.ckpt.manager.ZeroCheckpointManager`.
"""

from __future__ import annotations

import dataclasses
import glob
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ckpt.pytree_io import load_tree_npz, save_tree_npz

try:
    import orbax.checkpoint as ocp
    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False

PyTree = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    """Everything a bitwise resume needs (cf. README.md:60-100 recipe)."""

    step: jax.Array
    params: PyTree              # fp32 masters (O2StateDictHook semantics)
    opt_state: PyTree
    scaler_state: Optional[PyTree] = None
    extra: Optional[PyTree] = None  # e.g. BN running stats


def save_checkpoint(path: str, state: TrainState) -> None:
    if _HAS_ORBAX:
        ckpt = ocp.StandardCheckpointer()
        ckpt.save(path, state)
        ckpt.wait_until_finished()
    else:
        # orbax-free fallback: the same bitwise round-trip through npz
        # (fp32/bf16/int leaves preserve raw bytes); `path` becomes a
        # single archive instead of a directory
        save_tree_npz(_npz_path(path), state)


def restore_checkpoint(path: str, template: TrainState) -> TrainState:
    """Restore into the shapes/dtypes (and shardings) of ``template``.

    Format is probed from DISK, not from the installed libraries: an
    orbax checkpoint directory at ``path`` wins when one exists (so a
    stale ``path.npz`` from an earlier orbax-less run can never shadow
    a newer orbax save to the same path); the npz archive restores with
    or without orbax installed."""
    npz = _npz_path(path)
    if _HAS_ORBAX and os.path.isdir(path):
        ckpt = ocp.StandardCheckpointer()
        return ckpt.restore(path, template)
    if os.path.isfile(npz):
        return load_tree_npz(npz, template)
    if not _HAS_ORBAX:
        raise FileNotFoundError(
            f"no npz checkpoint at {npz} and orbax is unavailable to "
            f"read {path!r}")
    ckpt = ocp.StandardCheckpointer()
    return ckpt.restore(path, template)


def _npz_path(path: str) -> str:
    return path if path.endswith(".npz") else path + ".npz"


class CheckpointManager:
    """Rotating, optionally-async checkpoints over :class:`TrainState` —
    beyond the reference's library-level state dicts (its trainers save
    synchronously with ``torch.save``): ``save`` returns once the on-device
    state is snapshotted and the write overlaps subsequent train steps;
    ``max_to_keep`` rotates old steps out. Thin policy layer over
    ``orbax.checkpoint.CheckpointManager`` when orbax is importable;
    otherwise the same surface runs on the npz fallback (synchronous
    writes — the ASYNC sharded path is
    :class:`apex_tpu.ckpt.manager.ZeroCheckpointManager`).
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 async_save: bool = True, save_interval_steps: int = 1):
        self._directory = directory
        self._max_to_keep = max_to_keep
        self._interval = max(int(save_interval_steps), 1)
        self._last_saved: Optional[int] = None
        if _HAS_ORBAX:
            self._mgr = ocp.CheckpointManager(
                directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep,
                    save_interval_steps=save_interval_steps,
                    enable_async_checkpointing=async_save,
                ),
            )
        else:
            self._mgr = None
            os.makedirs(directory, exist_ok=True)

    # -- npz-fallback internals ------------------------------------------------

    def _step_path(self, step: int) -> str:
        return os.path.join(self._directory, f"state_{step:08d}.npz")

    def _steps(self):
        out = []
        for p in glob.glob(os.path.join(self._directory, "state_*.npz")):
            name = os.path.basename(p)
            try:
                out.append(int(name[len("state_"):-len(".npz")]))
            except ValueError:
                continue
        return sorted(out)

    # -- the surface -----------------------------------------------------------

    def save(self, step: int, state: TrainState) -> bool:
        """Returns False when skipped by ``save_interval_steps``."""
        if self._mgr is not None:
            return self._mgr.save(step, args=ocp.args.StandardSave(state))
        if (self._last_saved is not None
                and step < self._last_saved + self._interval):
            return False
        save_tree_npz(self._step_path(step), state)
        self._last_saved = step
        for old in self._steps()[:-self._max_to_keep]:
            os.remove(self._step_path(old))
        return True

    def latest_step(self) -> Optional[int]:
        if self._mgr is not None:
            return self._mgr.latest_step()
        steps = self._steps()
        return steps[-1] if steps else None

    def restore(self, template: TrainState,
                step: Optional[int] = None) -> TrainState:
        if self._mgr is not None:
            step = self._mgr.latest_step() if step is None else step
            if step is None:
                raise FileNotFoundError("no checkpoint to restore")
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(template))
        step = self.latest_step() if step is None else step
        if step is None or not os.path.isfile(self._step_path(step)):
            raise FileNotFoundError(
                f"no checkpoint for step {step} under {self._directory!r}")
        return load_tree_npz(self._step_path(step), template)

    def wait_until_finished(self) -> None:
        if self._mgr is not None:
            self._mgr.wait_until_finished()

    def close(self) -> None:
        if self._mgr is not None:
            self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# --- auto-resume / preemption (pipeline_parallel/utils.py:142-144) ------------

class AutoResume:
    """Save-on-preemption protocol. The reference carries an ADLR auto-resume
    stub (``get_autoresume`` ``apex/transformer/pipeline_parallel/utils.py:142-144``
    and the commented termination check ``:286-300``) that defers to an
    external cluster library; on Cloud TPU the termination signal is a plain
    SIGTERM delivered ahead of preemption, so the guard is self-contained:
    install signal handlers, poll ``termination_requested()`` from the train
    loop, and ``check_and_save`` writes the TrainState before exit.

    Handlers chain to any previously-installed handler and are restored by
    ``uninstall()``.
    """

    def __init__(self, signals=None):
        import signal as _signal

        self._signal = _signal
        self._requested = False
        self._prev = {}
        for s in signals if signals is not None else (_signal.SIGTERM,):
            try:
                self._prev[s] = _signal.signal(s, self._handler)
            except ValueError:
                # signal.signal only works on the main thread; degrade to the
                # cooperative protocol (request_termination still works)
                pass

    def _handler(self, signum, frame):
        self._requested = True
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)

    def request_termination(self) -> None:
        """Mark termination as requested (tests / cooperative shutdown)."""
        self._requested = True

    def termination_requested(self) -> bool:
        return self._requested

    def check_and_save(self, path: str, state: TrainState) -> bool:
        """If termination was requested, checkpoint ``state`` to ``path`` and
        return True (caller should break its train loop). The analog of the
        reference's ``check_adlr_autoresume_termination``.

        On multi-host meshes the decision is agreed across processes first
        (a signal can land between two hosts' polls; an unagreed flag would
        have one host enter the collective orbax save while the others run
        ahead — the reason Megatron all-reduces its termination flag). All
        processes therefore return the same value and enter the save
        together."""
        if not self._agreed_termination():
            return False
        save_checkpoint(path, state)
        return True

    def check_and_save_sharded(self, manager, step: int, state, *, dp: int,
                               params: Optional[PyTree] = None,
                               scaler_state: Any = None) -> bool:
        """The sharded-format flavor: on (agreed) termination, push one
        SYNCHRONOUS save through a :class:`~apex_tpu.ckpt.manager.
        ZeroCheckpointManager` — the process is about to die, so the
        async writer's overlap buys nothing and the save must be durable
        (committed, manifest on disk) before returning True. If a
        committed checkpoint for ``step`` already exists (the scheduled
        save of this very step landed just before the signal), that IS
        the durable state — return True without re-saving instead of
        dying on the shutdown path."""
        if not self._agreed_termination():
            return False
        manager.wait_until_finished()
        if step not in manager.all_steps():
            manager.save(step, state, dp=dp, params=params,
                         scaler_state=scaler_state, force=True)
            manager.wait_until_finished()
        return True

    def _agreed_termination(self) -> bool:
        if jax.process_count() == 1:
            return self._requested
        import numpy as np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            jnp.asarray(self._requested, jnp.int32))
        agreed = bool(np.max(np.asarray(flags)))
        if agreed:
            self._requested = True  # adopt the peer's signal
        return agreed

    def uninstall(self) -> None:
        global _AUTORESUME
        for s, prev in self._prev.items():
            self._signal.signal(s, prev)
        self._prev.clear()
        if _AUTORESUME is self:
            # never leave the singleton pointing at a dead (handler-less)
            # guard — the next get_autoresume() installs a fresh one
            _AUTORESUME = None


_AUTORESUME: Optional[AutoResume] = None


def get_autoresume() -> AutoResume:
    """Process-wide ``AutoResume`` (reference spelling:
    ``pipeline_parallel/utils.py:142-144``), installed on first use."""
    global _AUTORESUME
    if _AUTORESUME is None:
        _AUTORESUME = AutoResume()
    return _AUTORESUME


# --- amp state-dict parity (frontend.py:361-400) ------------------------------

def amp_state_dict(scaler_states) -> dict:
    """``amp.state_dict()``: {'loss_scaler0': {...}, ...} per loss."""
    from apex_tpu.amp.scaler import state_dict as scaler_sd

    if not isinstance(scaler_states, (list, tuple)):
        scaler_states = [scaler_states]
    return {f"loss_scaler{i}": scaler_sd(s) for i, s in enumerate(scaler_states)}


def amp_load_state_dict(sd: dict, scaler_states):
    """``amp.load_state_dict()`` — loads each payload into the matching
    scaler state, returning the new states in order."""
    from apex_tpu.amp.scaler import load_state_dict as scaler_ld

    if not isinstance(scaler_states, (list, tuple)):
        scaler_states = [scaler_states]
    return [
        scaler_ld(s, sd[f"loss_scaler{i}"]) for i, s in enumerate(scaler_states)
    ]
