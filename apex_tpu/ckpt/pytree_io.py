"""Pure-numpy pytree <-> ``.npz`` persistence.

The orbax-free engine under :mod:`apex_tpu.ckpt`: a pytree of arrays
flattens to one ``.npz`` archive keyed ``leaf_00000`` ... in traversal
order (jax's deterministic ``tree.flatten`` order), dtype- and
shape-preserving, plus a tiny JSON side record of the leaf count.
Restore is template-shaped — the caller supplies a pytree with the
SAME structure (the repo's ``restore_checkpoint(path, template)``
convention) and gets its leaves replaced bitwise.

This is both the fallback for the legacy :class:`~apex_tpu.ckpt.state.
TrainState` round-trip when orbax is not importable (the seed's
``raise RuntimeError("orbax is unavailable")`` made every checkpoint
test environment-dependent) and the per-shard writer the sharded ZeRO
format (:mod:`apex_tpu.ckpt.sharded`) builds on — ``fp32`` buffers
round-trip exactly through npz, which is what makes same-dp resume
bitwise.
"""

from __future__ import annotations

import os
from typing import Any, Dict

import numpy as np

PyTree = Any

_KEY = "leaf_{:05d}"
_EXT = "ext_dtype_{:05d}"

_INT_OF_WIDTH = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def tree_to_arrays(tree: PyTree) -> Dict[str, np.ndarray]:
    """Flatten to ``{leaf_00000: ndarray, ...}`` in traversal order.

    Extension dtypes numpy cannot serialize (ml_dtypes: bfloat16,
    float8_*) ride as same-width unsigned-int views plus an
    ``ext_dtype_i`` marker naming the real dtype — bit-exact, which is
    what keeps the round-trip bitwise."""
    import jax

    out: Dict[str, np.ndarray] = {}
    for i, x in enumerate(jax.tree.leaves(tree)):
        arr = np.asarray(x)
        if not arr.flags.c_contiguous:
            # (ascontiguousarray unconditionally would promote 0-d
            # scalars to 1-d and break the shape round-trip)
            arr = np.ascontiguousarray(arr)
        if arr.dtype.kind == "V":  # ml_dtypes register as void-backed
            out[_KEY.format(i)] = arr.view(
                _INT_OF_WIDTH[arr.dtype.itemsize])
            out[_EXT.format(i)] = np.asarray(arr.dtype.name)
        else:
            out[_KEY.format(i)] = arr
    return out


def save_tree_npz(path: str, tree: PyTree) -> int:
    """Write the pytree's leaves to ``path`` (``.npz``); returns the
    byte size written. The write goes through a temp file + atomic
    ``os.replace`` so a crash mid-write never leaves a torn archive
    under the final name."""
    arrays = tree_to_arrays(tree)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "wb") as fh:
        np.savez(fh, **arrays)
    os.replace(tmp, path)
    return os.path.getsize(path)


def load_tree_npz(path: str, template: PyTree) -> PyTree:
    """Restore into ``template``'s structure: leaf count, shapes and
    dtypes must match, each mismatch named eagerly."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree.flatten(template)
    with np.load(path) as zf:
        data_keys = sorted(k for k in zf.files if k.startswith("leaf_"))
        want = [_KEY.format(i) for i in range(len(leaves))]
        if data_keys != want:
            raise ValueError(
                f"checkpoint {path} holds {len(data_keys)} leaves but "
                f"the template has {len(leaves)} — restore into the "
                f"same pytree structure it was saved from")
        out = []
        for i, leaf in enumerate(leaves):
            arr = zf[_KEY.format(i)]
            lshape = tuple(np.shape(leaf))
            ldtype = np.asarray(leaf).dtype
            ext = _EXT.format(i)
            if ext in zf.files:  # extension dtype rode as an int view
                saved_name = str(zf[ext])
                if ldtype.name != saved_name:
                    raise ValueError(
                        f"checkpoint {path} leaf {i}: saved dtype "
                        f"{saved_name} != template dtype {ldtype}")
                arr = arr.view(ldtype)
            if tuple(arr.shape) != lshape:
                raise ValueError(
                    f"checkpoint {path} leaf {i}: saved shape "
                    f"{tuple(arr.shape)} != template shape {lshape}")
            if arr.dtype != ldtype:
                raise ValueError(
                    f"checkpoint {path} leaf {i}: saved dtype "
                    f"{arr.dtype} != template dtype {ldtype}")
            out.append(jnp.asarray(arr))
    return jax.tree.unflatten(treedef, out)


def array_digest(arr: np.ndarray) -> str:
    """sha256 of an array's raw bytes (C-order) prefixed with shape/
    dtype — the manifest's per-buffer integrity witness."""
    import hashlib

    a = np.ascontiguousarray(arr)
    h = hashlib.sha256()
    h.update(str((a.dtype.str, a.shape)).encode())
    h.update(a.tobytes())
    return h.hexdigest()


def savez_atomic(path: str, arrays: Dict[str, np.ndarray]) -> int:
    """``np.savez`` streamed straight into a temp file + ``os.replace``
    (no intermediate BytesIO — a multi-GB shard must not double its
    peak host memory during an async save); returns the byte size."""
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "wb") as fh:
        np.savez(fh, **arrays)
    size = os.path.getsize(tmp)
    os.replace(tmp, path)
    return size
