"""Async sharded saves: snapshot between steps, write off the step path.

The save cost a train loop actually pays is split in two:

* **snapshot** — the device→host transfer of the sharded fp32 buffers
  (m/v + masters) plus the host flatten of master-less params. This is
  the only part on the step path; it runs BETWEEN steps (the caller
  invokes :meth:`AsyncZeroSaver.save` after an optimizer step returns)
  and is measured per save (``snapshot_ms``).
* **write + commit** — npz shard files, manifest, atomic rename. A
  background thread does all of it against the host snapshot, so the
  next train steps overlap the disk I/O (``write_ms``, measured on the
  thread).

Crash safety is the :mod:`apex_tpu.ckpt.sharded` commit protocol: the
whole checkpoint lands in a ``.tmp-*`` sibling and one ``os.rename``
publishes it. A process killed mid-write (or the injected
:class:`~apex_tpu.ckpt.sharded.SimulatedCrash` test fault) leaves the
temp litter and NO new checkpoint — the previous committed one stays
restorable, which ``tests/test_ckpt.py`` witnesses by injecting the
fault at every stage.

One save is in flight at a time: a second :meth:`save` first waits for
the previous write to land (the snapshot already decoupled the device
state, so "waits" means disk, not training)."""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from apex_tpu.ckpt import sharded as _sharded
from apex_tpu.ckpt.sharded import SimulatedCrash
from apex_tpu.monitor import registry as _reg
from apex_tpu.monitor import trace as _trace

PyTree = Any


class _HostSnapshot:
    """A ZeroState frozen on the host: what the writer thread consumes."""

    __slots__ = ("buffers", "count", "layout")

    def __init__(self, state):
        self.buffers, self.count, _ = _sharded.snapshot_zero_state(state)
        self.layout = state.layout

    # duck-types ZeroState for save_zero_sharded


class AsyncZeroSaver:
    """Drives :func:`~apex_tpu.ckpt.sharded.save_zero_sharded` off the
    step path. ``fault`` is the crash-injection hook threaded through to
    the writer (tests only)."""

    def __init__(self, *, fault=None):
        self._fault = fault
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self.crashed = False          # a SimulatedCrash consumed the save
        self.last_timings: Dict[str, float] = {}
        # the most recent save's trace id: joins the snapshot (step
        # path) and commit (writer thread) records of ONE save in a
        # merged timeline — and what a ckpt bench record stamps
        self.last_trace_id: Optional[str] = None

    def save(self, directory: str, state, *, dp: int,
             params: Optional[PyTree] = None, scaler_state: Any = None,
             step: int = 0, on_commit=None) -> Dict[str, float]:
        """Snapshot ``state`` now (blocking, between steps), write in the
        background. Returns ``{"snapshot_ms": ...}`` immediately; the
        thread fills ``write_ms`` into :attr:`last_timings` when the
        commit lands. ``on_commit(step)`` runs on the writer thread
        after a successful rename (the manager hangs rotation off it)."""
        self.wait()
        t0 = time.perf_counter()
        snap = _HostSnapshot(state)
        if params is not None:
            import jax

            # host-copy the leaves NOW (the device params keep training)
            # as an int-keyed dict: jax.tree.leaves of it reproduces the
            # original traversal order, which is all flatten_to_chunks
            # needs once the layout is supplied
            params = {i: np.asarray(x)
                      for i, x in enumerate(jax.tree.leaves(params))}
        if scaler_state is not None and not isinstance(scaler_state, dict):
            from apex_tpu.amp.scaler import state_dict as scaler_sd
            scaler_state = scaler_sd(scaler_state)
        snapshot_ms = (time.perf_counter() - t0) * 1e3
        timings = {"snapshot_ms": round(snapshot_ms, 3)}
        self.last_timings = timings
        # one trace id per SAVE (reusing an ambient train-step context
        # when one is active): the step-path snapshot record and the
        # writer thread's commit record carry it explicitly, so the two
        # halves of an async save join across threads in a timeline
        tid = _trace.current_trace_id() or _trace.new_trace_id("ckpt")
        self.last_trace_id = tid
        _reg.emit_event("ckpt_save_start", trace_id=tid, step=int(step),
                        snapshot_ms=timings["snapshot_ms"])

        def _write():
            t1 = time.perf_counter()
            try:
                _sharded.save_zero_sharded(
                    directory, snap, dp=dp, params=params,
                    scaler_state=scaler_state, step=step,
                    fault=self._fault)
                timings["write_ms"] = round(
                    (time.perf_counter() - t1) * 1e3, 3)
                # explicit trace_id: the writer thread must not inherit
                # whatever ambient context the TRAIN thread is in now
                _reg.emit_event("ckpt_commit", trace_id=tid,
                                step=int(step),
                                write_ms=timings["write_ms"])
                if on_commit is not None:
                    on_commit(step)
            except SimulatedCrash:
                # the injected SIGKILL: stop where we stand, clean
                # nothing, commit nothing — exactly a killed process
                self.crashed = True
            except BaseException as e:  # surfaced on the next wait()
                self._error = e

        self._thread = threading.Thread(target=_write, daemon=True,
                                        name="apex-tpu-ckpt-writer")
        self._thread.start()
        return timings

    def wait(self) -> None:
        """Block until the in-flight write (if any) lands; re-raise any
        writer error on the caller's thread."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.wait()


def cleanup_stale_tmp(directory: str) -> int:
    """Remove ``*.tmp-<pid>`` litter a KILLED writer left under
    ``directory``; returns how many were removed. Two classes of tmp
    dir are spared: one whose embedded pid names a live FOREIGN process
    (a resuming job sharing the root with a still-draining fleet must
    not rmtree a save out from under its writer thread), and one this
    very process is actively writing (``sharded._ACTIVE_TMP`` — a
    second manager constructed over the same root mid-save). A dead
    pid's litter, and our own writes that ENDED without committing
    (crash-injected saves), can never commit — the rename only ever
    runs in the thread that wrote the tmp — so sweeping them is safe."""
    removed = 0
    if not os.path.isdir(directory):
        return 0
    for name in os.listdir(directory):
        if ".tmp-" not in name:
            continue
        path = os.path.join(directory, name)
        pid_part = name.rsplit(".tmp-", 1)[1]
        try:
            pid = int(pid_part)
        except ValueError:
            pid = None
        if pid is not None and pid != os.getpid() and _pid_alive(pid):
            continue  # another live process may still be writing it
        if os.path.abspath(path) in _sharded._ACTIVE_TMP:
            continue  # OUR live writer thread is mid-save here
        shutil.rmtree(path, ignore_errors=True)
        if not os.path.exists(path):
            removed += 1
    return removed


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    return True
