"""ZeroCheckpointManager: rotation + auto-resume over the sharded format.

The policy layer tying :mod:`apex_tpu.ckpt.sharded` (format),
:mod:`apex_tpu.ckpt.async_save` (off-step writes) and
:class:`~apex_tpu.ckpt.state.AutoResume` (preemption) together:

* step directories ``step_00000042/`` under one root, discovered by
  committed manifest (an interrupted save's ``.tmp-*`` litter is never
  a checkpoint and is swept on manager construction);
* ``max_to_keep`` rotation runs AFTER a commit lands (on the writer
  thread for async saves) — the newest checkpoint is durable before an
  old one is deleted, so there is no instant with fewer restorable
  checkpoints than before the save;
* ``save_interval_steps`` thins saves the same way the orbax-backed
  legacy manager does; ``force=True`` (the preemption path) bypasses it;
* ``restore`` is dp-elastic: ``restore(params_template, dp=dp_new)``
  re-slices the chunk rows regardless of the width the checkpoint was
  written at (same-dp restores are bitwise).
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Any, List, Optional

from apex_tpu.ckpt import sharded as _sharded
from apex_tpu.ckpt.async_save import AsyncZeroSaver, cleanup_stale_tmp
from apex_tpu.ckpt.manifest import MANIFEST_NAME

PyTree = Any

_STEP_RE = re.compile(r"^step_(\d{8})$")


def _step_dir(root: str, step: int) -> str:
    if step < 0:
        raise ValueError(f"step must be >= 0, got {step}")
    return os.path.join(root, f"step_{step:08d}")


class ZeroCheckpointManager:
    """``with ZeroCheckpointManager(root, max_to_keep=3) as mgr: ...``

    ``mgr.save(step, zstate, dp=dp, params=..., scaler_state=...)``
    between train steps; ``mgr.restore(params, dp=dp_new)`` on resume
    (at ANY dp_new — the elastic re-slice). ``async_save=False`` makes
    every save synchronous (the preemption/exit path wants that).
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 async_save: bool = True, save_interval_steps: int = 1,
                 fault=None):
        if max_to_keep < 1:
            raise ValueError(f"max_to_keep must be >= 1, got {max_to_keep}")
        self.directory = directory
        self.max_to_keep = int(max_to_keep)
        self.async_save = bool(async_save)
        self.save_interval_steps = max(int(save_interval_steps), 1)
        self._last_saved: Optional[int] = None
        self._saver = AsyncZeroSaver(fault=fault)
        os.makedirs(directory, exist_ok=True)
        cleanup_stale_tmp(directory)  # a killed writer's litter

    # -- discovery -------------------------------------------------------------

    def all_steps(self) -> List[int]:
        """Committed steps (manifest present), ascending."""
        out = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m and os.path.isfile(os.path.join(self.directory, name,
                                                 MANIFEST_NAME)):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def step_path(self, step: int) -> str:
        return _step_dir(self.directory, step)

    # -- save ------------------------------------------------------------------

    def save(self, step: int, state, *, dp: int,
             params: Optional[PyTree] = None, scaler_state: Any = None,
             force: bool = False) -> bool:
        """Returns False when thinned out by ``save_interval_steps``
        (``force=True`` bypasses — the preemption save must land)."""
        if (not force and self._last_saved is not None
                and step < self._last_saved + self.save_interval_steps):
            return False
        target = _step_dir(self.directory, step)
        if os.path.exists(target):
            raise FileExistsError(
                f"checkpoint for step {step} already exists at "
                f"{target!r}")
        self._saver.save(target, state, dp=dp, params=params,
                         scaler_state=scaler_state, step=step,
                         on_commit=self._rotate)
        if not self.async_save:
            self._saver.wait()
        self._last_saved = step
        return True

    def _rotate(self, _committed_step: int) -> None:
        # rotation is post-commit (writer thread): the new checkpoint is
        # already durable, so deleting the oldest can never shrink the
        # set of restorable checkpoints below where it started
        for old in self.all_steps()[:-self.max_to_keep]:
            shutil.rmtree(_step_dir(self.directory, old),
                          ignore_errors=True)

    @property
    def last_timings(self):
        """The most recent save's measured ``snapshot_ms``/``write_ms``
        (the ``ckpt`` bench record's raw material)."""
        return self._saver.last_timings

    @property
    def last_trace_id(self):
        """The most recent save's trace id (joins its snapshot and
        commit records; the ``ckpt`` bench record stamps it)."""
        return self._saver.last_trace_id

    # -- restore ---------------------------------------------------------------

    def restore(self, params_template: PyTree, *, dp: int,
                step: Optional[int] = None, verify: bool = True):
        """``(ZeroState, RestoredZero)`` at width ``dp`` from ``step``
        (default: latest committed)."""
        self.wait_until_finished()
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f"no committed checkpoint under {self.directory!r}")
        return _sharded.load_zero_state(
            _step_dir(self.directory, step), params_template, dp=dp,
            verify=verify)

    def restore_params(self, like: PyTree, step: Optional[int] = None, *,
                       verify: bool = True) -> PyTree:
        """The param tree alone (serving hot-swap loader)."""
        self.wait_until_finished()
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f"no committed checkpoint under {self.directory!r}")
        return _sharded.restore_params(
            _step_dir(self.directory, step), like, verify=verify)

    # -- lifecycle -------------------------------------------------------------

    def wait_until_finished(self) -> None:
        self._saver.wait()

    @property
    def crashed(self) -> bool:
        return self._saver.crashed

    def close(self) -> None:
        self.wait_until_finished()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
