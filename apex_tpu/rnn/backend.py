"""RNN driver: scan over time, layer stacking, bidirection.

Re-design of ``apex/RNN/RNNBackend.py:25`` (``stackedRNN``/``bidirectionalRNN``):
the time loop is ``lax.scan`` (compiled once, no per-step dispatch), layers
stack by function composition, bidirection concatenates a reversed scan.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

PyTree = Any


@dataclasses.dataclass
class RNN:
    """Multi-layer unidirectional RNN over (batch, time, features)."""

    cell: Any
    num_layers: int = 1
    dropout: float = 0.0

    def init(self, key, dtype=jnp.float32) -> list:
        return [
            self._layer_cell(i).init(jax.random.fold_in(key, i), dtype)
            for i in range(self.num_layers)
        ]

    def _layer_cell(self, i):
        if i == 0:
            return self.cell
        return dataclasses.replace(self.cell, input_size=self.cell.hidden_size)

    def __call__(self, params: list, x: jax.Array,
                 initial_states: Optional[list] = None,
                 key: Optional[jax.Array] = None):
        """Returns (outputs (B, T, H), final_states list).

        Under an ambient O1 policy, inputs and weights cast to the 'rnn'
        rule's dtype on entry — the reference's RNN-specific cast machinery
        (``apex/amp/wrap.py:157-265`` ``rnn_cast``/``new_rnn_cast``,
        ``rnn_compat.py``) collapsed to one pytree cast; states follow via
        ``x.dtype``."""
        from apex_tpu.amp.lists import apply_op_rules

        (x,) = apply_op_rules("rnn", x)
        params = jax.tree.map(lambda a: apply_op_rules("rnn", a)[0], params)
        if initial_states is not None:
            # user-supplied states must join the cast too, or the fp32
            # carry would promote every gate sum back to fp32
            initial_states = jax.tree.map(
                lambda a: apply_op_rules("rnn", a)[0], initial_states)
        b = x.shape[0]
        finals = []
        h = x
        for i, p in enumerate(params):
            cell = self._layer_cell(i)
            state0 = (initial_states[i] if initial_states is not None
                      else cell.initial_state(b, x.dtype))

            def step(state, xt, p=p, cell=cell):
                state, y = cell.step(p, state, xt)
                return state, y

            final, ys = jax.lax.scan(step, state0, h.transpose(1, 0, 2))
            h = ys.transpose(1, 0, 2)
            if self.dropout > 0 and key is not None and i < len(params) - 1:
                keep = jax.random.bernoulli(
                    jax.random.fold_in(key, i), 1.0 - self.dropout, h.shape)
                h = jnp.where(keep, h / (1.0 - self.dropout), 0.0).astype(h.dtype)
            finals.append(final)
        return h, finals


def stacked_rnn(cell, num_layers: int, dropout: float = 0.0) -> RNN:
    """``stackedRNN`` factory (``RNNBackend.py``)."""
    return RNN(cell, num_layers=num_layers, dropout=dropout)


def bidirectional(rnn: RNN):
    """``bidirectionalRNN`` (``RNNBackend.py``): run forward and
    time-reversed stacks, concat features."""

    def init(key, dtype=jnp.float32):
        return {"fwd": rnn.init(jax.random.fold_in(key, 0), dtype),
                "bwd": rnn.init(jax.random.fold_in(key, 1), dtype)}

    def apply(params, x, **kw):
        yf, sf = rnn(params["fwd"], x, **kw)
        yb, sb = rnn(params["bwd"], x[:, ::-1], **kw)
        return jnp.concatenate([yf, yb[:, ::-1]], axis=-1), (sf, sb)

    return init, apply
