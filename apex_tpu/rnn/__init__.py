"""RNN cells and stacks.

Re-design of ``apex.RNN`` (``apex/RNN/__init__.py:1``, ``RNNBackend.py:25``,
``cells.py`` — deprecated in the reference but part of its surface): LSTM,
GRU, ReLU/Tanh RNN, and mLSTM, as ``lax.scan``-driven functional cells. The
reference's "fused" forgetgate-style cells map to XLA's elementwise fusion
inside the scan body.
"""

from apex_tpu.rnn.cells import (  # noqa: F401
    GRUCell,
    LSTMCell,
    RNNReLUCell,
    RNNTanhCell,
    mLSTMCell,
)
from apex_tpu.rnn.backend import RNN, bidirectional, stacked_rnn  # noqa: F401


def LSTM(input_size, hidden_size, num_layers=1, **kw):
    """``apex.RNN.LSTM`` factory (``apex/RNN/models.py``)."""
    return RNN(LSTMCell(input_size, hidden_size), num_layers=num_layers, **kw)


def GRU(input_size, hidden_size, num_layers=1, **kw):
    return RNN(GRUCell(input_size, hidden_size), num_layers=num_layers, **kw)


def ReLU(input_size, hidden_size, num_layers=1, **kw):
    return RNN(RNNReLUCell(input_size, hidden_size), num_layers=num_layers, **kw)


def Tanh(input_size, hidden_size, num_layers=1, **kw):
    return RNN(RNNTanhCell(input_size, hidden_size), num_layers=num_layers, **kw)


def mLSTM(input_size, hidden_size, num_layers=1, **kw):
    return RNN(mLSTMCell(input_size, hidden_size), num_layers=num_layers, **kw)
