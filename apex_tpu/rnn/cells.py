"""RNN cells — re-design of ``apex/RNN/cells.py``.

Each cell is (init, step): ``init(key) -> params``; ``step(params, h, x) ->
(h', y)``. Gate matmuls are fused into one GEMM per input/hidden (the
reference's ``fusedBackend``-style packing); XLA fuses the elementwise gate
math into the GEMM consumers inside the scan body.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


def _uniform(key, shape, dtype, bound):
    return jax.random.uniform(key, shape, dtype, -bound, bound)


@dataclasses.dataclass
class _Cell:
    input_size: int
    hidden_size: int
    n_gates: int = 1

    def init(self, key, dtype=jnp.float32) -> dict:
        b = 1.0 / self.hidden_size ** 0.5
        k1, k2, k3, k4 = jax.random.split(key, 4)
        g = self.n_gates * self.hidden_size
        return {
            "w_ih": _uniform(k1, (g, self.input_size), dtype, b),
            "w_hh": _uniform(k2, (g, self.hidden_size), dtype, b),
            "b_ih": _uniform(k3, (g,), dtype, b),
            "b_hh": _uniform(k4, (g,), dtype, b),
        }

    def initial_state(self, batch, dtype=jnp.float32):
        return jnp.zeros((batch, self.hidden_size), dtype)


@dataclasses.dataclass
class RNNTanhCell(_Cell):
    def step(self, p, h, x):
        h = jnp.tanh(x @ p["w_ih"].T + p["b_ih"] + h @ p["w_hh"].T + p["b_hh"])
        return h, h


@dataclasses.dataclass
class RNNReLUCell(_Cell):
    def step(self, p, h, x):
        h = jnp.maximum(x @ p["w_ih"].T + p["b_ih"] + h @ p["w_hh"].T + p["b_hh"], 0)
        return h, h


@dataclasses.dataclass
class LSTMCell(_Cell):
    n_gates: int = 4

    def initial_state(self, batch, dtype=jnp.float32):
        return (jnp.zeros((batch, self.hidden_size), dtype),
                jnp.zeros((batch, self.hidden_size), dtype))

    def step(self, p, state, x):
        h, c = state
        gates = x @ p["w_ih"].T + p["b_ih"] + h @ p["w_hh"].T + p["b_hh"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c), h


@dataclasses.dataclass
class GRUCell(_Cell):
    n_gates: int = 3

    def step(self, p, h, x):
        gi = x @ p["w_ih"].T + p["b_ih"]
        gh = h @ p["w_hh"].T + p["b_hh"]
        ir, iz, in_ = jnp.split(gi, 3, axis=-1)
        hr, hz, hn = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(ir + hr)
        z = jax.nn.sigmoid(iz + hz)
        n = jnp.tanh(in_ + r * hn)
        h = (1 - z) * n + z * h
        return h, h


@dataclasses.dataclass
class mLSTMCell(_Cell):
    """Multiplicative LSTM (``apex/RNN/cells.py`` mLSTMRNNCell): hidden
    state is modulated by m = (W_mx x) * (W_mh h) before the gates."""

    n_gates: int = 4

    def init(self, key, dtype=jnp.float32) -> dict:
        b = 1.0 / self.hidden_size ** 0.5
        params = super().init(key, dtype)
        k1, k2 = jax.random.split(jax.random.fold_in(key, 99))
        params["w_mx"] = _uniform(k1, (self.hidden_size, self.input_size), dtype, b)
        params["w_mh"] = _uniform(k2, (self.hidden_size, self.hidden_size), dtype, b)
        return params

    def initial_state(self, batch, dtype=jnp.float32):
        return (jnp.zeros((batch, self.hidden_size), dtype),
                jnp.zeros((batch, self.hidden_size), dtype))

    def step(self, p, state, x):
        h, c = state
        m = (x @ p["w_mx"].T) * (h @ p["w_mh"].T)
        gates = x @ p["w_ih"].T + p["b_ih"] + m @ p["w_hh"].T + p["b_hh"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c), h
