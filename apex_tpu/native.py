"""ctypes bindings for the native runtime components in ``csrc/``.

The reference binds its C++/CUDA through pybind11 extension modules
(``setup.py``); pybind11 is not available here, so the native tier uses a
plain C ABI + ctypes (zero build-time Python deps). The library builds with
``make -C csrc`` (g++ only); every caller has a pure-Python fallback, so the
framework is fully functional without the build — the native path removes
host-side Python overhead for very large models/traces.

Components:
* ``plan_layout`` — chunk-layout metadata (apex_C / multi_tensor_apply host
  loop analog) — pure numpy: a vectorized repeat/cumsum, so a C version
  had nothing to add (r2 review agreed; the former ``layout_planner.cpp``
  duplicating it is deleted);
* ``aggregate_trace`` — profiler record aggregation (pyprof.prof analog,
  ``csrc/trace_analyzer.cpp``);
* ``parse_trace`` — gunzip + parse of ``trace.json.gz`` profiler dumps
  (pyprof.parse / sqlite analog, ``csrc/trace_parser.cpp``) — the IO stage
  that dominates post-processing of real multi-MB traces.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
from typing import Dict, Optional, Tuple

import numpy as np

_LIB_NAME = "libapex_tpu_native.so"
_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "csrc")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    path = os.path.join(_CSRC, _LIB_NAME)
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.aggregate_trace_json.restype = ctypes.c_int64
        lib.aggregate_trace_json.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
        ]
        lib.parse_trace_gz.restype = ctypes.c_int64
        lib.parse_trace_gz.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
        ]
        lib.free_buffer.restype = None
        lib.free_buffer.argtypes = [ctypes.POINTER(ctypes.c_char)]
        _lib = lib
    except (OSError, AttributeError):
        # AttributeError: a stale .so from an older build missing newer
        # symbols — treat as not built so callers use the Python fallback
        _lib = None
    return _lib


def build(verbose: bool = False) -> bool:
    """Compile the native library (``make -C csrc``). Returns success."""
    global _tried
    try:
        r = subprocess.run(
            ["make", "-C", _CSRC], capture_output=not verbose, check=False
        )
        _tried = False  # force re-probe
        return r.returncode == 0 and _load() is not None
    except OSError:
        return False


def available() -> bool:
    return _load() is not None


def plan_layout(sizes, chunk_size: int) -> Tuple[np.ndarray, np.ndarray]:
    """(chunk_to_tensor i32[n_chunks], tensor_offsets i64[n_tensors]).
    Vectorized numpy — already optimal host-side (no per-tensor Python
    loop), which is why this component has no native counterpart."""
    sizes = np.asarray(sizes, np.int64)
    chunk_counts = np.maximum(1, -(-sizes // chunk_size))
    c2t = np.repeat(np.arange(len(sizes), dtype=np.int32), chunk_counts)
    offsets = np.concatenate([[0], np.cumsum(chunk_counts)[:-1]]) * chunk_size
    return c2t, offsets.astype(np.int64)


def parse_trace(path: str) -> list:
    """Parse a ``*.trace.json.gz`` profiler dump natively; returns the
    resolved event list ([{"name","ts","dur","device","track","args"}]).
    Raises if the native library is absent (callers check
    :func:`available`) or the file is unreadable/malformed."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library not built; run apex_tpu.native.build()")
    buf = ctypes.POINTER(ctypes.c_char)()
    n = lib.parse_trace_gz(path.encode(), ctypes.byref(buf))
    if n < 0:
        raise ValueError(f"native trace parse failed for {path!r}")
    try:
        return json.loads(ctypes.string_at(buf, n).decode())
    finally:
        lib.free_buffer(buf)


def aggregate_trace(records_json: str) -> Dict[str, dict]:
    """Aggregate op records (see ``analyzer.analyze_ops``); raises if the
    native library is absent (callers check :func:`available`)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library not built; run apex_tpu.native.build()")
    cap = max(1 << 16, len(records_json))
    out = ctypes.create_string_buffer(cap)
    n = lib.aggregate_trace_json(records_json.encode(), out, cap)
    if n < 0:
        raise ValueError("native trace aggregation failed")
    return json.loads(out.value.decode())
