"""Drafter framework for speculative decoding: propose a STATIC ``k``
tokens per round, cheaply, per stream.

A drafter's contract is deliberately host-facing and tiny — the device
side of speculation (batched verification, the fused accept/reject
tail, cache rewind) lives entirely in the engines; a drafter only has
to GUESS. Wrong guesses cost one wasted verify row, never correctness:
the fused verifier (:func:`apex_tpu.ops.fused_verify`) accepts exactly
the prefix the target model would have produced, so the emitted stream
is token-identical to non-speculative decoding regardless of drafter
quality. What the drafter controls is the ACCEPTANCE RATE, i.e. how
many of the k drafted tokens survive per round — the amortization
factor on the target's weight/KV streaming.

Two implementations:

* :class:`NGramDrafter` — host-side n-gram lookahead: an order-``n``
  suffix table built incrementally from each stream's own context
  (prompt + generated tokens) predicts the continuation; misses repeat
  the last token. Zero device memory, zero extra compiled programs —
  the cheapest possible drafter, strong on self-similar text (code,
  chat templates, the repetitive tails greedy LMs produce).
* :class:`ModelDrafter` — a small :class:`~apex_tpu.models.gpt.
  GPTConfig` model with its own KV cache per stream, driven through
  ONE jitted single-token step (the target engine's own decode-step
  program shape: batch-1, stable avals, compiled exactly once across
  every stream, round, and churn event). Context rows are teacher-
  forced through the same step — no per-prompt-length prefill program
  exists, so the zero-recompile discipline holds by construction.

Streams: engines key drafter state by request id. State survives
preemption for free — an evicted-and-recomputed request's context
re-grows token-identically, so the incremental ``consumed`` frontier
stays valid; a context that SHRANK (a genuinely new stream reusing an
id) resets the stream. :meth:`Drafter.release` frees a finished
stream's state (the drafter's memory is bounded by concurrent streams,
never by request history).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from apex_tpu.monitor import spans as monitor_spans

__all__ = ["Drafter", "NGramDrafter", "ModelDrafter", "validate_drafter"]

#: sane bound on the per-round draft length: past ~32 the verify step's
#: k+1-row cost dominates any plausible acceptance run
MAX_DRAFT_K = 32


class Drafter:
    """The drafter protocol: ``propose(stream, context)`` returns
    exactly ``self.k`` int32 token ids continuing ``context`` (the
    stream's full prompt + generated tokens so far). ``k`` is STATIC
    for the drafter's lifetime — it shapes the engines' compiled verify
    programs. ``vocab_size`` is the id space the proposals live in
    (``None`` = inherits the target's, e.g. the n-gram drafter which
    only ever replays context tokens)."""

    k: int = 0
    vocab_size: Optional[int] = None
    #: paged-pool granularity the drafter's cache rides, when it has
    #: one; None = the drafter imposes no block constraint
    block_size: Optional[int] = None

    def propose(self, stream: int,
                context: Sequence[int]) -> np.ndarray:  # pragma: no cover
        raise NotImplementedError

    def release(self, stream: int) -> None:
        """Drop per-stream state (request finished); default no-op."""

    def reset(self) -> None:
        """Drop ALL stream state (a fresh serve run reusing ids)."""


def validate_drafter(draft: Drafter, config, *, needed_rows: int,
                     cache_rows: Optional[int] = None,
                     block_size: Optional[int] = None) -> int:
    """Eager construction-time validation of a drafter against a target
    engine — every mismatch raises a knob-naming ``ValueError`` here,
    never a deep XLA shape error three layers down. Returns ``draft.k``.

    ``needed_rows`` is the worst-case cache rows a spec round can touch
    (prompt + new tokens + k); ``cache_rows`` the drafter's own cache
    capacity when it has one; ``block_size`` the target engine's paged
    granularity (checked against a paged drafter's).
    """
    k = getattr(draft, "k", None)
    if not isinstance(k, int) or not 1 <= k <= MAX_DRAFT_K:
        raise ValueError(
            f"draft.k must be an int in [1, {MAX_DRAFT_K}] (it shapes "
            f"the compiled verify program); got {k!r}")
    dv = getattr(draft, "vocab_size", None)
    if dv is not None and dv != config.vocab_size:
        raise ValueError(
            f"drafter vocab_size ({dv}) != target vocab_size "
            f"({config.vocab_size}) — drafted ids would index a "
            f"different token space; use a drafter model sharing the "
            f"target's tokenizer/vocab")
    db = getattr(draft, "block_size", None)
    if block_size is not None and db is not None and db != block_size:
        raise ValueError(
            f"drafter block_size ({db}) != engine block_size "
            f"({block_size}) — the drafter's paged cache cannot ride "
            f"the engine's block tables; construct the drafter with "
            f"block_size={block_size} (or leave it None)")
    rows = getattr(draft, "cache_rows", None) \
        if cache_rows is None else cache_rows
    if rows is not None and rows < needed_rows:
        raise ValueError(
            f"drafter cache holds {rows} rows but a spec round can "
            f"touch {needed_rows} (prompt + max_new_tokens + k) — "
            f"raise the drafter's max_seq_len to >= {needed_rows}")
    depth = getattr(draft, "depth", None)
    branching = getattr(draft, "branching", None)
    if depth is not None and branching is not None:
        depth, branching = int(depth), int(branching)
        nodes = depth * branching
        if nodes > MAX_DRAFT_K:
            raise ValueError(
                f"draft tree ({branching} branches x depth {depth} = "
                f"{nodes} nodes) exceeds MAX_DRAFT_K={MAX_DRAFT_K} "
                f"verify rows — shrink branching or depth so "
                f"branching x depth <= {MAX_DRAFT_K}")
        if depth + 1 > needed_rows:
            raise ValueError(
                f"draft tree depth ({depth}) + 1 bonus row exceeds the "
                f"per-slot row cap ({needed_rows}) — even an empty slot "
                f"cannot hold one tree round's writes; shrink the "
                f"drafter's depth to <= {needed_rows - 1} or raise the "
                f"engine's max_seq_len (rows round up to whole "
                f"block_size blocks, so the cap is "
                f"ceil(max_seq_len / block_size) x block_size)")
        if not isinstance(getattr(draft, "chain_k", k), int) \
                or not 1 <= getattr(draft, "chain_k", k) <= depth:
            raise ValueError(
                f"tree drafter chain_k must be an int in [1, depth="
                f"{depth}] (the chain-fallback rung cannot draft deeper "
                f"than the tree); got {getattr(draft, 'chain_k', k)!r}")
    return k


class NGramDrafter(Drafter):
    """Host-side n-gram/lookahead drafter: no device memory, no extra
    compiled programs.

    Per stream, an order-``n`` suffix table maps each length-``n``
    window of the context to the token that followed it (latest
    occurrence wins — recency beats frequency on the self-similar text
    speculation pays off on). :meth:`propose` walks the table ``k``
    steps from the context's tail, falling back to repeating the last
    token on a miss (the cheapest guess that is often right for
    degenerate/greedy tails). The table updates INCREMENTALLY from the
    stream's ``consumed`` frontier, so a propose costs O(new tokens +
    k) dict work.
    """

    def __init__(self, k: int = 4, n: int = 3):
        if not 1 <= int(k) <= MAX_DRAFT_K:
            raise ValueError(
                f"NGramDrafter k must be in [1, {MAX_DRAFT_K}], got {k}")
        if int(n) < 1:
            raise ValueError(f"NGramDrafter n must be >= 1, got {n}")
        self.k = int(k)
        self.n = int(n)
        # stream -> (suffix table, consumed context length)
        self._streams: Dict[int, Any] = {}

    def propose(self, stream: int, context: Sequence[int]) -> np.ndarray:
        n = self.n
        table, consumed = self._streams.get(stream, (None, 0))
        if table is None or consumed > len(context):
            table, consumed = {}, 0  # fresh (or shrunk: a reused id)
        ctx = [int(t) for t in context]
        for i in range(max(consumed, n), len(ctx)):
            table[tuple(ctx[i - n:i])] = ctx[i]
        self._streams[stream] = (table, len(ctx))
        window: List[int] = ctx[-n:] if len(ctx) >= n else ctx[:]
        out = []
        for _ in range(self.k):
            guess = table.get(tuple(window[-n:]), window[-1])
            out.append(guess)
            window.append(guess)
        return np.asarray(out, np.int32)

    def release(self, stream: int) -> None:
        self._streams.pop(stream, None)

    def reset(self) -> None:
        self._streams.clear()


class ModelDrafter(Drafter):
    """A small-``GPTConfig`` model drafter: greedy continuations from a
    cheap model, one KV cache per stream.

    The drafter rides ONE jitted single-token decode step (the
    :class:`~apex_tpu.inference.engine.DecodeEngine` program at
    batch 1): context tokens are teacher-forced through it row by row
    and the k proposals greedy-decoded from the frontier — stable avals
    throughout, so the step compiles exactly once no matter how many
    streams, rounds, or churn events it serves (witnessed by
    ``decode_step._cache_size() == 1`` in the spec tests). Drafted
    rows land in the cache past the trusted frontier and are simply
    re-written when the real stream catches up — the contiguous-cache
    analog of the serving engine's block-table rewind (length masking
    IS the rewind).

    ``max_seq_len`` sizes every stream's cache (128-multiple, the
    decode kernel's tiling rule) and must cover the target's worst
    case plus ``k`` draft rows; the engines validate that eagerly via
    :func:`validate_drafter`. Vocab must equal the target's — checked
    at wiring time, never discovered as an XLA gather error.
    """

    def __init__(self, model, params, *, k: int = 4,
                 max_seq_len: Optional[int] = None,
                 block_size: Optional[int] = None):
        from apex_tpu.inference.engine import DecodeEngine

        if not 1 <= int(k) <= MAX_DRAFT_K:
            raise ValueError(
                f"ModelDrafter k must be in [1, {MAX_DRAFT_K}], got {k}")
        self.k = int(k)
        self.model = model
        self.params = params
        self.vocab_size = int(model.config.vocab_size)
        self.block_size = None if block_size is None else int(block_size)
        if max_seq_len is None:
            # default the cache to the model's position table rounded UP
            # to the decode kernel's 128-row tiling grid (the slack holds
            # no positions; generation stays capped by the table)
            max_seq_len = ((model.config.max_seq_len + 127) // 128) * 128
        # greedy proposals: the point-mass drafts the exact-acceptance
        # math in ops.fused_verify assumes
        self.engine = DecodeEngine(model, max_seq_len=max_seq_len,
                                   temperature=0.0)
        self.cache_rows = self.engine.max_s
        # stream -> {"cache": donated-cache tree, "consumed": rows
        # trusted as real context}
        self._streams: Dict[int, Dict[str, Any]] = {}
        self._key = None  # lazily built greedy dummy key (fixed avals)

    def _step(self, cache, tok: int, pos: int):
        import jax
        import jax.numpy as jnp

        if self._key is None:
            self._key = jax.random.PRNGKey(0)  # apexlint: disable=APX502
        return self.engine.decode_step(
            self.params, cache, jnp.asarray([tok], jnp.int32),
            jnp.int32(pos), self._key)

    def propose(self, stream: int, context: Sequence[int]) -> np.ndarray:
        st = self._streams.get(stream)
        if st is None or st["consumed"] > len(context):
            st = {"cache": self.engine.init_cache(1), "consumed": 0}
        cache, consumed = st["cache"], st["consumed"]
        ctx = [int(t) for t in context]
        if len(ctx) - 1 + self.k > self.cache_rows:
            raise ValueError(
                f"ModelDrafter cache ({self.cache_rows} rows) cannot "
                f"hold context ({len(ctx)}) + k ({self.k}) draft rows — "
                f"raise max_seq_len (the engines validate this bound at "
                f"wiring time; hitting it here means the drafter was "
                f"driven directly past it)")
        # one spec_draft span per round: its trace slice (and the
        # decode_step device scopes nested under it) joins the round's
        # spec lifecycle record through the ambient serve trace id —
        # no-op while monitoring is off
        with monitor_spans.span("spec_draft", stream=int(stream)):
            # teacher-force the unconsumed context rows (every token but
            # the last writes its k/v; its sampled candidate is discarded)
            for i in range(consumed, len(ctx) - 1):
                cache, _, _ = self._step(cache, ctx[i], i)
            # draft greedily from the frontier; each step writes the fed
            # token's k/v one row further (rows past the trusted
            # frontier: re-written by the next teacher-forcing pass if
            # rejected)
            out = []
            tok = ctx[-1]
            for j in range(self.k):
                cache, nxt, _ = self._step(cache, tok, len(ctx) - 1 + j)
                tok = int(np.asarray(nxt)[0])
                out.append(tok)
        st["cache"], st["consumed"] = cache, len(ctx)
        self._streams[stream] = st
        return np.asarray(out, np.int32)

    def release(self, stream: int) -> None:
        self._streams.pop(stream, None)

    def reset(self) -> None:
        self._streams.clear()
