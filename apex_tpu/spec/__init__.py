"""Speculative decoding: amortize the target model's weight/KV
streaming over k drafted tokens per step.

Decode is memory-bandwidth-bound — every generated token streams the
whole model plus the live KV cache through HBM for ONE row of logits.
Speculation attacks the per-token factor directly: a cheap drafter
proposes ``k`` tokens, the target model scores all ``k+1`` positions in
one multi-token step (the chunked-prefill/flash machinery the engines
already have), and a fused verify-and-sample tail
(:func:`apex_tpu.ops.fused_verify` — extending the arXiv:2502.17728
operation-fusion argument from the sampling tail to the whole
accept/reject tail) emits the longest accepted prefix plus the
corrected next token. Acceptance is EXACT: greedy spec output is
token-identical to the non-speculative baseline, and the
temperature/top-p path is rejection sampling under the same filtered
distribution the fused sampling tail draws from — drafter quality
moves THROUGHPUT (the acceptance rate), never the distribution.

This package is the drafter side:

* :class:`~apex_tpu.spec.drafter.Drafter` — the protocol: a static
  ``k``, ``propose(stream, context)``, per-stream state keyed by
  request id (preemption-safe: a resumed stream's context re-grows
  token-identically, so the incremental frontier survives eviction).
* :class:`~apex_tpu.spec.drafter.NGramDrafter` — host-side n-gram
  lookahead: zero device memory, zero extra compiled programs.
* :class:`~apex_tpu.spec.drafter.ModelDrafter` — a small ``GPTConfig``
  model with a per-stream KV cache behind ONE batch-1 jitted step
  (stable avals; compiled once across streams/rounds/churn).

The device side lives in the engines: ``DecodeEngine.generate(...,
draft=...)`` (batch-1 spec rounds over the contiguous cache) and
``ServingEngine.serve(..., draft=...)`` (batched spec rounds over the
whole slot array, interleaved with chunked prefill, with block-table/
length rewind to the accepted frontier under churn). ``bench.py
--spec`` measures tokens/s/request and acceptance rate into a
schema-validated ``spec`` record; see ``docs/api/inference.md`` for
the acceptance math and the rewind contract.
"""

from apex_tpu.spec.drafter import (  # noqa: F401
    MAX_DRAFT_K,
    Drafter,
    ModelDrafter,
    NGramDrafter,
    validate_drafter,
)
from apex_tpu.spec.tree import (  # noqa: F401
    AdaptiveSpecController,
    DraftTree,
    NGramTreeDrafter,
    PagedModelDrafter,
    draft_tree,
    is_tree_drafter,
)
