"""Tree speculative decoding — the drafter side: static draft-tree
topologies, tree-capable drafters, the paged-pool model drafter, and
the acceptance-adaptive (k, b) controller.

Chain speculation (PR 15) accepts ONE prefix per round: a single early
mismatch discards the whole tail, so the measured acceptance rate is a
hard ceiling on tokens/s/request. A draft TREE hedges the first —
highest-entropy — positions: ``branching`` alternative first tokens,
each continued ``depth`` tokens deep, are all scored by the target in
ONE batched forward (the per-round launch/HBM cost amortizes across
every branch — arXiv:2502.17728's fusion argument, wider), and the
fused tree verify (:func:`apex_tpu.ops.fused_verify_tree`) emits the
DEEPEST fully-accepted root path plus a bonus/corrected token.

Everything here is host-side and static-shaped:

* :class:`DraftTree` — a fixed topology per ``(branching, depth)``:
  parent pointers, the ancestor-or-self closure (the verify kernel's
  walk operand AND the tree-attention mask, precomputed once — it
  ships as constant operand CONTENTS, so the zero-recompile contract
  holds across rounds), and the host path walk that turns a verify
  verdict back into emitted tokens. One compiled program per
  ``(branching, depth)`` in use; the instances are cached.
* :class:`NGramTreeDrafter` — the n-gram drafter, branching on TIE
  FREQUENCY: where several tokens followed the same context window,
  the runner-ups seed the extra branches (exactly the positions where
  a single chain guess is most likely wrong).
* :class:`PagedModelDrafter` — the model drafter with its KV moved
  into the SHARED paged-pool economy: blocks come from the serving
  scheduler's own :class:`~apex_tpu.serving.kv_blocks.BlockAllocator`
  (same refcount ledger, visible in ``check_accounting()``/pool
  telemetry), and a preempted stream's drafter blocks free through
  the identical eviction path.
* :class:`AdaptiveSpecController` — per-stream windowed acceptance →
  a (depth, branching) choice from a small STATIC set (one compiled
  program per choice, caches pinned): a hard stream stops wasting
  draft compute, an easy stream drafts deeper (the AMP move,
  arXiv:2210.07297 — a tunable knob priced per stream instead of
  frozen).

The device side lives in the engines (``DecodeEngine.generate(...,
draft=<tree drafter>)`` and ``ServingEngine.serve`` — which degrades
tree→chain→plain per round on headroom, never stalls); see
``docs/api/inference.md`` ("Tree speculative decoding").
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from apex_tpu.monitor import spans as monitor_spans
from apex_tpu.spec.drafter import MAX_DRAFT_K, Drafter

__all__ = [
    "DraftTree",
    "draft_tree",
    "NGramTreeDrafter",
    "PagedModelDrafter",
    "AdaptiveSpecController",
    "is_tree_drafter",
]


class DraftTree:
    """One STATIC draft-tree topology: ``branching`` root branches,
    each a chain of ``depth`` nodes (the shape that hedges the
    highest-entropy FIRST position while keeping the node count
    linear; ``branching == 1`` is exactly the chain). Node 0 is the
    committed pending token (the root); drafted node ``1 + b*depth +
    l`` is branch ``b``'s level-``l`` token. All arrays are host
    numpy, computed once and shipped as operand CONTENTS — the device
    avals depend only on ``(branching, depth)``.
    """

    def __init__(self, branching: int, depth: int):
        branching, depth = int(branching), int(depth)
        if branching < 1 or depth < 1:
            raise ValueError(
                f"DraftTree needs branching >= 1 and depth >= 1; got "
                f"branching={branching}, depth={depth}")
        if branching * depth > MAX_DRAFT_K:
            raise ValueError(
                f"DraftTree ({branching} branches x depth {depth} = "
                f"{branching * depth} nodes) exceeds MAX_DRAFT_K="
                f"{MAX_DRAFT_K} verify rows — shrink branching or depth "
                f"(branching x depth must be <= {MAX_DRAFT_K})")
        self.branching = branching
        self.depth = depth
        self.num_nodes = branching * depth
        self.n1 = self.num_nodes + 1
        parents = np.zeros((self.n1,), np.int32)
        for b in range(branching):
            for lv in range(depth):
                j = 1 + b * depth + lv
                parents[j] = 0 if lv == 0 else j - 1
        self.parents = parents
        anc = np.zeros((self.n1, self.n1), np.int32)
        anc[0, 0] = 1
        for j in range(1, self.n1):
            anc[j] = anc[parents[j]]
            anc[j, j] = 1
        self.anc = anc
        self.depths = anc.sum(-1).astype(np.int32) - 1

    def path(self, j_star: int) -> List[int]:
        """Node indices of ``j_star``'s root path, root EXCLUDED,
        shallow→deep — the drafted nodes a verify verdict accepted."""
        out = []
        j = int(j_star)
        while j != 0:
            out.append(j)
            j = int(self.parents[j])
        return out[::-1]

    def path_tokens(self, node_tokens: Sequence[int], a: int,
                    j_star: int, next_token: int) -> List[int]:
        """The tokens one tree round emits: the accepted path's drafted
        tokens (``node_tokens`` indexes drafted nodes only — entry
        ``j - 1`` is node ``j``'s token) plus the bonus/corrected
        token. ``a`` (the verify's accept length) must equal
        ``j_star``'s depth — checked, because a mismatch means the
        verdict and the topology disagree."""
        nodes = self.path(int(j_star))
        if len(nodes) != int(a):
            raise ValueError(
                f"verify verdict disagrees with the topology: j_star="
                f"{j_star} has depth {len(nodes)} but accept_len={a}")
        return [int(node_tokens[j - 1]) for j in nodes] + [int(next_token)]

    def operands(self, batch: int) -> Tuple[np.ndarray, np.ndarray]:
        """``(parents (batch, N+1), anc (batch, N+1, N+1))`` int32 —
        the verify/attention operands, tiled over the slot array."""
        return (np.tile(self.parents, (batch, 1)),
                np.tile(self.anc, (batch, 1, 1)))


@functools.lru_cache(maxsize=None)
def draft_tree(branching: int, depth: int) -> DraftTree:
    """The cached topology for ``(branching, depth)`` — one instance
    (and downstream, one compiled program) per shape in use."""
    return DraftTree(branching, depth)


def is_tree_drafter(draft) -> bool:
    """A drafter that can propose trees: it has ``propose_tree`` plus
    the static ``depth``/``branching`` shape attributes."""
    return (hasattr(draft, "propose_tree")
            and getattr(draft, "depth", None) is not None
            and getattr(draft, "branching", None) is not None)


class NGramTreeDrafter(Drafter):
    """N-gram drafter with TIE-FREQUENCY branching: per stream, an
    order-``n`` table maps each context window to EVERY token observed
    following it (with counts + recency). Branch 0 walks the top
    candidate exactly like :class:`~apex_tpu.spec.drafter.
    NGramDrafter`; branches 1.. seed from the runner-up candidates of
    the FIRST position — the ties are precisely where a single chain
    guess is most likely wrong, so that is where the tree hedges.
    Windows with fewer candidates than branches repeat the top one
    (a duplicate sibling wastes a verify row, never correctness).

    ``chain_k`` (default ``depth``) is the CHAIN-fallback draft length
    (``self.k``): near the row cap the engines degrade tree→chain, and
    a ``chain_k < depth`` makes the chain rung strictly cheaper in
    rows than the tree rung.
    """

    def __init__(self, depth: int = 4, branching: int = 2, n: int = 3,
                 chain_k: Optional[int] = None):
        draft_tree(branching, depth)  # eager shape validation
        self.depth = int(depth)
        self.branching = int(branching)
        k = self.depth if chain_k is None else int(chain_k)
        if not 1 <= k <= self.depth:
            raise ValueError(
                f"chain_k must be in [1, depth={self.depth}] (the chain "
                f"fallback cannot draft deeper than the tree); got {k}")
        self.k = self.chain_k = k
        if int(n) < 1:
            raise ValueError(f"NGramTreeDrafter n must be >= 1, got {n}")
        self.n = int(n)
        # stream -> (window -> token -> [count, last position], consumed)
        self._streams: Dict[int, Any] = {}

    @property
    def tree(self) -> DraftTree:
        return draft_tree(self.branching, self.depth)

    def _table(self, stream: int, context: Sequence[int]):
        n = self.n
        table, consumed = self._streams.get(stream, (None, 0))
        if table is None or consumed > len(context):
            table, consumed = {}, 0  # fresh (or shrunk: a reused id)
        ctx = [int(t) for t in context]
        for i in range(max(consumed, n), len(ctx)):
            stats = table.setdefault(tuple(ctx[i - n:i]), {})
            cnt, _ = stats.get(ctx[i], (0, 0))
            stats[ctx[i]] = (cnt + 1, i)
        self._streams[stream] = (table, len(ctx))
        return table, ctx

    def _candidates(self, table, window: List[int]) -> List[int]:
        """Tokens observed after ``window``, most-frequent first (ties
        to most recent); fallback: repeat the last token."""
        stats = table.get(tuple(window[-self.n:]), None)
        if not stats:
            return [window[-1]]
        return [t for t, _ in sorted(
            stats.items(), key=lambda kv: (-kv[1][0], -kv[1][1]))]

    def _walk(self, table, window: List[int], steps: int) -> List[int]:
        out = []
        w = list(window)
        for _ in range(steps):
            guess = self._candidates(table, w)[0]
            out.append(guess)
            w.append(guess)
        return out

    def propose(self, stream: int, context: Sequence[int]) -> np.ndarray:
        table, ctx = self._table(stream, context)
        window = ctx[-self.n:] if len(ctx) >= self.n else ctx[:]
        return np.asarray(self._walk(table, window, self.k), np.int32)

    def propose_tree(self, stream: int, context: Sequence[int],
                     shape: Optional[Tuple[int, int]] = None) -> np.ndarray:
        """Node tokens for the :class:`DraftTree` topology, drafted-node
        order (``shape=(depth, branching)`` overrides the static shape
        — the adaptive controller's per-round choice)."""
        depth, branching = shape or (self.depth, self.branching)
        table, ctx = self._table(stream, context)
        window = ctx[-self.n:] if len(ctx) >= self.n else ctx[:]
        cands = self._candidates(table, window)
        out = np.zeros((branching * depth,), np.int32)
        for b in range(branching):
            seed = cands[min(b, len(cands) - 1)]
            chain = [seed] + self._walk(table, window + [seed], depth - 1)
            out[b * depth:(b + 1) * depth] = chain
        return out

    def release(self, stream: int) -> None:
        self._streams.pop(stream, None)

    def reset(self) -> None:
        self._streams.clear()


class PagedModelDrafter(Drafter):
    """A small-model drafter whose per-stream KV cache is FIRST-CLASS
    paged-pool state: block ids come from the serving scheduler's own
    :class:`~apex_tpu.serving.kv_blocks.BlockAllocator` (the same
    refcount ledger the target streams use), so drafter blocks are
    visible in pool accounting (``check_accounting()`` stays exact
    across churn), count against the same capacity, and free through
    the identical eviction path — when the scheduler preempts a
    stream, its drafter blocks rewind with it (``Scheduler`` calls
    :meth:`evict_stream` from ``_preempt``/``_finish``), and the
    resumed stream's context re-grows token-identically so the
    ``consumed`` frontier rebuilds by replay.

    The device side is ONE jitted paged decode step (an inner
    batch-1 :class:`~apex_tpu.serving.ServingEngine` over a pool in
    the DRAFTER's geometry but indexed by the SHARED block ids):
    context rows teacher-force through it and branches draft greedily
    from the frontier, re-seeding branch ``b`` from the frontier
    logits' ``b``-th candidate — stable avals throughout, compiled
    once across streams/rounds/churn. Scratch rows past the trusted
    frontier are simply re-written next round (length masking IS the
    rewind, as everywhere else).

    :meth:`bind` wires the drafter to a scheduler; ``ServingEngine.
    serve`` calls it. Standalone drives must bind first.
    """

    def __init__(self, model, params, *, depth: int = 4,
                 branching: int = 2, chain_k: Optional[int] = None):
        draft_tree(branching, depth)  # eager shape validation
        self.depth = int(depth)
        self.branching = int(branching)
        k = self.depth if chain_k is None else int(chain_k)
        if not 1 <= k <= self.depth:
            raise ValueError(
                f"chain_k must be in [1, depth={self.depth}] (the chain "
                f"fallback cannot draft deeper than the tree); got {k}")
        self.k = self.chain_k = k
        self.model = model
        self.params = params
        self.vocab_size = int(model.config.vocab_size)
        self.block_size: Optional[int] = None  # set at bind
        self.cache_rows: Optional[int] = None  # set at bind
        self._engine = None
        self._pool = None
        self._sched = None
        self._alloc = None
        self._key = None
        # stream -> {"table": (max_blocks,) int32, "block_ids": [...],
        #            "n_blocks": int, "consumed": int}
        self._streams: Dict[int, Dict[str, Any]] = {}
        # high-water of live drafter blocks in the SHARED pool (bench
        # witness: the drafter really lives in the pool economy)
        self.peak_blocks = 0

    @property
    def tree(self) -> DraftTree:
        return draft_tree(self.branching, self.depth)

    def bind(self, scheduler, *, block_size: int) -> None:
        """Attach to ``scheduler``'s allocator (the shared ledger) and
        build the inner paged engine + drafter-geometry pool sized to
        the SAME block-id space. Rebinding to a different scheduler
        first releases every stream's blocks against the old one."""
        if self._sched is scheduler:
            return
        from apex_tpu.serving.engine import ServingEngine
        self.reset()  # old blocks go back to the OLD allocator
        self._sched = scheduler
        self._alloc = scheduler.allocator
        self.block_size = int(block_size)
        self._engine = ServingEngine(
            self.model, num_slots=1, block_size=self.block_size,
            prefill_chunk=self.block_size,
            num_blocks=self._alloc.num_blocks)
        self._pool = self._engine.init_pool()
        self.cache_rows = self._engine.max_s
        scheduler.draft_owner = self

    def _require_bound(self):
        if self._alloc is None:
            raise ValueError(
                "PagedModelDrafter is not bound to a scheduler — its KV "
                "blocks live in the shared pool, so call bind(scheduler, "
                "block_size=...) first (ServingEngine.serve does this "
                "for you)")

    def _ensure_rows(self, st: Dict[str, Any], rows: int) -> None:
        from apex_tpu.serving.kv_blocks import blocks_needed
        need = blocks_needed(rows, self.block_size) - st["n_blocks"]
        if need <= 0:
            return
        if need > self._alloc.num_free:
            raise RuntimeError(
                f"drafter needs {need} pool block(s) with "
                f"{self._alloc.num_free} free — the serve loop's "
                f"headroom check (round_blocks_needed) should have "
                f"degraded this round to chain/plain decode first")
        for bid in self._alloc.allocate(need):
            st["table"][st["n_blocks"]] = bid
            st["block_ids"].append(bid)
            st["n_blocks"] += 1
        self.peak_blocks = max(self.peak_blocks, self.pool_blocks())

    def round_blocks_needed(self, stream: int, context_len: int,
                            depth: Optional[int] = None) -> int:
        """Fresh pool blocks one tree round would allocate for this
        stream (the serve loop's drafter-headroom check)."""
        from apex_tpu.serving.kv_blocks import blocks_needed
        self._require_bound()
        st = self._streams.get(stream)
        have = st["n_blocks"] if st is not None else 0
        rows = int(context_len) - 1 + (self.depth if depth is None
                                       else int(depth))
        return max(0, blocks_needed(rows, self.block_size) - have)

    def _step(self, st: Dict[str, Any], tok: int, pos: int):
        import jax
        import jax.numpy as jnp

        if self._key is None:
            self._key = jax.random.PRNGKey(0)  # apexlint: disable=APX502
        self._pool, toks, logits = self._engine.decode_step(
            self.params, self._pool,
            jnp.asarray(st["table"][None, :]),
            jnp.asarray([tok], jnp.int32),
            jnp.asarray([pos + 1], jnp.int32), self._key)
        return np.asarray(logits)[0]

    def propose(self, stream: int, context: Sequence[int]) -> np.ndarray:
        return self.propose_tree(stream, context, shape=(self.k, 1))

    def propose_tree(self, stream: int, context: Sequence[int],
                     shape: Optional[Tuple[int, int]] = None) -> np.ndarray:
        from apex_tpu.serving.kv_blocks import DEAD_BLOCK
        self._require_bound()
        depth, branching = shape or (self.depth, self.branching)
        st = self._streams.get(stream)
        if st is None or st["consumed"] > len(context):
            if st is not None:  # shrunk context: a reused stream id
                self.evict_stream(stream)
            st = {"table": np.full((self._engine.max_blocks_per_slot,),
                                   DEAD_BLOCK, np.int32),
                  "block_ids": [], "n_blocks": 0, "consumed": 0}
        ctx = [int(t) for t in context]
        rows = len(ctx) - 1 + depth
        if rows > self.cache_rows:
            raise ValueError(
                f"PagedModelDrafter cache ({self.cache_rows} rows) "
                f"cannot hold context ({len(ctx)}) - 1 + depth "
                f"({depth}) draft rows — raise the drafter model's "
                f"max_seq_len (the engines validate this bound at "
                f"wiring time)")
        # register BEFORE allocating so the peak_blocks high-water in
        # _ensure_rows (which reads pool_blocks()) counts this stream's
        # own fresh blocks, not just the other live streams'
        self._streams[stream] = st
        self._ensure_rows(st, rows)
        consumed = st["consumed"]
        with monitor_spans.span("spec_draft", stream=int(stream)):
            # teacher-force the unconsumed context rows
            for i in range(consumed, len(ctx) - 1):
                self._step(st, ctx[i], i)
            # the frontier row (+ its logits, which seed every branch)
            frontier = self._step(st, ctx[-1], len(ctx) - 1)
            order = np.argsort(-frontier, kind="stable")
            out = np.zeros((branching * depth,), np.int32)
            V = frontier.shape[-1]
            for b in range(branching):
                tok = int(order[min(b, V - 1)])
                out[b * depth] = tok
                # continue this branch greedily; its tokens overwrite
                # the scratch rows the previous branch used
                for lv in range(1, depth):
                    logits = self._step(st, tok, len(ctx) - 1 + lv)
                    tok = int(np.argmax(logits))
                    out[b * depth + lv] = tok
        st["consumed"] = len(ctx)
        self._streams[stream] = st
        return out

    def evict_stream(self, stream: int) -> None:
        """Free the stream's drafter blocks through the shared
        allocator — the scheduler calls this from the SAME preempt/
        finish paths that free the stream's target blocks."""
        st = self._streams.pop(stream, None)
        if st is not None and st["block_ids"]:
            self._alloc.free(st["block_ids"])

    def release(self, stream: int) -> None:
        self.evict_stream(stream)

    def reset(self) -> None:
        for stream in list(self._streams):
            self.evict_stream(stream)

    def pool_blocks(self) -> int:
        """Live drafter blocks in the shared pool (bench/telemetry)."""
        return sum(st["n_blocks"] for st in self._streams.values())


class AdaptiveSpecController:
    """Per-stream acceptance-adaptive (depth, branching) choice from a
    small STATIC set.

    Each stream keeps a rolling window of its last ``window`` rounds'
    (accepted, depth) pairs — fed from the same per-round numbers the
    ``spec`` lifecycle events carry. When the windowed acceptance
    fraction (accepted rows per drafted depth) exceeds ``hi`` the
    stream steps UP the choice ladder (drafts deeper/wider); below
    ``lo`` it steps DOWN; in between it holds (hysteresis — one
    adjustment per full window, so a single lucky round never flaps
    the program choice). ``choices`` must be ordered shallow→deep;
    every entry is a compiled-program shape the engines pin, so the
    set stays small by design.
    """

    def __init__(self, choices: Sequence[Tuple[int, int]] = (
            (2, 1), (4, 1), (4, 2)), window: int = 6,
            lo: float = 0.45, hi: float = 0.8):
        if not choices:
            raise ValueError("AdaptiveSpecController needs >= 1 choice")
        for d, b in choices:
            draft_tree(b, d)  # eager shape validation for every choice
        self.choices = tuple((int(d), int(b)) for d, b in choices)
        self.window = int(window)
        self.lo, self.hi = float(lo), float(hi)
        # stream -> {"idx": int, "hist": [(accepted, depth)...],
        #            "since": rounds since last adjustment}
        self._streams: Dict[int, Dict[str, Any]] = {}
        self.adjustments = 0
        # optional ladder CEILING index (set_cap): an online re-plan
        # bounds how deep the ladder may walk without adding any new
        # compiled shape — every choice stays one of the pinned set
        self.cap: Optional[int] = None

    def _state(self, stream: int) -> Dict[str, Any]:
        st = self._streams.get(stream)
        if st is None:
            st = {"idx": 0, "hist": [], "since": 0}
            self._streams[stream] = st
        return st

    def choice(self, stream: int) -> Tuple[int, int]:
        """The stream's current (depth, branching)."""
        return self.choices[self._state(stream)["idx"]]

    def round_shape(self, streams: Sequence[int]) -> Tuple[int, int]:
        """One shape for a batched round: the SHALLOWEST live stream's
        choice (conservative — a deep program would waste every hard
        stream's rows; the easy streams catch up when the hard ones
        finish)."""
        if not streams:
            return self.choices[0]
        idx = min(self._state(s)["idx"] for s in streams)
        return self.choices[idx]

    def set_cap(self, shape) -> int:
        """Pin the ladder's CEILING to one of the pre-validated
        ``choices`` (or lift it with ``None``): streams above the cap
        clamp down NOW, and :meth:`note_round` never steps past it.
        This is the aval-stable spec-shape knob an online re-plan
        (:class:`~apex_tpu.serving.scheduler.ReplanPolicy`) applies
        live — the dispatched shape stays one of the compiled set, so
        no new program is ever traced mid-serve. Returns the cap
        index."""
        if shape is None:
            self.cap = None
            return len(self.choices) - 1
        shape = (int(shape[0]), int(shape[1]))
        if shape not in self.choices:
            raise ValueError(
                f"cap shape {shape} is not one of this controller's "
                f"choices {self.choices} — a cap outside the compiled "
                f"set would force a new trace mid-serve")
        self.cap = self.choices.index(shape)
        for st in self._streams.values():
            if st["idx"] > self.cap:
                st["idx"] = self.cap
                st["since"] = 0
        return self.cap

    def note_round(self, stream: int, accepted: int, depth: int) -> None:
        """Feed one round's verdict (the numbers ``on_spec_round``
        gets) and maybe adjust the stream's choice."""
        st = self._state(stream)
        st["hist"].append((int(accepted), int(depth)))
        if len(st["hist"]) > self.window:
            st["hist"] = st["hist"][-self.window:]
        st["since"] += 1
        if len(st["hist"]) < self.window or st["since"] < self.window:
            return
        drafted = sum(d for _, d in st["hist"])
        rate = sum(a for a, _ in st["hist"]) / max(drafted, 1)
        top = len(self.choices) - 1 if self.cap is None else self.cap
        if rate >= self.hi and st["idx"] < top:
            st["idx"] += 1
            st["since"] = 0
            self.adjustments += 1
        elif rate <= self.lo and st["idx"] > 0:
            st["idx"] -= 1
            st["since"] = 0
            self.adjustments += 1

    def release(self, stream: int) -> None:
        self._streams.pop(stream, None)

    def reset(self) -> None:
        self._streams.clear()
