"""Megatron-style batch samplers — re-design of ``apex/transformer/_data/``
— plus host→device prefetching (the torch-DataLoader overlap, TPU-style)."""

from apex_tpu.transformer._data._batchsampler import (  # noqa: F401
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)
from apex_tpu.transformer._data.prefetch import (  # noqa: F401
    data_parallel_iterator,
    prefetch_to_device,
)
