"""Host→device prefetching over a batch iterator.

The reference leans on ``torch.utils.data.DataLoader`` (pinned memory +
``non_blocking`` copies) to hide host→device transfer behind compute; the
TPU-native analog is explicit double buffering: while step N computes,
batch N+1's ``jax.device_put`` is already in flight (device transfers are
asynchronous in JAX — the put returns immediately and the train step's
dispatch queues behind it). This is the standard flax/``jax_utils``
prefetch pattern, here with sharding support so the batch lands already
laid out over the mesh's data axis.
"""

from __future__ import annotations

import collections
import itertools
from typing import Any, Iterable, Iterator, Optional

import jax

from apex_tpu.parallel import distributed as dist_lib


def prefetch_to_device(
    iterator: Iterable[Any],
    size: int = 2,
    sharding: Optional[Any] = None,
) -> Iterator[Any]:
    """Yield batches from ``iterator`` with ``size`` transfers in flight.

    ``sharding``: a ``jax.sharding.Sharding`` (or pytree of them) applied to
    every leaf — e.g. :func:`apex_tpu.parallel.data_parallel_sharding` to
    split the batch over ``dp``. Default places on the default device(s).

    ``size=2`` (double buffering) is enough to hide transfer latency; more
    only adds host memory pressure. The reference gets the same overlap
    from DataLoader workers + pinned-memory ``cuda(non_blocking=True)``.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    queue: collections.deque = collections.deque()
    it = iter(iterator)

    def put(batch):
        if sharding is None:
            return jax.tree.map(jax.device_put, batch)
        # device_put broadcasts a single Sharding over the pytree, and
        # accepts a matching pytree of shardings
        return jax.device_put(batch, sharding)

    for batch in itertools.islice(it, size):
        queue.append(put(batch))
    while queue:
        yield queue.popleft()
        for batch in itertools.islice(it, 1):
            queue.append(put(batch))


def data_parallel_iterator(
    iterator: Iterable[Any], *, batch_axis: int = 0, size: int = 2
) -> Iterator[Any]:
    """:func:`prefetch_to_device` with the batch dimension sharded over the
    global mesh's ``dp`` axis — the loader-side half of the DDP recipe."""
    return prefetch_to_device(
        iterator, size=size,
        sharding=dist_lib.data_parallel_sharding(batch_axis=batch_axis),
    )
