"""Batch samplers for data-parallel pretraining.

Re-design of ``apex/transformer/_data/_batchsampler.py:16-180``: yield index
lists such that each data-parallel rank reads its own contiguous slice of
every global batch, resumable from ``consumed_samples``. Pure host-side
iterators (no torch DataLoader dependency — any indexable dataset works).
"""

from __future__ import annotations

import numpy as np


class MegatronPretrainingSampler:
    """Sequential sampler (``_batchsampler.py:16-98``)."""

    def __init__(self, total_samples: int, consumed_samples: int,
                 micro_batch_size: int, data_parallel_rank: int,
                 data_parallel_size: int, drop_last: bool = True):
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self.micro_batch_size = micro_batch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)
        self.drop_last = drop_last
        if total_samples <= 0:
            raise ValueError(f"total_samples must be positive, got {total_samples}")
        if consumed_samples >= total_samples:
            raise ValueError(
                f"consumed_samples ({consumed_samples}) already >= "
                f"total_samples ({total_samples})")
        if data_parallel_rank >= data_parallel_size:
            raise ValueError(
                f"data_parallel_rank {data_parallel_rank} out of range for "
                f"data_parallel_size {data_parallel_size}")

    def __len__(self):
        return self.total_samples

    def get_start_end_idx(self):
        start = self.data_parallel_rank * self.micro_batch_size
        return start, start + self.micro_batch_size

    def __iter__(self):
        batch = []
        for idx in range(self.consumed_samples, self.total_samples):
            batch.append(idx)
            if len(batch) == self.micro_batch_times_data_parallel_size:
                s, e = self.get_start_end_idx()
                yield batch[s:e]
                batch = []
        if batch and not self.drop_last:
            s, e = self.get_start_end_idx()
            yield batch[s:e]


class MegatronPretrainingRandomSampler:
    """Shuffled epoch-bucketed sampler (``_batchsampler.py:100-180``):
    shuffle within the current epoch's remaining pool, deterministic in
    (epoch, seed)."""

    def __init__(self, total_samples: int, consumed_samples: int,
                 micro_batch_size: int, data_parallel_rank: int,
                 data_parallel_size: int, seed: int = 0):
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self.micro_batch_size = micro_batch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)
        self.last_batch_size = (
            self.total_samples % self.micro_batch_times_data_parallel_size)
        self.seed = seed

    def __len__(self):
        return self.total_samples

    def __iter__(self):
        active_total_samples = self.total_samples - self.last_batch_size
        self.epoch = self.consumed_samples // active_total_samples
        current_epoch_samples = self.consumed_samples % active_total_samples
        assert current_epoch_samples % self.micro_batch_times_data_parallel_size == 0

        # data sharded over dp ranks: contiguous bucket per rank, shuffled
        bucket_size = (self.total_samples // self.micro_batch_times_data_parallel_size
                       ) * self.micro_batch_size
        bucket_offset = current_epoch_samples // self.data_parallel_size
        start_idx = self.data_parallel_rank * bucket_size

        rng = np.random.RandomState(self.seed + self.epoch)
        random_idx = rng.permutation(bucket_size)
        idx_range = [start_idx + int(x) for x in random_idx[bucket_offset:]]

        batch = []
        for idx in idx_range:
            batch.append(idx)
            if len(batch) == self.micro_batch_size:
                self.consumed_samples += self.micro_batch_times_data_parallel_size
                yield batch
                batch = []
