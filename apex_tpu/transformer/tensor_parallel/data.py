"""TP data distribution.

Re-design of ``apex/transformer/tensor_parallel/data.py``: the reference
broadcasts the data batch from TP rank 0 to the other TP ranks of each model
replica (``broadcast_data``, ``data.py:80``, with dtype/size checks) because
each rank has its own dataloader process.

Under SPMD there is one logical program: placing a batch with a sharding
that is *replicated over tp* IS the broadcast — XLA materializes it on every
tp rank of the replica. ``broadcast_data`` here therefore builds exactly that
sharding and device_puts the host batch once per process.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.parallel import mesh as mesh_lib

PyTree = Any


def data_sharding(mesh=None, batch_axes: Sequence[str] = (mesh_lib.DATA_AXIS,)):
    """Sharding for an input batch: batch dim split over dp, replicated over
    tp/pp — the SPMD form of 'rank 0 broadcasts to the TP group'."""
    mesh = mesh or mesh_lib.get_mesh()
    return NamedSharding(mesh, P(tuple(batch_axes)))


def broadcast_data(keys: Sequence[str], data: Dict[str, Any], dtype=None, mesh=None) -> Dict[str, jax.Array]:
    """Place ``data[k]`` for k in keys with batch-over-dp, replicated-over-tp
    sharding (semantics of ``data.py:80``'s broadcast; the dtype check
    mirrors its ``_check_data_types``)."""
    sharding = data_sharding(mesh)
    out = {}
    for k in keys:
        arr = jnp.asarray(data[k], dtype=dtype)
        out[k] = jax.device_put(arr, sharding)
    return out
