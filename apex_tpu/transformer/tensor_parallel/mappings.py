"""The four TP collectives as differentiable functions.

Re-design of ``apex/transformer/tensor_parallel/mappings.py:23-141``, where
each mapping is an autograd Function pairing a forward collective with its
transpose in backward:

| mapping  | forward            | backward           |
|----------|--------------------|--------------------|
| copy     | identity           | all-reduce         |
| reduce   | all-reduce (psum)  | identity           |
| scatter  | split last dim     | all-gather         |
| gather   | all-gather last dim| split              |

JAX's ``psum``/``all_gather``/dynamic-slice already have these transposes
under autodiff, but *not* in matched pairs (e.g. ``psum``'s gradient is
another psum, not identity — the ``psum(psum(x))`` trap). We pin the exact
Megatron semantics with ``custom_vjp`` so gradients match the reference
contract. All functions must run inside ``shard_map`` with ``axis`` bound.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu.parallel import mesh as mesh_lib


def _count_collective(kind: str, x: jax.Array, axis_name: str) -> None:
    """Trace-time collective accounting (the same hook
    ``all_reduce_gradients`` and the pipeline ``_rotate`` use) — without
    it the TP axis is invisible in ``monitor report``'s traffic line.
    Lazy-import shim only; the counting contract lives in
    ``monitor.hooks.count_traffic``."""
    from apex_tpu.monitor import hooks as monitor_hooks

    monitor_hooks.count_traffic(kind, x, axis_name)


def _psum_counted(x: jax.Array, axis_name: str) -> jax.Array:
    from apex_tpu.monitor import spans as monitor_spans

    _count_collective("psum", x, axis_name)
    # trace-time span: the psum's HLOs carry the psum_<axis> scope into
    # device traces and the span record carries bytes for calibration
    with monitor_spans.collective_span("psum", x, axis_name):
        return jax.lax.psum(x, axis_name)


def _split_local(x: jax.Array, axis_name: str) -> jax.Array:
    """This rank's slice of the last dimension (mappings.py:79-90)."""
    size = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    chunk = x.shape[-1] // size
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=x.ndim - 1)


def _gather_last(x: jax.Array, axis_name: str) -> jax.Array:
    """All-gather along the last dim (mappings.py:92-105)."""
    from apex_tpu.monitor import spans as monitor_spans

    _count_collective("all_gather", x, axis_name)
    with monitor_spans.collective_span("all_gather", x, axis_name):
        return jax.lax.all_gather(x, axis_name, axis=x.ndim - 1, tiled=True)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _copy_core(x, axis_name):
    return x


def _copy_fwd(x, axis_name):
    del axis_name
    return x, None


def _copy_bwd(axis_name, _, g):
    return (_psum_counted(g, axis_name),)


_copy_core.defvjp(lambda x, axis_name: _copy_fwd(x, axis_name), _copy_bwd)


def copy_to_tensor_model_parallel_region(x, axis_name=mesh_lib.TENSOR_AXIS):
    """Identity forward, all-reduce backward (``_CopyToModelParallelRegion``,
    ``mappings.py:108-117``): marks the point where a replicated activation
    enters the TP region. ``axis_name=None`` (tp=1) is the identity."""
    return x if axis_name is None else _copy_core(x, axis_name)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _reduce_core(x, axis_name):
    return _psum_counted(x, axis_name)


def _reduce_fwd(x, axis_name):
    return _psum_counted(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


_reduce_core.defvjp(_reduce_fwd, _reduce_bwd)


def reduce_from_tensor_model_parallel_region(x, axis_name=mesh_lib.TENSOR_AXIS):
    """All-reduce forward, identity backward (``_ReduceFromModelParallelRegion``,
    ``mappings.py:119-128``). ``axis_name=None`` (tp=1) is the identity."""
    return x if axis_name is None else _reduce_core(x, axis_name)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _scatter_core(x, axis_name):
    return _split_local(x, axis_name)


def _scatter_fwd(x, axis_name):
    return _split_local(x, axis_name), None


def _scatter_bwd(axis_name, _, g):
    return (_gather_last(g, axis_name),)


_scatter_core.defvjp(_scatter_fwd, _scatter_bwd)


def scatter_to_tensor_model_parallel_region(x, axis_name=mesh_lib.TENSOR_AXIS):
    """Split last dim forward, all-gather backward
    (``_ScatterToModelParallelRegion``, ``mappings.py:130-139``)."""
    return x if axis_name is None else _scatter_core(x, axis_name)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _gather_core(x, axis_name):
    return _gather_last(x, axis_name)


def _gather_fwd(x, axis_name):
    return _gather_last(x, axis_name), None


def _gather_bwd(axis_name, _, g):
    return (_split_local(g, axis_name),)


_gather_core.defvjp(_gather_fwd, _gather_bwd)


def gather_from_tensor_model_parallel_region(x, axis_name=mesh_lib.TENSOR_AXIS):
    """All-gather last dim forward, split backward
    (``_GatherFromModelParallelRegion``, ``mappings.py:141-150``)."""
    return x if axis_name is None else _gather_core(x, axis_name)
