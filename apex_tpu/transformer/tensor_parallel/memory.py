"""Preallocated activation buffers — API parity with
``apex/transformer/tensor_parallel/memory.py:23-133`` (``MemoryBuffer``,
``RingMemBuffer``, ``allocate_mem_buff``).

Why this exists on TPU at all: the reference preallocates contiguous CUDA
memory so per-microbatch activation-checkpoint tensors don't fragment the
caching allocator (its ``CheckpointFunction`` copies distributed hidden
states into the buffer, ``random.py:45-84``). XLA has no runtime allocator
to fragment — buffers are planned at compile time, and *donation*
(``jax.jit(..., donate_argnums=...)``) is the idiomatic way to reuse a
buffer across steps (see ``tests/test_aux.py::TestMemoryBuffer``'s aliasing
evidence). The functional buffer below is therefore useful for the
reference's *other* use: carrying a bounded scratch region through a scan
(e.g. stashed hidden states) with explicit offset bookkeeping, while
keeping the reference's allocate/get/reset call surface.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

_MEM_BUFFS: Dict[str, "MemoryBuffer"] = {}


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MemoryBuffer:
    """Functional contiguous buffer: ``add`` copies a tensor in at the
    current offset and returns (new_buffer, view-shape slice info); ``get``
    reads a chunk back. Unlike the CUDA original, every mutation returns a
    new buffer value (donation makes the copy free under jit)."""

    data: jax.Array
    start: jax.Array  # scalar int32 offset of free space
    in_use_value: float = dataclasses.field(
        default=0.0, metadata=dict(static=False))

    @classmethod
    def create(cls, numel: int, dtype=jnp.float32) -> "MemoryBuffer":
        return cls(data=jnp.zeros((numel,), dtype),
                   start=jnp.zeros((), jnp.int32),
                   in_use_value=0.0)

    @property
    def numel(self) -> int:
        return self.data.shape[0]

    def add(self, tensor: jax.Array) -> Tuple["MemoryBuffer", jax.Array]:
        """Copy ``tensor`` into the buffer; returns (buffer', offset).

        Overflow raises when the offset is concrete (eager / top of jit,
        mirroring the reference's ``assert`` on double allocation). Under a
        traced offset (inside scan) the caller must size the buffer
        statically — ``dynamic_update_slice`` would clamp the start index
        and silently corrupt earlier entries."""
        flat = tensor.reshape(-1).astype(self.data.dtype)
        if not isinstance(self.start, jax.core.Tracer):
            if int(self.start) + flat.shape[0] > self.numel:
                raise ValueError(
                    f"MemoryBuffer overflow: offset {int(self.start)} + "
                    f"{flat.shape[0]} elements > capacity {self.numel}")
        elif flat.shape[0] > self.numel:
            raise ValueError(
                f"MemoryBuffer overflow: tensor of {flat.shape[0]} elements "
                f"can never fit capacity {self.numel}")
        data = jax.lax.dynamic_update_slice(self.data, flat, (self.start,))
        offset = self.start
        return dataclasses.replace(
            self, data=data, start=self.start + flat.shape[0]
        ), offset

    def get(self, offset: jax.Array, shape) -> jax.Array:
        size = 1
        for s in shape:
            size *= int(s)
        return jax.lax.dynamic_slice(self.data, (offset,), (size,)).reshape(shape)

    def reset(self) -> "MemoryBuffer":
        """``MemoryBuffer.reset`` — rewind the free pointer, keep storage."""
        return dataclasses.replace(self, start=jnp.zeros((), jnp.int32))


class RingMemBuffer:
    """``RingMemBuffer`` (``memory.py:133``): a rotation of N buffers handed
    out round-robin (the reference uses it for double-buffered checkpoint
    activations)."""

    def __init__(self, num_buffers: int, numel: int, dtype=jnp.float32):
        self.buffers = [MemoryBuffer.create(numel, dtype)
                        for _ in range(num_buffers)]
        self._idx = -1

    def get_next_buffer(self) -> MemoryBuffer:
        self._idx = (self._idx + 1) % len(self.buffers)
        return self.buffers[self._idx]


def allocate_mem_buff(name: str, numel: int, dtype=jnp.float32,
                      track_usage: bool = False) -> MemoryBuffer:
    """``allocate_mem_buff`` (``memory.py:23``) — registry-backed."""
    del track_usage  # usage is visible in the functional value itself
    if name in _MEM_BUFFS:
        raise ValueError(f"memory buffer {name!r} already allocated")
    _MEM_BUFFS[name] = MemoryBuffer.create(numel, dtype)
    return _MEM_BUFFS[name]


def get_mem_buff(name: str) -> MemoryBuffer:
    return _MEM_BUFFS[name]


def destroy_mem_buffs() -> None:
    _MEM_BUFFS.clear()
