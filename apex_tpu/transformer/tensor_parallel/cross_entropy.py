"""Vocab-parallel softmax cross-entropy.

Re-design of ``apex/transformer/tensor_parallel/cross_entropy.py:23-103``.
The algorithm ports directly — it is three collectives over the tp axis:

1. ``pmax`` of per-shard logit maxima (reference ``all_reduce(MAX)``, :29);
2. ``psum`` of the target logit, where only the shard owning the target id
   contributes (reference masked gather + all_reduce, :40-58);
3. ``psum`` of per-shard ``sum(exp)`` (reference :60-66).

Backward computes the reference's gradient (``:80-99``):
``d logits = (softmax - onehot_masked) * dloss`` on each shard, with label
smoothing exactly as the reference's ``label_smoothing`` branch computes
it — but from (logits, max, sum_exp) residuals with the softmax recomputed
in the backward pass (the ops/xentropy.py memory design) rather than the
reference's saved fp32 softmax.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu.ops import _backend
from apex_tpu.ops.pallas import xentropy as _xk
from apex_tpu.parallel import mesh as mesh_lib


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def vocab_parallel_cross_entropy(
    logits: jax.Array,
    target: jax.Array,
    label_smoothing: float = 0.0,
    axis_name: str = mesh_lib.TENSOR_AXIS,
    impl: str = "auto",
) -> jax.Array:
    """Per-token loss; ``logits`` are this shard's (..., V/tp) slice, target
    is the *global* token id. Must run inside shard_map with ``axis_name``.
    ``impl``: auto|pallas|xla — dispatch of the fused statistics kernel,
    the per-op override convention shared with the other fused ops."""
    loss, _ = _vce_fwd(logits, target, label_smoothing, axis_name, impl)
    return loss


def _shard_info(logits, axis_name):
    per = logits.shape[-1]
    if axis_name is None:
        return per, jnp.zeros((), jnp.int32)
    rank = jax.lax.axis_index(axis_name)
    return per, rank * per


def _vce_fwd(logits, target, label_smoothing, axis_name, impl="auto"):
    per, start = _shard_info(logits, axis_name)
    psum = (lambda v: v) if axis_name is None else (lambda v: jax.lax.psum(v, axis_name))
    pmax = (lambda v: v) if axis_name is None else (lambda v: jax.lax.pmax(v, axis_name))

    local_t = target - start
    in_shard = (local_t >= 0) & (local_t < per)
    t_idx = jnp.where(in_shard, local_t, 0)

    n = 1
    for d in logits.shape[:-1]:
        n *= d
    vocab = per * (1 if axis_name is None else jax.lax.axis_size(axis_name))
    # the Mosaic dialect has no f16: strict-fp16 logits take the jnp path
    use_kernel = _backend.choose_impl(
        impl, _xk.shapes_ok(n, per) and logits.dtype != jnp.float16
    ) == "pallas"
    if use_kernel:
        # One blockwise pass over the bf16/fp32 logits gives the per-row
        # (max, exp-sum, target-logit, row-sum) stats without the full-size
        # fp32 ``logits - max`` temporary the jnp formulation materializes
        # (it has three consumers, so XLA stages it: ~2 GB and ~5 ms/step of
        # HBM traffic on the flagship bench). Out-of-shard labels contribute
        # 0 to the target stat inside the kernel — the masked-gather psum of
        # the reference (:40-58) falls out for free.
        m_loc, l_loc, t_raw, s_raw = _xk.xent_stats(
            logits.reshape(n, per), local_t.reshape(n),
            interpret=_backend.interpret_mode(),
        )
        stats_shape = logits.shape[:-1]
        m_loc = m_loc.reshape(stats_shape)
        m = pmax(m_loc)
        sum_exp = psum(l_loc.reshape(stats_shape) * jnp.exp(m_loc - m))
        # rebase the raw target logit to the global max *on the owning shard
        # only*: a label no shard owns (ignore/padding sentinel) must yield
        # t_logit == 0, matching the jnp path's masked gather
        t_logit = psum(t_raw.reshape(stats_shape) - jnp.where(in_shard, m, 0.0))
        sum_logits = (psum(s_raw.reshape(stats_shape)) - vocab * m
                      if label_smoothing > 0 else None)
    else:
        lf = logits.astype(jnp.float32)

        # 1. global max for stability
        m = pmax(jnp.max(lf, axis=-1))
        lf = lf - m[..., None]

        # 2. target logit: only the owning shard contributes
        t_logit = jnp.take_along_axis(lf, t_idx[..., None], axis=-1)[..., 0]
        t_logit = psum(jnp.where(in_shard, t_logit, 0.0))

        # 3. global sum-exp
        sum_exp = psum(jnp.sum(jnp.exp(lf), axis=-1))
        sum_logits = psum(jnp.sum(lf, axis=-1)) if label_smoothing > 0 else None

    log_sum_exp = jnp.log(sum_exp)
    loss = log_sum_exp - t_logit
    if label_smoothing > 0:
        # reference's smoothing branch (:68-77): loss = (1-ε)·nll + ε/V · Σ nll_i
        smooth = label_smoothing / vocab
        loss = (1.0 - label_smoothing) * loss + smooth * (
            vocab * log_sum_exp - sum_logits
        )

    # Residuals: the input logits (aliasing the unembedding gemm's output —
    # no extra (..., V/tp) write) plus the O(tokens) stats; backward
    # recomputes the softmax the way ops/xentropy.py does. Saving the fp32
    # softmax instead would add a residual 2× the logits' size at bf16 and a
    # full extra HBM pass to write it.
    return loss, (logits, m, sum_exp, in_shard, t_idx)


def _vce_bwd(label_smoothing, axis_name, impl, res, dloss):
    del impl  # backward recomputes from residuals; no kernel dispatch
    logits, m, sum_exp, in_shard, t_idx = res
    per = logits.shape[-1]
    sf = jnp.exp(logits.astype(jnp.float32) - m[..., None]) / sum_exp[..., None]
    onehot = jax.nn.one_hot(t_idx, per, dtype=jnp.float32) * in_shard[..., None]
    if label_smoothing > 0:
        vocab = per * (1 if axis_name is None else jax.lax.axis_size(axis_name))
        grad = sf - (1.0 - label_smoothing) * onehot - label_smoothing / vocab
    else:
        grad = sf - onehot
    return (grad * dloss[..., None]).astype(logits.dtype), None


vocab_parallel_cross_entropy.defvjp(_vce_fwd, _vce_bwd)


def masked_mean(losses: jax.Array, loss_mask=None) -> jax.Array:
    """Mean per-token loss, optionally weighted by a 0/1 ``loss_mask``
    (1 = count) — the reduction every loss head shares (reference
    ``pipeline_parallel/utils.py:303``: EOD/padding positions excluded
    the same way). The 1.0 denominator floor keeps an all-masked batch
    finite (loss 0) instead of NaN."""
    if loss_mask is None:
        return jnp.mean(losses)
    m = loss_mask.astype(losses.dtype)
    return jnp.sum(losses * m) / jnp.maximum(jnp.sum(m), 1.0)
