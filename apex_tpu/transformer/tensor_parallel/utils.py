"""TP utility helpers — parity with ``apex/transformer/tensor_parallel/utils.py``."""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def ensure_divisibility(numerator: int, denominator: int) -> None:
    if numerator % denominator != 0:
        raise ValueError(f"{numerator} is not divisible by {denominator}")


def divide(numerator: int, denominator: int) -> int:
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_along_last_dim(x: jax.Array, num_partitions: int) -> Tuple[jax.Array, ...]:
    """Static split (``utils.py``'s helper of the same name)."""
    chunk = divide(x.shape[-1], num_partitions)
    return tuple(
        jax.lax.slice_in_dim(x, i * chunk, (i + 1) * chunk, axis=x.ndim - 1)
        for i in range(num_partitions)
    )


class VocabUtility:
    """Vocab-shard index ranges (``tensor_parallel/utils.py`` VocabUtility)."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(
        per_partition_vocab_size: int, rank, world_size: int
    ):
        first = rank * per_partition_vocab_size
        return first, first + per_partition_vocab_size

    @staticmethod
    def vocab_range_from_global_vocab_size(global_vocab_size: int, rank, world_size: int):
        per_partition = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per_partition, rank, world_size
        )
