"""Model-parallel RNG streams + activation checkpointing.

Re-design of ``apex/transformer/tensor_parallel/random.py``. The reference
maintains named CUDA RNG states (``CudaRNGStatesTracker``, ``random.py:120``)
so dropout inside TP regions differs per rank while data-parallel replicas
agree, and an activation-checkpoint Function that saves/restores those states
around recompute (``CheckpointFunction`` ``random.py:233``).

In JAX both problems are key-plumbing:

* a *named stream* is ``jax.random.fold_in`` of a base key with a stream id;
* the model-parallel stream folds in ``axis_index('tp')`` so TP ranks draw
  different bits (``model_parallel_cuda_manual_seed``'s
  ``seed + 2718 + tp_rank`` offset, ``random.py:195-230``);
* recompute with identical randomness is ``jax.checkpoint`` — keys are
  explicit inputs, so the recomputed dropout is bitwise-identical by
  construction; no state save/restore exists to get wrong.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.parallel import mesh as mesh_lib

_MODEL_PARALLEL_RNG = "model-parallel-rng"  # reference stream name (random.py:74)
_DATA_PARALLEL_OFFSET = 0
_MODEL_PARALLEL_OFFSET = 2718  # reference's tensor-model-parallel seed offset


def model_parallel_rng_key(
    key: jax.Array, axis_name: str = mesh_lib.TENSOR_AXIS
) -> jax.Array:
    """Key for the 'model-parallel-rng' stream: distinct per tp rank,
    shared across dp replicas. Must run inside shard_map."""
    key = jax.random.fold_in(key, _MODEL_PARALLEL_OFFSET)
    return jax.random.fold_in(key, jax.lax.axis_index(axis_name))


class RngTracker:
    """API-parity wrapper over key folding (``CudaRNGStatesTracker``,
    ``random.py:120-193``): ``add`` registers named streams, ``fork``
    yields the stream's key for a region."""

    def __init__(self, base_key: Optional[jax.Array] = None):
        self._streams: dict = {}
        self._base = base_key

    def reset(self):
        self._streams.clear()

    def get_states(self):
        return dict(self._streams)

    def set_states(self, states):
        self._streams = dict(states)

    def add(self, name: str, seed: int):
        if name in self._streams:
            raise RuntimeError(f"rng stream {name} already exists")
        self._streams[name] = jax.random.PRNGKey(seed)

    def key(self, name: str = _MODEL_PARALLEL_RNG, fold_axis: Optional[str] = None):
        if name not in self._streams:
            raise RuntimeError(f"rng stream {name} is not added")
        k = self._streams[name]
        if fold_axis is not None:
            k = jax.random.fold_in(k, jax.lax.axis_index(fold_axis))
        return k


_TRACKER = RngTracker()


def get_rng_tracker() -> RngTracker:
    """``get_cuda_rng_tracker`` analog (``random.py:195-198``)."""
    return _TRACKER


def model_parallel_seed(seed: int, tracker: Optional[RngTracker] = None) -> None:
    """``model_parallel_cuda_manual_seed`` (``random.py:200-230``): installs
    the default + model-parallel streams."""
    t = tracker or _TRACKER
    t.reset()
    t.add("data-parallel-rng", seed + _DATA_PARALLEL_OFFSET)
    t.add(_MODEL_PARALLEL_RNG, seed + _MODEL_PARALLEL_OFFSET)


def checkpoint(fn: Callable, *args, policy=None, prevent_cse: bool = True):
    """Activation checkpointing (``CheckpointFunction``/``checkpoint()``,
    ``random.py:233-320``): recompute ``fn`` in backward. RNG keys passed as
    arguments are replayed exactly; ``policy`` is a
    ``jax.checkpoint_policies`` entry (the analog of the reference's
    ``distribute_saved_activations`` memory knob — what to keep vs
    recompute)."""
    wrapped = jax.checkpoint(fn, policy=policy, prevent_cse=prevent_cse)
    return wrapped(*args)


def checkpoint_wrapper(fn: Callable, policy=None) -> Callable:
    """Decorator form, for wrapping transformer blocks."""
    return jax.checkpoint(fn, policy=policy)
