"""Tensor-model-parallel layers and collectives.

Re-design of ``apex/transformer/tensor_parallel/__init__.py``. All functions
here are written to run *inside* ``shard_map`` with the mesh's ``tp`` axis
bound — the SPMD analog of "executing on one TP rank's process".
"""

from apex_tpu.transformer.tensor_parallel.mappings import (  # noqa: F401
    copy_to_tensor_model_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.layers import (  # noqa: F401
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from apex_tpu.transformer.tensor_parallel.cross_entropy import (  # noqa: F401
    masked_mean,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.random import (  # noqa: F401
    RngTracker,
    checkpoint,
    get_rng_tracker,
    model_parallel_rng_key,
)
from apex_tpu.transformer.tensor_parallel.utils import (  # noqa: F401
    VocabUtility,
    divide,
    split_tensor_along_last_dim,
)
from apex_tpu.transformer.tensor_parallel.data import broadcast_data  # noqa: F401
