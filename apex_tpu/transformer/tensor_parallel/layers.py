"""Tensor-parallel linear and embedding layers.

Re-design of ``apex/transformer/tensor_parallel/layers.py``:

* ``ColumnParallelLinear`` (``layers.py:377-538``) — weight sharded along the
  output dim; forward is a local matmul on a copied input; optional
  all-gather of the output.
* ``RowParallelLinear`` (``layers.py:541-663``) — weight sharded along the
  input dim; forward is a local matmul followed by an all-reduce.
* ``VocabParallelEmbedding`` (``layers.py:154-256``) — vocabulary sharded;
  out-of-shard token ids are masked to zero locally, then psum combines.

The reference's ``LinearWithGradAccumulationAndAsyncAllreduce``
(``layers.py:259-315``) overlaps the input-grad all-reduce with the wgrad
GEMM on a side stream; under XLA the same overlap comes from the
latency-hiding scheduler — the ``copy_to``/``reduce_from`` mappings place
the collectives, XLA schedules them. Its fused ``main_grad`` accumulation
(``fused_weight_gradient_mlp_cuda``) corresponds to grad-accumulation buffer
donation in the training step (see pipeline_parallel.schedules).

Layers are plain param-pytree modules meant to be called inside
``shard_map`` with the ``tp`` axis bound; ``init`` takes the *tp rank* so
each shard initializes its own slice (the reference's
``_initialize_affine_weight_gpu`` gives each rank a distinct RNG stream —
here the key is folded with the rank, see random.py).

Sequence parallelism (Megatron-style, absent in the reference — SURVEY.md
§2.3): ``sequence_parallel=True`` on either linear switches the boundary
collectives to all-gather/reduce-scatter over the sequence dim, the SP
extension the survey calls out as new work.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer.tensor_parallel import mappings
from apex_tpu.transformer.tensor_parallel.utils import VocabUtility, divide


def _default_init(key, shape, dtype):
    fan_in = shape[-1] if len(shape) > 1 else shape[0]
    bound = 1.0 / jnp.sqrt(fan_in)
    return jax.random.uniform(key, shape, dtype, -bound, bound)


# --- sequence-parallel collectives (SP extension) -----------------------------

# trace-time collective accounting (cf. ``all_reduce_gradients``) — the
# TP axis shows up in ``monitor report``'s collective traffic; one shim,
# shared with the blocking mappings
_count_collective = mappings._count_collective


def _check_seq_axis(x, seq_dim, layer, op):
    if not 0 <= seq_dim < x.ndim:
        raise ValueError(
            f"{layer}(sequence_parallel=True): seq_dim={seq_dim} is not an "
            f"axis of the activation (shape {x.shape}) — the {op} would "
            f"die inside XLA; pass seq_dim=0 for (s, b, h) or 1 for "
            f"(b, s, h)")


def _sp_all_gather_seq(x, axis_name, seq_dim=0, layer="ColumnParallelLinear"):
    """Gather the sequence dim entering a TP matmul (Megatron-SP boundary).

    ``x`` is this rank's sequence SHARD (any local length gathers); the
    eager check here is the seq_dim itself — an out-of-range dim fails
    deep inside XLA's tiled all-gather naming neither layer nor knob."""
    _check_seq_axis(x, seq_dim, layer, "tiled all_gather")
    _count_collective("all_gather", x, axis_name)
    return jax.lax.all_gather(x, axis_name, axis=seq_dim, tiled=True)


def _sp_reduce_scatter_seq(x, axis_name, seq_dim=0,
                           layer="RowParallelLinear"):
    """Reduce-scatter the sequence dim leaving a TP matmul. Validated
    eagerly: an uneven ``tiled=True`` scatter otherwise fails deep inside
    XLA with a shape error that names neither the layer nor the knob."""
    _check_seq_axis(x, seq_dim, layer, "tiled psum_scatter")
    size = jax.lax.axis_size(axis_name)
    if x.shape[seq_dim] % size:
        raise ValueError(
            f"{layer}(sequence_parallel=True): sequence extent "
            f"{x.shape[seq_dim]} (axis {seq_dim} of {x.shape}) is not "
            f"divisible by the {axis_name!r} axis size {size} — the SP "
            f"reduce-scatter splits the sequence per rank; pad the "
            f"sequence to a multiple of {size} or disable "
            f"sequence_parallel on this linear")
    _count_collective("psum_scatter", x, axis_name)
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=seq_dim, tiled=True)


@dataclasses.dataclass
class ColumnParallelLinear:
    """Y = X A^T + b with A sharded along output (rows of the torch-layout
    weight). ``gather_output`` matches the reference flag (default True
    there; Megatron uses False + downstream RowParallel)."""

    input_size: int
    output_size: int
    bias: bool = True
    gather_output: bool = False
    sequence_parallel: bool = False
    seq_dim: int = 0  # which activation axis is sequence (0 for (s,b,h), 1 for (b,s,h))
    init_method: Callable = _default_init
    axis_name: str = mesh_lib.TENSOR_AXIS
    tp_size: int = 1
    # ring-overlapped boundary collectives (ops.collective_matmul): with
    # SP, the input all-gather becomes the bidirectional ag→matmul ring;
    # without, the backward's dx psum rides copy_matmul's overlapped ring.
    # The blocking path (False) is kept as the parity oracle.
    overlap_comm: bool = False

    def __post_init__(self):
        if self.overlap_comm and self.gather_output:
            raise ValueError(
                "overlap_comm rides gather_output=False (the Megatron "
                "Column→Row pairing); the output-gather boundary has no "
                "overlapped form")

    @property
    def output_size_per_partition(self) -> int:
        return divide(self.output_size, self.tp_size)

    def init(self, key, rank: int = 0, dtype=jnp.float32) -> dict:
        """Per-shard params; ``rank`` folds into the key so shards differ
        (the reference's model-parallel RNG stream, random.py:200)."""
        k = jax.random.fold_in(key, rank)
        params = {
            "weight": self.init_method(
                k, (self.output_size_per_partition, self.input_size), dtype
            )
        }
        if self.bias:
            params["bias"] = jnp.zeros((self.output_size_per_partition,), dtype)
        return params

    def gather_input(self, x: jax.Array) -> jax.Array:
        """The input-side collective of ``__call__`` (SP all-gather or TP
        copy), exposed for callers that fuse the matmul differently — e.g.
        the head-batched QKV einsum in ``models/gpt.py``, which needs the
        gathered activations but emits (b, heads, s, d) directly."""
        if self.sequence_parallel:
            return _sp_all_gather_seq(x, self.axis_name, self.seq_dim)
        return mappings.copy_to_tensor_model_parallel_region(x, self.axis_name)

    def __call__(self, params: dict, x: jax.Array) -> jax.Array:
        if self.overlap_comm and self.axis_name is not None and self.tp_size > 1:
            from apex_tpu.ops import collective_matmul as cm

            fn = (cm.all_gather_matmul if self.sequence_parallel
                  else cm.copy_matmul)
            y = fn(x, params["weight"], axis_name=self.axis_name,
                   seq_dim=self.seq_dim)
        else:
            x = self.gather_input(x)
            y = jnp.dot(x, params["weight"].T)
        if self.bias:
            y = y + params["bias"]
        if self.gather_output:
            y = mappings.gather_from_tensor_model_parallel_region(y, self.axis_name)
        return y

    def headwise(self, params: dict, x: jax.Array, groups: int) -> jax.Array:
        """Head-batched projection: (b, s, hidden) -> (b, groups, s, d) with
        the local output features viewed as (groups, d). Emits the attention
        layout straight from the MXU — no per-head transpose (at head_dim
        128 the batched contraction fills all MXU lanes, so this costs
        nothing in GEMM efficiency; measured 0.62 vs 1.31 ms/layer fwd+bwd
        on the flagship bench shape)."""
        if self.gather_output:
            raise ValueError("headwise projection requires gather_output=False")
        xg = self.gather_input(x)
        d = divide(self.output_size_per_partition, groups)
        w = params["weight"].reshape(groups, d, xg.shape[-1])
        y = jnp.einsum("bsH,gdH->bgsd", xg, w)
        if self.bias:
            y = y + params["bias"].reshape(groups, 1, d)
        return y


@dataclasses.dataclass
class RowParallelLinear:
    """Y = X A^T + b with A sharded along input; forward all-reduces the
    partial products (``layers.py:541-663``)."""

    input_size: int
    output_size: int
    bias: bool = True
    input_is_parallel: bool = True
    sequence_parallel: bool = False
    seq_dim: int = 0
    init_method: Callable = _default_init
    axis_name: str = mesh_lib.TENSOR_AXIS
    tp_size: int = 1
    # ring-overlapped epilogue (ops.collective_matmul): with SP the
    # matmul→reduce-scatter transpose ring, without the reduce-scatter
    # ring + chunk all-gather. Blocking path kept as the parity oracle.
    overlap_comm: bool = False

    @property
    def input_size_per_partition(self) -> int:
        return divide(self.input_size, self.tp_size)

    def init(self, key, rank: int = 0, dtype=jnp.float32) -> dict:
        k = jax.random.fold_in(key, rank)
        params = {
            "weight": self.init_method(
                k, (self.output_size, self.input_size_per_partition), dtype
            )
        }
        if self.bias:
            # bias is replicated; added after the reduce (layers.py:663)
            params["bias"] = jnp.zeros((self.output_size,), dtype)
        return params

    def reduce_output(self, y: jax.Array) -> jax.Array:
        """The output-side collective of ``__call__`` (TP partial-product
        reduce or SP reduce-scatter), exposed for callers that fuse the
        matmul differently (cf. ``ColumnParallelLinear.gather_input``).
        The bias, which the reference adds *after* the reduce
        (``layers.py:663``), stays with the caller."""
        if self.sequence_parallel:
            return _sp_reduce_scatter_seq(y, self.axis_name, self.seq_dim)
        return mappings.reduce_from_tensor_model_parallel_region(y, self.axis_name)

    def __call__(self, params: dict, x: jax.Array) -> jax.Array:
        if not self.input_is_parallel:
            x = mappings.scatter_to_tensor_model_parallel_region(x, self.axis_name)
        if self.overlap_comm and self.axis_name is not None and self.tp_size > 1:
            from apex_tpu.ops import collective_matmul as cm

            fn = (cm.matmul_reduce_scatter if self.sequence_parallel
                  else cm.matmul_all_reduce)
            y = fn(x, params["weight"], axis_name=self.axis_name,
                   seq_dim=self.seq_dim)
        else:
            y = jnp.dot(x, params["weight"].T)
            y = self.reduce_output(y)
        if self.bias:
            y = y + params["bias"]
        return y

    def headwise(self, params: dict, x: jax.Array) -> jax.Array:
        """Head-batched output projection: (b, h, s, d) with h*d equal to
        this shard's input features -> (b, s, output). The (heads, d)
        contraction replaces transpose-back-then-GEMM; the reduce/SP
        epilogue and the post-reduce bias order (``layers.py:663``) are the
        same as ``__call__``."""
        h, d = x.shape[1], x.shape[3]
        if h * d != self.input_size_per_partition:
            raise ValueError(
                f"headwise input ({h}x{d}) != input features per partition "
                f"({self.input_size_per_partition})")
        w = params["weight"].reshape(self.output_size, h, d)
        y = jnp.einsum("bhsd,Hhd->bsH", x, w)
        y = self.reduce_output(y)
        if self.bias:
            y = y + params["bias"]
        return y


@dataclasses.dataclass
class VocabParallelEmbedding:
    """Embedding with the vocabulary dimension sharded
    (``layers.py:154-256``): each shard holds rows
    [rank·V/tp, (rank+1)·V/tp); out-of-range ids produce zeros locally and
    the psum combines shards."""

    num_embeddings: int
    embedding_dim: int
    init_method: Callable = _default_init
    axis_name: str = mesh_lib.TENSOR_AXIS
    tp_size: int = 1

    @property
    def num_embeddings_per_partition(self) -> int:
        return divide(self.num_embeddings, self.tp_size)

    def init(self, key, rank: int = 0, dtype=jnp.float32) -> dict:
        k = jax.random.fold_in(key, rank)
        return {
            "weight": self.init_method(
                k, (self.num_embeddings_per_partition, self.embedding_dim), dtype
            )
        }

    def __call__(self, params: dict, token_ids: jax.Array) -> jax.Array:
        if self.axis_name is None or self.tp_size == 1:
            # same out-of-range semantics as the sharded path: invalid ids
            # yield zero vectors, never a clamped row
            valid = (token_ids >= 0) & (token_ids < self.num_embeddings)
            emb = jnp.take(params["weight"], jnp.where(valid, token_ids, 0), axis=0)
            return jnp.where(valid[..., None], emb, 0.0)
        rank = jax.lax.axis_index(self.axis_name)
        per = self.num_embeddings_per_partition
        start = rank * per
        local = token_ids - start
        in_shard = (local >= 0) & (local < per)
        local = jnp.where(in_shard, local, 0)
        emb = jnp.take(params["weight"], local, axis=0)
        emb = jnp.where(in_shard[..., None], emb, 0.0)
        return mappings.reduce_from_tensor_model_parallel_region(emb, self.axis_name)
