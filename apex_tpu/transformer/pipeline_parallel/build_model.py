"""Stage partitioner: run the flagship ``GPTModel`` through the pipeline.

Equivalent of the reference's ``build_model`` + pre/post-process placement
(``apex/transformer/pipeline_parallel/schedules/common.py:29-148``): there,
``build_model`` constructs one module (or ``virtual_pipeline`` chunk
modules) per pipeline rank, with ``pre_process`` (embedding) true only on
the first stage and ``post_process`` (loss head) only on the last, and the
schedules thread tensors between them over NCCL p2p.

The TPU-native formulation keeps one SPMD program: :class:`GPTPipeline`
*partitions the parameters* instead of the module —

* ``partition()`` reshapes the model's stacked ``(num_layers, ...)`` layer
  params into per-stage / per-virtual-chunk slices whose leading axis is
  sharded over the ``pp`` mesh axis (virtual stage ``k = c·pp + rank`` runs
  global layers ``[k·Lc, (k+1)·Lc)`` — the reference's interleaved
  assignment, ``parallel_state.py:135-145``, is a plain reshape here);
* pre-process (vocab-parallel embedding + positions) is *computed*
  replicated on every pp rank — a cheap gather — but its parameters only
  receive cotangents through pp rank 0's microbatch injection, which is the
  SPMD image of "embedding lives on the first stage";
* post-process (final LN, tied unembedding, vocab-parallel cross entropy)
  likewise runs replicated but the loss is broadcast from rank 0 with a
  masked transpose, so head/tied-embedding gradients are exactly the first
  stage's — one ``psum`` over pp replicates them (the reference needs a
  dedicated embedding all-reduce group for the tied weight,
  ``parallel_state.py:338-375``; here it is the same psum).

Everything of the shipped model crosses the schedule: flash attention,
grouped-query kv, Megatron-SP boundary collectives, the remat policies, and
vocab-parallel CE with its fused-statistics kernel.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import fused_layer_norm
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer import tensor_parallel as tp_lib
from apex_tpu.transformer.moe import ROUTER_AUX_ZEROS, router_aux_zeros
from apex_tpu.transformer.pipeline_parallel import schedules

PyTree = Any


def build_model(
    model,
    *,
    pipeline_model_parallel_size: Optional[int] = None,
    virtual_chunks: Optional[int] = None,
    pp_axis: str = mesh_lib.PIPELINE_AXIS,
) -> "GPTPipeline":
    """Reference-named frontend (``schedules/common.py:29``): build the
    pipeline decomposition of ``model`` from the installed mesh (or explicit
    sizes)."""
    if pipeline_model_parallel_size is None:
        pipeline_model_parallel_size = \
            mesh_lib.get_pipeline_model_parallel_world_size()
        if virtual_chunks is None:
            virtual_chunks = \
                mesh_lib.get_virtual_pipeline_model_parallel_world_size()
    return GPTPipeline(
        model, pipeline_model_parallel_size,
        virtual_chunks=virtual_chunks or 1, pp_axis=pp_axis,
    )


@dataclasses.dataclass
class GPTPipeline:
    """Pipeline-parallel execution of a :class:`~apex_tpu.models.GPTModel`.

    ``partition``/``unpartition`` convert between the model's native param
    pytree and the stage-sharded one; :meth:`loss_and_grads` is the full
    fwd+bwd (to be called inside ``shard_map`` with the ``pp`` — and, when
    ``model.config.tp_size > 1``, ``tp`` — axes bound), returning the same
    loss as ``model.loss_fn`` on the concatenated microbatches, with
    gradients laid out like the partitioned params.
    """

    model: Any
    pp: int
    virtual_chunks: int = 1
    pp_axis: str = mesh_lib.PIPELINE_AXIS

    def __post_init__(self):
        c = self.model.config
        v = self.virtual_chunks
        if self.pp < 2:
            raise ValueError("GPTPipeline needs pipeline_model_parallel_size"
                             f" >= 2, got {self.pp}")
        if c.num_layers % (self.pp * v):
            raise ValueError(
                f"num_layers ({c.num_layers}) must be divisible by pp*v "
                f"({self.pp}*{v})")
        # dropout: supported — per-application keys fold from
        # (tick, pp rank, layer-in-chunk); pass `key` to loss_and_grads.
        # MoE: supported — the schedule's validity-masked aux accumulator
        # threads the router losses differentiably (`aux_init`), and with
        # ``config.ep_axis`` set the expert banks' E axis is sharded over
        # ep (param_specs) while the two all_to_alls run stage-local inside
        # the scanned tick — ep composes with pp/tp/dp in ONE program.

    @property
    def layers_per_chunk(self) -> int:
        return self.model.config.num_layers // (self.pp * self.virtual_chunks)

    # --- parameter layout -----------------------------------------------------

    def partition(self, params: PyTree) -> PyTree:
        """Model params (layers stacked ``(L, ...)``) → pipeline params:

        * ``stages``: layer leaves reshaped ``(pp, Lc, ...)`` (or
          ``(v, pp, Lc, ...)`` interleaved) — shard the ``pp`` axis;
        * ``embed``: embedding + positions (replicate over pp);
        * ``head``: final LN (replicate over pp).

        Works per TP shard: apply under ``jax.vmap`` for params carrying a
        leading ``(tp,)`` axis (see ``models.gpt.shard_params_for_tp``).
        """
        pp, v, lc = self.pp, self.virtual_chunks, self.layers_per_chunk

        def split(x):
            y = x.reshape(v, pp, lc, *x.shape[1:])
            return y[0] if v == 1 else y

        return {
            "embed": {"embedding": params["embedding"],
                      "pos_embedding": params["pos_embedding"]},
            "stages": jax.tree.map(split, params["layers"]),
            "head": {"lnf_w": params["lnf_w"], "lnf_b": params["lnf_b"]},
        }

    def unpartition(self, pipe_params: PyTree) -> PyTree:
        """Inverse of :meth:`partition` (checkpoint compatibility: saved
        pipelines round-trip to the plain model layout)."""
        pp, v, lc = self.pp, self.virtual_chunks, self.layers_per_chunk

        def join(x):
            y = x[None] if v == 1 else x
            return y.reshape(pp * v * lc, *y.shape[3:])

        e, h = pipe_params["embed"], pipe_params["head"]
        return {
            "embedding": e["embedding"],
            "pos_embedding": e["pos_embedding"],
            "layers": jax.tree.map(join, pipe_params["stages"]),
            "lnf_w": h["lnf_w"], "lnf_b": h["lnf_b"],
        }

    def param_specs(self, pipe_params: PyTree, *leading) -> PyTree:
        """PartitionSpecs matching a :meth:`partition` output: stage leaves
        sharded over ``pp`` on their stage axis, embed/head replicated over
        pp. With ``config.ep_axis`` set, the expert banks' E axis (just
        after the per-stage layer axis) additionally shards over ep —
        inside shard_map each device then holds its stage's slice of ITS
        experts only. ``leading`` axis names (e.g. ``'tp'``) are prepended
        to every spec for trees carrying extra leading mesh axes (the
        ``shard_params_for_tp`` → ``jax.vmap(partition)`` composition)."""
        from jax.sharding import PartitionSpec as P
        pre = (*leading, *((None,) if self.virtual_chunks > 1 else ()))
        stage_spec = P(*pre, self.pp_axis)
        ep_ax = getattr(self.model.config, "ep_axis", None)
        expert_spec = P(*pre, self.pp_axis, None, ep_ax)
        rep = P(*leading)

        def stage_leaf(path, _):
            names = {q.key for q in path if hasattr(q, "key")}
            if (ep_ax is not None and "moe" in names
                    and names & {"w1", "b1", "w2", "b2"}):
                return expert_spec
            return stage_spec

        return {
            "embed": jax.tree.map(lambda _: rep, pipe_params["embed"]),
            "stages": jax.tree_util.tree_map_with_path(
                stage_leaf, pipe_params["stages"]),
            "head": jax.tree.map(lambda _: rep, pipe_params["head"]),
        }

    # --- forward pieces (all run inside shard_map) ----------------------------

    def _embed(self, ep, tokens):
        """(M, b, s) int tokens → (M, b, s[/tp], hid) stage-0 activations.
        Computed on every pp rank; only rank 0's injection into the
        pipeline consumes cotangents (pre-process placement)."""
        model = self.model
        M, b, s = tokens.shape
        x = model.embedding(ep["embedding"], tokens.reshape(M * b, s))
        if getattr(model.config, "cp_axis", None) is not None:
            x = x + ep["pos_embedding"][model._cp_positions(s)]
        else:
            x = x + ep["pos_embedding"][:s]
        if model.sp:
            x = model._sp_scatter(x)
        return x.reshape(M, b, *x.shape[1:])

    def _stage(self, chunk_params, x, t=None, key=None):
        """One virtual stage: ``layers_per_chunk`` full transformer blocks
        (the model's own remat policy per block). With ``key`` (dropout),
        each block folds a distinct key from (tick, pp rank, layer) — the
        (microbatch, stage) identity the schedule's tick index carries.
        MoE models return ``(x, summed router aux)`` for the schedule's
        masked accumulator."""
        block = self.model.wrapped_block()
        moe = self.model.moe
        if key is not None:
            rank = jax.lax.axis_index(self.pp_axis)
            key = jax.random.fold_in(jax.random.fold_in(key, t), rank)

        def body(carry, layer_i):
            x, aux = carry
            layer, i = layer_i
            k = None if key is None else jax.random.fold_in(key, i)
            out = block(layer, x, k)
            if moe:
                x, a = out
                aux = jax.tree.map(jnp.add, aux, a)
            else:
                x = out
            return (x, aux), None

        n = jax.tree.leaves(chunk_params)[0].shape[0]
        aux0 = router_aux_zeros() if moe else jnp.zeros(())
        (x, aux), _ = jax.lax.scan(
            body, (x, aux0), (chunk_params, jnp.arange(n)))
        return (x, aux) if moe else x

    def _head_loss(self, hp, ep, outs, targets, loss_mask):
        """Final LN → tied unembedding → vocab-parallel CE → masked mean.
        ``outs`` are valid on pp rank 0 only; the caller broadcasts the
        resulting loss with a masked transpose (post-process placement)."""
        model = self.model
        M, b = outs.shape[0], outs.shape[1]
        x = outs.reshape(M * b, *outs.shape[2:])
        if model.sp:
            x = model._sp_gather(x)
        x = fused_layer_norm(x, hp["lnf_w"], hp["lnf_b"])
        logits = model.unembed({"embedding": ep["embedding"]}, x)
        losses = tp_lib.vocab_parallel_cross_entropy(
            logits, targets.reshape(M * b, -1), axis_name=model.axis)
        lm = None if loss_mask is None else loss_mask.reshape(M * b, -1)
        return tp_lib.masked_mean(losses, lm)

    # --- the full step --------------------------------------------------------

    def loss_and_grads(
        self,
        pipe_params: PyTree,
        tokens: jax.Array,
        targets: jax.Array,
        *,
        loss_mask: Optional[jax.Array] = None,
        accum_dtype=jnp.float32,
        dp_axis: Optional[str] = None,
        key: Optional[jax.Array] = None,
        return_aux: bool = False,
        schedule: Optional[str] = None,
        overlap_p2p: Optional[bool] = None,
    ):
        """Pipelined forward+backward over ``(M, b, s)`` microbatched
        tokens. Must run inside ``shard_map``; ``pipe_params`` are this
        device's local slices (stage leaves ``(Lc, ...)``, or
        ``(v, Lc, ...)`` interleaved). Returns ``(loss, grads)`` with grads
        shaped like ``pipe_params`` in ``accum_dtype`` (fp32 main-grad
        accumulation across microbatch ticks, cf.
        ``schedules._main_grad_cast``). ``dp_axis`` adds the data-parallel
        pmean of loss and grads; it may be a tuple of axes — pass
        ``('dp', 'cp')`` when context parallelism shards the sequence
        (params replicated over cp, per-shard grads partial: cp reduces
        exactly like dp). With ``config.ep_axis`` set the ep axis is
        ALWAYS reduced over (it is a data axis carrying different batch
        rows per shard): loss/replicated-param grads pmean over ep, while
        expert-bank grads — sharded, already group-summed by the a2a
        transpose — are normalized by 1/ep. ``key`` enables dropout (required when
        ``config.dropout > 0``): keys fold per (tick, stage, layer) so
        every (microbatch, layer) application draws a distinct mask, and
        when ``dp_axis`` is given the dp rank folds in here too — data-
        parallel replicas draw decorrelated masks without caller effort.
        Probs dropout rides IN-KERNEL on every flash path (counter-hash
        masks, O(block) memory — ``ops.pallas.attention.dropout_keep``),
        so ``dropout > 0`` keeps O(s) attention memory at long sequence.

        ``schedule``/``overlap_p2p`` default to the model's
        ``config.pp_schedule``/``config.overlap_p2p`` — ``"zb"`` runs the
        zero-bubble split backward (dW deferred into a real-items-only
        sweep), ``overlap_p2p=True`` issues every stage-boundary ppermute
        before the stage body it is independent of (see
        ``schedules.pipeline_spmd_forward``). All pre/post-process
        placement, MoE aux accumulation, dropout keying, and the fp32
        main-grad contract are schedule-independent."""
        model, v = self.model, self.virtual_chunks
        if schedule is None:
            schedule = getattr(model.config, "pp_schedule", "1f1b")
        if overlap_p2p is None:
            overlap_p2p = getattr(model.config, "overlap_p2p", False)
        ep_ax = getattr(model.config, "ep_axis", None)
        if model.config.dropout > 0 and key is None:
            raise ValueError(
                "config.dropout > 0 requires a `key` for loss_and_grads")
        if key is not None and dp_axis is not None:
            # dp_axis may be a tuple of data-like axes (e.g. ('dp', 'cp')
            # — context parallelism reduces like dp: replicated params,
            # per-shard partial grads)
            for ax in (dp_axis if isinstance(dp_axis, (tuple, list))
                       else (dp_axis,)):
                key = jax.random.fold_in(key, jax.lax.axis_index(ax))
        if key is not None and ep_ax is not None:
            # ep is a data axis (each ep shard holds different batch rows)
            key = jax.random.fold_in(key, jax.lax.axis_index(ep_ax))
        e_acc, e_down = schedules._main_grad_cast(
            pipe_params["embed"], accum_dtype)
        s_acc, s_down = schedules._main_grad_cast(
            pipe_params["stages"], accum_dtype)
        h_acc, h_down = schedules._main_grad_cast(
            pipe_params["head"], accum_dtype)

        M = tokens.shape[0]

        def full_loss(p):
            emb = self._embed(e_down(p["embed"]), tokens)
            out = schedules.pipeline_spmd_forward(
                lambda cp, x, t: self._stage(s_down(cp), x, t, key),
                p["stages"], emb,
                axis_name=self.pp_axis, virtual_chunks=v,
                remat=model.config.remat, broadcast_outputs=False,
                tick_arg=True,
                aux_init=ROUTER_AUX_ZEROS if model.moe else None,
                schedule=schedule, overlap_p2p=overlap_p2p,
            )
            if model.moe:
                outs, aux_local = out
                # per-rank masked sums over this rank's real work, totaled
                # over pp with the psum-forward/IDENTITY-backward mapping:
                # a raw lax.psum here would transpose conservatively to
                # another psum (check_vma=False) and scale every aux-path
                # gradient by pp_size (review r3; same hazard
                # _broadcast_from_first's custom VJP exists to avoid)
                aux = jax.tree.map(
                    lambda x: tp_lib.reduce_from_tensor_model_parallel_region(
                        x, self.pp_axis) / (M * model.config.num_layers),
                    aux_local)
            else:
                outs, aux = out, None
            loss = self._head_loss(
                h_down(p["head"]), e_down(p["embed"]), outs, targets,
                loss_mask)
            # all pre/post-process parameter cotangents mask to pp rank 0
            loss = schedules._broadcast_from_first(loss, self.pp_axis)
            if model.moe:
                c = model.config
                loss = (loss + c.moe_aux_coeff * aux["load_balance_loss"]
                        + c.moe_z_coeff * aux["router_z_loss"])
            return loss, aux

        (loss, aux), g = jax.value_and_grad(full_loss, has_aux=True)(
            {"embed": e_acc, "stages": s_acc, "head": h_acc})

        # embedding/head grads live on pp rank 0 (masked transpose of the
        # loss broadcast); replicate — the reference's embedding-group
        # all-reduce for the tied weight (parallel_state.py:338-375)
        psum_pp = lambda t: jax.tree.map(
            lambda x: jax.lax.psum(x, self.pp_axis), t)
        g["embed"], g["head"] = psum_pp(g["embed"]), psum_pp(g["head"])

        if model.sp:
            # params applied to seq-sharded activations saw one tp rank's
            # slice each (cf. GPTModel.sp_grad_sync)
            synced = model.sp_grad_sync({"layers": g["stages"]})
            g["stages"] = synced["layers"]

        if ep_ax is not None:
            # ep is data parallelism for everything EXCEPT the expert
            # banks: replicated params need the pmean over ep like any
            # data axis, while each ep shard's expert-bank grads already
            # hold the whole ep group's token contributions (the a2a
            # transpose routed them in) — the group-mean objective only
            # needs the 1/ep normalization, no collective.
            ep_size = jax.lax.axis_size(ep_ax)
            loss = jax.lax.pmean(loss, ep_ax)

            def ep_stage_leaf(path, x):
                names = {q.key for q in path if hasattr(q, "key")}
                if "moe" in names and names & {"w1", "b1", "w2", "b2"}:
                    return x / ep_size
                return jax.lax.pmean(x, ep_ax)

            g["stages"] = jax.tree_util.tree_map_with_path(
                ep_stage_leaf, g["stages"])
            g["embed"] = jax.tree.map(
                lambda x: jax.lax.pmean(x, ep_ax), g["embed"])
            g["head"] = jax.tree.map(
                lambda x: jax.lax.pmean(x, ep_ax), g["head"])
            if aux is not None:
                aux = jax.tree.map(lambda x: jax.lax.pmean(x, ep_ax), aux)

        if dp_axis is not None:
            loss = jax.lax.pmean(loss, dp_axis)
            g = jax.tree.map(lambda x: jax.lax.pmean(x, dp_axis), g)
            if aux is not None:
                aux = jax.tree.map(lambda x: jax.lax.pmean(x, dp_axis), aux)
        if return_aux:
            return loss, g, aux
        return loss, g
