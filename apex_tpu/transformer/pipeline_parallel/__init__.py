"""Pipeline parallelism over the mesh ``pp`` axis.

Re-design of ``apex/transformer/pipeline_parallel/``: the reference drives
stage-to-stage tensor exchange with ``batch_isend_irecv`` + CUDA syncs
(``p2p_communication.py:29-67,166``) and hand-written 1F1B/interleaved
schedules (``schedules/``); here stages are SPMD programs over the ``pp``
mesh axis, exchange is ``lax.ppermute``, the schedule is a ``lax.scan`` over
pipeline ticks, and the *backward* schedule falls out of ``jax.grad`` of the
scanned forward (with ``jax.checkpoint`` controlling the memory/recompute
trade-off that 1F1B exists to manage).
"""

from apex_tpu.transformer.pipeline_parallel.p2p_communication import (  # noqa: F401
    recv_backward,
    recv_forward,
    rotate_overlapped,
    send_backward,
    send_forward,
    send_backward_recv_forward,
    send_forward_recv_backward,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (  # noqa: F401
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_zero_bubble,
    get_forward_backward_func,
    pipeline_spmd_forward,
)
from apex_tpu.transformer.pipeline_parallel.build_model import (  # noqa: F401
    GPTPipeline,
    build_model,
)
from apex_tpu.transformer.pipeline_parallel.encoder_decoder import (  # noqa: F401
    forward_backward_pipelining_enc_dec,
    pipeline_spmd_forward_enc_dec,
)
