"""Pipeline/training utilities.

Re-design of ``apex/transformer/pipeline_parallel/utils.py``: microbatch
setup re-exports, LM mask/position helpers, DP loss averaging, memory
reporting, and wall timers. The reference's CUDA-sync timers
(``_timers.py:6-49``) become ``block_until_ready``-fenced timers.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer.microbatches import (  # noqa: F401  (re-exports)
    get_current_global_batch_size,
    get_num_microbatches,
    setup_microbatch_calculator,
    update_num_microbatches,
)


def listify_model(model):
    """``listify_model`` (``utils.py``): virtual-pipeline models are lists."""
    return model if isinstance(model, list) else [model]


def unwrap_model(model, *_):
    """API parity (``utils.py:185``): no wrapper modules exist here."""
    return model


def get_ltor_masks_and_position_ids(
    tokens: jax.Array,
    eod_token: Optional[int] = None,
    reset_position_ids: bool = False,
    reset_attention_mask: bool = False,
    eod_mask_loss: bool = False,
):
    """Left-to-right (causal) masks + positions (``utils.py:303-374``).

    Returns (attention_mask (b,1,s,s) bool — True means *masked out*, like
    the fused-softmax convention; loss_mask (b,s) f32; position_ids (b,s)).
    EOD resets are data-dependent; the reset variants keep the same shapes
    (static under jit) by building masks with cumsum over EOD markers.
    """
    b, s = tokens.shape
    causal = ~(jnp.arange(s)[None, :] <= jnp.arange(s)[:, None])  # True above diag
    att = jnp.broadcast_to(causal, (b, 1, s, s))

    loss_mask = jnp.ones((b, s), jnp.float32)
    if eod_mask_loss and eod_token is not None:
        loss_mask = jnp.where(tokens == eod_token, 0.0, loss_mask)

    position_ids = jnp.broadcast_to(jnp.arange(s), (b, s))
    if (reset_position_ids or reset_attention_mask) and eod_token is not None:
        # document id = number of EODs strictly before each position
        doc = jnp.cumsum((tokens == eod_token).astype(jnp.int32), axis=1)
        doc = jnp.concatenate([jnp.zeros((b, 1), jnp.int32), doc[:, :-1]], axis=1)
        if reset_position_ids:
            # position within the document: index - start-of-document index
            idx = jnp.arange(s)[None, :]
            start = jnp.where(
                doc[:, :, None] == doc[:, None, :], idx[:, None, :], s
            ).min(axis=2)
            position_ids = idx - start
        if reset_attention_mask:
            cross_doc = doc[:, :, None] != doc[:, None, :]
            att = att | cross_doc[:, None, :, :]
    return att, loss_mask, position_ids


def average_losses_across_data_parallel_group(losses: List[jax.Array],
                                              axis_name: str = mesh_lib.DATA_AXIS):
    """``utils.py:242-250``: pmean of stacked losses over dp (inside
    shard_map); outside a mapped context it is a plain mean."""
    stacked = jnp.stack([jnp.asarray(l) for l in losses])
    try:
        return jax.lax.pmean(stacked, axis_name)
    except NameError:
        return stacked


def report_memory(name: str = "") -> str:
    """``report_memory`` (``utils.py:253-263``): per-device live-buffer
    stats from the JAX runtime."""
    lines = []
    for d in jax.local_devices():
        stats = d.memory_stats() or {}
        used = stats.get("bytes_in_use", 0) / 2**20
        peak = stats.get("peak_bytes_in_use", 0) / 2**20
        lines.append(f"[{name}] {d}: in_use {used:.1f} MiB, peak {peak:.1f} MiB")
    report = "\n".join(lines)
    return report


def param_norms(params) -> Dict[str, float]:
    """min/max/norm dump (``utils.py:265-285``)."""
    leaves = jax.tree.leaves(params)
    if not leaves:
        return {}
    flat = jnp.concatenate([jnp.ravel(x).astype(jnp.float32) for x in leaves])
    return {
        "min": float(jnp.min(flat)),
        "max": float(jnp.max(flat)),
        "norm": float(jnp.linalg.norm(flat)),
    }


class _Timer:
    """One named timer (``_timers.py:6-49``); device-fenced via
    block_until_ready on a tracked array when provided."""

    def __init__(self, name):
        self.name = name
        self.elapsed_ = 0.0
        self.started = False
        self.start_time = 0.0

    def start(self, fence=None):
        assert not self.started
        if fence is not None:
            jax.block_until_ready(fence)
        self.start_time = time.perf_counter()
        self.started = True

    def stop(self, fence=None):
        assert self.started
        if fence is not None:
            jax.block_until_ready(fence)
        self.elapsed_ += time.perf_counter() - self.start_time
        self.started = False

    def elapsed(self, reset=True):
        e = self.elapsed_
        if reset:
            self.elapsed_ = 0.0
        return e


class Timers:
    """``get_timers()`` registry (``pipeline_parallel/utils.py:146-157``)."""

    def __init__(self):
        self._timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self._timers:
            self._timers[name] = _Timer(name)
        return self._timers[name]

    def log(self, names=None, normalizer: float = 1.0) -> str:
        names = names or list(self._timers)
        parts = [f"{n}: {self._timers[n].elapsed(reset=True)/normalizer*1000:.2f}ms"
                 for n in names if n in self._timers]
        return " | ".join(parts)


_GLOBAL_TIMERS: Optional[Timers] = None


def get_timers() -> Timers:
    global _GLOBAL_TIMERS
    if _GLOBAL_TIMERS is None:
        _GLOBAL_TIMERS = Timers()
    return _GLOBAL_TIMERS


def get_autoresume():
    """Reference spelling (``pipeline_parallel/utils.py:142-144``). Returns
    the process-wide :class:`apex_tpu.checkpoint.AutoResume` — a working
    SIGTERM-based guard rather than the reference's external-library stub."""
    from apex_tpu import checkpoint as _ckpt

    return _ckpt.get_autoresume()


def check_adlr_autoresume_termination(iteration, state, path,
                                      interval: int = 1) -> bool:
    """Every ``interval`` iterations, checkpoint-and-signal-stop if
    preemption was requested (the reference's commented check,
    ``pipeline_parallel/utils.py:286-300``). Returns True when the caller
    should break its train loop (instead of the reference's ``sys.exit``)."""
    if interval and iteration % interval != 0:
        return False
    return get_autoresume().check_and_save(path, state)
