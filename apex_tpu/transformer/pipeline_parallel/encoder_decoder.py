"""Two-segment (encoder→decoder) pipeline: the split-rank schedule.

Re-design of the reference's encoder-decoder pipeline support: there,
``parallel_state`` carries a ``pipeline_model_parallel_split_rank``
(``parallel_state.py:147-149``) with dedicated embedding groups
(``:338-375``), and the schedules route two tensor streams — decoder
activations plus the encoder output for cross-attention — through the p2p
machinery, with the decoder's own input embedding entering at the split
stage.

SPMD formulation: one program for all stages. The pipeline state is a PAIR
``(h, ctx)`` that rotates the ring together —

* ``h``: the working activations. Stage 0 injects the embedded *encoder*
  microbatch; the split stage swaps in the embedded *decoder* microbatch
  (mid-pipeline pre-process placement);
* ``ctx``: the cross-attention context. Zero through the encoder segment;
  latched to the arriving ``h`` (the completed encoder output) at the
  split stage, then traveling with its microbatch through every decoder
  stage — the SPMD image of the reference forwarding the encoder output
  stage-to-stage alongside the decoder stream.

Stages select encoder vs decoder compute with ``lax.cond`` on the pp rank
(one branch executes per device at runtime — encoder stages don't pay for
decoder FLOPs or vice versa). Every stage holds the union param structure;
the unused fields on the other segment's stages are dead weights (the cost
of program uniformity — pp·v times smaller than the model, irrelevant).

Encoder and decoder activations must share (batch, seq, hidden) shape —
the same uniform-``tensor_shape`` constraint the reference's schedules
impose (``fwd_bwd_pipelining_without_interleaving.py:187``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer.pipeline_parallel import schedules

PyTree = Any


def pipeline_spmd_forward_enc_dec(
    enc_fn: Callable[[PyTree, jax.Array], jax.Array],
    dec_fn: Callable[[PyTree, jax.Array, jax.Array], jax.Array],
    stage_params: PyTree,
    enc_microbatches: jax.Array,
    dec_microbatches: jax.Array,
    *,
    split_rank: Optional[int] = None,
    axis_name: str = mesh_lib.PIPELINE_AXIS,
    remat: bool = True,
    broadcast_outputs: bool = True,
    mb_index: bool = False,
):
    """Forward of the two-segment pipeline. ``enc_fn(params, h)`` runs on
    stages [0, split); ``dec_fn(params, h, enc_ctx)`` on [split, pp).
    ``enc_microbatches``/``dec_microbatches``: (M, ...) embedded inputs for
    the two segments (same trailing shape). Returns the decoder outputs per
    microbatch (masked to pp rank 0 unless ``broadcast_outputs``).

    ``mb_index=True`` changes the stage-fn signatures to
    ``enc_fn(params, h, m)`` / ``dec_fn(params, h, ctx, m)`` where ``m``
    is the (traced, clipped) index of the microbatch this stage processes
    on this tick — what per-microbatch side inputs (e.g. encoder padding
    lengths) index by. On stage r at tick t the resident microbatch is
    ``t - r`` (one hop per tick), clipped to [0, M) during fill/drain
    where the compute is discarded anyway."""
    S = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    if split_rank is None:
        split_rank = mesh_lib.get_pipeline_model_parallel_split_rank()
    if split_rank is None or not (0 < split_rank < S):
        raise ValueError(
            f"encoder-decoder pipeline needs 0 < split_rank < pp "
            f"(got {split_rank}, pp={S})")
    M = enc_microbatches.shape[0]
    mb_shape = enc_microbatches.shape[1:]
    T = M + S - 1

    if not mb_index:
        # normalize the two signatures to the mb_index form so ONE stage
        # dispatch serves both modes
        enc_fn = (lambda f: lambda p, h, m: f(p, h))(enc_fn)
        dec_fn = (lambda f: lambda p, h, c, m: f(p, h, c))(dec_fn)

    def stage(params, h, ctx, m):
        return jax.lax.cond(
            rank < split_rank,
            lambda p, h_, c_, m_: enc_fn(p, h_, m_),
            lambda p, h_, c_, m_: dec_fn(p, h_, c_, m_),
            params, h, ctx, m,
        )

    fn = jax.checkpoint(stage) if remat else stage
    perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        (h, ctx), outputs = carry
        # stage-0 pre-process: inject the embedded encoder microbatch
        m0 = jnp.clip(t, 0, M - 1)
        enc_in = jax.lax.dynamic_index_in_dim(
            enc_microbatches, m0, 0, keepdims=False)
        h = jnp.where(rank == 0, enc_in, h)
        # split-stage pre-process: the arriving h is the completed encoder
        # output for microbatch (t - split); latch it as cross-attention
        # context and swap in that microbatch's embedded decoder input
        ms = jnp.clip(t - split_rank, 0, M - 1)
        dec_in = jax.lax.dynamic_index_in_dim(
            dec_microbatches, ms, 0, keepdims=False)
        at_split = rank == split_rank
        ctx = jnp.where(at_split, h, ctx)
        h = jnp.where(at_split, dec_in, h)

        # the microbatch resident on this stage this tick (fill/drain
        # ticks clip to a valid index; their compute is discarded)
        m_here = jnp.clip(t - rank, 0, M - 1)
        y = fn(stage_params, h, ctx, m_here)
        # the context travels with its microbatch through decoder stages
        h_next, ctx_next = jax.tree.map(
            lambda x: jax.lax.ppermute(x, axis_name, perm), (y, ctx))

        out_idx = jnp.clip(t - (S - 1), 0, M - 1)
        valid = (t >= S - 1) & (rank == 0)
        updated = jax.lax.dynamic_update_index_in_dim(
            outputs, h_next.astype(outputs.dtype), out_idx, 0)
        outputs = jnp.where(valid, updated, outputs)
        return ((h_next, ctx_next), outputs), None

    state0 = (jnp.zeros(mb_shape, enc_microbatches.dtype),
              jnp.zeros(mb_shape, enc_microbatches.dtype))
    outputs0 = jnp.zeros((M,) + mb_shape, enc_microbatches.dtype)
    (_, outputs), _ = jax.lax.scan(tick, (state0, outputs0), jnp.arange(T))
    if not broadcast_outputs:
        return outputs
    return schedules._broadcast_from_first(outputs, axis_name)


def forward_backward_pipelining_enc_dec(
    enc_fn: Callable,
    dec_fn: Callable,
    loss_head: Callable[[jax.Array, Any], jax.Array],
    stage_params: PyTree,
    enc_microbatches: jax.Array,
    dec_microbatches: jax.Array,
    targets: Any,
    *,
    split_rank: Optional[int] = None,
    axis_name: str = mesh_lib.PIPELINE_AXIS,
    accum_dtype=jnp.float32,
):
    """1F1B-class fwd+bwd of the two-segment pipeline (cf.
    ``forward_backward_pipelining_without_interleaving``). Returns
    (mean loss, grads wrt stage_params in ``accum_dtype``)."""
    p_acc, down = schedules._main_grad_cast(stage_params, accum_dtype)

    def full_loss(p):
        outs = pipeline_spmd_forward_enc_dec(
            lambda pp, h: enc_fn(down(pp), h),
            lambda pp, h, c: dec_fn(down(pp), h, c),
            p, enc_microbatches, dec_microbatches,
            split_rank=split_rank, axis_name=axis_name, remat=True,
        )
        losses = jax.vmap(loss_head)(outs, targets)
        return jnp.mean(losses)

    return jax.value_and_grad(full_loss)(p_acc)
