"""Stage-to-stage exchange primitives.

Re-design of ``apex/transformer/pipeline_parallel/p2p_communication.py``.
The reference composes 8 helpers (``recv_forward`` …
``send_forward_backward_recv_forward_backward``, ``:187-409``) over one
``_communicate`` that batches isend/irecv, guards a race with
``torch.cuda.synchronize()`` (``:166``), and scatter-gathers activations
across TP ranks to cut P2P volume (``:120-123,155-182``).

On TPU all of that is one primitive: ``lax.ppermute`` along the ``pp`` mesh
axis — a compiled ICI collective with no race to guard (XLA orders it) and
no need for the scatter-gather trick (ICI links are not shared with a TP
NVLink domain the same way; and XLA already overlaps the permute with
compute). The helpers keep the reference's names so schedule code reads the
same. All run inside ``shard_map`` with ``axis_name`` bound.

Note the SPMD difference: a ppermute *rotation* moves every stage's tensor
simultaneously; "first/last stage" masking is the caller's job (the
schedules mask by tick index), matching how the reference passes
``recv_prev=False`` at the pipeline ends.
"""

from __future__ import annotations

from typing import Any

import jax

from apex_tpu.monitor import hooks as monitor_hooks
from apex_tpu.monitor import spans as monitor_spans
from apex_tpu.parallel import mesh as mesh_lib

PyTree = Any


def _rotate(x: PyTree, axis_name: str, shift: int) -> PyTree:
    size = jax.lax.axis_size(axis_name)
    perm = [(i, (i + shift) % size) for i in range(size)]
    if monitor_hooks.enabled():  # trace-time count, zero run-time cost
        monitor_hooks.count_collective(
            "ppermute", bytes=monitor_hooks.tree_bytes(x), axis=axis_name)
    # span at trace time: the ppermute's HLOs carry the ppermute_<axis>
    # scope into device traces (the anatomy/CostDB join key), and the span
    # record carries the counted bytes for calibration
    with monitor_spans.collective_span("ppermute", x, axis_name):
        return jax.tree.map(
            lambda a: jax.lax.ppermute(a, axis_name, perm), x)


def rotate_overlapped(x: PyTree, compute_fn, *,
                      axis_name: str = mesh_lib.PIPELINE_AXIS,
                      shift: int = +1):
    """Issue the hop, run ``compute_fn`` — which must NOT depend on the
    hop's operand or result — then hand both back as
    ``(rotated, compute_out)``.

    This is PR 5's collective-matmul scheduling story applied to the
    pipeline boundary: XLA will not overlap a ``ppermute`` with compute
    that *consumes* it, but its latency-hiding scheduler freely runs the
    hop (async collective-permute start/done) concurrently with ops that
    are data-independent of it. Structuring a pipeline tick as
    issue → stage body → consume-next-tick creates exactly that
    independence; the schedules' ``overlap_p2p=True`` path drives it (one
    extra in-flight activation per device and S extra drain ticks buy
    every hop priced at zero — ``schedules.pipeline_spmd_forward`` has
    the geometry, ``monitor.pipeline_cost_model`` the unit-cost model).

    The blocking helpers above remain the right call when there is no
    independent compute to hide behind — a lone rotation hides nothing.
    """
    rotated = _rotate(x, axis_name, shift)
    return rotated, compute_fn()


def send_forward(x: PyTree, axis_name: str = mesh_lib.PIPELINE_AXIS) -> PyTree:
    """Rotate activations to the next stage (``send_forward`` ``:232-248``
    fused with the matching ``recv_forward`` ``:187-207`` — in SPMD the send
    and the receive are the same collective)."""
    return _rotate(x, axis_name, +1)


def send_backward(g: PyTree, axis_name: str = mesh_lib.PIPELINE_AXIS) -> PyTree:
    """Rotate gradients to the previous stage (``send_backward`` ``:250-266``
    + ``recv_backward`` ``:210-229``)."""
    return _rotate(g, axis_name, -1)


# aliases completing the reference's helper set; each pair is one rotation
recv_forward = send_forward
recv_backward = send_backward


def send_forward_recv_backward(x: PyTree, g: PyTree, axis_name: str = mesh_lib.PIPELINE_AXIS):
    """``:269-289``: both directions in one step (two independent permutes —
    XLA runs them concurrently on opposite ring directions)."""
    return _rotate(x, axis_name, +1), _rotate(g, axis_name, -1)


def send_backward_recv_forward(g: PyTree, x: PyTree, axis_name: str = mesh_lib.PIPELINE_AXIS):
    """``:292-312``."""
    return _rotate(g, axis_name, -1), _rotate(x, axis_name, +1)
