"""Pipeline schedules: no-pipelining, 1F1B-equivalent, interleaved.

Re-design of ``apex/transformer/pipeline_parallel/schedules/`` (dispatcher
``schedules/__init__.py:22-35``; no-pipelining
``fwd_bwd_no_pipelining.py:31``; 1F1B
``fwd_bwd_pipelining_without_interleaving.py:155-345``; interleaved
``fwd_bwd_pipelining_with_interleaving.py:25-375``).

The reference hand-schedules warmup/steady/cooldown phases, because with
eager CUDA + autograd the *order* of forward and backward microbatches
determines peak memory (1F1B exists to bound live activations at
``pp_size`` microbatches instead of ``num_microbatches``).

The TPU-native design inverts this: the forward pipeline is a single
``lax.scan`` over ticks inside ``shard_map`` — each tick every stage runs
its layer block and a ``ppermute`` rotates activations one stage down the
ring. ``jax.grad`` of that scan *is* the backward pipeline (cooldown order
falls out of reverse-mode). The memory knob that 1F1B turns is here
``jax.checkpoint`` on the stage function:

* no remat           → GPipe-like memory (all ticks' residuals live);
* remat per stage    → 1F1B-class memory (per-tick activations only,
  recomputed during the backward sweep) — this is what
  ``forward_backward_pipelining_without_interleaving`` applies;
* remat + offload policies → beyond the reference.

Utilization note: warmup/cooldown bubbles are identical to the reference's
(pipeline theory doesn't change). The interleaved schedule implements the
classic v-fold bubble shrink (``fwd_bwd_pipelining_with_interleaving.py:25``)
in scan form: with microbatches injected in groups of S, device r at tick t
holds exactly ONE in-flight item — ``u = t − r`` determines its chunk
``(u//S) mod v`` and microbatch ``S·((u//S)//v) + u mod S`` — so every tick
costs ONE chunk (1/v of a stage) and the fill is S−1 *chunk*-ticks instead
of the non-interleaved S−1 stage-ticks: total forward time
``M·v + S − 1`` chunk-times vs ``(M + S − 1)·v``. Requires ``M % S == 0``
(the reference's ``num_microbatches % pipeline_parallel_size == 0`` assert,
``fwd_bwd_pipelining_with_interleaving.py:87``).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.monitor import hooks as monitor_hooks
from apex_tpu.monitor import spans as monitor_spans
from apex_tpu.parallel import mesh as mesh_lib

PyTree = Any


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _broadcast_from_first(x, axis_name):
    """Replicate stage 0's value to all pp ranks. Forward is a masked psum;
    the hand-written transpose masks the cotangent back to stage 0 — the
    conservative psum-transpose (psum again) would scale gradients by
    pp_size because every stage holds a replicated copy of the loss."""
    rank = jax.lax.axis_index(axis_name)
    return jax.lax.psum(jnp.where(rank == 0, x, 0.0), axis_name)


def _bcast_fwd(x, axis_name):
    return _broadcast_from_first(x, axis_name), None


def _bcast_bwd(axis_name, _, g):
    rank = jax.lax.axis_index(axis_name)
    return (jnp.where(rank == 0, g, 0.0),)


_broadcast_from_first.defvjp(_bcast_fwd, _bcast_bwd)


def pipeline_spmd_forward(
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    stage_params: PyTree,
    microbatches: jax.Array,
    *,
    axis_name: str = mesh_lib.PIPELINE_AXIS,
    virtual_chunks: int = 1,
    remat: bool = True,
    broadcast_outputs: bool = True,
    tick_arg: bool = False,
    aux_init: PyTree = None,
):
    """Run the SPMD pipeline forward; returns per-microbatch outputs of the
    final stage (shape = microbatches.shape with the feature dims of the
    stage output), valid on the stage that holds them (masked elsewhere).

    ``broadcast_outputs=False`` skips the final replication: outputs are
    valid on pp rank 0 only (zeros elsewhere). Callers that reduce the
    outputs to a scalar loss should prefer this and broadcast the *loss*
    with :func:`_broadcast_from_first` instead — then every parameter
    consumed outside the pipelined middle (embedding, loss head, tied
    unembedding weights) gets a cotangent masked to rank 0, and one psum
    over pp replicates the true gradient. Broadcasting the outputs instead
    makes head-parameter gradients replicated but *tied* parameters (used
    both inside the rank-0-masked injection and the replicated head) a mix
    of masked and replicated contributions that no single collective fixes.

    ``stage_fn(params, x) -> y`` must keep ``y.shape == x.shape`` (uniform
    inter-stage activations — the reference has the same constraint via its
    fixed ``tensor_shape``, ``fwd_bwd_pipelining_without_interleaving.py:187``).

    ``microbatches``: (M, ...) — the *embedded* activations entering stage 0.
    Embedding/loss heads run outside the pipelined middle (on TPU the
    embedding is cheap to compute replicated; the reference instead gates
    pre_process/post_process per stage, ``schedules/common.py:29-148``).

    With ``virtual_chunks=v > 1``, ``stage_params`` must have a leading axis
    of size v (this device's chunks, virtual stage k = c·S + rank for chunk
    c — the reference's interleaved assignment, ``parallel_state.py:135-145``)
    and ``M % S == 0`` (microbatches flow in groups of S). Per tick each
    device computes exactly ONE chunk — the classic interleaved schedule's
    1/v-stage ticks; see the module docstring for the timing model.

    ``tick_arg=True`` calls ``stage_fn(params, x, t)`` with the tick index
    — combined with ``axis_index`` inside the stage this identifies the
    (microbatch, stage) pair, which is exactly what per-microbatch RNG
    (dropout) needs to fold a distinct key per application.

    ``aux_init`` (a pytree of scalars) switches the stage to an
    aux-carrying contract: ``stage_fn`` returns ``(y, aux_tree)`` and the
    scan accumulates each tick's aux — masked by tick VALIDITY, so
    warmup/cooldown garbage lanes contribute zero — into the init tree;
    the function then returns ``(outputs, aux_sum)``. The per-rank sum
    covers this rank's real (microbatch, stage) work only; ``psum`` over
    pp gives the global total (MoE router aux losses are the consumer —
    they must enter the objective differentiably, which the scan-carried
    accumulator provides).
    """
    S = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    v = virtual_chunks
    mb_shape = microbatches.shape[1:]

    perm = [(i, (i + 1) % S) for i in range(S)]

    aux = aux_init is not None

    def _mask_aux(a, ok):
        m = ok.astype(jnp.float32)
        return jax.tree.map(lambda x: x * m, a)

    if v == 1:
        base_fn = (stage_fn if tick_arg
                   else (lambda p, x, t: stage_fn(p, x)))
        fn = jax.checkpoint(base_fn) if remat else base_fn
        T = M + S - 1

        def tick(carry, t):
            x, outputs, aux_sum = carry  # x: (*mb), outputs: (M, *mb)
            inject = jax.lax.dynamic_index_in_dim(
                microbatches, jnp.clip(t, 0, M - 1), 0, keepdims=False
            )
            x = jnp.where(rank == 0, inject, x)
            # spans run once at trace time: the stage's HLOs carry the
            # pp_stage scope and the rotation the ppermute_<axis> scope
            # into device traces (step-anatomy/CostDB join keys)
            with monitor_spans.span("pp_stage"):
                y = fn(stage_params, x, t)
            if aux:
                y, a = y
                # this rank holds a REAL microbatch iff 0 <= t-rank < M
                u = t - rank
                aux_sum = jax.tree.map(
                    jnp.add, aux_sum, _mask_aux(a, (u >= 0) & (u < M)))
            with monitor_spans.collective_span("ppermute", y, axis_name):
                sent = jax.lax.ppermute(y, axis_name, perm)

            # microbatch m exits at tick m + S - 1, arriving (post-rotate)
            # at device 0
            out_idx = jnp.clip(t - (S - 1), 0, M - 1)
            valid = (t >= S - 1) & (rank == 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, sent.astype(outputs.dtype), out_idx, 0
            )
            outputs = jnp.where(valid, updated, outputs)
            return (sent, outputs, aux_sum), None

    else:
        if M % S:
            raise ValueError(
                f"the interleaved schedule needs num_microbatches ({M}) "
                f"divisible by the pipeline size ({S}) — microbatches flow "
                "in groups of S (the reference asserts the same, "
                "fwd_bwd_pipelining_with_interleaving.py:87)")
        T = M * v + S - 1

        def chunk_fn(params, c, x, t):
            # the chunk slice lives INSIDE the (rematted) tick function:
            # it is recomputed from the loop-invariant stacked params in
            # backward rather than stacked into T-length scan residuals
            chunk_params = jax.tree.map(
                lambda p: jax.lax.dynamic_index_in_dim(
                    p, c, 0, keepdims=False), params)
            if tick_arg:
                return stage_fn(chunk_params, x, t)
            return stage_fn(chunk_params, x)

        cfn = jax.checkpoint(chunk_fn) if remat else chunk_fn

        def item(u):
            """(chunk, microbatch, in-range) of the item with phase ``u``:
            the unique work unit at (device r, tick t) with u = t − r.
            Conflict-freedom: u determines (c, m) bijectively, and the
            chunk-c→c+1 wrap adds exactly S to u, so activations rotate one
            device per tick with no stalls."""
            uc = jnp.maximum(u, 0)
            c = (uc // S) % v
            m = S * ((uc // S) // v) + uc % S
            return c, jnp.clip(m, 0, M - 1), (u >= 0) & (m < M)

        def tick(carry, t):
            x, outputs, aux_sum = carry  # ONE in-flight activation/device
            c, m, in_flight = item(t - rank)
            # stage-0 pre-process: whenever device 0's active chunk is 0 it
            # starts a fresh microbatch (this also retires the item that
            # just finished chunk v-1 on the wrap-around)
            inject = jax.lax.dynamic_index_in_dim(
                microbatches, m, 0, keepdims=False)
            x = jnp.where((rank == 0) & (c == 0), inject, x)
            with monitor_spans.span("pp_stage"):
                y = cfn(stage_params, c, x, t)
            if aux:
                y, a = y
                aux_sum = jax.tree.map(
                    jnp.add, aux_sum, _mask_aux(a, in_flight))
            with monitor_spans.collective_span("ppermute", y, axis_name):
                sent = jax.lax.ppermute(y, axis_name, perm)

            # the item device S-1 just finished (u = t − (S−1)) arrives at
            # device 0 post-rotate; it is final iff its chunk was v−1
            c_out, m_out, in_range = item(t - (S - 1))
            valid = in_range & (c_out == v - 1) & (rank == 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, sent.astype(outputs.dtype), m_out, 0
            )
            outputs = jnp.where(valid, updated, outputs)
            return (sent, outputs, aux_sum), None

    # trace-time telemetry: schedule geometry (M, S, v → ticks, bubble
    # fraction) and the scanned ppermute's traffic (ticks × one microbatch
    # activation). S, M, T are static Python ints here, so this costs
    # nothing unless monitoring is enabled, and nothing at run time either
    # way (re-emitted per retrace, not per step).
    monitor_hooks.record_pipeline_schedule(
        num_microbatches=M, pipeline_size=S, virtual_chunks=v,
        tick_bytes=(functools.reduce(lambda a, b: a * b, mb_shape, 1)
                    * microbatches.dtype.itemsize),
        axis=axis_name)

    state0 = jnp.zeros(mb_shape, microbatches.dtype)
    outputs0 = jnp.zeros((M,) + mb_shape, microbatches.dtype)
    aux0 = (jax.tree.map(lambda x: jnp.zeros_like(x, jnp.float32), aux_init)
            if aux else jnp.zeros(()))
    (_, outputs, aux_sum), _ = jax.lax.scan(
        tick, (state0, outputs0, aux0), jnp.arange(T))
    # replicate the collected outputs unless the caller wants the raw
    # rank-0-valid array (they live on device 0 post-rotation)
    out = (outputs if not broadcast_outputs
           else _broadcast_from_first(outputs, axis_name))
    return (out, aux_sum) if aux else out


def forward_backward_no_pipelining(
    loss_fn: Callable[[PyTree, Any], jax.Array],
    params: PyTree,
    microbatches: Any,
    *,
    grad_reduce_axis: Optional[str] = None,
    accum_dtype=jnp.float32,
):
    """Grad accumulation over microbatches without pipelining
    (``fwd_bwd_no_pipelining.py:31``): the reference defers the DDP grad
    sync to the last microbatch; here grads accumulate in a scan and the
    single ``psum`` (if ``grad_reduce_axis``) happens once at the end —
    the same once-per-step communication.

    ``accum_dtype``: the accumulator's dtype, fp32 by default — the
    reference's ``main_grad`` semantics (wgrads accumulate into a
    persistent fp32 buffer even for half params,
    ``tensor_parallel/layers.py:259-315`` /
    ``csrc/megatron/fused_weight_gradient_dense.cpp:19-20``); with M
    microbatches of bf16 grads a bf16 accumulator would lose up to
    log2(M) bits of the sum. Pass ``None`` to accumulate in each param's
    own dtype. The scan's donated carry keeps the buffer in place — no
    per-microbatch HBM round trip beyond the grads themselves.

    ``loss_fn(params, microbatch) -> scalar mean loss``; returns
    (mean loss, grads averaged over microbatches, in ``accum_dtype``).
    """
    vg = jax.value_and_grad(loss_fn)

    def step(acc, mb):
        loss, g = vg(params, mb)
        acc_loss, acc_g = acc
        return (acc_loss + loss,
                jax.tree.map(lambda a, x: a + x.astype(a.dtype), acc_g, g)), None

    def zeros_like_acc(p):
        return jnp.zeros(p.shape, accum_dtype or p.dtype)

    zero = (jnp.zeros(()), jax.tree.map(zeros_like_acc, params))
    (loss_sum, grad_sum), _ = jax.lax.scan(step, zero, microbatches)
    n = jax.tree.leaves(microbatches)[0].shape[0]
    loss = loss_sum / n
    grads = jax.tree.map(lambda g: g / n, grad_sum)
    if grad_reduce_axis is not None:
        loss = jax.lax.pmean(loss, grad_reduce_axis)
        grads = jax.lax.pmean(grads, grad_reduce_axis)
    return loss, grads


def _main_grad_cast(params, accum_dtype):
    """fp32 main-grad accumulation for the scanned schedules: upcast the
    params the autodiff differentiates, and re-cast to the compute dtype
    *inside* each pipeline tick — the scan transpose then accumulates the
    per-tick cotangents in ``accum_dtype`` (the reference's persistent fp32
    ``main_grad`` buffer, ``tensor_parallel/layers.py:259-315``), while every
    tick still computes in the params' own dtype. Returns
    (upcast params, per-tick downcast fn)."""
    if accum_dtype is None:
        return params, lambda p: p

    def up(x):
        return (x.astype(accum_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x)

    def down(p):
        return jax.tree.map(
            lambda x, like: (x.astype(like.dtype)
                             if jnp.issubdtype(like.dtype, jnp.floating)
                             else x),
            p, params)

    return jax.tree.map(up, params), down


def forward_backward_pipelining_without_interleaving(
    stage_fn: Callable,
    loss_head: Callable[[jax.Array, Any], jax.Array],
    stage_params: PyTree,
    microbatches: jax.Array,
    targets: Any,
    *,
    axis_name: str = mesh_lib.PIPELINE_AXIS,
    accum_dtype=jnp.float32,
):
    """1F1B-equivalent schedule (``fwd_bwd_pipelining_without_interleaving.py:155``):
    pipelined forward via scan+ppermute, backward from autodiff, stage remat
    bounding live activations the way 1F1B's eager interleave does.

    ``loss_head(outputs_m, targets_m) -> scalar`` maps a final-stage output
    microbatch + its targets to a loss (the reference's last-stage
    ``loss_func``, ``schedules/common.py:297-301``).
    Returns (mean loss, grads wrt stage_params in ``accum_dtype`` — see
    :func:`_main_grad_cast`; ``None`` accumulates in the params' dtype).
    """
    p_acc, down = _main_grad_cast(stage_params, accum_dtype)

    def full_loss(p):
        outs = pipeline_spmd_forward(
            lambda pp, x: stage_fn(down(pp), x), p, microbatches,
            axis_name=axis_name, remat=True
        )
        losses = jax.vmap(loss_head)(outs, targets)
        return jnp.mean(losses)

    return jax.value_and_grad(full_loss)(p_acc)


def forward_backward_pipelining_with_interleaving(
    stage_fn: Callable,
    loss_head: Callable,
    stage_params_chunks: PyTree,
    microbatches: jax.Array,
    targets: Any,
    *,
    virtual_chunks: int,
    axis_name: str = mesh_lib.PIPELINE_AXIS,
    accum_dtype=jnp.float32,
):
    """Interleaved (virtual-stage) schedule
    (``fwd_bwd_pipelining_with_interleaving.py:25-375``): each device holds
    ``virtual_chunks`` model chunks; activations make ``virtual_chunks``
    loops around the device ring. ``stage_params_chunks`` leaves carry a
    leading (virtual_chunks,) axis."""

    p_acc, down = _main_grad_cast(stage_params_chunks, accum_dtype)

    def full_loss(p):
        outs = pipeline_spmd_forward(
            # down only consults leaf dtypes, so it composes with the
            # per-tick chunk slice inside pipeline_spmd_forward (the
            # dynamic_index_in_dim preserves leaf dtypes; each tick's
            # compute re-casts to the original param dtype while the scan
            # transpose accumulates cotangents in accum_dtype)
            lambda pp, x: stage_fn(down(pp), x), p, microbatches,
            axis_name=axis_name, virtual_chunks=virtual_chunks, remat=True,
        )
        losses = jax.vmap(loss_head)(outs, targets)
        return jnp.mean(losses)

    return jax.value_and_grad(full_loss)(p_acc)


def get_forward_backward_func(
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_size: int = 1,
):
    """Dispatcher with the reference's selection logic
    (``schedules/__init__.py:22-35``)."""
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining


def build_schedule(
    *,
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    rampup_batch_size: Optional[list] = None,
):
    """Pick the schedule *and* its microbatch count from one config — the
    glue the reference spreads across ``setup_microbatch_calculator``
    (``pipeline_parallel/utils.py:58-104``) and
    ``get_forward_backward_func``.

    Returns ``(fwd_bwd_func, calculator)``: call ``calculator.get()`` for
    the number of microbatches to split the global batch into (it changes
    over time under ``rampup_batch_size``; call
    ``calculator.update(consumed_samples, ...)`` per step then re-split),
    and drive ``fwd_bwd_func`` with that many microbatches. The interleaved
    schedule additionally wants ``virtual_chunks=v`` and chunked params.

    When to interleave (PERF.md "Interleaved schedule"): v>1 shrinks the
    pipeline fill from (S−1)·v to S−1 chunk-times — per-device
    utilization ``(M·v)/(M·v + S − 1)``, measured from the schedule's own
    validity-masked work counters (0.727 → 0.842 → 0.914 at v=1/2/4,
    M=8 S=4 — tests/test_pipeline.py::TestBubbleUtilization) — at the
    price of v× more ppermutes of one microbatch activation (small next
    to a chunk's FLOPs on ICI). Prefer the largest v dividing
    ``num_layers // pp`` when the microbatch count is a multiple of pp
    (required); the marginal gain shrinks as M/S grows.
    """
    from apex_tpu.transformer.microbatches import (
        build_num_microbatches_calculator,
    )

    calc = build_num_microbatches_calculator(
        global_batch_size, micro_batch_size, data_parallel_size,
        rampup_batch_size,
    )
    if (pipeline_model_parallel_size > 1
            and calc.get() < pipeline_model_parallel_size):
        raise ValueError(
            f"{calc.get()} microbatches cannot fill a "
            f"{pipeline_model_parallel_size}-stage pipeline; lower "
            "micro_batch_size or raise global_batch_size"
        )
    if (virtual_pipeline_model_parallel_size is not None
            and pipeline_model_parallel_size > 1):
        # every batch size the ramp will ever produce must divide into
        # pp-sized microbatch groups — a mid-training ramp step must not
        # discover the ValueError inside the schedule
        per_mb = micro_batch_size * data_parallel_size
        if rampup_batch_size is None:
            batch_sizes = [global_batch_size]
        else:
            start, incr = int(rampup_batch_size[0]), int(rampup_batch_size[1])
            batch_sizes = list(range(start, global_batch_size, incr))
            batch_sizes.append(global_batch_size)
        for gbs in batch_sizes:
            if gbs % per_mb:
                raise ValueError(
                    f"ramped global batch size {gbs} is not divisible by "
                    f"micro_batch_size*dp ({per_mb}) — the calculator's "
                    f"consistency check would fail mid-training"
                )
            m = gbs // per_mb
            if m % pipeline_model_parallel_size:
                raise ValueError(
                    f"the interleaved schedule needs every microbatch count "
                    f"divisible by the pipeline size "
                    f"({pipeline_model_parallel_size}); batch size {gbs} "
                    f"yields {m} microbatches"
                )
    fn = get_forward_backward_func(
        virtual_pipeline_model_parallel_size, pipeline_model_parallel_size,
    )
    if virtual_pipeline_model_parallel_size is not None \
            and pipeline_model_parallel_size > 1:
        fn = functools.partial(
            fn, virtual_chunks=virtual_pipeline_model_parallel_size)
    if monitor_hooks.enabled():
        monitor_hooks.emit_event(
            "schedule_config",
            schedule=getattr(fn, "func", fn).__name__,
            num_microbatches=calc.get(),
            micro_batch_size=micro_batch_size,
            global_batch_size=global_batch_size,
            data_parallel_size=data_parallel_size,
            pipeline_model_parallel_size=pipeline_model_parallel_size,
            virtual_chunks=virtual_pipeline_model_parallel_size or 1,
        )
    return fn, calc
