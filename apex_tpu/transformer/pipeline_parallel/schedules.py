"""Pipeline schedules: no-pipelining, 1F1B-equivalent, interleaved.

Re-design of ``apex/transformer/pipeline_parallel/schedules/`` (dispatcher
``schedules/__init__.py:22-35``; no-pipelining
``fwd_bwd_no_pipelining.py:31``; 1F1B
``fwd_bwd_pipelining_without_interleaving.py:155-345``; interleaved
``fwd_bwd_pipelining_with_interleaving.py:25-375``).

The reference hand-schedules warmup/steady/cooldown phases, because with
eager CUDA + autograd the *order* of forward and backward microbatches
determines peak memory (1F1B exists to bound live activations at
``pp_size`` microbatches instead of ``num_microbatches``).

The TPU-native design inverts this: the forward pipeline is a single
``lax.scan`` over ticks inside ``shard_map`` — each tick every stage runs
its layer block and a ``ppermute`` rotates activations one stage down the
ring. ``jax.grad`` of that scan *is* the backward pipeline (cooldown order
falls out of reverse-mode). The memory knob that 1F1B turns is here
``jax.checkpoint`` on the stage function:

* no remat           → GPipe-like memory (all ticks' residuals live);
* remat per stage    → 1F1B-class memory (per-tick activations only,
  recomputed during the backward sweep) — this is what
  ``forward_backward_pipelining_without_interleaving`` applies;
* remat + offload policies → beyond the reference.

Utilization note: warmup/cooldown bubbles are identical to the reference's
(pipeline theory doesn't change). The interleaved schedule implements the
classic v-fold bubble shrink (``fwd_bwd_pipelining_with_interleaving.py:25``)
in scan form: with microbatches injected in groups of S, device r at tick t
holds exactly ONE in-flight item — ``u = t − r`` determines its chunk
``(u//S) mod v`` and microbatch ``S·((u//S)//v) + u mod S`` — so every tick
costs ONE chunk (1/v of a stage) and the fill is S−1 *chunk*-ticks instead
of the non-interleaved S−1 stage-ticks: total forward time
``M·v + S − 1`` chunk-times vs ``(M + S − 1)·v``. Requires ``M % S == 0``
(the reference's ``num_microbatches % pipeline_parallel_size == 0`` assert,
``fwd_bwd_pipelining_with_interleaving.py:87``).

Zero-bubble family (``schedule="zb"``): the autodiff backward pays B+W on
every backward tick (B = dX, the activation grad that feeds the upstream
stage; W = dW, the weight grad whose only deadline is the optimizer step)
— including the S−1 warmup/drain ticks whose lanes hold garbage. The zb
schedule hand-writes the transpose as TWO sweeps: a dX-only reverse sweep
(the critical path, B per tick over the same M·v + S − 1 ticks) that
stashes each tick's (stage input, output cotangent) pair, and a deferred
dW sweep of exactly ``M·v`` ticks — only real items, no garbage lanes.
Scheduled-slot totals: 3·(Mv+S−1) for the autodiff schedule vs
2·(Mv+S−1) + Mv for zb — the (S−1)·W drain-bubble term is gone (the
ZB-H1 decomposition of arXiv:2401.10241 / the schedule-vs-compute
separation of veScale, in scan/SPMD form). Priced honestly, the zb
sweeps RECOMPUTE the stage forward from the stashed inputs (``jax.vjp``
— remat-class memory), one F more per item than rematted 1f1b pays; what
zb buys in exchange is zero garbage dW slots and ``M·v`` dW ticks with
NO collective on the critical path (hop latency and inter-stage sync
exit for the whole sweep). ``monitor.pipeline_cost_model`` reports both
sides (``bubble_fraction`` = slot waste, ``recompute_units``,
``collective_free_ticks``); the wall-clock verdict is measured by
``bench.py --pipeline``, never projected. fp32 main-grad accumulation
order is pinned to the reverse-tick order the autodiff transpose uses,
so grads stay parity-exact against the serial oracle.

``overlap_p2p=True`` restructures the tick so the ``ppermute`` hop is
ISSUED before the stage compute it no longer feeds: the carry holds two
items per device — one being computed, one in flight — so the hop of the
previous tick's output and this tick's stage body are data-independent
and XLA's latency-hiding scheduler runs them concurrently (PR 5's
collective-matmul trick at the pp boundary). Cost: each hop spans a full
tick, so the per-hop latency L becomes 2 — items flow in groups of
G = 2·S phases (``M % 2S == 0`` when interleaved) and the drain grows by
S ticks; the win is every hop priced at zero instead of serializing with
the stage. Composes with both schedules (the zb backward's cotangent hop
is overlapped the same way, and its dW sweep is hop-free by
construction).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.monitor import hooks as monitor_hooks
from apex_tpu.monitor import spans as monitor_spans
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p

PyTree = Any

#: legal values of the ``schedule=`` knob (pipeline_spmd_forward and the
#: fwd_bwd wrappers; build_schedule additionally accepts "interleaved",
#: which is "1f1b" with virtual chunks)
PIPELINE_SCHEDULES = ("1f1b", "zb")


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _broadcast_from_first(x, axis_name):
    """Replicate stage 0's value to all pp ranks. Forward is a masked psum;
    the hand-written transpose masks the cotangent back to stage 0 — the
    conservative psum-transpose (psum again) would scale gradients by
    pp_size because every stage holds a replicated copy of the loss."""
    rank = jax.lax.axis_index(axis_name)
    return jax.lax.psum(jnp.where(rank == 0, x, 0.0), axis_name)


def _bcast_fwd(x, axis_name):
    return _broadcast_from_first(x, axis_name), None


def _bcast_bwd(axis_name, _, g):
    rank = jax.lax.axis_index(axis_name)
    return (jnp.where(rank == 0, g, 0.0),)


_broadcast_from_first.defvjp(_bcast_fwd, _bcast_bwd)


def _item_at(u, v, M, G):
    """(chunk, microbatch, in-range) of the item with phase ``u = t − L·r``
    where ``G = L·S`` is the injection-group span (L = 1 blocking hops,
    L = 2 overlapped hops — each hop then spans a full tick, so the
    chunk-c→c+1 wrap adds exactly G to the phase and the modular item
    arithmetic is the interleaved schedule's with S → G; only G enters
    the arithmetic)."""
    uc = jnp.maximum(u, 0)
    c = (uc // G) % v
    m = G * ((uc // G) // v) + uc % G
    return c, jnp.clip(m, 0, M - 1), (u >= 0) & (m < M)


def _chunk_call(stage_fn, v, tick_arg):
    """Uniform ``call(params, x, c, t)`` over the v=1 / chunked param
    layouts: the chunk slice lives INSIDE the call so a vjp with respect
    to the stacked params transposes it to a scatter-add into chunk c."""
    def call(params, x, c, t):
        chunk = (params if v == 1 else jax.tree.map(
            lambda q: jax.lax.dynamic_index_in_dim(q, c, 0, keepdims=False),
            params))
        return stage_fn(chunk, x, t) if tick_arg else stage_fn(chunk, x)
    return call


def _mask_aux_tree(a, ok):
    m = ok.astype(jnp.float32)
    return jax.tree.map(lambda x: x * m, a)


def _unified_forward(stage_call, stage_params, microbatches, aux0, *,
                     axis_name, virtual_chunks, latency, has_aux,
                     collect_xs):
    """Shared forward scan for the overlap/zero-bubble schedule family.

    ``stage_call(params, x, c, t) -> y`` (or ``(y, aux)`` with
    ``has_aux``). ``latency`` is the per-hop tick latency L: 1 = blocking
    rotation (the hop is consumed the tick it is issued, the classic
    scanned schedule); 2 = ``overlap_p2p`` (each tick issues the hop of
    the PREVIOUS tick's output through :func:`p2p.rotate_overlapped`,
    runs this tick's stage — independent of the in-flight hop — and only
    the next tick consumes the arrival).

    Returns ``(outputs, aux_sum, xs)``; ``xs`` stashes every tick's stage
    INPUT (the zero-bubble backward's residuals — the same per-tick
    activation remat keeps) when ``collect_xs``, else a dummy scalar.
    """
    S = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    v = virtual_chunks
    L = latency
    G = L * S
    mb_shape = microbatches.shape[1:]
    T = M * v + L * (S - 1) + (L - 1)
    perm = [(i, (i + 1) % S) for i in range(S)]

    def inject_for(m):
        return jax.lax.dynamic_index_in_dim(microbatches, m, 0,
                                            keepdims=False)

    outputs0 = jnp.zeros((M,) + mb_shape, microbatches.dtype)
    xs0 = (jnp.zeros((T,) + mb_shape, microbatches.dtype) if collect_xs
           else jnp.zeros(()))

    def collect(outputs, sent, u_out):
        c_o, m_o, in_range = _item_at(u_out, v, M, G)
        valid = in_range & (c_o == v - 1) & (rank == 0)
        updated = jax.lax.dynamic_update_index_in_dim(
            outputs, sent.astype(outputs.dtype), m_o, 0)
        return jnp.where(valid, updated, outputs)

    if L == 1:
        def tick(carry, t):
            x, outputs, aux_sum, xs = carry
            c, m, in_flight = _item_at(t - rank, v, M, G)
            x = jnp.where((rank == 0) & (c == 0), inject_for(m), x)
            if collect_xs:
                xs = jax.lax.dynamic_update_index_in_dim(xs, x, t, 0)
            with monitor_spans.span("pp_stage"):
                y = stage_call(stage_params, x, c, t)
            if has_aux:
                y, a = y
                aux_sum = jax.tree.map(
                    jnp.add, aux_sum, _mask_aux_tree(a, in_flight))
            with monitor_spans.collective_span("ppermute", y, axis_name):
                sent = jax.lax.ppermute(y, axis_name, perm)
            # the item device S-1 finished THIS tick arrives post-rotate
            outputs = collect(outputs, sent, t - (S - 1))
            return (sent, outputs, aux_sum, xs), None

        carry0 = (jnp.zeros(mb_shape, microbatches.dtype),
                  outputs0, aux0, xs0)
    else:
        def tick(carry, t):
            # two items per device: x (ready to compute), y_prev (output
            # of last tick, to hop this tick) — issue the hop, run the
            # stage, consume next tick
            x, y_prev, outputs, aux_sum, xs = carry
            c, m, in_flight = _item_at(t - L * rank, v, M, G)
            if collect_xs:
                xs = jax.lax.dynamic_update_index_in_dim(xs, x, t, 0)

            def compute():
                with monitor_spans.span("pp_stage"):
                    return stage_call(stage_params, x, c, t)

            sent, y = p2p.rotate_overlapped(y_prev, compute,
                                            axis_name=axis_name)
            if has_aux:
                y, a = y
                aux_sum = jax.tree.map(
                    jnp.add, aux_sum, _mask_aux_tree(a, in_flight))
            # the arriving item was computed on device S-1 at tick t-1
            outputs = collect(outputs, sent, t - 1 - L * (S - 1))
            # next tick's compute input: fresh injection when device 0's
            # next item starts chunk 0, the arrival otherwise
            c_n, m_n, _ = _item_at(t + 1 - L * rank, v, M, G)
            x_next = jnp.where((rank == 0) & (c_n == 0),
                               inject_for(m_n), sent)
            return (x_next, y, outputs, aux_sum, xs), None

        # tick 0 computes phase 0 on device 0 (no prior tick to inject it)
        x0 = jnp.where(rank == 0, inject_for(jnp.int32(0)),
                       jnp.zeros(mb_shape, microbatches.dtype))
        carry0 = (x0, jnp.zeros(mb_shape, microbatches.dtype),
                  outputs0, aux0, xs0)

    carry, _ = jax.lax.scan(tick, carry0, jnp.arange(T))
    outputs, aux_sum, xs = carry[-3], carry[-2], carry[-1]
    return outputs, aux_sum, xs


# --- zero-bubble: split backward with deferred dW -----------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _zb_pipeline(spec, stage_fn, stage_params, microbatches, aux0):
    """Scanned pipeline forward whose TRANSPOSE is the zero-bubble
    schedule: a dX-only reverse sweep (critical path) plus a deferred
    ``M·v``-tick dW sweep (see :func:`_zb_bwd`). ``spec`` is the hashable
    static geometry ``(axis_name, virtual_chunks, latency, tick_arg,
    has_aux)``; returns ``(outputs, aux_sum)``."""
    axis_name, v, L, tick_arg, has_aux = spec
    outputs, aux_sum, _ = _unified_forward(
        _chunk_call(stage_fn, v, tick_arg), stage_params, microbatches,
        aux0, axis_name=axis_name, virtual_chunks=v, latency=L,
        has_aux=has_aux, collect_xs=False)
    return outputs, aux_sum


def _zb_fwd(spec, stage_fn, stage_params, microbatches, aux0):
    axis_name, v, L, tick_arg, has_aux = spec
    outputs, aux_sum, xs = _unified_forward(
        _chunk_call(stage_fn, v, tick_arg), stage_params, microbatches,
        aux0, axis_name=axis_name, virtual_chunks=v, latency=L,
        has_aux=has_aux, collect_xs=True)
    return (outputs, aux_sum), (stage_params, microbatches, xs)


def _zb_bwd(spec, stage_fn, res, cot):
    """The zero-bubble backward.

    Sweep 1 (dX, the critical path): the exact transpose of the forward
    scan restricted to activation cotangents — T reverse ticks, each
    rotating the cotangent one stage up (``ppermute`` with the inverse
    permutation) and pulling it through the stage's input only; the
    (stage input, output cotangent) pair of every tick is stashed. Under
    ``overlap_p2p`` the hop is data-independent of the tick's vjp (the
    same two-item carry, transposed), so it stays overlapped.

    Sweep 2 (dW, deferred): exactly ``M·v`` ticks per device — one per
    REAL item, no warmup/drain garbage lanes — each pulling the stashed
    cotangent through the stage's parameters. Accumulation runs in
    reverse phase order, the same order the autodiff transpose uses, so
    fp32 main-grad sums are parity-exact against the serial oracle."""
    axis_name, v, L, tick_arg, has_aux = spec
    stage_params, microbatches, xs = res
    d_outputs, d_aux = cot
    S = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    G = L * S
    T = M * v + L * (S - 1) + (L - 1)
    N = M * v
    perm_back = [(i, (i - 1) % S) for i in range(S)]
    call = _chunk_call(stage_fn, v, tick_arg)
    mb_shape = microbatches.shape[1:]
    act_dtype = microbatches.dtype

    def out_cot(u_out, like):
        """Transpose of the output collection: lane m_out's cotangent is
        consumed at the single tick that wrote it (rank 0)."""
        c_o, m_o, in_range = _item_at(u_out, v, M, G)
        valid = in_range & (c_o == v - 1) & (rank == 0)
        d_out = jax.lax.dynamic_index_in_dim(d_outputs, m_o, 0,
                                             keepdims=False)
        return jnp.where(valid, d_out.astype(like.dtype),
                         jnp.zeros_like(like))

    def stage_cot(dy, ok):
        if has_aux:
            return (dy, _mask_aux_tree(d_aux, ok))
        return dy

    def pull_dx(t, dy):
        c, m, in_flight = _item_at(t - L * rank, v, M, G)
        x = jax.lax.dynamic_index_in_dim(xs, t, 0, keepdims=False)
        with monitor_spans.span("pp_dx"):
            _, vjp_fn = jax.vjp(lambda xx: call(stage_params, xx, c, t), x)
            (dx,) = vjp_fn(stage_cot(dy, in_flight))
        starts = (rank == 0) & (c == 0)
        return dx, m, starts, in_flight

    d_mb0 = jnp.zeros(microbatches.shape, act_dtype)
    dys0 = jnp.zeros((T,) + mb_shape, act_dtype)

    if L == 1:
        def dx_tick(carry, t):
            g, d_mb, dys = carry  # g = d(sent_t) from the downstream tick
            d_sent = g + out_cot(t - (S - 1), g)
            with monitor_spans.collective_span("ppermute", d_sent,
                                               axis_name):
                dy = jax.lax.ppermute(d_sent, axis_name, perm_back)
            dys = jax.lax.dynamic_update_index_in_dim(dys, dy, t, 0)
            dx, m, starts, in_flight = pull_dx(t, dy)
            d_mb = d_mb.at[m].add(
                jnp.where(starts & in_flight, dx, jnp.zeros_like(dx)))
            g_prev = jnp.where(starts, jnp.zeros_like(dx), dx)
            return (g_prev, d_mb, dys), None

        carry0 = (jnp.zeros(mb_shape, act_dtype), d_mb0, dys0)
        (_, d_mb, dys), _ = jax.lax.scan(
            dx_tick, carry0, jnp.arange(T), reverse=True)
    else:
        def dx_tick(carry, t):
            gx, gy, d_mb, dys = carry  # gx = d(x_{t+1}), gy = d(y_t)
            c_n, m_n, fl_n = _item_at(t + 1 - L * rank, v, M, G)
            starts_n = (rank == 0) & (c_n == 0)
            d_mb = d_mb.at[m_n].add(
                jnp.where(starts_n & fl_n, gx, jnp.zeros_like(gx)))
            d_sent = (jnp.where(starts_n, jnp.zeros_like(gx), gx)
                      + out_cot(t - 1 - L * (S - 1), gx))
            dys = jax.lax.dynamic_update_index_in_dim(dys, gy, t, 0)
            # the cotangent hop is independent of this tick's vjp — the
            # forward's overlap structure survives transposition
            def compute():
                dx, _, _, _ = pull_dx(t, gy)
                return dx

            d_y_prev, dx = p2p.rotate_overlapped(
                d_sent, compute, axis_name=axis_name, shift=-1)
            return (dx, d_y_prev, d_mb, dys), None

        carry0 = (jnp.zeros(mb_shape, act_dtype),
                  jnp.zeros(mb_shape, act_dtype), d_mb0, dys0)
        (gx_fin, _, d_mb, dys), _ = jax.lax.scan(
            dx_tick, carry0, jnp.arange(T), reverse=True)
        # x_0 was initialized to microbatch 0 on rank 0 outside the scan
        d_mb = d_mb.at[0].add(
            jnp.where(rank == 0, gx_fin, jnp.zeros_like(gx_fin)))

    # deferred dW: one tick per REAL item (phase u, forward tick u + L·r),
    # in reverse phase order — the order the autodiff transpose
    # accumulates in, so fp32 main-grad sums match the oracle bit-for-bit
    # in ordering (every u in [0, M·v) is real on every device)
    def add_cot(acc, dp):
        return jax.tree.map(
            lambda a, d: a if d.dtype == jax.dtypes.float0 else a + d,
            acc, dp)

    def dw_tick(d_params, u):
        t = u + L * rank
        x = jax.lax.dynamic_index_in_dim(xs, t, 0, keepdims=False)
        dy = jax.lax.dynamic_index_in_dim(dys, t, 0, keepdims=False)
        c, _, _ = _item_at(u, v, M, G)
        with monitor_spans.span("pp_dw"):
            _, vjp_fn = jax.vjp(lambda pp: call(pp, x, c, t), stage_params)
            (dp,) = vjp_fn(stage_cot(dy, jnp.asarray(True)))
        return add_cot(d_params, dp), None

    d_params0 = jax.tree.map(jnp.zeros_like, stage_params)
    d_params, _ = jax.lax.scan(
        dw_tick, d_params0, jnp.arange(N), reverse=True)
    return d_params, d_mb, d_aux


_zb_pipeline.defvjp(_zb_fwd, _zb_bwd)


def pipeline_spmd_forward(
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    stage_params: PyTree,
    microbatches: jax.Array,
    *,
    axis_name: str = mesh_lib.PIPELINE_AXIS,
    virtual_chunks: int = 1,
    remat: bool = True,
    broadcast_outputs: bool = True,
    tick_arg: bool = False,
    aux_init: PyTree = None,
    schedule: str = "1f1b",
    overlap_p2p: bool = False,
):
    """Run the SPMD pipeline forward; returns per-microbatch outputs of the
    final stage (shape = microbatches.shape with the feature dims of the
    stage output), valid on the stage that holds them (masked elsewhere).

    ``broadcast_outputs=False`` skips the final replication: outputs are
    valid on pp rank 0 only (zeros elsewhere). Callers that reduce the
    outputs to a scalar loss should prefer this and broadcast the *loss*
    with :func:`_broadcast_from_first` instead — then every parameter
    consumed outside the pipelined middle (embedding, loss head, tied
    unembedding weights) gets a cotangent masked to rank 0, and one psum
    over pp replicates the true gradient. Broadcasting the outputs instead
    makes head-parameter gradients replicated but *tied* parameters (used
    both inside the rank-0-masked injection and the replicated head) a mix
    of masked and replicated contributions that no single collective fixes.

    ``stage_fn(params, x) -> y`` must keep ``y.shape == x.shape`` (uniform
    inter-stage activations — the reference has the same constraint via its
    fixed ``tensor_shape``, ``fwd_bwd_pipelining_without_interleaving.py:187``).

    ``microbatches``: (M, ...) — the *embedded* activations entering stage 0.
    Embedding/loss heads run outside the pipelined middle (on TPU the
    embedding is cheap to compute replicated; the reference instead gates
    pre_process/post_process per stage, ``schedules/common.py:29-148``).

    With ``virtual_chunks=v > 1``, ``stage_params`` must have a leading axis
    of size v (this device's chunks, virtual stage k = c·S + rank for chunk
    c — the reference's interleaved assignment, ``parallel_state.py:135-145``)
    and ``M % S == 0`` (microbatches flow in groups of S). Per tick each
    device computes exactly ONE chunk — the classic interleaved schedule's
    1/v-stage ticks; see the module docstring for the timing model.

    ``tick_arg=True`` calls ``stage_fn(params, x, t)`` with the tick index
    — combined with ``axis_index`` inside the stage this identifies the
    (microbatch, stage) pair, which is exactly what per-microbatch RNG
    (dropout) needs to fold a distinct key per application.

    ``aux_init`` (a pytree of scalars) switches the stage to an
    aux-carrying contract: ``stage_fn`` returns ``(y, aux_tree)`` and the
    scan accumulates each tick's aux — masked by tick VALIDITY, so
    warmup/cooldown garbage lanes contribute zero — into the init tree;
    the function then returns ``(outputs, aux_sum)``. The per-rank sum
    covers this rank's real (microbatch, stage) work only; ``psum`` over
    pp gives the global total (MoE router aux losses are the consumer —
    they must enter the objective differentiably, which the scan-carried
    accumulator provides).

    ``schedule``: ``"1f1b"`` (default — scan forward, autodiff backward;
    interleaved when ``virtual_chunks > 1``) or ``"zb"`` (zero-bubble:
    hand-written split backward — dX on the critical path, dW deferred
    into a real-items-only sweep; the module docstring has the cost
    model). ``"zb"`` ignores ``remat`` (both sweeps recompute the stage
    from the per-tick stashed inputs — the same memory class).

    ``overlap_p2p``: restructure each tick so the ``ppermute`` hop is
    issued before the stage body it is independent of (one extra
    in-flight item per device; with ``virtual_chunks > 1`` microbatches
    must then flow in groups of ``2·S``). Composes with both schedules.
    """
    S = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    v = virtual_chunks
    mb_shape = microbatches.shape[1:]

    perm = [(i, (i + 1) % S) for i in range(S)]

    aux = aux_init is not None

    if schedule not in PIPELINE_SCHEDULES:
        raise ValueError(
            f"schedule={schedule!r} is not a pipeline schedule; legal "
            f"values of the schedule= knob are "
            f"{' / '.join(map(repr, PIPELINE_SCHEDULES))} ('1f1b' is the "
            "scanned autodiff schedule, interleaved when virtual_chunks "
            "> 1; 'zb' is the zero-bubble split backward)")
    if v > 1 and overlap_p2p and M % (2 * S):
        raise ValueError(
            f"overlap_p2p=True with virtual_chunks={v} needs "
            f"num_microbatches ({M}) divisible by 2*pipeline_size "
            f"({2 * S}) — each overlapped hop spans a full tick, so "
            "microbatches flow in groups of 2*S")
    if v > 1 and M % S:
        raise ValueError(
            f"the interleaved schedule needs num_microbatches ({M}) "
            f"divisible by the pipeline size ({S}) — microbatches flow "
            "in groups of S (the reference asserts the same, "
            "fwd_bwd_pipelining_with_interleaving.py:87)")

    if schedule == "zb" or overlap_p2p:
        monitor_hooks.record_pipeline_schedule(
            num_microbatches=M, pipeline_size=S, virtual_chunks=v,
            tick_bytes=(functools.reduce(lambda a, b: a * b, mb_shape, 1)
                        * microbatches.dtype.itemsize),
            axis=axis_name, schedule=schedule, overlap_p2p=overlap_p2p)
        L = 2 if overlap_p2p else 1
        aux0 = (jax.tree.map(lambda x: jnp.zeros_like(x, jnp.float32),
                             aux_init) if aux else jnp.zeros(()))
        if schedule == "zb":
            spec = (axis_name, v, L, tick_arg, aux)
            outputs, aux_sum = _zb_pipeline(
                spec, stage_fn, stage_params, microbatches, aux0)
        else:  # 1f1b forward restructured for the overlapped hop
            call = _chunk_call(stage_fn, v, tick_arg)
            fn = jax.checkpoint(call) if remat else call
            outputs, aux_sum, _ = _unified_forward(
                fn, stage_params, microbatches, aux0,
                axis_name=axis_name, virtual_chunks=v, latency=L,
                has_aux=aux, collect_xs=False)
        out = (outputs if not broadcast_outputs
               else _broadcast_from_first(outputs, axis_name))
        return (out, aux_sum) if aux else out

    _mask_aux = _mask_aux_tree

    if v == 1:
        base_fn = (stage_fn if tick_arg
                   else (lambda p, x, t: stage_fn(p, x)))
        fn = jax.checkpoint(base_fn) if remat else base_fn
        T = M + S - 1

        def tick(carry, t):
            x, outputs, aux_sum = carry  # x: (*mb), outputs: (M, *mb)
            inject = jax.lax.dynamic_index_in_dim(
                microbatches, jnp.clip(t, 0, M - 1), 0, keepdims=False
            )
            x = jnp.where(rank == 0, inject, x)
            # spans run once at trace time: the stage's HLOs carry the
            # pp_stage scope and the rotation the ppermute_<axis> scope
            # into device traces (step-anatomy/CostDB join keys)
            with monitor_spans.span("pp_stage"):
                y = fn(stage_params, x, t)
            if aux:
                y, a = y
                # this rank holds a REAL microbatch iff 0 <= t-rank < M
                u = t - rank
                aux_sum = jax.tree.map(
                    jnp.add, aux_sum, _mask_aux(a, (u >= 0) & (u < M)))
            with monitor_spans.collective_span("ppermute", y, axis_name):
                sent = jax.lax.ppermute(y, axis_name, perm)

            # microbatch m exits at tick m + S - 1, arriving (post-rotate)
            # at device 0
            out_idx = jnp.clip(t - (S - 1), 0, M - 1)
            valid = (t >= S - 1) & (rank == 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, sent.astype(outputs.dtype), out_idx, 0
            )
            outputs = jnp.where(valid, updated, outputs)
            return (sent, outputs, aux_sum), None

    else:
        # M % S validated above (shared with the zb/overlap paths)
        T = M * v + S - 1

        def chunk_fn(params, c, x, t):
            # the chunk slice lives INSIDE the (rematted) tick function:
            # it is recomputed from the loop-invariant stacked params in
            # backward rather than stacked into T-length scan residuals
            chunk_params = jax.tree.map(
                lambda p: jax.lax.dynamic_index_in_dim(
                    p, c, 0, keepdims=False), params)
            if tick_arg:
                return stage_fn(chunk_params, x, t)
            return stage_fn(chunk_params, x)

        cfn = jax.checkpoint(chunk_fn) if remat else chunk_fn

        def item(u):
            """(chunk, microbatch, in-range) of the item with phase ``u``:
            the unique work unit at (device r, tick t) with u = t − r.
            Conflict-freedom: u determines (c, m) bijectively, and the
            chunk-c→c+1 wrap adds exactly S to u, so activations rotate one
            device per tick with no stalls."""
            uc = jnp.maximum(u, 0)
            c = (uc // S) % v
            m = S * ((uc // S) // v) + uc % S
            return c, jnp.clip(m, 0, M - 1), (u >= 0) & (m < M)

        def tick(carry, t):
            x, outputs, aux_sum = carry  # ONE in-flight activation/device
            c, m, in_flight = item(t - rank)
            # stage-0 pre-process: whenever device 0's active chunk is 0 it
            # starts a fresh microbatch (this also retires the item that
            # just finished chunk v-1 on the wrap-around)
            inject = jax.lax.dynamic_index_in_dim(
                microbatches, m, 0, keepdims=False)
            x = jnp.where((rank == 0) & (c == 0), inject, x)
            with monitor_spans.span("pp_stage"):
                y = cfn(stage_params, c, x, t)
            if aux:
                y, a = y
                aux_sum = jax.tree.map(
                    jnp.add, aux_sum, _mask_aux(a, in_flight))
            with monitor_spans.collective_span("ppermute", y, axis_name):
                sent = jax.lax.ppermute(y, axis_name, perm)

            # the item device S-1 just finished (u = t − (S−1)) arrives at
            # device 0 post-rotate; it is final iff its chunk was v−1
            c_out, m_out, in_range = item(t - (S - 1))
            valid = in_range & (c_out == v - 1) & (rank == 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, sent.astype(outputs.dtype), m_out, 0
            )
            outputs = jnp.where(valid, updated, outputs)
            return (sent, outputs, aux_sum), None

    # trace-time telemetry: schedule geometry (M, S, v → ticks, bubble
    # fraction) and the scanned ppermute's traffic (ticks × one microbatch
    # activation). S, M, T are static Python ints here, so this costs
    # nothing unless monitoring is enabled, and nothing at run time either
    # way (re-emitted per retrace, not per step).
    monitor_hooks.record_pipeline_schedule(
        num_microbatches=M, pipeline_size=S, virtual_chunks=v,
        tick_bytes=(functools.reduce(lambda a, b: a * b, mb_shape, 1)
                    * microbatches.dtype.itemsize),
        axis=axis_name, schedule=schedule, overlap_p2p=overlap_p2p)

    state0 = jnp.zeros(mb_shape, microbatches.dtype)
    outputs0 = jnp.zeros((M,) + mb_shape, microbatches.dtype)
    aux0 = (jax.tree.map(lambda x: jnp.zeros_like(x, jnp.float32), aux_init)
            if aux else jnp.zeros(()))
    (_, outputs, aux_sum), _ = jax.lax.scan(
        tick, (state0, outputs0, aux0), jnp.arange(T))
    # replicate the collected outputs unless the caller wants the raw
    # rank-0-valid array (they live on device 0 post-rotation)
    out = (outputs if not broadcast_outputs
           else _broadcast_from_first(outputs, axis_name))
    return (out, aux_sum) if aux else out


def forward_backward_no_pipelining(
    loss_fn: Callable[[PyTree, Any], jax.Array],
    params: PyTree,
    microbatches: Any,
    *,
    grad_reduce_axis: Optional[str] = None,
    accum_dtype=jnp.float32,
):
    """Grad accumulation over microbatches without pipelining
    (``fwd_bwd_no_pipelining.py:31``): the reference defers the DDP grad
    sync to the last microbatch; here grads accumulate in a scan and the
    single ``psum`` (if ``grad_reduce_axis``) happens once at the end —
    the same once-per-step communication.

    ``accum_dtype``: the accumulator's dtype, fp32 by default — the
    reference's ``main_grad`` semantics (wgrads accumulate into a
    persistent fp32 buffer even for half params,
    ``tensor_parallel/layers.py:259-315`` /
    ``csrc/megatron/fused_weight_gradient_dense.cpp:19-20``); with M
    microbatches of bf16 grads a bf16 accumulator would lose up to
    log2(M) bits of the sum. Pass ``None`` to accumulate in each param's
    own dtype. The scan's donated carry keeps the buffer in place — no
    per-microbatch HBM round trip beyond the grads themselves.

    ``loss_fn(params, microbatch) -> scalar mean loss``; returns
    (mean loss, grads averaged over microbatches, in ``accum_dtype``).
    """
    vg = jax.value_and_grad(loss_fn)

    def step(acc, mb):
        loss, g = vg(params, mb)
        acc_loss, acc_g = acc
        return (acc_loss + loss,
                jax.tree.map(lambda a, x: a + x.astype(a.dtype), acc_g, g)), None

    def zeros_like_acc(p):
        return jnp.zeros(p.shape, accum_dtype or p.dtype)

    zero = (jnp.zeros(()), jax.tree.map(zeros_like_acc, params))
    (loss_sum, grad_sum), _ = jax.lax.scan(step, zero, microbatches)
    n = jax.tree.leaves(microbatches)[0].shape[0]
    loss = loss_sum / n
    grads = jax.tree.map(lambda g: g / n, grad_sum)
    if grad_reduce_axis is not None:
        loss = jax.lax.pmean(loss, grad_reduce_axis)
        grads = jax.lax.pmean(grads, grad_reduce_axis)
    return loss, grads


def _main_grad_cast(params, accum_dtype):
    """fp32 main-grad accumulation for the scanned schedules: upcast the
    params the autodiff differentiates, and re-cast to the compute dtype
    *inside* each pipeline tick — the scan transpose then accumulates the
    per-tick cotangents in ``accum_dtype`` (the reference's persistent fp32
    ``main_grad`` buffer, ``tensor_parallel/layers.py:259-315``), while every
    tick still computes in the params' own dtype. Returns
    (upcast params, per-tick downcast fn)."""
    if accum_dtype is None:
        return params, lambda p: p

    def up(x):
        return (x.astype(accum_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x)

    def down(p):
        return jax.tree.map(
            lambda x, like: (x.astype(like.dtype)
                             if jnp.issubdtype(like.dtype, jnp.floating)
                             else x),
            p, params)

    return jax.tree.map(up, params), down


def forward_backward_pipelining_without_interleaving(
    stage_fn: Callable,
    loss_head: Callable[[jax.Array, Any], jax.Array],
    stage_params: PyTree,
    microbatches: jax.Array,
    targets: Any,
    *,
    axis_name: str = mesh_lib.PIPELINE_AXIS,
    accum_dtype=jnp.float32,
    overlap_p2p: bool = False,
):
    """1F1B-equivalent schedule (``fwd_bwd_pipelining_without_interleaving.py:155``):
    pipelined forward via scan+ppermute, backward from autodiff, stage remat
    bounding live activations the way 1F1B's eager interleave does.

    ``loss_head(outputs_m, targets_m) -> scalar`` maps a final-stage output
    microbatch + its targets to a loss (the reference's last-stage
    ``loss_func``, ``schedules/common.py:297-301``).
    Returns (mean loss, grads wrt stage_params in ``accum_dtype`` — see
    :func:`_main_grad_cast`; ``None`` accumulates in the params' dtype).
    """
    p_acc, down = _main_grad_cast(stage_params, accum_dtype)

    def full_loss(p):
        outs = pipeline_spmd_forward(
            lambda pp, x: stage_fn(down(pp), x), p, microbatches,
            axis_name=axis_name, remat=True, overlap_p2p=overlap_p2p
        )
        losses = jax.vmap(loss_head)(outs, targets)
        return jnp.mean(losses)

    return jax.value_and_grad(full_loss)(p_acc)


def forward_backward_pipelining_with_interleaving(
    stage_fn: Callable,
    loss_head: Callable,
    stage_params_chunks: PyTree,
    microbatches: jax.Array,
    targets: Any,
    *,
    virtual_chunks: int,
    axis_name: str = mesh_lib.PIPELINE_AXIS,
    accum_dtype=jnp.float32,
    overlap_p2p: bool = False,
):
    """Interleaved (virtual-stage) schedule
    (``fwd_bwd_pipelining_with_interleaving.py:25-375``): each device holds
    ``virtual_chunks`` model chunks; activations make ``virtual_chunks``
    loops around the device ring. ``stage_params_chunks`` leaves carry a
    leading (virtual_chunks,) axis."""

    p_acc, down = _main_grad_cast(stage_params_chunks, accum_dtype)

    def full_loss(p):
        outs = pipeline_spmd_forward(
            # down only consults leaf dtypes, so it composes with the
            # per-tick chunk slice inside pipeline_spmd_forward (the
            # dynamic_index_in_dim preserves leaf dtypes; each tick's
            # compute re-casts to the original param dtype while the scan
            # transpose accumulates cotangents in accum_dtype)
            lambda pp, x: stage_fn(down(pp), x), p, microbatches,
            axis_name=axis_name, virtual_chunks=virtual_chunks, remat=True,
            overlap_p2p=overlap_p2p,
        )
        losses = jax.vmap(loss_head)(outs, targets)
        return jnp.mean(losses)

    return jax.value_and_grad(full_loss)(p_acc)


def forward_backward_pipelining_zero_bubble(
    stage_fn: Callable,
    loss_head: Callable,
    stage_params: PyTree,
    microbatches: jax.Array,
    targets: Any,
    *,
    virtual_chunks: int = 1,
    axis_name: str = mesh_lib.PIPELINE_AXIS,
    accum_dtype=jnp.float32,
    overlap_p2p: bool = False,
):
    """Zero-bubble schedule family (``schedule="zb"``): the stage backward
    splits into dX (activation grad, the critical path feeding the
    upstream stage) and dW (weight grad, deadline = optimizer step); the
    deferred dW work runs as its own ``M·v``-tick real-items-only sweep
    instead of riding every backward tick — the (S−1)·W warmup/drain term
    of the autodiff schedule's bubble is gone, and the whole dW sweep is
    collective-free. Cost honesty: both sweeps recompute the stage
    forward from the per-tick stashed inputs, one F per item more than
    rematted 1f1b — the trade favors zb when hops/sync dominate a tick
    (small per-stage compute, deep pipelines), not on raw FLOPs (module
    docstring has the full accounting; ``monitor.pipeline_cost_model``
    prices both sides, ``bench.py --pipeline`` measures). With
    ``virtual_chunks > 1`` this is the interleaved layout (chunked
    ``stage_params``) on the zb backward. Same contract as the other
    fwd_bwd functions: returns (mean loss, grads in ``accum_dtype``)."""
    p_acc, down = _main_grad_cast(stage_params, accum_dtype)

    def full_loss(p):
        outs = pipeline_spmd_forward(
            lambda pp, x: stage_fn(down(pp), x), p, microbatches,
            axis_name=axis_name, virtual_chunks=virtual_chunks,
            schedule="zb", overlap_p2p=overlap_p2p,
        )
        losses = jax.vmap(loss_head)(outs, targets)
        return jnp.mean(losses)

    return jax.value_and_grad(full_loss)(p_acc)


def get_forward_backward_func(
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_size: int = 1,
    schedule: Optional[str] = None,
):
    """Dispatcher with the reference's selection logic
    (``schedules/__init__.py:22-35``); ``schedule="zb"`` selects the
    zero-bubble family at pp > 1 (any v — the wrapper takes
    ``virtual_chunks``). An unknown name raises — a typo'd schedule must
    not silently train on the default (pp == 1 still dispatches to
    no-pipelining regardless: one stage has no pipeline to schedule)."""
    if schedule is not None and schedule not in BUILD_SCHEDULES:
        raise ValueError(
            f"schedule={schedule!r} is not a pipeline schedule; legal "
            f"values are {' / '.join(map(repr, BUILD_SCHEDULES))} (or "
            "None to infer 1f1b/interleaved from "
            "virtual_pipeline_model_parallel_size)")
    if pipeline_model_parallel_size > 1:
        if schedule == "zb":
            return forward_backward_pipelining_zero_bubble
        if (virtual_pipeline_model_parallel_size is not None
                or schedule == "interleaved"):
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining


#: build_schedule's schedule-name space: "interleaved" is "1f1b" with
#: virtual chunks, spelled out so a config can *demand* interleaving and
#: fail loudly when v is missing
BUILD_SCHEDULES = ("1f1b", "interleaved", "zb")


def build_schedule(
    *,
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    rampup_batch_size: Optional[list] = None,
    schedule: Optional[str] = None,
    overlap_p2p: bool = False,
):
    """Pick the schedule *and* its microbatch count from one config — the
    glue the reference spreads across ``setup_microbatch_calculator``
    (``pipeline_parallel/utils.py:58-104``) and
    ``get_forward_backward_func``.

    Returns ``(fwd_bwd_func, calculator)``: call ``calculator.get()`` for
    the number of microbatches to split the global batch into (it changes
    over time under ``rampup_batch_size``; call
    ``calculator.update(consumed_samples, ...)`` per step then re-split),
    and drive ``fwd_bwd_func`` with that many microbatches. The interleaved
    schedule additionally wants ``virtual_chunks=v`` and chunked params.

    When to interleave (PERF.md "Interleaved schedule"): v>1 shrinks the
    pipeline fill from (S−1)·v to S−1 chunk-times — per-device
    utilization ``(M·v)/(M·v + S − 1)``, measured from the schedule's own
    validity-masked work counters (0.727 → 0.842 → 0.914 at v=1/2/4,
    M=8 S=4 — tests/test_pipeline.py::TestBubbleUtilization) — at the
    price of v× more ppermutes of one microbatch activation (small next
    to a chunk's FLOPs on ICI). Prefer the largest v dividing
    ``num_layers // pp`` when the microbatch count is a multiple of pp
    (required); the marginal gain shrinks as M/S grows.

    ``schedule`` names the family explicitly: ``"1f1b"`` (autodiff
    backward, no virtual chunks), ``"interleaved"`` (``"1f1b"`` with
    ``virtual_pipeline_model_parallel_size`` chunks — demanding it fails
    loudly when v is missing instead of silently degrading), ``"zb"``
    (zero-bubble split backward, any v), or ``None`` (infer 1f1b /
    interleaved from v — the pre-zb behavior). Every geometry error —
    unknown name, unfillable pipeline, a microbatch count (including
    every ramped one) that does not divide into the schedule's injection
    groups — is raised HERE, naming the knob, instead of surfacing as a
    deep shape error mid-trace. ``overlap_p2p`` is threaded into the
    returned fwd_bwd function (and doubles the injection group when
    interleaved: ``2·pp``).
    """
    from apex_tpu.transformer.microbatches import (
        build_num_microbatches_calculator,
    )

    pp = pipeline_model_parallel_size
    v = virtual_pipeline_model_parallel_size
    if schedule is not None and schedule not in BUILD_SCHEDULES:
        raise ValueError(
            f"schedule={schedule!r} is not a pipeline schedule; legal "
            f"values of build_schedule(schedule=...) are "
            f"{' / '.join(map(repr, BUILD_SCHEDULES))} (or None to infer "
            "1f1b/interleaved from virtual_pipeline_model_parallel_size)")
    if schedule == "interleaved" and (v is None or v < 2):
        raise ValueError(
            "schedule='interleaved' needs "
            f"virtual_pipeline_model_parallel_size >= 2 (got {v!r}) — "
            "pass the chunk count, or use schedule='1f1b'")
    if schedule == "1f1b" and v is not None and v > 1:
        raise ValueError(
            f"schedule='1f1b' with virtual_pipeline_model_parallel_size="
            f"{v} is contradictory — interleaving IS the virtual-chunk "
            "schedule; pass schedule='interleaved' (or None)")

    # geometry legality is ParallelPlan.validate*()'s job (ISSUE 12
    # satellite): the same illegal combo rejected with the same message
    # whichever door it walks through (GPTConfig / make_mesh / here).
    # The plan's virtual_chunks carries v only when a pipeline exists or
    # interleaving was explicitly demanded — the legacy infer path
    # (schedule=None, v set, pp=1) stays a no-op.
    from apex_tpu.plan.parallel_plan import ParallelPlan, PlanError

    try:
        plan = ParallelPlan(
            dp=data_parallel_size, pp=pp,
            pp_schedule="zb" if schedule == "zb" else "1f1b",
            overlap_p2p=bool(overlap_p2p) and pp > 1,
            virtual_chunks=((v or 1) if (pp > 1
                                         or schedule == "interleaved")
                            else 1))
        if schedule is not None:
            plan.validate_schedule()
    except PlanError as e:
        raise ValueError(str(e)) from None

    calc = build_num_microbatches_calculator(
        global_batch_size, micro_batch_size, data_parallel_size,
        rampup_batch_size,
    )
    per_mb = micro_batch_size * data_parallel_size
    if rampup_batch_size is None:
        batch_sizes = [global_batch_size]
    else:
        start, incr = int(rampup_batch_size[0]), int(rampup_batch_size[1])
        batch_sizes = list(range(start, global_batch_size, incr))
        batch_sizes.append(global_batch_size)
    # every batch size the ramp will ever produce must fill the pipeline
    # and divide into the schedule's injection groups — a mid-training
    # ramp step must not discover the ValueError inside the schedule
    for gbs in batch_sizes:
        if gbs % per_mb:
            raise ValueError(
                f"ramped global batch size {gbs} is not divisible by "
                f"micro_batch_size*dp ({per_mb}) — the calculator's "
                f"consistency check would fail mid-training"
            )
        try:
            plan.validate_microbatches(gbs // per_mb)
        except PlanError as e:
            raise ValueError(str(e)) from None
    fn = get_forward_backward_func(v, pp, schedule=schedule)
    extra = {}
    if v is not None and pp > 1:
        extra["virtual_chunks"] = v
    if overlap_p2p and pp > 1:
        extra["overlap_p2p"] = True
    if extra:
        fn = functools.partial(fn, **extra)
    if monitor_hooks.enabled():
        monitor_hooks.emit_event(
            "schedule_config",
            schedule=getattr(fn, "func", fn).__name__,
            schedule_name=schedule or ("interleaved" if v else "1f1b"),
            overlap_p2p=overlap_p2p,
            num_microbatches=calc.get(),
            micro_batch_size=micro_batch_size,
            global_batch_size=global_batch_size,
            data_parallel_size=data_parallel_size,
            pipeline_model_parallel_size=pipeline_model_parallel_size,
            virtual_chunks=virtual_pipeline_model_parallel_size or 1,
        )
    return fn, calc
