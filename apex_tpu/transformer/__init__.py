"""Megatron-style model parallelism, TPU-native.

Re-design of ``apex.transformer`` (``apex/transformer/__init__.py:1-23``):
tensor + pipeline parallel layers and schedules built on one
``jax.sharding.Mesh`` (``apex_tpu.parallel.mesh`` is re-exported here as
``parallel_state`` for API parity) instead of NCCL process groups.
"""

from apex_tpu.parallel import mesh as parallel_state  # noqa: F401
from apex_tpu.transformer import tensor_parallel  # noqa: F401
from apex_tpu.transformer import pipeline_parallel  # noqa: F401
from apex_tpu.transformer import moe  # noqa: F401
from apex_tpu.transformer.enums import AttnMaskType, AttnType, LayerType, ModelType  # noqa: F401
