"""Microbatch calculators.

Port-equivalent of ``apex/transformer/microbatches.py:26-195`` (host-side
bookkeeping, no device code): constant and ramped-up numbers of microbatches
from (global_batch_size, micro_batch_size, data_parallel_size).
"""

from __future__ import annotations

from typing import List, Optional


def build_num_microbatches_calculator(
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
    rampup_batch_size: Optional[List[int]] = None,
):
    """``build_num_microbatches_calculator`` (``microbatches.py:26-64``)."""
    if rampup_batch_size is None:
        return ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size
        )
    if len(rampup_batch_size) != 3:
        raise ValueError(
            "rampup_batch_size must be [start_global_batch_size, increment, samples]"
        )
    return RampupBatchsizeNumMicroBatches(
        int(rampup_batch_size[0]),
        int(rampup_batch_size[1]),
        int(rampup_batch_size[2]),
        global_batch_size,
        micro_batch_size,
        data_parallel_size,
    )


class NumMicroBatchesCalculator:
    def __init__(self):
        self.num_micro_batches = None
        self.current_global_batch_size = None

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check):
        pass


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    """``microbatches.py:88-106``."""

    def __init__(self, global_batch_size, micro_batch_size, data_parallel_size):
        super().__init__()
        micro_batch_times_dp = micro_batch_size * data_parallel_size
        if global_batch_size % micro_batch_times_dp != 0:
            raise ValueError(
                f"global batch size ({global_batch_size}) is not divisible by "
                f"micro batch size ({micro_batch_size}) x data parallel size "
                f"({data_parallel_size})"
            )
        self.num_micro_batches = global_batch_size // micro_batch_times_dp
        self.current_global_batch_size = global_batch_size


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    """Linear ramp of the global batch size (``microbatches.py:109-195``)."""

    def __init__(self, start_batch_size, batch_size_increment, ramup_samples,
                 global_batch_size, micro_batch_size, data_parallel_size):
        super().__init__()
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size
        self.micro_batch_times_data_parallel_size = micro_batch_size * data_parallel_size

        diff = global_batch_size - start_batch_size
        if diff < 0 or diff % batch_size_increment != 0:
            raise ValueError(
                "expected global batch size to be reachable from the start "
                "batch size by increments"
            )
        num_increments = diff // batch_size_increment
        self.rampup_samples_per_increment = self.ramup_samples / max(num_increments, 1)
        self.update(0, False)

    def update(self, consumed_samples, consistency_check):
        if consumed_samples > self.ramup_samples:
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment)
            self.current_global_batch_size = (
                self.start_batch_size + steps * self.batch_size_increment
            )
            self.current_global_batch_size = min(
                self.current_global_batch_size, self.global_batch_size
            )
        if consistency_check:
            if self.current_global_batch_size % self.micro_batch_times_data_parallel_size:
                raise RuntimeError(
                    f"current global batch size ({self.current_global_batch_size}) "
                    "is not divisible by micro-batch-size x data-parallel-size"
                )
        self.num_micro_batches = (
            self.current_global_batch_size // self.micro_batch_times_data_parallel_size
        )


# global-singleton accessors (parity with pipeline_parallel/utils.py:58-104)
_CALCULATOR: Optional[NumMicroBatchesCalculator] = None


def setup_microbatch_calculator(
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
    rampup_batch_size: Optional[List[int]] = None,
) -> None:
    global _CALCULATOR
    _CALCULATOR = build_num_microbatches_calculator(
        global_batch_size, micro_batch_size, data_parallel_size, rampup_batch_size
    )


def get_num_microbatches() -> int:
    if _CALCULATOR is None:
        raise RuntimeError("microbatch calculator is not set up")
    return _CALCULATOR.get()


def get_current_global_batch_size() -> int:
    if _CALCULATOR is None:
        raise RuntimeError("microbatch calculator is not set up")
    return _CALCULATOR.get_current_global_batch_size()


def update_num_microbatches(consumed_samples, consistency_check=True) -> None:
    if _CALCULATOR is None:
        raise RuntimeError("microbatch calculator is not set up")
    _CALCULATOR.update(consumed_samples, consistency_check)
