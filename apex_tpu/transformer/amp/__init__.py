"""Model-parallel-aware gradient scaling.

Re-design of ``apex.transformer.amp.GradScaler``
(``apex/transformer/amp/grad_scaler.py:21-107``): the reference subclasses
torch's GradScaler to all-reduce found-inf across the model-parallel group so
every rank skips the same step. Here the functional scaler from
:mod:`apex_tpu.amp.scaler` is extended with an any-reduce of the non-finite
flag over the given mesh axes — the same "skip together" contract.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScalerState, update_loss_scaler
from apex_tpu.parallel import mesh as mesh_lib


def model_parallel_all_finite(
    grads, axes: Sequence[str] = (mesh_lib.TENSOR_AXIS, mesh_lib.PIPELINE_AXIS)
) -> jax.Array:
    """All-finite flag agreed across model-parallel axes (``grad_scaler.py:38-49``):
    a single non-finite grad anywhere makes every rank skip."""
    from apex_tpu.amp.scaler import all_finite

    finite = all_finite(grads).astype(jnp.float32)
    for ax in axes:
        finite = jax.lax.pmin(finite, ax)
    return finite > 0


def update_scaler_model_parallel(
    state: LossScalerState, grads,
    axes: Sequence[str] = (mesh_lib.TENSOR_AXIS, mesh_lib.PIPELINE_AXIS),
) -> Tuple[LossScalerState, jax.Array]:
    """update() with the cross-rank found-inf reduction
    (``grad_scaler.py:96-107``). Returns (new_state, finite)."""
    finite = model_parallel_all_finite(grads, axes)
    return update_loss_scaler(state, finite), finite


GradScaler = update_scaler_model_parallel  # reference class-name alias
