"""Utilities shared by tensor_parallel and pipeline_parallel — the
``apex/transformer/utils.py`` parity surface.

``split_tensor_into_1d_equal_chunks`` / ``gather_split_1d_tensor`` are the
reference's sequence-parallel activation scatter/gather (used by its
checkpoint buffer, ``tensor_parallel/random.py:45-84``). There the rank
indexes a flat view and an ``_all_gather_base`` reassembles it; here the
same pair is a ``dynamic_slice`` by ``axis_index`` and an ``all_gather``,
valid inside ``shard_map`` with the axis bound.
"""

from __future__ import annotations

import jax

from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer.tensor_parallel.utils import (  # noqa: F401
    divide,
    ensure_divisibility,
    split_tensor_along_last_dim,
)


def split_tensor_into_1d_equal_chunks(
    x: jax.Array, *, axis_name: str = mesh_lib.TENSOR_AXIS
) -> jax.Array:
    """This rank's equal chunk of the flattened tensor
    (``apex/transformer/utils.py:22-30``). Requires the flat size to divide
    the axis size; run inside shard_map."""
    flat = x.reshape(-1)
    world = jax.lax.axis_size(axis_name)
    if flat.shape[0] % world:
        raise ValueError(
            f"tensor of {flat.shape[0]} elements does not split evenly over "
            f"{world} ranks")
    per = flat.shape[0] // world
    rank = jax.lax.axis_index(axis_name)
    return jax.lax.dynamic_slice_in_dim(flat, rank * per, per, 0)


def gather_split_1d_tensor(
    chunk: jax.Array, *, axis_name: str = mesh_lib.TENSOR_AXIS
) -> jax.Array:
    """Inverse of :func:`split_tensor_into_1d_equal_chunks`
    (``apex/transformer/utils.py:33-46``): all-gather the rank chunks back
    into the full flat tensor."""
    return jax.lax.all_gather(chunk.reshape(-1), axis_name, axis=0,
                              tiled=True)
