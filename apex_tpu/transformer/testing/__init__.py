"""Testing utilities — re-design of ``apex/transformer/testing/``.

* standalone GPT/BERT live in :mod:`apex_tpu.models` (the reference keeps
  them here, ``standalone_gpt.py``/``standalone_bert.py``) — re-exported;
* :mod:`apex_tpu.transformer.testing.arguments` — the Megatron-style global
  argparse singleton (``arguments.py``, ``global_vars.py``);
* :mod:`apex_tpu.transformer.testing.commons` — toy pipeline models
  (``commons.py:34-72``);
* the multi-device harness is the 8-device CPU mesh in ``tests/conftest.py``
  (the DistributedTestBase analog — SURVEY.md §4).
"""

from apex_tpu.models.bert import BertConfig, BertModel  # noqa: F401
from apex_tpu.models.gpt import GPTConfig, GPTModel  # noqa: F401
from apex_tpu.transformer.testing.arguments import (  # noqa: F401
    get_args,
    parse_args,
    set_args,
)
from apex_tpu.transformer.testing.commons import MyModel, model_provider_func  # noqa: F401
